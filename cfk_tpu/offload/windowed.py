"""Out-of-core training: host factor stores + windowed half-steps.

The ALX move (arXiv 2112.02194): accept that factor tables exceed one
chip's HBM, keep them in host RAM (``HostFactorStore``), and stream
WINDOWS of the fixed side through the device while the solve streams the
chunk scan.  The execution per chunk is literally the resident tiled
half-step — ``ops.tiled.als_half_step_tiled`` (stream/all_gather mode) or
the ring schedules' per-slice chunk body (``parallel.spmd.
_make_tiled_slice_grams``'s ops, ring/hier_ring mode) run unmodified
against the staged window with rebased indices (PR 4's in-kernel gather
reads from ANY-memory-space tables, so the kernels just point at the
window) — which is what makes the windowed path BIT-EXACT vs the resident
path (``tests/test_offload.py`` + ``tests/test_offload_sharded.py`` pin it
per knob: shard count, exchange/ici_group, table dtype, gather mode, fused
epilogue, overlap).

Schedule per half-step (the ``ops/pipeline.py`` shape, one level up):

    stage(window 0)                     # host gather + device_put
    for w: stage(w+1)  ||  compute(w)   # double buffer
            scatter solved rows of w back to the host store

Window w's jitted compute is DISPATCHED first (jit dispatch is async),
then window w+1's host gather + ``device_put`` run under it — so the host
staging work AND the PCIe transfer both hide under the Gram+solve exactly
as the chunk pipelines overlap their gathers.  In the sharded ring modes
the same double buffer runs under the visit schedule's inner-ICI
rotations: window w+1 of the NEXT slice visit stages while the current
slice's Grams accumulate, and the only DCN-share traffic is each window's
row set gathered from a remote store shard — the "window residual" —
never the flat ring's O(S) full-table rotation.

Staged bytes per dtype (ISSUE 12): f32 windows stage 4 B/cell, bf16 2
(the cast is per-element, host-cast == device-cast bit-exactly), and int8
tables stage the (1-byte codes, one f32 per-row scale) pair the kernels
consume — a quarter of the f32 bytes — quantized ON THE HOST by
``store.quantize_rows_host``, whose arithmetic is pinned bit-identical to
the in-jit ``ops.quant.quantize_table`` (the per-row scheme makes a
window's rows quantize independently of the table around them).

``train_als_host_window`` is the ``offload_tier="host_window"`` executor
the planner resolves oversized problems to (``plan/resolver.py`` gates the
``device`` tier on ``offload.budget`` — the same per-shard predicate the
window sizing here consumes, so a plan can never promise a resident table
that does not fit).  Explicit ALS on the tiled layout; one process
driving all shards (each shard's windows stage against the entity-range
store shard placement a multi-host deployment would pin per host).
"""

from __future__ import annotations

import functools
import time

import jax
import numpy as np

from cfk_tpu.config import ALSConfig
from cfk_tpu.offload import budget as _budget
from cfk_tpu.offload.staging import (
    DEFAULT_POOL_DEPTH,
    StagingStats,
    WindowStager,
    pool_workers_for,
    resolve_staging,
    stats_add,
)
# _np_dtype: the ONE validated name→numpy-dtype mapping (raises on
# anything but float32/bfloat16 — no silent fallthrough).
from cfk_tpu.offload.store import (
    HostFactorStore,
    StoreIntegrityError,
    _np_dtype,
    quantize_rows_host,
)
from cfk_tpu.offload.window import (
    BucketWindowPlan,
    RingWindowPlan,
    WindowPlan,
    build_bucket_window_plan,
    build_ring_window_plan,
    build_window_plan,
)
from cfk_tpu.telemetry import record_event, span
from cfk_tpu.telemetry.recorder import dump_flight

# Trace counter for the windowed driver's jits: the bodies below bump it
# once per TRACE (python side effects run only while tracing), so the
# staging-A/B bench rows can report `trace_count` and a warm compile
# cache (ALSConfig.compile_cache_dir) shows up as fewer compile seconds
# at an unchanged trace count.
_TRACES = [0]


def trace_count() -> int:
    """Traces of the windowed driver's jitted programs this process."""
    return _TRACES[0]


def _stage_dtype(store_dtype: str, table_dtype: str | None) -> str:
    """The dtype windows cross PCIe at: bf16 tables stage bf16 (half the
    transfer), int8 tables stage the (int8 codes, f32 per-row scales)
    pair (a quarter — ``quantize_rows_host`` on the host side of the
    PCIe, bit-identical to the in-jit quantization the resident path
    runs); f32 stages the storage dtype."""
    if table_dtype in ("bfloat16", "int8"):
        return table_dtype
    return store_dtype


def _stage_cell_bytes(stage_name: str) -> tuple[int, int]:
    """(bytes per staged table cell, per-row overhead bytes)."""
    if stage_name == "int8":
        return 1, 4  # codes + one f32 scale per row
    return _np_dtype(stage_name).itemsize, 0


def _staged_donate_argnums(base: tuple, staged: tuple) -> tuple:
    """Donation positions for a window jit: ``base`` (device-owned
    carries — always donatable) plus the staged-table positions on TPU
    only.  On CPU ``jax.device_put`` ZERO-COPY-ALIASES host numpy arrays
    (measured in this container), and jax refuses to donate an aliased
    buffer with a "donated buffers were not usable" warning per program —
    so the staged (tbl, scale) pair donates only where the PCIe copy
    makes it device-owned (on-TPU validation backlog re-measures the
    reclaim).  The chunk operands are NEVER donated: they are stage-time
    VIEWS of the TiledBlocks, and a donated alias would let XLA scribble
    on the dataset itself."""
    if jax.default_backend() == "tpu":
        return base + staged
    return base


def _window_half_impl(tbl, scale, nb, rt, wt, ts, ent, cnt, cin, lseg, *,
                      statics, lam, solver, overlap, fused_epilogue,
                      in_kernel_gather, reg_solve_algo, table_dtype,
                      out_dtype):
    """One window's chunks through the UNMODIFIED stream-mode half-step
    (``return_chunk_rows`` skips the device scatter — the host does it).

    ``scale`` is the staged int8 window's per-row dequant scale (None for
    f32/bf16 staging): the fold into the weight channel happens HERE, the
    canonical order ``quantize_tiled_operand`` applies on the resident
    path, and the codes then flow to the half-step as an
    already-quantized table (``table_dtype=None`` — quantizing again
    would be wrong)."""
    from cfk_tpu.ops import quant
    from cfk_tpu.ops.tiled import tiled_half_step

    _TRACES[0] += 1
    if scale is not None:
        wt = quant.fold_scale(wt, scale, nb)
        table_dtype = None
    blk = dict(neighbor_idx=nb, rating=rt, weight=wt, tile_seg=ts,
               chunk_entity=ent, chunk_count=cnt, carry_in=cin,
               last_seg=lseg)
    xs = tiled_half_step(
        tbl, blk, ("tiled", "stream") + statics, 1, lam,
        solver=solver, overlap=overlap, fused_epilogue=fused_epilogue,
        in_kernel_gather=in_kernel_gather, reg_solve_algo=reg_solve_algo,
        table_dtype=table_dtype, return_chunk_rows=True,
    )
    return xs.astype(jax.numpy.dtype(out_dtype))


@functools.lru_cache(maxsize=None)
def _window_half_jit():
    """The stream-mode window jit, built lazily so the staged-pair
    donation can consult the backend (see ``_staged_donate_argnums``)."""
    return jax.jit(
        _window_half_impl,
        static_argnames=("statics", "lam", "solver", "overlap",
                         "fused_epilogue", "in_kernel_gather",
                         "reg_solve_algo", "table_dtype", "out_dtype"),
        donate_argnums=_staged_donate_argnums((), (0, 1)),
    )


@functools.lru_cache(maxsize=None)
def _window_half_hot_jit():
    """The stream-mode window jit under the hot/delta engine (ISSUE 15):
    the SAME program as ``_window_half_jit`` — one trace of the identical
    chunk body — but WITHOUT the staged-pair donation: the assembled
    (tbl, scale) window table must OUTLIVE the call, because the
    successor window's delta reuse copies its shared cold rows out of it
    device-to-device (the resident-cold arena).  Donating it would hand
    XLA a buffer the next assembly still reads."""
    return jax.jit(
        _window_half_impl,
        static_argnames=("statics", "lam", "solver", "overlap",
                         "fused_epilogue", "in_kernel_gather",
                         "reg_solve_algo", "table_dtype", "out_dtype"),
    )


def _ring_window_impl(acc_a, acc_b, tbl, scale, nb, rt, wt, ts, ent, *,
                      statics, backend, gather, int8):
    """One staged ring window's chunks, accumulated into the shard's
    persistent per-entity Gram carry — op-for-op the flat/hier ring's
    per-slice chunk body (``parallel.spmd._make_tiled_slice_grams``),
    with the staged window replacing the rotated block (gathered values
    are bitwise the block rows, so the Grams — and their scatter-add
    order — are identical)."""
    import jax.numpy as jnp
    from jax import lax

    from cfk_tpu.ops import quant
    from cfk_tpu.ops.tiled import _entity_gram_chunk

    _TRACES[0] += 1

    ncw, cap, t, e_c = statics
    nt = cap // t
    k = tbl.shape[-1]
    if gather == "fused":
        fz = tbl
    else:
        fz = jnp.concatenate([tbl, jnp.zeros((1, k), tbl.dtype)])

    def chunk_body(i, acc):
        a0, b0 = acc
        nb_c = lax.dynamic_slice(nb, (i * cap,), (cap,))
        rt_c = lax.dynamic_slice(rt, (i * cap,), (cap,))
        wt_c = lax.dynamic_slice(wt, (i * cap,), (cap,))
        ts_c = lax.dynamic_slice(ts, (i * nt,), (nt,))
        ent_c = lax.dynamic_slice(ent, (i * e_c,), (e_c,))
        wt_c = quant.fold_scale(wt_c, scale, nb_c)
        a, b = _entity_gram_chunk(
            fz, nb_c, wt_c, rt_c, ts_c, t, e_c + 1, backend,
            unit_weights=not int8,
            zero_appended=gather != "fused", gather=gather,
        )
        return (a0.at[ent_c].add(a[:e_c]), b0.at[ent_c].add(b[:e_c]))

    return lax.fori_loop(0, ncw, chunk_body, (acc_a, acc_b))


@functools.lru_cache(maxsize=None)
def _ring_window_jit():
    """The ring-mode window jit.  Donates the persistent Gram carry pair
    (ISSUE 13): the accumulation is in-place by construction
    (``acc.at[...].add``), so donation lets the output accumulator ALIAS
    the input — input and output never coexist across the dispatch
    boundary, which is exactly the ×2→×1 reservation reclaim
    ``budget.ring_accumulator_reservation`` credits (the
    ``models/als.py``/``spmd.py`` ``donate_argnums`` idiom applied at the
    window boundary).  The staged (tbl, scale) pair additionally donates
    on TPU (``_staged_donate_argnums``); the chunk operands never do
    (stage-time views of the blocks)."""
    return jax.jit(
        _ring_window_impl,
        static_argnames=("statics", "backend", "gather", "int8"),
        donate_argnums=_staged_donate_argnums((0, 1), (2, 3)),
    )


@functools.lru_cache(maxsize=None)
def _ring_window_hot_jit():
    """The ring-mode window jit under the hot/delta engine: identical
    program to ``_ring_window_jit`` with the Gram-carry donation kept
    (the ×1 accumulator reservation) but the staged-table donation
    dropped — the assembled window table is the successor's delta-reuse
    source (see ``_window_half_hot_jit``)."""
    return jax.jit(
        _ring_window_impl,
        static_argnames=("statics", "backend", "gather", "int8"),
        donate_argnums=(0, 1),
    )


def _assemble_impl(delta, dscale, prev_tbl, prev_scale, hot_tbl, hot_scale,
                   keep_dst, keep_src, new_dst, hot_dst, hot_src, *,
                   window_rows, int8):
    """Assemble one window's staged table from its three sources
    (ISSUE 15): the PCIe-staged cold delta, the predecessor window's
    assembled table (device-to-device reuse of shared cold rows), and
    the device-resident hot partition.  Every row is a COPY of bytes
    bitwise identical to what full staging would have produced, so the
    assembled table — and everything computed from it — is bit-exact vs
    the PR 12 engine by construction.

    Index pads point AT ``window_rows`` (out of bounds) and are dropped
    by the explicit scatter ``mode="drop"``; rows no source claims stay
    zero — they are the [row_count, window_rows) pad rows no rebased
    neighbor index ever references (the full-staging path filled them
    with row-0 repeats; either value is unread)."""
    import jax.numpy as jnp

    _TRACES[0] += 1
    r = window_rows
    tbl = jnp.zeros((r, delta.shape[-1]), delta.dtype)
    tbl = tbl.at[keep_dst].set(prev_tbl[keep_src], mode="drop")
    tbl = tbl.at[new_dst].set(delta, mode="drop")
    tbl = tbl.at[hot_dst].set(hot_tbl[hot_src], mode="drop")
    if not int8:
        return tbl, None
    sc = jnp.zeros((r,), jnp.float32)
    sc = sc.at[keep_dst].set(prev_scale[keep_src], mode="drop")
    sc = sc.at[new_dst].set(dscale, mode="drop")
    sc = sc.at[hot_dst].set(hot_scale[hot_src], mode="drop")
    return tbl, sc


@functools.lru_cache(maxsize=None)
def _assemble_jit():
    """The window-assembly jit.  Shapes re-trace per (delta bucket,
    index widths, window_rows) — a scatter/gather-only program, cheap
    next to the window compute (which keeps ONE trace because it always
    sees the same assembled [window_rows, k] table shape)."""
    return jax.jit(
        _assemble_impl, static_argnames=("window_rows", "int8"),
    )


def _hot_update_impl(hot_tbl, hot_scale, xs, src, dst, *, int8):
    """Scatter one window's solved hot rows back into the device
    partition IN PLACE — no host round-trip (ISSUE 15).  ``src`` indexes
    the solved [rows, k] output (last finalization slot per entity — the
    host scatter's last-write-wins), ``dst`` the partition (pads are out
    of bounds, dropped).  The cast/quantization is the in-jit arithmetic
    the host staging pipeline is pinned bit-identical to
    (``store.quantize_rows_host`` ≡ ``quant.quantize_table``), so a hot
    row's device copy always matches what re-staging it from the host
    master would produce."""
    from cfk_tpu.ops import quant

    _TRACES[0] += 1
    rows = xs[src]
    if int8:
        codes, scales = quant.quantize_table(rows, "int8")
        return (hot_tbl.at[dst].set(codes, mode="drop"),
                hot_scale.at[dst].set(scales, mode="drop"))
    return (hot_tbl.at[dst].set(rows.astype(hot_tbl.dtype), mode="drop"),
            hot_scale)


@functools.lru_cache(maxsize=None)
def _hot_update_jit():
    """The scatter-back jit.  The partition pair donates on TPU only
    (``_staged_donate_argnums``: in-place update ⇒ output aliases input;
    on CPU the initial ``device_put`` zero-copy-aliases host numpy and
    jax refuses aliased donations with a warning)."""
    return jax.jit(
        _hot_update_impl, static_argnames=("int8",),
        donate_argnums=_staged_donate_argnums((), (0, 1)),
    )


@functools.partial(
    jax.jit,
    static_argnames=("local", "lam", "solver", "fused_epilogue",
                     "reg_solve_algo", "out_dtype"),
    # NOT donated: the solve's [local, k] output is smaller than either
    # accumulator, so no output can alias them — XLA refuses the
    # donation ("donated buffers were not usable") and nothing is
    # reclaimed.  The window-boundary donation in _ring_window_jit is
    # where the ×2→×1 accumulator reservation actually comes from.
)
def _ring_solve_jit(acc_a, acc_b, cnt, *, local, lam, solver,
                    fused_epilogue, reg_solve_algo, out_dtype):
    from cfk_tpu.ops.solve import regularized_solve

    _TRACES[0] += 1
    x = regularized_solve(
        acc_a[:local], acc_b[:local], cnt, lam, solver,
        fused=fused_epilogue, algo=reg_solve_algo,
    )
    return x.astype(jax.numpy.dtype(out_dtype))


class WindowIntegrityError(RuntimeError):
    """A staged window's bytes no longer match the host store's (torn or
    corrupted transfer, caught by the staging checksum — the window
    analog of the checkpoint crc32 contract)."""


def hier_visit_order(num_shards: int, inner: int, shard: int) -> list[int]:
    """The slice visit order of ``parallel.spmd.half_step_tiled_ring_hier``
    for one shard: phases walk the outer (DCN) ring, inner steps walk the
    ICI ring — ``held(p, j) = ((g−p)%O)·I + (i+p−j)%I``.  ``inner ==
    num_shards`` degenerates to the flat ring's ``(shard − r) % S``
    order, which is the exchange='ring' schedule (the bit-identity the
    resident paths already pin)."""
    if inner < 1 or num_shards % inner != 0:
        raise ValueError(
            f"inner ring size {inner} must divide num_shards={num_shards}"
        )
    outer = num_shards // inner
    g, i_pos = shard // inner, shard % inner
    return [
        ((g - p) % outer) * inner + (i_pos + p - j) % inner
        for p in range(outer) for j in range(inner)
    ]


def _stage_table(fixed_store: HostFactorStore, rows: np.ndarray, *,
                 stage_np, int8: bool, faults, iteration: int, side: str,
                 window: int, shard: int, verify_windows: bool,
                 stats: dict | None, home_shard: int, ici_group: int):
    """Gather + (optionally) quantize one window's table rows on the host
    — the staging pipeline up to the ``device_put`` hand-off.

    Fault hooks and the integrity checksum run on the GATHERED rows
    (before quantization, so a NaN fault poisons the int8 scale exactly
    as the resident in-jit quantization would); the fabric attribution
    meters which store shard each row came from relative to the compute
    shard's home (local / same-ICI-group / DCN — the hier exchange's
    payload accounting)."""
    import zlib

    if faults is not None:
        faults.delay(iteration, side, window, shard=shard)
    tbl = fixed_store.gather(rows)
    if not int8 and tbl.dtype != stage_np:
        tbl = tbl.astype(stage_np)
    src_crc = zlib.crc32(tbl.tobytes()) if verify_windows else None
    # The fault hook models in-flight staging corruption: it fires
    # BETWEEN the source checksum and the device transfer.
    if faults is not None:
        tbl = faults.apply_window(iteration, side, window, tbl,
                                  shard=shard)
    if verify_windows and zlib.crc32(tbl.tobytes()) != src_crc:
        raise WindowIntegrityError(
            f"shard {shard} side {side!r} iteration {iteration} window "
            f"{window}: staged bytes diverge from the host store "
            "(torn/corrupt transfer)"
        )
    if int8:
        data, scale = quantize_rows_host(tbl)
    else:
        data, scale = tbl, None
    if stats is not None and fixed_store.num_shards > 1:
        owners = fixed_store.shard_of_rows(rows)
        home = (owners == home_shard)
        group = (owners // max(ici_group, 1)
                 == home_shard // max(ici_group, 1))
        # stats_add: staging may run on pool worker threads (ISSUE 13),
        # where an unguarded read-modify-write would lose counts.
        stats_add(stats, "rows_local", int(home.sum()))
        stats_add(stats, "rows_ici", int((group & ~home).sum()))
        stats_add(stats, "rows_dcn", int((~group).sum()))
    return data, scale


def _stage_window(fixed_store: HostFactorStore, plan_obj, w: int, *,
                  stage_np, int8: bool, faults, iteration: int, side: str,
                  shard: int, verify_windows: bool, stats: dict | None,
                  ici_group: int) -> tuple:
    """Stage window ``w`` of either plan kind (the stream ``WindowPlan``
    or the ``RingWindowPlan`` — both expose rows / neighbor_idx /
    stage_chunks): host gather + optional quantization + checksum via
    ``_stage_table``, staged-bytes metering, then the ``device_put``
    hand-off.  ONE copy of the metering so the bench rows recorded from
    both execution shapes can never drift apart."""
    data, scale = _stage_table(
        fixed_store, plan_obj.rows[w], stage_np=stage_np, int8=int8,
        faults=faults, iteration=iteration, side=side, window=w,
        shard=shard, verify_windows=verify_windows, stats=stats,
        home_shard=shard, ici_group=ici_group,
    )
    host = (data, scale, plan_obj.neighbor_idx[w],
            *plan_obj.stage_chunks(w))
    if stats is not None:
        stats_add(stats, "windows_staged", 1)
        # The FULL staged working set — table (+ int8 scales) AND chunk
        # arrays — the same quantity the per-window budget was sized
        # against (staged_bytes_per_window), so the recorded arithmetic
        # reproduces the sizing decision.  The chunk arrays are
        # zero-copy VIEWS of the block arrays on the host, but they
        # still cross PCIe per window — staged bytes meter the transfer,
        # not host allocations.  The TABLE share is metered separately
        # as staged_cold_bytes: the bytes the staging dtype AND the hot
        # cache lever (with the cache off — this path — every table row
        # is "cold"; int8 (codes, scales) ≈ ¼ of f32, the honest
        # per-dtype ratio the bench rows record).  Metered from the HOST
        # arrays BEFORE the device_put hand-off — the device (tbl,
        # scale) pair is donated through the window jit (ISSUE 13), so
        # nothing may read it after dispatch.
        stats_add(stats, "staged_bytes",
                  sum(a.nbytes for a in host if a is not None))
        stats_add(stats, "staged_cold_bytes",
                  data.nbytes + (scale.nbytes if scale is not None else 0))
        # rows_staged counts REAL table rows (pre-pad) on every staging
        # path — full windows here, the delta path in
        # _stage_window_delta, and the window_stage span attrs all agree
        # — while the byte meters above record the PADDED transfer.
        stats_add(stats, "rows_staged", int(plan_obj.row_counts[w]))
    # ONE pytree device_put for the whole window (None leaves pass
    # through): per-array puts paid jax dispatch overhead 7-10× per
    # window, which dominated staging at small windows — one issue per
    # window is also the shape a real PCIe queue wants.
    return jax.device_put(host)


def _stage_window_delta(fixed_store: HostFactorStore, plan_obj, hmap, w: int,
                        *, stage_np, int8: bool, faults, iteration: int,
                        side: str, shard: int, verify_windows: bool,
                        stats: dict | None, ici_group: int) -> tuple:
    """Stage window ``w``'s COLD DELTA (ISSUE 15): only the cold rows the
    predecessor window in the schedule did not already stage cross PCIe —
    the hot partition and the device-kept rows are assembled on device by
    ``_assemble_jit``.  Gather + quantize + checksum run through the SAME
    ``_stage_table`` as full staging (the fault hooks and the crc32
    integrity contract see exactly the bytes that ship), then the delta
    pads to its pow2 bucket (static jit shapes; the pad rows scatter out
    of bounds and are dropped)."""
    rows = hmap.delta_rows[w]
    data, scale = _stage_table(
        fixed_store, rows, stage_np=stage_np, int8=int8, faults=faults,
        iteration=iteration, side=side, window=w, shard=shard,
        verify_windows=verify_windows, stats=stats, home_shard=shard,
        ici_group=ici_group,
    )
    d = int(rows.shape[0])
    bucket = hmap.delta_bucket(w)
    pad = np.zeros((bucket, fixed_store.rank), dtype=data.dtype)
    pad[:d] = data
    if scale is not None:
        ps = np.zeros((bucket,), dtype=np.float32)
        ps[:d] = scale
        scale = ps
    data = pad
    host = (data, scale, plan_obj.neighbor_idx[w],
            *plan_obj.stage_chunks(w))
    if stats is not None:
        stats_add(stats, "windows_staged", 1)
        # Same metering seam as full staging: staged_bytes is the whole
        # transfer (delta table + chunk arrays), staged_cold_bytes the
        # table share that actually shipped — the quantity the hot
        # engine exists to cut, recorded at the PADDED bucket size (the
        # honest transfer, not the pre-pad row count).
        stats_add(stats, "staged_bytes",
                  sum(a.nbytes for a in host if a is not None))
        stats_add(stats, "staged_cold_bytes",
                  data.nbytes + (scale.nbytes if scale is not None else 0))
        stats_add(stats, "rows_staged", d)
        stats_add(stats, "rows_delta_skipped", int(hmap.keep_dst[w].size))
        stats_add(stats, "rows_hot_device", int(hmap.hot_dst[w].size))
    return jax.device_put(host)


class HotPartition:
    """One fixed side's device-resident hot rows (ISSUE 15), stored
    dequant-ready at the STAGING dtype: f32/bf16 data, or the (int8
    codes, f32 per-row scales) pair — exactly the bytes full staging
    would have shipped for these rows, so a window assembled from the
    partition is bitwise the fully-staged window.

    The host master store stays ground truth: ``rebuild`` re-gathers the
    partition from it (driver rollback — a poisoned partition is erased
    by the same snapshot restore that heals the stores), while the
    steady-state updates come from ``_hot_update_jit``'s in-place device
    scatter-back (no host round-trip)."""

    def __init__(self, rows: np.ndarray, stage_name: str) -> None:
        self.rows = np.asarray(rows, dtype=np.int64)
        self.stage_name = stage_name
        self.int8 = stage_name == "int8"
        self._stage_np = None if self.int8 else _np_dtype(stage_name)
        self.data = None
        self.scale = None

    @property
    def num_rows(self) -> int:
        return int(self.rows.shape[0])

    @property
    def nbytes(self) -> int:
        if self.data is None:
            return 0
        return int(self.data.nbytes
                   + (self.scale.nbytes if self.scale is not None else 0))

    def rebuild(self, store: HostFactorStore) -> None:
        """(Re)gather the partition from the host master — the initial
        build and the rollback path share it, so a recovered run's
        partition is bit-identical to a fresh one."""
        tbl = store.gather(self.rows)
        if tbl.shape[0] == 0:
            # A 0-row side still participates in the delta engine (the
            # other side may be the hot one); keep one zeros row so the
            # assembly's padded gathers stay in bounds (pad destinations
            # are out of bounds and dropped, so the value is never used).
            tbl = np.zeros((1, store.rank), dtype=tbl.dtype)
        if self.int8:
            data, scale = quantize_rows_host(tbl)
        else:
            data = (tbl if tbl.dtype == self._stage_np
                    else tbl.astype(self._stage_np))
            scale = None
        self.data = jax.device_put(data)
        self.scale = None if scale is None else jax.device_put(scale)

    def poison(self, rows: np.ndarray) -> None:
        """Chaos seam: NaN the given PARTITION positions in the device
        copy (the int8 pair poisons the scale — the only leaf that can
        go nonfinite, same as the in-flight quantization contract).  The
        host master is untouched, so rollback + ``rebuild`` recovers
        bit-exactly."""
        import jax.numpy as jnp

        rows = np.asarray(rows, dtype=np.int32)
        if self.int8:
            self.scale = self.scale.at[rows].set(jnp.nan, mode="drop")
        else:
            self.data = self.data.at[rows].set(
                jnp.asarray(np.nan, self.data.dtype), mode="drop"
            )


class _HotHalf:
    """One (side, shard)'s view of the hot/delta engine for a half-step:
    the FIXED side's partition (read by window assembly), the SOLVE
    side's partition (scatter-back target), this shard's window split
    map, and the device-resident index constants (built once — they are
    plan-time constants, so only the delta table pays PCIe per
    iteration)."""

    def __init__(self, fixed: HotPartition, solve: HotPartition | None,
                 hmap, sb_maps) -> None:
        self.fixed = fixed
        self.solve = solve
        self.hmap = hmap
        self.sb = sb_maps  # stream: {w: (src, dst)}; ring: (src, dst)
        r = hmap.window_rows
        self._idx = {}
        for w in hmap.prev_of:
            hp, kp = hmap.hot_pad, hmap.keep_pad
            bucket = hmap.delta_bucket(w)
            self._idx[w] = jax.device_put((
                _pad_idx(hmap.keep_dst[w], kp, r),
                _pad_idx(hmap.keep_src[w], kp, 0),
                _pad_idx(hmap.delta_dst[w], bucket, r),
                _pad_idx(hmap.hot_dst[w], hp, r),
                _pad_idx(hmap.hot_src[w], hp, 0),
            ))
        if isinstance(sb_maps, dict):
            pad = max((v[0].size for v in sb_maps.values()), default=0)
            self.sb_pad = pad
            f = solve.num_rows if solve is not None else 0
            self._sb_idx = {
                w: jax.device_put((_pad_idx(src, pad, 0),
                                   _pad_idx(dst, pad, f)))
                for w, (src, dst) in sb_maps.items()
            } if pad else {}
        else:
            self.sb_pad = 0 if sb_maps is None else int(sb_maps[0].size)
            self._sb_idx = (None if not self.sb_pad
                            else jax.device_put(tuple(sb_maps)))

    def idx(self, w):
        return self._idx[w]

    def sb_idx(self, w=None):
        return self._sb_idx if w is None else self._sb_idx.get(w)


def _fixed_rows_of(plan_obj) -> int:
    """The fixed-table row space a plan's windows gather from — the
    store's own row count (stream plans record it; ring plans address
    slice·H + local over every slice)."""
    if hasattr(plan_obj, "table_rows"):
        return int(plan_obj.table_rows)
    return int(plan_obj.num_slices * plan_obj.statics[3])


def _pad_idx(arr: np.ndarray, width: int, pad_val: int) -> np.ndarray:
    out = np.full((max(int(width), 1),), pad_val, dtype=np.int32)
    out[: arr.size] = arr
    return out


def _hot_zero_prev(window_rows: int, rank: int, stage_name: str):
    """The chain head's predecessor: a zeros (tbl, scale) pair at the
    staging dtype (nothing is kept from it — the first window of every
    schedule stages its full cold set as delta)."""
    import jax.numpy as jnp

    if stage_name == "int8":
        return (jnp.zeros((window_rows, rank), jnp.int8),
                jnp.zeros((window_rows,), jnp.float32))
    dt = jnp.bfloat16 if stage_name == "bfloat16" else jnp.float32
    return jnp.zeros((window_rows, rank), dt), None


def _own_stager(fixed_store, plan_obj, schedule, *, table_dtype, faults,
                iteration, side, shard, verify_windows, stats, ici_group,
                hot=None) -> WindowStager:
    """A single-shard SERIAL stager for direct half-step callers (tests,
    library use): byte-for-byte the PR 10/11 schedule — staging runs on
    the consuming thread at the classic double-buffer positions.  The
    sharded driver passes a shared pooled stager instead.  With ``hot``
    (a ``_HotHalf``), tasks stage the cold delta instead of the full
    window."""
    stage_name = _stage_dtype(fixed_store.dtype, table_dtype)
    int8 = stage_name == "int8"
    stage_np = None if int8 else _np_dtype(stage_name)

    def stage_task(d, w):
        if hot is not None:
            return _stage_window_delta(
                fixed_store, plan_obj, hot.hmap, w, stage_np=stage_np,
                int8=int8, faults=faults, iteration=iteration, side=side,
                shard=d, verify_windows=verify_windows, stats=stats,
                ici_group=ici_group,
            )
        return _stage_window(
            fixed_store, plan_obj, w, stage_np=stage_np, int8=int8,
            faults=faults, iteration=iteration, side=side, shard=d,
            verify_windows=verify_windows, stats=stats,
            ici_group=ici_group,
        )

    return WindowStager([(shard, w) for w in schedule], stage_task,
                        mode="serial", stats=stats,
                        span_attrs=lambda d, w: _stage_span_attrs(
                            hot.hmap if hot is not None else None,
                            plan_obj, w))


def _stage_span_attrs(hmap, plan_obj, w: int) -> dict:
    """The ``window_stage`` span attrs (ISSUE 15): rows_staged /
    rows_delta_skipped / rows_hot per window, so the trace shows the
    reuse.  ONE copy shared by the direct half-step callers and the
    sharded driver (the PR 11 no-two-meters discipline) — rows are REAL
    (pre-pad) counts, matching the ``rows_staged`` stats key.  Plan-time
    constants: a pure lookup, safe on worker threads."""
    if hmap is None:
        return {"rows_staged": int(plan_obj.row_counts[w])}
    return {
        "rows_staged": int(len(hmap.delta_rows[w])),
        "rows_delta_skipped": int(hmap.keep_dst[w].size),
        "rows_hot": int(hmap.hot_dst[w].size),
    }


def windowed_half_step(
    fixed_store: HostFactorStore, wplan: WindowPlan, *, lam: float,
    out_dtype: str = "float32", solver: str = "auto", overlap=None,
    fused_epilogue=None, in_kernel_gather=None, reg_solve_algo=None,
    table_dtype: str | None = None, faults=None, iteration: int = 0,
    side: str = "", stats: dict | None = None, verify_windows: bool = False,
    shard: int = 0, ici_group: int = 1, stager: WindowStager | None = None,
    hot: "_HotHalf | None" = None, host: int = 0,
) -> np.ndarray:
    """Solve one shard's entities against a host-resident fixed table,
    window by window (the stream-mode / all_gather-exchange scan).
    Returns the solved [local_entities, rank] host array in ``out_dtype``
    (untouched rows zero — exactly the resident scatter's output).
    ``faults`` (chaos only) is a ``resilience.faults.WindowFaultInjector``;
    ``verify_windows`` checksums each staged window at the store (crc32
    before the staging hand-off) against what is about to ship, and
    raises ``WindowIntegrityError`` on a mismatch — NaN poisoning is
    caught by the factor sentinel either way, but a TORN window is
    finite-and-wrong, which only an integrity check can see.  Scope is
    the HOST staging pipeline up to the ``device_put`` hand-off (which is
    where the chaos fault hook models its corruption); verifying the PCIe
    DMA itself would need a device-side checksum — on-TPU follow-up.

    ``stager`` (ISSUE 13): the staging engine serving this shard's
    windows — the sharded driver passes ONE pooled stager shared across
    every shard of a half-iteration, so shard d+1's staging overlaps
    shard d's compute on worker threads.  ``None`` builds a private
    serial stager (the classic double-buffer schedule, unchanged
    behavior for direct callers); the faults/verify/stats arguments
    configure only that private stager — a shared stager carries its
    own."""
    k = fixed_store.rank
    out = np.zeros((wplan.local_entities, k), dtype=_np_dtype(out_dtype))
    n_w = wplan.num_windows
    own = stager is None
    if own:
        stager = _own_stager(
            fixed_store, wplan, wplan.schedule(), table_dtype=table_dtype,
            faults=faults, iteration=iteration, side=side, shard=shard,
            verify_windows=verify_windows, stats=stats,
            ici_group=ici_group, hot=hot,
        )
    half_kw = dict(
        statics=wplan.statics, lam=float(lam), solver=solver,
        overlap=overlap, fused_epilogue=fused_epilogue,
        in_kernel_gather=in_kernel_gather, reg_solve_algo=reg_solve_algo,
        table_dtype=table_dtype, out_dtype=out_dtype,
    )
    stage_name = _stage_dtype(fixed_store.dtype, table_dtype)
    prev = (None if hot is None
            else _hot_zero_prev(wplan.window_rows, k, stage_name))
    try:
        staged = stager.take() if n_w else None
        for w in range(n_w):
            # DISPATCH window w's compute first (jit dispatch is async),
            # THEN take window w+1 — a serial stager runs the host gather
            # + device_put HERE, under the dispatched compute (the PR 10
            # double buffer); a pooled stager usually has it already
            # staged by a worker — and only then join w's result.  The
            # compute span covers dispatch → join, so a pooled staging
            # worker's window_stage span visibly overlaps it.
            with span("train/iter/half_step/window_compute",
                      side=side, shard=shard, window=w, host=host):
                if hot is None:
                    xs = _window_half_jit()(*staged, **half_kw)
                else:
                    # Assemble from delta + predecessor + hot partition,
                    # then the SAME window program (one trace — the
                    # assembled table shape never changes) WITHOUT the
                    # staged donation (the next window reuses this one).
                    delta, dscale, *rest = staged
                    tbl, scale = _assemble_jit()(
                        delta, dscale, *prev,
                        hot.fixed.data, hot.fixed.scale, *hot.idx(w),
                        window_rows=wplan.window_rows,
                        int8=hot.fixed.int8,
                    )
                    xs = _window_half_hot_jit()(tbl, scale, *rest,
                                                **half_kw)
                    prev = (tbl, scale)
                    sb = hot.sb_idx(w)
                    if sb is not None:
                        # Solved hot rows of THIS side scatter back into
                        # its partition in place — no host round-trip.
                        hot.solve.data, hot.solve.scale = _hot_update_jit()(
                            hot.solve.data, hot.solve.scale, xs, *sb,
                            int8=hot.solve.int8,
                        )
                nxt = stager.take() if w + 1 < n_w else None
                xs_np = np.asarray(xs)
            ent = wplan.chunk_entity_of(w)
            real = ent < wplan.local_entities
            out[ent[real]] = xs_np[real]
            staged = nxt
    finally:
        if own:
            stager.close()
    return out


def ring_windowed_half_step(
    fixed_store: HostFactorStore, rplan: RingWindowPlan, *, lam: float,
    visits: list[int], count_local: np.ndarray, out_dtype: str = "float32",
    solver: str = "auto", overlap=None, fused_epilogue=None,
    in_kernel_gather=None, reg_solve_algo=None,
    table_dtype: str | None = None, faults=None, iteration: int = 0,
    side: str = "", stats: dict | None = None, verify_windows: bool = False,
    shard: int = 0, ici_group: int = 1, stager: WindowStager | None = None,
    hot: "_HotHalf | None" = None, host: int = 0,
) -> np.ndarray:
    """One shard's ring/hier-ring half-iteration against staged windows.

    ``visits`` is the slice visit order the resident exchange would
    deliver blocks in (``hier_visit_order``); per visit, the slice's
    windows stage ahead (the shared pooled ``stager``, or a private
    serial one — see ``windowed_half_step``) while the persistent
    per-entity Gram accumulator — the SAME [E_local+1, k(,k)] carry the
    resident ring holds, DONATED through each window call so input and
    output never coexist (ISSUE 13) — absorbs each window's chunk Grams.
    One solve at the end.  The staged window is the slice rows this
    shard's chunks actually reference (the window residual) — never the
    whole block, which is how the flat ring's O(S) full-table traffic
    disappears."""
    import jax.numpy as jnp

    from cfk_tpu.ops.tiled import (
        default_tiled_gram_backend,
        resolve_gather_mode,
    )

    k = fixed_store.rank
    nc, cap, t, h, e_c = rplan.statics
    nt = cap // t
    local = rplan.local_entities
    backend = default_tiled_gram_backend()
    gather = resolve_gather_mode(
        in_kernel_gather, backend, "full", cap, nt, t, e_c + 1, k,
    )
    stage_name = _stage_dtype(fixed_store.dtype, table_dtype)
    int8 = stage_name == "int8"
    schedule = rplan.schedule(visits)
    own = stager is None
    if own:
        stager = _own_stager(
            fixed_store, rplan, schedule, table_dtype=table_dtype,
            faults=faults, iteration=iteration, side=side, shard=shard,
            verify_windows=verify_windows, stats=stats,
            ici_group=ici_group, hot=hot,
        )
    acc_a = jnp.zeros((local + 1, k, k), jnp.float32)
    acc_b = jnp.zeros((local + 1, k), jnp.float32)
    prev = (None if hot is None
            else _hot_zero_prev(rplan.window_rows, k, stage_name))
    try:
        staged = stager.take() if schedule else None
        for i, w in enumerate(schedule):
            # Dispatch this window's accumulation (async), then take the
            # next visit's window under it — the inner-ICI-rotation
            # overlap of the resident hier ring, one level up.  The
            # donated carry rebinds; nothing may read the pre-call pair.
            # The ring_visit span is the exchange-phase timeline: visit
            # order IS the block-delivery order the resident ring/hier
            # ring would rotate, so the trace shows each phase's staging
            # (window residual — the DCN-hop payload) against compute.
            with span("train/iter/half_step/ring_visit",
                      side=side, shard=shard, visit=i, window=w,
                      host=host):
                if hot is None:
                    acc_a, acc_b = _ring_window_jit()(
                        acc_a, acc_b, *staged,
                        statics=(rplan.window_chunks, cap, t, e_c),
                        backend=backend, gather=gather, int8=int8,
                    )
                else:
                    delta, dscale, *rest = staged
                    tbl, scale = _assemble_jit()(
                        delta, dscale, *prev,
                        hot.fixed.data, hot.fixed.scale, *hot.idx(w),
                        window_rows=rplan.window_rows,
                        int8=hot.fixed.int8,
                    )
                    acc_a, acc_b = _ring_window_hot_jit()(
                        acc_a, acc_b, tbl, scale, *rest,
                        statics=(rplan.window_chunks, cap, t, e_c),
                        backend=backend, gather=gather, int8=int8,
                    )
                    prev = (tbl, scale)
                staged = (stager.take() if i + 1 < len(schedule) else None)
    finally:
        if own:
            stager.close()
    with span("train/iter/half_step/ring_solve", side=side, shard=shard):
        x = _ring_solve_jit(
            acc_a, acc_b, jax.numpy.asarray(count_local), local=local,
            lam=float(lam), solver=solver, fused_epilogue=fused_epilogue,
            reg_solve_algo=reg_solve_algo, out_dtype=out_dtype,
        )
        if hot is not None and hot.sb_pad:
            # The ring modes solve once at the end: one in-place scatter
            # of this shard's hot solve rows back into the partition.
            hot.solve.data, hot.solve.scale = _hot_update_jit()(
                hot.solve.data, hot.solve.scale, x, *hot.sb_idx(),
                int8=hot.solve.int8,
            )
        x = np.asarray(x)
    return x


def _resolve_side_modes(dataset, config: ALSConfig
                        ) -> tuple[bool, bool]:
    """(movie_side_ring, user_side_ring) — which execution shape each
    half runs, mirroring the resident trainer's resolution EXACTLY: the
    ring exchanges apply only at num_shards > 1 (a single-device trainer
    never consults the exchange knob), ``exchange='auto'`` takes each
    half's ring flag AS BUILT (the resident per-side memory optimum,
    ``spmd.gathered_layout_trees``), and the explicit exchanges require
    matching blocks (validated by ``_blocks_for``)."""
    from cfk_tpu.data.blocks import TiledBlocks

    if config.num_shards == 1 or config.exchange == "all_gather":
        return False, False
    if config.exchange in ("ring", "hier_ring"):
        return True, True
    # exchange == "auto": per-side, from how the blocks were built.
    mb, ub = dataset.movie_blocks, dataset.user_blocks
    return (
        bool(isinstance(mb, TiledBlocks) and mb.ring),
        bool(isinstance(ub, TiledBlocks) and ub.ring),
    )


def _blocks_for(dataset, config: ALSConfig, tile_rows: int | None,
                ring_m: bool, ring_u: bool):
    """The tiled blocks the windowed driver runs on, per side.

    Stream (all_gather-shape) sides need stream mode at the config's
    shard count — the dataset's own blocks when they qualify, else a
    rebuild from the dense COO with accum mode disabled (accum's
    persistent [E, k, k] device accumulator is exactly the structure the
    out-of-core regime cannot hold).  Ring sides need the dataset's
    ring-built accum blocks as-is (their slice structure IS the exchange
    schedule; no rebuild can synthesize it honestly).  Mismatches raise
    with the same remedies the resident trainer gives."""
    from cfk_tpu.data.blocks import TiledBlocks, build_tiled_blocks

    s = config.num_shards
    mb, ub = dataset.movie_blocks, dataset.user_blocks

    def side_ok(blocks, ring):
        if not isinstance(blocks, TiledBlocks) or blocks.num_shards != s:
            return False
        if ring:
            return blocks.mode == "accum" and blocks.ring
        return blocks.mode == "stream" and not blocks.ring

    rebuilt = None

    def stream_rebuild():
        nonlocal rebuilt
        if rebuilt is None:
            coo = dataset.coo_dense
            t = tile_rows or (mb.tile_rows
                              if isinstance(mb, TiledBlocks) else 128)
            build = functools.partial(
                build_tiled_blocks, num_shards=s, tile_rows=t,
                chunk_elems=config.chunk_cells(), accum_max_entities=0,
            )
            m_dense = coo.movie_raw.astype(np.int64)
            u_dense = coo.user_raw.astype(np.int64)
            rebuilt = (
                build(m_dense, u_dense, coo.rating,
                      dataset.movie_map.num_entities,
                      dataset.user_map.num_entities),
                build(u_dense, m_dense, coo.rating,
                      dataset.user_map.num_entities,
                      dataset.movie_map.num_entities),
            )
        return rebuilt

    sides = (("movie", mb, ring_m, 0), ("user", ub, ring_u, 1))
    # Validate first: mismatches that cannot be rebuilt raise with the
    # resident trainer's own remedies.
    for name, blocks, ring, _ in sides:
        if ring and not side_ok(blocks, True):
            # Ring blocks cannot be synthesized here — their slice
            # structure IS the exchange schedule.
            raise ValueError(
                f"exchange={config.exchange!r} windowed training runs "
                f"the {name} half on ring-built tiled blocks at "
                f"num_shards={s}; rebuild with Dataset.from_coo(..., "
                f"layout='tiled', num_shards={s}, ring=True)"
            )
        if (not ring and isinstance(blocks, TiledBlocks) and blocks.ring):
            # Mirror the resident trainer: an all_gather half on
            # ring-built blocks raises there too — silently rebuilding
            # would train a different exchange schedule than the
            # resident path the bit-exactness contract compares against.
            raise ValueError(
                f"exchange={config.exchange!r} runs the {name} half as "
                "a stream scan, but its blocks were ring-built; pass "
                "exchange='ring'/'hier_ring' (the windowed ring driver) "
                "or rebuild with ring=False"
            )
    # If ANY stream side needs the rebuild, rebuild EVERY stream side:
    # mixing dataset-built and driver-rebuilt stream blocks could differ
    # in chunking (the dataset's build parameters vs the config's), and
    # one consistent build is the PR 10 discipline.
    rebuild_streams = any(
        not ring and not side_ok(blocks, False)
        for _, blocks, ring, _ in sides
    )
    out = [
        stream_rebuild()[idx] if (not ring and rebuild_streams)
        else blocks
        for _, blocks, ring, idx in sides
    ]
    return out[0], out[1]


def _probe(u: np.ndarray, m: np.ndarray, norm_limit: float | None) -> str | None:
    """Host-side sentinel over the solved stores: NaN/Inf anywhere, or a
    factor-row 2-norm past the watchdog limit.  Returns the trip reason or
    None (the same reason vocabulary as ``resilience.sentinel``)."""
    for name, x in (("user", u), ("movie", m)):
        xf = np.asarray(x, dtype=np.float32)
        if not np.isfinite(xf).all():
            return f"nonfinite {name} factors"
        if norm_limit is not None:
            n = float(np.sqrt((xf * xf).sum(axis=1)).max()) if xf.size else 0.0
            if n > norm_limit:
                return f"{name} row norm {n:.3g} > {norm_limit:.3g}"
    return None


def resolve_window_inner(config: ALSConfig) -> int:
    """The windowed driver's inner-ring size: the SAME resolution the
    resident hier ring uses (``parallel.spmd.resolve_ici_group``) for
    ``hier_ring`` — visit order must match the exchange being replaced —
    and one flat ring otherwise."""
    if config.exchange == "hier_ring":
        from cfk_tpu.parallel.spmd import resolve_ici_group

        return resolve_ici_group(config)
    return config.num_shards


def train_als_host_window(
    dataset,
    config: ALSConfig,
    *,
    metrics=None,
    window_faults=None,
    tile_rows: int | None = None,
    chunks_per_window: int | None = None,
    device_budget_bytes: float | None = None,
    plan_provenance=None,
    verify_windows: bool | None = None,
    staging: str | None = None,
    pool_depth: int | None = None,
    hot_rows: int | None = None,
    checkpoint_manager=None,
    checkpoint_every: int = 1,
    watchdog=None,
    fleet=None,
    fleet_manifests=None,
):
    """ALS-WR with host-resident factor tables and windowed half-steps.

    Same math, init, and iteration order as ``train_als`` (one shard) or
    ``parallel.spmd.train_als_sharded`` (sharded — all_gather, ring, or
    hier_ring exchange) on the same tiled blocks — bit-exact at every
    supported knob (``tests/test_offload.py`` /
    ``tests/test_offload_sharded.py``).  Explicit ALS, ``layout='tiled'``.
    Under ``jax.distributed`` with ``process_count() > 1`` the SAME entry
    point runs the fleet mode (ISSUE 17): each process keeps only its
    own entity-range store slice and the hier-ring DCN phases allgather
    the cold window residual (``offload/exchange.py``) into a read-only
    mirror — factors stay crc-identical to the one-process driver, whose
    per-shard schedules are the degenerate single-host case;
    divergence recovery runs the PR 3 ladder against in-RAM last-good
    snapshots of the stores (each rung is recorded with the loop
    vocabulary and as a plan transition when provenance rides along).

    ``device_budget_bytes`` bounds the staged working set PER SHARD
    (default: the detected device's HBM through ``offload.budget`` — the
    SAME predicate the planner gates the ``device`` tier with);
    ``chunks_per_window`` overrides the derived window size.

    ``hot_rows`` (ISSUE 15) sizes the skew-aware hot-row device cache:
    ``None`` defers to ``config.hot_rows`` (whose ``None`` default is
    AUTO — the coverage-curve knee of the plans' own cross-window
    reference counts, clamped by the budget headroom left after the
    accumulator + window + delta-arena reservations); ``0`` pins the
    cache OFF (byte-for-byte the PR 12 engine); ``>= 1`` pins the TOTAL
    resident row count across both sides (split proportionally to each
    side's reference mass), raising when the reservation cannot fit —
    the same loud-refusal convention as the per-window budget.  With the
    cache on, windows stage only their COLD DELTA vs the schedule
    predecessor; factors are crc-identical across the knob (the
    assembled window tables are bitwise the fully-staged ones).

    ``staging`` (ISSUE 13) picks the host staging engine's mode —
    ``"pool"`` (the default: one bounded thread pool per half-iteration
    stages every shard's windows ahead of consumption, overlapping the
    host gather/quantize/checksum/``device_put`` across shards AND
    windows) or ``"serial"`` (the PR 10/11 one-thread double buffer, the
    A/B baseline) — defaulting to ``config.staging``.  ``pool_depth``
    bounds the staged-ahead windows (default ``config.staging_pool_depth``
    or ``offload.staging.DEFAULT_POOL_DEPTH``), and is always CLAMPED so
    ``depth + 1`` worst-case windows fit the per-shard staging budget
    next to the ring accumulator reservation (``budget.max_pool_depth``
    — the staging-arena term).  Both modes are crc-identical to each
    other and to the resident paths.

    ``fleet`` injects the multi-process transport explicitly (the
    threaded elastic harness and tests; ``None`` auto-detects the jax
    runtime as before).  ``fleet_manifests`` — a
    ``cfk_tpu.offload.elastic.FleetManifests`` over the fleet's shared
    per-host checkpoint tree — arms **elastic membership** (ISSUE 20,
    overridable via ``config.fleet_elastic``): a dead peer triggers the
    shrink protocol (min-agree the last jointly covered step from the
    manifests, repartition ownership over the survivors, reload the
    orphaned slice from committed bytes, roll back, continue) instead
    of an exit, and a restarted host passed a ``fleet`` whose
    ``is_joiner`` is set rejoins at an iteration boundary via the
    health-gated readmission handshake.  Factors reconverge
    crc-identical to the uninterrupted run (shard-count-invariant init
    + committed-byte reload).
    """
    from cfk_tpu.config import enable_compile_cache
    from cfk_tpu.ops.solve import init_factors_stats
    from cfk_tpu.resilience.policy import (
        Overrides,
        TrainingDivergedError,
        policy_from_config,
    )
    from cfk_tpu.transport.checkpoint import should_save
    from cfk_tpu.utils.metrics import Metrics

    enable_compile_cache(getattr(config, "compile_cache_dir", None))
    if config.algorithm != "als":
        raise ValueError(
            f"host-window offload supports the explicit ALS optimizer; "
            f"algorithm={config.algorithm!r} (iALS needs the global YᵀY "
            "over the full fixed table — an out-of-core reduction is the "
            "documented follow-up)"
        )
    if config.layout != "tiled":
        raise ValueError(
            f"host-window offload streams the tiled layout; "
            f"layout={config.layout!r}"
        )
    # Fleet mode (ISSUE 17): under a multi-process jax runtime each
    # process owns only its contiguous shard block's store slice and the
    # halves exchange cold window residuals over the hier-ring's DCN
    # phases (offload.exchange).  Everything below that reads or writes
    # a factor table goes through the slice store or its ResidualMirror;
    # the single-process path is byte-for-byte untouched.
    from cfk_tpu.offload import elastic as _elastic
    from cfk_tpu.offload import exchange as _exchange

    metrics = metrics if metrics is not None else Metrics()
    if fleet is None and jax.process_count() > 1:
        fleet = _exchange.GlooFleet()
    joiner = fleet is not None and getattr(fleet, "is_joiner", False)
    if fleet is not None and not joiner:
        if config.num_shards % fleet.num_processes != 0:
            raise ValueError(
                f"num_shards={config.num_shards} must be divisible by "
                f"the fleet size ({fleet.num_processes} processes) for "
                "contiguous shard-block store ownership"
            )
    # Elastic membership (ISSUE 20): armed when per-host fleet manifests
    # are available (config.fleet_elastic overrides).  The transport is
    # wrapped for transient-vs-fatal classification — retried transient
    # collective failures never shrink the fleet; exhaustion or a fatal
    # error raises PeerDeadError, which the loop turns into the shrink
    # protocol instead of an exit.
    elastic_on = fleet is not None and (
        config.fleet_elastic if config.fleet_elastic is not None
        else fleet_manifests is not None
    )
    if elastic_on and fleet_manifests is None:
        raise ValueError(
            "fleet_elastic=True needs fleet_manifests (the shrink "
            "protocol agrees on and reloads from the per-host manifest "
            "tree); pass a cfk_tpu.offload.elastic.FleetManifests"
        )
    if elastic_on and not isinstance(fleet, _elastic.ElasticFleet):
        fleet = _elastic.ElasticFleet(
            fleet,
            retry=_elastic.RetryPolicy(
                attempts=config.fleet_retry_attempts,
                base=config.fleet_retry_base_s,
                max_delay=config.fleet_retry_max_delay_s,
            ),
            collective_timeout_s=config.fleet_collective_timeout_s,
            metrics=metrics,
        )
    fleet_epoch = 0
    s = config.num_shards
    ring_m, ring_u = _resolve_side_modes(dataset, config)
    any_ring = ring_m or ring_u
    inner = resolve_window_inner(config) if any_ring else max(s, 1)
    with metrics.phase("window_plan"):
        mb, ub = _blocks_for(dataset, config, tile_rows, ring_m, ring_u)
        stage_name = _stage_dtype(config.dtype, config.table_dtype)
        cell_bytes, row_overhead = _stage_cell_bytes(stage_name)
        if device_budget_bytes is None:
            from cfk_tpu.plan import DeviceSpec

            device_budget_bytes = DeviceSpec.detect().hbm_bytes
        # The ring modes hold a persistent per-shard Gram accumulator
        # next to the staged windows; reserve it at ×1 (ISSUE 13:
        # ``_ring_window_jit`` DONATES the carry pair, so a window call's
        # output accumulator aliases its input — the ×2 the PR 11
        # dispatch boundary used to keep alive is reclaimed, which is
        # exactly why the budget now admits larger windows here) before
        # splitting the remainder across the window double buffer.
        acc_reserved = 0.0
        for blocks, ring in ((mb, ring_m), (ub, ring_u)):
            if ring:
                acc_reserved = max(
                    acc_reserved,
                    _budget.ring_accumulator_reservation(
                        blocks.local_entities, config.rank, donated=True
                    ),
                )
        per_window_budget = _budget.window_budget_bytes(
            device_budget_bytes, reserved_bytes=acc_reserved
        )

        def side_plans(blocks, fixed, ring, cpw):
            if ring:
                return [build_ring_window_plan(blocks, shard=d,
                                               chunks_per_window=cpw)
                        for d in range(s)]
            return [build_window_plan(blocks, fixed.padded_entities,
                                      chunks_per_window=cpw, shard=d)
                    for d in range(s)]

        def plans_for(cpw):
            return (side_plans(mb, ub, ring_m, cpw),
                    side_plans(ub, mb, ring_u, cpw))

        cpw = chunks_per_window or 4
        while True:
            m_plans, u_plans = plans_for(cpw)
            worst = max(
                p.staged_bytes_per_window(config.rank, cell_bytes,
                                          row_overhead_bytes=row_overhead)
                for p in (*m_plans, *u_plans)
            )
            if worst <= per_window_budget or cpw == 1:
                break
            cpw = max(1, cpw // 2)
        if worst > per_window_budget:
            raise ValueError(
                f"one staged window needs {worst / 1e6:.1f} MB but the "
                f"per-window budget is {per_window_budget / 1e6:.1f} MB "
                "((device_budget · RESIDENT_FRACTION − ring accumulator "
                "reserve) / WINDOW_BUFFERS) — lower hbm_chunk_elems so "
                "single chunks fit the budget"
            )
        # Staging engine resolution (ISSUE 13): mode from the explicit
        # argument or the config, depth clamped by the staging arena —
        # depth + 1 worst-case windows must fit the budget share next to
        # the accumulator reservation, so a deep pool can never promise
        # device memory the window sizing above did not leave free.
        staging = resolve_staging(
            staging if staging is not None
            else getattr(config, "staging", "auto"),
        )
        if pool_depth is None:
            pool_depth = (getattr(config, "staging_pool_depth", None)
                          or DEFAULT_POOL_DEPTH)
        pool_depth = max(1, min(
            int(pool_depth),
            _budget.max_pool_depth(device_budget_bytes, worst,
                                   reserved_bytes=acc_reserved),
        ))
        # --- skew-aware hot-row cache resolution (ISSUE 15) ----------
        # Decided HERE, at window-plan build time, from the plans' own
        # per-window row sets: the planner's plan field carries the
        # budget-admitted TARGET; this is the exact resolution against
        # the real reference skew.  The window sizing above is untouched
        # by the knob on purpose — hot on/off share cpw, so their
        # schedules (and therefore every bit) are identical.
        from cfk_tpu.offload import hot as _hotmod

        requested = (hot_rows if hot_rows is not None
                     else getattr(config, "hot_rows", None))
        schedules = {
            ("m", d): (m_plans[d].schedule(hier_visit_order(s, inner, d))
                       if ring_m else m_plans[d].schedule())
            for d in range(s)
        }
        schedules.update({
            ("u", d): (u_plans[d].schedule(hier_visit_order(s, inner, d))
                       if ring_u else u_plans[d].schedule())
            for d in range(s)
        })
        hot_note = None
        f_u = f_m = 0
        if requested != 0:
            row_b = _budget.stage_row_bytes(config.rank, stage_name)
            arena = max(
                p.window_rows * row_b for p in (*m_plans, *u_plans)
            )
            live = (pool_depth + 1 if staging == "pool"
                    else _budget.WINDOW_BUFFERS)
            live = max(live, _budget.WINDOW_BUFFERS)
            hot_reserved = acc_reserved + live * worst + arena
            admit = _budget.max_hot_rows(
                device_budget_bytes, config.rank, stage_name,
                reserved_bytes=hot_reserved,
            )
            # Per-side reference counts over the FIXED table each side's
            # windows gather, zeroed outside the rows the OTHER half
            # provably re-solves (so an in-place device copy can never
            # go stale vs the host master — on real data this is a
            # no-op: referenced rows have interactions, interactions
            # make solve entities).
            counts_u = _hotmod.reference_counts(
                m_plans, _fixed_rows_of(m_plans[0])
            )
            counts_m = _hotmod.reference_counts(
                u_plans, _fixed_rows_of(u_plans[0])
            )
            solved_u = np.concatenate([
                _hotmod.solved_rows_of(u_plans[d], d, ub.local_entities)
                for d in range(s)
            ]) if s else np.zeros(0, np.int64)
            solved_m = np.concatenate([
                _hotmod.solved_rows_of(m_plans[d], d, mb.local_entities)
                for d in range(s)
            ]) if s else np.zeros(0, np.int64)
            mask_u = np.zeros(counts_u.shape, bool)
            mask_u[solved_u] = True
            counts_u[~mask_u] = 0
            mask_m = np.zeros(counts_m.shape, bool)
            mask_m[solved_m] = True
            counts_m[~mask_m] = 0
            slots_u = int(counts_u.sum())
            slots_m = int(counts_m.sum())
            if requested is None:
                f_u = _hotmod.knee_hot_rows(counts_u)
                f_m = _hotmod.knee_hot_rows(counts_m)
                total = f_u + f_m
                if total > admit:
                    # Budget clamp, proportional — deterministic ints.
                    f_u = f_u * admit // max(total, 1)
                    f_m = min(admit - f_u, f_m)
                    hot_note = (f"knee clamped by budget headroom "
                                f"({admit} rows admitted)")
                else:
                    hot_note = "coverage-curve knee within headroom"
            else:
                req = int(requested)
                if not _budget.hot_reservation_fits(
                    req, config.rank, stage_name, device_budget_bytes,
                    reserved_bytes=hot_reserved,
                ):
                    need = _budget.hot_reservation_bytes(
                        req, config.rank, stage_name
                    )
                    raise ValueError(
                        f"hot_rows={req} pinned but its reservation "
                        f"({need / 1e6:.2f} MB at the {stage_name!r} "
                        f"staging dtype) exceeds the headroom left by "
                        f"the accumulator/window/delta-arena terms "
                        f"({admit * row_b / 1e6:.2f} MB ≈ {admit} rows) "
                        "— lower hot_rows, raise the device budget, or "
                        "use hot_rows=0 (the full-staging engine)"
                    )
                denom = max(slots_u + slots_m, 1)
                f_u = req * slots_u // denom
                f_m = req - f_u
                hot_note = f"pinned total {req}"
            f_u = min(f_u, int((counts_u > 0).sum()))
            f_m = min(f_m, int((counts_m > 0).sum()))
            if f_u + f_m == 0:
                hot_note = (hot_note or "") + "; resolved 0 (off)"
        hot_ctx = None
        if f_u + f_m > 0:
            rows_hot_u = _hotmod.select_hot_rows(counts_u, f_u)
            rows_hot_m = _hotmod.select_hot_rows(counts_m, f_m)
            hmaps = {
                ("m", d): _hotmod.build_hot_map(
                    m_plans[d], schedules[("m", d)], rows_hot_u)
                for d in range(s)
            }
            hmaps.update({
                ("u", d): _hotmod.build_hot_map(
                    u_plans[d], schedules[("u", d)], rows_hot_m)
                for d in range(s)
            })
            hot_ctx = {"rows_u": rows_hot_u, "rows_m": rows_hot_m,
                       "maps": hmaps, "note": hot_note}
    metrics.gauge("offload_windows_m",
                  sum(p.num_windows for p in m_plans))
    metrics.gauge("offload_windows_u",
                  sum(p.num_windows for p in u_plans))
    metrics.gauge("offload_window_rows_m",
                  max(p.window_rows for p in m_plans))
    metrics.gauge("offload_window_rows_u",
                  max(p.window_rows for p in u_plans))
    metrics.gauge("offload_chunks_per_window", cpw)
    metrics.gauge("offload_shards", s)
    metrics.gauge(
        "offload_plan_held_mb",
        round(sum(p.plan_held_bytes()
                  for p in (*m_plans, *u_plans)) / 1e6, 3),
    )
    if any_ring:
        metrics.gauge("offload_ici_group", inner)
        metrics.gauge("offload_acc_reserved_mb",
                      round(acc_reserved / 1e6, 3))
        metrics.note("offload_exchange", config.exchange)
    metrics.note("offload_staging", staging)
    if staging == "pool":
        metrics.gauge("offload_pool_depth", pool_depth)
        metrics.gauge("offload_pool_workers",
                      pool_workers_for(pool_depth))
    metrics.note("offload_hot", "on" if hot_ctx is not None else "off")
    if hot_note:
        metrics.note("offload_hot_decision", hot_note)
    if hot_ctx is not None:
        maps_all = hot_ctx["maps"].values()
        slots_total = sum(m.slots_total for m in maps_all)
        metrics.gauge("offload_hot_rows", f_u + f_m)
        metrics.gauge("offload_hot_rows_u", f_u)
        metrics.gauge("offload_hot_rows_m", f_m)
        if slots_total:
            # Reference coverage: the fraction of per-window row-slots
            # served from the device (hot partition + delta reuse) — the
            # staged-table-byte cut before pow2 padding.
            metrics.gauge("offload_hot_coverage", round(
                sum(m.slots_hot for m in hot_ctx["maps"].values())
                / slots_total, 4))
            metrics.gauge("offload_delta_coverage", round(
                sum(m.slots_kept for m in hot_ctx["maps"].values())
                / slots_total, 4))

    # Init: identical to the resident trainers (init_factors_stats drawn
    # at the REAL entity count — the shard-count-invariant init — zero
    # movie seed).
    key = jax.random.PRNGKey(config.seed)
    u0 = jax.jit(
        init_factors_stats, static_argnames=("rank", "num_entities")
    )(
        key, jax.numpy.asarray(ub.rating_sum), jax.numpy.asarray(ub.count),
        rank=config.rank, num_entities=ub.num_entities,
    ).astype(jax.numpy.dtype(config.dtype))
    u_full_init = np.asarray(u0)
    rows_u_total = ub.padded_entities
    rows_m_total = mb.padded_entities
    visits_all = [hier_visit_order(s, inner, d) for d in range(s)]
    hmaps_m = hmaps_u = rows_hot_u = rows_hot_m = None
    if hot_ctx is not None:
        hmaps_m = [hot_ctx["maps"][("m", d)] for d in range(s)]
        hmaps_u = [hot_ctx["maps"][("u", d)] for d in range(s)]
        rows_hot_u = hot_ctx["rows_u"]
        rows_hot_m = hot_ctx["rows_m"]
    u_store = m_store = None
    own_u = own_m = fleet_sides = owned_shards = None
    hot_u_part = hot_m_part = None
    hot_halves: dict = {}

    def _load_full(step: int):
        """Both full tables at committed ``step``, reassembled from
        every reachable host's manifest bytes (the elastic reload)."""
        u_full = fleet_manifests.load_rows(step, 0, rows_u_total, "u",
                                           rank=config.rank)
        m_full = fleet_manifests.load_rows(step, 0, rows_m_total, "m",
                                           rank=config.rank)
        return u_full, m_full

    def _build_hot_halves(step) -> None:
        """Hot partitions + per-(side, shard) contexts (ISSUE 15): the
        device copies gather from the masters (the movie side starts
        all-zero, exactly like its store), index constants device_put
        once — only the cold delta crosses PCIe per window from here
        on.  Rebuilt whole on every partition change (init, elastic
        shrink, rejoin): the rebuild-≡-restage invariant keeps the
        post-change bits identical to a fresh run's."""
        nonlocal hot_u_part, hot_m_part, hot_halves
        hot_halves = {}
        hot_u_part = hot_m_part = None
        if hot_ctx is None:
            return
        hot_u_part = HotPartition(hot_ctx["rows_u"], stage_name)
        hot_m_part = HotPartition(hot_ctx["rows_m"], stage_name)
        if fleet is None:
            hot_u_part.rebuild(u_store)
            hot_m_part.rebuild(m_store)
        else:
            # Fleet: the masters are slices, so the initial partitions
            # build from transient full-table views (u0 is already fully
            # materialized on every process; the movie side is zeros —
            # or, after an elastic reload, the committed bytes of
            # ``step``).  From here on each half START rebuilds the
            # FIXED side's partition from the exchange mirror — master
            # bytes, the same pinned rebuild-≡-restage invariant the
            # rollback path relies on — replacing the in-half device
            # scatter-back (disabled below: its update would be
            # process-local, and the next half's rebuild overwrites it
            # anyway).
            if step is None:
                u_full = u_full_init
                m_full = np.zeros((rows_m_total, config.rank),
                                  _np_dtype(config.dtype))
            else:
                u_full, m_full = _load_full(int(step))
            hot_u_part.rebuild(HostFactorStore.from_array(
                np.asarray(u_full, _np_dtype(config.dtype)),
                dtype=config.dtype))
            hot_m_part.rebuild(HostFactorStore.from_array(
                np.asarray(m_full, _np_dtype(config.dtype)),
                dtype=config.dtype))
        from cfk_tpu.offload import hot as _hotmod
        for d in (range(s) if fleet is None else owned_shards):
            if fleet is not None:
                # No in-half device scatter-back across a fleet (the
                # update would be process-local); the mirror rebuild at
                # each half start refreshes the partition from master
                # bytes instead.  Ring mode disables via None (guarded
                # by sb_pad), stream mode via an empty map dict.
                sb_m = None if ring_m else {}
            else:
                sb_m = (_hotmod.ring_scatter_back(d, mb.local_entities,
                                                  hot_m_part.rows)
                        if ring_m else
                        _hotmod.scatter_back_maps(m_plans[d], d,
                                                  mb.local_entities,
                                                  hot_m_part.rows))
            hot_halves[("m", d)] = _HotHalf(
                hot_u_part, hot_m_part, hot_ctx["maps"][("m", d)], sb_m,
            )
            if fleet is not None:
                sb_u = None if ring_u else {}
            else:
                sb_u = (_hotmod.ring_scatter_back(d, ub.local_entities,
                                                  hot_u_part.rows)
                        if ring_u else
                        _hotmod.scatter_back_maps(u_plans[d], d,
                                                  ub.local_entities,
                                                  hot_u_part.rows))
            hot_halves[("u", d)] = _HotHalf(
                hot_m_part, hot_u_part, hot_ctx["maps"][("u", d)], sb_u,
            )
        metrics.gauge("offload_hot_resident_mb", round(
            (hot_u_part.nbytes + hot_m_part.nbytes) / 1e6, 3))

    def _setup_partition(new_fleet, step=None) -> None:
        """THE partition constructor: ownership maps, store slices,
        exchange plans, mirrors, and hot partitions for the CURRENT
        fleet (or single-host when ``new_fleet`` is None).  ``step``
        None seeds from init (every process draws the SAME full u0 —
        deterministic, shard-count-invariant — and keeps its owned
        slice; store bounds coincide with shard solve ranges, so solve
        write-back stays purely local); otherwise the stores reload
        committed step bytes from the fleet manifests — the elastic
        shrink/rejoin repartition path."""
        nonlocal fleet, u_store, m_store, own_u, own_m
        nonlocal fleet_sides, owned_shards
        fleet = new_fleet
        if fleet is None:
            if step is None:
                u_store = HostFactorStore.from_array(u_full_init,
                                                     dtype=config.dtype,
                                                     num_shards=s)
                m_store = HostFactorStore(rows_m_total, config.rank,
                                          dtype=config.dtype,
                                          num_shards=s)
            else:
                u_full, m_full = _load_full(int(step))
                u_store = HostFactorStore.from_array(
                    u_full, dtype=config.dtype, num_shards=s)
                m_store = HostFactorStore.from_array(
                    m_full, dtype=config.dtype, num_shards=s)
            own_u = own_m = fleet_sides = owned_shards = None
        else:
            own_u = _exchange.OwnershipMap(s, fleet.num_processes,
                                           fleet.process,
                                           rows_u_total // s)
            own_m = _exchange.OwnershipMap(s, fleet.num_processes,
                                           fleet.process,
                                           rows_m_total // s)
            owned_shards = own_u.owned_shards()
            u_lo, u_hi = own_u.row_bounds()
            m_lo, m_hi = own_m.row_bounds()
            if step is None:
                u_store = HostFactorStore.from_array(
                    u_full_init[u_lo:u_hi], dtype=config.dtype,
                    num_shards=own_u.shards_per_process,
                )
                m_store = HostFactorStore(m_hi - m_lo, config.rank,
                                          dtype=config.dtype,
                                          num_shards=own_m.shards_per_process)
            else:
                u_store = HostFactorStore.from_array(
                    fleet_manifests.load_rows(int(step), u_lo, u_hi, "u",
                                              rank=config.rank),
                    dtype=config.dtype,
                    num_shards=own_u.shards_per_process,
                )
                m_store = HostFactorStore.from_array(
                    fleet_manifests.load_rows(int(step), m_lo, m_hi, "m",
                                              rank=config.rank),
                    dtype=config.dtype,
                    num_shards=own_m.shards_per_process,
                )
            explan_m = _exchange.build_half_exchange(
                own_u, m_plans, [schedules[("m", d)] for d in range(s)],
                inner=inner, visits=visits_all if ring_m else None,
                hmaps=hmaps_m, hot_rows=rows_hot_u, side="m",
            )
            explan_u = _exchange.build_half_exchange(
                own_m, u_plans, [schedules[("u", d)] for d in range(s)],
                inner=inner, visits=visits_all if ring_u else None,
                hmaps=hmaps_u, hot_rows=rows_hot_m, side="u",
            )
            fleet_sides = {
                "m": (_exchange.ResidualMirror(u_store, own_u), explan_m),
                "u": (_exchange.ResidualMirror(m_store, own_m), explan_u),
            }
            metrics.gauge("offload_fleet_processes", fleet.num_processes)
            metrics.gauge("offload_fleet_process", fleet.process)
            metrics.gauge("offload_fleet_epoch", fleet_epoch)
            metrics.gauge("offload_exchange_phases",
                          explan_m.num_phases + explan_u.num_phases)
            metrics.gauge("offload_exchange_recv_rows_iter",
                          explan_m.recv_rows_total
                          + explan_u.recv_rows_total)
            metrics.gauge("offload_exchange_rows_dense_iter",
                          explan_m.dense_rows_total
                          + explan_u.dense_rows_total)
        _build_hot_halves(step)

    # Resume / rejoin.  Non-joiners build their initial partition, then
    # roll forward to the newest jointly restorable step: with fleet
    # manifests that is the manifest-coverage agreement (pure filesystem
    # reads, tightened by the collective min); otherwise the PR 17
    # per-manager fleet-min path, unchanged.  A restarted host instead
    # runs the readmission handshake FIRST — its partition is whatever
    # the surviving fleet admits it back into.
    start_it = 0
    if joiner:
        info = {
            "healthy": fleet_manifests is not None,
            "pid": int(getattr(fleet, "orig_process", -1)),
        }
        adm = fleet.join(info)
        fleet_epoch = int(adm["epoch"])
        start_it = int(adm["step"])
        _setup_partition(fleet, start_it)
        metrics.gauge("offload_resumed_from", start_it)
        metrics.gauge("offload_fleet_epoch", fleet_epoch)
        metrics.incr("fleet_rejoined")
        record_event("fleet", "fleet_rejoined", pid=info["pid"],
                     epoch=fleet_epoch, iteration=start_it)
    else:
        _setup_partition(fleet, None)
        if fleet is not None and fleet_manifests is not None:
            step = fleet_manifests.latest_coverage_step(rows_u_total,
                                                        rows_m_total)
            step = -1 if step is None else int(step)
            step = int(_exchange.agree_min_i32(fleet, step))
            if step >= 0:
                _setup_partition(fleet, step)
                start_it = step
                metrics.gauge("offload_resumed_from", step)
                record_event("train", "offload_resume", iteration=step)
        elif checkpoint_manager is not None:
            # Resume (ISSUE 17): restore the newest checkpoint step
            # EVERY process holds intact — the fleet-wide minimum of
            # each host's latest_valid_iteration, so a host whose shard
            # slice died recovers from its own manifest while the
            # survivors roll back to the same step (the PR 5 lockstep
            # contract, per-host stores edition).
            latest = checkpoint_manager.latest_valid_iteration()
            step = -1 if latest is None else int(latest)
            if fleet is not None:
                step = _exchange.agree_min_i32(fleet, step)
            if step >= 0:
                st = checkpoint_manager.restore(iteration=step)
                if st.user_factors.shape != (u_store.rows, config.rank):
                    raise ValueError(
                        f"checkpoint step {step} holds user factors "
                        f"{st.user_factors.shape} but this process's store "
                        f"slice is {(u_store.rows, config.rank)} — resuming "
                        "under a different fleet size or shard count is not "
                        "a thing the ownership map can reinterpret"
                    )
                u_store.write_range(0, np.asarray(st.user_factors))
                m_store.write_range(0, np.asarray(st.movie_factors))
                start_it = step
                metrics.gauge("offload_resumed_from", step)
                record_event("train", "offload_resume", iteration=step)
                # Re-gather the hot partitions from the RESUMED masters
                # (single mode reads them directly; fleet partitions are
                # rebuilt from the mirror at each half start anyway).
                _build_hot_halves(None)

    policy = policy_from_config(config)
    base_ov = Overrides(lam=config.lam, fused_epilogue=config.fused_epilogue)
    ov = base_ov
    norm_limit = (config.health_norm_limit
                  if config.health_check_every is not None else None)
    probe_every = config.health_check_every or 1
    # StagingStats, not a dict: pooled staging increments these from
    # worker threads (the guard the donated-buffer/step-hook audit asks
    # for — every gauge below reads HOST-side counters metered before
    # the device_put hand-off, never a donated device array).
    stats = StagingStats()
    if verify_windows is None:
        # Checksumming every staged window costs a host pass over its
        # bytes, and its scope is the host staging pipeline up to the
        # device_put hand-off (exactly the seam the chaos fault hook
        # corrupts) — so it defaults on precisely when a fault plan is
        # armed.  It is NOT a PCIe-DMA integrity check (that needs a
        # device-side checksum; on-TPU follow-up).
        verify_windows = window_faults is not None
    half_kw = dict(
        out_dtype=config.dtype, solver=config.solver,
        overlap=bool(config.overlap),
        in_kernel_gather=config.in_kernel_gather,
        table_dtype=config.table_dtype, faults=window_faults, stats=stats,
        verify_windows=verify_windows, ici_group=inner,
    )
    m_local = mb.local_entities
    u_local = ub.local_entities
    count_m = mb.count.reshape(s, -1)
    count_u = ub.count.reshape(s, -1)

    stage_name_cfg = _stage_dtype(config.dtype, config.table_dtype)
    int8_cfg = stage_name_cfg == "int8"
    stage_np_cfg = None if int8_cfg else _np_dtype(stage_name_cfg)

    def half(side, fixed_store, plans, local, counts, it, ring):
        """One half-iteration across every shard: per-shard windowed
        scans against the shared host store, in this side's execution
        shape (``ring`` — the per-side resolution of
        ``_resolve_side_modes``, so an ``exchange='auto'`` mixed build
        runs each half exactly as the resident trainer would).  Reads
        one store, writes a host buffer (committed by the caller) — no
        read-after-write hazard across shards, matching the resident
        step's solve-all-then-exchange structure.

        ONE staging engine serves the whole half (ISSUE 13): the task
        list flattens every shard's schedule shard-major — exactly the
        order the per-shard half-steps consume below — and the pool
        stages ahead across that order, so shard d+1's host gather +
        ``device_put`` run under shard d's dispatched compute instead of
        after it.  Staging is a pure read of ``fixed_store`` (written
        only after the half commits), so any staging-ahead interleave is
        bit-safe; consumption order — and therefore every bit — is
        unchanged.  ``close()`` in the ``finally`` drains workers before
        any rollback can swap the store under them."""
        algo = ov.reg_solve_algo or config.reg_solve_algo
        shards = range(s) if fleet is None else owned_shards
        hot_on = bool(hot_halves)
        if armed and fleet is None:
            # Gather-boundary integrity check (ISSUE 20): the fixed
            # table is about to be staged — verify its sealed shards
            # before any rotten byte can launder into a window.  Fleet
            # mode scrubs at the lockstep boundary instead (a raise
            # here would desync the collective schedule).
            fixed_store.scrub()
        fixed_read = fixed_store
        if fleet is not None:
            # Distributed window exchange (ISSUE 17): every DCN phase's
            # cold residual lands in the mirror BEFORE compute starts
            # (the pooled stager may stage any window ahead), then the
            # fixed side's hot partition rebuilds from the just-shipped
            # master bytes.  The staging pipeline below runs unchanged
            # against the mirror — same gathers, same checksums, same
            # fabric attribution, same bits.
            mirror, explan = fleet_sides[side]
            _exchange.exchange_half(explan, fixed_store, mirror, fleet,
                                    stats=stats, iteration=it)
            if hot_on:
                hot_halves[(side, shards.start)].fixed.rebuild(mirror)
            fixed_read = mirror
        out = np.zeros((local * len(shards), config.rank),
                       dtype=_np_dtype(config.dtype))
        schedules = [
            (plans[d].schedule(hier_visit_order(s, inner, d)) if ring
             else plans[d].schedule())
            for d in range(s)
        ]
        tasks = [(d, w) for d in shards for w in schedules[d]]
        if hot_on and window_faults is not None:
            # Chaos seam (ISSUE 15): poison the FIXED side's device
            # partition before the half reads it — the host master is
            # untouched, so the sentinel trip that follows rolls back
            # and `rebuild` recovers the partition bit-exactly.
            part = hot_halves[(side, shards.start)].fixed
            pois = (window_faults.apply_hot(it, side, part.num_rows)
                    if hasattr(window_faults, "apply_hot") else None)
            if pois is not None:
                record_event("fault", "hot_cache_corruption",
                             iteration=it, side=side, rows=len(pois))
                part.poison(pois)

        def stage_task(d, w):
            if hot_on:
                return _stage_window_delta(
                    fixed_read, plans[d], hot_halves[(side, d)].hmap, w,
                    stage_np=stage_np_cfg, int8=int8_cfg,
                    faults=window_faults, iteration=it, side=side,
                    shard=d, verify_windows=verify_windows, stats=stats,
                    ici_group=inner,
                )
            return _stage_window(
                fixed_read, plans[d], w, stage_np=stage_np_cfg,
                int8=int8_cfg, faults=window_faults, iteration=it,
                side=side, shard=d, verify_windows=verify_windows,
                stats=stats, ici_group=inner,
            )

        def stage_attrs(d, w):
            attrs = _stage_span_attrs(
                hot_halves[(side, d)].hmap if hot_on else None,
                plans[d], w,
            )
            attrs["host"] = 0 if fleet is None else fleet.process
            return attrs

        stager = WindowStager(tasks, stage_task, mode=staging,
                              depth=pool_depth, stats=stats,
                              span_attrs=stage_attrs)
        try:
            for d in shards:
                kw = dict(half_kw, lam=ov.lam,
                          fused_epilogue=ov.fused_epilogue,
                          reg_solve_algo=algo, iteration=it, side=side,
                          shard=d, stager=stager,
                          hot=hot_halves.get((side, d)),
                          host=0 if fleet is None else fleet.process)
                with span("train/iter/half_step", side=side, shard=d,
                          ring=bool(ring), iteration=it,
                          host=0 if fleet is None else fleet.process):
                    if ring:
                        rows = ring_windowed_half_step(
                            fixed_read, plans[d],
                            visits=hier_visit_order(s, inner, d),
                            count_local=counts[d], **kw,
                        )
                    else:
                        rows = windowed_half_step(fixed_read, plans[d],
                                                  **kw)
                out[(d - shards.start) * local:
                    (d - shards.start + 1) * local] = rows
        finally:
            stager.close()
        return out

    # Probing + last-good snapshots cost a full host pass + memcpy over
    # both stores per cadence — at the ALX regime that is gigabytes per
    # iteration — so they arm only when something can trip: the sentinel
    # (health_check_every), the staging checksum, or a chaos fault plan.
    # Unarmed runs match the resident trainer's default (no sentinel).
    armed = (config.health_check_every is not None
             or verify_windows or window_faults is not None)

    snap = (u_store.copy(), m_store.copy()) if armed else (None, None)
    snap_iter = start_it
    trips = 0
    it = start_it
    degraded = False
    traces0 = trace_count()
    train_t0 = time.time()
    first_step_s = None

    def _rebuild_hot() -> None:
        """Rollback heals the hot partitions the same way it heals the
        stores: re-gather from the restored host masters (ISSUE 15 —
        a poisoned or stale device partition cannot survive a rollback,
        so replay is bit-identical to a fresh run)."""
        if hot_u_part is not None and fleet is None:
            hot_u_part.rebuild(u_store)
            hot_m_part.rebuild(m_store)
        # Fleet: partitions rebuild from the exchange mirror at each
        # half start (master bytes of the ROLLED-BACK stores — the
        # exchange rebinds to the restored slice), so there is nothing
        # to heal here.

    def trip(reason: str) -> bool:
        """Rollback + ladder climb; returns False when retries are
        exhausted (degrade — the caller breaks the loop)."""
        nonlocal u_store, m_store, it, trips, ov
        trips += 1
        metrics.incr("health_trips")
        metrics.note(f"health_trip_{trips}", f"iteration {it}: {reason}")
        # Flight-record + dump: the ring buffer holds the window/half
        # events of the iterations leading here — the forensic timeline
        # every chaos offload scenario asserts on.
        record_event("fault", "health_trip", iteration=it, trip=trips,
                     reason=reason)
        dump_flight(f"health_trip_{trips}")
        if trips > policy.max_recoveries:
            detail = (
                f"recovery exhausted after {policy.max_recoveries} "
                f"trips; last: {reason}"
            )
            if policy.on_unrecoverable == "raise":
                record_event("fault", "unrecoverable", detail=detail)
                dump_flight("unrecoverable")
                raise TrainingDivergedError(detail)
            metrics.note("degraded", detail)
            record_event("fault", "degraded", detail=detail)
            dump_flight("degraded")
            u_store, m_store = snap
            it = snap_iter
            u_store.seal()
            m_store.seal()
            _rebuild_hot()
            return False
        u_store, m_store = snap[0].copy(), snap[1].copy()
        it = snap_iter
        # Snapshot copies start unsealed (HostFactorStore.copy()) —
        # reseal so the integrity scrub keeps covering the rolled-back
        # bytes.
        u_store.seal()
        m_store.seal()
        _rebuild_hot()
        metrics.incr("rollbacks")
        new_ov = policy.escalate(ov, trips)
        detail = (
            f"rung {trips}: rollback to iter {snap_iter}, "
            f"lam={new_ov.lam}, fused={new_ov.fused_epilogue}, "
            f"algo={new_ov.reg_solve_algo or config.reg_solve_algo}"
        )
        if new_ov != ov:
            metrics.gauge("escalation_level", trips)
            metrics.note(f"escalation_{trips}", detail)
            record_event("fault", "escalation", rung=trips, detail=detail)
        ov = new_ov
        if plan_provenance is not None:
            t = plan_provenance.record_transition(
                "recovery_escalation", detail
            )
            metrics.note(f"plan_transition_{trips}", str(t))
        return True

    def _shrink_infeasible(why: str) -> bool:
        record_event("fault", "fleet_shrink_infeasible", iteration=it,
                     detail=why)
        metrics.note("fleet_shrink_infeasible", why)
        dump_flight("fleet_shrink_infeasible")
        return False

    def _fleet_shrink(err) -> bool:
        """The shrink protocol (ISSUE 20): a peer is dead for good —
        min-agree the last jointly covered step from the per-host
        manifests, reform (or drop) the fleet, repartition ownership
        over the survivors, reload the orphaned slice from committed
        bytes, roll back, continue.  Returns False when live shrink is
        infeasible (the caller re-raises into the bounded-exit path) —
        ARCHITECTURE.md's "what still requires restart" list."""
        nonlocal it, snap, snap_iter, fleet_epoch
        record_event("fault", "fleet_peer_dead", iteration=it,
                     peers=[int(p) for p in getattr(err, "peers", ())],
                     detail=str(err))
        metrics.incr("fleet_peers_lost")
        if fleet is None or fleet_manifests is None:
            return False
        try:
            alive = [int(p) for p in fleet.surviving(err)]
        except _elastic.ShrinkInfeasibleError as e2:
            return _shrink_infeasible(str(e2))
        me = int(getattr(fleet, "orig_process", fleet.process))
        if not alive or me not in alive:
            return _shrink_infeasible(
                f"this host ({me}) is not in the surviving set {alive}"
            )
        if s % len(alive) != 0:
            return _shrink_infeasible(
                f"num_shards={s} is not divisible by the surviving "
                f"fleet size {len(alive)} — contiguous shard-block "
                "ownership cannot repartition; restart required"
            )
        step = fleet_manifests.latest_coverage_step(rows_u_total,
                                                    rows_m_total)
        if step is None:
            return _shrink_infeasible(
                "no checkpoint step is jointly covered by the reachable "
                "manifests — nothing to reload the orphaned slice from"
            )
        try:
            new_fleet = fleet.shrink_to(alive)
        except _elastic.ShrinkInfeasibleError as e2:
            return _shrink_infeasible(str(e2))
        if new_fleet is not None and len(alive) > 1:
            # >1 survivors share a reformed transport: tighten the
            # filesystem agreement with the collective min (identical
            # by construction on shared storage; belt and braces on
            # anything eventually-consistent).
            step = int(_exchange.agree_min_i32(new_fleet, int(step)))
        fleet_epoch = (int(getattr(new_fleet, "epoch", fleet_epoch + 1))
                       if new_fleet is not None else fleet_epoch + 1)
        _setup_partition(new_fleet, int(step))
        it = int(step)
        if armed:
            snap = (u_store.copy(), m_store.copy())
            snap_iter = it
            u_store.seal()
            m_store.seal()
        metrics.incr("fleet_shrinks")
        metrics.gauge("offload_fleet_epoch", fleet_epoch)
        metrics.note(
            f"fleet_shrink_{fleet_epoch}",
            f"peers {[int(p) for p in getattr(err, 'peers', ())]} lost; "
            f"continuing with {len(alive)} host(s) from step {step} at "
            f"epoch {fleet_epoch}",
        )
        record_event("fleet", "fleet_shrink", epoch=fleet_epoch,
                     alive=alive, step=int(step))
        dump_flight("fleet_shrink")
        return True

    def _poll_rejoin() -> bool:
        """The readmission handshake's fleet side, run at every
        iteration boundary: triage pending join requests (health gate +
        shard divisibility, refused by rank 0), then allgather the
        candidate so admission is unanimous at ONE boundary — a request
        visible to only some members postpones to the next boundary.
        On admission every member acks, the epoch bumps (stale frames
        from the joiner's previous life are fenced from here on), and
        everyone — joiner included — rebuilds the partition at the
        agreed step.  Returns True when membership changed (the caller
        restarts the boundary)."""
        nonlocal it, snap, snap_iter, fleet_epoch
        cand = -1
        for pid, info in fleet.poll_joiners():
            if not info.get("healthy", True):
                if fleet.process == 0:
                    fleet.refuse_join(int(pid), "health gate failed")
                continue
            if s % (fleet.num_processes + 1) != 0:
                if fleet.process == 0:
                    fleet.refuse_join(
                        int(pid),
                        f"num_shards={s} not divisible by the rejoined "
                        f"fleet size {fleet.num_processes + 1}",
                    )
                continue
            cand = int(pid)
            break
        words = fleet.allgather_i32([cand])
        cands = [int(w[0]) for w in words]
        if len(set(cands)) != 1 or cands[0] < 0:
            return False
        pid = cands[0]
        step = fleet_manifests.latest_coverage_step(rows_u_total,
                                                    rows_m_total)
        step = -1 if step is None else int(step)
        step = int(_exchange.agree_min_i32(fleet, step))
        if step < 0:
            if fleet.process == 0:
                fleet.refuse_join(
                    pid, "no jointly covered checkpoint step to rejoin at"
                )
            return False
        new_alive = sorted(set(int(p) for p in fleet.alive) | {pid})
        new_epoch = int(getattr(fleet, "epoch", fleet_epoch)) + 1
        fleet.admit(pid, new_epoch, new_alive, step)
        fleet_epoch = int(getattr(fleet, "epoch", new_epoch))
        _setup_partition(fleet, step)
        it = step
        if armed:
            snap = (u_store.copy(), m_store.copy())
            snap_iter = it
            u_store.seal()
            m_store.seal()
        metrics.incr("fleet_rejoins")
        metrics.gauge("offload_fleet_epoch", fleet_epoch)
        record_event("fleet", "fleet_rejoin", pid=pid, epoch=fleet_epoch,
                     step=step, alive=new_alive)
        dump_flight("fleet_rejoin")
        return True

    def _save_meta() -> dict:
        """Checkpoint manifest meta: the ISSUE 20 schema extension —
        fleet epoch, membership, and this host's owned row ranges, so
        the shrink/rejoin protocol can agree on coverage and reload any
        slice from pure manifest reads."""
        u_bounds = ((0, rows_u_total) if own_u is None
                    else own_u.row_bounds())
        m_bounds = ((0, rows_m_total) if own_m is None
                    else own_m.row_bounds())
        return {
            "tier": "host_window",
            "processes": (1 if fleet is None
                          else int(fleet.num_processes)),
            "process": 0 if fleet is None else int(fleet.process),
            "fleet_epoch": int(fleet_epoch),
            "alive": ([0] if fleet is None else
                      [int(p) for p in
                       getattr(fleet, "alive",
                               range(fleet.num_processes))]),
            "u_row_lo": int(u_bounds[0]), "u_row_hi": int(u_bounds[1]),
            "m_row_lo": int(m_bounds[0]), "m_row_hi": int(m_bounds[1]),
        }

    if watchdog is not None:
        watchdog.arm()
    try:
        with metrics.phase("train"):
            while it < config.num_iterations:
                try:
                    with span("train/iter", i=it, tier="host_window"):
                        m_new = half("m", u_store, m_plans, m_local,
                                     count_m, it, ring_m)
                        m_store.write_range(0, m_new)
                        if armed:
                            m_store.seal()
                        u_new = half("u", m_store, u_plans, u_local,
                                     count_u, it, ring_u)
                        u_store.write_range(0, u_new)
                        if armed:
                            u_store.seal()
                    record_event("train", "iter", i=it, tier="host_window")
                    it += 1
                    metrics.incr("iterations")
                    if (checkpoint_manager is not None
                            and should_save(it, checkpoint_every,
                                            config.num_iterations)):
                        # Per-process manifest of the OWNED slice, after
                        # the iteration commit — the recovery unit a
                        # killed host's replacement restores (fleet-min
                        # agreement at startup picks the step every host
                        # holds).
                        checkpoint_manager.save(
                            it, u_store.as_array(), m_store.as_array(),
                            meta=_save_meta(),
                        )
                    if (window_faults is not None
                            and hasattr(window_faults, "apply_store")):
                        # Master-store chaos seam (ISSUE 20): bit-rot
                        # lands AFTER the seal and the checkpoint commit
                        # — the committed bytes stay clean, which is
                        # exactly what the repair path restores.
                        window_faults.apply_store(it - 1, "u", u_store)
                        window_faults.apply_store(it - 1, "m", m_store)
                    if watchdog is not None:
                        watchdog.tick(it)
                    if first_step_s is None:
                        # Cold-start attribution (ISSUE 13): how long
                        # until the first full iteration lands — the
                        # quantity a warm persistent compile cache
                        # (compile_cache_dir) shrinks.
                        first_step_s = time.time() - train_t0
                    if (elastic_on and fleet is not None
                            and getattr(fleet, "supports_join", False)):
                        if _poll_rejoin():
                            continue
                    if not armed:
                        continue
                    if (it % probe_every != 0
                            and it < config.num_iterations):
                        continue
                    reason = _probe(u_new, m_new, norm_limit)
                    if reason is None:
                        try:
                            # Boundary scrub (ISSUE 20): both masters
                            # verified against their seals once per
                            # probe cadence.  Fleet mode folds a hit
                            # into the lockstep trip below (a raise here
                            # would desync the collective schedule);
                            # single mode raises into the checkpoint-
                            # repair handler.
                            u_store.scrub()
                            m_store.scrub()
                        except StoreIntegrityError as e:
                            if fleet is None:
                                raise
                            reason = f"store integrity: {e}"
                    if fleet is not None:
                        # Lockstep trip sync (the PR 5 contract): one
                        # word per process; ANY nonzero rolls every host
                        # back to the same snapshot step with the same
                        # ladder rung — the collective schedules stay
                        # aligned.
                        flags = _exchange.any_flag(fleet,
                                                   reason is not None)
                        if reason is None and flags.any():
                            peers = [p for p in range(fleet.num_processes)
                                     if flags[p]]
                            reason = f"lockstep trip from peer {peers}"
                    if reason is None:
                        snap = (u_store.copy(), m_store.copy())
                        snap_iter = it
                        continue
                    if not trip(reason):
                        degraded = True
                        break
                except WindowIntegrityError as e:
                    # The staging checksum caught a torn/corrupt window
                    # BEFORE it reached a kernel; the store is intact, so
                    # rollback + replay is exact (the stores may hold a
                    # half-written m — the snapshot restore erases it).
                    if fleet is not None:
                        # A half-iteration trip desyncs the fleet's
                        # collective schedule (peers are already past the
                        # probe sync) — fatal here; peers are bounded by
                        # the Gloo transport error or their StallWatchdog.
                        record_event("fault", "window_integrity_fleet",
                                     iteration=it, detail=str(e))
                        dump_flight("window_integrity_fleet")
                        raise
                    if not trip(f"window integrity: {e}"):
                        degraded = True
                        break
                    continue
                except StoreIntegrityError as e:
                    # Host-RAM bit-rot in a MASTER table (the seals
                    # caught it at a gather boundary or the boundary
                    # scrub): the store itself is wrong, so a snapshot
                    # rollback only helps if the snapshot predates the
                    # rot — the committed checkpoint bytes are the
                    # authoritative repair source.
                    record_event("fault", "store_integrity", iteration=it,
                                 shard=getattr(e, "shard", -1),
                                 detail=str(e))
                    metrics.incr("store_integrity_detected")
                    repair_step = (
                        checkpoint_manager.latest_valid_iteration()
                        if (fleet is None and checkpoint_manager
                            is not None) else None
                    )
                    if repair_step is None:
                        # No committed bytes to repair from: the in-RAM
                        # last-good snapshot is the only recourse.
                        dump_flight("store_integrity")
                        if not trip(f"store integrity: {e}"):
                            degraded = True
                            break
                        continue
                    st = checkpoint_manager.restore(int(repair_step))
                    u_store = HostFactorStore.from_array(
                        np.asarray(st.user_factors), dtype=config.dtype,
                        num_shards=u_store.num_shards,
                    )
                    m_store = HostFactorStore.from_array(
                        np.asarray(st.movie_factors), dtype=config.dtype,
                        num_shards=m_store.num_shards,
                    )
                    it = int(repair_step)
                    u_store.seal()
                    m_store.seal()
                    snap = (u_store.copy(), m_store.copy())
                    snap_iter = it
                    _rebuild_hot()
                    metrics.incr("store_repairs")
                    record_event("fault", "store_repair", iteration=it,
                                 step=int(repair_step))
                    dump_flight("store_integrity_repair")
                    continue
                except _elastic.PeerDeadError as e:
                    # A peer is gone for good (retries exhausted / fatal
                    # transport error / collective timeout).  Elastic
                    # fleets shrink and continue; anything else keeps
                    # the PR 16 bounded-exit contract (the caller's
                    # StallWatchdog/drill harness handles the exit).
                    if not (elastic_on and _fleet_shrink(e)):
                        raise
                    continue
    finally:
        if watchdog is not None:
            watchdog.disarm()
    metrics.gauge("offload_windows_staged", stats.get("windows_staged", 0))
    metrics.gauge("offload_staged_mb",
                  round(stats.get("staged_bytes", 0) / 1e6, 3))
    # The staged TABLE share, split per ISSUE 15: cold bytes actually
    # shipped over PCIe vs the device-resident hot partition (0 when the
    # cache is off — then cold == the whole table share, the PR 12
    # number under its new name).
    metrics.gauge("offload_staged_cold_mb",
                  round(stats.get("staged_cold_bytes", 0) / 1e6, 3))
    for key_ in ("rows_staged", "rows_delta_skipped", "rows_hot_device"):
        if key_ in stats:
            metrics.gauge(f"offload_{key_}", stats[key_])
    # Staging-engine accounting (ISSUE 13): busy = summed staging task
    # seconds, stall = the consuming thread's exposed wait (== busy in
    # serial mode by construction), hidden = 1 − stall/busy.  All read
    # from HOST-side counters — never a donated device buffer.
    busy = float(stats.get("stage_busy_s", 0.0))
    stall = float(stats.get("stage_stall_s", 0.0))
    metrics.gauge("offload_stage_busy_s", round(busy, 4))
    metrics.gauge("offload_stage_stall_s", round(stall, 4))
    if busy > 0:
        metrics.gauge("offload_stage_hidden_frac",
                      round(max(0.0, 1.0 - stall / busy), 4))
        metrics.gauge("offload_staged_mb_per_s",
                      round(stats.get("staged_bytes", 0) / 1e6 / busy, 2))
    if staging == "pool":
        metrics.gauge("offload_pool_peak_inflight",
                      stats.get("pool_peak_inflight", 0))
        metrics.gauge("offload_pool_worker_stagings",
                      stats.get("pool_worker_stagings", 0))
    metrics.gauge("offload_trace_count", trace_count() - traces0)
    if first_step_s is not None:
        metrics.gauge("time_to_first_step_s", round(first_step_s, 4))
    for key_ in ("rows_local", "rows_ici", "rows_dcn"):
        if key_ in stats:
            metrics.gauge(f"offload_{key_}", stats[key_])
    if fleet is not None:
        # Residual DCN accounting (ISSUE 17): rows/bytes a pairwise DCN
        # fabric would carry per the exchange manifests (cumulative-
        # deduped cold residual — the quantity the hot/delta split
        # shrinks), plus the actual allgather wire bytes (pad × peers).
        for key_ in ("exchange_rows_dcn", "exchange_bytes_dcn",
                     "exchange_wire_bytes"):
            if key_ in stats:
                metrics.gauge(f"offload_{key_}", stats[key_])
        metrics.gauge("offload_exchange_mb_dcn",
                      round(stats.get("exchange_bytes_dcn", 0) / 1e6, 3))
        metrics.gauge("offload_exchange_wire_mb",
                      round(stats.get("exchange_wire_bytes", 0) / 1e6, 3))
    if degraded:
        metrics.gauge("iterations_completed", snap_iter)

    from cfk_tpu.models.als import ALSModel

    if fleet is None:
        u_arr, m_arr = u_store.as_array(), m_store.as_array()
    else:
        # Final hand-off: assemble the full tables from every process's
        # slice (the drills' crc comparison reads this; slice-only
        # consumers at true ALX scale would skip it — ROADMAP).
        u_arr = _exchange.allgather_store(fleet, u_store, own_u)
        m_arr = _exchange.allgather_store(fleet, m_store, own_m)
    return ALSModel(
        user_factors=u_arr,
        movie_factors=m_arr,
        num_users=dataset.user_map.num_entities,
        num_movies=dataset.movie_map.num_entities,
    )


# ---------------------------------------------------------------------------
# Out-of-core iALS / iALS++ (ISSUE 19): the global-Gram reduction over the
# host store, the bucketed width-class window jits, and the implicit driver.
# ---------------------------------------------------------------------------


def _gram_block_impl(acc, data, scale):
    """One staged block's contribution to the global YᵀY accumulator —
    the SAME ``gram_block_add`` body the resident ``global_gram_blocked``
    scans (per-block bits are scan-length-invariant, so the streamed
    reduction is bit-equal to the resident in-jit scan), fed the
    dequantized view the kernels read (``quant.dequantize_table`` — the
    int8 Gram must see codes·scale, not raw codes)."""
    from cfk_tpu.ops import quant
    from cfk_tpu.ops.solve import gram_block_add

    _TRACES[0] += 1
    return gram_block_add(acc, quant.dequantize_table(data, scale))


@functools.lru_cache(maxsize=None)
def _gram_block_jit():
    """The Gram-reduction jit.  The accumulator donates (in-place add —
    output aliases input, the ring-carry idiom at the [k,k] scale); the
    staged block pair additionally donates on TPU only
    (``_staged_donate_argnums`` — on CPU ``device_put`` zero-copy-aliases
    the host block)."""
    return jax.jit(
        _gram_block_impl,
        donate_argnums=_staged_donate_argnums((0,), (1, 2)),
    )


def windowed_store_gram(store: HostFactorStore, *,
                        table_dtype: str | None = None,
                        stats: dict | None = None,
                        block_rows: int | None = None):
    """Global YᵀY of a host-resident factor table, reduced block-by-block
    into a device [k, k] f32 accumulator (ISSUE 19's piece 1).

    The implicit half-steps need the FULL fixed-side Gram, which the
    resident bucketed paths compute in-jit from the whole table — exactly
    the array the out-of-core regime cannot hold.  Here the store streams
    through the device in ``ops.solve.GRAM_BLOCK_ROWS`` blocks at the
    STAGING dtype (host cast / ``quantize_rows_host`` — per-row pinned
    bit-identical to the resident in-jit quantization), each block's
    partial Gram accumulating via the SAME ``gram_block_add`` body the
    resident ``global_gram_blocked`` scans.  The tail block zero-pads in
    the dequantized domain (int8 pads ship zero codes with scale 1.0 —
    dequantize to exact 0.0 rows, a zero Gram contribution), matching the
    resident zero-pad bit-for-bit.  Result: the streamed accumulator is
    BIT-EQUAL to the resident global Gram at every staging dtype.

    The [k,k] accumulator plus the double-buffered staged block are the
    ``budget.gram_reservation_bytes`` term the driver reserves before
    window sizing — refused loudly when it does not fit.

    Lifetime: recomputed from the host MASTERS at the start of each half
    (never carried across iterations), so the rollback ladder's store
    restore heals the accumulator for free — replay recomputes it from
    the restored bytes."""
    from cfk_tpu.ops.solve import GRAM_BLOCK_ROWS

    import jax.numpy as jnp

    br = int(block_rows) if block_rows else GRAM_BLOCK_ROWS
    stage_name = _stage_dtype(store.dtype, table_dtype)
    int8 = stage_name == "int8"
    stage_np = None if int8 else _np_dtype(stage_name)
    k = store.rank
    acc = jnp.zeros((k, k), jnp.float32)
    for lo in range(0, store.rows, br):
        hi = min(lo + br, store.rows)
        tbl = store.gather(np.arange(lo, hi, dtype=np.int64))
        if int8:
            data, scale = quantize_rows_host(tbl)
        else:
            data = (tbl if tbl.dtype == stage_np
                    else tbl.astype(stage_np))
            scale = None
        if hi - lo < br:
            pad = np.zeros((br, k), dtype=data.dtype)
            pad[: hi - lo] = data
            data = pad
            if scale is not None:
                ps = np.ones((br,), dtype=np.float32)
                ps[: hi - lo] = scale
                scale = ps
        if stats is not None:
            stats_add(stats, "gram_staged_bytes",
                      data.nbytes + (scale.nbytes if scale is not None
                                     else 0))
            stats_add(stats, "gram_blocks_staged", 1)
        data, scale = jax.device_put((data, scale))
        acc = _gram_block_jit()(acc, data, scale)
    return acc


def _bucket_window_impl(tbl, scale, nb, rt, mk, gram, *, shape, lam, alpha,
                        solver, overlap, fused_epilogue, in_kernel_gather,
                        reg_solve_algo, out_dtype):
    """One staged width-class window through the UNMODIFIED resident
    bucket piece (``ops.solve.ials_half_step_bucketed``'s solve_piece):
    the ported gather/Gram kernels where the static gates admit them,
    else the legacy XLA schedule against the dequantized window view.
    Whole-bucket windows run the direct call; chunked windows run the
    resident ``chunk_map`` scan at the resident per-chunk batch shape
    (scan-length-invariant bits for length ≥ 2 — the plan's floor), so
    the per-entity solves are bit-identical to the resident walk."""
    import jax.numpy as jnp

    from cfk_tpu.ops import bucketed as bport
    from cfk_tpu.ops import quant
    from cfk_tpu.ops.pipeline import chunk_map
    from cfk_tpu.ops.solve import (
        gather_gram_implicit,
        regularized_solve_matrix,
    )

    _TRACES[0] += 1
    ncw, chunk, width, whole = shape
    view = quant.dequantize_table(tbl, scale)
    k = view.shape[-1]
    reg_m = gram + lam * jnp.eye(k, dtype=jnp.float32)

    def solve_piece(ni, rt_c, mk_c):
        rows = ni.shape[0]
        modes = bport.resolve_bucket_modes(
            fused_epilogue, in_kernel_gather, solver, rows, width, k,
            None, reg_solve_algo,
        )
        if modes is None:
            a_obs, b = gather_gram_implicit(view, ni, alpha * rt_c, mk_c)
            return regularized_solve_matrix(a_obs, b, reg_m, solver,
                                            algo=reg_solve_algo)
        fused, gather = modes
        wt, rt_b = bport.ials_reparam(rt_c, mk_c, alpha)
        return bport.bucket_gram_solve(
            tbl, scale, ni, wt, rt_b, reg_m, lam=0.0, reg_mode="matrix",
            solver=solver, fused=fused, gather=gather, algo=reg_solve_algo,
        )

    if whole:
        xs = solve_piece(nb.reshape(chunk, width),
                         rt.reshape(chunk, width),
                         mk.reshape(chunk, width))
    else:
        xs = chunk_map(
            solve_piece,
            (nb.reshape(ncw, chunk, width), rt.reshape(ncw, chunk, width),
             mk.reshape(ncw, chunk, width)),
            ncw, overlap=overlap,
        ).reshape(ncw * chunk, k)
    return xs.astype(jnp.dtype(out_dtype))


_BUCKET_STATICS = ("shape", "lam", "alpha", "solver", "overlap",
                   "fused_epilogue", "in_kernel_gather", "reg_solve_algo",
                   "out_dtype")


@functools.lru_cache(maxsize=None)
def _bucket_window_jit():
    """The bucketed-iALS window jit (one trace per width-class shape).
    The staged (tbl, scale) pair donates on TPU only; the Gram
    accumulator is NEVER donated — every window of the half reads it."""
    return jax.jit(
        _bucket_window_impl, static_argnames=_BUCKET_STATICS,
        donate_argnums=_staged_donate_argnums((), (0, 1)),
    )


@functools.lru_cache(maxsize=None)
def _bucket_window_hot_jit():
    """Same program under the hot/delta engine: no staged donation — the
    assembled window table is the successor's delta-reuse source."""
    return jax.jit(
        _bucket_window_impl, static_argnames=_BUCKET_STATICS,
    )


def _bucket_window_pp_impl(tbl, scale, nb, rt, mk, xw, gram, *, shape, lam,
                           alpha, block_size, sweeps, solver, overlap,
                           fused_epilogue, in_kernel_gather,
                           reg_solve_algo, out_dtype):
    """One staged width-class window through the UNMODIFIED iALS++
    subspace sweep (``ops.subspace._sweep_rect`` — the identical body the
    resident ``ials_pp_half_step_bucketed`` walks), warm-started from the
    staged ``xw`` rows (the solve side's previous factors gathered per
    window slot; trash slots zero — exactly the resident warm walk's
    zero-seeded scratch row).  The sweeps are purely per-entity, so the
    windowed per-chunk results are bit-identical to the resident scan at
    the same chunk shape."""
    import jax.numpy as jnp

    from cfk_tpu.ops.pipeline import chunk_map
    from cfk_tpu.ops.subspace import _sweep_rect

    _TRACES[0] += 1
    ncw, chunk, width, whole = shape
    k = xw.shape[-1]

    def sweep_piece(xb, ni, rt_c, mk_c):
        for _ in range(sweeps):
            xb = _sweep_rect(
                tbl, xb, ni, rt_c, mk_c, lam, alpha, gram, block_size,
                solver, scale=scale, in_kernel_gather=in_kernel_gather,
                fused_epilogue=fused_epilogue,
                reg_solve_algo=reg_solve_algo,
            )
        return xb

    x0 = xw.astype(jnp.float32)
    if whole:
        xs = sweep_piece(x0, nb.reshape(chunk, width),
                         rt.reshape(chunk, width),
                         mk.reshape(chunk, width))
    else:
        xs = chunk_map(
            sweep_piece,
            (x0.reshape(ncw, chunk, k), nb.reshape(ncw, chunk, width),
             rt.reshape(ncw, chunk, width), mk.reshape(ncw, chunk, width)),
            ncw, overlap=overlap,
        ).reshape(ncw * chunk, k)
    return xs.astype(jnp.dtype(out_dtype))


_BUCKET_PP_STATICS = _BUCKET_STATICS + ("block_size", "sweeps")


@functools.lru_cache(maxsize=None)
def _bucket_window_pp_jit():
    """The iALS++ window jit: staged table pair AND the per-window
    warm-start rows donate on TPU (both are freshly staged per window);
    the Gram accumulator never donates."""
    return jax.jit(
        _bucket_window_pp_impl, static_argnames=_BUCKET_PP_STATICS,
        donate_argnums=_staged_donate_argnums((), (0, 1, 5)),
    )


@functools.lru_cache(maxsize=None)
def _bucket_window_pp_hot_jit():
    """iALS++ under the hot/delta engine: the assembled table outlives
    the call (delta reuse), so nothing donates."""
    return jax.jit(
        _bucket_window_pp_impl, static_argnames=_BUCKET_PP_STATICS,
    )


def _bucket_stager(fixed_store, bplan, schedule, *, table_dtype, faults,
                   iteration, side, shard, verify_windows, stats, ici_group,
                   hot=None, x_prev=None, mode="serial",
                   depth=1) -> WindowStager:
    """The staging engine for one bucketed half: the SAME
    ``_stage_window`` / ``_stage_window_delta`` pipeline the tiled driver
    runs (gather → fault hook → checksum → quantize → ONE ``device_put``),
    plus — for iALS++ — each window's warm-start rows ``x_prev[entity]``
    appended to the staged tuple (gathered from an immutable snapshot
    padded with one zeros trash row, so pooled staging threads read a
    frozen array; the bytes are metered into ``staged_bytes`` — they
    cross PCIe like every other staged operand)."""
    stage_name = _stage_dtype(fixed_store.dtype, table_dtype)
    int8 = stage_name == "int8"
    stage_np = None if int8 else _np_dtype(stage_name)
    x_pad = None
    if x_prev is not None:
        xp = np.asarray(x_prev)
        x_pad = np.zeros((bplan.local_entities + 1, xp.shape[1]),
                         dtype=xp.dtype)
        x_pad[: bplan.local_entities] = xp[: bplan.local_entities]

    def stage_task(d, w):
        if hot is not None:
            staged = _stage_window_delta(
                fixed_store, bplan, hot.hmap, w, stage_np=stage_np,
                int8=int8, faults=faults, iteration=iteration, side=side,
                shard=d, verify_windows=verify_windows, stats=stats,
                ici_group=ici_group,
            )
        else:
            staged = _stage_window(
                fixed_store, bplan, w, stage_np=stage_np, int8=int8,
                faults=faults, iteration=iteration, side=side, shard=d,
                verify_windows=verify_windows, stats=stats,
                ici_group=ici_group,
            )
        if x_pad is None:
            return staged
        xw = x_pad[bplan.chunk_entity_of(w)]
        if stats is not None:
            stats_add(stats, "staged_bytes", xw.nbytes)
        return staged + (jax.device_put(xw),)

    return WindowStager([(shard, w) for w in schedule], stage_task,
                        mode=mode, depth=depth, stats=stats,
                        span_attrs=lambda d, w: _stage_span_attrs(
                            hot.hmap if hot is not None else None,
                            bplan, w))


def bucket_windowed_half_step(
    fixed_store: HostFactorStore, bplan: BucketWindowPlan, *, gram,
    lam: float, alpha: float, algorithm: str = "als", block_size: int = 32,
    sweeps: int = 1, x_prev: np.ndarray | None = None,
    out_dtype: str = "float32", solver: str = "auto", overlap=None,
    fused_epilogue=None, in_kernel_gather=None, reg_solve_algo=None,
    table_dtype: str | None = None, faults=None, iteration: int = 0,
    side: str = "", stats: dict | None = None,
    verify_windows: bool = False, shard: int = 0, ici_group: int = 1,
    stager: WindowStager | None = None, hot: "_HotHalf | None" = None,
    host: int = 0,
) -> np.ndarray:
    """Solve one side's bucketed entities against a host-resident fixed
    table, width-class window by window (ISSUE 19's piece 2).

    ``gram`` is the device [k,k] f32 global YᵀY of the fixed table
    (``windowed_store_gram``), shared read-only by every window.
    ``algorithm='als'`` runs the full per-entity implicit solve;
    ``'ials++'`` runs ``sweeps`` subspace passes warm-started from
    ``x_prev`` (the solve side's previous factors, [padded_entities, k]
    host array — REQUIRED for ials++; untouched entities keep their
    previous rows in the output, exactly the resident warm walk).
    Returns the solved [padded_entities, rank] host array in
    ``out_dtype``.  Same staging/fault/checksum/hot-delta semantics as
    ``windowed_half_step`` — the hot engine's assembly, scatter-back, and
    delta reuse run UNMODIFIED against the width-class windows."""
    k = fixed_store.rank
    pp = algorithm == "ials++"
    out_np = _np_dtype(out_dtype)
    if pp:
        if x_prev is None:
            raise ValueError(
                "algorithm='ials++' needs x_prev (the solve side's "
                "previous factors) for the warm-started subspace sweeps"
            )
        out = np.array(np.asarray(x_prev)[: bplan.local_entities],
                       dtype=out_np, copy=True)
    else:
        out = np.zeros((bplan.local_entities, k), dtype=out_np)
    n_w = bplan.num_windows
    own = stager is None
    if own:
        stager = _bucket_stager(
            fixed_store, bplan, bplan.schedule(), table_dtype=table_dtype,
            faults=faults, iteration=iteration, side=side, shard=shard,
            verify_windows=verify_windows, stats=stats,
            ici_group=ici_group, hot=hot,
            x_prev=x_prev if pp else None,
        )
    half_kw = dict(
        lam=float(lam), alpha=float(alpha), solver=solver, overlap=overlap,
        fused_epilogue=fused_epilogue, in_kernel_gather=in_kernel_gather,
        reg_solve_algo=reg_solve_algo, out_dtype=out_dtype,
    )
    if pp:
        half_kw.update(block_size=int(block_size), sweeps=int(sweeps))
    stage_name = _stage_dtype(fixed_store.dtype, table_dtype)
    prev = (None if hot is None
            else _hot_zero_prev(bplan.window_rows, k, stage_name))
    try:
        staged = stager.take() if n_w else None
        for w in range(n_w):
            shape = bplan.window_shape(w)
            with span("train/iter/half_step/window_compute",
                      side=side, shard=shard, window=w, host=host):
                if hot is None:
                    if pp:
                        xs = _bucket_window_pp_jit()(*staged, gram,
                                                     shape=shape,
                                                     **half_kw)
                    else:
                        xs = _bucket_window_jit()(*staged, gram,
                                                  shape=shape, **half_kw)
                else:
                    delta, dscale, nb, rt, mk, *xw_t = staged
                    tbl, scale = _assemble_jit()(
                        delta, dscale, *prev,
                        hot.fixed.data, hot.fixed.scale, *hot.idx(w),
                        window_rows=bplan.window_rows,
                        int8=hot.fixed.int8,
                    )
                    if pp:
                        xs = _bucket_window_pp_hot_jit()(
                            tbl, scale, nb, rt, mk, xw_t[0], gram,
                            shape=shape, **half_kw)
                    else:
                        xs = _bucket_window_hot_jit()(
                            tbl, scale, nb, rt, mk, gram,
                            shape=shape, **half_kw)
                    prev = (tbl, scale)
                    sb = hot.sb_idx(w)
                    if sb is not None:
                        hot.solve.data, hot.solve.scale = _hot_update_jit()(
                            hot.solve.data, hot.solve.scale, xs, *sb,
                            int8=hot.solve.int8,
                        )
                nxt = stager.take() if w + 1 < n_w else None
                xs_np = np.asarray(xs)
            ent = bplan.chunk_entity_of(w)
            real = ent < bplan.local_entities
            out[ent[real]] = xs_np[real]
            staged = nxt
    finally:
        if own:
            stager.close()
    return out


def train_ials_host_window(
    dataset,
    config,
    *,
    metrics=None,
    window_faults=None,
    chunks_per_window: int | None = None,
    device_budget_bytes: float | None = None,
    plan_provenance=None,
    verify_windows: bool | None = None,
    staging: str | None = None,
    pool_depth: int | None = None,
    hot_rows: int | None = None,
):
    """Implicit ALS / iALS++ with host-resident factor tables and
    windowed width-class half-steps (ISSUE 19's tentpole driver).

    Same math, init, and iteration order as ``models.ials.train_ials`` on
    the same bucketed blocks — bit-exact at f32 defaults and pinned per
    knob by ``tests/test_offload_ials.py`` (table dtype, hot cache,
    window size, shard count).  Per half-iteration:

        gram  = windowed_store_gram(fixed store)   # streamed YᵀY
        solve = width-class windows through the resident bucket pieces
        commit = store.write_range (the atomic host hand-off)

    The [k,k] Gram accumulator + its double-buffered staged block are
    reserved via ``budget.gram_reservation_bytes`` BEFORE window sizing,
    and the sizing refuses loudly — naming the Gram reserve — when one
    window cannot fit next to it.  Divergence recovery runs the PR 3
    ladder against in-RAM last-good snapshots; the Gram accumulator needs
    no snapshot (recomputed from the restored masters each half), and the
    hot partitions rebuild from them — replay is bit-identical.

    Single-process only (the fleet residual exchange is tiled-layout;
    bucketed fleet mode is a documented follow-up)."""
    from cfk_tpu.config import enable_compile_cache
    from cfk_tpu.data.blocks import BucketedBlocks
    from cfk_tpu.ops.solve import init_factors_stats
    from cfk_tpu.resilience.policy import (
        Overrides,
        TrainingDivergedError,
        policy_from_config,
    )
    from cfk_tpu.utils.metrics import Metrics

    import jax.numpy as jnp

    enable_compile_cache(getattr(config, "compile_cache_dir", None))
    if getattr(config, "alpha", None) is None:
        raise ValueError(
            "host-window iALS needs an implicit-feedback config "
            "(IALSConfig — the confidence weight alpha drives the solve)"
        )
    if config.algorithm not in ("als", "ials++"):
        raise ValueError(
            f"host-window iALS supports algorithm in ('als', 'ials++'); "
            f"got {config.algorithm!r}"
        )
    if config.layout != "bucketed":
        raise ValueError(
            f"host-window iALS streams the bucketed width-class layout; "
            f"layout={config.layout!r}"
        )
    if jax.process_count() > 1:
        raise NotImplementedError(
            "the multi-process fleet mode (ISSUE 17) is tiled-layout "
            "only; bucketed iALS fleet exchange is a documented follow-up"
        )
    mb, ub = dataset.movie_blocks, dataset.user_blocks
    if not isinstance(mb, BucketedBlocks) or not isinstance(
            ub, BucketedBlocks):
        raise ValueError(
            "host-window iALS needs BucketedBlocks on both sides — "
            "build the dataset with layout='bucketed'"
        )
    s = config.num_shards
    if mb.num_shards != s or ub.num_shards != s:
        raise ValueError(
            f"blocks built at num_shards={mb.num_shards}/{ub.num_shards} "
            f"but config.num_shards={s} — rebuild the dataset"
        )
    pp = config.algorithm == "ials++"
    metrics = metrics if metrics is not None else Metrics()
    with metrics.phase("window_plan"):
        stage_name = _stage_dtype(config.dtype, config.table_dtype)
        cell_bytes, row_overhead = _stage_cell_bytes(stage_name)
        if device_budget_bytes is None:
            from cfk_tpu.plan import DeviceSpec

            device_budget_bytes = DeviceSpec.detect().hbm_bytes
        # The global-Gram reduction holds a [k,k] f32 accumulator plus a
        # double-buffered staged Gram block next to the staged windows —
        # one more reservation term, carved out BEFORE the window split
        # (the ring-accumulator template).
        gram_reserved = _budget.gram_reservation_bytes(
            config.rank, stage_name
        )
        per_window_budget = _budget.window_budget_bytes(
            device_budget_bytes, reserved_bytes=gram_reserved
        )
        cpw = chunks_per_window or 4
        while True:
            m_plan = build_bucket_window_plan(mb, ub.padded_entities,
                                              chunks_per_window=cpw)
            u_plan = build_bucket_window_plan(ub, mb.padded_entities,
                                              chunks_per_window=cpw)
            worst = max(
                p.staged_bytes_per_window(config.rank, cell_bytes,
                                          row_overhead_bytes=row_overhead)
                for p in (m_plan, u_plan)
            )
            if worst <= per_window_budget or cpw == 1:
                break
            cpw = max(1, cpw // 2)
        if worst > per_window_budget:
            raise ValueError(
                f"one staged window needs {worst / 1e6:.1f} MB but the "
                f"per-window budget is {per_window_budget / 1e6:.1f} MB "
                f"((device_budget · RESIDENT_FRACTION − "
                f"{gram_reserved / 1e6:.2f} MB global-Gram accumulator "
                "reserve) / WINDOW_BUFFERS) — lower hbm_chunk_elems so "
                "single chunks fit the budget, or raise the device budget"
            )
        staging = resolve_staging(
            staging if staging is not None
            else getattr(config, "staging", "auto"),
        )
        if pool_depth is None:
            pool_depth = (getattr(config, "staging_pool_depth", None)
                          or DEFAULT_POOL_DEPTH)
        pool_depth = max(1, min(
            int(pool_depth),
            _budget.max_pool_depth(device_budget_bytes, worst,
                                   reserved_bytes=gram_reserved),
        ))
        # Skew-aware hot-row cache resolution (ISSUE 15), unchanged
        # machinery against the width-class plans: one plan per side
        # covers every shard (absolute entity ids), so the helpers run
        # at shard=0 / local=padded_entities.
        from cfk_tpu.offload import hot as _hotmod

        requested = (hot_rows if hot_rows is not None
                     else getattr(config, "hot_rows", None))
        schedules = {("m", 0): m_plan.schedule(),
                     ("u", 0): u_plan.schedule()}
        hot_note = None
        f_u = f_m = 0
        if requested != 0:
            row_b = _budget.stage_row_bytes(config.rank, stage_name)
            arena = max(p.window_rows * row_b for p in (m_plan, u_plan))
            live = (pool_depth + 1 if staging == "pool"
                    else _budget.WINDOW_BUFFERS)
            live = max(live, _budget.WINDOW_BUFFERS)
            hot_reserved = gram_reserved + live * worst + arena
            admit = _budget.max_hot_rows(
                device_budget_bytes, config.rank, stage_name,
                reserved_bytes=hot_reserved,
            )
            counts_u = _hotmod.reference_counts(
                [m_plan], _fixed_rows_of(m_plan)
            )
            counts_m = _hotmod.reference_counts(
                [u_plan], _fixed_rows_of(u_plan)
            )
            solved_u = _hotmod.solved_rows_of(u_plan, 0,
                                              ub.padded_entities)
            solved_m = _hotmod.solved_rows_of(m_plan, 0,
                                              mb.padded_entities)
            mask_u = np.zeros(counts_u.shape, bool)
            mask_u[solved_u] = True
            counts_u[~mask_u] = 0
            mask_m = np.zeros(counts_m.shape, bool)
            mask_m[solved_m] = True
            counts_m[~mask_m] = 0
            slots_u = int(counts_u.sum())
            slots_m = int(counts_m.sum())
            if requested is None:
                f_u = _hotmod.knee_hot_rows(counts_u)
                f_m = _hotmod.knee_hot_rows(counts_m)
                total = f_u + f_m
                if total > admit:
                    f_u = f_u * admit // max(total, 1)
                    f_m = min(admit - f_u, f_m)
                    hot_note = (f"knee clamped by budget headroom "
                                f"({admit} rows admitted)")
                else:
                    hot_note = "coverage-curve knee within headroom"
            else:
                req = int(requested)
                if not _budget.hot_reservation_fits(
                    req, config.rank, stage_name, device_budget_bytes,
                    reserved_bytes=hot_reserved,
                ):
                    need = _budget.hot_reservation_bytes(
                        req, config.rank, stage_name
                    )
                    raise ValueError(
                        f"hot_rows={req} pinned but its reservation "
                        f"({need / 1e6:.2f} MB at the {stage_name!r} "
                        f"staging dtype) exceeds the headroom left by "
                        f"the Gram/window/delta-arena terms "
                        f"({admit * row_b / 1e6:.2f} MB ≈ {admit} rows) "
                        "— lower hot_rows, raise the device budget, or "
                        "use hot_rows=0 (the full-staging engine)"
                    )
                denom = max(slots_u + slots_m, 1)
                f_u = req * slots_u // denom
                f_m = req - f_u
                hot_note = f"pinned total {req}"
            f_u = min(f_u, int((counts_u > 0).sum()))
            f_m = min(f_m, int((counts_m > 0).sum()))
            if f_u + f_m == 0:
                hot_note = (hot_note or "") + "; resolved 0 (off)"
        hot_ctx = None
        if f_u + f_m > 0:
            rows_hot_u = _hotmod.select_hot_rows(counts_u, f_u)
            rows_hot_m = _hotmod.select_hot_rows(counts_m, f_m)
            hmaps = {
                ("m", 0): _hotmod.build_hot_map(
                    m_plan, schedules[("m", 0)], rows_hot_u),
                ("u", 0): _hotmod.build_hot_map(
                    u_plan, schedules[("u", 0)], rows_hot_m),
            }
            hot_ctx = {"rows_u": rows_hot_u, "rows_m": rows_hot_m,
                       "maps": hmaps, "note": hot_note}
    metrics.gauge("offload_windows_m", m_plan.num_windows)
    metrics.gauge("offload_windows_u", u_plan.num_windows)
    metrics.gauge("offload_window_rows_m", m_plan.window_rows)
    metrics.gauge("offload_window_rows_u", u_plan.window_rows)
    metrics.gauge("offload_chunks_per_window", cpw)
    metrics.gauge("offload_shards", s)
    metrics.gauge(
        "offload_plan_held_mb",
        round((m_plan.plan_held_bytes()
               + u_plan.plan_held_bytes()) / 1e6, 3),
    )
    metrics.gauge("offload_gram_reserved_mb",
                  round(gram_reserved / 1e6, 3))
    metrics.note("offload_optimizer",
                 "ials++" if pp else "ials")
    metrics.note("offload_staging", staging)
    if staging == "pool":
        metrics.gauge("offload_pool_depth", pool_depth)
        metrics.gauge("offload_pool_workers",
                      pool_workers_for(pool_depth))
    metrics.note("offload_hot", "on" if hot_ctx is not None else "off")
    if hot_note:
        metrics.note("offload_hot_decision", hot_note)
    if hot_ctx is not None:
        maps_all = hot_ctx["maps"].values()
        slots_total = sum(m.slots_total for m in maps_all)
        metrics.gauge("offload_hot_rows", f_u + f_m)
        metrics.gauge("offload_hot_rows_u", f_u)
        metrics.gauge("offload_hot_rows_m", f_m)
        if slots_total:
            metrics.gauge("offload_hot_coverage", round(
                sum(m.slots_hot for m in hot_ctx["maps"].values())
                / slots_total, 4))
            metrics.gauge("offload_delta_coverage", round(
                sum(m.slots_kept for m in hot_ctx["maps"].values())
                / slots_total, 4))

    # Init: identical to the resident trainer — init_factors_stats over
    # the bucketed per-entity stats (drawn at the real entity count, the
    # shard-count-invariant init), zero movie seed.
    key = jax.random.PRNGKey(config.seed)
    u0 = jax.jit(
        init_factors_stats, static_argnames=("rank", "num_entities")
    )(
        key, jnp.asarray(ub.rating_sum), jnp.asarray(ub.count),
        rank=config.rank, num_entities=ub.num_entities,
    ).astype(jnp.dtype(config.dtype))
    u_store = HostFactorStore.from_array(np.asarray(u0),
                                         dtype=config.dtype,
                                         num_shards=s)
    m_store = HostFactorStore(mb.padded_entities, config.rank,
                              dtype=config.dtype, num_shards=s)

    # Hot partitions + per-side contexts: device copies gather from the
    # just-initialized masters; only the cold delta crosses PCIe per
    # window from here on.
    hot_u_part = hot_m_part = None
    hot_halves: dict = {}
    if hot_ctx is not None:
        hot_u_part = HotPartition(hot_ctx["rows_u"], stage_name)
        hot_m_part = HotPartition(hot_ctx["rows_m"], stage_name)
        hot_u_part.rebuild(u_store)
        hot_m_part.rebuild(m_store)
        sb_m = _hotmod.scatter_back_maps(m_plan, 0, mb.padded_entities,
                                         hot_m_part.rows)
        sb_u = _hotmod.scatter_back_maps(u_plan, 0, ub.padded_entities,
                                         hot_u_part.rows)
        hot_halves[("m", 0)] = _HotHalf(
            hot_u_part, hot_m_part, hot_ctx["maps"][("m", 0)], sb_m)
        hot_halves[("u", 0)] = _HotHalf(
            hot_m_part, hot_u_part, hot_ctx["maps"][("u", 0)], sb_u)
        metrics.gauge("offload_hot_resident_mb", round(
            (hot_u_part.nbytes + hot_m_part.nbytes) / 1e6, 3))

    policy = policy_from_config(config)
    base_ov = Overrides(lam=config.lam,
                        fused_epilogue=config.fused_epilogue)
    ov = base_ov
    norm_limit = (config.health_norm_limit
                  if config.health_check_every is not None else None)
    probe_every = config.health_check_every or 1
    stats = StagingStats()
    if verify_windows is None:
        verify_windows = window_faults is not None

    def half(side, fixed_store, solve_store, plan, it, gram):
        """One bucketed half-iteration: stage the fixed side's windows
        (pool or serial), sweep/solve them against the shared Gram
        accumulator, return the solved host buffer (committed by the
        caller — the same solve-all-then-commit structure as the tiled
        driver)."""
        algo = ov.reg_solve_algo or config.reg_solve_algo
        hot_half = hot_halves.get((side, 0))
        if hot_half is not None and window_faults is not None:
            part = hot_half.fixed
            pois = (window_faults.apply_hot(it, side, part.num_rows)
                    if hasattr(window_faults, "apply_hot") else None)
            if pois is not None:
                record_event("fault", "hot_cache_corruption",
                             iteration=it, side=side, rows=len(pois))
                part.poison(pois)
        x_prev = solve_store.as_array() if pp else None
        stager = _bucket_stager(
            fixed_store, plan, plan.schedule(),
            table_dtype=config.table_dtype, faults=window_faults,
            iteration=it, side=side, shard=0,
            verify_windows=verify_windows, stats=stats, ici_group=1,
            hot=hot_half, x_prev=x_prev, mode=staging, depth=pool_depth,
        )
        try:
            with span("train/iter/half_step", side=side, shard=0,
                      iteration=it, tier="host_window"):
                rows = bucket_windowed_half_step(
                    fixed_store, plan, gram=gram, lam=ov.lam,
                    alpha=config.alpha, algorithm=config.algorithm,
                    block_size=config.block_size, sweeps=config.sweeps,
                    x_prev=x_prev, out_dtype=config.dtype,
                    solver=config.solver, overlap=bool(config.overlap),
                    fused_epilogue=ov.fused_epilogue,
                    in_kernel_gather=config.in_kernel_gather,
                    reg_solve_algo=algo, table_dtype=config.table_dtype,
                    faults=window_faults, iteration=it, side=side,
                    stats=stats, verify_windows=verify_windows,
                    shard=0, stager=stager, hot=hot_half,
                )
        finally:
            stager.close()
        return rows

    armed = (config.health_check_every is not None
             or verify_windows or window_faults is not None)
    snap = (u_store.copy(), m_store.copy()) if armed else (None, None)
    snap_iter = 0
    trips = 0
    it = 0
    degraded = False
    traces0 = trace_count()
    train_t0 = time.time()
    first_step_s = None

    def _rebuild_hot() -> None:
        if hot_u_part is not None:
            hot_u_part.rebuild(u_store)
            hot_m_part.rebuild(m_store)

    def trip(reason: str) -> bool:
        """Rollback + ladder climb (the tiled driver's ladder verbatim):
        restore the last-good stores, rebuild the hot partitions from
        them, and recompute the Gram accumulator on the next half — the
        accumulator has no snapshot because it needs none."""
        nonlocal u_store, m_store, it, trips, ov
        trips += 1
        metrics.incr("health_trips")
        metrics.note(f"health_trip_{trips}", f"iteration {it}: {reason}")
        record_event("fault", "health_trip", iteration=it, trip=trips,
                     reason=reason)
        dump_flight(f"health_trip_{trips}")
        if trips > policy.max_recoveries:
            detail = (
                f"recovery exhausted after {policy.max_recoveries} "
                f"trips; last: {reason}"
            )
            if policy.on_unrecoverable == "raise":
                record_event("fault", "unrecoverable", detail=detail)
                dump_flight("unrecoverable")
                raise TrainingDivergedError(detail)
            metrics.note("degraded", detail)
            record_event("fault", "degraded", detail=detail)
            dump_flight("degraded")
            u_store, m_store = snap
            it = snap_iter
            _rebuild_hot()
            return False
        u_store, m_store = snap[0].copy(), snap[1].copy()
        it = snap_iter
        _rebuild_hot()
        metrics.incr("rollbacks")
        new_ov = policy.escalate(ov, trips)
        detail = (
            f"rung {trips}: rollback to iter {snap_iter}, "
            f"lam={new_ov.lam}, fused={new_ov.fused_epilogue}, "
            f"algo={new_ov.reg_solve_algo or config.reg_solve_algo}"
        )
        if new_ov != ov:
            metrics.gauge("escalation_level", trips)
            metrics.note(f"escalation_{trips}", detail)
            record_event("fault", "escalation", rung=trips,
                         detail=detail)
        ov = new_ov
        if plan_provenance is not None:
            t = plan_provenance.record_transition(
                "recovery_escalation", detail
            )
            metrics.note(f"plan_transition_{trips}", str(t))
        return True

    with metrics.phase("train"):
        while it < config.num_iterations:
            try:
                with span("train/iter", i=it, tier="host_window",
                          optimizer="ials++" if pp else "ials"):
                    # Per-half Gram over the CURRENT fixed masters —
                    # exactly the resident iteration body's order (the
                    # u-half's Gram reads the freshly committed m).
                    gram_u = windowed_store_gram(
                        u_store, table_dtype=config.table_dtype,
                        stats=stats)
                    m_new = half("m", u_store, m_store, m_plan, it,
                                 gram_u)
                    m_store.write_range(0, m_new)
                    gram_m = windowed_store_gram(
                        m_store, table_dtype=config.table_dtype,
                        stats=stats)
                    u_new = half("u", m_store, u_store, u_plan, it,
                                 gram_m)
                    u_store.write_range(0, u_new)
                record_event("train", "iter", i=it, tier="host_window")
            except WindowIntegrityError as e:
                if not trip(f"window integrity: {e}"):
                    degraded = True
                    break
                continue
            it += 1
            metrics.incr("iterations")
            if first_step_s is None:
                first_step_s = time.time() - train_t0
            if not armed:
                continue
            if it % probe_every != 0 and it < config.num_iterations:
                continue
            reason = _probe(u_new, m_new, norm_limit)
            if reason is None:
                snap = (u_store.copy(), m_store.copy())
                snap_iter = it
                continue
            if not trip(reason):
                degraded = True
                break
    metrics.gauge("offload_windows_staged",
                  stats.get("windows_staged", 0))
    metrics.gauge("offload_staged_mb",
                  round(stats.get("staged_bytes", 0) / 1e6, 3))
    metrics.gauge("offload_staged_cold_mb",
                  round(stats.get("staged_cold_bytes", 0) / 1e6, 3))
    metrics.gauge("offload_gram_staged_mb",
                  round(stats.get("gram_staged_bytes", 0) / 1e6, 3))
    for key_ in ("rows_staged", "rows_delta_skipped", "rows_hot_device",
                 "gram_blocks_staged"):
        if key_ in stats:
            metrics.gauge(f"offload_{key_}", stats[key_])
    busy = float(stats.get("stage_busy_s", 0.0))
    stall = float(stats.get("stage_stall_s", 0.0))
    metrics.gauge("offload_stage_busy_s", round(busy, 4))
    metrics.gauge("offload_stage_stall_s", round(stall, 4))
    if busy > 0:
        metrics.gauge("offload_stage_hidden_frac",
                      round(max(0.0, 1.0 - stall / busy), 4))
        metrics.gauge("offload_staged_mb_per_s",
                      round(stats.get("staged_bytes", 0) / 1e6 / busy, 2))
    if staging == "pool":
        metrics.gauge("offload_pool_peak_inflight",
                      stats.get("pool_peak_inflight", 0))
        metrics.gauge("offload_pool_worker_stagings",
                      stats.get("pool_worker_stagings", 0))
    metrics.gauge("offload_trace_count", trace_count() - traces0)
    if first_step_s is not None:
        metrics.gauge("time_to_first_step_s", round(first_step_s, 4))
    if degraded:
        metrics.gauge("iterations_completed", snap_iter)

    from cfk_tpu.models.als import ALSModel

    return ALSModel(
        user_factors=u_store.as_array(),
        movie_factors=m_store.as_array(),
        num_users=dataset.user_map.num_entities,
        num_movies=dataset.movie_map.num_entities,
    )
