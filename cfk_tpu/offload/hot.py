"""Skew-aware hot-row device cache + delta staging (ISSUE 15).

The host_window tier (PRs 10–12) re-stages every window's full referenced
row set from host RAM each half-iteration — but the workload is power-law
by construction (``data/synth.py``; Netflix/ML-25M in the wild): a small
fraction of entities appears in nearly every window's neighbor set, so the
same hot rows cross PCIe over and over.  ALX (arXiv 2112.02194) keeps its
entire factor tables device-resident because HBM traffic, not host memory,
is the scarce resource; this module is the middle ground the billion-
interaction regime needs: the staged-byte floor scales with the COLD
RESIDUAL, not the full per-window row set.

Two reuse levers, both decided statically at window-plan build time from
the plans' OWN per-window row sets (no sampling, no heuristics about the
data — the plan already knows exactly which rows each window gathers):

- **hot partition**: the top-f fixed-table rows by cross-window reference
  count live device-resident for the whole run (at the staging dtype —
  int8 hot rows keep their per-row scales device-side, dequant-ready, so
  the canonical fold order is unchanged).  Each window's rebased index map
  splits into a hot half (gathered in-device from the partition — PR 4's
  gather reads any memory space, so the kernels never know) and a cold
  half (staged).  Solved hot rows scatter straight back into the partition
  in-place on device — no host round-trip; the host master store stays
  ground truth (staging cold rows, rollback snapshots) via the unchanged
  host scatter.
- **delta staging**: the schedules (``WindowPlan.schedule()`` /
  ``RingWindowPlan.schedule()``) fix consumption order, so each window's
  cold rows split again into the rows its PREDECESSOR window already
  staged (copied device-to-device out of the previous assembled window
  table — the bounded resident-cold arena: exactly one predecessor table
  stays alive) and the fresh stage-delta that actually crosses PCIe.

Bit-exactness is the PR 10–12 contract unchanged: every row of the
assembled window table is a copy of bytes that are bitwise identical to
what full staging would have produced (hot rows: the host↔device cast and
quantization contracts ``store.quantize_rows_host`` pins; kept rows:
inductively the predecessor's; delta rows: the very same host gather), so
hot/cold ∈ {off, on} × f is crc-identical to the resident path on the
whole knob matrix.  ``hot_rows=0`` runs the PR 12 engine byte-for-byte
(no maps are built, no assembly jits trace — pinned by
``tests/test_offload_hot.py``).

Everything here is pure numpy over already-built plans — a build-time
cost, paid once per dataset, like window planning itself.
"""

from __future__ import annotations

import dataclasses

import numpy as np


def _pow2_bucket(n: int, lo: int = 8) -> int:
    """Pad a delta row count to its pow2 bucket (floor ``lo``): the
    staged-delta arrays need static shapes per jit trace, and pow2
    bucketing bounds the trace count at log2(window_rows) while keeping
    the padded transfer ≤ 2× the real delta."""
    n = max(int(n), 1)
    b = lo
    while b < n:
        b <<= 1
    return b


def plan_row_sets(plan_obj):
    """Iterate one plan's real per-window row sets (absolute store rows,
    sorted ascending — exactly what the staging gather reads)."""
    for w in range(plan_obj.num_windows):
        c = int(plan_obj.row_counts[w])
        yield w, np.asarray(plan_obj.rows[w, :c], dtype=np.int64)


def reference_counts(plans, table_rows: int) -> np.ndarray:
    """Per fixed-table row: how many (shard, window) row sets reference
    it across ``plans`` (one side's per-shard plans).  THE classification
    signal: a row's count is exactly the number of stagings the hot
    partition saves per half-iteration, so top-by-count is optimal for
    the staged-byte objective (before delta reuse)."""
    counts = np.zeros(int(table_rows), dtype=np.int64)
    for p in plans:
        for _, rows_w in plan_row_sets(p):
            counts[rows_w] += 1
    return counts


def coverage_curve(counts: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """(rows ordered hottest-first, cumulative reference-coverage).

    ``coverage[i]`` is the fraction of all per-window row-slots served
    from the device if the first ``i+1`` ordered rows are resident — the
    exact staged-table-byte saving of the hot lever alone (delta reuse
    stacks on top).  Deterministic: ties break toward the lower row id
    (stable mergesort on (-count, row)).  Rows with zero references are
    excluded — residency can never pay for them."""
    counts = np.asarray(counts, dtype=np.int64)
    referenced = np.nonzero(counts > 0)[0]
    order = referenced[np.argsort(-counts[referenced], kind="stable")]
    total = counts[order].sum()
    if total == 0:
        return order, np.zeros(0, dtype=np.float64)
    return order, np.cumsum(counts[order]) / float(total)


def knee_hot_rows(counts: np.ndarray) -> int:
    """The coverage curve's knee: the f maximizing
    ``coverage(f) − f / F_referenced`` — the classic farthest-above-the-
    diagonal elbow.  On power-law data this lands near the top ~10% of
    rows covering well over half the references; on uniform data the
    curve IS the diagonal and the knee is ~0 (residency buys nothing,
    which is the right answer)."""
    order, cov = coverage_curve(counts)
    if order.size == 0:
        return 0
    gain = cov - (np.arange(1, order.size + 1) / float(order.size))
    best = int(np.argmax(gain))
    if gain[best] <= 0.0:
        return 0
    return best + 1


def select_hot_rows(counts: np.ndarray, f: int) -> np.ndarray:
    """The top-``f`` referenced rows by cross-window count, returned
    SORTED ASCENDING (the canonical partition order — the device
    partition's row i holds store row ``hot_rows[i]``)."""
    order, _ = coverage_curve(counts)
    f = max(0, min(int(f), order.size))
    return np.sort(order[:f])


def _membership(sorted_rows: np.ndarray, query: np.ndarray,
                ) -> tuple[np.ndarray, np.ndarray]:
    """(insertion positions, membership mask) of ``query`` against a
    sorted row set — the one searchsorted-membership idiom every split
    here uses (safe on empty sets)."""
    pos = np.searchsorted(sorted_rows, query)
    if sorted_rows.size == 0 or query.size == 0:
        return pos, np.zeros(query.shape, dtype=bool)
    pos_c = np.minimum(pos, sorted_rows.size - 1)
    return pos, (pos < sorted_rows.size) & (sorted_rows[pos_c] == query)


@dataclasses.dataclass(frozen=True)
class HotWindowMap:
    """One plan's per-window hot/keep/delta split, in SCHEDULE order.

    For each window ``w`` (keys are window ids — each appears exactly
    once in a schedule, so the predecessor relation is a function of
    ``w``):

    - ``hot_dst[w]`` / ``hot_src[w]``: window-table positions filled from
      the device hot partition (src indexes the partition);
    - ``keep_dst[w]`` / ``keep_src[w]``: positions copied device-to-
      device out of the PREDECESSOR window's assembled table (src is the
      row's position there) — the delta-skipped rows;
    - ``delta_rows[w]`` / ``delta_dst[w]``: the cold residual actually
      staged over PCIe (sorted ascending, like full staging).

    ``hot_pad`` / ``keep_pad`` are the static index-array widths (one
    trace per plan); delta widths bucket to pow2 (``_pow2_bucket``).
    Scatter pads use OUT-OF-BOUNDS destinations (window_rows for the
    table, dropped by jax's documented scatter drop mode), so no trash
    slot is materialized."""

    hot_dst: dict
    hot_src: dict
    keep_dst: dict
    keep_src: dict
    delta_rows: dict
    delta_dst: dict
    prev_of: dict        # window -> predecessor window id (or -1)
    hot_pad: int
    keep_pad: int
    window_rows: int
    # Slot accounting (the bench/telemetry columns).
    slots_total: int
    slots_hot: int
    slots_kept: int
    slots_delta: int

    def delta_bucket(self, w: int) -> int:
        return _pow2_bucket(len(self.delta_rows[w]))


def build_hot_map(plan_obj, schedule, hot_rows: np.ndarray,
                  ) -> HotWindowMap:
    """Split one plan's windows against a sorted-ascending hot row set,
    walking ``schedule`` (the consumption order the half-step commits —
    the SAME authority the staging engine serves windows in, which is
    what makes the predecessor relation static)."""
    hot_rows = np.asarray(hot_rows, dtype=np.int64)
    hd, hs, kd, ks, dr, dd, prev_of = {}, {}, {}, {}, {}, {}, {}
    s_tot = s_hot = s_keep = s_delta = 0
    prev = -1
    for w in schedule:
        c = int(plan_obj.row_counts[w])
        rows_w = np.asarray(plan_obj.rows[w, :c], dtype=np.int64)
        pos, is_hot = _membership(hot_rows, rows_w)
        hd[w] = np.nonzero(is_hot)[0].astype(np.int32)
        hs[w] = pos[is_hot].astype(np.int32)
        cold_dst = np.nonzero(~is_hot)[0].astype(np.int32)
        cold_rows = rows_w[~is_hot]
        if prev >= 0:
            pc = int(plan_obj.row_counts[prev])
            prows = np.asarray(plan_obj.rows[prev, :pc], dtype=np.int64)
            ppos, shared = _membership(prows, cold_rows)
        else:
            ppos = np.zeros(cold_rows.shape, dtype=np.int64)
            shared = np.zeros(cold_rows.shape, dtype=bool)
        kd[w] = cold_dst[shared]
        ks[w] = ppos[shared].astype(np.int32)
        dd[w] = cold_dst[~shared]
        dr[w] = cold_rows[~shared]
        prev_of[w] = prev
        prev = w
        s_tot += c
        s_hot += int(hd[w].size)
        s_keep += int(kd[w].size)
        s_delta += int(dd[w].size)
    return HotWindowMap(
        hot_dst=hd, hot_src=hs, keep_dst=kd, keep_src=ks,
        delta_rows=dr, delta_dst=dd, prev_of=prev_of,
        hot_pad=max([v.size for v in hd.values()], default=0),
        keep_pad=max([v.size for v in kd.values()], default=0),
        window_rows=int(plan_obj.window_rows),
        slots_total=s_tot, slots_hot=s_hot, slots_kept=s_keep,
        slots_delta=s_delta,
    )


def solved_rows_of(plan_obj, shard: int, local: int) -> np.ndarray:
    """The ABSOLUTE solve-side rows one shard's plan finalizes (every
    entity with interactions on the shard): ``shard·local + entity`` over
    the windows' real ``chunk_entity`` slots.  Used to (a) verify every
    hot row of a side is re-solved each half (so the in-place device
    scatter-back can never go stale vs the host master) and (b) build the
    per-window scatter-back maps."""
    ents = []
    for w in range(plan_obj.num_windows):
        if hasattr(plan_obj, "chunk_entity_of"):
            e = plan_obj.chunk_entity_of(w)
        else:  # RingWindowPlan stages entities per chunk view
            e = plan_obj.stage_chunks(w)[3]
        e = np.asarray(e, dtype=np.int64)
        ents.append(e[e < local])
    if not ents:
        return np.zeros(0, dtype=np.int64)
    return np.unique(np.concatenate(ents)) + shard * local


def scatter_back_maps(plan_obj, shard: int, local: int,
                      hot_rows: np.ndarray) -> dict:
    """Per-window (src, dst) index pairs for the SOLVE side's in-place
    device scatter-back (stream mode): ``src`` positions into the
    window's solved ``xs`` ([ncw·Ec] finalization slots, LAST occurrence
    per entity — exactly the host scatter's last-write-wins), ``dst``
    positions into the solve side's hot partition.  Windows with no hot
    solves map to empty pairs.  Pads use dst == len(hot_rows) (OOB →
    dropped)."""
    hot_rows = np.asarray(hot_rows, dtype=np.int64)
    out = {}
    for w in range(plan_obj.num_windows):
        ent = np.asarray(plan_obj.chunk_entity_of(w), dtype=np.int64)
        # Last occurrence per entity (reversed unique keeps the LAST
        # index in the original order — the host scatter's winner).
        rev = ent[::-1]
        uniq, first_rev = np.unique(rev, return_index=True)
        last = ent.size - 1 - first_rev
        keep = uniq < local
        uniq, last = uniq[keep], last[keep]
        absolute = uniq + shard * local
        pos, m = _membership(hot_rows, absolute)
        out[w] = (last[m].astype(np.int32), pos[m].astype(np.int32))
    return out


def ring_scatter_back(shard: int, local: int, hot_rows: np.ndarray,
                      ) -> tuple[np.ndarray, np.ndarray]:
    """(src, dst) for the ring modes' once-per-shard scatter-back: the
    hot solve rows this shard owns, as (shard-local row, partition
    position) pairs — applied to the end-of-half solve output before it
    leaves the device."""
    hot_rows = np.asarray(hot_rows, dtype=np.int64)
    lo, hi = shard * local, (shard + 1) * local
    m = (hot_rows >= lo) & (hot_rows < hi)
    return ((hot_rows[m] - lo).astype(np.int32),
            np.nonzero(m)[0].astype(np.int32))
