"""Host-RAM factor tables, sharded by entity range.

The out-of-core tier's ground truth: the full factor matrix lives in host
memory as contiguous entity-range shards (the ALX placement — each shard
is what one host would own; a future multi-host driver maps shards to
processes, a single-process run simply holds them all).  The device only
ever sees gathered WINDOWS of it (``cfk_tpu.offload.windowed``), and the
solved rows stream back per window.

Rows are stored at the staging dtype: the storage dtype of the master
factors (float32, or bfloat16 via ``ml_dtypes`` — the same
round-to-nearest-even cast XLA performs, so a windowed run's staged rows
are bit-identical to the resident run's cast table).
"""

from __future__ import annotations

import zlib

import numpy as np


class StoreIntegrityError(RuntimeError):
    """A sealed shard's bytes no longer match their crc32 — host-RAM
    bit-rot (or an unsanctioned in-place write).  ``shard`` names the
    block so the repair path can be surgical."""

    def __init__(self, msg: str, *, shard: int = -1) -> None:
        super().__init__(msg)
        self.shard = int(shard)


def _np_dtype(name: str):
    if name in ("float32", None):
        return np.dtype(np.float32)
    if name == "bfloat16":
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    raise ValueError(
        f"HostFactorStore stores master factors as 'float32' or "
        f"'bfloat16', got {name!r}"
    )


# XLA's algebraic simplifier rewrites the in-jit ``amax / 127.0`` of
# ``ops.quant.quantize_table`` into ``amax * (1/127)`` with the reciprocal
# folded at compile time — measured on XLA:CPU (a handful of 1-ulp scale
# differences vs a true division).  The host staging quantizer must
# reproduce THAT arithmetic, not the textbook division, or staged int8
# windows drift ~1e-6 from the resident in-jit quantization
# (tests/test_offload_sharded.py pins host == jit bitwise).
_INT8_RECIP = np.float32(1.0) / np.float32(127.0)
_INT8_LEVELS = np.float32(127.0)


def quantize_rows_host(rows: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """int8-quantize factor rows on the HOST — (codes, per-row scales),
    bit-identical to slicing ``ops.quant.quantize_table``'s in-jit output
    (the per-row scheme makes any row subset quantize independently).

    This is what lets the staging pipeline ship int8 windows over PCIe as
    (1-byte codes + one f32 scale per row) instead of storage-dtype
    floats — a quarter of the staged bytes — while the kernels consume
    exactly the codes the resident path would have quantized on device.
    NaN rows poison their scale (``amax == 0`` is False for NaN), the
    same laundering guard as ``quantize_table``."""
    f = np.asarray(rows, dtype=np.float32)
    amax = np.max(np.abs(f), axis=-1) if f.size else np.zeros(
        (f.shape[0],), np.float32
    )
    scale = np.where(
        amax == 0.0, np.float32(1.0), amax * _INT8_RECIP
    ).astype(np.float32)
    with np.errstate(invalid="ignore"):
        q = np.clip(
            np.round(f / scale[:, None]), -_INT8_LEVELS, _INT8_LEVELS
        ).astype(np.int8)
    return q, scale


class HostFactorStore:
    """[rows, rank] factor table in host RAM, entity-range sharded."""

    def __init__(self, rows: int, rank: int, *, dtype: str = "float32",
                 num_shards: int = 1) -> None:
        if rows < 1 or rank < 1:
            raise ValueError(f"rows/rank must be >= 1, got {rows}/{rank}")
        if num_shards < 1 or num_shards > rows:
            raise ValueError(
                f"num_shards must be in [1, rows={rows}], got {num_shards}"
            )
        self.rows, self.rank = int(rows), int(rank)
        self.dtype = "float32" if dtype is None else dtype
        self._np_dtype = _np_dtype(dtype)
        per = -(-rows // num_shards)
        # Clip, don't just pin the tail: a ceil-split can overshoot rows
        # by more than one shard (rows=10, shards=7 → per=2 walks past 10
        # at shard 5), and unclipped bounds go non-monotonic — trailing
        # shards are then empty, which is fine.
        self.bounds = np.minimum(
            np.arange(0, num_shards + 1) * per, rows
        )
        self._shards = [
            np.zeros((self.bounds[s + 1] - self.bounds[s], rank),
                     dtype=self._np_dtype)
            for s in range(num_shards)
        ]
        # Per-shard integrity seals: crc32 of the shard bytes as of the
        # last ``seal()``, or None while the shard is dirty (unsealed).
        # Writes through the public API invalidate the touched shards;
        # ``scrub()`` verifies the sealed ones.
        self._crcs: list = [None] * num_shards

    @classmethod
    def from_array(cls, arr, *, dtype: str | None = None,
                   num_shards: int = 1) -> "HostFactorStore":
        """Wrap a host array (copied into the shard layout).  ``dtype``
        defaults to the array's own (must be float32/bfloat16)."""
        arr = np.asarray(arr)
        name = dtype or ("bfloat16" if arr.dtype.name == "bfloat16"
                         else "float32")
        store = cls(arr.shape[0], arr.shape[1], dtype=name,
                    num_shards=num_shards)
        store.write_range(0, arr)
        return store

    @property
    def num_shards(self) -> int:
        return len(self._shards)

    @property
    def nbytes(self) -> int:
        return sum(s.nbytes for s in self._shards)

    def shard(self, s: int) -> np.ndarray:
        """Direct (mutable) view of shard ``s`` — the multi-host seam."""
        return self._shards[s]

    def shard_of_rows(self, rows: np.ndarray) -> np.ndarray:
        """Which store shard owns each row — the staging path's fabric
        attribution (rows from the compute shard's own store shard are
        local; same-ICI-group shards cross the fast fabric; the rest is
        the DCN share the hier exchange meters)."""
        rows = np.asarray(rows, dtype=np.int64)
        return np.searchsorted(self.bounds, rows, side="right") - 1

    def gather(self, rows: np.ndarray) -> np.ndarray:
        """[len(rows), rank] window of the table (any order, repeats OK) —
        the staging read.  Crosses shard boundaries transparently.

        Implemented with ``np.take`` (identical values to fancy
        indexing): its copy loop releases the GIL, which is what lets the
        pooled staging engine (``offload/staging.py``) actually gather
        several shards' windows concurrently on worker threads instead of
        serializing on the interpreter lock."""
        rows = np.asarray(rows, dtype=np.int64)
        if rows.size and (rows.min() < 0 or rows.max() >= self.rows):
            raise IndexError(
                f"window rows outside [0, {self.rows}): "
                f"[{rows.min()}, {rows.max()}]"
            )
        if self.num_shards == 1:
            return np.take(self._shards[0], rows, axis=0)
        out = np.empty((rows.shape[0], self.rank), dtype=self._np_dtype)
        sh = np.searchsorted(self.bounds, rows, side="right") - 1
        for s in range(self.num_shards):
            m = sh == s
            if m.any():
                out[m] = np.take(self._shards[s], rows[m] - self.bounds[s],
                                 axis=0)
        return out

    def write_range(self, start: int, values: np.ndarray) -> None:
        """Write a contiguous [n, rank] row block at ``start`` (the solved
        rows streaming back; values are cast to the store dtype)."""
        values = np.asarray(values)
        stop = start + values.shape[0]
        if start < 0 or stop > self.rows:
            raise IndexError(
                f"write [{start}, {stop}) outside [0, {self.rows})"
            )
        sh0 = int(np.searchsorted(self.bounds, start, side="right") - 1)
        pos = start
        while pos < stop:
            s = sh0
            while self.bounds[s + 1] <= pos:
                s += 1
            sh0 = s
            hi = min(stop, int(self.bounds[s + 1]))
            self._shards[s][pos - self.bounds[s]:hi - self.bounds[s]] = (
                values[pos - start:hi - start].astype(
                    self._np_dtype, copy=False
                )
            )
            self._crcs[s] = None
            pos = hi

    def write_rows(self, rows: np.ndarray, values: np.ndarray) -> None:
        """Scatter [n, rank] values at arbitrary row ids (solved entities
        of one window)."""
        rows = np.asarray(rows, dtype=np.int64)
        values = np.asarray(values)
        if self.num_shards == 1:
            self._shards[0][rows] = values.astype(self._np_dtype, copy=False)
            self._crcs[0] = None
            return
        sh = np.searchsorted(self.bounds, rows, side="right") - 1
        for s in range(self.num_shards):
            m = sh == s
            if m.any():
                self._shards[s][rows[m] - self.bounds[s]] = (
                    values[m].astype(self._np_dtype, copy=False)
                )
                self._crcs[s] = None

    def as_array(self) -> np.ndarray:
        """The whole table as one host array (tests / small shapes / the
        final model hand-off; defeats the sharding on purpose)."""
        if self.num_shards == 1:
            return self._shards[0]
        return np.concatenate(self._shards, axis=0)

    def copy(self) -> "HostFactorStore":
        """Deep copy (the resilient loop's last-good snapshot).  The copy
        starts unsealed — its seals are its own, not inherited."""
        out = HostFactorStore(self.rows, self.rank, dtype=self.dtype,
                              num_shards=self.num_shards)
        for s in range(self.num_shards):
            out._shards[s][...] = self._shards[s]
        return out

    # --- integrity seals ---------------------------------------------------

    def seal(self) -> None:
        """Checksum every dirty shard (crc32 of the raw shard bytes).
        Called at write boundaries — after the solved rows of a half are
        committed — so any later mutation that is NOT a sanctioned write
        (cosmic ray, wild pointer, buggy in-place op) is detectable."""
        for s in range(self.num_shards):
            if self._crcs[s] is None:
                self._crcs[s] = zlib.crc32(self._shards[s].tobytes())

    def scrub(self) -> None:
        """Verify every *sealed* shard against its crc32; dirty shards
        (written since the last seal) are skipped.  Raises
        ``StoreIntegrityError`` naming the first corrupt shard — the
        caller repairs from the last committed checkpoint rather than
        laundering rotten factors into the exchange."""
        for s in range(self.num_shards):
            want = self._crcs[s]
            if want is None:
                continue
            got = zlib.crc32(self._shards[s].tobytes())
            if got != want:
                raise StoreIntegrityError(
                    f"factor store shard {s} fails its integrity seal "
                    f"(crc32 {got:#010x} != sealed {want:#010x}): host-RAM "
                    f"bit-rot in rows [{int(self.bounds[s])}, "
                    f"{int(self.bounds[s + 1])}) — repair from the last "
                    "committed checkpoint",
                    shard=s,
                )
