"""Elastic fleet membership for the multi-process out-of-core tier.

PR 16's fleet contract is stop-the-world: a dead peer turns every
survivor's next collective into an error (or a hang), and the drills
answer with ``os._exit(STALL_EXIT_CODE)`` — correct, but the whole fleet
pays a full restart for one evicted host.  This module is the live
alternative: classification, agreement, and membership-change machinery
that lets ``train_als_host_window`` *shrink* around a dead peer and
*readmit* it when it comes back, instead of dying.

Layers (bottom up):

- **Errors** — the protocol vocabulary.  ``PeerDeadError`` is what the
  driver catches to trigger a shrink; ``StaleEpochError`` is what a
  zombie (a frame from a host's previous life) receives; the rest name
  the refusal reasons.
- **``RetryPolicy`` + ``ElasticFleet``** — transient-vs-fatal peer
  classification.  Wraps any fleet (``GlooFleet``, ``LocalFleet``, a
  ``ThreadFleet``) and retries *transient* collective failures with
  backoff+jitter (``resilience/retry.py``'s schedule) before declaring
  the peer dead; a fatal error type or retry exhaustion raises
  ``PeerDeadError``.  An optional collective timeout catches the hang
  case (a SIGKILL'd Gloo peer sometimes hangs the survivor instead of
  erroring).
- **``FleetManifests``** — per-host checkpoint manifests on shared
  storage (``<dir>/host_<pid>/step_*/manifest.json``).  Each save
  records the writer's fleet epoch and owned row ranges, so survivors
  can (a) min-agree the last step whose manifests jointly cover every
  factor row and (b) reload a dead host's orphaned slice from exactly
  those committed bytes.
- **``Rendezvous`` / ``ThreadFleet``** — an in-process fleet fabric
  (threads + a condition variable) that supports what jax 0.4.37's Gloo
  runtime cannot: membership change and rejoin mid-run.  The REAL
  driver runs on it unmodified via ``train_als_host_window(fleet=...)``,
  which is how the general P→P′ shrink and the rejoin handshake are
  tested without a reformable collective runtime.  Epoch fencing lives
  here: every membership change bumps the epoch, and frames tagged with
  an older epoch from a declared-dead pid raise ``StaleEpochError`` at
  the *sender*.

Under real Gloo the supported live-shrink is 2→1 (the survivor needs no
further collectives, so the un-reformable runtime is simply abandoned);
wider fleets fall back to the bounded-exit path.  That boundary is
documented in ARCHITECTURE.md ("what still requires restart").
"""

from __future__ import annotations

import dataclasses
import random
import threading
import time

import numpy as np

from cfk_tpu.resilience.retry import backoff_delays
from cfk_tpu.telemetry.recorder import record_event


# --------------------------------------------------------------------------
# Protocol errors
# --------------------------------------------------------------------------


class TransientFleetError(RuntimeError):
    """A collective failure worth retrying (injected by tests; real
    transports map their retryable failures here or to ``OSError``)."""


class PeerDeadError(RuntimeError):
    """A peer is gone for good: retries exhausted, a fatal transport
    error, or a collective timeout.  ``peers`` names the dead original
    pids when the transport knows them (may be empty)."""

    def __init__(self, msg: str, *, peers: tuple = ()) -> None:
        super().__init__(msg)
        self.peers = tuple(peers)


class StaleEpochError(RuntimeError):
    """A frame from a previous fleet life: the sender was declared dead
    and the epoch has moved on.  Raised at the *sender* — the zombie
    learns it must rejoin, the survivors never see the frame."""


class CollectiveTimeoutError(RuntimeError):
    """A collective did not complete within ``collective_timeout_s`` —
    the hang flavor of a dead peer (SIGKILL'd Gloo peers sometimes hang
    the survivor instead of erroring)."""


class ShrinkInfeasibleError(RuntimeError):
    """The surviving fleet cannot continue live (shard count not
    divisible, no covering checkpoint, >1 survivor on a Gloo fleet);
    callers fall back to the bounded-exit path."""


class RejoinRefusedError(RuntimeError):
    """The fleet declined a rejoin request (health gate failed, shape
    mismatch, no covering step)."""


class SimulatedHostLoss(BaseException):
    """Raised inside a ThreadFleet 'host' to simulate SIGKILL.  Derives
    from BaseException so no ``except Exception`` recovery path in the
    driver can accidentally swallow the simulated death."""


# --------------------------------------------------------------------------
# Transient-vs-fatal classification
# --------------------------------------------------------------------------


@dataclasses.dataclass
class RetryPolicy:
    """Bounded backoff+jitter schedule for fleet collectives.

    ``attempts`` is the number of *retries* after the first failure;
    ``seed`` makes the jitter deterministic (tests pin the schedule).
    ``sleep`` is injectable so tests assert delays without waiting."""

    attempts: int = 2
    base: float = 0.05
    factor: float = 2.0
    max_delay: float = 1.0
    jitter: float = 0.5
    seed: int | None = None
    sleep = staticmethod(time.sleep)

    def delays(self):
        rng = None if self.seed is None else random.Random(self.seed)
        return backoff_delays(base=self.base, factor=self.factor,
                              max_delay=self.max_delay, jitter=self.jitter,
                              rng=rng)


class ElasticFleet:
    """A fleet wrapper that classifies collective failures.

    Transient errors (``TransientFleetError``, ``OSError`` by default)
    are retried per ``retry``; exhaustion or any other exception declares
    the peer dead (``PeerDeadError``).  With ``collective_timeout_s``
    set, a collective is run on a daemon thread and a timeout is treated
    as a dead peer too — the only way to catch the hang flavor of host
    loss without a reformable runtime.  Membership operations
    (``shrink_to``, ``poll_joiners``/``admit``/``refuse_join``,
    ``join``) delegate to the base fleet when it supports them; for a
    plain Gloo fleet, ``shrink_to`` supports exactly the 2→1 case by
    returning ``None`` (the driver drops to single-host mode and never
    touches the broken runtime again).
    """

    def __init__(self, base, *, retry: RetryPolicy | None = None,
                 collective_timeout_s: float | None = None,
                 metrics=None,
                 transient_types: tuple = (TransientFleetError, OSError)):
        self.base = base
        self.retry = retry or RetryPolicy()
        self.collective_timeout_s = collective_timeout_s
        self.metrics = metrics
        self.transient_types = transient_types

    # -- identity ----------------------------------------------------------

    @property
    def num_processes(self) -> int:
        return self.base.num_processes

    @property
    def process(self) -> int:
        return self.base.process

    @property
    def alive(self) -> tuple:
        return getattr(self.base, "alive",
                       tuple(range(self.base.num_processes)))

    @property
    def epoch(self) -> int:
        return getattr(self.base, "epoch", 0)

    @property
    def is_joiner(self) -> bool:
        return getattr(self.base, "is_joiner", False)

    @property
    def supports_join(self) -> bool:
        return getattr(self.base, "supports_join", False)

    @property
    def orig_process(self) -> int:
        # Original (pre-shrink) pid — stable across membership changes,
        # unlike ``process`` which is the rank within the current fleet.
        return getattr(self.base, "orig_process", self.base.process)

    # -- classification core ----------------------------------------------

    def _declare_dead(self, cause: BaseException) -> "PeerDeadError":
        peers = getattr(cause, "peers", ())
        if not peers and self.num_processes == 2:
            # Two-host fleet: the dead peer can only be the other one.
            peers = tuple(p for p in self.alive if p != self.process)
        record_event("fault", "fleet_peer_declared_dead",
                     process=self.process, peers=list(peers),
                     error=f"{type(cause).__name__}: {cause}")
        if self.metrics is not None:
            self.metrics.incr("fleet_peers_declared_dead")
        err = PeerDeadError(
            f"fleet peer declared dead after collective failure: "
            f"{type(cause).__name__}: {cause}", peers=peers)
        err.__cause__ = cause
        return err

    def _run_with_timeout(self, fn):
        box: dict = {}
        done = threading.Event()

        def _worker():
            try:
                box["value"] = fn()
            except BaseException as e:  # noqa: BLE001 - reported below
                box["error"] = e
            finally:
                done.set()

        t = threading.Thread(target=_worker, daemon=True,
                             name="cfk-fleet-collective")
        t.start()
        if not done.wait(self.collective_timeout_s):
            # The thread is abandoned (nothing can cancel a hung Gloo
            # collective); post-shrink the survivor never runs another
            # collective, so the zombie thread is harmless.
            raise CollectiveTimeoutError(
                f"fleet collective did not complete within "
                f"{self.collective_timeout_s:.1f}s"
            )
        if "error" in box:
            raise box["error"]
        return box.get("value")

    def _call(self, fn, describe: str):
        if isinstance(self.base, ElasticFleet):  # avoid double-wrapping
            return fn()
        delays = self.retry.delays()
        attempt = 0
        while True:
            try:
                if self.collective_timeout_s is not None:
                    return self._run_with_timeout(fn)
                return fn()
            except (PeerDeadError, StaleEpochError):
                raise  # already classified by the base fleet
            except self.transient_types as e:
                attempt += 1
                if attempt > self.retry.attempts:
                    raise self._declare_dead(e) from e
                record_event("retry", "fleet_transient_retry", op=describe,
                             attempt=attempt,
                             error=f"{type(e).__name__}: {e}")
                if self.metrics is not None:
                    self.metrics.incr("fleet_transient_retries")
                self.retry.sleep(next(delays))
            except BaseException as e:
                if isinstance(e, SimulatedHostLoss):
                    raise  # this host "died" — never classify our own death
                raise self._declare_dead(e) from e

    # -- collectives -------------------------------------------------------

    def allgather_bytes(self, payload: np.ndarray) -> np.ndarray:
        return self._call(lambda: self.base.allgather_bytes(payload),
                          "allgather_bytes")

    def allgather_i32(self, values) -> np.ndarray:
        return self._call(lambda: self.base.allgather_i32(values),
                          "allgather_i32")

    # -- membership --------------------------------------------------------

    def surviving(self, exc: PeerDeadError) -> list[int]:
        """Original pids still alive after ``exc``.  Prefers the base
        fleet's own view (a Rendezvous knows), falls back to the error's
        ``peers``, then to "just me" for a 2-host fleet."""
        base_fn = getattr(self.base, "surviving", None)
        if base_fn is not None:
            return list(base_fn())
        if exc.peers:
            return [p for p in self.alive if p not in exc.peers]
        if self.num_processes == 2:
            return [self.process]
        raise ShrinkInfeasibleError(
            "cannot identify survivors: the transport reported no dead "
            "peers and the fleet has more than two hosts"
        )

    def shrink_to(self, alive: list[int]):
        """Reform the fleet around ``alive``; returns the new fleet
        handle, or ``None`` when the survivor continues single-host."""
        base_fn = getattr(self.base, "shrink_to", None)
        if base_fn is not None:
            new_base = base_fn(list(alive))
            if new_base is None:
                return None
            if new_base is self.base:
                # The base reformed in place — keep this wrapper (and its
                # classification/retry state) bound to it.
                return self
            return ElasticFleet(
                new_base, retry=self.retry,
                collective_timeout_s=self.collective_timeout_s,
                metrics=self.metrics,
                transient_types=self.transient_types,
            )
        if len(alive) == 1:
            # Gloo 2→1: the lone survivor needs no further collectives,
            # so the dead runtime is simply never touched again.
            return None
        raise ShrinkInfeasibleError(
            f"this fleet transport cannot reform around {len(alive)} "
            "survivors (jax's Gloo runtime is fixed at init); only the "
            "2-host → 1-survivor shrink is live, wider fleets restart"
        )

    def join(self, info: dict) -> dict:
        return self.base.join(info)

    def poll_joiners(self) -> list:
        fn = getattr(self.base, "poll_joiners", None)
        return [] if fn is None else fn()

    def refuse_join(self, pid: int, reason: str) -> None:
        self.base.refuse_join(pid, reason)

    def admit(self, pid: int, new_epoch: int, new_alive: list[int],
              step: int) -> None:
        self.base.admit(self.process, pid, new_epoch, new_alive, step)


# --------------------------------------------------------------------------
# Per-host manifests: agreement + orphan-slice reload
# --------------------------------------------------------------------------


class FleetManifests:
    """The fleet's shared-storage checkpoint layout:
    ``<base>/host_<pid>/step_*/...``, one ``CheckpointManager`` per host.

    Every save records the writer's fleet epoch and owned row ranges in
    the step manifest, which makes two things pure filesystem reads:
    agreeing on the last *jointly covered* step (no collectives needed —
    crucial when the runtime that would carry ``agree_min_i32`` is the
    thing that just died), and reassembling any row range of either
    factor table from committed bytes (the orphan-slice reload)."""

    def __init__(self, base_dir: str) -> None:
        import os

        self.base_dir = base_dir
        os.makedirs(base_dir, exist_ok=True)
        self._managers: dict[int, object] = {}

    def host_dir(self, pid: int) -> str:
        import os

        return os.path.join(self.base_dir, f"host_{pid}")

    def manager_for(self, pid: int):
        from cfk_tpu.transport.checkpoint import CheckpointManager

        if pid not in self._managers:
            self._managers[pid] = CheckpointManager(self.host_dir(pid))
        return self._managers[pid]

    def reachable(self) -> list[int]:
        """Pids with at least one committed step on shared storage."""
        import os
        import re

        pids = []
        for name in sorted(os.listdir(self.base_dir)):
            m = re.fullmatch(r"host_(\d+)", name)
            if m and self.manager_for(int(m.group(1))).iterations():
                pids.append(int(m.group(1)))
        return pids

    def latest_coverage_step(self, rows_u: int, rows_m: int) -> int | None:
        """Newest step whose per-host manifests jointly cover every row
        of both factor tables — the min-agree over manifests.  A host
        that died before committing a step simply leaves a hole; the
        search walks older steps until coverage closes (or returns
        ``None``: no step is jointly restorable)."""
        pids = self.reachable()
        if not pids:
            return None
        steps: set[int] = set()
        for pid in pids:
            steps.update(self.manager_for(pid).iterations())
        for step in sorted(steps, reverse=True):
            if (self._covered(step, pids, "u", rows_u)
                    and self._covered(step, pids, "m", rows_m)):
                return step
        return None

    def _metas(self, step: int, pids) -> list[tuple[int, dict]]:
        out = []
        for pid in pids:
            mgr = self.manager_for(pid)
            if step not in mgr.iterations():
                continue
            try:
                out.append((pid, mgr.manifest_meta(step)))
            except Exception:
                continue  # torn step on one host: treat as a hole
        return out

    def _covered(self, step: int, pids, side: str, rows: int) -> bool:
        spans = []
        for _, meta in self._metas(step, pids):
            lo, hi = meta.get(f"{side}_row_lo"), meta.get(f"{side}_row_hi")
            if lo is None or hi is None:
                # Pre-elastic manifest: the writer held the full table.
                lo, hi = 0, rows
            spans.append((int(lo), int(hi)))
        spans.sort()
        pos = 0
        for lo, hi in spans:
            if lo > pos:
                return False
            pos = max(pos, hi)
        return pos >= rows

    def load_rows(self, step: int, lo: int, hi: int, side: str, *,
                  rank: int) -> np.ndarray:
        """Reassemble rows ``[lo, hi)`` of factor table ``side`` ("u" or
        "m") at ``step`` from committed per-host bytes.  When ranges
        overlap across hosts (a host's range moved between epochs), the
        higher ``fleet_epoch`` wins — later lives overwrite earlier
        ones.  Raises ``ShrinkInfeasibleError`` on any uncovered row."""
        out = np.zeros((hi - lo, rank), np.float32)
        covered = np.zeros(hi - lo, bool)
        metas = self._metas(step, self.reachable())
        metas.sort(key=lambda pm: int(pm[1].get("fleet_epoch", 0)))
        for pid, meta in metas:
            h_lo = meta.get(f"{side}_row_lo")
            h_hi = meta.get(f"{side}_row_hi")
            if h_lo is None or h_hi is None:
                h_lo, h_hi = 0, None  # full table
            a, b = max(lo, int(h_lo)), hi if h_hi is None else min(hi, int(h_hi))
            if a >= b:
                continue
            state = self.manager_for(pid).restore(step)
            table = state.user_factors if side == "u" else state.movie_factors
            if h_hi is None:
                h_hi = table.shape[0]
                b = min(hi, h_hi)
                if a >= b:
                    continue
            out[a - lo:b - lo] = np.asarray(
                table[a - int(h_lo):b - int(h_lo)], np.float32
            )
            covered[a - lo:b - lo] = True
        if not covered.all():
            holes = int((~covered).sum())
            raise ShrinkInfeasibleError(
                f"orphan-slice reload of {side}[{lo}:{hi}) at step {step} "
                f"has {holes} uncovered rows — no committed manifest holds "
                "them; the covering-step search should have rejected this "
                "step"
            )
        return out


# --------------------------------------------------------------------------
# In-process rendezvous fabric: membership change + epoch fencing
# --------------------------------------------------------------------------


class Rendezvous:
    """The in-process fleet fabric: N threads rendezvous per collective,
    with live membership (``mark_dead``/``begin_epoch``), epoch fencing
    (stale frames from a dead pid's previous life raise
    ``StaleEpochError`` at the sender), and a join handshake
    (``request_join`` blocks until the fleet ``admit``s or refuses).

    This is what lets the REAL ``train_als_host_window`` exercise the
    general shrink and the rejoin protocol in one process — jax's Gloo
    runtime can't reform, threads can."""

    def __init__(self, num_processes: int, *, timeout_s: float = 60.0):
        self.timeout_s = timeout_s
        self._cv = threading.Condition()
        self.epoch = 0
        self.alive: tuple = tuple(range(num_processes))
        self.dead: set = set()
        self.stale_rejected = 0
        self._slots: dict = {}
        self._join_requests: dict = {}
        self._admissions: dict = {}
        self._refusals: dict = {}
        self._admit_acks: dict = {}

    # -- collectives -------------------------------------------------------

    def contribute(self, pid: int, epoch: int, seq: int,
                   payload: np.ndarray) -> list:
        """One host's contribution to collective ``(epoch, seq)``.
        Blocks until every live member has contributed; returns payloads
        ordered by sorted pid.  Entry checks fence the three failure
        shapes: a zombie (declared-dead pid) gets ``StaleEpochError``, a
        lagging survivor (old epoch but still alive) gets
        ``PeerDeadError`` so it runs its own shrink, and any other
        epoch/membership mismatch is stale."""
        with self._cv:
            while True:
                if pid in self.dead:
                    self.stale_rejected += 1
                    record_event("fault", "stale_epoch_rejected", pid=pid,
                                 frame_epoch=epoch, fleet_epoch=self.epoch,
                                 seq=seq)
                    raise StaleEpochError(
                        f"frame from pid {pid} epoch {epoch} rejected: the "
                        f"fleet is at epoch {self.epoch} and pid {pid} was "
                        "declared dead — rejoin to continue"
                    )
                if epoch < self.epoch and pid in self.alive:
                    raise PeerDeadError(
                        f"pid {pid} is at epoch {epoch} but the fleet moved "
                        f"to {self.epoch}: a peer died while this host was "
                        "mid-collective", peers=tuple(sorted(self.dead)))
                if epoch != self.epoch or pid not in self.alive:
                    self.stale_rejected += 1
                    record_event("fault", "stale_epoch_rejected", pid=pid,
                                 frame_epoch=epoch, fleet_epoch=self.epoch,
                                 seq=seq)
                    raise StaleEpochError(
                        f"frame from pid {pid} epoch {epoch} does not match "
                        f"fleet epoch {self.epoch} alive={self.alive}"
                    )
                key = (epoch, seq)
                slot = self._slots.setdefault(
                    key, {"got": {}, "served": set()})
                slot["got"][pid] = np.array(payload, copy=True)
                self._cv.notify_all()
                deadline = time.monotonic() + self.timeout_s
                while True:
                    if set(slot["got"]) >= set(self.alive):
                        ordered = [slot["got"][p]
                                   for p in sorted(self.alive)]
                        slot["served"].add(pid)
                        if slot["served"] >= set(self.alive):
                            self._slots.pop(key, None)
                        return ordered
                    if self.dead & set(self.alive):
                        raise PeerDeadError(
                            f"peer(s) {sorted(self.dead & set(self.alive))} "
                            f"died during collective (epoch {epoch}, "
                            f"seq {seq})",
                            peers=tuple(sorted(self.dead & set(self.alive))))
                    if epoch != self.epoch:
                        # Membership changed under us while waiting.
                        break  # re-run the entry checks
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        missing = sorted(set(self.alive) - set(slot["got"]))
                        raise PeerDeadError(
                            f"collective (epoch {epoch}, seq {seq}) timed "
                            f"out waiting for {missing}",
                            peers=tuple(missing))
                    self._cv.wait(remaining)

    # -- membership --------------------------------------------------------

    def mark_dead(self, pid: int) -> None:
        with self._cv:
            self.dead.add(pid)
            self._cv.notify_all()

    def surviving(self) -> list[int]:
        with self._cv:
            return sorted(set(self.alive) - self.dead)

    def begin_epoch(self, new_epoch: int, new_alive: list[int]) -> None:
        """Flip the fleet to ``new_epoch``/``new_alive``.  Idempotent:
        the first survivor flips, later survivors validate they agree."""
        with self._cv:
            if self.epoch == new_epoch:
                if tuple(sorted(new_alive)) != tuple(sorted(self.alive)):
                    raise RuntimeError(
                        f"epoch {new_epoch} already begun with alive="
                        f"{self.alive}, got {sorted(new_alive)}"
                    )
                return
            if new_epoch != self.epoch + 1:
                raise RuntimeError(
                    f"epoch must advance by 1: {self.epoch} -> {new_epoch}"
                )
            self.epoch = new_epoch
            self.alive = tuple(sorted(new_alive))
            self._slots.clear()
            self._cv.notify_all()

    # -- join handshake ----------------------------------------------------

    def request_join(self, pid: int, info: dict) -> dict:
        """A restarted host asks back in.  Blocks until a survivor
        ``admit``s (returns ``{"epoch", "alive", "step"}``) or refuses
        (``RejoinRefusedError``)."""
        with self._cv:
            self._join_requests[pid] = dict(info)
            self._cv.notify_all()
            deadline = time.monotonic() + self.timeout_s
            while True:
                if pid in self._admissions:
                    adm = self._admissions.pop(pid)
                    self._join_requests.pop(pid, None)
                    return adm
                if pid in self._refusals:
                    reason = self._refusals.pop(pid)
                    self._join_requests.pop(pid, None)
                    raise RejoinRefusedError(
                        f"fleet refused rejoin of pid {pid}: {reason}"
                    )
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    self._join_requests.pop(pid, None)
                    raise RejoinRefusedError(
                        f"rejoin request from pid {pid} timed out after "
                        f"{self.timeout_s:.1f}s"
                    )
                self._cv.wait(remaining)

    def poll_joiners(self) -> list[tuple[int, dict]]:
        with self._cv:
            return sorted(self._join_requests.items())

    def refuse_join(self, pid: int, reason: str) -> None:
        with self._cv:
            if pid in self._join_requests and pid not in self._refusals:
                self._refusals[pid] = reason
                self._cv.notify_all()

    def admit(self, acker: int, pid: int, new_epoch: int,
              new_alive: list[int], step: int) -> None:
        """One survivor's vote to admit ``pid``.  Every current member
        must ack (they all reached the same boundary decision); the last
        ack flips the epoch, revives the pid, and unblocks the joiner.
        Earlier ackers block until the flip so everyone leaves admit in
        the new epoch together."""
        with self._cv:
            key = (pid, new_epoch)
            acks = self._admit_acks.setdefault(key, set())
            acks.add(acker)
            need = set(self.alive)
            if acks >= need and self.epoch < new_epoch:
                self.epoch = new_epoch
                self.alive = tuple(sorted(new_alive))
                self.dead.discard(pid)
                self._slots.clear()
                self._admissions[pid] = {
                    "epoch": new_epoch,
                    "alive": tuple(sorted(new_alive)),
                    "step": int(step),
                }
                self._admit_acks.pop(key, None)
                record_event("fleet", "fleet_rejoin_admitted", pid=pid,
                             epoch=new_epoch, alive=sorted(new_alive),
                             step=int(step))
                self._cv.notify_all()
                return
            deadline = time.monotonic() + self.timeout_s
            while self.epoch < new_epoch:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise RuntimeError(
                        f"admit of pid {pid} at epoch {new_epoch} timed out "
                        f"waiting for peer acks ({sorted(acks)} of "
                        f"{sorted(need)})"
                    )
                self._cv.wait(remaining)


class ThreadFleet:
    """One 'host' of a Rendezvous fleet — the fleet handle the driver
    sees.  Satisfies the ``GlooFleet`` surface (``num_processes``,
    ``process``, ``allgather_bytes``, ``allgather_i32``) plus the
    elastic extensions (``shrink_to``, ``surviving``, join handshake).

    ``process`` is the *rank within the current alive set* (what the
    exchange plans index by); ``orig_process`` is the stable identity
    used on the wire and in manifests."""

    supports_join = True

    def __init__(self, rdv: Rendezvous, process: int, *,
                 joiner: bool = False):
        self.rdv = rdv
        self.orig_process = process
        self.is_joiner = joiner
        self._kill_in: int | None = None
        if joiner:
            self.epoch = -1
            self.alive: tuple = ()
            self.num_processes = 0
            self.process = -1
        else:
            self._apply(rdv.epoch, rdv.alive)
        self._seq = 0

    def _apply(self, epoch: int, alive) -> None:
        self.epoch = epoch
        self.alive = tuple(sorted(alive))
        self.num_processes = len(self.alive)
        self.process = self.alive.index(self.orig_process)
        self._seq = 0

    def _maybe_kill(self) -> None:
        if self._kill_in is None:
            return
        self._kill_in -= 1
        if self._kill_in <= 0:
            self._kill_in = None
            self.rdv.mark_dead(self.orig_process)
            raise SimulatedHostLoss(
                f"simulated SIGKILL of pid {self.orig_process}"
            )

    def kill_after(self, n: int) -> None:
        """Die (SimulatedHostLoss + mark_dead) on the ``n``-th collective
        from now — mid-half when armed at an iteration boundary."""
        self._kill_in = int(n)

    # -- collectives -------------------------------------------------------

    def _collect(self, payload: np.ndarray) -> np.ndarray:
        self._maybe_kill()
        seq = self._seq
        self._seq += 1
        parts = self.rdv.contribute(self.orig_process, self.epoch, seq,
                                    payload)
        return np.stack(parts, axis=0)

    def allgather_bytes(self, payload: np.ndarray) -> np.ndarray:
        return self._collect(np.ascontiguousarray(payload, np.uint8))

    def allgather_i32(self, values) -> np.ndarray:
        arr = np.atleast_1d(np.asarray(values, np.int32))
        return self._collect(arr)

    # -- membership --------------------------------------------------------

    def surviving(self) -> list[int]:
        return self.rdv.surviving()

    def shrink_to(self, alive: list[int]) -> "ThreadFleet":
        self.rdv.begin_epoch(self.epoch + 1, alive)
        self._apply(self.rdv.epoch, self.rdv.alive)
        # Keep the handle even at P'=1: a later rejoin re-inflates it.
        return self

    def join(self, info: dict) -> dict:
        adm = self.rdv.request_join(self.orig_process, info)
        self._apply(adm["epoch"], adm["alive"])
        self.is_joiner = False
        return adm

    def poll_joiners(self) -> list:
        return self.rdv.poll_joiners()

    def refuse_join(self, pid: int, reason: str) -> None:
        self.rdv.refuse_join(pid, reason)

    def admit(self, acker_rank: int, pid: int, new_epoch: int,
              new_alive: list[int], step: int) -> None:
        self.rdv.admit(self.orig_process, pid, new_epoch, new_alive, step)
        self._apply(self.rdv.epoch, self.rdv.alive)


# --------------------------------------------------------------------------
# Threaded-fleet harness (tests + chaos_lab's in-process scenarios)
# --------------------------------------------------------------------------


class _KillAtIteration:
    """Watchdog stand-in that arms a ThreadFleet's kill switch once the
    victim completes ``iteration`` iterations.  kill_after(3) dies on
    the 3rd collective after the boundary: the rejoin poll (1) and the
    lockstep any_flag (2) pass, the next half's first exchange phase (3)
    kills — i.e. mid-half, the hard case."""

    def __init__(self, tf: ThreadFleet, iteration: int):
        self.tf = tf
        self.iteration = iteration
        self._armed = False

    def arm(self) -> None:
        pass

    def disarm(self) -> None:
        pass

    def tick(self, done: int) -> None:
        if not self._armed and done >= self.iteration:
            self._armed = True
            self.tf.kill_after(3)


class _PaceForJoin:
    """Watchdog stand-in for SURVIVORS in rejoin scenarios: after the
    kill iteration, hold each boundary until the restarted host has
    filed its join request (or the rejoin completed, epoch >= 2) so the
    admission lands deterministically instead of racing the survivor to
    the end of training.  Timeout keeps a broken joiner from hanging the
    harness."""

    def __init__(self, rdv: Rendezvous, after_iteration: int,
                 timeout_s: float):
        self.rdv = rdv
        self.after_iteration = after_iteration
        self.timeout_s = timeout_s

    def arm(self) -> None:
        pass

    def disarm(self) -> None:
        pass

    def tick(self, done: int) -> None:
        if done <= self.after_iteration:
            return
        deadline = time.monotonic() + self.timeout_s
        while (self.rdv.epoch < 2 and not self.rdv.poll_joiners()
               and time.monotonic() < deadline):
            time.sleep(0.01)


def run_threaded_fleet(dataset, config, *, ckdir: str,
                       num_processes: int = 2, kill_pid: int | None = None,
                       kill_iteration: int | None = None,
                       rejoin: bool = False, zombie_probe: bool = False,
                       thread_timeout_s: float = 300.0) -> dict:
    """Run the REAL ``train_als_host_window`` as an N-thread fleet over a
    Rendezvous fabric, optionally killing one 'host' mid-half and
    optionally restarting it as a joiner.

    Returns ``{"results": {key: model-or-exception}, "rendezvous",
    "stale_rejected", "stale_error", "epoch"}``.  ``results`` keys are
    pids (and ``"<pid>:rejoin"`` for the restarted life)."""
    from cfk_tpu.offload.windowed import train_als_host_window
    from cfk_tpu.telemetry.metrics import Metrics

    rdv = Rendezvous(num_processes, timeout_s=thread_timeout_s)
    manifests = FleetManifests(ckdir)
    results: dict = {}
    metrics: dict = {}

    def _run(key, pid, *, joiner=False, watchdog=None):
        tf = ThreadFleet(rdv, pid, joiner=joiner)
        met = Metrics()
        metrics[key] = met

        def _target():
            try:
                results[key] = train_als_host_window(
                    dataset, config, metrics=met,
                    checkpoint_manager=manifests.manager_for(pid),
                    fleet=tf, fleet_manifests=manifests,
                    watchdog=watchdog(tf) if watchdog else None,
                )
            except BaseException as e:  # noqa: BLE001 - harness boundary
                results[key] = e

        t = threading.Thread(target=_target, daemon=True,
                             name=f"cfk-fleet-host-{key}")
        t.start()
        return t

    threads = {}
    for pid in range(num_processes):
        wd = None
        if pid == kill_pid and kill_iteration is not None:
            wd = lambda tf: _KillAtIteration(tf, kill_iteration)  # noqa: E731
        elif rejoin and kill_iteration is not None:
            wd = lambda tf: _PaceForJoin(  # noqa: E731
                rdv, kill_iteration, min(thread_timeout_s, 60.0))
        threads[pid] = _run(pid, pid, watchdog=wd)

    stale_error = None
    if rejoin and kill_pid is not None:
        threads[kill_pid].join(thread_timeout_s)
        # Wait for the survivors to finish the shrink (epoch >= 1).
        deadline = time.monotonic() + thread_timeout_s
        while rdv.epoch < 1 and time.monotonic() < deadline:
            time.sleep(0.01)
        if zombie_probe:
            # A frame from the dead host's first life must be fenced.
            try:
                rdv.contribute(kill_pid, 0, 10_000,
                               np.zeros(1, np.int32))
            except StaleEpochError as e:
                stale_error = e
        threads[f"{kill_pid}:rejoin"] = _run(
            f"{kill_pid}:rejoin", kill_pid, joiner=True)

    for key, t in threads.items():
        t.join(thread_timeout_s)
        if t.is_alive():
            results.setdefault(
                key, TimeoutError(f"fleet thread {key} did not finish"))

    return {
        "results": results,
        "metrics": metrics,
        "rendezvous": rdv,
        "stale_rejected": rdv.stale_rejected,
        "stale_error": stale_error,
        "epoch": rdv.epoch,
    }
