"""Model-FLOP / HBM-byte accounting and MFU for the ALS iteration.

The reference has no notion of compute efficiency — its hot loop is a
per-entity EJML solve (``processors/MFeatureCalculator.java:85-99``) and its
only telemetry is wall-clock milliseconds.  On TPU the honest yardstick is
the hardware: model FLOPs per iteration over the chip's peak (MFU), and the
minimum HBM traffic over measured bandwidth (roofline).  These numbers are
printed by ``bench.py`` so every recorded benchmark carries its efficiency.

Conventions
-----------
- *Model FLOPs* count the algorithmic minimum, independent of backend: the
  Gram/RHS contractions (2 FLOPs per MAC) plus one Cholesky-cost solve per
  entity.  Implementation overhead (the pallas Gauss-Jordan's 2k³ vs
  Cholesky's k³/3, padding waste, masked lanes) deliberately does NOT count —
  MFU measures useful work extracted from the chip.
- *Min HBM bytes* count each operand's unavoidable traffic once: the random
  neighbor-factor gathers, one read of the block arrays, one write+read of
  the per-entity Gram/RHS intermediates (they cross an op boundary into the
  solve), and the factor write-back.  Fusion can only approach this from
  above; the gap between the measured iteration and ``min_bytes / bandwidth``
  is the tractable inefficiency.
"""

from __future__ import annotations

import dataclasses

# TPU v5e (v5 lite) single chip, from the public spec sheet.
V5E_PEAK_BF16_FLOPS = 197e12  # per second
V5E_HBM_BYTES_PER_S = 819e9
V5E_HBM_BYTES = 16 * 1024**3
# Measured on this chip (r3 gather micro-bench + in-scan profile): XLA's
# row-gather engine sustains ~600M rows/s on ≤34 MB tables REGARDLESS of
# row width (64-col bf16 and 128-col rows time identically) — the gather
# is row-slot-bound, not byte-bound.  ALS is two gathers per rating per
# iteration, which makes THIS the binding resource at full Netflix scale,
# not HBM bandwidth: the row-gather floor (~0.36 s/iter) sits 6.7× above
# the naive HBM roofline (54 ms).
V5E_GATHER_ROWS_PER_S = 600e6


@dataclasses.dataclass(frozen=True)
class IterationCost:
    """Per-full-iteration (both half-steps) model cost of one ALS sweep."""

    model_flops: float
    min_hbm_bytes: float
    gather_rows: float  # factor rows fetched by index per iteration
    gather_bytes: float = 0.0  # bytes those row fetches move (table dtype)

    def achieved_tflops(self, seconds: float) -> float:
        return self.model_flops / seconds / 1e12

    def mfu(self, seconds: float, peak_flops: float = V5E_PEAK_BF16_FLOPS) -> float:
        return self.model_flops / seconds / peak_flops

    def hbm_bound_s(self, bandwidth: float = V5E_HBM_BYTES_PER_S) -> float:
        """Naive roofline floor: minimum HBM traffic over peak bandwidth."""
        return self.min_hbm_bytes / bandwidth

    def gather_bound_s(
        self, rows_per_s: float = V5E_GATHER_ROWS_PER_S,
        bandwidth: float = V5E_HBM_BYTES_PER_S,
    ) -> float:
        """Gather floor: the binding resource for ALS on this chip.

        Every rating needs its neighbor's factor row on each side every
        iteration.  Two sub-floors, the floor is their max:

        - row-slot: the measured engine rate is per ROW, independent of
          row bytes (XLA's gather engine; the in-kernel DMA gather issues
          one descriptor per row, so rows/s bounds it the same way), and
        - bytes: the rows must still physically cross HBM —
          ``gather_bytes / bandwidth``.  This is the sub-floor the table
          dtype moves (bf16 halves it, int8+scale quarters it); the
          row-slot sub-floor is dtype-independent, which is exactly why
          ``vs_gather_roofline`` must model both or quantized runs would
          be compared against a floor they can no longer touch.
        """
        return max(self.gather_rows / rows_per_s,
                   self.gather_bytes / bandwidth)


FULL_NETFLIX_NNZ = 100_480_507


def roofline_row(cost: IterationCost, s_per_iter: float,
                 table_dtype: str | None = None) -> dict:
    """The efficiency fields every recorded benchmark row carries.

    One definition so bench.py's rows and scripts/perf_lab.py can never
    drift on which metrics exist or how they're computed.  ``table_dtype``
    records the gather-table quantization the run used (None → float32
    pre-quantization semantics are NOT implied — pass what the run ran)."""
    row = {
        "model_tflops_per_iter": round(cost.model_flops / 1e12, 4),
        "achieved_tflops": round(cost.achieved_tflops(s_per_iter), 4),
        "mfu": round(cost.mfu(s_per_iter), 5),
        "min_hbm_gb_per_iter": round(cost.min_hbm_bytes / 1e9, 3),
        "hbm_roofline_s": round(cost.hbm_bound_s(), 4),
        "vs_hbm_roofline": round(s_per_iter / cost.hbm_bound_s(), 2),
        "gather_roofline_s": round(cost.gather_bound_s(), 4),
        "vs_gather_roofline": round(s_per_iter / cost.gather_bound_s(), 2),
        "gather_gb_per_iter": round(cost.gather_bytes / 1e9, 3),
    }
    if table_dtype is not None:
        row["table_dtype"] = table_dtype
    return row


def table_gather_bytes_per_row(rank: int, table_dtype: str | None,
                               factor_bytes: int = 4) -> float:
    """Bytes one gathered factor row moves under the given table dtype —
    k cells at the table itemsize, plus the int8 scheme's one f32 scale
    per row (``ops.quant``).  ``table_dtype="float32"`` is the quant
    IDENTITY — the table stays at the storage dtype — so the effective
    cell size is min(table, storage): a bf16-stored f32-table run still
    gathers 2-byte cells."""
    from cfk_tpu.ops.quant import resolve_table_dtype, table_itemsize

    per_row = rank * min(table_itemsize(table_dtype), factor_bytes)
    if resolve_table_dtype(table_dtype) == "int8":
        per_row += 4  # the per-row f32 dequant scale rides along
    return float(per_row)


def bucketed_gather_rows(movie_blocks, user_blocks) -> float:
    """Honest gather-row count for the bucketed layout: every PADDED cell
    of every width class fetches a row (padding slots gather the clamped /
    zero row like any other — the engine charges the slot), so the floor
    is Σ rows·width per class per side, not 2·nnz.  BENCH_r05's bucketed
    rows were computed at 2·nnz, which understated the floor by the
    padding ratio (~1.3–2× on power-law data) — part of why
    ``ialspp_ml25m`` read as 9.94× its roofline."""
    return float(movie_blocks.padded_cells + user_blocks.padded_cells)


@dataclasses.dataclass(frozen=True)
class ServeBatchCost:
    """Per-scoring-batch model cost of the top-K serve path (ISSUE 8).

    The serve kernel's traffic model is simple and strict: every batch
    scans the ENTIRE item factor table exactly once (movie-axis tiles
    streamed through VMEM — there is no reuse across batches to model,
    and no dense [B, M] score matrix to charge because none exists), plus
    the [B, k] batch in and the [B, K] selection out.  The table scan is
    what the quantized-table dtypes shrink — bf16 halves it, int8+scale
    quarters it — which is why ``vs_roofline`` must be computed against
    the dtype-aware floor or quantized rows would be compared against a
    floor they can no longer touch (the same honesty rule as the gather
    roofline)."""

    model_flops: float  # 2·B·M_pad·k score MACs (the merge is negligible)
    hbm_bytes: float  # table scan + batch in + [B, K] out

    def flops_bound_s(self, peak=V5E_PEAK_BF16_FLOPS) -> float:
        return self.model_flops / peak

    def bytes_bound_s(self, bandwidth=V5E_HBM_BYTES_PER_S) -> float:
        return self.hbm_bytes / bandwidth

    def batch_bound_s(self, peak=V5E_PEAK_BF16_FLOPS,
                      bandwidth=V5E_HBM_BYTES_PER_S) -> float:
        """The floor is max(compute, bytes): at serving batch sizes the
        table scan dominates (B ≪ M), so the roofline QPS is essentially
        batch · bandwidth / table_bytes — bigger batches and smaller
        table dtypes are THE two levers."""
        return max(self.flops_bound_s(peak), self.bytes_bound_s(bandwidth))


def expected_shortlist_rows(num_movies: int, batch: int, clusters: int,
                            probe_clusters: int) -> float:
    """Expected batch-union shortlist rows of the two-stage path.

    Each user probes ``probe`` of ``clusters`` clusters; the rescore
    gathers the BATCH-UNION, so the expected covered-cluster fraction is
    ``1 − (1 − probe/clusters)^batch`` under the independence prior (the
    model's conservative default — correlated traffic, the common case
    under zipf user popularity, only shrinks the union).  This is the
    model-side estimate; the bench charges the MEASURED union instead."""
    c = max(int(clusters), 1)
    p = min(max(int(probe_clusters), 1), c)
    frac = 1.0 - (1.0 - p / c) ** max(int(batch), 1)
    return float(num_movies) * frac


def serve_batch_cost(num_movies: int, rank: int, batch: int, k_top: int,
                     *, table_dtype: str | None = None,
                     m_pad: int | None = None,
                     serve_mode: str = "exact",
                     clusters: int = 0, probe_clusters: int = 0,
                     shortlist_rows: float | None = None) -> ServeBatchCost:
    """Model cost of one [batch, k_top] top-K scoring batch.

    ``m_pad`` is the padded table row count actually scanned (tile/shard
    padding scans too — charge what the kernel reads); the per-row bytes
    follow the table dtype exactly like the gather floor
    (``table_gather_bytes_per_row`` — int8 is charged codes PLUS the
    per-row f32 scale, never a flat 1 B/row).

    ``serve_mode="two_stage"`` (ISSUE 16) swaps the full table scan for
    the clustered path's traffic: the [clusters, k] centroid scan (same
    dtype as the table — the coarse stage scores the quantized view) plus
    the gathered shortlist rows (``shortlist_rows`` when MEASURED —
    bench/engine pass the real union — else the expected batch-union,
    ``expected_shortlist_rows``) at table-row bytes plus 4 B/row of
    gather indices.  That byte swap IS the lever the planner prices:
    two_stage wins exactly where centroids + shortlist undercut the scan.
    """
    row_bytes = table_gather_bytes_per_row(rank, table_dtype)
    io_bytes = batch * rank * 4.0 + batch * k_top * 8.0
    if serve_mode == "two_stage":
        if clusters <= 0:
            raise ValueError("two_stage cost needs clusters >= 1")
        sl_rows = (float(shortlist_rows) if shortlist_rows is not None
                   else expected_shortlist_rows(num_movies, batch, clusters,
                                                probe_clusters))
        centroid_bytes = clusters * row_bytes
        shortlist_bytes = sl_rows * (row_bytes + 4.0)  # + int32 gather idx
        flops = 2.0 * batch * (clusters + sl_rows) * rank
        return ServeBatchCost(
            model_flops=flops,
            hbm_bytes=centroid_bytes + shortlist_bytes + io_bytes,
        )
    rows = float(m_pad if m_pad is not None else num_movies)
    flops = 2.0 * batch * rows * rank
    table_bytes = rows * row_bytes
    return ServeBatchCost(
        model_flops=flops, hbm_bytes=table_bytes + io_bytes
    )


def serve_roofline_row(cost: ServeBatchCost, s_per_batch: float,
                       table_dtype: str | None = None) -> dict:
    """The efficiency fields every ``bench.py --serve`` row carries — one
    definition shared with ``perf_lab --serve`` (the same no-drift rule as
    ``roofline_row``)."""
    floor = cost.batch_bound_s()
    row = {
        "serve_batch_tflops": round(cost.model_flops / 1e12, 6),
        "serve_batch_mb": round(cost.hbm_bytes / 1e6, 3),
        # The EXECUTED mode's per-batch HBM traffic (ISSUE 16): for exact
        # rows this is the table scan + io; for two_stage rows the caller
        # builds the cost from the MEASURED shortlist union, so the byte
        # column is what the batch actually moved, not the model's guess.
        "bytes_scanned_per_batch": round(cost.hbm_bytes),
        "serve_roofline_s": round(floor, 6),
        "vs_roofline": round(s_per_batch / floor, 2),
    }
    if table_dtype is not None:
        row["table_dtype"] = table_dtype
    return row


def als_iteration_cost(
    nnz: int,
    num_users: int,
    num_movies: int,
    rank: int,
    *,
    factor_bytes: int = 2,  # bf16 storage
    implicit: bool = False,
    table_dtype: str | None = None,  # gather-table quantization (ops.quant)
    gather_rows: float | None = None,  # layout-aware row count override
    sweeps: int = 1,  # subspace sweeps per half-iteration (iALS++/ALS++)
) -> IterationCost:
    """Model FLOPs + minimum HBM bytes for one full ALS(-WR / iALS) iteration.

    FLOPs:
      - Gram + RHS: every rating contributes one rank-k outer product and one
        scaled vector add on each side → 2 · nnz · k · (k+1) FLOPs per side
        (the RHS rides as column k+1 of the grouped matmul).
      - Solves: one SPD solve per entity per iteration, counted at Cholesky
        cost k³/3 + 2k² (factorization + two triangular solves).
      - iALS adds the global Gram YᵀY: 2 · (U+M) · k² per iteration.

    Bytes (minimum):
      - neighbor-factor gathers: gather_rows · bytes/row — the table dtype
        sets the bytes (``table_gather_bytes_per_row``; bf16 halves the
        f32 rows, int8+scale quarters them), and ``gather_rows`` defaults
        to 2·nnz (one row per rating per side) with layout-aware
        overrides (``bucketed_gather_rows`` counts padded cells per width
        class; ``sweeps`` > 1 multiplies — each subspace sweep re-gathers),
      - block arrays read once: neighbor idx (4 B) + rating (4 B) per rating
        per side (the mask is derivable and the segment metadata is O(E)),
      - Gram/RHS intermediates cross the matmul→solve op boundary:
        (U + M) · (k² + k) · 4 bytes written + read,
      - factor write-back: (U + M) · k · factor_bytes.
    """
    k = rank
    entities = num_users + num_movies
    gram = 2.0 * nnz * k * (k + 1) * 2  # both sides
    solve = entities * (k**3 / 3.0 + 2.0 * k**2)
    flops = gram + solve
    if implicit:
        flops += 2.0 * entities * k * k  # global YᵀY

    if gather_rows is None:
        gather_rows = 2.0 * nnz
    gather_rows = gather_rows * max(sweeps, 1)
    if table_dtype is None:
        row_bytes = float(k * factor_bytes)
    else:
        row_bytes = table_gather_bytes_per_row(k, table_dtype, factor_bytes)
    gather = gather_rows * row_bytes
    blocks = 2.0 * nnz * 8
    gram_io = entities * (k * k + k) * 4.0 * 2
    factors_out = entities * k * factor_bytes
    return IterationCost(
        model_flops=flops,
        min_hbm_bytes=gather + blocks + gram_io + factors_out,
        gather_rows=gather_rows,
        gather_bytes=gather,
    )
