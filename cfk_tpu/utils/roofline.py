"""Model-FLOP / HBM-byte accounting and MFU for the ALS iteration.

The reference has no notion of compute efficiency — its hot loop is a
per-entity EJML solve (``processors/MFeatureCalculator.java:85-99``) and its
only telemetry is wall-clock milliseconds.  On TPU the honest yardstick is
the hardware: model FLOPs per iteration over the chip's peak (MFU), and the
minimum HBM traffic over measured bandwidth (roofline).  These numbers are
printed by ``bench.py`` so every recorded benchmark carries its efficiency.

Conventions
-----------
- *Model FLOPs* count the algorithmic minimum, independent of backend: the
  Gram/RHS contractions (2 FLOPs per MAC) plus one Cholesky-cost solve per
  entity.  Implementation overhead (the pallas Gauss-Jordan's 2k³ vs
  Cholesky's k³/3, padding waste, masked lanes) deliberately does NOT count —
  MFU measures useful work extracted from the chip.
- *Min HBM bytes* count each operand's unavoidable traffic once: the random
  neighbor-factor gathers, one read of the block arrays, one write+read of
  the per-entity Gram/RHS intermediates (they cross an op boundary into the
  solve), and the factor write-back.  Fusion can only approach this from
  above; the gap between the measured iteration and ``min_bytes / bandwidth``
  is the tractable inefficiency.
"""

from __future__ import annotations

import dataclasses

# TPU v5e (v5 lite) single chip, from the public spec sheet.
V5E_PEAK_BF16_FLOPS = 197e12  # per second
V5E_HBM_BYTES_PER_S = 819e9
V5E_HBM_BYTES = 16 * 1024**3


@dataclasses.dataclass(frozen=True)
class IterationCost:
    """Per-full-iteration (both half-steps) model cost of one ALS sweep."""

    model_flops: float
    min_hbm_bytes: float

    def achieved_tflops(self, seconds: float) -> float:
        return self.model_flops / seconds / 1e12

    def mfu(self, seconds: float, peak_flops: float = V5E_PEAK_BF16_FLOPS) -> float:
        return self.model_flops / seconds / peak_flops

    def hbm_bound_s(self, bandwidth: float = V5E_HBM_BYTES_PER_S) -> float:
        """Roofline floor: the iteration can never beat this wall-clock."""
        return self.min_hbm_bytes / bandwidth


def als_iteration_cost(
    nnz: int,
    num_users: int,
    num_movies: int,
    rank: int,
    *,
    factor_bytes: int = 2,  # bf16 storage
    implicit: bool = False,
) -> IterationCost:
    """Model FLOPs + minimum HBM bytes for one full ALS(-WR / iALS) iteration.

    FLOPs:
      - Gram + RHS: every rating contributes one rank-k outer product and one
        scaled vector add on each side → 2 · nnz · k · (k+1) FLOPs per side
        (the RHS rides as column k+1 of the grouped matmul).
      - Solves: one SPD solve per entity per iteration, counted at Cholesky
        cost k³/3 + 2k² (factorization + two triangular solves).
      - iALS adds the global Gram YᵀY: 2 · (U+M) · k² per iteration.

    Bytes (minimum):
      - neighbor-factor gathers: nnz · k · factor_bytes per side,
      - block arrays read once: neighbor idx (4 B) + rating (4 B) per rating
        per side (the mask is derivable and the segment metadata is O(E)),
      - Gram/RHS intermediates cross the matmul→solve op boundary:
        (U + M) · (k² + k) · 4 bytes written + read,
      - factor write-back: (U + M) · k · factor_bytes.
    """
    k = rank
    entities = num_users + num_movies
    gram = 2.0 * nnz * k * (k + 1) * 2  # both sides
    solve = entities * (k**3 / 3.0 + 2.0 * k**2)
    flops = gram + solve
    if implicit:
        flops += 2.0 * entities * k * k  # global YᵀY

    gather = 2.0 * nnz * k * factor_bytes
    blocks = 2.0 * nnz * 8
    gram_io = entities * (k * k + k) * 4.0 * 2
    factors_out = entities * k * factor_bytes
    return IterationCost(
        model_flops=flops,
        min_hbm_bytes=gather + blocks + gram_io + factors_out,
    )
