"""Structured metrics + phase timing (compat shim; see cfk_tpu.telemetry).

Replaces the reference's observability story — raw ``System.out.println``
wall-clock stamps at phase edges (``apps/ALSAppRunner.java:25,32``,
``processors/FeatureCollector.java:47,94``) and a per-partition solve-time
accumulator printed by a 60 s punctuator
(``processors/MFeatureCalculator.java:40-45,135``) — with a typed registry:
counters, gauges, phase timers, and bounded-reservoir histograms, dumped
as one JSON line or logfmt, streamed as JSONL, or scraped as Prometheus
text.

The implementation lives in ``cfk_tpu.telemetry.metrics`` (ISSUE 14 made
the registry thread-safe — PR 12's staging-pool workers and the serve
server's commit listeners mutate it from worker threads); this module
keeps the historical import path every call site uses.
"""

from __future__ import annotations

import contextlib

from cfk_tpu.telemetry.metrics import (  # noqa: F401  (re-exports)
    Histogram,
    Metrics,
    MetricsEmitter,
    MetricsRegistry,
)


@contextlib.contextmanager
def maybe_profile(profile_dir: str | None):
    """jax.profiler trace hook: writes a TensorBoard-loadable trace when a
    directory is given, otherwise a no-op.  Pass the same directory as
    ``--trace-dir`` to line the device timeline up with the host span
    trace (``cfk_tpu.telemetry.trace``)."""
    if profile_dir is None:
        yield
        return
    import jax

    with jax.profiler.trace(profile_dir):
        yield
