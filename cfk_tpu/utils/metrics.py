"""Structured metrics + phase timing.

Replaces the reference's observability story — raw ``System.out.println``
wall-clock stamps at phase edges (``apps/ALSAppRunner.java:25,32``,
``processors/FeatureCollector.java:47,94``) and a per-partition solve-time
accumulator printed by a 60 s punctuator
(``processors/MFeatureCalculator.java:40-45,135``) — with a typed registry:
counters, gauges, and phase timers, dumped as one JSON line or logfmt.
"""

from __future__ import annotations

import contextlib
import json
import time
from collections import defaultdict


class Metrics:
    """Process-local metrics registry: counters, gauges, phase timers."""

    def __init__(self) -> None:
        self.counters: dict[str, float] = defaultdict(float)
        self.gauges: dict[str, float] = {}
        self.phases: dict[str, float] = defaultdict(float)
        self.notes: dict[str, str] = {}

    def incr(self, name: str, value: float = 1.0) -> None:
        self.counters[name] += value

    def gauge(self, name: str, value: float) -> None:
        self.gauges[name] = value

    def note(self, name: str, text: str) -> None:
        """Free-text diagnostic (health-sentinel trip reasons, escalation
        decisions, degradation notices) — the report channel the resilience
        loop writes so a degraded run's output says *why*."""
        self.notes[name] = text

    @contextlib.contextmanager
    def phase(self, name: str):
        """Accumulate wall seconds spent inside the block under ``name``."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.phases[name] += time.perf_counter() - t0

    def to_dict(self) -> dict:
        d = {
            "counters": dict(self.counters),
            "gauges": dict(self.gauges),
            "phase_seconds": {k: round(v, 6) for k, v in self.phases.items()},
        }
        if self.notes:
            d["notes"] = dict(self.notes)
        return d

    def json_line(self) -> str:
        return json.dumps(self.to_dict(), sort_keys=True)

    def logfmt(self) -> str:
        parts = []
        for k, v in sorted(self.counters.items()):
            parts.append(f"ctr.{k}={v:g}")
        for k, v in sorted(self.gauges.items()):
            parts.append(f"g.{k}={v:g}")
        for k, v in sorted(self.phases.items()):
            parts.append(f"t.{k}={v:.3f}s")
        for k, v in sorted(self.notes.items()):
            parts.append(f"n.{k}={v!r}")
        return " ".join(parts)


@contextlib.contextmanager
def maybe_profile(profile_dir: str | None):
    """jax.profiler trace hook: writes a TensorBoard-loadable trace when a
    directory is given, otherwise a no-op."""
    if profile_dir is None:
        yield
        return
    import jax

    with jax.profiler.trace(profile_dir):
        yield
