"""JAX version compatibility shims.

The framework targets the current JAX API (top-level ``jax.shard_map`` with
``check_vma``, ``jax.typeof`` + varying-mesh-axes types, ``lax.pvary`` /
``lax.pcast``), but must also run on older installs (0.4.x) where none of
those exist: there the vma system is absent entirely, so the correct
degradation is "no vma marking at all" — collectives still place correctly,
we just lose the static checker.  Every call site goes through this module
instead of sniffing ``hasattr`` locally, so the support matrix lives in one
file.
"""

from __future__ import annotations

import functools
import inspect

import jax
from jax import lax


@functools.lru_cache(maxsize=1)
def _shard_map_fn():
    try:  # jax >= 0.6 exposes shard_map at top level
        return jax.shard_map
    except AttributeError:  # pragma: no cover - version-dependent
        from jax.experimental.shard_map import shard_map as sm

        return sm


@functools.lru_cache(maxsize=1)
def _shard_map_check_kwarg() -> str | None:
    """Name of shard_map's static-checker toggle on this JAX.

    ``check_vma`` on current JAX, ``check_rep`` on 0.4.x-era shard_map,
    None if the signature is opaque (pass nothing and take the default).
    """
    try:
        params = inspect.signature(_shard_map_fn()).parameters
    except (TypeError, ValueError):  # pragma: no cover - exotic builds
        return None
    for name in ("check_vma", "check_rep"):
        if name in params:
            return name
    return None  # pragma: no cover - exotic builds


def shard_map(f, *, mesh, in_specs, out_specs, check=True):
    """``jax.shard_map`` across JAX versions.

    ``check`` maps onto whichever static replication/vma checker this JAX
    has (``check_vma`` today, ``check_rep`` historically).  On 0.4.x the
    rep checker predates several collectives/ops we emit inside the ring
    bodies (``optimization_barrier`` has no rep rule there), so ``check``
    is only honored when True is known to work — callers that must disable
    it still can.
    """
    kw = {}
    name = _shard_map_check_kwarg()
    if name is not None:
        kw[name] = check if name == "check_vma" else False
    return _shard_map_fn()(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw
    )


def has_vma_system() -> bool:
    """True when this JAX has the typed varying-mesh-axes system (and the
    pallas toolchain that goes with it).  Old installs (0.4.x) predate it;
    their pallas HLO interpreter is also orders of magnitude slower on the
    grouped-Gram kernels, so callers use this to prefer the XLA emulation
    there."""
    return hasattr(jax, "typeof")


def typeof_vma(x):
    """``jax.typeof(x).vma`` where the vma system exists, else None."""
    typeof = getattr(jax, "typeof", None)
    if typeof is None:
        return None
    try:
        return getattr(typeof(x), "vma", None)
    except TypeError:  # pragma: no cover - non-typeable values
        return None


def to_varying(x, axis):
    """Mark x device-varying over ``axis``.

    ``pcast`` on jax >= 0.9, ``pvary`` before; identity on installs that
    predate the vma system (nothing to mark — carries typecheck unmarked).
    """
    if hasattr(lax, "pcast"):
        return lax.pcast(x, axis, to="varying")
    if hasattr(lax, "pvary"):
        return lax.pvary(x, axis)
    return x


def emulate_in_kernel_gather(table, nb, wt, ct):
    """XLA twin of the gather-fused Gram kernels' in-kernel row fetch —
    the interpret/old-jax route, so CPU CI exercises the same code shape
    the Mosaic DMA gather runs.

    The Mosaic kernels (``ops.pallas.gram_kernel`` ``*_gather_pallas``)
    keep the RAW fixed table in HBM/ANY memory, DMA each tile's indexed
    rows into VMEM (indices clamped to the last real row), and apply the
    per-entry premultiply ``wt`` in-register — ``wt`` is the 0/1 validity
    mask for unit-weight callers (which is what realizes the zero-appended
    padding row without materializing it) or √aw·mask for the weighted
    (iALS) stream.  This twin runs the numerically identical ops the
    XLA-gather path runs: append the zero row, gather, cast to the
    compute dtype, multiply — so fused-gather and XLA-gather factors are
    BIT-IDENTICAL on this route (``tests/test_in_kernel_gather.py`` pins
    it).  Index convention: ``nb == table.shape[0]`` is the virtual zero
    row; larger indices are invalid.
    """
    import jax.numpy as jnp

    k = table.shape[-1]
    zrow = jnp.zeros((1, k), table.dtype)
    try:  # mark the zero row varying like the table under shard_map
        vma = jax.typeof(table).vma
    except (AttributeError, TypeError):
        vma = None
    if vma:
        zrow = to_varying(zrow, tuple(vma))
    fz = jnp.concatenate([table, zrow])
    g = fz[nb].astype(ct)
    if wt is not None:
        g = g * wt.astype(ct)[:, None]
    return g


def emulate_topk_scores(u, table, scale, seen_tiles, *, k_top, num_movies,
                        tile_m, row_offset=0):
    """XLA twin of the serving score+top-K kernel — the interpret/old-jax
    route, so CPU CI exercises the same code shape the Mosaic kernel runs.

    Scans the SAME per-tile fold the kernel body runs
    (``serving.topk_kernel._score_tile_fold`` — one shared function, the
    same twin discipline as the Gram kernels) over the same movie tiles in
    the same order, carrying the same [B, K] selection — so kernel and
    twin are BIT-IDENTICAL on this route (``tests/test_serving.py`` pins
    it).  Crucially the scan's per-step block is [B, tile_m]: no
    [B, num_movies] score matrix is ever materialized here either (the
    emulation-path memory check in the tests compiles this and bounds its
    temp memory below B·M·4 bytes).
    """
    import jax.numpy as jnp

    from cfk_tpu.serving.topk_kernel import _score_tile_fold

    b = u.shape[0]
    m_pad = table.shape[0]
    nt = m_pad // tile_m
    tbl = table.reshape(nt, tile_m, -1)
    sc = (None if scale is None
          else scale.reshape(nt, tile_m, 1).astype(jnp.float32))
    carry0 = (
        jnp.full((b, k_top), -jnp.inf, jnp.float32),
        jnp.full((b, k_top), -1, jnp.int32),
    )

    off = jnp.asarray(row_offset, jnp.int32)

    def step(carry, i):
        idx = lambda a: lax.dynamic_index_in_dim(a, i, 0, keepdims=False)
        v, ids = _score_tile_fold(
            carry[0], carry[1], u, idx(tbl),
            None if sc is None else idx(sc),
            None if seen_tiles is None else idx(seen_tiles),
            off + i * tile_m,
            num_movies=num_movies, k_top=k_top,
        )
        return (v, ids), None

    (vals, ids), _ = lax.scan(step, carry0, jnp.arange(nt, dtype=jnp.int32))
    return vals, ids


def emulate_fused_gram_solve(a, b, reg, *, reg_mode, lam, lseg):
    """XLA twin of the fused Gram+solve epilogue — the interpret/old-jax
    route, so CPU CI exercises the same code shape the Mosaic kernel runs.

    Given the chunk's emulated (A [S, k, k], b [S, k]) normal-equation
    sums, return exactly what ``gram_solve_tiles_pallas`` returns:

        (x [S, k], carry_a [k, k], carry_b [k])

    — the carry row extracted RAW (pre-ridge) at ``lseg``, and the whole
    batch regularized + solved by the same fused reg+solve elimination the
    kernel's epilogue runs (``gauss_solve_reg_pallas``, which interprets
    off-TPU).  Because the split chunk path computes the identical
    segment-sum (A, b) and calls the identical reg+solve on it, fused and
    split factors are BIT-IDENTICAL on this route — the equivalence the
    fused/split regression tests pin.
    """
    from cfk_tpu.ops.pallas.solve_kernel import gauss_solve_reg_pallas

    x = gauss_solve_reg_pallas(a, b, reg, reg_mode=reg_mode, lam=lam)
    ca = lax.dynamic_index_in_dim(a, lseg, 0, keepdims=False)
    cb = lax.dynamic_index_in_dim(b, lseg, 0, keepdims=False)
    return x, ca, cb
