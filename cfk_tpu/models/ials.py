"""Implicit-feedback ALS (iALS, Hu-Koren-Volinsky 2008) — second model family.

Same block-partitioned layout as the explicit model, different normal
equations: per entity A = YᵀY + Σ_obs (c−1)·f fᵀ + λI with confidence
c = 1 + α·r, preferences 1 at observed cells.  The global Gram YᵀY is
computed once per half-iteration — locally per shard and ``psum``'d over the
mesh (a [k,k] collective, the cheapest message in the whole framework).

This is the BASELINE.md "MovieLens-25M implicit, rank 128" family.  The
reference has no implicit model; capability parity plus one — but the
transport/ingest/checkpoint plumbing is shared with the explicit path.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from cfk_tpu.config import ALSConfig
from cfk_tpu.data.blocks import BucketedBlocks, Dataset, SegmentBlocks, TiledBlocks
from cfk_tpu.models.als import (
    ALSModel,
    _blocks_to_device,
    _bucketed_device_setup,
    _segment_device_setup,
    _tiled_device_setup,
)
from cfk_tpu.ops.solve import (
    ials_half_step,
    ials_half_step_bucketed,
    ials_half_step_segment,
    init_factors,
    init_factors_stats,
)
from cfk_tpu.parallel.mesh import AXIS, shard_rows, to_host


@dataclasses.dataclass(frozen=True)
class IALSConfig(ALSConfig):
    """iALS hyper-parameters; ``lam`` here is plain-λI regularization.

    ``algorithm="ials++"`` switches the per-entity solve from the full k×k
    normal equations to subspace block coordinate descent (Rendle et al.,
    PAPERS.md): ``sweeps`` passes over ``rank/block_size`` coordinate blocks
    per half-iteration, warm-started from the previous epoch's factors.
    With ``block_size == rank`` one sweep equals the full solve exactly.
    """

    alpha: float = 40.0
    lam: float = 0.1

    def _valid_algorithms(self) -> tuple[str, ...]:
        return ("als", "ials++")

    def _check_host_window(self) -> None:
        """Implicit out-of-core (ISSUE 19): the windowed driver streams
        the BUCKETED width-class layout (the global-Gram reduction plus
        per-class windows), for both the full implicit solve and the
        iALS++ subspace sweeps — the tiled stream-mode layout is the
        explicit family's format."""
        if self.layout != "bucketed":
            raise ValueError(
                "offload_tier='host_window' for the implicit family "
                "streams the bucketed width-class layout; layout="
                f"{self.layout!r}"
            )

    def __post_init__(self) -> None:
        super().__post_init__()
        if self.alpha <= 0:
            raise ValueError(f"alpha must be > 0, got {self.alpha}")
        if self.exchange != "all_gather":
            raise ValueError(
                "iALS currently supports exchange='all_gather' only (the "
                "global-Gram trick needs the full fixed side per shard)"
            )


def _ials_half(fixed, blk, *, lam, alpha, solver, gram=None, chunks=None,
               entities=None, x_prev=None, algorithm="als", block_size=32,
               sweeps=1, overlap=None, fused_epilogue=None,
               in_kernel_gather=None, reg_solve_algo=None, table_dtype=None):
    """Dispatch on block layout (tuple = buckets, dict with segment ids =
    flat segment run, other dict = padded rectangle).  ``algorithm="ials++"``
    runs warm-started subspace sweeps from ``x_prev`` instead of full
    solves (padded/bucketed layouts)."""
    if algorithm == "ials++":
        from cfk_tpu.ops.subspace import (
            ials_pp_half_step,
            ials_pp_half_step_bucketed,
        )

        pp_kw = dict(
            gram=gram, block_size=block_size, sweeps=sweeps, solver=solver,
            in_kernel_gather=in_kernel_gather,
            fused_epilogue=fused_epilogue, reg_solve_algo=reg_solve_algo,
            table_dtype=table_dtype,
        )
        if isinstance(blk, tuple):
            return ials_pp_half_step_bucketed(
                fixed, x_prev, blk, chunks, entities, lam, alpha,
                overlap=overlap, **pp_kw,
            )
        return ials_pp_half_step(
            fixed, x_prev, blk["neighbor_idx"], blk["rating"], blk["mask"],
            lam, alpha, **pp_kw,
        )
    if isinstance(blk, tuple):
        return ials_half_step_bucketed(
            fixed, blk, chunks, entities, lam, alpha, gram=gram,
            solver=solver, overlap=overlap, reg_solve_algo=reg_solve_algo,
            fused_epilogue=fused_epilogue, in_kernel_gather=in_kernel_gather,
            table_dtype=table_dtype,
        )
    if "weight" in blk or "tile_meta" in blk:  # tiled layout
        from cfk_tpu.ops.tiled import ials_tiled_half_step

        # dstream blocks run the weighted dense path (gw premultiply)
        # when staged with their weighted channels; unweighted staging
        # raises a rebuild/steering error inside.
        return ials_tiled_half_step(
            fixed, blk, chunks, entities, lam, alpha, gram=gram,
            solver=solver, overlap=overlap, fused_epilogue=fused_epilogue,
            in_kernel_gather=in_kernel_gather, reg_solve_algo=reg_solve_algo,
            table_dtype=table_dtype,
        )
    from cfk_tpu.ops import quant

    fixed = quant.gather_operand_view(fixed, table_dtype)
    if "seg_rel" in blk:
        return ials_half_step_segment(
            fixed, blk["neighbor_idx"], blk["rating"], blk["mask"],
            blk["seg_rel"], blk["chunk_entity"], blk["group_sizes"],
            blk["carry_in"], blk["last_seg"], entities, lam, alpha,
            gram=gram, statics=chunks, solver=solver,
            reg_solve_algo=reg_solve_algo,
        )
    return ials_half_step(
        fixed, blk["neighbor_idx"], blk["rating"], blk["mask"], lam, alpha,
        gram=gram, solver=solver, reg_solve_algo=reg_solve_algo,
    )


@functools.partial(
    jax.jit,
    static_argnames=(
        "rank", "num_iterations", "lam", "alpha", "dtype", "solver",
        "algorithm", "block_size", "sweeps", "overlap", "fused_epilogue",
        "in_kernel_gather", "reg_solve_algo", "table_dtype",
        "health_every", "health_norm_limit",
        "m_chunks", "u_chunks", "m_entities", "u_entities",
    ),
)
def _train_loop(
    key, movie_blocks, user_blocks, u_stats=None, *, rank, num_iterations, lam,
    alpha, dtype, solver="cholesky", algorithm="als", block_size=32, sweeps=1,
    overlap=None, fused_epilogue=None, in_kernel_gather=None,
    reg_solve_algo=None, table_dtype=None,
    health_every=None, health_norm_limit=0.0,
    m_chunks=None, u_chunks=None, m_entities=None, u_entities=None,
):
    dt = jnp.dtype(dtype)
    if u_stats is not None:  # bucketed layout
        u = init_factors_stats(key, u_stats["rating_sum"], u_stats["count"], rank)
        m_rows = m_entities
    else:
        u = init_factors(
            key, user_blocks["rating"], user_blocks["mask"], user_blocks["count"], rank
        )
        m_rows = movie_blocks["rating"].shape[0]
    u = u.astype(dt)
    m0 = jnp.zeros((m_rows, rank), dtype=dt)

    def step(u, m_prev):
        return _ials_iteration_body(
            u, m_prev, movie_blocks, user_blocks,
            lam=lam, alpha=alpha, dt=dt, solver=solver,
            algorithm=algorithm, block_size=block_size, sweeps=sweeps,
            overlap=overlap, fused_epilogue=fused_epilogue,
            in_kernel_gather=in_kernel_gather,
            reg_solve_algo=reg_solve_algo, table_dtype=table_dtype,
            m_chunks=m_chunks, u_chunks=u_chunks,
            m_entities=m_entities, u_entities=u_entities,
        )

    if health_every is None:
        return lax.fori_loop(
            0, num_iterations, lambda i, c: step(*c), (u, m0)
        )

    # In-carry health word, as in als._train_loop (see there).
    from cfk_tpu.resilience import sentinel

    def probed(i, carry):
        u, m_prev, hw = carry
        u2, m2 = step(u, m_prev)
        hw = sentinel.fold_probe(
            hw, i, u2, m2, every=health_every,
            norm_limit=health_norm_limit, total=num_iterations,
        )
        return u2, m2, hw

    return lax.fori_loop(
        0, num_iterations, probed, (u, m0, sentinel.carry_init())
    )


def _ials_iteration_body(u, m_prev, movie_blocks, user_blocks, *, lam, alpha,
                         dt, solver, algorithm, block_size, sweeps,
                         overlap=None, fused_epilogue=None,
                         in_kernel_gather=None, reg_solve_algo=None,
                         table_dtype=None,
                         m_chunks=None, u_chunks=None,
                         m_entities=None, u_entities=None):
    """One full iALS iteration (movies from users, then users from movies) —
    the single source of the per-iteration math for the fused-loop and
    checkpointed paths (mirrors ``als._iteration_body``)."""
    alg = dict(algorithm=algorithm, block_size=block_size, sweeps=sweeps,
               overlap=overlap, fused_epilogue=fused_epilogue,
               in_kernel_gather=in_kernel_gather,
               reg_solve_algo=reg_solve_algo, table_dtype=table_dtype)
    m = _ials_half(
        u, movie_blocks, lam=lam, alpha=alpha, solver=solver,
        chunks=m_chunks, entities=m_entities, x_prev=m_prev, **alg,
    ).astype(dt)
    u_new = _ials_half(
        m, user_blocks, lam=lam, alpha=alpha, solver=solver,
        chunks=u_chunks, entities=u_entities, x_prev=u, **alg,
    ).astype(dt)
    return (u_new, m)


@functools.partial(
    jax.jit,
    static_argnames=(
        "lam", "alpha", "dtype", "solver", "algorithm", "block_size",
        "sweeps", "overlap", "fused_epilogue", "in_kernel_gather",
        "reg_solve_algo", "table_dtype", "m_chunks", "u_chunks",
        "m_entities", "u_entities",
    ),
    donate_argnums=(0, 1),
)
def _one_iteration(
    u, m_prev, movie_blocks, user_blocks, *, lam, alpha, dtype,
    solver="cholesky", algorithm="als", block_size=32, sweeps=1,
    overlap=None, fused_epilogue=None, in_kernel_gather=None,
    reg_solve_algo=None, table_dtype=None,
    m_chunks=None, u_chunks=None, m_entities=None, u_entities=None,
):
    return _ials_iteration_body(
        u, m_prev, movie_blocks, user_blocks,
        lam=lam, alpha=alpha, dt=jnp.dtype(dtype), solver=solver,
        algorithm=algorithm, block_size=block_size, sweeps=sweeps,
        overlap=overlap, fused_epilogue=fused_epilogue,
        in_kernel_gather=in_kernel_gather, reg_solve_algo=reg_solve_algo,
        table_dtype=table_dtype,
        m_chunks=m_chunks, u_chunks=u_chunks,
        m_entities=m_entities, u_entities=u_entities,
    )


def _check_nonnegative_strengths(dataset: Dataset) -> None:
    """iALS semantics require interaction strengths ≥ 0 (confidence
    c = 1 + α·r must be ≥ 1, and the sqrt-reparameterized weight stream
    takes √(α·r) — ``ops.tiled.ials_tiled_half_step``).  A negative rating
    would silently train an inconsistent normal equation, so steer loudly
    at trainer entry (one host-side pass over the ratings, ~0.1 s at
    100M)."""
    import numpy as np

    r = dataset.coo_dense.rating
    if not r.size:
        return
    mn = float(np.min(r))  # once — the second np.min re-scanned 100M rows
    if mn < 0:
        raise ValueError(
            "iALS requires non-negative interaction strengths "
            f"(min rating {mn}); rescale or clamp the data "
            "(see cfk_tpu.models.ials docstring)"
        )


def train_ials(
    dataset: Dataset,
    config: IALSConfig,
    *,
    checkpoint_manager=None,
    checkpoint_every: int = 1,
    metrics=None,
    fault_injector=None,
    preemption_guard=None,
    watchdog=None,
) -> ALSModel:
    """Single-device implicit ALS. Ratings in the dataset are interaction
    strengths (counts, play-time, explicit stars — anything ≥ 0).

    Checkpoint semantics match ``als.train_als``: without a manager the loop
    runs as one fused ``fori_loop``; with one, iterations step from Python,
    factors are journaled every ``checkpoint_every`` iterations, and training
    resumes from the latest committed step (the reference's ``setup.sh:18-21``
    journal applies to every model, so ours does too).  Health sentinel /
    recovery / ``fault_injector`` / ``preemption_guard`` / ``watchdog``
    semantics also match ``train_als``."""
    from cfk_tpu.resilience.loop import validate_cadence
    from cfk_tpu.resilience.sentinel import health_from_config
    from cfk_tpu.utils.metrics import Metrics

    from cfk_tpu.plan import plan_for_config

    _check_nonnegative_strengths(dataset)
    health = health_from_config(config)
    validate_cadence(checkpoint_every, health)
    metrics = metrics if metrics is not None else Metrics()
    # Execution plan + provenance (cfk_tpu.plan) — the same seam as
    # als.train_als: pinned config knobs pass through bit-identically,
    # deferred knobs are priced, provenance rides metrics + manifests.
    exec_plan, plan_prov = plan_for_config(
        config,
        num_users=dataset.user_map.num_entities,
        num_movies=dataset.movie_map.num_entities,
        nnz=max(int(dataset.movie_blocks.count.sum()), 1),
        implicit=True,
    )
    knobs = exec_plan.half_step_kwargs(config)
    metrics.note("plan", plan_prov.summary())
    if exec_plan.offload_tier == "host_window":
        # Out-of-core implicit tier (ISSUE 19): the memory-budget
        # predicate said the resident tables cannot fit (or the config
        # pinned the tier), so training runs through the bucketed
        # windowed driver — global-Gram reduction + width-class windows,
        # bit-exact vs the resident bucketed path on the same blocks.
        unsupported = [
            name for name, v in (
                ("checkpoint_manager", checkpoint_manager),
                ("fault_injector", fault_injector),
                ("preemption_guard", preemption_guard),
                ("watchdog", watchdog),
            ) if v is not None
        ]
        if unsupported:
            raise NotImplementedError(
                f"offload_tier='host_window' does not support "
                f"{unsupported} yet — the windowed driver keeps factors "
                "in host stores (see cfk_tpu/offload/windowed.py; "
                "window-level fault injection uses its window_faults=)"
            )
        from cfk_tpu.offload.windowed import train_ials_host_window

        # Same knob-threading seam as als.train_als's host_window exit:
        # every knob the windowed driver reads off the config is either
        # pinned there or deferred with the config's own sentinel — the
        # recorded provenance cannot diverge from execution.
        return train_ials_host_window(
            dataset, config, metrics=metrics, plan_provenance=plan_prov,
        )
    key = jax.random.PRNGKey(config.seed)
    if isinstance(dataset.movie_blocks, BucketedBlocks):
        mblocks, ublocks, u_stats, layout_kw = _bucketed_device_setup(dataset)
    elif isinstance(dataset.movie_blocks, SegmentBlocks):
        mblocks, ublocks, u_stats, layout_kw = _segment_device_setup(dataset)
    elif isinstance(dataset.movie_blocks, TiledBlocks):
        mblocks, ublocks, u_stats, layout_kw = _tiled_device_setup(
            dataset, weighted=dataset.movie_blocks.mode == "dstream"
            or dataset.user_blocks.mode == "dstream"
        )
    else:
        mblocks = _blocks_to_device(dataset.movie_blocks)
        ublocks = _blocks_to_device(dataset.user_blocks)
        u_stats = None
        layout_kw = {}
    stepped = (checkpoint_manager is not None or fault_injector is not None
               or preemption_guard is not None or watchdog is not None)
    if not stepped:
        from cfk_tpu.telemetry import record_event, span

        train_s_before = metrics.phases.get("train", 0.0)
        # One span per fused fori_loop — see models/als.py (per-iteration
        # host spans live on the stepped path only).
        with metrics.phase("train"), \
                span("train/fused_loop", iters=config.num_iterations):
            out = _train_loop(
                key,
                mblocks,
                ublocks,
                u_stats,
                rank=config.rank,
                num_iterations=config.num_iterations,
                lam=config.lam,
                alpha=config.alpha,
                dtype=config.dtype,
                solver=knobs["solver"],
                algorithm=config.algorithm,
                block_size=config.block_size,
                sweeps=config.sweeps,
                overlap=knobs["overlap"],
                fused_epilogue=knobs["fused_epilogue"],
                in_kernel_gather=knobs["in_kernel_gather"],
                reg_solve_algo=knobs["reg_solve_algo"],
                table_dtype=knobs["table_dtype"],
                health_every=None if health is None else health.every,
                health_norm_limit=(
                    0.0 if health is None else health.norm_limit
                ),
                **layout_kw,
            )
            u, m = out[0], out[1]
            u.block_until_ready()
        report = None
        if health is not None:
            from cfk_tpu.resilience.sentinel import report_from_carry

            report = report_from_carry(out[2], u, m)
        if report is None or report.healthy:
            metrics.incr("iterations", config.num_iterations)
            record_event("train", "fused_loop_done",
                         iters=config.num_iterations)
        else:
            import warnings

            # The fused attempt is discarded and replayed below, so keep
            # its accounting out of the headline counters: its wall time
            # moves to "train_discarded" and its iterations are not
            # counted (the stepped replay re-detects this divergence and
            # does the health_trips / rollback accounting exactly once).
            discarded = metrics.phases.get("train", 0.0) - train_s_before
            metrics.phases["train"] = train_s_before
            metrics.phases["train_discarded"] += discarded
            metrics.note("fused_loop_trip", report.summary())
            warnings.warn(
                f"health sentinel tripped in the fused training loop "
                f"({report.summary()}); replaying through the "
                "resilient stepped loop"
            )
            stepped = True
    if stepped:
        dt = jnp.dtype(config.dtype)

        def init_fn():
            if u_stats is not None:
                u = init_factors_stats(
                    key, u_stats["rating_sum"], u_stats["count"], config.rank
                ).astype(dt)
            else:
                u = init_factors(
                    key, ublocks["rating"], ublocks["mask"], ublocks["count"],
                    config.rank,
                ).astype(dt)
            m = jnp.zeros((dataset.movie_blocks.padded_entities, config.rank), dt)
            return u, m

        def make_step(ov):
            def step_fn(u, m):
                return _one_iteration(
                    u, m, mblocks, ublocks,
                    lam=ov.lam, alpha=config.alpha, dtype=config.dtype,
                    solver=knobs["solver"], algorithm=config.algorithm,
                    block_size=config.block_size, sweeps=config.sweeps,
                    overlap=knobs["overlap"],
                    fused_epilogue=ov.fused_epilogue,
                    in_kernel_gather=knobs["in_kernel_gather"],
                    # GJ escalation rung as a threaded jit-static (see
                    # als.train_als make_step).
                    reg_solve_algo=(ov.reg_solve_algo
                                    or knobs["reg_solve_algo"]),
                    table_dtype=knobs["table_dtype"],
                    **layout_kw,
                )

            return step_fn

        from cfk_tpu.resilience.loop import resilient_train_loop
        from cfk_tpu.resilience.policy import Overrides, policy_from_config

        u, m = resilient_train_loop(
            checkpoint_manager,
            model="ials",
            rank=config.rank,
            num_iterations=config.num_iterations,
            u_shape=(dataset.user_blocks.padded_entities, config.rank),
            m_shape=(dataset.movie_blocks.padded_entities, config.rank),
            dtype=dt,
            init_fn=init_fn,
            make_step=make_step,
            base_overrides=Overrides(
                lam=config.lam, fused_epilogue=knobs["fused_epilogue"]
            ),
            metrics=metrics,
            checkpoint_every=checkpoint_every,
            health=health,
            policy=policy_from_config(config),
            fault_injector=fault_injector,
            preemption_guard=preemption_guard,
            watchdog=watchdog,
            plan_provenance=plan_prov,
        )
    return ALSModel(
        user_factors=u,
        movie_factors=m,
        num_users=dataset.user_map.num_entities,
        num_movies=dataset.movie_map.num_entities,
    )


def make_ials_training_step(
    mesh: Mesh,
    config: IALSConfig,
    *,
    m_chunks=None,
    u_chunks=None,
    m_local=None,
    u_local=None,
    mspecs=None,
    uspecs=None,
    segment=False,
    tiled=False,
    m_ring=False,
    u_ring=False,
):
    """Jittable one-full-iteration SPMD step for iALS.

    Per half-iteration: psum the local [k,k] Grams, all_gather the fixed
    factors, solve local entities (per width bucket when ``m_chunks`` given,
    or by segment_sum over the flat local run when ``segment=True``).
    ``config.algorithm="ials++"`` swaps the full solves for warm-started
    subspace sweeps — entities are row-sharded and the sweep is per-entity,
    so the only additional data it needs is the side's own previous local
    factors (no extra collectives).
    """
    from cfk_tpu.parallel.spmd import gathered_half, wrap_step

    if m_ring or u_ring:
        raise ValueError(
            "iALS needs the full fixed side per shard (global-Gram trick): "
            "ring-built tiled blocks are unusable — rebuild with "
            "Dataset.from_coo(..., ring=False)"
        )
    if config.algorithm == "ials++":
        from cfk_tpu.ops.subspace import (
            ials_pp_half_step,
            ials_pp_half_step_bucketed,
        )

        alg = dict(block_size=config.block_size, sweeps=config.sweeps,
                   solver=config.solver,
                   in_kernel_gather=config.in_kernel_gather,
                   fused_epilogue=config.fused_epilogue,
                   reg_solve_algo=config.reg_solve_algo,
                   table_dtype=config.table_dtype)

        if m_chunks is not None:  # bucketed layout

            def pp_bkt(chunks, local):
                def solve(fixed_full, prev_local, blk, gram):
                    return ials_pp_half_step_bucketed(
                        fixed_full, prev_local, blk, chunks, local,
                        config.lam, config.alpha, gram=gram,
                        overlap=config.overlap, **alg,
                    )

                return solve

            return wrap_step(
                mesh, config,
                gathered_half(pp_bkt(m_chunks, m_local), with_gram=True,
                              with_prev=True,
                              table_dtype=config.table_dtype),
                gathered_half(pp_bkt(u_chunks, u_local), with_gram=True,
                              with_prev=True,
                              table_dtype=config.table_dtype),
                mspecs, uspecs, carry_prev=True,
            )

        def pp_padded(fixed_full, prev_local, blk, gram):
            return ials_pp_half_step(
                fixed_full, prev_local, blk["neighbor"], blk["rating"],
                blk["mask"], config.lam, config.alpha, gram=gram, **alg,
            )

        spec = {
            "neighbor": P(AXIS, None),
            "rating": P(AXIS, None),
            "mask": P(AXIS, None),
            "count": P(AXIS),
        }
        half = gathered_half(pp_padded, with_gram=True, with_prev=True,
                             table_dtype=config.table_dtype)
        return wrap_step(mesh, config, half, half, spec, spec,
                         carry_prev=True)

    if tiled:  # tile-padded layout

        from cfk_tpu.ops.tiled import ials_tiled_half_step

        def tl_solve(chunks, local):
            def solve(fixed_full, blk, gram):
                return ials_tiled_half_step(
                    fixed_full, blk, chunks, local, config.lam, config.alpha,
                    gram=gram, solver=config.solver, overlap=config.overlap,
                    fused_epilogue=config.fused_epilogue,
                    in_kernel_gather=config.in_kernel_gather,
                    reg_solve_algo=config.reg_solve_algo,
                    table_dtype=config.table_dtype,
                )

            return solve

        return wrap_step(
            mesh, config,
            gathered_half(tl_solve(m_chunks, m_local), with_gram=True,
                          table_dtype=config.table_dtype),
            gathered_half(tl_solve(u_chunks, u_local), with_gram=True,
                          table_dtype=config.table_dtype),
            mspecs, uspecs,
        )

    if segment:  # flat segment layout

        def seg_solve(statics, local):
            def solve(fixed_full, blk, gram):
                return ials_half_step_segment(
                    fixed_full, blk["neighbor"], blk["rating"], blk["mask"],
                    blk["seg"], blk["entity"], blk["gsizes"], blk["cin"],
                    blk["lseg"], local, config.lam, config.alpha,
                    gram=gram, statics=statics, solver=config.solver,
                    reg_solve_algo=config.reg_solve_algo,
                )

            return solve

        return wrap_step(
            mesh, config,
            gathered_half(seg_solve(m_chunks, m_local), with_gram=True,
                          table_dtype=config.table_dtype),
            gathered_half(seg_solve(u_chunks, u_local), with_gram=True,
                          table_dtype=config.table_dtype),
            mspecs, uspecs,
        )

    if m_chunks is not None:  # bucketed layout

        def bkt_solve(chunks, local):
            def solve(fixed_full, blk, gram):
                return ials_half_step_bucketed(
                    fixed_full, blk, chunks, local, config.lam, config.alpha,
                    gram=gram, solver=config.solver, overlap=config.overlap,
                    reg_solve_algo=config.reg_solve_algo,
                    fused_epilogue=config.fused_epilogue,
                    in_kernel_gather=config.in_kernel_gather,
                    table_dtype=config.table_dtype,
                )

            return solve

        return wrap_step(
            mesh, config,
            gathered_half(bkt_solve(m_chunks, m_local), with_gram=True,
                          table_dtype=config.table_dtype),
            gathered_half(bkt_solve(u_chunks, u_local), with_gram=True,
                          table_dtype=config.table_dtype),
            mspecs, uspecs,
        )

    def padded_solve(fixed_full, blk, gram):
        return ials_half_step(
            fixed_full, blk["neighbor"], blk["rating"], blk["mask"],
            config.lam, config.alpha, gram=gram, solver=config.solver,
            reg_solve_algo=config.reg_solve_algo,
        )

    spec = {
        "neighbor": P(AXIS, None),
        "rating": P(AXIS, None),
        "mask": P(AXIS, None),
        "count": P(AXIS),
    }
    half = gathered_half(padded_solve, with_gram=True,
                         table_dtype=config.table_dtype)
    return wrap_step(mesh, config, half, half, spec, spec)


def train_ials_sharded(
    dataset: Dataset,
    config: IALSConfig,
    mesh: Mesh,
    *,
    checkpoint_manager=None,
    checkpoint_every: int = 1,
    metrics=None,
    fault_injector=None,
    preemption_guard=None,
    watchdog=None,
) -> ALSModel:
    """Multi-device iALS over a 1-D mesh, with optional checkpoint/resume.

    Health sentinel / rollback+escalation / ``fault_injector`` /
    ``preemption_guard`` / ``watchdog`` semantics match
    ``train_als_sharded`` (iALS is all_gather-only, so the probe is the
    step-level factor word — there is no ring carry to instrument)."""
    from cfk_tpu.utils.metrics import Metrics

    from cfk_tpu.config import apply_overlap_xla_flags
    from cfk_tpu.resilience.loop import validate_cadence
    from cfk_tpu.resilience.sentinel import health_from_config

    from cfk_tpu.plan import plan_for_config

    _check_nonnegative_strengths(dataset)
    health = health_from_config(config)
    validate_cadence(checkpoint_every, health)
    apply_overlap_xla_flags(config)
    metrics = metrics if metrics is not None else Metrics()
    from cfk_tpu.parallel.spmd import validate_sharded_dataset
    from cfk_tpu.transport.checkpoint import resume_state_synced

    validate_sharded_dataset(dataset, config, mesh)
    exec_plan, plan_prov = plan_for_config(
        config,
        num_users=dataset.user_map.num_entities,
        num_movies=dataset.movie_map.num_entities,
        nnz=max(int(dataset.movie_blocks.count.sum()), 1),
        implicit=True,
    )
    metrics.note("plan", plan_prov.summary())
    from cfk_tpu.parallel.spmd import _config_under_plan

    # Same seam as train_als_sharded: the sharded step builder reads its
    # knobs off the config, so execute the plan by writing its
    # half_step_kwargs back over the knob fields (identity for
    # pinned/default configs).
    config = _config_under_plan(config, exec_plan)

    def to_tree(blocks):
        return {
            "neighbor": blocks.neighbor_idx,
            "rating": blocks.rating,
            "mask": blocks.mask,
            "count": blocks.count,
        }

    from cfk_tpu.parallel.spmd import gathered_layout_trees, tree_specs

    gathered = gathered_layout_trees(
        dataset, config,
        weighted=isinstance(dataset.movie_blocks, TiledBlocks)
        and "dstream" in (dataset.movie_blocks.mode,
                          dataset.user_blocks.mode),
    )
    stats_init = gathered is not None  # bucketed/segment: init from stats
    step_kw = {}
    if gathered is not None:
        mtree, utree, step_kw = gathered
        step_kw.update(mspecs=tree_specs(mtree), uspecs=tree_specs(utree))
        mtree = shard_rows(mesh, mtree)
        utree = shard_rows(mesh, utree)
    else:
        mtree = shard_rows(mesh, to_tree(dataset.movie_blocks))
        utree = shard_rows(mesh, to_tree(dataset.user_blocks))

    dt = jnp.dtype(config.dtype)

    def init_fn():
        # Draw at the REAL entity count so the init (hence the trajectory)
        # is independent of shard-count padding — see init_factors_stats.
        key = jax.random.PRNGKey(config.seed)
        init_kw = dict(
            rank=config.rank,
            num_entities=dataset.user_blocks.num_entities,
        )
        if stats_init:
            u = jax.jit(
                init_factors_stats, static_argnames=("rank", "num_entities")
            )(
                key,
                jnp.asarray(dataset.user_blocks.rating_sum),
                jnp.asarray(dataset.user_blocks.count),
                **init_kw,
            ).astype(dt)
        else:
            u = jax.jit(
                init_factors, static_argnames=("rank", "num_entities")
            )(
                key,
                jnp.asarray(dataset.user_blocks.rating),
                jnp.asarray(dataset.user_blocks.mask),
                jnp.asarray(dataset.user_blocks.count),
                **init_kw,
            ).astype(dt)
        u = shard_rows(mesh, u)
        m = shard_rows(
            mesh, np.zeros((dataset.movie_blocks.padded_entities, config.rank), dt)
        )
        return u, m

    from cfk_tpu.parallel.spmd import _sharded_resilient_loop

    u, m = _sharded_resilient_loop(
        checkpoint_manager,
        model="ials",
        dataset=dataset,
        config=config,
        mesh=mesh,
        dtype=dt,
        init_fn=init_fn,
        make_raw_step=lambda cfg: make_ials_training_step(
            mesh, cfg, **step_kw
        ),
        mtree=mtree,
        utree=utree,
        metrics=metrics,
        checkpoint_every=checkpoint_every,
        health=health,
        fault_injector=fault_injector,
        preemption_guard=preemption_guard,
        watchdog=watchdog,
        resume_fn=lambda: resume_state_synced(
            checkpoint_manager,
            rank=config.rank,
            model="ials",
            num_iterations=config.num_iterations,
            u_shape=(dataset.user_blocks.padded_entities, config.rank),
            m_shape=(dataset.movie_blocks.padded_entities, config.rank),
            num_shards=config.num_shards,
        ),
        save_meta={"rank": config.rank, "model": "ials",
                   "num_shards": config.num_shards},
        plan_provenance=plan_prov,
    )

    return ALSModel(
        user_factors=u,
        movie_factors=m,
        num_users=dataset.user_map.num_entities,
        num_movies=dataset.movie_map.num_entities,
    )
