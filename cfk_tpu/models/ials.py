"""Implicit-feedback ALS (iALS, Hu-Koren-Volinsky 2008) — second model family.

Same block-partitioned layout as the explicit model, different normal
equations: per entity A = YᵀY + Σ_obs (c−1)·f fᵀ + λI with confidence
c = 1 + α·r, preferences 1 at observed cells.  The global Gram YᵀY is
computed once per half-iteration — locally per shard and ``psum``'d over the
mesh (a [k,k] collective, the cheapest message in the whole framework).

This is the BASELINE.md "MovieLens-25M implicit, rank 128" family.  The
reference has no implicit model; capability parity plus one — but the
transport/ingest/checkpoint plumbing is shared with the explicit path.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

try:
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map

from cfk_tpu.config import ALSConfig
from cfk_tpu.data.blocks import Dataset
from cfk_tpu.models.als import ALSModel, _blocks_to_device
from cfk_tpu.ops.solve import global_gram, ials_half_step, init_factors
from cfk_tpu.parallel.mesh import AXIS, shard_rows
from cfk_tpu.parallel.spmd import use_check_vma


@dataclasses.dataclass(frozen=True)
class IALSConfig(ALSConfig):
    """iALS hyper-parameters; ``lam`` here is plain-λI regularization."""

    alpha: float = 40.0
    lam: float = 0.1

    def __post_init__(self) -> None:
        super().__post_init__()
        if self.alpha <= 0:
            raise ValueError(f"alpha must be > 0, got {self.alpha}")
        if self.exchange != "all_gather":
            raise ValueError(
                "iALS currently supports exchange='all_gather' only (the "
                "global-Gram trick needs the full fixed side per shard)"
            )


@functools.partial(
    jax.jit, static_argnames=("rank", "num_iterations", "lam", "alpha", "dtype", "solver")
)
def _train_loop(
    key, movie_blocks, user_blocks, *, rank, num_iterations, lam, alpha, dtype,
    solver="cholesky",
):
    dt = jnp.dtype(dtype)
    u = init_factors(
        key, user_blocks["rating"], user_blocks["mask"], user_blocks["count"], rank
    ).astype(dt)
    m0 = jnp.zeros((movie_blocks["rating"].shape[0], rank), dtype=dt)

    def one_iteration(_, carry):
        u, _ = carry
        m = ials_half_step(
            u, movie_blocks["neighbor_idx"], movie_blocks["rating"],
            movie_blocks["mask"], lam, alpha, solver=solver,
        ).astype(dt)
        u_new = ials_half_step(
            m, user_blocks["neighbor_idx"], user_blocks["rating"],
            user_blocks["mask"], lam, alpha, solver=solver,
        ).astype(dt)
        return (u_new, m)

    return lax.fori_loop(0, num_iterations, one_iteration, (u, m0))


def train_ials(dataset: Dataset, config: IALSConfig, *, metrics=None) -> ALSModel:
    """Single-device implicit ALS. Ratings in the dataset are interaction
    strengths (counts, play-time, explicit stars — anything ≥ 0)."""
    from cfk_tpu.utils.metrics import Metrics

    metrics = metrics if metrics is not None else Metrics()
    key = jax.random.PRNGKey(config.seed)
    with metrics.phase("train"):
        u, m = _train_loop(
            key,
            _blocks_to_device(dataset.movie_blocks),
            _blocks_to_device(dataset.user_blocks),
            rank=config.rank,
            num_iterations=config.num_iterations,
            lam=config.lam,
            alpha=config.alpha,
            dtype=config.dtype,
            solver=config.solver,
        )
        u.block_until_ready()
    metrics.incr("iterations", config.num_iterations)
    return ALSModel(
        user_factors=u,
        movie_factors=m,
        num_users=dataset.user_map.num_entities,
        num_movies=dataset.movie_map.num_entities,
    )


def make_ials_training_step(mesh: Mesh, config: IALSConfig):
    """Jittable one-full-iteration SPMD step for iALS.

    Per half-iteration: psum the local [k,k] Grams, all_gather the fixed
    factors, solve local entities.
    """
    dt = jnp.dtype(config.dtype)

    def half(fixed_local, blk):
        gram = lax.psum(global_gram(fixed_local), AXIS)
        fixed_full = lax.all_gather(fixed_local, AXIS, axis=0, tiled=True)
        return ials_half_step(
            fixed_full, blk["neighbor"], blk["rating"], blk["mask"],
            config.lam, config.alpha, gram=gram, solver=config.solver,
        ).astype(dt)

    def iteration(u, m_unused, mblk, ublk):
        del m_unused
        m = half(u, mblk)
        u_new = half(m, ublk)
        return u_new, m

    spec = {
        "neighbor": P(AXIS, None),
        "rating": P(AXIS, None),
        "mask": P(AXIS, None),
        "count": P(AXIS),
    }
    return _shard_map(
        iteration,
        mesh=mesh,
        in_specs=(P(AXIS, None), P(AXIS, None), spec, spec),
        out_specs=(P(AXIS, None), P(AXIS, None)),
        check_vma=use_check_vma(config),
    )


def train_ials_sharded(
    dataset: Dataset,
    config: IALSConfig,
    mesh: Mesh,
    *,
    checkpoint_manager=None,
    checkpoint_every: int = 1,
    metrics=None,
) -> ALSModel:
    """Multi-device iALS over a 1-D mesh, with optional checkpoint/resume."""
    from cfk_tpu.utils.metrics import Metrics

    metrics = metrics if metrics is not None else Metrics()
    from cfk_tpu.parallel.spmd import validate_sharded_dataset
    from cfk_tpu.transport.checkpoint import resume_state, should_save

    validate_sharded_dataset(dataset, config, mesh)

    def to_tree(blocks):
        return {
            "neighbor": blocks.neighbor_idx,
            "rating": blocks.rating,
            "mask": blocks.mask,
            "count": blocks.count,
        }

    mtree = shard_rows(mesh, to_tree(dataset.movie_blocks))
    utree = shard_rows(mesh, to_tree(dataset.user_blocks))

    dt = jnp.dtype(config.dtype)
    state = resume_state(
        checkpoint_manager,
        rank=config.rank,
        model="ials",
        num_iterations=config.num_iterations,
    )
    if state is not None:
        start_iter = state.iteration
        u = shard_rows(mesh, state.user_factors.astype(dt))
        m = shard_rows(mesh, state.movie_factors.astype(dt))
    else:
        start_iter = 0
        key = jax.random.PRNGKey(config.seed)
        u = jax.jit(init_factors, static_argnames="rank")(
            key,
            jnp.asarray(dataset.user_blocks.rating),
            jnp.asarray(dataset.user_blocks.mask),
            jnp.asarray(dataset.user_blocks.count),
            rank=config.rank,
        ).astype(dt)
        u = shard_rows(mesh, u)
        m = shard_rows(
            mesh, np.zeros((dataset.movie_blocks.padded_entities, config.rank), dt)
        )

    step = jax.jit(make_ials_training_step(mesh, config), donate_argnums=(0, 1))
    for i in range(start_iter, config.num_iterations):
        with metrics.phase("train"):
            u, m = step(u, m, mtree, utree)
            u.block_until_ready()
        metrics.incr("iterations")
        done = i + 1
        if checkpoint_manager is not None and should_save(
            done, checkpoint_every, config.num_iterations
        ):
            with metrics.phase("checkpoint"):
                checkpoint_manager.save(
                    done, np.asarray(u), np.asarray(m),
                    meta={"rank": config.rank, "model": "ials"},
                )
            metrics.incr("checkpoints")

    return ALSModel(
        user_factors=u,
        movie_factors=m,
        num_users=dataset.user_map.num_entities,
        num_movies=dataset.movie_map.num_entities,
    )
