"""Explicit-feedback ALS-WR — the flagship model.

Single-device training loop with exact reference semantics
(``apps/ALSApp.java:115-151`` unrolled topology, re-expressed as a jitted
``lax.fori_loop``):

  - init user factors: avg-rating + U(0,1) (``processors/UFeatureInitializer.java:50-56``)
  - per iteration i: solve movies from users (``MFeatureCalculator-i``), then
    users from movies (``UFeatureCalculator-i``)
  - prediction P = U·Mᵀ (``processors/FeatureCollector.java:91-92``), rows =
    users ascending id, cols = movies ascending id.

The multi-device SPMD path lives in ``cfk_tpu.parallel``; this module is the
1-shard special case and the semantic reference for its equivalence tests.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

from cfk_tpu.config import ALSConfig
from cfk_tpu.data.blocks import Dataset, PaddedBlocks
from cfk_tpu.ops.solve import als_half_step, init_factors


@dataclasses.dataclass(frozen=True)
class ALSModel:
    """Trained factor matrices (rows = ascending external id order)."""

    user_factors: jax.Array  # [num_users, k]  (includes pad rows at the end)
    movie_factors: jax.Array  # [num_movies, k]
    num_users: int
    num_movies: int

    def predict_dense(self) -> np.ndarray:
        """Dense prediction matrix P = U·Mᵀ, [num_users, num_movies]."""
        u = np.asarray(self.user_factors[: self.num_users], dtype=np.float32)
        m = np.asarray(self.movie_factors[: self.num_movies], dtype=np.float32)
        return u @ m.T


def _blocks_to_device(blocks: PaddedBlocks) -> dict[str, jax.Array]:
    return {
        "neighbor_idx": jnp.asarray(blocks.neighbor_idx),
        "rating": jnp.asarray(blocks.rating),
        "mask": jnp.asarray(blocks.mask),
        "count": jnp.asarray(blocks.count),
    }


@functools.partial(
    jax.jit, static_argnames=("rank", "num_iterations", "lam", "solve_chunk", "dtype")
)
def _train_loop(
    key: jax.Array,
    movie_blocks: dict[str, jax.Array],
    user_blocks: dict[str, jax.Array],
    *,
    rank: int,
    num_iterations: int,
    lam: float,
    solve_chunk: int | None,
    dtype: str = "float32",
) -> tuple[jax.Array, jax.Array]:
    dt = jnp.dtype(dtype)
    u = init_factors(
        key, user_blocks["rating"], user_blocks["mask"], user_blocks["count"], rank
    ).astype(dt)
    m0 = jnp.zeros((movie_blocks["rating"].shape[0], rank), dtype=dt)

    def one_iteration(_, carry):
        u, _ = carry
        # Factors are stored in `dtype` (bfloat16 halves HBM traffic); the
        # Gram accumulation upcasts to float32 inside gather_gram.
        m = als_half_step(
            u,
            movie_blocks["neighbor_idx"],
            movie_blocks["rating"],
            movie_blocks["mask"],
            movie_blocks["count"],
            lam,
            solve_chunk=solve_chunk,
        ).astype(dt)
        u_new = als_half_step(
            m,
            user_blocks["neighbor_idx"],
            user_blocks["rating"],
            user_blocks["mask"],
            user_blocks["count"],
            lam,
            solve_chunk=solve_chunk,
        ).astype(dt)
        return (u_new, m)

    u_final, m_final = jax.lax.fori_loop(
        0, num_iterations, one_iteration, (u, m0)
    )
    return u_final, m_final


def train_als(dataset: Dataset, config: ALSConfig) -> ALSModel:
    """Train ALS-WR on one device. Returns factors in ascending-id order."""
    key = jax.random.PRNGKey(config.seed)
    u, m = _train_loop(
        key,
        _blocks_to_device(dataset.movie_blocks),
        _blocks_to_device(dataset.user_blocks),
        rank=config.rank,
        num_iterations=config.num_iterations,
        lam=config.lam,
        solve_chunk=config.solve_chunk,
        dtype=config.dtype,
    )
    return ALSModel(
        user_factors=u,
        movie_factors=m,
        num_users=dataset.user_map.num_entities,
        num_movies=dataset.movie_map.num_entities,
    )
