"""Explicit-feedback ALS-WR — the flagship model.

Single-device training loop with exact reference semantics
(``apps/ALSApp.java:115-151`` unrolled topology, re-expressed as a jitted
``lax.fori_loop``):

  - init user factors: avg-rating + U(0,1) (``processors/UFeatureInitializer.java:50-56``)
  - per iteration i: solve movies from users (``MFeatureCalculator-i``), then
    users from movies (``UFeatureCalculator-i``)
  - prediction P = U·Mᵀ (``processors/FeatureCollector.java:91-92``), rows =
    users ascending id, cols = movies ascending id.

The multi-device SPMD path lives in ``cfk_tpu.parallel``; this module is the
1-shard special case and the semantic reference for its equivalence tests.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

from cfk_tpu.config import ALSConfig
from cfk_tpu.data.blocks import (
    BucketedBlocks,
    Dataset,
    PaddedBlocks,
    SegmentBlocks,
    TiledBlocks,
)
from cfk_tpu.ops.solve import (
    als_half_step,
    als_half_step_bucketed,
    als_half_step_segment,
    init_factors,
    init_factors_stats,
)


@dataclasses.dataclass(frozen=True)
class ALSModel:
    """Trained factor matrices (rows = ascending external id order)."""

    user_factors: jax.Array  # [num_users, k]  (includes pad rows at the end)
    movie_factors: jax.Array  # [num_movies, k]
    num_users: int
    num_movies: int

    def host_factors(self) -> tuple[np.ndarray, np.ndarray]:
        """float32 host copies of (U, M) with pad rows trimmed.

        The one place factor hosting is defined — the dense predictor and
        the factored evaluators (``cfk_tpu.eval.metrics.mse_rmse_from_model``,
        ``cfk_tpu.eval.ranking.ranks_from_model``) all share it, so they can
        never diverge on trimming/dtype.  Works under multi-process JAX too:
        non-addressable sharded factors are process_allgather'd so every host
        sees the same matrices.  Cached: the post-training path (MSE eval,
        ranking eval, CSV dump) fetches from device exactly once.
        """
        return self._host_factors

    @functools.cached_property
    def _host_factors(self) -> tuple[np.ndarray, np.ndarray]:
        from cfk_tpu.parallel.mesh import to_host

        u = to_host(self.user_factors)[: self.num_users].astype(np.float32)
        m = to_host(self.movie_factors)[: self.num_movies].astype(np.float32)
        return u, m

    def predict_dense(self, *, allow_huge: bool = False) -> np.ndarray:
        """Dense prediction matrix P = U·Mᵀ, [num_users, num_movies].

        Refuses matrices over ~4e9 cells (16 GB float32) unless
        ``allow_huge`` — at full-Netflix scale the dense matrix is the one
        thing that genuinely cannot scale (the reference's collector had
        the same ceiling); serve with ``recommend_top_k`` instead, which is
        chunked and never materializes P.
        """
        cells = self.num_users * self.num_movies
        if cells > 4_000_000_000 and not allow_huge:
            raise ValueError(
                f"dense prediction matrix would be {self.num_users}×"
                f"{self.num_movies} = {cells:.2e} float32 cells; use "
                "recommend_top_k (chunked top-K serving) or pass "
                "allow_huge=True if you really have the RAM"
            )
        u, m = self.host_factors()
        return u @ m.T

    def recommend_top_k(self, user_rows, k: int = 10, *, dataset=None,
                        chunk: int = 8192):
        """Top-K movie rows per user row; see ``cfk_tpu.eval.recommend``."""
        from cfk_tpu.eval.recommend import recommend_top_k

        return recommend_top_k(self, user_rows, k, dataset=dataset, chunk=chunk)


def _blocks_to_device(blocks: PaddedBlocks) -> dict[str, jax.Array]:
    return {
        "neighbor_idx": jnp.asarray(blocks.neighbor_idx),
        "rating": jnp.asarray(blocks.rating),
        "mask": jnp.asarray(blocks.mask),
        "count": jnp.asarray(blocks.count),
    }


def _bucketed_to_device(blocks: BucketedBlocks):
    """Device trees (pytree of per-bucket dicts) + static chunk hints."""
    trees, chunks = blocks.to_tree()
    return jax.tree.map(jnp.asarray, trees), chunks


def _segment_to_device(blocks: SegmentBlocks) -> dict[str, jax.Array]:
    return {
        "neighbor_idx": jnp.asarray(blocks.neighbor_idx),
        "rating": jnp.asarray(blocks.rating),
        "mask": jnp.asarray(blocks.mask),
        "seg_rel": jnp.asarray(blocks.seg_rel),
        "chunk_entity": jnp.asarray(blocks.chunk_entity),
        "chunk_count": jnp.asarray(blocks.chunk_count),
        "group_sizes": jnp.asarray(blocks.group_sizes),
        "carry_in": jnp.asarray(blocks.carry_in),
        "last_seg": jnp.asarray(blocks.last_seg),
    }


def _stats_setup_guard(blocks, layout: str) -> None:
    if blocks.num_shards != 1:
        raise ValueError(
            f"{layout} blocks were built for num_shards={blocks.num_shards}; "
            "their row/segment indices are shard-local, so the single-device "
            "trainer needs num_shards=1 — use the sharded trainer, or rebuild "
            "with Dataset.from_coo(..., num_shards=1)"
        )


def _bucketed_device_setup(dataset: Dataset):
    """Single-device bucketed setup shared by train_als / train_ials:
    device block trees, user init stats, and the static layout kwargs."""
    mb, ub = dataset.movie_blocks, dataset.user_blocks
    _stats_setup_guard(mb, "bucketed")
    mblocks, m_chunks = _bucketed_to_device(mb)
    ublocks, u_chunks = _bucketed_to_device(ub)
    u_stats = {
        "rating_sum": jnp.asarray(ub.rating_sum),
        "count": jnp.asarray(ub.count),
    }
    layout_kw = dict(
        m_chunks=m_chunks,
        u_chunks=u_chunks,
        m_entities=mb.padded_entities,
        u_entities=ub.padded_entities,
    )
    return mblocks, ublocks, u_stats, layout_kw


def _tiled_to_device(blocks: TiledBlocks, weighted: bool = False
                     ) -> dict[str, jax.Array]:
    if blocks.mode == "dstream":
        # Window metadata rides in tile_meta; upload only what the model's
        # kernel reads — the weighted channels (tile-aligned weight +
        # stream-aligned rating_dense, ~1 GB at full Netflix) only for
        # iALS, never for the unit-weight explicit path.
        d = {
            "neighbor_idx": jnp.asarray(blocks.neighbor_idx),
            "rating": jnp.asarray(blocks.rating),
            "tile_meta": jnp.asarray(blocks.tile_meta),
            "chunk_entity": jnp.asarray(blocks.chunk_entity),
            "chunk_count": jnp.asarray(blocks.chunk_count),
            "carry_in": jnp.asarray(blocks.carry_in),
            "last_seg": jnp.asarray(blocks.last_seg),
            "count": jnp.asarray(blocks.count),
        }
        if weighted:
            if not blocks.weight.size or blocks.rating_dense is None:
                raise ValueError(
                    "these dense-stream blocks predate the weighted "
                    "channels — rebuild the dataset (delete its cache)"
                )
            d["weight"] = jnp.asarray(blocks.weight)
            d["rating_dense"] = jnp.asarray(blocks.rating_dense)
        return d
    return {
        "neighbor_idx": jnp.asarray(blocks.neighbor_idx),
        "rating": jnp.asarray(blocks.rating),
        "weight": jnp.asarray(blocks.weight),
        "tile_seg": jnp.asarray(blocks.tile_seg),
        "chunk_base": jnp.asarray(blocks.chunk_base),
        "chunk_entity": jnp.asarray(blocks.chunk_entity),
        "chunk_count": jnp.asarray(blocks.chunk_count),
        "carry_in": jnp.asarray(blocks.carry_in),
        "last_seg": jnp.asarray(blocks.last_seg),
        "slice_starts": jnp.asarray(blocks.slice_starts),
        "count": jnp.asarray(blocks.count),
    }


def _tiled_device_setup(dataset: Dataset, weighted: bool = False):
    """Single-device tiled-layout setup; statics carry ("tiled", mode, ...).

    ``weighted=True`` (the iALS trainer) stages the dense-stream weighted
    channels too."""
    mb, ub = dataset.movie_blocks, dataset.user_blocks
    _stats_setup_guard(mb, "tiled")
    u_stats = {
        "rating_sum": jnp.asarray(ub.rating_sum),
        "count": jnp.asarray(ub.count),
    }
    layout_kw = dict(
        m_chunks=("tiled", mb.mode) + mb.statics,
        u_chunks=("tiled", ub.mode) + ub.statics,
        m_entities=mb.padded_entities,
        u_entities=ub.padded_entities,
    )
    return (_tiled_to_device(mb, weighted), _tiled_to_device(ub, weighted),
            u_stats, layout_kw)


def _segment_device_setup(dataset: Dataset):
    """Single-device segment-layout setup: flat device arrays, init stats,
    static local-entity counts + scan-window hints."""
    mb, ub = dataset.movie_blocks, dataset.user_blocks
    _stats_setup_guard(mb, "segment")
    u_stats = {
        "rating_sum": jnp.asarray(ub.rating_sum),
        "count": jnp.asarray(ub.count),
    }
    layout_kw = dict(
        m_chunks=mb.statics,
        u_chunks=ub.statics,
        m_entities=mb.padded_entities,
        u_entities=ub.padded_entities,
    )
    return _segment_to_device(mb), _segment_to_device(ub), u_stats, layout_kw


def _half(fixed, blk, *, lam, solve_chunk, solver, chunks=None, entities=None,
          x_prev=None, algorithm="als", block_size=32, sweeps=1,
          overlap=None, fused_epilogue=None, in_kernel_gather=None,
          reg_solve_algo=None, table_dtype=None):
    """Solve one side against fixed factors; dispatches on the block layout
    (tuple = width buckets, dict with segment ids = flat segment run,
    other dict = one padded rectangle).  ``algorithm="als++"`` runs
    warm-started subspace sweeps from ``x_prev`` instead of full solves
    (padded/bucketed layouts).  ``table_dtype`` quantizes the gather table
    (``ops.quant``) — the tiled/bucketed/subspace entries quantize and
    fold internally; the padded/segment paths take the bf16 cast here
    (config validation refuses int8 for them)."""
    if algorithm == "als++":
        from cfk_tpu.ops.subspace import (
            als_pp_half_step,
            als_pp_half_step_bucketed,
        )

        pp_kw = dict(
            block_size=block_size, sweeps=sweeps, solver=solver,
            in_kernel_gather=in_kernel_gather,
            fused_epilogue=fused_epilogue, reg_solve_algo=reg_solve_algo,
            table_dtype=table_dtype,
        )
        if isinstance(blk, tuple):
            return als_pp_half_step_bucketed(
                fixed, x_prev, blk, chunks, entities, lam,
                overlap=overlap, **pp_kw,
            )
        return als_pp_half_step(
            fixed, x_prev, blk["neighbor_idx"], blk["rating"], blk["mask"],
            blk["count"], lam, **pp_kw,
        )
    if isinstance(blk, tuple):
        return als_half_step_bucketed(
            fixed, blk, chunks, entities, lam, solver=solver,
            overlap=overlap, reg_solve_algo=reg_solve_algo,
            fused_epilogue=fused_epilogue, in_kernel_gather=in_kernel_gather,
            table_dtype=table_dtype,
        )
    if "weight" in blk or "tile_meta" in blk:  # tiled layout
        from cfk_tpu.ops.tiled import tiled_half_step

        return tiled_half_step(
            fixed, blk, chunks, entities, lam, solver=solver,
            overlap=overlap, fused_epilogue=fused_epilogue,
            in_kernel_gather=in_kernel_gather, reg_solve_algo=reg_solve_algo,
            table_dtype=table_dtype,
        )
    from cfk_tpu.ops import quant

    fixed = quant.gather_operand_view(fixed, table_dtype)
    if "seg_rel" in blk:
        return als_half_step_segment(
            fixed,
            blk["neighbor_idx"],
            blk["rating"],
            blk["mask"],
            blk["seg_rel"],
            blk["chunk_entity"],
            blk["chunk_count"],
            blk["group_sizes"],
            blk["carry_in"],
            blk["last_seg"],
            entities,
            lam,
            statics=chunks,
            solver=solver,
            reg_solve_algo=reg_solve_algo,
        )
    return als_half_step(
        fixed,
        blk["neighbor_idx"],
        blk["rating"],
        blk["mask"],
        blk["count"],
        lam,
        solve_chunk=solve_chunk,
        solver=solver,
        overlap=overlap,
        reg_solve_algo=reg_solve_algo,
    )


_LAYOUT_STATICS = ("m_chunks", "u_chunks", "m_entities", "u_entities")
_ALG_STATICS = ("algorithm", "block_size", "sweeps", "overlap",
                "fused_epilogue", "in_kernel_gather", "reg_solve_algo",
                "table_dtype")


@functools.partial(
    jax.jit,
    static_argnames=("rank", "num_iterations", "lam", "solve_chunk", "dtype",
                     "solver", "health_every", "health_norm_limit")
    + _LAYOUT_STATICS + _ALG_STATICS,
)
def _train_loop(
    key: jax.Array,
    movie_blocks,
    user_blocks,
    u_stats=None,
    *,
    rank: int,
    num_iterations: int,
    lam: float,
    solve_chunk: int | None,
    dtype: str = "float32",
    solver: str = "cholesky",
    algorithm: str = "als",
    block_size: int = 32,
    sweeps: int = 1,
    overlap: bool | None = None,
    fused_epilogue: bool | None = None,
    in_kernel_gather: bool | None = None,
    reg_solve_algo: str | None = None,
    table_dtype: str | None = None,
    health_every: int | None = None,
    health_norm_limit: float = 0.0,
    m_chunks=None,
    u_chunks=None,
    m_entities=None,
    u_entities=None,
):
    dt = jnp.dtype(dtype)
    if u_stats is not None:  # bucketed layout: init from per-entity stats
        u = init_factors_stats(key, u_stats["rating_sum"], u_stats["count"], rank)
        m_rows = m_entities
    else:
        u = init_factors(
            key, user_blocks["rating"], user_blocks["mask"], user_blocks["count"], rank
        )
        m_rows = movie_blocks["rating"].shape[0]
    u = u.astype(dt)
    m0 = jnp.zeros((m_rows, rank), dtype=dt)

    def step(i, u, m_prev):
        return _iteration_body(
            u, movie_blocks, user_blocks,
            lam=lam, solve_chunk=solve_chunk, dt=dt, solver=solver,
            algorithm=algorithm, block_size=block_size, sweeps=sweeps,
            overlap=overlap, fused_epilogue=fused_epilogue,
            in_kernel_gather=in_kernel_gather,
            reg_solve_algo=reg_solve_algo, table_dtype=table_dtype,
            m_prev=m_prev,
            m_chunks=m_chunks, u_chunks=u_chunks,
            m_entities=m_entities, u_entities=u_entities,
        )

    if health_every is None:
        u_final, m_final = jax.lax.fori_loop(
            0, num_iterations, lambda i, c: step(i, *c), (u, m0)
        )
        return u_final, m_final

    # Health sentinel folded into the fori_loop carry: an int32
    # [first_bad_iter, reasons] word updated (via lax.cond, so off-cadence
    # iterations pay nothing) every ``health_every`` iterations — the host
    # inspects it once after the loop and reruns through the resilient
    # stepped loop only when it tripped (cfk_tpu.resilience.sentinel).
    from cfk_tpu.resilience import sentinel

    def probed(i, carry):
        u, m_prev, hw = carry
        u2, m2 = step(i, u, m_prev)
        hw = sentinel.fold_probe(
            hw, i, u2, m2, every=health_every,
            norm_limit=health_norm_limit, total=num_iterations,
        )
        return u2, m2, hw

    return jax.lax.fori_loop(
        0, num_iterations, probed, (u, m0, sentinel.carry_init())
    )


def _iteration_body(u, movie_blocks, user_blocks, *, lam, solve_chunk, dt,
                    solver="cholesky", algorithm="als", block_size=32,
                    sweeps=1, overlap=None, fused_epilogue=None,
                    in_kernel_gather=None, reg_solve_algo=None,
                    table_dtype=None, m_prev=None, m_chunks=None,
                    u_chunks=None, m_entities=None, u_entities=None):
    """One full iteration (solve M from U, then U from M) — the single source
    of the per-iteration math for both the fused-loop and checkpointed paths.

    Factors are stored in ``dt`` (bfloat16 halves HBM traffic); Gram
    contractions accumulate float32 inside the half-step kernels.
    ``algorithm="als++"`` warm-starts each side from its previous factors
    (``m_prev`` / the ``u`` carry) with subspace sweeps.
    """
    alg = dict(algorithm=algorithm, block_size=block_size, sweeps=sweeps,
               overlap=overlap, fused_epilogue=fused_epilogue,
               in_kernel_gather=in_kernel_gather,
               reg_solve_algo=reg_solve_algo, table_dtype=table_dtype)
    m = _half(
        u, movie_blocks, lam=lam, solve_chunk=solve_chunk, solver=solver,
        chunks=m_chunks, entities=m_entities, x_prev=m_prev, **alg,
    ).astype(dt)
    u_new = _half(
        m, user_blocks, lam=lam, solve_chunk=solve_chunk, solver=solver,
        chunks=u_chunks, entities=u_entities, x_prev=u, **alg,
    ).astype(dt)
    return u_new, m


@functools.partial(
    jax.jit,
    static_argnames=("lam", "solve_chunk", "dtype", "solver")
    + _LAYOUT_STATICS + _ALG_STATICS,
    donate_argnums=(0, 1),
)
def _one_iteration(
    u: jax.Array,
    m_prev: jax.Array,
    movie_blocks,
    user_blocks,
    *,
    lam: float,
    solve_chunk: int | None,
    dtype: str,
    solver: str = "cholesky",
    algorithm: str = "als",
    block_size: int = 32,
    sweeps: int = 1,
    overlap: bool | None = None,
    fused_epilogue: bool | None = None,
    in_kernel_gather: bool | None = None,
    reg_solve_algo: str | None = None,
    table_dtype: str | None = None,
    m_chunks=None,
    u_chunks=None,
    m_entities=None,
    u_entities=None,
) -> tuple[jax.Array, jax.Array]:
    return _iteration_body(
        u, movie_blocks, user_blocks,
        lam=lam, solve_chunk=solve_chunk, dt=jnp.dtype(dtype), solver=solver,
        algorithm=algorithm, block_size=block_size, sweeps=sweeps,
        overlap=overlap, fused_epilogue=fused_epilogue,
        in_kernel_gather=in_kernel_gather, reg_solve_algo=reg_solve_algo,
        table_dtype=table_dtype, m_prev=m_prev,
        m_chunks=m_chunks, u_chunks=u_chunks,
        m_entities=m_entities, u_entities=u_entities,
    )


def train_als(
    dataset: Dataset,
    config: ALSConfig,
    *,
    checkpoint_manager=None,
    checkpoint_every: int = 1,
    metrics=None,
    fault_injector=None,
    preemption_guard=None,
    watchdog=None,
    warm_start=None,
) -> ALSModel:
    """Train ALS-WR on one device. Returns factors in ascending-id order.

    Without a checkpoint manager the whole loop runs as one fused
    ``fori_loop`` program; with one, iterations are stepped from Python so
    factors can be saved every ``checkpoint_every`` iterations and training
    resumes from the latest step.  ``metrics`` (a ``cfk_tpu.utils.metrics.
    Metrics``) records phase timings and iteration counters when provided.

    ``config.health_check_every`` arms the numerical-health sentinel: the
    fused loop folds the probe into its carry and, when it trips, the run
    is replayed through the resilient stepped loop, which rolls back to the
    last good state and climbs the escalation ladder
    (``cfk_tpu.resilience``).  ``fault_injector`` (chaos testing only)
    forces the stepped loop so faults can fire at step boundaries.

    ``preemption_guard``/``watchdog`` (``cfk_tpu.resilience.preempt``) arm
    preemption tolerance: they also force the stepped loop (the fused
    ``fori_loop`` exposes no iteration boundary to poll), which polls the
    guard between iterations — on SIGTERM/SIGINT it drains the async
    checkpoint writer, commits a final checkpoint, and returns resumable —
    and ticks the watchdog per completed iteration.

    ``warm_start=(u0, m0)`` seeds the factors instead of the reference's
    avg-rating + U(0,1) init — the streaming fold-in path's periodic full
    retrains pass the live factors here (``cfk_tpu.streaming.session``).
    Rows are host arrays in this dataset's ascending-id order; shorter
    matrices are zero-padded to the padded entity counts, longer ones
    refused.  Forces the stepped (resilient) loop; a resumable checkpoint
    in ``checkpoint_manager`` still wins over the seed (resume semantics
    are unchanged — the warm start only defines iteration 0).
    """
    from cfk_tpu.resilience.loop import validate_cadence
    from cfk_tpu.resilience.sentinel import health_from_config
    from cfk_tpu.utils.metrics import Metrics

    from cfk_tpu.config import enable_compile_cache
    from cfk_tpu.plan import plan_for_config

    # Before the first compile (ISSUE 13): warm-start compile caching.
    enable_compile_cache(getattr(config, "compile_cache_dir", None))
    health = health_from_config(config)
    validate_cadence(checkpoint_every, health)
    metrics = metrics if metrics is not None else Metrics()
    num_ratings = int(dataset.movie_blocks.count.sum())
    metrics.gauge("num_users", dataset.user_map.num_entities)
    metrics.gauge("num_movies", dataset.movie_map.num_entities)
    metrics.gauge("num_ratings", num_ratings)
    # Resolve the execution plan (cfk_tpu.plan): the config's concrete
    # knobs arrive as pinned constraints, the deferred ones are priced by
    # the cost model, and the trainer reads the knob values through the
    # plan seam below — bit-identical routing for pinned/default configs,
    # with provenance (chosen plan + estimated cost + cache hit/miss)
    # recorded in the metrics and in every checkpoint manifest.
    exec_plan, plan_prov = plan_for_config(
        config,
        num_users=dataset.user_map.num_entities,
        num_movies=dataset.movie_map.num_entities,
        nnz=max(num_ratings, 1),
    )
    knobs = exec_plan.half_step_kwargs(config)
    metrics.note("plan", plan_prov.summary())
    if exec_plan.offload_tier == "host_window":
        # Out-of-core tier (ISSUE 11): the memory-budget predicate said
        # the resident tables cannot fit (or the config pinned the tier),
        # so training runs through the windowed host-offload driver —
        # bit-exact vs the resident path on the same stream blocks.
        unsupported = [
            name for name, v in (
                ("checkpoint_manager", checkpoint_manager),
                ("fault_injector", fault_injector),
                ("preemption_guard", preemption_guard),
                ("watchdog", watchdog),
                ("warm_start", warm_start),
            ) if v is not None
        ]
        if unsupported:
            raise NotImplementedError(
                f"offload_tier='host_window' does not support "
                f"{unsupported} yet — the windowed driver keeps factors "
                "in host stores (see cfk_tpu/offload/windowed.py; "
                "window-level fault injection uses its window_faults=)"
            )
        from cfk_tpu.offload.windowed import train_als_host_window

        # Threading the CONFIG here is exactly the plan's half_step_kwargs
        # seam: every knob the windowed driver reads is either always
        # pinned by the config (table_dtype, overlap — concrete dataclass
        # defaults) or deferred, in which case half_step_kwargs returns
        # the config's own sentinel (None/"auto") — the same value the
        # driver reads off the config.  Execution can therefore never
        # diverge from the provenance recorded above.
        return train_als_host_window(
            dataset, config, metrics=metrics, plan_provenance=plan_prov,
        )
    key = jax.random.PRNGKey(config.seed)
    bucketed = isinstance(dataset.movie_blocks, BucketedBlocks)
    segment = isinstance(dataset.movie_blocks, SegmentBlocks)
    tiled = isinstance(dataset.movie_blocks, TiledBlocks)
    with metrics.phase("blocks_to_device"):
        if bucketed:
            mblocks, ublocks, u_stats, layout_kw = _bucketed_device_setup(dataset)
        elif segment:
            mblocks, ublocks, u_stats, layout_kw = _segment_device_setup(dataset)
        elif tiled:
            mblocks, ublocks, u_stats, layout_kw = _tiled_device_setup(dataset)
        else:
            mblocks = _blocks_to_device(dataset.movie_blocks)
            ublocks = _blocks_to_device(dataset.user_blocks)
            u_stats = None
            layout_kw = {}
    # The padded layout consumes the unified HBM budget at solve time:
    # entities per chunk derived from the wider rectangle (conservative for
    # the narrower side).  Build-time layouts consumed it at from_coo.
    solve_chunk = None
    if not (bucketed or segment or tiled):
        width = max(
            dataset.movie_blocks.neighbor_idx.shape[1],
            dataset.user_blocks.neighbor_idx.shape[1],
        )
        solve_chunk = config.padded_solve_chunk(width)
    stepped = (checkpoint_manager is not None or fault_injector is not None
               or preemption_guard is not None or watchdog is not None
               or warm_start is not None)
    if not stepped:
        from cfk_tpu.telemetry import record_event, span

        train_s_before = metrics.phases.get("train", 0.0)
        # ONE span for the whole fused fori_loop: the iterations live
        # inside a single jit, so per-iteration host spans exist only on
        # the stepped path (resilience/loop.py) — the device-side
        # breakdown is the jax-profiler trace's job (same --trace-dir).
        with metrics.phase("train"), \
                span("train/fused_loop", iters=config.num_iterations):
            out = _train_loop(
                key,
                mblocks,
                ublocks,
                u_stats,
                rank=config.rank,
                num_iterations=config.num_iterations,
                lam=config.lam,
                solve_chunk=solve_chunk,
                dtype=config.dtype,
                solver=knobs["solver"],
                algorithm=config.algorithm,
                block_size=config.block_size,
                sweeps=config.sweeps,
                overlap=knobs["overlap"],
                fused_epilogue=knobs["fused_epilogue"],
                in_kernel_gather=knobs["in_kernel_gather"],
                reg_solve_algo=knobs["reg_solve_algo"],
                table_dtype=knobs["table_dtype"],
                health_every=None if health is None else health.every,
                health_norm_limit=(
                    0.0 if health is None else health.norm_limit
                ),
                **layout_kw,
            )
            u, m = out[0], out[1]
            u.block_until_ready()
        report = None
        if health is not None:
            from cfk_tpu.resilience.sentinel import report_from_carry

            report = report_from_carry(out[2], u, m)
        if report is None or report.healthy:
            metrics.incr("iterations", config.num_iterations)
            record_event("train", "fused_loop_done",
                         iters=config.num_iterations)
        else:
            import warnings

            # The fused attempt is discarded and replayed below, so keep
            # its accounting out of the headline counters: its wall time
            # moves to "train_discarded" and its iterations are not
            # counted (the stepped replay re-detects this divergence and
            # does the health_trips / rollback accounting exactly once).
            discarded = metrics.phases.get("train", 0.0) - train_s_before
            metrics.phases["train"] = train_s_before
            metrics.phases["train_discarded"] += discarded
            metrics.note("fused_loop_trip", report.summary())
            warnings.warn(
                f"health sentinel tripped in the fused training loop "
                f"({report.summary()}); replaying through the "
                "resilient stepped loop"
            )
            stepped = True
    if stepped:
        dt = jnp.dtype(config.dtype)

        def _padded_seed(x, rows, what):
            x = np.asarray(x)
            if x.shape[0] > rows or x.shape[1:] != (config.rank,):
                raise ValueError(
                    f"warm_start {what} factors have shape {x.shape}; this "
                    f"dataset solves [{rows}, {config.rank}] (padded rows) — "
                    "rebuild the seed against the same entity universe"
                )
            out = jnp.zeros((rows, config.rank), dt)
            return out.at[: x.shape[0]].set(jnp.asarray(x, dtype=dt))

        def init_fn():
            if warm_start is not None:
                wu, wm = warm_start
                return (
                    _padded_seed(
                        wu, dataset.user_blocks.padded_entities, "user"),
                    _padded_seed(
                        wm, dataset.movie_blocks.padded_entities, "movie"),
                )
            if u_stats is not None:
                u = init_factors_stats(
                    key, u_stats["rating_sum"], u_stats["count"], config.rank
                ).astype(dt)
            else:
                u = init_factors(
                    key, ublocks["rating"], ublocks["mask"], ublocks["count"],
                    config.rank,
                ).astype(dt)
            m = jnp.zeros((dataset.movie_blocks.padded_entities, config.rank), dt)
            return u, m

        def make_step(ov):
            def step_fn(u, m):
                return _one_iteration(
                    u, m, mblocks, ublocks,
                    lam=ov.lam, solve_chunk=solve_chunk,
                    dtype=config.dtype, solver=knobs["solver"],
                    algorithm=config.algorithm, block_size=config.block_size,
                    sweeps=config.sweeps, overlap=knobs["overlap"],
                    fused_epilogue=ov.fused_epilogue,
                    in_kernel_gather=knobs["in_kernel_gather"],
                    # The GJ escalation rung: a real jit-static now, so the
                    # rebuilt step re-traces with the overridden elimination
                    # (it used to ride the CFK_REG_SOLVE_ALGO env var).
                    reg_solve_algo=(ov.reg_solve_algo
                                    or knobs["reg_solve_algo"]),
                    table_dtype=knobs["table_dtype"],
                    **layout_kw,
                )

            return step_fn

        from cfk_tpu.resilience.loop import resilient_train_loop
        from cfk_tpu.resilience.policy import Overrides, policy_from_config

        u, m = resilient_train_loop(
            checkpoint_manager,
            model="als",
            rank=config.rank,
            num_iterations=config.num_iterations,
            u_shape=(dataset.user_blocks.padded_entities, config.rank),
            m_shape=(dataset.movie_blocks.padded_entities, config.rank),
            dtype=dt,
            init_fn=init_fn,
            make_step=make_step,
            base_overrides=Overrides(
                lam=config.lam, fused_epilogue=knobs["fused_epilogue"]
            ),
            metrics=metrics,
            checkpoint_every=checkpoint_every,
            health=health,
            policy=policy_from_config(config),
            fault_injector=fault_injector,
            preemption_guard=preemption_guard,
            watchdog=watchdog,
            plan_provenance=plan_prov,
        )
    return ALSModel(
        user_factors=u,
        movie_factors=m,
        num_users=dataset.user_map.num_entities,
        num_movies=dataset.movie_map.num_entities,
    )
