from cfk_tpu.models.als import ALSModel, train_als
from cfk_tpu.models.ials import IALSConfig, train_ials, train_ials_sharded

__all__ = ["ALSModel", "train_als", "IALSConfig", "train_ials", "train_ials_sharded"]
