from cfk_tpu.models.als import ALSModel, train_als

__all__ = ["ALSModel", "train_als"]
