"""Unified execution planner (ROADMAP item 5).

``plan(problem_shape, device_spec, constraints) -> ExecutionPlan``: one
cost-model-driven resolution of every execution-affecting knob, an opt-in
autotune mode with a (shape-class, device fingerprint, version)-keyed
winner cache, and a backend-pluggable ``KernelRegistry`` behind which the
Pallas kernels and their XLA-emulation twins live (``mosaic_tpu`` /
``xla_emulation`` today; a Mosaic-GPU backend is a registry entry, not a
rewrite).  See ARCHITECTURE.md "Execution planner & kernel registry".
"""

from cfk_tpu.plan.autotune import PlanCache, autotune, cache_key
from cfk_tpu.plan.cost import PlanCost, plan_cost
from cfk_tpu.plan.registry import (
    KERNEL_BACKENDS,
    KERNEL_SLOTS,
    REGISTRY,
    KernelRegistry,
    KernelSpec,
)
from cfk_tpu.plan.resolver import (
    plan,
    plan_for_config,
    rank_plans,
    shape_for_config,
)
from cfk_tpu.plan.spec import (
    DeviceSpec,
    ExecutionPlan,
    PlanConstraintError,
    PlanConstraints,
    PlanProvenance,
    ProblemShape,
    constraints_from_config,
)

__all__ = [
    "KERNEL_BACKENDS",
    "KERNEL_SLOTS",
    "REGISTRY",
    "DeviceSpec",
    "ExecutionPlan",
    "KernelRegistry",
    "KernelSpec",
    "PlanCache",
    "PlanConstraintError",
    "PlanConstraints",
    "PlanCost",
    "PlanProvenance",
    "ProblemShape",
    "autotune",
    "cache_key",
    "constraints_from_config",
    "plan",
    "plan_cost",
    "plan_for_config",
    "rank_plans",
    "shape_for_config",
]
