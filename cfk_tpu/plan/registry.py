"""KernelRegistry: every compute kernel behind one backend-pluggable seam.

The Pallas kernels and their XLA-emulation twins used to be dispatched by
``compat``/gate checks scattered across ``ops/tiled.py``, ``ops/bucketed.py``
and both SPMD ring half-steps.  Here each kernel SLOT (gram, gram+solve,
their gather-fused twins, the fused reg+solve, the serve top-K) registers
its implementations per BACKEND:

- ``mosaic_tpu``     — the Pallas kernels (Mosaic lowering on TPU; the
                       bit-exact interpret/emulation route off-TPU, which
                       is why forcing this backend off is a *plan change*,
                       not a numeric change),
- ``xla_emulation``  — the plain-XLA formulations (materialized gather
                       stream, einsum Gram, batched Cholesky, scan top-K).

A Mosaic-GPU or JAXMg-style multi-GPU backend (arXiv 2601.14466) becomes a
third registry entry, not a rewrite: register loaders for the slots it
implements and the resolver's feasibility gates pick it up.

The central mode resolvers (``resolve_gather_mode``/``resolve_fused_chunk_
lam`` — previously duplicated logic in ``ops.tiled``, mirrored by
``ops.bucketed.resolve_bucket_modes``) live HERE now; ``ops.tiled`` keeps
thin aliases so existing call sites and tests are untouched.  Both consult
``backend_available``: forcing ``mosaic_tpu`` unavailable (an outage, a
chaos drill, a not-yet-ported platform) reroutes every next trace to the
emulation backend — and bumps ``generation()`` so the resilient loop knows
a rebuilt step would resolve differently (a recovery rung is a plan
transition).

Importable without jax; kernel loaders and gates import lazily.
"""

from __future__ import annotations

import contextlib
import dataclasses
import threading

KERNEL_BACKENDS = ("mosaic_tpu", "xla_emulation")

# slot → what executes there.  One name per dispatch seam in the half-steps
# and the serve path.
KERNEL_SLOTS = (
    "gram",               # per-chunk tile Gram (split epilogue)
    "gram_solve",         # fused in-VMEM Gram+ridge+solve
    "gram_gather",        # Gram with in-kernel DMA row gather
    "gram_solve_gather",  # both fusions
    "reg_solve",          # batched ridge+solve (the fused reg kernels)
    "topk",               # streaming score+top-K serve kernel
    "topk_coarse",        # two-stage candidate stage (centroid probe)
)


@dataclasses.dataclass(frozen=True)
class KernelSpec:
    """One (slot, backend) registration.  ``loader`` returns the callable
    lazily (kernels import jax); ``supported`` is the static feasibility
    gate the resolver consults (None = always feasible)."""

    slot: str
    backend: str
    loader: object  # () -> callable
    supported: object = None  # (**shape_kwargs) -> bool


class KernelRegistry:
    """slot × backend → KernelSpec, with a forced-unavailability switch.

    ``generation`` increments on every availability change so long-lived
    consumers (the resilient training loop) can detect that a step rebuilt
    NOW would resolve to different kernels than the step they hold.
    """

    def __init__(self) -> None:
        self._specs: dict[tuple[str, str], KernelSpec] = {}
        self._unavailable: set[str] = set()
        self._generation = 0
        self._lock = threading.Lock()

    # -- registration -----------------------------------------------------

    def register(self, slot: str, backend: str, loader, supported=None,
                 ) -> KernelSpec:
        if slot not in KERNEL_SLOTS:
            raise ValueError(
                f"unknown kernel slot {slot!r}; slots: {KERNEL_SLOTS}"
            )
        spec = KernelSpec(slot=slot, backend=backend, loader=loader,
                          supported=supported)
        with self._lock:
            self._specs[(slot, backend)] = spec
        return spec

    def get(self, slot: str, backend: str) -> KernelSpec:
        try:
            return self._specs[(slot, backend)]
        except KeyError:
            raise KeyError(
                f"no kernel registered for slot={slot!r} "
                f"backend={backend!r}; registered: "
                f"{sorted(self._specs)}"
            ) from None

    def backends_for(self, slot: str) -> tuple[str, ...]:
        return tuple(b for (s, b) in self._specs if s == slot)

    # -- availability -----------------------------------------------------

    def backend_available(self, backend: str) -> bool:
        """Is the backend currently usable?  ``xla_emulation`` always is
        (it is the degradation floor); ``mosaic_tpu`` unless forced off.
        Off-TPU the mosaic entries still count as available — they run
        through the bit-exact interpret/emulation route, and refusing them
        here would change CPU CI's coverage of the kernel code paths."""
        return backend not in self._unavailable

    def force_unavailable(self, backend: str, unavailable: bool = True,
                          ) -> None:
        """Flip a backend's availability (chaos drills, real outages).
        Every mode resolver consults this at trace time, so the next step
        REBUILD lands on a still-available backend; already-compiled
        programs keep running their traced kernels."""
        if backend == "xla_emulation" and unavailable:
            raise ValueError(
                "xla_emulation is the degradation floor and cannot be "
                "forced unavailable"
            )
        with self._lock:
            before = backend in self._unavailable
            if unavailable:
                self._unavailable.add(backend)
            else:
                self._unavailable.discard(backend)
            if before != unavailable:
                self._generation += 1

    @contextlib.contextmanager
    def unavailable(self, backend: str):
        """Scoped ``force_unavailable`` for tests/drills."""
        self.force_unavailable(backend, True)
        try:
            yield self
        finally:
            self.force_unavailable(backend, False)

    def generation(self) -> int:
        return self._generation

    def availability_summary(self) -> str:
        down = sorted(self._unavailable)
        if not down:
            return "all kernel backends available"
        return (f"backend(s) {','.join(down)} unavailable "
                f"(generation {self._generation}); "
                "falling back to xla_emulation")


REGISTRY = KernelRegistry()


def backend_available(backend: str) -> bool:
    return REGISTRY.backend_available(backend)


def generation() -> int:
    return REGISTRY.generation()


def _register_builtins() -> None:
    """The in-tree kernels.  Loaders are lazy (jax imports); the
    ``supported`` gates are the SAME functions the half-steps gate on, so
    registry feasibility and executed behavior cannot drift."""

    def _gk(name):
        def load():
            from cfk_tpu.ops.pallas import gram_kernel

            return getattr(gram_kernel, name)

        return load

    def _gather_gate(entries=None, meta_words=None, tile_rows=None,
                     block_rows=None, **_):
        from cfk_tpu.ops.pallas.gram_kernel import in_kernel_gather_supported

        if entries is None:
            return True
        return in_kernel_gather_supported(entries, meta_words, tile_rows,
                                          block_rows)

    def _fused_gate(num_segments=None, k=None, algo=None, **_):
        from cfk_tpu.ops.pallas.gram_kernel import fused_gram_solve_supported

        if k is None:
            return True
        return fused_gram_solve_supported(num_segments, k, algo)

    R = REGISTRY
    R.register("gram", "mosaic_tpu", _gk("gram_tiles_pallas"))
    R.register("gram_solve", "mosaic_tpu", _gk("gram_solve_tiles_pallas"),
               supported=_fused_gate)
    R.register("gram_gather", "mosaic_tpu", _gk("gram_tiles_gather_pallas"),
               supported=_gather_gate)
    R.register("gram_solve_gather", "mosaic_tpu",
               _gk("gram_solve_tiles_gather_pallas"),
               supported=lambda **kw: _gather_gate(**kw) and _fused_gate(**kw))

    def _load_reg_solve():
        from cfk_tpu.ops.pallas import gauss_solve_reg_pallas

        return gauss_solve_reg_pallas

    def _reg_solve_gate(k=None, algo=None, **_):
        from cfk_tpu.ops.pallas.solve_kernel import _fused_reg_rank_cap

        return True if k is None else k <= _fused_reg_rank_cap(algo)

    R.register("reg_solve", "mosaic_tpu", _load_reg_solve,
               supported=_reg_solve_gate)

    def _load_topk():
        from cfk_tpu.serving.topk_kernel import topk_scores_pallas

        return topk_scores_pallas

    R.register("topk", "mosaic_tpu", _load_topk)

    def _load_coarse():
        from cfk_tpu.serving.twostage import _coarse_call

        return _coarse_call

    # The candidate stage is one XLA matmul + top_k on both backends (the
    # exact rescore underneath it is the "topk" slot); registering it
    # keeps the serve plan's kernel list complete — and "topk" remains
    # the un-disableable fallback: forcing "topk_coarse" unavailable
    # degrades the ENGINE to the exact scan, never to no serving.
    R.register("topk_coarse", "mosaic_tpu", _load_coarse)

    # XLA-emulation twins — the same math through plain XLA ops (the
    # compat twins where one exists, the split/einsum formulations
    # otherwise).  Always feasible: this backend is the degradation floor.
    def _load_emulate(name):
        def load():
            from cfk_tpu import compat

            return getattr(compat, name)

        return load

    def _load_solve(name):
        def load():
            from cfk_tpu.ops import solve

            return getattr(solve, name)

        return load

    def _load_tiled_xla():
        # The einsum+segment-sum formulation lives in the tiled chunk
        # dispatcher (backend="xla"); the dispatcher IS the entry point.
        from cfk_tpu.ops.tiled import _entity_gram_chunk

        return _entity_gram_chunk

    R.register("gram", "xla_emulation", _load_tiled_xla)
    R.register("gram_solve", "xla_emulation",
               _load_emulate("emulate_fused_gram_solve"))
    R.register("gram_gather", "xla_emulation",
               _load_emulate("emulate_in_kernel_gather"))
    R.register("gram_solve_gather", "xla_emulation",
               _load_emulate("emulate_fused_gram_solve"))
    R.register("reg_solve", "xla_emulation",
               _load_solve("dispatch_spd_solve"))
    R.register("topk", "xla_emulation", _load_emulate("emulate_topk_scores"))

    def _load_coarse_emu():
        from cfk_tpu.serving.twostage import _coarse_call

        return _coarse_call

    R.register("topk_coarse", "xla_emulation", _load_coarse_emu)


_register_builtins()


# -- central mode resolution (the logic ops.tiled/ops.bucketed/both spmd
# -- ring half-steps used to carry copies of) ------------------------------

def resolve_gather_mode(in_kernel_gather, backend, stage, entries,
                        meta_words, tile_rows, num_segments, k,
                        block_rows=None) -> str:
    """Static gating of the in-kernel gather: ``"fused"`` (the kernel DMAs
    the indexed rows itself) or ``"xla"`` (the materialized-stream
    schedule).  Gates: the knob, the pallas Gram backend (the XLA A/B
    backend has no kernel to gather inside), ``mosaic_tpu`` registry
    availability (a forced-unavailable backend reroutes the next trace to
    the emulation schedule), production stage only (the decompose probes
    time the XLA gather as its own phase), the kernels' SMEM/alignment
    support gate, and the same resident-output VMEM cap the split kernels
    fall back on.  A refused shape keeps the XLA-gather path — same math
    via the same emulation twins, so the two modes stay bit-identical
    (tests/test_in_kernel_gather.py)."""
    if stage != "full" or backend != "pallas":
        return "xla"
    if not REGISTRY.backend_available("mosaic_tpu"):
        return "xla"
    from cfk_tpu.ops.tiled import resolve_in_kernel_gather

    if not resolve_in_kernel_gather(in_kernel_gather):
        return "xla"
    if 2 * num_segments * k * (k + 1) * 4 > (96 << 20):
        return "xla"  # mirrors _entity_gram_chunk's resident-output cap
    gate = REGISTRY.get("gram_gather", "mosaic_tpu").supported
    if not gate(entries=entries, meta_words=meta_words, tile_rows=tile_rows,
                block_rows=block_rows):
        return "xla"
    return "fused"


def resolve_fused_chunk_lam(fused_epilogue, solver, k, num_segments,
                            backend, lam, implicit, algo=None):
    """Static gating of the fused Gram+solve chunk path.

    Returns the concretized λ (0.0 for the implicit/matrix mode, whose λ
    rides inside the shared reg matrix) when the fused path is legal, or
    None → the caller keeps the split Gram→HBM→solve schedule.  Gates:
    the per-call/config/process fused knob, the pallas Gram backend (the
    XLA A/B backend has no VMEM residency to exploit), ``mosaic_tpu``
    registry availability, the pallas solver (cholesky callers asked for
    XLA's solve — honoring that means splitting), the fused elimination's
    rank/VMEM caps (for the elimination ``algo`` the caller threads — GJ
    caps at 64 where LU reaches 128), and a concretizable λ (the kernel
    bakes it in as a compile-time constant; a traced per-step λ falls
    back to the split path's unfused solve, same math).
    """
    import jax

    from cfk_tpu.ops.solve import _resolve_solver, resolve_fused_epilogue

    if not resolve_fused_epilogue(fused_epilogue):
        return None
    if backend != "pallas" or _resolve_solver(solver) != "pallas":
        return None
    if not REGISTRY.backend_available("mosaic_tpu"):
        return None
    gate = REGISTRY.get("gram_solve", "mosaic_tpu").supported
    if not gate(num_segments=num_segments, k=k, algo=algo):
        return None
    if implicit:
        return 0.0
    try:
        return float(lam)
    except (jax.errors.ConcretizationTypeError, TypeError):
        return None
