"""Execution-plan vocabulary: shapes, devices, plans, constraints, provenance.

Everything that decides HOW a half-iteration or a serve batch executes —
layout, chunk size, fused epilogue, in-kernel gather, overlap, elimination
algorithm, gather-table dtype, exchange strategy, serve batch quantum, and
the kernel backend per slot — is captured by one frozen ``ExecutionPlan``.
Before this subsystem those knobs were resolved ad-hoc across ``config.py``,
``ops/tiled.py``, ``ops/bucketed.py``, ``ops/solve.py``, ``parallel/spmd.py``,
``serving/engine.py`` and the four trainers, each with its own fallback
logic (ROADMAP item 5).  ALX (arXiv 2112.02194) is the argument for making
these placement/tiering decisions from a byte/flop model; JAXMg
(arXiv 2601.14466) for putting kernel selection behind one seam so a second
backend is a registry entry, not a rewrite.

This module is deliberately importable WITHOUT jax (like ``config.py``):
the resolver and registry import the heavy gates lazily.

Bit-exactness contract: an ``ALSConfig``'s concrete knobs become PINNED
constraints (``constraints_from_config``), and ``ExecutionPlan.
half_step_kwargs`` threads the config's own sentinel (``None``/``"auto"``)
for every knob the config left deferred — so the default-config path routes
through exactly the same downstream resolution (process defaults, perf_lab
patch points, jit cache keys) as before the planner existed, and is
bit-identical by construction.  The plan's *resolved* concrete choices are
what provenance records and what the cost model priced.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Literal


class PlanConstraintError(ValueError):
    """Raised when pinned constraints conflict with each other or with a
    feasibility gate (e.g. ``table_dtype='int8'`` pinned against
    ``layout='padded'``).  The message names the conflicting pins."""


# Every execution-affecting knob, with the candidate values the resolver
# may enumerate when the field is unpinned.  Order encodes the tie-break
# preference (the legacy default first), so a cost tie resolves to the
# pre-planner behavior.
PLAN_FIELDS: dict[str, tuple] = {
    "layout": ("tiled", "bucketed", "padded", "segment"),
    # "hier_ring" (ISSUE 11): the ICI-ring-within-DCN-ring schedule —
    # inner rings rotate device-resident slices, outer hops cross the
    # slower fabric once per phase (parallel.spmd.half_step_tiled_ring_hier).
    "exchange": ("all_gather", "ring", "hier_ring"),
    # 64k is the measured-best full-scale chunk (BENCH r4) AND the largest
    # class that fits the in-kernel gather's scalar-prefetch SMEM gate.
    "chunk_elems": (1 << 20, 1 << 16, 1 << 18, 1 << 22),
    "fused_epilogue": (True, False),
    "in_kernel_gather": (True, False),
    "overlap": (True, False),
    "reg_solve_algo": ("lu", "gj"),
    "table_dtype": ("float32", "bfloat16", "int8"),
    "solver": ("pallas", "cholesky"),
    "gram_backend": ("pallas", "xla"),
    "serve_batch_quantum": (8, 16, 32, 64, 128, 256),
    "serve_tile_m": (512,),
    # Two-stage clustered retrieval (ISSUE 16).  "exact" streams the full
    # item table per batch (the PR 8 path — bit-identical, and the
    # un-disableable fallback the engine degrades to on a corrupt or
    # stale index); "two_stage" probes the k-means centroid index
    # (serving.cluster) and rescores only the selected clusters' rows
    # through the same kernel.  clusters/probe_clusters size the index
    # (0/0 is exact mode's only value); a free serve_mode resolves
    # through BOTH the cost byte model (centroid scan + expected
    # short-list gather vs the full scan) and the recall model
    # (cost.estimated_recall ≥ cost.SERVE_MIN_RECALL — candidates below
    # the plan recall constraint are never enumerated).  Adding the
    # fields rotates the autotune field-set digest: pre-two_stage
    # winners carry no decision for them and must miss.
    "serve_mode": ("exact", "two_stage"),
    "clusters": (0, 256, 512, 1024, 2048, 4096),
    "probe_clusters": (0, 8, 16, 32, 64, 128),
    # Out-of-core tier (ISSUE 11): "device" keeps both factor tables
    # HBM-resident (feasible ONLY while cfk_tpu.offload.budget's predicate
    # passes — the same PER-SHARD predicate the executor sizes windows
    # with); "host_window" keeps them in host RAM and streams device_put
    # windows (cfk_tpu.offload.windowed — sharded too, ISSUE 12).  The
    # resolver's enumeration axis is the predicate itself, so oversized
    # problems resolve to host_window instead of promising a resident
    # table that cannot exist.
    "offload_tier": ("device", "host_window"),
    # Inner-ring size of the hierarchical exchange (ISSUE 12 — promoted
    # from an ALSConfig-only knob so the cost model can SEE the hierarchy
    # it prices).  0 = auto: the device's ici_domain (execution resolves
    # devices-per-process via spmd.resolve_ici_group — the same physical
    # quantity).  An explicit ALSConfig.ici_group pins it, so the model
    # prices the hierarchy that actually runs; adding this field also
    # rotates the autotune cache's plan-field-set digest, invalidating
    # every pre-ici_group winner (they carry no decision for it).
    "ici_group": (0,),
    # Host staging engine mode of the host_window tier (ISSUE 13):
    # "pool" overlaps the per-(shard, window) host staging work across
    # shards and windows on a bounded thread pool (the default execution
    # mode — the ALX per-shard transfer pipeline's host half), "serial"
    # is the PR 10/11 one-thread double buffer.  crc-identical across
    # the knob; the cost model prices only how much of the
    # host_window_pcie term stays exposed.  ALSConfig.staging always
    # pins it (a concrete dataclass default, like overlap), and its
    # existence rotates the autotune field-set digest — pre-staging
    # winners carry no decision for it and must miss.
    "staging": ("pool", "serial"),
    # Skew-aware hot-row device cache of the host_window tier (ISSUE
    # 15): the TOTAL top-referenced fixed-table rows (both sides) kept
    # device-resident at the staging dtype, so windows stage only their
    # cold delta.  0 = off (the PR 12 full-staging engine).  A free
    # field resolves through the resolver's budget-predicate axis: the
    # ~10% power-law target when the reservation fits the headroom
    # (offload.budget.planner_hot_rows), 0 otherwise — "nonzero only
    # when the budget admits".  The executor re-resolves the exact count
    # against the real coverage-curve knee at window-plan build time;
    # the plan's value is the budget-admitted TARGET the cost model
    # priced.  crc-identical across the knob; adding the field rotates
    # the autotune digest (pre-hot winners carry no decision for it).
    "hot_rows": (0,),
}

# Semantic version of the plan field SET (ISSUE 19).  The autotune cache
# digests the sorted field NAMES, which rotates on any field add — but a
# feasibility change that adds no field (bucketed × host_window becoming
# resolvable for the implicit family) would leave stale winners readable
# under the old semantics.  Bump this whenever the feasible set of an
# EXISTING field changes; autotune folds it into the field-set digest so
# every pre-change winner reads as a miss.
PLAN_FIELDSET_VERSION = 2

# Fields whose pins are free-form positive ints (the candidate tuples
# above are only the resolver's enumeration grid for UNPINNED fields).
_NUMERIC_FIELDS = ("chunk_elems", "serve_batch_quantum", "serve_tile_m",
                   "ici_group", "hot_rows", "clusters", "probe_clusters")
# Numeric fields where 0 is a legal pin (an explicit OFF, not "unset"):
# hot_rows=0 pins the full-staging engine; clusters/probe_clusters=0 is
# the exact serve mode's (only) value.
_ZERO_OK_FIELDS = ("hot_rows", "clusters", "probe_clusters")


@dataclasses.dataclass(frozen=True)
class ProblemShape:
    """The workload the plan is resolved for.

    ``kind="train"`` describes one ALS(-WR/iALS) half-iteration pair;
    ``kind="serve"`` one top-K scoring stream.  ``gather_rows`` optionally
    carries the MEASURED layout-aware gather-slot count (padded cells per
    width class) when real blocks exist — the cost model falls back to
    per-layout padding heuristics otherwise."""

    num_users: int
    num_movies: int
    nnz: int
    rank: int
    num_shards: int = 1
    implicit: bool = False
    algorithm: str = "als"
    sweeps: int = 1
    dtype: str = "float32"  # factor storage dtype (not a plan knob)
    tile_rows: int = 16
    kind: Literal["train", "serve"] = "train"
    serve_k: int = 100
    gather_rows: float | None = None

    def __post_init__(self) -> None:
        for f in ("num_users", "num_movies", "nnz", "rank", "num_shards"):
            if getattr(self, f) < 1:
                raise ValueError(f"{f} must be >= 1, got {getattr(self, f)}")
        if self.kind not in ("train", "serve"):
            raise ValueError(f"unknown shape kind {self.kind!r}")

    def shape_class(self) -> str:
        """The autotune cache's shape key: sizes bucketed to powers of two
        (a 162k-user and a 180k-user problem share a tuned plan; rank and
        shard count are exact — they change kernel shapes)."""
        b = lambda n: 1 << max(int(n) - 1, 0).bit_length()
        tag = (f"{self.kind}:u{b(self.num_users)}:m{b(self.num_movies)}:"
               f"n{b(self.nnz)}:k{self.rank}:s{self.num_shards}:"
               f"{self.algorithm}")
        if self.implicit:
            tag += ":implicit"
        if self.kind == "serve":
            tag += f":top{self.serve_k}"
        return tag


# TPU v5e reference numbers (utils.roofline's measured/spec constants).
_V5E = dict(hbm_bytes=16 * 1024**3, hbm_bytes_per_s=819e9,
            peak_flops=197e12, gather_rows_per_s=600e6)


@dataclasses.dataclass(frozen=True)
class DeviceSpec:
    """What the cost model knows about the hardware.

    ``kind="cpu"`` carries NOMINAL numbers: off-TPU the model is used only
    to RANK candidate plans (CI, the plan CLI), never as an absolute
    latency claim — the ratios (gather is row-slot-bound, fusion saves the
    A-batch round trip, quantization shrinks the scan) are what transfer.
    """

    kind: str  # "tpu" | "cpu" | "gpu"
    name: str = ""
    num_devices: int = 1
    hbm_bytes: float = _V5E["hbm_bytes"]
    hbm_bytes_per_s: float = _V5E["hbm_bytes_per_s"]
    peak_flops: float = _V5E["peak_flops"]
    gather_rows_per_s: float = _V5E["gather_rows_per_s"]
    vmem_bytes: int = 96 << 20  # the gram kernels' resident-output cap
    smem_bytes: int = 512 << 10  # _GATHER_SMEM_BYTES_CAP
    # Fabric tiers the offload/hier-exchange terms price (ISSUE 11).
    # ALL THREE ARE OFF-TPU GUESSES pending the on-TPU validation backlog
    # (ROADMAP): PCIe ≈ gen4 ×16 effective, ICI ≈ one v5e link pair,
    # DCN ≈ per-host data-center NIC share.  Off-TPU the model only RANKS,
    # so the ratios (PCIe ≪ HBM, DCN ≪ ICI) are what matter.
    pcie_bytes_per_s: float = 32e9
    ici_bytes_per_s: float = 90e9
    dcn_bytes_per_s: float = 25e9
    # Devices per ICI domain (host): the hier-ring cost term's inner-ring
    # size.  0 = all devices share one ICI domain (single host).
    ici_domain: int = 0

    # Nominal host-CPU numbers: a memory-bandwidth-bound machine with no
    # dedicated gather engine (rows/s set high enough never to bind —
    # every fetch is just bytes), so the bytes floors dominate the
    # ranking off-TPU.  That matches what this container MEASURES
    # (bf16/int8 tables measurably cheaper per PR 7/8 rows); the flops
    # number is deliberately generous so compute never masks the byte
    # terms the host ranking exists to compare.
    _CPU = dict(hbm_bytes=32 * 1024**3, hbm_bytes_per_s=50e9,
                peak_flops=2e13, gather_rows_per_s=2e9)

    @classmethod
    def nominal(cls, kind: str, name: str = "", num_devices: int = 1,
                ) -> "DeviceSpec":
        """A spec for ``kind`` with the reference numbers: v5e for
        ``"tpu"``, the nominal byte-bound host numbers otherwise."""
        extra = {} if kind == "tpu" else dict(cls._CPU)
        return cls(kind=kind, name=name or kind,
                   num_devices=num_devices, **extra)

    @classmethod
    def detect(cls) -> "DeviceSpec":
        """The current jax backend, as a spec (see ``nominal``)."""
        import jax

        backend = jax.default_backend()
        dev = jax.devices()[0]
        return cls.nominal(
            backend,
            name=getattr(dev, "device_kind", backend),
            num_devices=len(jax.devices()),
        )

    def fingerprint(self) -> str:
        """The autotune cache's device key: a measured winner is only
        trusted on the hardware (and device count) it was measured on."""
        name = self.name.replace(" ", "_") or self.kind
        return f"{self.kind}:{name}:x{self.num_devices}"


@dataclasses.dataclass(frozen=True)
class PlanConstraints:
    """Optional pins, one per plan field.  ``None`` = the resolver is free
    to choose; a concrete value fixes that plan field (and is validated
    against the feasibility gates — an impossible pin raises
    ``PlanConstraintError`` instead of silently un-pinning)."""

    layout: str | None = None
    exchange: str | None = None
    chunk_elems: int | None = None
    fused_epilogue: bool | None = None
    in_kernel_gather: bool | None = None
    overlap: bool | None = None
    reg_solve_algo: str | None = None
    table_dtype: str | None = None
    solver: str | None = None
    gram_backend: str | None = None
    serve_batch_quantum: int | None = None
    serve_tile_m: int | None = None
    serve_mode: str | None = None
    clusters: int | None = None
    probe_clusters: int | None = None
    offload_tier: str | None = None
    ici_group: int | None = None
    staging: str | None = None
    hot_rows: int | None = None

    def __post_init__(self) -> None:
        for f, candidates in PLAN_FIELDS.items():
            v = getattr(self, f)
            if v is None:
                continue
            if f in _NUMERIC_FIELDS:
                # Numeric pins accept any positive value (the candidate
                # tuple is only the resolver's enumeration grid); the
                # _ZERO_OK_FIELDS additionally accept an explicit 0.
                floor = 0 if f in _ZERO_OK_FIELDS else 1
                if not isinstance(v, int) or v < floor:
                    raise PlanConstraintError(
                        f"constraint {f}={v!r} must be a positive int"
                        + (" (or 0 = off)" if floor == 0 else "")
                    )
            elif v not in candidates:
                raise PlanConstraintError(
                    f"constraint {f}={v!r} is not a known value; "
                    f"candidates: {candidates}"
                )

    def pinned(self) -> dict:
        return {f: getattr(self, f) for f in PLAN_FIELDS
                if getattr(self, f) is not None}

    def merge(self, other: "PlanConstraints") -> "PlanConstraints":
        """Combine two pin sets; the same field pinned to two different
        values is a CONFLICT (loud error naming both), not a silent win."""
        out = {}
        for f in PLAN_FIELDS:
            a, b = getattr(self, f), getattr(other, f)
            if a is not None and b is not None and a != b:
                raise PlanConstraintError(
                    f"conflicting constraints: {f}={a!r} vs {f}={b!r} — "
                    "unpin one side (an ALSConfig knob and an explicit "
                    "constraint must agree)"
                )
            out[f] = a if a is not None else b
        return PlanConstraints(**out)


def constraints_from_config(config) -> PlanConstraints:
    """An ``ALSConfig``'s explicit knobs, as pinned plan constraints.

    Concrete config fields pin (``layout``, ``table_dtype``, ``overlap``,
    ``exchange`` — their dataclass defaults are real values, so the
    default config pins them to today's behavior); tri-state knobs
    (``fused_epilogue``/``in_kernel_gather`` ``None``, ``reg_solve_algo``/
    ``solver`` ``"auto"``) stay free — those are exactly the knobs whose
    downstream resolution is bit-exact across choices, which is what keeps
    the default path bit-identical while the resolver prices them."""
    return PlanConstraints(
        layout=config.layout,
        exchange=config.exchange if config.exchange != "auto" else None,
        chunk_elems=(config.chunk_cells()
                     if config.hbm_chunk_elems is not None else None),
        fused_epilogue=config.fused_epilogue,
        in_kernel_gather=config.in_kernel_gather,
        overlap=bool(config.overlap),
        reg_solve_algo=(None if config.reg_solve_algo == "auto"
                        else config.reg_solve_algo),
        table_dtype=config.table_dtype,
        solver=None if config.solver == "auto" else config.solver,
        offload_tier=(None
                      if getattr(config, "offload_tier", "auto") == "auto"
                      else config.offload_tier),
        ici_group=getattr(config, "ici_group", None),
        # staging always pins (ISSUE 13): 'auto' resolves to the pool
        # deterministically (offload.staging.resolve_staging), so the
        # plan records the engine that actually runs.
        staging=("pool"
                 if getattr(config, "staging", "auto") == "auto"
                 else config.staging),
        # hot_rows: None (auto) stays FREE — the resolver's budget-
        # predicate axis decides; an explicit 0 (off) or count pins.
        hot_rows=getattr(config, "hot_rows", None),
    )


@dataclasses.dataclass(frozen=True)
class ExecutionPlan:
    """One fully-resolved execution: every knob concrete, plus the kernel
    backend per slot and the set of fields that were pinned (vs chosen by
    the cost model).  Frozen + hashable — safe as a jit-static and as a
    cache value."""

    layout: str
    exchange: str
    chunk_elems: int
    fused_epilogue: bool
    in_kernel_gather: bool
    overlap: bool
    reg_solve_algo: str
    table_dtype: str
    solver: str
    gram_backend: str
    serve_batch_quantum: int = 8
    serve_tile_m: int = 512
    # Two-stage clustered retrieval (ISSUE 16): "exact" | "two_stage",
    # with the k-means index size and per-user probe count (0/0 in exact
    # mode).  Exact is the un-disableable fallback: the engine keeps the
    # PR 8 scan path alive regardless of this field and degrades to it
    # on index corruption or bounded-staleness overrun.
    serve_mode: str = "exact"
    clusters: int = 0
    probe_clusters: int = 0
    # Out-of-core tier (ISSUE 11): "device" = HBM-resident factor tables,
    # "host_window" = host-RAM stores + device_put-pipelined windows
    # (cfk_tpu.offload) — gated by offload.budget's per-shard fit
    # predicate.
    offload_tier: str = "device"
    # Hierarchical-exchange inner-ring size (ISSUE 12); 0 = the device's
    # ICI domain (spmd.resolve_ici_group's physical default).
    ici_group: int = 0
    # Host staging engine of the host_window tier (ISSUE 13): "pool"
    # (concurrent per-(shard, window) staging, the default) | "serial".
    staging: str = "pool"
    # Hot-row device cache target of the host_window tier (ISSUE 15):
    # total resident rows across both sides (0 = off — the device tier's
    # only value, and the budget-refused resolution).
    hot_rows: int = 0
    # (slot, backend) pairs — "mosaic_tpu" | "xla_emulation" per kernel
    # slot (cfk_tpu.plan.registry.KERNEL_SLOTS).
    kernels: tuple = ()
    pinned: frozenset = frozenset()

    def knob_dict(self) -> dict:
        return {f: getattr(self, f) for f in PLAN_FIELDS}

    def kernel_backends(self) -> dict:
        return dict(self.kernels)

    def half_step_kwargs(self, config=None) -> dict:
        """The trainer-facing knob dict — the ONE seam the trainers read
        instead of poking ``ALSConfig`` fields directly.

        For a knob the caller's config left deferred (not pinned), this
        returns the config's own sentinel (``None``/``"auto"``) rather
        than the resolved concrete value: the downstream half-steps then
        resolve through the same process defaults as before the planner,
        so jit cache keys, perf_lab patch points, and bit-exactness are
        untouched.  The resolved value is still visible in ``knob_dict``
        and in the provenance record.  A PINNED knob threads concrete.
        """
        pin = self.pinned
        return dict(
            overlap=self.overlap if "overlap" in pin else None,
            fused_epilogue=(self.fused_epilogue
                            if "fused_epilogue" in pin else None),
            in_kernel_gather=(self.in_kernel_gather
                              if "in_kernel_gather" in pin else None),
            reg_solve_algo=(self.reg_solve_algo
                            if "reg_solve_algo" in pin else "auto"),
            table_dtype=self.table_dtype,
            solver=self.solver if "solver" in pin else "auto",
        )

    def summary(self) -> str:
        """Compact one-line description (bench rows, metrics notes)."""
        kb = ",".join(f"{s}={b.split('_')[0]}" for s, b in self.kernels)
        tier = ("" if self.offload_tier == "device"
                else f"tier={self.offload_tier} ")
        if self.ici_group:
            tier += f"ici={self.ici_group} "
        if self.offload_tier == "host_window" and self.staging != "pool":
            tier += f"stage={self.staging} "
        if self.offload_tier == "host_window" and self.hot_rows:
            tier += f"hot={self.hot_rows} "
        serve = f"serve_q={self.serve_batch_quantum}"
        # Provenance must NAME the serve mode (ISSUE 16): a bench row's
        # plan column says which retrieval path the row executed.
        if self.serve_mode != "exact":
            serve += (f" serve={self.serve_mode} c={self.clusters}"
                      f" probe={self.probe_clusters}")
        return (f"{tier}{self.layout}/{self.exchange} "
                f"chunk={self.chunk_elems} "
                f"fused={'on' if self.fused_epilogue else 'off'} "
                f"gather={'fused' if self.in_kernel_gather else 'xla'} "
                f"overlap={'on' if self.overlap else 'off'} "
                f"algo={self.reg_solve_algo} table={self.table_dtype} "
                f"solver={self.solver} "
                f"{serve} [{kb}]")

    def as_dict(self) -> dict:
        d = self.knob_dict()
        d["kernels"] = list(map(list, self.kernels))
        d["pinned"] = sorted(self.pinned)
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "ExecutionPlan":
        d = dict(d)
        kernels = tuple((s, b) for s, b in d.pop("kernels", ()))
        pinned = frozenset(d.pop("pinned", ()))
        known = {f: d[f] for f in PLAN_FIELDS if f in d}
        missing = set(PLAN_FIELDS) - set(known)
        if missing:
            raise ValueError(f"plan dict missing fields: {sorted(missing)}")
        return cls(**known, kernels=kernels, pinned=pinned)


@dataclasses.dataclass
class PlanProvenance:
    """Where a plan came from and what it was believed/measured to cost.

    Recorded in every bench row and checkpoint manifest that executes
    under a plan, so a regression is attributable to the DECISION that
    caused it (model mis-ranking, stale cache, forced fallback), not just
    the symptom.  ``transitions`` accumulates mid-run plan changes — a
    recovery-ladder rung or a kernel-backend outage is a plan transition
    now, recorded with the same vocabulary."""

    plan: ExecutionPlan
    source: str  # "model" | "pinned" | "autotune" | "autotune-cache"
    est_cost_s: float | None = None
    measured_s: float | None = None
    cache: str | None = None  # "hit" | "miss" | None (no cache consulted)
    explain: tuple = ()  # (field, value, reason) rows from the resolver
    transitions: list = dataclasses.field(default_factory=list)

    def record_transition(self, reason: str, detail: str) -> dict:
        t = {"reason": reason, "detail": detail,
             "index": len(self.transitions)}
        self.transitions.append(t)
        return t

    def summary(self) -> str:
        bits = [f"source={self.source}"]
        if self.est_cost_s is not None:
            bits.append(f"est={self.est_cost_s:.4g}s")
        if self.measured_s is not None:
            bits.append(f"measured={self.measured_s:.4g}s")
        if self.cache is not None:
            bits.append(f"cache={self.cache}")
        return f"{self.plan.summary()} ({' '.join(bits)})"

    def as_row(self) -> dict:
        """The bench-row provenance column(s) — flat, JSON-friendly."""
        row = {
            "plan": self.plan.summary(),
            "plan_source": self.source,
        }
        if self.est_cost_s is not None:
            row["plan_est_s"] = round(self.est_cost_s, 6)
        if self.measured_s is not None:
            row["plan_measured_s"] = round(self.measured_s, 6)
        if self.cache is not None:
            row["plan_cache"] = self.cache
        if self.transitions:
            row["plan_transitions"] = json.dumps(self.transitions)
        return row

    def as_meta(self) -> dict:
        """The checkpoint-manifest provenance record."""
        return {
            "plan": self.plan.as_dict(),
            "plan_source": self.source,
            "plan_est_s": self.est_cost_s,
            "plan_measured_s": self.measured_s,
            "plan_cache": self.cache,
            "plan_transitions": list(self.transitions),
        }
