"""Per-plan cost estimation — ``utils/roofline``'s byte/flop accounting
extended to price CANDIDATE plans, not just the plan that ran.

``utils.roofline`` answers "how far is this measured iteration from the
hardware floor?".  The planner needs the prospective version: "what would
this iteration cost under THAT knob setting?" — so each term the roofline
charges (gather bytes per table dtype, per-width-class padded cells, the
fused epilogue's removed A-batch round trip, the materialized gather
stream, ring payload bytes, the serve table scan) appears here as a
per-plan delta.  The total is an ESTIMATE for ranking plans (and for the
autotune mode's "measure the 2–3 nearest the optimum" trim); absolute
accuracy is neither promised nor needed — monotonicity in each knob is
(the matrix test in tests/test_plan.py pins the orderings that matter).

All terms are seconds on the given ``DeviceSpec``.  The breakdown dict is
what ``cfk_tpu plan --explain`` prints.
"""

from __future__ import annotations

import dataclasses
import math

from cfk_tpu.plan.spec import DeviceSpec, ExecutionPlan, ProblemShape

# Gather-slot inflation per layout when no measured ``gather_rows`` is
# available: padding slots fetch rows like real slots (the engine charges
# the slot).  tiled ≈ 1.26 (the measured tile-padding share at the full
# Netflix build), bucketed ≈ 1.57 (measured at the ML-25M build, ROADMAP
# item 4), segment = exact O(nnz), padded = the rectangle pads every
# entity to the max degree — unknowable without the data, call it 3×
# (power-law data routinely exceeds it; the pin exists so the model
# PENALIZES padded at scale, which is the decision that matters).
_GATHER_PAD_FACTOR = {
    "tiled": 1.26,
    "bucketed": 1.57,
    "segment": 1.0,
    "padded": 3.0,
}

# Interpret-mode pallas off-TPU is a test-only path, orders of magnitude
# slow — the model must never pick it on a cpu/gpu device.
_OFFCHIP_PALLAS_SOLVER_PENALTY = 50.0
# XLA's batched-Cholesky custom calls measured ~1.7× the fused pallas
# solve end-to-end on TPU (BASELINE round 2).
_TPU_CHOLESKY_PENALTY = 1.7


@dataclasses.dataclass(frozen=True)
class PlanCost:
    """Estimated seconds for one unit of work (a full train iteration, or
    one serve batch at the plan's quantum) plus the term breakdown."""

    seconds: float
    unit: str  # "s/iter" | "s/batch"
    terms: dict

    def explain_lines(self) -> list[str]:
        out = []
        for name, val in sorted(self.terms.items(), key=lambda t: -t[1]):
            out.append(f"{name:28s} {val:.6f} s")
        out.append(f"{'TOTAL (' + self.unit + ')':28s} {self.seconds:.6f} s")
        return out


def gather_rows_for(shape: ProblemShape, plan: ExecutionPlan) -> float:
    """Layout-aware gather-slot count per iteration (both sides), before
    the sweeps multiplier — the measured count when the shape carries one
    (real blocks exist), the per-layout heuristic otherwise."""
    if shape.gather_rows is not None:
        return float(shape.gather_rows)
    return 2.0 * shape.nnz * _GATHER_PAD_FACTOR[plan.layout]


def train_iteration_cost(shape: ProblemShape, device: DeviceSpec,
                         plan: ExecutionPlan) -> PlanCost:
    """One full ALS iteration (both half-steps) under ``plan``."""
    from cfk_tpu.utils.roofline import (
        als_iteration_cost,
        table_gather_bytes_per_row,
    )

    k = shape.rank
    factor_bytes = 2 if shape.dtype == "bfloat16" else 4
    rows = gather_rows_for(shape, plan) * max(shape.sweeps, 1)
    base = als_iteration_cost(
        shape.nnz, shape.num_users, shape.num_movies, k,
        factor_bytes=factor_bytes, implicit=shape.implicit,
        table_dtype=plan.table_dtype,
        gather_rows=gather_rows_for(shape, plan), sweeps=shape.sweeps,
    )
    shards = max(shape.num_shards, 1)
    bw = device.hbm_bytes_per_s
    terms: dict[str, float] = {}

    # The three floors, per shard (work divides; the roofline model's
    # min-bytes already include the gather bytes).
    compute_s = base.model_flops / shards / device.peak_flops
    if plan.solver == "cholesky":
        # the solve share of the flops pays the latency-bound custom call
        solve_flops = (shape.num_users + shape.num_movies) * (
            k**3 / 3.0 + 2.0 * k**2
        )
        penalty = (_TPU_CHOLESKY_PENALTY if device.kind == "tpu" else 1.0)
        compute_s += solve_flops * (penalty - 1.0) / shards / device.peak_flops
    if plan.solver == "pallas" and device.kind != "tpu":
        compute_s *= _OFFCHIP_PALLAS_SOLVER_PENALTY
    if plan.reg_solve_algo == "gj":
        # GJ's k³ elimination vs LU's k³/3 — only the solve term triples.
        solve_flops = (shape.num_users + shape.num_movies) * (k**3 / 3.0)
        compute_s += 2.0 * solve_flops / shards / device.peak_flops
    terms["compute"] = compute_s
    terms["hbm_min_bytes"] = base.min_hbm_bytes / shards / bw
    terms["gather_floor"] = base.gather_bound_s(
        rows_per_s=device.gather_rows_per_s, bandwidth=bw,
    ) / shards

    floor = max(terms["compute"], terms["hbm_min_bytes"],
                terms["gather_floor"])
    total = floor

    extra = 0.0
    if not plan.in_kernel_gather or plan.gram_backend != "pallas":
        # The materialized [C, k] stream: every gathered row is written to
        # HBM and read back once per side.
        stream_bytes = 2.0 * rows * k * factor_bytes
        extra += stream_bytes / shards / bw
        terms["xla_gather_stream"] = stream_bytes / shards / bw
    if not plan.fused_epilogue or plan.gram_backend != "pallas":
        # The per-chunk [Ec, k, k] A-batch round trip the fusion deletes.
        ents = shape.num_users + shape.num_movies
        abatch_bytes = ents * (k * k + k) * 4.0 * 2
        extra += abatch_bytes / shards / bw
        terms["split_epilogue_abatch"] = abatch_bytes / shards / bw

    # Exchange: bytes every half-iteration moves between shards.  The
    # ring rotates (S-1)/S of the fixed table through each device; the
    # all_gather replicates (S-1)/S of it inbound.  Payload cells follow
    # the TABLE dtype (quantized ring payloads, PR 7).
    if shards > 1:
        row_bytes = table_gather_bytes_per_row(
            k, plan.table_dtype, factor_bytes
        )
        table_rows = shape.num_users + shape.num_movies  # both halves
        wire = table_rows * row_bytes * (shards - 1) / shards
        # Intra-domain legs of EVERY exchange are modeled at HBM-bandwidth
        # order (the pre-planner convention — `ici_bytes_per_s` is kept on
        # the DeviceSpec for the on-TPU recalibration, ROADMAP backlog
        # item (f)); only DOMAIN-CROSSING transfers pay `dcn_bytes_per_s`,
        # so the fabric model is consistent across the three exchanges and
        # the hierarchy's advantage is exactly its fewer slow-fabric hops.
        multi_host = bool(device.ici_domain
                          and shards > device.ici_domain)
        if plan.exchange == "hier_ring":
            # Of the S-1 transfers, O·(I-1) rotate inside the domain and
            # O-1 hop the DCN.  ici_domain=0 means one domain (all inner)
            # — the schedule and the cost degenerate to the flat ring's.
            # ``ici_group`` is a real plan field now (ISSUE 12): an
            # explicit ALSConfig.ici_group pin reaches the model here, so
            # it prices the hierarchy that actually runs; 0 (auto) falls
            # back to the DEVICE topology (ici_domain), the same physical
            # quantity execution's resolve_ici_group defaults to.
            inner = plan.ici_group or device.ici_domain or shards
            inner = inner if shards % inner == 0 else shards
            outer = shards // inner
            inner_frac = (outer * (inner - 1)) / max(shards - 1, 1)
            exch = (wire * inner_frac / bw
                    + wire * (1.0 - inner_frac) / device.dcn_bytes_per_s)
        elif plan.exchange == "ring" and multi_host:
            # Bulk-synchronous shift-by-1: EVERY ring step is gated by
            # its domain-boundary edge, so the whole rotation runs at DCN
            # speed — the inversion hier_ring exists to fix.
            exch = wire / device.dcn_bytes_per_s
        else:
            exch = wire / bw
            if multi_host:
                # all_gather's inbound share crossing domains.
                exch += (wire / device.ici_domain
                         / device.dcn_bytes_per_s)
        # Overlap hides the exchange behind compute up to the floor,
        # serial schedules expose it.
        if plan.overlap:
            exposed = max(0.0, exch - floor * 0.5)
        else:
            exposed = exch
        terms["exchange_exposed"] = exposed
        extra += exposed

    # Out-of-core tier (ISSUE 11/12): every half-iteration stages the
    # fixed side's windows over PCIe — the full table once per half-step,
    # plus the duplication of rows shared between adjacent windows (~15%
    # on power-law data) — DIVIDED across shards: each shard stages only
    # the window residual its own chunks reference, concurrently on its
    # own host's PCIe (the DCN share of remote-shard rows is priced by
    # the exchange term above, unchanged).  Staged cells follow the
    # STAGING dtype (ISSUE 12): bf16 halves, int8 ships the (1-byte
    # codes + one f32 scale per row) pair — a quarter, the honest bytes
    # the executor's ``offload_staged_mb`` now records.
    #
    # Hiding (ISSUE 13): the POOLED staging engine overlaps the whole
    # host pipeline (gather, quantize, checksum, device_put issue)
    # across shards and windows on worker threads, so staging hides
    # under compute up to the FULL floor; the serial double buffer only
    # overlaps one window at a time on the consuming thread and — like
    # the exchange term — is credited half the floor, and only when the
    # chunk pipelines overlap at all.  (The donation reclaim is a
    # MEMORY credit, not a time term: it lands in offload.budget —
    # larger admitted windows, the ×1 accumulator reservation, and the
    # resident-tier solve-output credit the tier predicate consumes.)
    if plan.offload_tier == "host_window":
        stage_itemsize = {"bfloat16": 2.0, "int8": 1.0}.get(
            plan.table_dtype, float(factor_bytes)
        )
        row_overhead = 4.0 if plan.table_dtype == "int8" else 0.0
        stage_bytes_per_row = k * stage_itemsize + row_overhead
        window_dup = 1.15
        pcie = ((shape.num_users + shape.num_movies) * stage_bytes_per_row
                * window_dup / shards / device.pcie_bytes_per_s)
        # Hot-row cache (ISSUE 15): the term scales by the COLD
        # reference fraction.  The resolver cannot see the real skew, so
        # the coverage of a top-f head is estimated with the Zipf(1)
        # harmonic mass H_f/H_n ≈ ln(1+f)/ln(1+n) — the curve the
        # counter-based synth generator (and Netflix-like data) follows
        # closely enough to RANK hot against cold staging; the executor
        # meters the real per-window coverage (offload_hot_coverage) and
        # the bench hot-A/B row records the measured cut.  Floored so a
        # hot plan never looks free: the cold tail and the chunk arrays
        # still cross PCIe every window.
        if plan.hot_rows > 0:
            import math

            n = shape.num_users + shape.num_movies
            f = min(plan.hot_rows, n)
            coverage = math.log1p(f) / max(math.log1p(n), 1e-9)
            pcie *= max(1.0 - coverage, 0.05)
        if plan.staging == "pool":
            exposed_pcie = max(0.0, pcie - floor)
        elif plan.overlap:
            exposed_pcie = max(0.0, pcie - floor * 0.5)
        else:
            exposed_pcie = pcie
        terms["host_window_pcie"] = exposed_pcie
        extra += exposed_pcie
        # Implicit out-of-core (ISSUE 19): each half-iteration also
        # streams the fixed side's FULL table once more for the
        # global-Gram reduction (the [k,k] accumulator's block feed) —
        # a second pass at the staging dtype, never hidden by the hot
        # cache (the Gram must see every row) and serial with compute
        # today (the accumulator is a device-side dependency of every
        # window's solve, so only the double buffer overlaps it).
        if shape.implicit:
            gram_pcie = ((shape.num_users + shape.num_movies)
                         * stage_bytes_per_row / shards
                         / device.pcie_bytes_per_s)
            terms["host_window_gram_pcie"] = gram_pcie
            extra += gram_pcie

    # Chunking overhead: each chunk pays a fixed dispatch cost (scan step
    # + DMA setup), so tiny chunks are overhead-bound; oversized chunks
    # pay transient-gather HBM pressure (the measured r4 knee — gather
    # rate falls as the per-chunk working set grows past ~256 MB).
    chunks = max(1.0, rows / max(plan.chunk_elems, 1))
    dispatch = chunks * 20e-6
    terms["chunk_dispatch"] = dispatch
    extra += dispatch
    chunk_bytes = plan.chunk_elems * k * factor_bytes
    if chunk_bytes > 256 << 20:
        pressure = terms["gather_floor"] * 0.25
        terms["chunk_gather_pressure"] = pressure
        extra += pressure

    return PlanCost(seconds=total + extra, unit="s/iter", terms=terms)


# Plan recall constraint of the two-stage serve mode (ISSUE 16): a
# two_stage candidate whose MODELED recall@K falls below this floor is
# never enumerated, and a pinned (clusters, probe_clusters) below it
# raises at resolution.  The measured contract lives in bench/tests —
# recall@K vs the exact oracle is a first-class column; this model only
# gates what the resolver may promise.
SERVE_MIN_RECALL = 0.95

# Recall-curve steepness of the probe model below.  Calibrated so the IVF
# rule of thumb (probe ≈ √clusters reaches high recall on clusterable
# factor tables) sits just above the 0.95 floor: probe = 0.75·√clusters
# models to 0.95, probe = √clusters to ~0.98.
_RECALL_ALPHA = 4.0


def estimated_recall(clusters: int, probe_clusters: int) -> float:
    """Modeled recall@K of probing ``probe_clusters`` of ``clusters``.

    ``1 − exp(−α·probe/√clusters)``: monotone up in the probe count, down
    in the cluster count at a fixed probe — the classic IVF trade surface
    (finer index → fewer bytes per probe but more probes for the same
    recall).  Probing every cluster is exact coverage by construction."""
    c = int(clusters)
    if c <= 0:
        return 1.0  # exact mode: no index, full scan
    p = min(int(probe_clusters), c)
    if p <= 0:
        return 0.0
    if p >= c:
        return 1.0
    return 1.0 - math.exp(-_RECALL_ALPHA * p / math.sqrt(c))


def serve_batch_cost_for(shape: ProblemShape, device: DeviceSpec,
                         plan: ExecutionPlan) -> PlanCost:
    """One coalesced serve batch at the plan's quantum — reported per
    REQUEST-slot second so quanta are comparable: the table scan amortizes
    over the batch, which is exactly the lever the quantum moves.

    ``serve_mode="two_stage"`` prices the clustered path instead: the
    centroid scan plus the EXPECTED batch-union shortlist gather
    (``roofline.serve_batch_cost``) — so two_stage wins exactly where the
    byte model says the centroids + shortlist undercut the full scan,
    and loses where the batch-union approaches the table (large quanta
    over a coarse index)."""
    from cfk_tpu.utils.roofline import serve_batch_cost

    b = plan.serve_batch_quantum
    cost = serve_batch_cost(
        shape.num_movies, shape.rank, b, shape.serve_k,
        table_dtype=plan.table_dtype, serve_mode=plan.serve_mode,
        clusters=plan.clusters, probe_clusters=plan.probe_clusters,
    )
    shards = max(shape.num_shards, 1)
    flops_s = cost.model_flops / shards / device.peak_flops
    bytes_s = cost.hbm_bytes / shards / device.hbm_bytes_per_s
    batch_s = max(flops_s, bytes_s)
    # Coalescing wait: a batch cannot dispatch before it fills (or the
    # server's poll quantum passes); model half a batch service time of
    # queueing so unbounded quanta do not look free.
    wait_s = batch_s * 0.5
    per_request = (batch_s + wait_s) / b
    terms = {
        "score_flops": flops_s,
        ("shortlist_gather_bytes" if plan.serve_mode == "two_stage"
         else "table_scan_bytes"): bytes_s,
        "coalesce_wait": wait_s,
    }
    # Ranked PER REQUEST-SLOT: quanta are only comparable on what one
    # request costs — per batch, a bigger quantum always looks worse even
    # though it amortizes the table scan, which is the whole lever.
    return PlanCost(seconds=per_request, unit="s/request", terms=terms)


def plan_cost(shape: ProblemShape, device: DeviceSpec,
              plan: ExecutionPlan) -> PlanCost:
    if shape.kind == "serve":
        return serve_batch_cost_for(shape, device, plan)
    return train_iteration_cost(shape, device, plan)
