"""Autotune: measure the plans nearest the model's optimum, cache the winner.

The cost model ranks; hardware decides.  ``autotune`` takes the model's
top ``top_n`` candidates (always including the legacy-default "pinned"
plan, so the tuned winner can never be worse than the pre-planner
behavior on the measured workload), times each with the injected
``measure(plan) -> seconds`` callable on a TRIMMED workload, and persists
the winner in a JSON store keyed by

    (shape-class, device fingerprint, cfk_tpu version)

— a stale key on any axis (new problem scale, different chip/count, code
upgrade) is a MISS, never a silently-wrong hit.  Plan provenance records
model-estimated and measured cost plus hit/miss so a regression is
attributable to the decision.

Measurement is always opt-in: trainers consult the cache but never
measure (warm it offline with ``cfk_tpu plan --autotune`` or
``perf_lab --plan autotune``).
"""

from __future__ import annotations

import json
import os
import tempfile
import time
import zlib

from cfk_tpu.plan.cost import plan_cost
from cfk_tpu.plan.spec import (
    PLAN_FIELDS,
    PLAN_FIELDSET_VERSION,
    DeviceSpec,
    ExecutionPlan,
    PlanConstraints,
    PlanProvenance,
    ProblemShape,
)

_SCHEMA = 1
DEFAULT_CACHE_PATH = os.path.join(
    os.path.expanduser("~"), ".cache", "cfk_tpu", "plan_cache.json"
)


def cache_key(shape: ProblemShape, device: DeviceSpec,
              constraints: PlanConstraints | None = None) -> str:
    from cfk_tpu import __version__

    # The PLAN-FIELD SET is part of the key (ISSUE 11): a winner tuned
    # before a new plan field existed (e.g. offload_tier) carries no
    # decision for it, so it must read as a MISS — not silently resolve
    # the new knob to whatever from_dict would default.  crc of the
    # sorted field names: stable per schema, changes with any field add.
    # PLAN_FIELDSET_VERSION folds in semantic changes to EXISTING fields
    # (ISSUE 19: bucketed × host_window became resolvable) so winners
    # tuned under the old feasible set also miss.
    fields_tag = zlib.crc32(
        (f"v{PLAN_FIELDSET_VERSION}|"
         + "|".join(sorted(PLAN_FIELDS))).encode()
    )
    key = (f"{shape.shape_class()}|{device.fingerprint()}|v{__version__}"
           f"|p{fields_tag:08x}")
    pins = (constraints or PlanConstraints()).pinned()
    if pins:
        # The pins are part of the tuning PROBLEM: a winner measured with
        # table_dtype free must never answer a query that pinned it (the
        # cached plan would override an explicit config knob — including
        # combinations the config layer refuses outright).
        key += "|" + ",".join(f"{f}={pins[f]}" for f in sorted(pins))
    return key


class PlanCache:
    """The JSON winner store.  Load-on-read, atomic rewrite-on-put; a
    corrupt or wrong-schema file reads as empty (autotune re-measures —
    the cache is an optimization, never a correctness dependency)."""

    def __init__(self, path: str | None = None) -> None:
        self.path = path or DEFAULT_CACHE_PATH

    def _load(self) -> dict:
        try:
            with open(self.path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            return {}
        if not isinstance(doc, dict) or doc.get("schema") != _SCHEMA:
            return {}
        entries = doc.get("entries")
        return entries if isinstance(entries, dict) else {}

    def get(self, key: str) -> dict | None:
        entry = self._load().get(key)
        if not isinstance(entry, dict) or "plan" not in entry:
            return None
        return entry

    def put(self, key: str, plan: ExecutionPlan, *, measured_s: float,
            model_s: float) -> None:
        entries = self._load()
        entries[key] = {
            "plan": plan.as_dict(),
            "measured_s": measured_s,
            "model_s": model_s,
            "saved_at": time.strftime("%Y-%m-%dT%H:%M:%S"),
        }
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        fd, tmp = tempfile.mkstemp(
            dir=os.path.dirname(self.path) or ".", suffix=".tmp"
        )
        try:
            with os.fdopen(fd, "w") as f:
                json.dump({"schema": _SCHEMA, "entries": entries}, f,
                          indent=1, sort_keys=True)
            os.replace(tmp, self.path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise


def autotune(shape: ProblemShape, device: DeviceSpec | None = None,
             constraints: PlanConstraints | None = None, *,
             cache_path: str | None = None, measure=None, top_n: int = 3,
             ) -> tuple[ExecutionPlan, PlanProvenance]:
    """Resolve via the measured-winner cache (see module docstring).

    ``measure(plan) -> seconds`` runs the trimmed workload; None means
    cache-consult only — a miss falls back to the model's choice with
    ``cache="miss"`` recorded (the trainer-entry mode)."""
    from cfk_tpu.plan.resolver import rank_plans
    from cfk_tpu.plan.resolver import plan as _plan

    device = device or DeviceSpec.detect()
    constraints = constraints or PlanConstraints()
    cache = PlanCache(cache_path)
    key = cache_key(shape, device, constraints)
    hit = cache.get(key)
    if hit is not None:
        try:
            ep = ExecutionPlan.from_dict(hit["plan"])
        except (ValueError, TypeError):
            ep = None  # stale/corrupt entry: treat as miss
        # Belt over the keyed braces: a hit must still AGREE with every
        # current pin (hand-edited/legacy cache files), or it is stale.
        # Pins absent from the stored plan's own ``pinned`` set were
        # soft-released at tune time (e.g. fused pinned on past the rank
        # cap) — those legitimately differ.
        if ep is not None and any(
            f in ep.pinned and getattr(ep, f) != v
            for f, v in constraints.pinned().items()
        ):
            ep = None
        if ep is not None:
            return ep, PlanProvenance(
                plan=ep, source="autotune-cache",
                est_cost_s=hit.get("model_s"),
                measured_s=hit.get("measured_s"), cache="hit",
            )
    if measure is None:
        ep, prov = _plan(shape, device, constraints, mode="model")
        prov.source = "model"
        prov.cache = "miss"
        return ep, prov
    ranked = rank_plans(shape, device, constraints)
    # The candidates: the model's top-N, plus the legacy-default plan so
    # the tuned winner is never worse than pre-planner behavior.
    pinned_ep, _ = _plan(shape, device, constraints, mode="pinned")
    cands = [ep for _, ep in ranked[:top_n]]
    if pinned_ep not in cands:
        cands.append(pinned_ep)
    results = []
    for ep in cands:
        s = float(measure(ep))
        results.append((s, ep))
    results.sort(key=lambda t: t[0])
    measured_s, winner = results[0]
    model_s = plan_cost(shape, device, winner).seconds
    cache.put(key, winner, measured_s=measured_s, model_s=model_s)
    return winner, PlanProvenance(
        plan=winner, source="autotune", est_cost_s=model_s,
        measured_s=measured_s, cache="miss",
        explain=tuple(
            ("candidate", round(s, 6), ep.summary()) for s, ep in results
        ),
    )


def trimmed_shape(shape: ProblemShape, *, max_nnz: int = 200_000,
                  ) -> ProblemShape:
    """Scale a shape down for measurement: entity counts and nnz shrink
    proportionally (rank/shards/algorithm are exact — they change kernel
    shapes, which is what is being measured)."""
    import dataclasses

    if shape.nnz <= max_nnz:
        return shape
    f = max_nnz / shape.nnz
    return dataclasses.replace(
        shape,
        num_users=max(int(shape.num_users * f), 64),
        num_movies=max(int(shape.num_movies * f), 16),
        nnz=max_nnz, gather_rows=None,
    )


def measure_with_training(shape: ProblemShape, base_config=None, *,
                          iters: int = 2, seed: int = 0):
    """The default offline measure: a trimmed synthetic workload through
    the REAL trainer with the candidate plan pinned as config knobs.
    Returns ``measure(plan) -> s/iter`` (min over ``iters`` timed after a
    warmup iteration).  Used by ``cfk_tpu plan --autotune``."""
    import dataclasses as dc

    import numpy as np

    from cfk_tpu.config import ALSConfig

    tshape = trimmed_shape(shape)

    def measure(ep: ExecutionPlan) -> float:
        from cfk_tpu.data.cache import cached_scale_dataset

        base = base_config or ALSConfig()
        cfg = dc.replace(
            base,
            rank=tshape.rank,
            num_iterations=1,
            num_shards=1,
            layout=ep.layout,
            exchange="all_gather",
            overlap=ep.overlap,
            fused_epilogue=ep.fused_epilogue,
            in_kernel_gather=ep.in_kernel_gather,
            reg_solve_algo=ep.reg_solve_algo,
            table_dtype=ep.table_dtype,
            solver=ep.solver,
            # Thread the staging engine too (ISSUE 13): on a host_window
            # resolve the enumerated pool/serial candidates must EXECUTE
            # their own mode, or both arms would measure the config
            # default and the cached winner's staging value would not be
            # backed by any measurement.
            staging=ep.staging,
            plan="pinned",
        )
        ds = cached_scale_dataset(
            users=tshape.num_users, movies=tshape.num_movies,
            nnz=tshape.nnz, seed=seed, layout=ep.layout,
            chunk_elems=ep.chunk_elems, tile_rows=tshape.tile_rows,
            log=lambda *a, **k: None,
        )
        from cfk_tpu.models.als import train_als

        times = []
        train_als(ds, cfg)  # warmup/compile
        for _ in range(max(iters, 1)):
            t0 = time.time()
            model = train_als(ds, cfg)
            np.asarray(model.user_factors[:1])
            times.append(time.time() - t0)
        return min(times)

    return measure
