"""Plan resolution: enumerate feasible candidates, cost them, pick cheapest.

``plan(shape, device, constraints)`` is the one entry point: constraints
pin fields (an ``ALSConfig``'s explicit knobs arrive as pins via
``spec.constraints_from_config``), the resolver enumerates the free
fields' candidates in legacy-preference order, drops candidates any
feasibility gate refuses — the SAME gates the half-steps execute under
(``quant.validate_table_dtype_layout``, the config layout/exchange/
algorithm rules, the kernel registry's ``supported`` predicates, the
device's VMEM/SMEM budgets) — and returns the cost-model minimum.  Ties
resolve to the first-enumerated candidate, i.e. the pre-planner default.

Pinned-but-impossible combinations split two ways, mirroring today's
behavior exactly:

- HARD conflicts (the ones ``ALSConfig.__post_init__`` itself refuses:
  int8 × padded/segment, ring × bucketed/segment, als++ × tiled/segment…)
  raise ``PlanConstraintError`` with both pins named.
- SOFT fallbacks (fused epilogue pinned on past the rank cap, in-kernel
  gather pinned on for an unsupported tile shape…) resolve to the
  effective execution — the pin is RELEASED (recorded in ``explain``) so
  the trainers thread the same deferred sentinel as before and the
  downstream gates do what they always did.
"""

from __future__ import annotations

import itertools

from cfk_tpu.plan import registry as _registry
from cfk_tpu.plan.cost import plan_cost
from cfk_tpu.plan.spec import (
    PLAN_FIELDS,
    DeviceSpec,
    ExecutionPlan,
    PlanConstraintError,
    PlanConstraints,
    PlanProvenance,
    ProblemShape,
    constraints_from_config,
)

_TRAIN_FIELDS = ("layout", "exchange", "chunk_elems", "fused_epilogue",
                 "in_kernel_gather", "overlap", "reg_solve_algo",
                 "table_dtype", "solver", "gram_backend", "offload_tier",
                 "ici_group", "staging", "hot_rows")
_SERVE_FIELDS = ("table_dtype", "serve_batch_quantum", "serve_tile_m",
                 "serve_mode", "clusters", "probe_clusters")


def hard_conflict(shape: ProblemShape, pins: dict) -> str | None:
    """A pinned combination today's config layer REFUSES (vs silently
    falls back from).  Returns the conflict message, or None."""
    layout = pins.get("layout")
    if pins.get("table_dtype") == "int8" and layout not in (
        None, "tiled", "bucketed"
    ):
        return (f"table_dtype='int8' needs layout 'tiled'/'bucketed' (the "
                f"per-row scale rides their weight streams); pinned "
                f"layout={layout!r}")
    if pins.get("exchange") == "ring" and layout in ("bucketed", "segment"):
        return (f"exchange='ring' supports the padded/tiled layouts; "
                f"pinned layout={layout!r}")
    if pins.get("exchange") == "hier_ring" and layout not in (None, "tiled"):
        return (f"exchange='hier_ring' is implemented for the tiled "
                f"layout; pinned layout={layout!r}")
    if pins.get("offload_tier") == "host_window":
        if shape.kind != "train":
            return ("offload_tier='host_window' is a TRAINING tier; "
                    "serve shapes keep the item table device-resident "
                    "by construction — unpin it for a serve resolve")
        if shape.implicit:
            # Implicit out-of-core (ISSUE 19): the bucketed windowed
            # driver runs iALS and iALS++ via the streamed global-Gram
            # reduction + width-class windows.
            if layout not in (None, "bucketed"):
                return ("offload_tier='host_window' for the implicit "
                        "family streams the bucketed width-class layout; "
                        f"pinned layout={layout!r}")
        else:
            if layout not in (None, "tiled"):
                return (f"offload_tier='host_window' streams the tiled "
                        f"stream-mode layout; pinned layout={layout!r}")
            if shape.algorithm != "als":
                return ("offload_tier='host_window' supports explicit ALS "
                        f"at layout='tiled'; algorithm="
                        f"{shape.algorithm!r} (the explicit subspace "
                        "windowed walk is the documented follow-up)")
        # Sharded host_window is a real executor now (ISSUE 12): the
        # windowed driver runs per-shard staged windows under the
        # all_gather scan or the ring/hier_ring visit schedules.
    if shape.algorithm != "als":
        if layout in ("segment", "tiled"):
            return (f"algorithm={shape.algorithm!r} supports padded/"
                    f"bucketed layouts; pinned layout={layout!r}")
        if pins.get("exchange") in ("ring", "hier_ring"):
            return (f"algorithm={shape.algorithm!r} supports "
                    "exchange='all_gather' only; pinned "
                    f"exchange={pins['exchange']!r}")
    ici = pins.get("ici_group")
    if ici and shape.num_shards % ici != 0:
        # The same divisibility rule ALSConfig enforces (the outer ring
        # walks whole inner rings) — a plan must never promise a
        # hierarchy hier_visit_order/half_step_tiled_ring_hier refuse.
        return (f"ici_group={ici} must divide "
                f"num_shards={shape.num_shards} (the outer ring walks "
                "whole inner rings)")
    if pins.get("hot_rows") and pins.get("offload_tier") == "device":
        # The hot cache is the host_window tier's staged-byte lever; the
        # device tier has no staging to cut.
        return (f"hot_rows={pins['hot_rows']} is a host_window-tier "
                "knob (it cuts staged PCIe bytes); pinned "
                "offload_tier='device' has no staging — unpin one side")
    mode = pins.get("serve_mode")
    if mode == "two_stage" and shape.kind != "serve":
        return ("serve_mode='two_stage' is a serve-kind mode (the "
                "clustered index exists only behind ServeEngine); "
                "unpin it for a train resolve")
    if mode == "exact" and (pins.get("clusters")
                            or pins.get("probe_clusters")):
        return (f"clusters={pins.get('clusters')}/probe_clusters="
                f"{pins.get('probe_clusters')} are two_stage index knobs; "
                "pinned serve_mode='exact' scans the full table — unpin "
                "one side")
    c_pin, p_pin = pins.get("clusters"), pins.get("probe_clusters")
    if (c_pin is not None and p_pin is not None and c_pin > 0
            and p_pin > c_pin):
        return (f"probe_clusters={p_pin} exceeds clusters={c_pin} "
                "(cannot probe more clusters than exist)")
    if mode == "two_stage" and c_pin and p_pin:
        from cfk_tpu.plan.cost import SERVE_MIN_RECALL, estimated_recall

        est = estimated_recall(c_pin, p_pin)
        if est < SERVE_MIN_RECALL:
            # The recall constraint is a RESOLUTION-time raise (ISSUE
            # 16): a pinned two_stage below the plan floor must never
            # resolve — the measured contract (bench recall column)
            # assumes no plan promises a sub-floor configuration.
            return (f"serve_mode='two_stage' pinned at clusters={c_pin}, "
                    f"probe_clusters={p_pin} models recall@K {est:.3f} "
                    f"< the plan constraint {SERVE_MIN_RECALL} — raise "
                    "probe_clusters (≈ 0.75·√clusters reaches the "
                    "floor), coarsen the index, or unpin")
    return None


def _feasible(shape: ProblemShape, device: DeviceSpec, cand: dict,
              ) -> str | None:
    """Reason this fully-assigned candidate cannot execute, or None.
    These mirror the execution-time gates one-for-one."""
    layout = cand["layout"]
    if cand["table_dtype"] == "int8" and layout not in ("tiled", "bucketed"):
        return "int8 table needs a weight stream (tiled/bucketed)"
    if cand["exchange"] == "ring" and layout not in ("padded", "tiled"):
        return "ring exchange needs the padded/tiled layouts"
    if cand["exchange"] == "hier_ring" and layout != "tiled":
        return "hier_ring exchange is implemented for the tiled layout"
    if shape.num_shards == 1 and cand["exchange"] != "all_gather":
        return "ring exchanges are multi-shard schedules"
    if shape.algorithm != "als" and layout in ("segment", "tiled"):
        return "subspace optimizers need padded/bucketed"
    if shape.algorithm != "als" and cand["exchange"] != "all_gather":
        return "subspace optimizers are all_gather only"
    if cand["offload_tier"] == "host_window" and shape.kind == "train":
        if shape.implicit:
            # ISSUE 19: the implicit windowed driver streams the
            # bucketed width-class layout (both iALS and iALS++ — the
            # global-Gram reduction serves either solve).  iALS is
            # all_gather only, and the generic exchange rules above
            # already refuse ring exchanges at bucketed layouts.
            if layout != "bucketed":
                return ("implicit host-window offload streams the "
                        "bucketed width-class layout")
        else:
            if layout != "tiled":
                return ("host-window offload streams the tiled stream "
                        "layout")
            if shape.algorithm != "als":
                return ("explicit host-window offload supports the full "
                        "ALS solve (the explicit subspace windowed walk "
                        "is the ROADMAP follow-up)")
        # Sharded host_window executes (ISSUE 12): the windowed driver
        # pairs per-shard staged windows with the all_gather scan or the
        # ring/hier_ring visit schedules; the generic exchange rules
        # above already refuse ring exchanges at one shard and non-tiled
        # ring layouts.
    if cand["hot_rows"] and cand["offload_tier"] != "host_window":
        return ("the hot-row cache is the host_window tier's staged-byte "
                "lever (the resident tier has no staging)")
    mosaic = _registry.backend_available("mosaic_tpu")
    if cand["gram_backend"] == "pallas" and not mosaic:
        return "mosaic_tpu backend unavailable"
    if cand["fused_epilogue"]:
        if cand["gram_backend"] != "pallas" or cand["solver"] != "pallas":
            return "fused epilogue needs the pallas gram backend + solver"
        gate = _registry.REGISTRY.get("gram_solve", "mosaic_tpu").supported
        if not gate(num_segments=1, k=shape.rank,
                    algo=cand["reg_solve_algo"]):
            return (f"rank {shape.rank} exceeds the fused "
                    f"{cand['reg_solve_algo']} elimination cap")
    if cand["in_kernel_gather"]:
        if cand["gram_backend"] != "pallas":
            return "in-kernel gather lives inside the pallas gram kernel"
        tr = shape.tile_rows
        entries = min(cand["chunk_elems"], 2 * shape.nnz)
        gate = _registry.REGISTRY.get("gram_gather", "mosaic_tpu").supported
        if not gate(entries=entries, meta_words=entries // max(tr, 1) + 2,
                    tile_rows=tr, block_rows=None):
            return "chunk shape refused by the gather SMEM/alignment gate"
    if cand["solver"] == "pallas":
        from cfk_tpu.ops.pallas import PALLAS_MAX_RANK

        if shape.rank > 2 * PALLAS_MAX_RANK:
            return (f"rank {shape.rank} exceeds the pallas solver's "
                    f"blocked cap {2 * PALLAS_MAX_RANK}")
    return None


def _serve_feasible(shape: ProblemShape, cand: dict) -> str | None:
    """Reason a serve-kind candidate cannot execute (ISSUE 16), or None.

    Mirrors the engine's own gates: exact mode carries no index knobs
    (refusing the duplicates keeps cost-identical candidates from
    crowding autotune's measured top-N, the staging-axis rule), and a
    two_stage candidate must clear BOTH the structural gates (a real
    index, probe ≤ clusters, expected coverage ≥ K) and the plan recall
    constraint — the resolver never enumerates a configuration the
    recall model puts below ``cost.SERVE_MIN_RECALL``."""
    from cfk_tpu.plan.cost import SERVE_MIN_RECALL, estimated_recall

    mode = cand.get("serve_mode", "exact")
    c = int(cand.get("clusters", 0) or 0)
    p = int(cand.get("probe_clusters", 0) or 0)
    if mode == "exact":
        if c or p:
            return "clusters/probe_clusters are two_stage index knobs"
        return None
    if c < 2:
        return "two_stage needs a real index (clusters >= 2)"
    if c > shape.num_movies:
        return "more clusters than catalog rows"
    if not 1 <= p <= c:
        return "probe_clusters must be in [1, clusters]"
    if shape.num_movies * p < shape.serve_k * c:
        return ("expected probe coverage (M·probe/clusters) below K — "
                "index too fine for this catalog")
    est = estimated_recall(c, p)
    if est < SERVE_MIN_RECALL:
        return (f"modeled recall {est:.3f} below the plan constraint "
                f"{SERVE_MIN_RECALL}")
    return None


# (knob, pinned value that may be infeasible, minimal-dependency probe
# overrides).  Each is a pin today's EXECUTION silently falls back from,
# so the resolver must release it (recording why) rather than raise —
# `ops.solve.dispatch_spd_solve` quietly takes cholesky past the pallas
# rank cap, the chunk resolvers quietly split/XLA-gather, and a
# single-device trainer never consults the exchange knob.  The probe
# overrides disable DEPENDENT knobs so the trial's refusal reason is
# about this pin, not a knock-on (fused needs the pallas solver, so a
# solver probe must not fail on the fused gate).
_SOFT_PINS = (
    ("gram_backend", "pallas",
     dict(fused_epilogue=False, in_kernel_gather=False)),
    ("solver", "pallas",
     dict(fused_epilogue=False, in_kernel_gather=False)),
    ("fused_epilogue", True, {}),
    ("in_kernel_gather", True, dict(fused_epilogue=False)),
    ("exchange", "ring", dict(fused_epilogue=False,
                              in_kernel_gather=False)),
    ("exchange", "hier_ring", dict(fused_epilogue=False,
                                   in_kernel_gather=False)),
)


def _soft_release(shape, device, pins, explain):
    """Release pins whose execution would silently fall back today
    (``_SOFT_PINS``), so the resolved plan reports the EFFECTIVE
    execution instead of raising on a config that has always trained.
    The released knob goes back to the resolver (which re-derives the
    fallback the gates would take) and the release is recorded in
    ``explain``."""
    pins = dict(pins)
    for knob, value, overrides in _SOFT_PINS:
        if pins.get(knob) != value:
            continue
        trial = dict(pins)
        for f in PLAN_FIELDS:
            trial.setdefault(f, PLAN_FIELDS[f][0])
        trial.update(overrides)
        trial[knob] = value
        reason = _feasible(shape, device, trial)
        if reason is not None:
            explain.append((knob, None,
                            f"pinned {value!r} but infeasible ({reason}); "
                            "released to the execution-time fallback"))
            pins.pop(knob)
    return pins


def candidates(shape: ProblemShape, constraints: PlanConstraints,
               device: DeviceSpec | None = None) -> "itertools.product":
    """(field order, value tuples) for the free-field product."""
    fields = _SERVE_FIELDS if shape.kind == "serve" else _TRAIN_FIELDS
    pins = constraints.pinned()
    axes = []
    tier_vals: tuple = ("device",)
    for f in fields:
        if f in pins:
            axes.append((f, (pins[f],)))
            if f == "offload_tier":
                tier_vals = (pins[f],)
        else:
            vals = PLAN_FIELDS[f]
            if f == "exchange" and shape.num_shards == 1:
                vals = ("all_gather",)
            if f == "staging" and "host_window" not in tier_vals:
                # The staging engine exists only on the host_window tier
                # — enumerating it for resident candidates would mint
                # cost-identical duplicates that crowd real candidates
                # out of autotune's measured top-N.
                vals = (PLAN_FIELDS[f][0],)
            if f == "offload_tier":
                # The axis IS the memory-budget predicate (ISSUE 11): a
                # fitting problem enumerates only the resident tier (the
                # legacy default, zero extra candidates), an oversized one
                # only host_window — so the resolver can never promise a
                # resident table the executor's own predicate refuses.
                # Workloads no windowed driver serves (serve kind, the
                # explicit subspace optimizer) keep the legacy resident
                # tier regardless — the budget cannot re-route them (and
                # a pinned 'device' there is never refused: _rank_plans'
                # budget raise shares THIS eligibility).  Implicit
                # shapes route to the bucketed windowed driver (ISSUE
                # 19); explicit ALS to the tiled one.
                vals = (("host_window",)
                        if (_host_window_eligible(shape, pins)
                            and device is not None
                            and not _fits_device(
                                shape, device,
                                table_dtype=pins.get("table_dtype")))
                        else ("device",))
                tier_vals = vals
            if f == "hot_rows":
                # Like the tier axis, this one IS a budget predicate
                # (ISSUE 15): a free hot_rows on the host_window tier
                # resolves to the ~10% power-law target when the hot
                # reservation fits the planner-side headroom, 0
                # otherwise — so the plan carries a nonzero hot fraction
                # ONLY when the budget admits it.  The executor clamps
                # the target to the real coverage-curve knee (and its
                # exact headroom) at window-plan build time.
                vals = ((_planner_hot_rows(shape, device, pins),)
                        if ("host_window" in tier_vals
                            and device is not None)
                        else (0,))
            axes.append((f, vals))
    names = [f for f, _ in axes]
    return names, itertools.product(*[v for _, v in axes])


def _fits_device(shape: ProblemShape, device: DeviceSpec,
                 table_dtype: str | None = None) -> bool:
    from cfk_tpu.offload.budget import shape_fits_device

    return shape_fits_device(shape, device, table_dtype=table_dtype)


def _stage_dtype_of(shape: ProblemShape, pins: dict) -> str:
    """The staging dtype the hot reservation is charged at: the pinned
    table dtype when it shrinks staging (bf16/int8), else the storage
    dtype — with an UNPINNED table dtype charged at the storage dtype
    (the largest candidate: the conservative reservation)."""
    td = pins.get("table_dtype")
    if td in ("bfloat16", "int8"):
        return td
    return shape.dtype


def _planner_hot_rows(shape: ProblemShape, device: DeviceSpec,
                      pins: dict) -> int:
    from cfk_tpu.offload.budget import planner_hot_rows

    return planner_hot_rows(
        shape.num_users, shape.num_movies, shape.rank,
        _stage_dtype_of(shape, pins), device.hbm_bytes,
    )


def _host_window_eligible(shape: ProblemShape, pins: dict) -> bool:
    """Whether the host_window tier is an ALTERNATIVE for this resolve —
    the one eligibility both the offload_tier axis and the pinned-device
    budget raise consult, so an explicit ``offload_tier='device'`` pin is
    refused exactly when unpinning it would have re-routed (and never
    with a dead-end remedy on shapes the windowed driver cannot serve).
    Sharded shapes qualify (ISSUE 12) — every exchange the sharded
    trainers run (all_gather / ring / hier_ring) has a windowed twin."""
    exchange_ok = (pins.get("exchange")
                   in (None, "all_gather", "ring", "hier_ring"))
    if shape.num_shards == 1:
        exchange_ok = pins.get("exchange") in (None, "all_gather")
    if shape.implicit:
        # ISSUE 19: the implicit family's out-of-core twin is the
        # bucketed windowed driver — iALS and iALS++ both qualify
        # (all_gather only; IALSConfig refuses other exchanges anyway).
        return (shape.kind == "train"
                and shape.algorithm in ("als", "ials++")
                and pins.get("layout") in (None, "bucketed")
                and pins.get("exchange") in (None, "all_gather"))
    return (shape.kind == "train"
            and shape.algorithm == "als"
            and pins.get("layout") in (None, "tiled")
            and exchange_ok)


def _assemble(shape: ProblemShape, cand: dict, pinned: frozenset,
              pins: dict | None = None) -> ExecutionPlan:
    """Fill non-enumerated fields with pins, then defaults, and name the
    kernel backend per slot from the resolved knobs (a serve-kind resolve
    enumerates only the serve fields, but pinned train fields must still
    appear in the plan verbatim)."""
    full = {f: PLAN_FIELDS[f][0] for f in PLAN_FIELDS}
    full.update(pins or {})
    full.update(cand)
    mosaic = (_registry.backend_available("mosaic_tpu")
              and full["gram_backend"] == "pallas")
    emu = "xla_emulation"
    moz = "mosaic_tpu"
    fused = full["fused_epilogue"] and full["solver"] == "pallas" and mosaic
    gather = full["in_kernel_gather"] and mosaic
    kernels = (
        ("gram", moz if mosaic else emu),
        ("gram_gather", moz if gather else emu),
        ("gram_solve", moz if fused else emu),
        ("gram_solve_gather", moz if (fused and gather) else emu),
        ("reg_solve",
         moz if (full["solver"] == "pallas"
                 and _registry.backend_available(moz)) else emu),
        ("topk", moz if _registry.backend_available(moz) else emu),
    )
    if full["serve_mode"] == "two_stage":
        # The candidate stage rides its own slot; "topk" above stays the
        # un-disableable exact fallback (and the rescore executor).
        kernels += (
            ("topk_coarse", moz if _registry.backend_available(moz)
             else emu),
        )
    return ExecutionPlan(**full, kernels=kernels, pinned=pinned)


def _rank_plans(shape: ProblemShape, device: DeviceSpec,
                constraints: PlanConstraints | None = None,
                ) -> tuple[list[tuple[float, "ExecutionPlan"]], tuple]:
    """(ranked candidates cheapest-first, soft-release explain rows).
    Stable: enumeration order — legacy defaults first — breaks ties."""
    constraints = constraints or PlanConstraints()
    explain: list = []
    pins = constraints.pinned()
    conflict = hard_conflict(shape, pins)
    if conflict is not None:
        raise PlanConstraintError(conflict)
    if (pins.get("offload_tier") == "device"
            and _host_window_eligible(shape, pins)
            and not _fits_device(shape, device,
                                 table_dtype=pins.get("table_dtype"))):
        # The core ISSUE 11 guarantee: no plan may promise a resident
        # table the memory-budget predicate (offload.budget — the SAME
        # predicate the executor uses) says cannot exist.
        from cfk_tpu.offload.budget import train_resident_bytes

        need = train_resident_bytes(
            shape.num_users, shape.num_movies, shape.nnz, shape.rank,
            dtype=shape.dtype, table_dtype=pins.get("table_dtype"),
            num_shards=shape.num_shards,
        )["total"]
        raise PlanConstraintError(
            f"offload_tier='device' pinned but the PER-SHARD resident "
            f"working set (~{need / 1e9:.2f} GB at "
            f"num_shards={shape.num_shards}) exceeds the device budget "
            f"({device.hbm_bytes / 1e9:.2f} GB × budget fraction) — "
            "unpin offload_tier (the resolver will pick 'host_window') "
            "or shrink the problem"
        )
    # Hot-row cache resolution (ISSUE 15) — the hot-fraction decision
    # the plan CLI's --explain prints: which tier this resolve takes,
    # whether the reservation fits, and the target the axis will carry.
    will_host_window = (
        pins.get("offload_tier") == "host_window"
        or (_host_window_eligible(shape, pins)
            and "offload_tier" not in pins
            and not _fits_device(shape, device,
                                 table_dtype=pins.get("table_dtype")))
    )
    hot_pin = pins.get("hot_rows")
    if hot_pin:
        if not will_host_window:
            # Execution ignores the knob on the resident tier (the
            # windowed driver is the only consumer) — release, don't
            # raise, per the _SOFT_PINS convention.
            explain.append(("hot_rows", None,
                            f"pinned {hot_pin} but this resolve stays on "
                            "the resident tier (no staging to cut); "
                            "released to the execution-time no-op"))
            pins.pop("hot_rows")
        else:
            from cfk_tpu.offload.budget import (
                hot_reservation_bytes,
                hot_reservation_fits,
                max_hot_rows,
            )

            stage = _stage_dtype_of(shape, pins)
            if not hot_reservation_fits(hot_pin, shape.rank, stage,
                                        device.hbm_bytes):
                need = hot_reservation_bytes(hot_pin, shape.rank, stage)
                admit = max_hot_rows(device.hbm_bytes, shape.rank, stage)
                # Mirror the pinned-impossible offload_tier convention:
                # a reservation the budget predicate refuses raises AT
                # RESOLUTION, naming the bytes.
                raise PlanConstraintError(
                    f"hot_rows={hot_pin} pinned but its device "
                    f"reservation ({need / 1e6:.2f} MB at the {stage!r} "
                    f"staging dtype) exceeds the hot-cache budget share "
                    f"({admit} rows on this device) — lower hot_rows, "
                    "unpin it (the resolver clamps to the headroom), or "
                    "pin 0 for the full-staging engine"
                )
    elif hot_pin is None and will_host_window and device is not None:
        target = _planner_hot_rows(shape, device, pins)
        stage = _stage_dtype_of(shape, pins)
        if target > 0:
            from cfk_tpu.offload.budget import hot_reservation_bytes

            explain.append((
                "hot_rows", target,
                f"budget headroom admits the hot reservation "
                f"({hot_reservation_bytes(target, shape.rank, stage) / 1e6:.2f}"
                f" MB at {stage}) — target min(~10% of rows, headroom); "
                "the executor clamps to the coverage-curve knee"
            ))
        else:
            explain.append((
                "hot_rows", 0,
                "hot reservation refused by the budget headroom — "
                "windows stage their full row sets"
            ))
    pins = _soft_release(shape, device, pins, explain)
    constraints = PlanConstraints(**pins)
    names, prod = candidates(shape, constraints, device)
    pinned = frozenset(pins)
    ranked = []
    for idx, values in enumerate(prod):
        cand = dict(zip(names, values))
        reason = (_serve_feasible(shape, cand) if shape.kind == "serve"
                  else _feasible(shape, device, _with_defaults(cand)))
        if reason is not None:
            continue
        ep = _assemble(shape, cand, pinned, pins)
        cost = plan_cost(shape, device, ep)
        ranked.append((cost.seconds, idx, ep, cost))
    if not ranked:
        raise PlanConstraintError(
            f"no feasible plan for {shape.shape_class()} under pins "
            f"{sorted(pins.items())} — every candidate was refused"
        )
    ranked.sort(key=lambda t: (t[0], t[1]))
    return [(s, ep) for s, _, ep, _ in ranked], tuple(explain)


def rank_plans(shape: ProblemShape, device: DeviceSpec,
               constraints: PlanConstraints | None = None,
               ) -> list[tuple[float, ExecutionPlan]]:
    """All feasible candidates, cheapest first."""
    return _rank_plans(shape, device, constraints)[0]


def _with_defaults(cand: dict) -> dict:
    full = {f: PLAN_FIELDS[f][0] for f in PLAN_FIELDS}
    full.update(cand)
    return full


def plan(shape: ProblemShape, device: DeviceSpec | None = None,
         constraints: PlanConstraints | None = None, *,
         mode: str = "model", cache_path: str | None = None,
         measure=None) -> tuple[ExecutionPlan, PlanProvenance]:
    """Resolve an execution plan.

    ``mode="model"``    — cost-model minimum over the feasible set.
    ``mode="pinned"``   — no optimization: pins + legacy defaults (the
                          pre-planner behavior, as a plan object).
    ``mode="autotune"`` — consult the JSON cache; on a miss, measure the
                          top candidates when a ``measure`` callable is
                          given (``autotune.autotune``), else fall back
                          to the model choice with cache="miss".
    """
    device = device or DeviceSpec.detect()
    constraints = constraints or PlanConstraints()
    if mode == "autotune":
        from cfk_tpu.plan.autotune import autotune

        return autotune(shape, device, constraints,
                        cache_path=cache_path, measure=measure)
    if mode not in ("model", "pinned"):
        raise ValueError(f"unknown plan mode {mode!r}")
    ranked, explain = _rank_plans(shape, device, constraints)
    if mode == "pinned":
        # First-enumerated feasible candidate == pins + preference-order
        # defaults; rank_plans sorts by cost, so re-derive by index order.
        best = min(
            ((s, ep) for s, ep in ranked),
            key=lambda t: _preference_index(t[1], device),
        )[1]
        cost = plan_cost(shape, device, best)
        prov = PlanProvenance(plan=best, source="pinned",
                              est_cost_s=cost.seconds, explain=explain)
        return best, prov
    est, best = ranked[0]
    cost = plan_cost(shape, device, best)
    explain = explain + tuple(
        (name, round(val, 6), "cost term (s)")
        for name, val in sorted(cost.terms.items(), key=lambda t: -t[1])
    )
    source = "model" if len(ranked) > 1 else "pinned"
    prov = PlanProvenance(plan=best, source=source, est_cost_s=est,
                          explain=explain)
    return best, prov


def _preference_index(ep: ExecutionPlan, device: DeviceSpec) -> tuple:
    """Lexicographic position of a plan in legacy-preference order.

    The solver's legacy default is device-dependent (``"auto"`` resolves
    pallas on TPU, cholesky elsewhere — ``ops.solve._resolve_solver``),
    so the preference order flips with the device kind; every other
    field's preference is the candidate-tuple order."""
    idx = []
    for f, vals in PLAN_FIELDS.items():
        if f == "solver" and device.kind != "tpu":
            vals = tuple(reversed(vals))
        v = getattr(ep, f)
        idx.append(vals.index(v) if v in vals else len(vals))
    return tuple(idx)


def shape_for_config(config, *, num_users: int, num_movies: int, nnz: int,
                     implicit: bool = False,
                     gather_rows: float | None = None) -> ProblemShape:
    """The ``ProblemShape`` a trainer resolves its plan for."""
    return ProblemShape(
        num_users=max(num_users, 1), num_movies=max(num_movies, 1),
        nnz=max(nnz, 1), rank=config.rank, num_shards=config.num_shards,
        implicit=implicit, algorithm=config.algorithm,
        sweeps=config.sweeps if config.algorithm != "als" else 1,
        dtype=config.dtype, gather_rows=gather_rows,
    )


def plan_for_config(config, *, num_users: int, num_movies: int, nnz: int,
                    implicit: bool = False,
                    gather_rows: float | None = None,
                    device: DeviceSpec | None = None,
                    cache_path: str | None = None,
                    ) -> tuple[ExecutionPlan, PlanProvenance]:
    """The trainer entry: shape from the dataset's counts, pins from the
    config's explicit knobs, mode from ``config.plan``.  Trainer-side
    autotune NEVER measures (that belongs offline — ``cfk_tpu plan
    --autotune`` / ``perf_lab --plan autotune``); it consults the cache
    and falls back to the model on a miss, recording hit/miss."""
    shape = shape_for_config(
        config, num_users=num_users, num_movies=num_movies, nnz=nnz,
        implicit=implicit, gather_rows=gather_rows,
    )
    constraints = constraints_from_config(config)
    mode = getattr(config, "plan", "model")
    return plan(shape, device, constraints, mode=mode,
                cache_path=cache_path)


def fleet_host_window_plan(shape: ProblemShape, *, host_ram_bytes: float,
                           processes: int, armed: bool = True) -> dict:
    """Provenance for the FLEET out-of-core tier: prove that a shape whose
    factor tables exceed one host's RAM budget fits once the
    ``HostFactorStore`` is range-sharded over ``processes`` hosts.

    Returns a breakdown dict recording both verdicts — the single-host
    refusal (``single_host_fits``) and the per-process fit
    (``fleet_fits``) — alongside the byte terms they were judged on, so a
    bench row or a fleet launcher can show WHY the fleet was required.
    Raises ``PlanConstraintError`` when even the fleet does not fit (the
    message names the two levers: more processes, or more host RAM)."""
    from cfk_tpu.offload.budget import (
        RESIDENT_FRACTION,
        fleet_host_ram_bytes,
        fits_fleet_host,
    )

    if processes < 1:
        raise PlanConstraintError(f"processes must be >= 1, got {processes}")
    if shape.num_shards % processes != 0:
        raise PlanConstraintError(
            f"num_shards={shape.num_shards} must be divisible by "
            f"processes={processes}: the window exchange assigns each "
            f"process a contiguous run of shards")
    kw = dict(dtype=shape.dtype, armed=armed)
    single = fleet_host_ram_bytes(shape.num_users, shape.num_movies,
                                  shape.nnz, shape.rank, processes=1, **kw)
    fleet = fleet_host_ram_bytes(shape.num_users, shape.num_movies,
                                 shape.nnz, shape.rank,
                                 processes=processes, **kw)
    single_fits = fits_fleet_host(
        shape.num_users, shape.num_movies, shape.nnz, shape.rank,
        host_ram_bytes=host_ram_bytes, processes=1, **kw)
    fleet_fits = fits_fleet_host(
        shape.num_users, shape.num_movies, shape.nnz, shape.rank,
        host_ram_bytes=host_ram_bytes, processes=processes, **kw)
    if not fleet_fits:
        raise PlanConstraintError(
            f"per-process host window footprint "
            f"{fleet['total'] / 2**20:.1f} MiB exceeds the "
            f"{host_ram_bytes * RESIDENT_FRACTION / 2**20:.1f} MiB resident "
            f"budget even at processes={processes}; raise processes (shards "
            f"permitting) or host_ram_bytes")
    return {
        "tier": "fleet_host_window",
        "processes": processes,
        "host_ram_bytes": float(host_ram_bytes),
        "resident_fraction": RESIDENT_FRACTION,
        "single_host_bytes": single["total"],
        "single_host_fits": single_fits,
        "per_process_bytes": fleet["total"],
        "per_process_breakdown": fleet,
        "fleet_fits": fleet_fits,
    }
