"""cfk_tpu — a TPU-native collaborative-filtering framework.

A from-scratch re-design of the capabilities of the Kafka-Streams ALS reference
(trinh-hoang-hiep/Collaborative-Filtering-Kafka): block-partitioned ALS-WR
matrix factorization on Netflix-Prize-format data — expressed TPU-first:

- the rating matrix is sharded over a ``jax.sharding.Mesh`` (the analog of the
  reference's mod-N Kafka partitioning, ``producers/PureModPartitioner.java:17``),
- each half-iteration is a bulk-synchronous SPMD step under ``shard_map``:
  exchange fixed-side factors (``all_gather`` over ICI, or a ``ppermute`` ring —
  the block-to-block join analog), then batched normal-equation solves on the
  MXU (the analog of ``processors/MFeatureCalculator.java:85-99``),
- the EOF-barrier protocol of the reference (``processors/URatings2BlocksProcessor.java:56-63``)
  survives in the pluggable ingest/transport layer, and the per-iteration Kafka
  topics become an explicit checkpoint API.
"""

from cfk_tpu.config import ALSConfig
from cfk_tpu.data.netflix import parse_netflix
from cfk_tpu.data.movielens import parse_movielens_csv
from cfk_tpu.data.blocks import Dataset, IdMap, RatingsCOO, build_padded_blocks
from cfk_tpu.models.als import ALSModel, train_als
from cfk_tpu.models.ials import IALSConfig, train_ials, train_ials_sharded

__version__ = "0.1.0"

__all__ = [
    "ALSConfig",
    "IALSConfig",
    "parse_netflix",
    "parse_movielens_csv",
    "Dataset",
    "IdMap",
    "RatingsCOO",
    "build_padded_blocks",
    "ALSModel",
    "train_als",
    "train_ials",
    "train_ials_sharded",
    "__version__",
]
