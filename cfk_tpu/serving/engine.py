"""ServeEngine: live factors + seen lists behind the score+top-K kernel.

The stateful core of the request server — everything between "a batch of
user rows" and "[B, K] ids+scores":

- the item factor table, padded to the kernel's tile grid, quantized per
  ``ALSConfig.table_dtype`` (``ops.quant``) and kept device-resident (it
  is read every request; re-uploading 30 MB per query would dominate),
- the user factor source: a base snapshot taken at attach time plus a
  HOT-ROW OVERLAY — the factor rows most recently re-solved by streaming
  fold-in commits.  ``StreamSession`` publishes every commit through
  ``attach_session``'s listener; the event carries COPIES of the solved
  rows, applied under the engine lock, so a concurrently-scoring batch
  reads either the old or the new row, never a torn half-write (the
  serving side never reaches into the session's mutable arrays),
- the seen-list CSR for exclusion, with the same overlay treatment: a
  commit's (user, movie) cells append to the overlay so a just-rated
  movie disappears from that user's recommendations at the next request,
- pow2 request-batch bucketing: batches pad to a power of two (and the
  seen rectangle width is pow2 from ``build_seen_tiles``), so live
  traffic converges onto a handful of compiled programs instead of
  re-tracing per batch — the same trick PR 6 used for fold-in shapes,
- two-stage clustered retrieval (ISSUE 16, ``serve_mode="two_stage"``):
  a k-means index over the item factors (``serving.cluster``), rebuilt
  ATOMICALLY on every table swap, probed by a centroid stage
  (``serve/candidate``) whose selected clusters' rows are rescored
  exactly through the same kernel (``serve/rescore`` —
  ``serving.twostage``).  The exact scan is the un-disableable fallback:
  a corrupt index (NaN centroids, broken offsets, non-finite coarse
  scores) or a staleness overrun degrades THIS engine to the exact path
  bit-exactly — same table, same jitted program — records the plan
  transition + flight-recorder event, and recovers two_stage at the
  next table swap.  Per-row fold-in movie deltas update the clustered
  table IN PLACE at their cluster-major position (staleness counted);
  only a full snapshot swap re-clusters.
"""

from __future__ import annotations

import functools
import threading

import numpy as np

from cfk_tpu.serving.topk_kernel import (
    _pow2_ceil,
    build_seen_tiles,
    topk_scores_pallas,
)
from cfk_tpu.telemetry import dump_flight, record_event, span


def pad_table(table: np.ndarray, tile_m: int, shards: int = 1) -> np.ndarray:
    """Zero-pad item rows to a multiple of ``shards × tile_m`` (the padding
    rows are masked by the kernel's global ``num_movies`` bound)."""
    quantum = tile_m * max(shards, 1)
    m_pad = -(-table.shape[0] // quantum) * quantum
    if m_pad == table.shape[0]:
        return table
    out = np.zeros((m_pad, table.shape[1]), table.dtype)
    out[: table.shape[0]] = table
    return out


class ServeEngine:
    """Score top-K requests against live factors.

    ``seen_movies``/``seen_indptr`` (per-user-row CSR of rated movie rows,
    sorted ascending per user — ``Dataset.coo_dense`` order after a stable
    user sort) enables exclude-seen; None serves without exclusion.
    """

    def __init__(
        self,
        user_factors,  # [U, k] (np or jax; snapshot is taken)
        movie_factors,  # [M_pad0, k]
        *,
        num_users: int,
        num_movies: int,
        seen_movies=None,
        seen_indptr=None,
        table_dtype: str | None = None,
        tile_m: int = 512,
        batch_quantum: int = 8,
        mesh=None,
        plan=None,  # cfk_tpu.plan.ExecutionPlan (serve knobs)
        plan_provenance=None,
        serve_mode: str | None = None,  # "exact" | "two_stage"
        clusters: int | None = None,
        probe_clusters: int | None = None,
        cluster_seed: int = 0,
        max_stale_fraction: float = 0.25,
        metrics=None,  # telemetry.Metrics — recall/bytes-scanned gauges
    ) -> None:
        from cfk_tpu.ops.quant import resolve_table_dtype

        # Opt-in plan consumption (cfk_tpu.plan): when a plan is given its
        # serve knobs (batch quantum, movie tile rows, retrieval mode +
        # index size, and — unless passed explicitly — the table dtype)
        # configure the engine, and the provenance rides along for the
        # bench rows.  No plan → the pre-planner defaults, unchanged.
        self.plan = plan
        self.plan_provenance = plan_provenance
        if plan is not None:
            if table_dtype is None:
                table_dtype = plan.table_dtype
            batch_quantum = plan.serve_batch_quantum
            tile_m = plan.serve_tile_m
            if serve_mode is None:
                serve_mode = plan.serve_mode
            if clusters is None and plan.clusters:
                clusters = plan.clusters
            if probe_clusters is None and plan.probe_clusters:
                probe_clusters = plan.probe_clusters
        self.serve_mode = serve_mode or "exact"
        if self.serve_mode not in ("exact", "two_stage"):
            raise ValueError(
                f"serve_mode must be 'exact' or 'two_stage', "
                f"got {self.serve_mode!r}"
            )
        self.num_movies = int(num_movies)
        self.num_users = int(num_users)
        if self.serve_mode == "two_stage":
            from cfk_tpu.serving.twostage import default_two_stage_params

            dc, dp = default_two_stage_params(self.num_movies)
            clusters = int(clusters or dc)
            probe_clusters = int(probe_clusters or dp)
        self.clusters = int(clusters or 0)
        self.probe_clusters = int(probe_clusters or 0)
        self.cluster_seed = int(cluster_seed)
        self.max_stale_fraction = float(max_stale_fraction)
        self.metrics = metrics
        self.table_dtype = resolve_table_dtype(table_dtype)
        self.tile_m = int(tile_m)
        self.batch_quantum = int(batch_quantum)
        self.mesh = mesh
        self._shards = 1 if mesh is None else int(mesh.devices.size)
        self._lock = threading.RLock()
        # Two-stage state: (ClusterIndex, cluster-major quantized table,
        # its scales, quantized centroids, centroid scales) — ONE tuple so
        # every swap is a single atomic reference assignment, like _table.
        self._cluster = None
        self._two_stage_disabled = False
        self.two_stage_fallbacks = 0
        self.last_scan: dict = {}
        self._u_base = np.asarray(user_factors, np.float32)[:num_users]
        self._u_hot: dict[int, np.ndarray] = {}
        if (seen_movies is None) != (seen_indptr is None):
            raise ValueError(
                "pass both of seen_movies/seen_indptr or neither"
            )
        self._seen_movies = (
            None if seen_movies is None
            else np.asarray(seen_movies, np.int32)
        )
        self._seen_indptr = (
            None if seen_indptr is None
            else np.asarray(seen_indptr, np.int64)
        )
        self._seen_hot: dict[int, list[int]] = {}
        m_host = np.asarray(movie_factors, np.float32)[:num_movies]
        self._set_table(m_host)
        self.invalidations = 0
        self.table_swaps = 0
        # Fleet state (ISSUE 18): the factor-table epoch every response is
        # stamped with (bumped on each full-table swap), and the readiness
        # flag behind /readyz — an engine is live from construction but
        # READY only once prewarm() has traced the batch-bucket set.
        self.epoch = 0
        self.prewarmed = False

    @property
    def ready(self) -> bool:
        """Readiness (vs liveness): prewarmed AND an epoch table loaded —
        the /readyz signal and the fleet's rollover gate."""
        return bool(self.prewarmed and getattr(self, "_table", None)
                    is not None)

    def load_state(self, user_factors, movie_factors=None, *,
                   hot_rows=None, seen_cells=None, num_users=None,
                   epoch=None) -> None:
        """Atomically replace the live user-side state (and optionally the
        item table) from an epoch snapshot — the fleet replica's resync
        seam (ISSUE 18).  ``user_factors`` becomes the new base snapshot,
        ``hot_rows`` ({row: factor row}) the new overlay, ``seen_cells``
        ((user_row, movie_row) pairs) rebuild the seen overlay from
        scratch; ``movie_factors``/``epoch`` additionally swap the item
        table (a cross-epoch resync).  All under the engine lock, so a
        concurrently scoring batch reads entirely-old or entirely-new
        state, never a mixture."""
        with self._lock:
            self._u_base = np.asarray(user_factors, np.float32)
            self._u_hot = (
                {int(r): np.asarray(f, np.float32)
                 for r, f in hot_rows.items()} if hot_rows else {}
            )
            self._seen_hot = {}
            for row, movie in seen_cells or ():
                self._seen_hot.setdefault(int(row), []).append(int(movie))
            if num_users is not None:
                self.num_users = int(num_users)
            if movie_factors is not None:
                self._set_table(
                    np.asarray(movie_factors, np.float32)[: self.num_movies]
                )
                self.table_swaps += 1
            if epoch is not None:
                self.epoch = int(epoch)

    # -- table ---------------------------------------------------------------

    def _set_table(self, movie_factors_host: np.ndarray) -> None:
        import jax
        import jax.numpy as jnp

        from cfk_tpu.ops.quant import quantize_table

        padded = pad_table(
            movie_factors_host.astype(np.float32), self.tile_m, self._shards
        )
        data, scale = quantize_table(jnp.asarray(padded), self.table_dtype)
        # one atomic reference swap: a batch in flight keeps the table it
        # captured; the next batch sees the new one
        self._table = (jax.device_put(data),
                       None if scale is None else jax.device_put(scale))
        if self.serve_mode == "two_stage":
            # Rebuild the cluster index with every swap (re-cluster ONLY
            # here — fold-in deltas update rows in place).  Built off to
            # the side, swapped as one reference: a batch in flight keeps
            # the (index, table) pair it captured.
            from cfk_tpu.serving.cluster import build_cluster_index

            host = np.asarray(movie_factors_host, np.float32)
            index = build_cluster_index(
                host, min(self.clusters, max(host.shape[0], 1)),
                seed=self.cluster_seed,
            )
            cpad = pad_table(host[index.perm], self.tile_m, 1)
            cdata, cscale = quantize_table(
                jnp.asarray(cpad), self.table_dtype
            )
            # the coarse stage scores the QUANTIZED centroid view — the
            # same canonical ops.quant placement as the kernel's tiles
            qc, qcs = quantize_table(
                jnp.asarray(index.centroids), self.table_dtype
            )
            self._cluster = (
                index,
                jax.device_put(cdata),
                None if cscale is None else jax.device_put(cscale),
                jax.device_put(qc),
                None if qcs is None else jax.device_put(qcs),
            )
            # a fresh index is healthy by construction — re-arm two_stage
            # after any fault-driven degradation (the recovery half of the
            # chaos contract)
            self._two_stage_disabled = False

    @property
    def table_rows(self) -> int:
        return int(self._table[0].shape[0])

    # -- live-update listener ------------------------------------------------

    def attach_session(self, session) -> None:
        """Subscribe to a ``StreamSession``'s commits: fold-in rows refresh
        the hot-row overlay, rated cells extend the seen overlay, retrains
        swap the whole table.  Fired AFTER each durable commit, so a
        request served after the commit returns reflects it."""
        session.add_commit_listener(self.on_commit)

    def on_commit(self, event: dict) -> None:
        """Apply one commit event (see ``StreamSession._fire_commit``)."""
        with self._lock:
            rows = event.get("rows")
            touched = event.get("touched_rows") or ()
            if rows is not None:
                for i, row in enumerate(touched):
                    self._u_hot[int(row)] = np.array(rows[i], np.float32)
                self.invalidations += len(touched)
            for row, movie in event.get("cells") or ():
                self._seen_hot.setdefault(int(row), []).append(int(movie))
            self.num_users = max(self.num_users,
                                 int(event.get("num_users", self.num_users)))
            # Item-side per-row deltas (ISSUE 16): a commit that ships
            # re-solved MOVIE rows updates both table views in place —
            # within each row's existing cluster — without re-clustering.
            mrows = event.get("movie_rows")
            if mrows is not None and not event.get("retrain"):
                self.apply_movie_deltas(mrows, event["movie_row_factors"])
            if event.get("retrain"):
                # a warm retrain re-solves EVERY row: drop the overlay and
                # re-snapshot both sides
                self._u_base = np.asarray(
                    event["user_factors"], np.float32
                )[: self.num_users]
                self._u_hot.clear()
                self._set_table(
                    np.asarray(event["movie_factors"],
                               np.float32)[: self.num_movies]
                )
                self.table_swaps += 1
                self.epoch += 1

    def apply_movie_deltas(self, rows, factors) -> int:
        """Update item factor rows IN PLACE in both table views.

        The exact table updates at the global row; the cluster-major
        table (when two_stage) at the row's EXISTING cluster position —
        assignments and centroids intentionally go stale (recorded via
        ``ClusterIndex.note_stale``; re-clustering happens only on a full
        snapshot swap).  Quantization is per-row (``ops.quant``), so a
        delta row's codes+scale are bit-identical to what a full-table
        requantization would produce.  Returns the rows applied."""
        import jax.numpy as jnp

        from cfk_tpu.ops.quant import quantize_table

        rows = np.asarray(rows, np.int64)
        f = np.asarray(factors, np.float32)
        keep = (rows >= 0) & (rows < self.num_movies)
        rows, f = rows[keep], f[keep]
        if rows.size == 0:
            return 0
        qd, qs = quantize_table(jnp.asarray(f), self.table_dtype)
        with self._lock:
            data, scale = self._table
            data = data.at[rows].set(qd.astype(data.dtype))
            if scale is not None:
                scale = scale.at[rows].set(qs)
            self._table = (data, scale)
            if self._cluster is not None:
                index, ctable, cscale, qc, qcs = self._cluster
                pos = index.positions_of(rows)
                ctable = ctable.at[pos].set(qd.astype(ctable.dtype))
                if cscale is not None:
                    cscale = cscale.at[pos].set(qs)
                index.note_stale(rows.size)
                self._cluster = (index, ctable, cscale, qc, qcs)
                if self.metrics is not None:
                    self.metrics.gauge("serve/index_stale_rows",
                                       index.stale_rows)
        return int(rows.size)

    # -- request path --------------------------------------------------------

    def _gather_users(self, user_rows: np.ndarray) -> np.ndarray:
        u = np.zeros((user_rows.shape[0], self._u_base.shape[1]), np.float32)
        base_n = self._u_base.shape[0]
        for i, row in enumerate(user_rows):
            hot = self._u_hot.get(int(row))
            if hot is not None:
                u[i] = hot
            elif row < base_n:
                u[i] = self._u_base[row]
            # else: streamed-in user with no commit yet → zero row
        return u

    def _batch_seen(self, user_rows: np.ndarray):
        """Per-batch CSR = base slice ⊕ hot overlay, sorted per user."""
        if self._seen_movies is None and not self._seen_hot:
            return None
        per_user = []
        base_n = (0 if self._seen_indptr is None
                  else self._seen_indptr.shape[0] - 1)
        for row in user_rows:
            row = int(row)
            if self._seen_movies is not None and row < base_n:
                base = self._seen_movies[
                    self._seen_indptr[row]: self._seen_indptr[row + 1]
                ]
            else:
                base = np.zeros(0, np.int32)
            extra = self._seen_hot.get(row)
            if extra:
                base = np.unique(np.concatenate(
                    [base, np.asarray(extra, np.int32)]
                ))
            per_user.append(base)
        indptr = np.zeros(len(per_user) + 1, np.int64)
        indptr[1:] = np.cumsum([a.size for a in per_user])
        movies = (np.concatenate(per_user) if indptr[-1]
                  else np.zeros(0, np.int32))
        return movies, indptr

    def topk(self, user_rows, k: int, *, exclude_seen: bool = True,
             force_exact: bool = False):
        """(scores [n, k] f32, movie rows [n, k] int32) for the requested
        user rows.  The batch is padded to the pow2 quantum (padding rows
        score with a zero factor vector and are sliced off), so request
        coalescing shares compiled programs across batch sizes.

        ``force_exact`` skips the two-stage candidate path for this one
        batch (same table, same masks, same jitted exact program) — the
        dense oracle the recall@K measurements score against."""
        import jax.numpy as jnp

        user_rows = np.asarray(user_rows, dtype=np.int64)
        n = user_rows.shape[0]
        if n == 0:
            return (np.zeros((0, k), np.float32),
                    np.zeros((0, k), np.int32))
        if np.any((user_rows < 0) | (user_rows >= self.num_users)):
            bad = user_rows[(user_rows < 0)
                            | (user_rows >= self.num_users)][:5]
            raise ValueError(
                f"user rows out of range [0, {self.num_users}): {bad}"
            )
        if not 1 <= k <= self.num_movies:
            raise ValueError(f"k must be in [1, {self.num_movies}], got {k}")
        b = _pow2_ceil(n, self.batch_quantum)
        with span("serve/batch/assemble", n=n, b=b):
            with self._lock:
                table, scale = self._table
                cluster = self._cluster
                u = np.zeros((b, self._u_base.shape[1]), np.float32)
                u[:n] = self._gather_users(user_rows)
                seen = self._batch_seen(user_rows) if exclude_seen else None
            seen_pad = None
            if seen is not None:
                movies, indptr = seen
                # padding slots carry EMPTY seen lists (repeat the last
                # indptr entry), not user 0's — aliasing the heaviest user
                # into every pad slot would inflate the seen-rectangle
                # width for rows whose output is sliced off anyway
                indptr_pad = np.concatenate(
                    [indptr, np.full(b - n, indptr[-1], np.int64)]
                )
                seen_pad = (movies, indptr_pad)
        if (self.serve_mode == "two_stage" and not force_exact
                and not self._two_stage_disabled):
            out = self._topk_two_stage(cluster, u, n, b, k, seen_pad)
            if out is not None:
                return out
            # a detected fault fell through: the exact path below IS the
            # un-disableable fallback — same table, same jitted program
            # as serve_mode="exact", so the degraded answer is bit-exact
        seen_tiles = None
        if seen_pad is not None:
            movies, indptr_pad = seen_pad
            seen_tiles = jnp.asarray(build_seen_tiles(
                movies, indptr_pad, np.arange(b),
                num_movies=self.num_movies,
                tile_m=self.tile_m,
                num_tiles=self.table_rows // self.tile_m,
            ))
        with span("serve/batch/compute", n=n, b=b, k=k):
            if self.mesh is not None:
                from cfk_tpu.parallel.spmd import serve_topk_sharded

                vals, ids = serve_topk_sharded(
                    self.mesh, jnp.asarray(u), table, scale, seen_tiles,
                    k_top=k, num_movies=self.num_movies, tile_m=self.tile_m,
                )
            else:
                vals, ids = _topk_jit_fn()(
                    jnp.asarray(u), table, scale, seen_tiles,
                    k_top=k, num_movies=self.num_movies, tile_m=self.tile_m,
                )
            vals, ids = np.asarray(vals)[:n], np.asarray(ids)[:n]
        self._record_scan(mode="exact", b=b, k=k)
        return vals, ids

    def _topk_two_stage(self, cluster, u, n, b, k, seen_pad):
        """One two-stage batch: centroid probe → batch-union shortlist →
        exact rescore.  Returns ``(vals, ids)`` sliced to ``n``, or None
        after recording a fault — the caller then takes the exact scan."""
        import jax.numpy as jnp

        from cfk_tpu.serving.twostage import (
            build_shortlist,
            coarse_jit_fn,
            map_shortlist_ids,
            rescore_jit_fn,
            shortlist_seen_tiles,
        )

        if cluster is None:
            self._two_stage_fault("cluster index missing")
            return None
        index, ctable, cscale, qc, qcs = cluster
        reason = index.quick_check()
        if reason is not None:
            self._two_stage_fault(reason)
            return None
        if index.stale_fraction > self.max_stale_fraction:
            self._two_stage_fault(
                f"index staleness {index.stale_fraction:.3f} over the "
                f"{self.max_stale_fraction} bound (awaiting table swap)"
            )
            return None
        probe = min(max(self.probe_clusters, 1), index.num_clusters)
        with span("serve/candidate", n=n, b=b, probe=probe):
            cvals, cids = coarse_jit_fn()(jnp.asarray(u), qc, qcs,
                                          probe=probe)
            if not np.isfinite(np.asarray(cvals)[:n]).all():
                self._two_stage_fault("non-finite coarse scores")
                return None
            # union over the REAL rows only — padding slots carry a zero
            # factor vector and would vote junk clusters into the gather
            shortlist = build_shortlist(
                index, np.asarray(cids)[:n].ravel(),
                tile_m=self.tile_m, min_rows=k,
            )
            seen_tiles = None
            if seen_pad is not None:
                movies, indptr_pad = seen_pad
                seen_tiles = jnp.asarray(shortlist_seen_tiles(
                    index, shortlist, movies, indptr_pad, b,
                    tile_m=self.tile_m,
                ))
        with span("serve/rescore", n=n, b=b, k=k, rows=shortlist.rows,
                  rows_padded=shortlist.rows_padded):
            vals, ids = rescore_jit_fn()(
                jnp.asarray(u), jnp.asarray(shortlist.indices), ctable,
                cscale, seen_tiles, np.int32(shortlist.offset),
                k_top=k, tile_m=self.tile_m,
            )
            vals = np.asarray(vals)[:n]
            ids = map_shortlist_ids(np.asarray(ids)[:n], shortlist)
        self._record_scan(mode="two_stage", b=b, k=k, shortlist=shortlist,
                          probe=probe, index=index)
        return vals, ids

    def _two_stage_fault(self, reason: str) -> None:
        """Degrade to the exact scan until the next table swap.

        The chaos contract (``chaos_lab two_stage_fallback``): the fault
        is RECORDED (flight-recorder event + dump, plan transition,
        fallback counter), the answer comes from the exact path
        bit-exactly, and ``_set_table`` re-arms two_stage when a healthy
        index is rebuilt."""
        self._two_stage_disabled = True
        self.two_stage_fallbacks += 1
        record_event("serve", "two_stage_fault", reason=reason,
                     fallbacks=self.two_stage_fallbacks)
        dump_flight(f"two_stage_fallback: {reason}")
        if self.plan_provenance is not None:
            self.plan_provenance.record_transition(
                "two_stage_fallback",
                f"{reason}; exact scan until the next table swap "
                "rebuilds the index",
            )
        if self.metrics is not None:
            self.metrics.incr("serve/two_stage_fallbacks")

    def _record_scan(self, *, mode, b, k, shortlist=None, probe=0,
                     index=None) -> None:
        """Per-batch scan accounting: the MEASURED byte traffic of the
        executed mode (``utils.roofline.serve_batch_cost`` over the real
        shortlist union for two_stage), exposed as ``last_scan`` for the
        bench rows and as metrics gauges."""
        from cfk_tpu.utils.roofline import serve_batch_cost

        rank = int(self._u_base.shape[1])
        if mode == "two_stage":
            cost = serve_batch_cost(
                self.num_movies, rank, b, k, table_dtype=self.table_dtype,
                serve_mode="two_stage", clusters=index.num_clusters,
                probe_clusters=probe,
                shortlist_rows=shortlist.rows_padded,
            )
            self.last_scan = {
                "serve_mode": "two_stage",
                "clusters": index.num_clusters,
                "probe_clusters": probe,
                "shortlist_rows": shortlist.rows,
                "shortlist_rows_padded": shortlist.rows_padded,
                "index_stale_rows": index.stale_rows,
                "bytes_scanned_per_batch": round(cost.hbm_bytes),
            }
        else:
            cost = serve_batch_cost(
                self.num_movies, rank, b, k, table_dtype=self.table_dtype,
                m_pad=self.table_rows,
            )
            self.last_scan = {
                "serve_mode": "exact",
                "bytes_scanned_per_batch": round(cost.hbm_bytes),
            }
        if self.metrics is not None:
            self.metrics.gauge("serve/bytes_scanned_per_batch",
                               self.last_scan["bytes_scanned_per_batch"])

    @property
    def trace_count(self) -> int:
        """Serve-program traces this PROCESS (engines share the jitted
        entry, so this is a process-wide counter — delta it around a
        call, as ``prewarm`` does)."""
        return trace_count()

    def prewarm(self, k: int, *, max_batch: int | None = None,
                user_rows=None, exclude_seen: bool = True) -> dict:
        """Trace (and compile) the pow2 batch-bucket program set up
        front (ISSUE 13), so the first REAL request batch after attach
        pays zero traces — the cold-process counterpart of the pow2
        bucketing that already bounds steady-state re-traces (PR 6/8).

        Walks the batch-quantum ladder ``q, 2q, ... pow2_ceil(max_batch)``
        and scores a representative batch at each size (``user_rows``
        when given — pass a workload sample so the seen-rectangle widths
        it produces match live traffic — else the first users of the
        table; results are discarded, and the jit cache keys on shapes
        only, so bit-exactness is untouched).  With
        ``ALSConfig.compile_cache_dir`` wired, the XLA compile behind
        each new trace is also served from the persistent cache — a warm
        restart pays neither.  Returns
        ``{"programs", "new_traces", "prewarm_s"}``; a later batch whose
        (padded size, seen width) bucket was covered here traces
        nothing, which ``tests/test_staging.py`` pins.  In two_stage
        mode each rung additionally traces the centroid probe and the
        rescore at the shortlist width that rung's union produced —
        pass a workload ``user_rows`` sample so those widths land in
        the same pow2 buckets as live traffic."""
        import time as _time

        with span("serve/prewarm", k=k, max_batch=max_batch):
            t0 = _time.time()
            top = _pow2_ceil(max(max_batch or self.batch_quantum, 1),
                             self.batch_quantum)
            if user_rows is None:
                rows = np.arange(min(top, self.num_users), dtype=np.int64)
            else:
                rows = np.asarray(user_rows, dtype=np.int64)
            if rows.size == 0:
                return {"programs": 0, "new_traces": 0, "prewarm_s": 0.0}
            before = trace_count()
            programs = 0
            b = self.batch_quantum
            while b <= top:
                take = rows[: min(b, rows.size)]
                # pad by REPEATING the sample rather than truncating the
                # bucket: topk pads to _pow2_ceil(n, quantum), so a short
                # sample still traces the intended batch size
                if take.size < b:
                    take = np.resize(take, b)
                self.topk(take, k, exclude_seen=exclude_seen)
                programs += 1
                if self.serve_mode == "two_stage" and rows.size > b:
                    # a second, disjoint sample per rung: the shortlist
                    # union width is data-dependent, so one sample warms
                    # one pow2 width bucket — a second makes the
                    # neighboring bucket resident when live unions
                    # straddle a boundary
                    alt = rows[b:2 * b]
                    if alt.size < b:
                        alt = np.resize(alt, b)
                    self.topk(alt, k, exclude_seen=exclude_seen)
                b *= 2
            self.prewarmed = True  # the /readyz gate flips here
            return {
                "programs": programs,
                "new_traces": trace_count() - before,
                "prewarm_s": round(_time.time() - t0, 4),
            }


# Trace counter (ISSUE 13): bumped once per TRACE of the serve program
# (the body below runs only while jax traces a new (B, W, K) variant), so
# prewarm() can prove its contract — zero new traces on the first real
# batch — and the bench rows can report trace_count next to
# time-to-first-batch.
_TRACES = [0]


def trace_count() -> int:
    """Traces of the single-device serve programs this process — the
    exact scan plus (ISSUE 16) the two-stage coarse/rescore stages, so
    the prewarm contract covers whichever mode the plan picked."""
    from cfk_tpu.serving import twostage

    return _TRACES[0] + twostage.trace_count()


def _topk_call(u, table, scale, seen_tiles, *, k_top, num_movies, tile_m):
    _TRACES[0] += 1
    return topk_scores_pallas(
        u, table, scale, seen_tiles, k_top=k_top, num_movies=num_movies,
        tile_m=tile_m,
    )


@functools.lru_cache(maxsize=1)
def _topk_jit_fn():
    """Jitted single-device entry — with pow2 batch/width bucketing, live
    traffic converges onto a handful of (B, W, K) program variants."""
    import jax

    return jax.jit(
        _topk_call, static_argnames=("k_top", "num_movies", "tile_m")
    )


def plan_for_serving(num_users: int, num_movies: int, rank: int, *,
                     k_top: int = 100, table_dtype: str | None = None,
                     serve_mode: str | None = None,
                     clusters: int | None = None,
                     probe_clusters: int | None = None,
                     mode: str = "model", cache_path: str | None = None):
    """Resolve a serve-side ExecutionPlan: the batch quantum, table dtype
    and (ISSUE 16) serve mode chosen from the scan/shortlist byte model
    (``cost.serve_batch_cost_for``), with explicit knobs arriving as
    pins — a pinned two_stage whose modeled recall@K falls below the
    0.95 floor raises at resolution rather than serving bad answers.
    Returns ``(plan, provenance)`` — hand both to
    ``ServeEngine(plan=...)``."""
    from cfk_tpu.plan import PlanConstraints, ProblemShape, plan

    shape = ProblemShape(
        num_users=num_users, num_movies=num_movies,
        nnz=max(num_users, num_movies), rank=rank, kind="serve",
        serve_k=k_top,
    )
    cons = PlanConstraints(table_dtype=table_dtype, serve_mode=serve_mode,
                           clusters=clusters,
                           probe_clusters=probe_clusters)
    return plan(shape, None, cons, mode=mode, cache_path=cache_path)


def engine_from_model(model, dataset=None, *, table_dtype=None, tile_m=512,
                      mesh=None, batch_quantum=8, plan=None,
                      plan_provenance=None, serve_mode=None, clusters=None,
                      probe_clusters=None, metrics=None) -> ServeEngine:
    """Build an engine from an ``ALSModel`` (+ optional dataset/index whose
    ``coo_dense`` provides the exclude-seen lists).  ``plan`` (see
    ``plan_for_serving``) optionally supplies the serve knobs."""
    seen_movies = seen_indptr = None
    if dataset is not None:
        coo = dataset.coo_dense
        order = np.argsort(
            coo.user_raw * (dataset.movie_map.num_entities + 1)
            + coo.movie_raw, kind="stable",
        )
        seen_movies = coo.movie_raw[order].astype(np.int32)
        counts = np.bincount(
            coo.user_raw.astype(np.int64),
            minlength=dataset.user_map.num_entities,
        )
        seen_indptr = np.zeros(dataset.user_map.num_entities + 1, np.int64)
        np.cumsum(counts, out=seen_indptr[1:])
    u, m = model.user_factors, model.movie_factors
    if not getattr(u, "is_fully_addressable", True):
        from cfk_tpu.parallel.mesh import to_host

        u, m = to_host(u), to_host(m)
    return ServeEngine(
        np.asarray(u), np.asarray(m),
        num_users=model.num_users, num_movies=model.num_movies,
        seen_movies=seen_movies, seen_indptr=seen_indptr,
        table_dtype=table_dtype, tile_m=tile_m, mesh=mesh,
        batch_quantum=batch_quantum, plan=plan,
        plan_provenance=plan_provenance, serve_mode=serve_mode,
        clusters=clusters, probe_clusters=probe_clusters, metrics=metrics,
    )
