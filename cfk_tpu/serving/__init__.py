"""Top-K recommendation serving at traffic (ISSUE 8 / ROADMAP item 1).

The serving half the reference never had: instead of materializing U·Mᵀ
(``processors/FeatureCollector.java``), a Pallas score+top-K kernel streams
movie-axis tiles of the (optionally quantized) item table through VMEM and
only [B, K] ids+scores ever reach HBM (``topk_kernel``); the table shards
over the item axis with an O(B·shards·K) merge (``parallel.spmd.
serve_topk_sharded``); a request server coalesces queries from the
transport log into pow2-bucketed batches (``server``) over a live-updating
``ServeEngine`` whose hot-user factor cache re-serves streaming fold-in
commits (``engine``); and an open-loop generator measures QPS/p50/p99
honestly (``loadgen``; ``bench.py --serve`` for the recorded rows).

Two-stage clustered retrieval (ISSUE 16 / ROADMAP item 4) breaks the
O(users × catalog) scan floor: a seeded k-means over the item factors
(``cluster``) stores the table CLUSTER-MAJOR, a centroid probe picks
top-probe clusters per user, and only the batch union of those clusters'
rows is rescored EXACTLY through the same Pallas kernel (``twostage``) —
recall@K vs the dense oracle is measured first-class and the exact scan
stays the un-disableable fallback.

The replicated fleet (ISSUE 18 / ROADMAP item 3, ``fleet``) puts N
replicas behind the request log: user-keyed routing, admission control
with explicit retriable rejections, versioned factor-delta shipping with
seq-gap detection + epoch-snapshot resync (bit-exact, ``table_crc``),
zero-downtime epoch rollover (background prewarm + single pointer flip),
and kill/failover at the committed cursor (at-least-once re-serve).
"""

from cfk_tpu.serving.cluster import (
    ClusterIndex,
    build_cluster_index,
    kmeans_item_clusters,
)
from cfk_tpu.serving.engine import (
    ServeEngine,
    engine_from_model,
    pad_table,
    plan_for_serving,
)
from cfk_tpu.serving.fleet import (
    DELTAS_TOPIC,
    AdmissionController,
    DeltaPublisher,
    FleetReplica,
    ServeFleet,
    SnapshotStore,
    ensure_deltas_topic,
    table_crc,
)
from cfk_tpu.serving.twostage import (
    Shortlist,
    build_shortlist,
    default_two_stage_params,
    recall_at_k,
)
from cfk_tpu.serving.loadgen import (
    LoadReport,
    run_open_loop,
    warm_serve_programs,
    zipf_user_rows,
)
from cfk_tpu.serving.server import (
    REQUESTS_TOPIC,
    RESPONSES_TOPIC,
    RecommendServer,
    ServeClient,
    ensure_serve_topics,
)
from cfk_tpu.serving.topk_kernel import (
    build_seen_tiles,
    topk_scores_pallas,
)

__all__ = [
    "ServeEngine",
    "engine_from_model",
    "plan_for_serving",
    "pad_table",
    "ClusterIndex",
    "build_cluster_index",
    "kmeans_item_clusters",
    "Shortlist",
    "build_shortlist",
    "default_two_stage_params",
    "recall_at_k",
    "LoadReport",
    "run_open_loop",
    "warm_serve_programs",
    "zipf_user_rows",
    "REQUESTS_TOPIC",
    "RESPONSES_TOPIC",
    "DELTAS_TOPIC",
    "RecommendServer",
    "ServeClient",
    "ensure_serve_topics",
    "ensure_deltas_topic",
    "AdmissionController",
    "DeltaPublisher",
    "FleetReplica",
    "ServeFleet",
    "SnapshotStore",
    "table_crc",
    "build_seen_tiles",
    "topk_scores_pallas",
]
