"""Pallas TPU kernel: batched score + streaming top-K over movie tiles.

The reference's only serving artifact is the dense U·Mᵀ CSV dump
(``processors/FeatureCollector.java:90-109``) — O(users × movies) memory
for any query, the one part of its design that cannot reach
millions-of-users traffic.  This kernel is the serving analog of the
training stack's chunked half-steps: for a [B, k] batch of user factors it
streams [T, k] movie-axis tiles of the (optionally quantized,
``ops.quant``) item factor table through VMEM, computes each [B, T] score
block on the MXU, and folds it into a running per-user K-selection carried
in VMEM — so the only thing that ever reaches HBM is the [B, K] result.
No [B, num_movies] score matrix exists anywhere, on-chip or off.

Per grid step (one movie tile):

- score block  S = U · tileᵀ on the MXU (f32 accumulation; an int8 tile is
  dequantized in-register by its per-row scale — the same canonical
  dequant placement as the Gram kernels, ``ops.quant``),
- padding mask: global column ≥ ``num_movies`` → −inf (the table is padded
  to a tile multiple),
- exclusion mask: already-rated items are −inf'd in-register from the
  batch's per-user CSR slice, re-bucketed per tile on the host
  (``build_seen_tiles``: ``seen[b]``'s movie rows, already sorted, split
  at tile boundaries into a [NT, B, W] rectangle of in-tile columns — W is
  the pow2-bucketed max per-(user, tile) seen count, so the kernel's mask
  pass is W comparisons against the tile's column iota, not a [B, S×T]
  blow-up),
- K-selection merge: the tile's masked scores are concatenated onto the
  [B, K] carry and one ``lax.top_k`` re-selects — equal scores resolve to
  the earlier tile (carry first), making tie order deterministic.

The merge step (``_score_tile_fold``) is ONE function shared by the Mosaic
kernel body and the XLA emulation twin (``compat.emulate_topk_scores``
scans it over the same tiles), so the two routes are bit-identical on the
interpret path — the same twin discipline as the Gram kernels.  On real
hardware the open questions are whether the [B, K+T] top_k lowers
efficiently in Mosaic or the K-selection carry should spill to a VMEM
scratch merge-sort, and the score tile's MXU utilization at small B — both
recorded in the ROADMAP on-TPU backlog.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from cfk_tpu.compat import has_vma_system, typeof_vma
from jax.experimental import pallas as pl

try:  # TPU-specific extensions; absent on some builds
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

import numpy as np

# Exclusion-mask compare chunk: W seen slots are checked against the tile's
# column iota in slices of this many slots, bounding both the trace length
# and the [B, chunk, T] boolean intermediate (≤ ~1 MB at the default tile).
_SEEN_CHUNK = 16


def _pow2_ceil(x: int, floor: int = 1) -> int:
    out = max(floor, 1)
    while out < x:
        out *= 2
    return out


def serve_compute_dtype(table_dtype):
    """(compute dtype, matmul precision) for the score block — the serving
    analog of ``ops.solve._gram_compute_dtype``: f32 operands keep the
    full-precision MXU pass (bit-parity with the dense oracle), bf16 tables
    feed the MXU bf16 with f32 accumulation, int8 tables dequantize to f32
    in-register first (the int8×f32-scale product is exact in f32)."""
    if table_dtype == jnp.bfloat16:
        return jnp.bfloat16, None
    return jnp.float32, lax.Precision.HIGHEST


def _score_tile_fold(carry_v, carry_i, u, tile, scale, seen, tile_base,
                     *, num_movies, k_top):
    """Fold one movie tile into the running top-K carry.

    The ONE copy of the per-tile math — the Mosaic kernel body and the XLA
    emulation twin both call exactly this, which is what makes the two
    routes bit-identical on the interpret path.

    carry_v [B, K] f32, carry_i [B, K] int32 (−1 empty), u [B, k],
    tile [T, k] (f32/bf16/int8), scale [T, 1] f32 or None, seen [B, W]
    int32 in-tile columns (T = padding), tile_base scalar int32.
    """
    t = tile.shape[0]
    b = u.shape[0]
    ct, prec = serve_compute_dtype(tile.dtype)
    if tile.dtype == jnp.int8:
        # canonical dequant placement (ops.quant): codes → f32 × per-row
        # scale, before the single matmul
        tile_f = tile.astype(jnp.float32) * scale
    else:
        tile_f = tile.astype(ct)
    scores = jax.lax.dot_general(
        u.astype(ct), tile_f,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=prec,
    )  # [B, T]
    col = lax.broadcasted_iota(jnp.int32, (1, t), 1)  # [1, T] in-tile column
    gid = tile_base + col  # [1, T] global movie row
    neg = jnp.float32(-jnp.inf)
    scores = jnp.where(gid < num_movies, scores, neg)
    if seen is not None:
        w = seen.shape[1]

        def mask_chunk(j, sc):
            chunk = lax.dynamic_slice(seen, (0, j * _SEEN_CHUNK),
                                      (b, _SEEN_CHUNK))  # [B, C]
            hit = (chunk[:, :, None] == col[None, :, :]).any(axis=1)
            return jnp.where(hit, neg, sc)

        scores = lax.fori_loop(0, w // _SEEN_CHUNK, mask_chunk, scores)
    cat_v = jnp.concatenate([carry_v, scores], axis=1)  # [B, K+T]
    cat_i = jnp.concatenate(
        [carry_i, jnp.broadcast_to(gid, (b, t))], axis=1
    )
    new_v, pos = lax.top_k(cat_v, k_top)
    new_i = jnp.take_along_axis(cat_i, pos, axis=1)
    return new_v, new_i


def build_seen_tiles(seen_movies, seen_indptr, batch_rows, *, num_movies,
                     tile_m, num_tiles: int | None = None,
                     min_width: int = _SEEN_CHUNK):
    """[NT, B, W] per-tile exclusion rectangle from a per-user CSR.

    ``seen_movies``/``seen_indptr`` is the CSR of already-rated movie rows
    by user row (movie rows sorted ascending within each user — the
    ``StreamState.neighbors`` / ``eval.ranking`` convention);
    ``batch_rows`` [B] selects the batch.  Entry [t, b, w] is the w-th
    in-tile column of batch user b's seen movies inside movie tile t,
    padded with ``tile_m`` (which no in-tile column equals).  W is the
    pow2-bucketed max per-(user, tile) count — pow2 so the rectangle
    shape, which is jit-static in the kernel, converges onto a handful of
    compiled programs under live traffic (the PR 6 fold-in trick).
    """
    nt = -(-num_movies // tile_m) if num_tiles is None else num_tiles
    b = len(batch_rows)
    batch_rows = np.asarray(batch_rows, dtype=np.int64)
    counts = (seen_indptr[batch_rows + 1] - seen_indptr[batch_rows]).astype(
        np.int64
    )
    rows = np.repeat(np.arange(b, dtype=np.int64), counts)
    flat = np.concatenate([
        np.arange(seen_indptr[r], seen_indptr[r + 1], dtype=np.int64)
        for r in batch_rows
    ]) if counts.sum() else np.zeros(0, np.int64)
    mv = seen_movies[flat].astype(np.int64)
    keep = mv < num_movies
    rows, mv = rows[keep], mv[keep]
    tile_of = mv // tile_m
    local = (mv % tile_m).astype(np.int32)
    # mv is sorted within each row, so (row, tile) groups are contiguous;
    # position within group = running index − group start.
    key = rows * nt + tile_of
    if key.size:
        starts = np.flatnonzero(np.concatenate(([True], key[1:] != key[:-1])))
        group_sizes = np.diff(np.concatenate((starts, [key.size])))
        pos = np.arange(key.size) - np.repeat(starts, group_sizes)
        width = int(group_sizes.max())
    else:
        pos = np.zeros(0, np.int64)
        width = 0
    w = _pow2_ceil(max(width, 1), min_width)
    out = np.full((nt, b, w), tile_m, dtype=np.int32)
    out[tile_of, rows, pos] = local
    return out


def _topk_kernel(off_ref, u_ref, tbl_ref, *refs, t, k_top, num_movies, b,
                 with_scale, with_seen):
    """Grid step i: fold movie tile i into the resident [B, K] carry.

    The outputs are the carry (constant-index resident blocks, the Gram
    kernels' accumulation idiom): step 0 initializes them, every step
    merges its tile, the final state IS the result.  ``off_ref`` (scalar-
    prefetched, [1] int32) is the shard's global row offset — 0 on a
    single device; under item-axis sharding each shard's tile i covers
    global movie rows [off + i·T, off + (i+1)·T).
    """
    refs = list(refs)
    scale_ref = refs.pop(0) if with_scale else None
    seen_ref = refs.pop(0) if with_seen else None
    vals_ref, ids_ref = refs
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        vals_ref[...] = jnp.full((b, k_top), -jnp.inf, jnp.float32)
        ids_ref[...] = jnp.full((b, k_top), -1, jnp.int32)

    new_v, new_i = _score_tile_fold(
        vals_ref[...], ids_ref[...], u_ref[...], tbl_ref[...],
        scale_ref[...] if scale_ref is not None else None,
        seen_ref[0] if seen_ref is not None else None,
        off_ref[0] + i * t,
        num_movies=num_movies, k_top=k_top,
    )
    vals_ref[...] = new_v
    ids_ref[...] = new_i


def topk_scores_pallas(
    u: jax.Array,  # [B, k] user-factor batch (f32 or bf16)
    table: jax.Array,  # [M_pad, k] item table (f32 / bf16 / int8 codes)
    scale: jax.Array | None,  # [M_pad] f32 per-row int8 scales, else None
    seen_tiles: jax.Array | None,  # [NT, B, W] int32 (build_seen_tiles)
    *,
    k_top: int,
    num_movies: int,
    tile_m: int = 512,
    row_offset=0,
    interpret: bool | None = None,
) -> tuple[jax.Array, jax.Array]:
    """(scores [B, K] f32 descending, movie rows [B, K] int32).

    Only the [B, K] selection reaches HBM — the out_specs below ARE the
    no-dense-score-matrix guarantee (``tests/test_serving.py`` additionally
    pins the emulation route's compiled temp memory below B·M).  Excluded
    and padding columns score −inf; when fewer than K candidates exist the
    tail ids are −1.  ``row_offset`` (python int or traced scalar) maps
    this table slice's rows to global movie rows — the item-axis sharded
    path (``parallel.spmd.serve_topk_sharded``) passes each shard's base
    row; ids come back global and ``num_movies`` stays the GLOBAL count.
    """
    b, k = u.shape
    m_pad = table.shape[0]
    if m_pad % tile_m != 0:
        raise ValueError(
            f"table rows {m_pad} not divisible by tile_m {tile_m}; pad the "
            "table (serving.engine.pad_table does)"
        )
    if not 1 <= k_top:
        raise ValueError(f"k_top must be >= 1, got {k_top}")
    nt = m_pad // tile_m
    if seen_tiles is not None and seen_tiles.shape[:2] != (nt, b):
        raise ValueError(
            f"seen_tiles shape {seen_tiles.shape} != ({nt}, {b}, W)"
        )
    if seen_tiles is not None and seen_tiles.shape[2] % _SEEN_CHUNK != 0:
        raise ValueError(
            f"seen_tiles width {seen_tiles.shape[2]} must be a multiple of "
            f"{_SEEN_CHUNK} (build_seen_tiles pads it)"
        )
    if (scale is None) != (table.dtype != jnp.int8):
        raise ValueError(
            "per-row scale required exactly when the table is int8 "
            "(ops.quant.quantize_table provides it)"
        )
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if interpret and (typeof_vma(u) or not has_vma_system()):
        # Same routing rule as the Gram kernels: sharded-interpret and
        # old-jax runs take the bit-exact XLA twin.
        from cfk_tpu.compat import emulate_topk_scores

        return emulate_topk_scores(
            u, table, scale, seen_tiles, k_top=k_top,
            num_movies=num_movies, tile_m=tile_m, row_offset=row_offset,
        )
    if pltpu is None:  # pragma: no cover - non-TPU pallas build
        raise RuntimeError("pallas TPU extensions unavailable")
    in_specs = [
        pl.BlockSpec((b, k), lambda i, off: (0, 0)),  # u: resident
        pl.BlockSpec((tile_m, k), lambda i, off: (i, 0)),  # table: streamed
    ]
    ops = [u, table]
    if scale is not None:
        in_specs.append(pl.BlockSpec((tile_m, 1), lambda i, off: (i, 0)))
        ops.append(scale.reshape(m_pad, 1).astype(jnp.float32))
    if seen_tiles is not None:
        w = seen_tiles.shape[2]
        in_specs.append(pl.BlockSpec((1, b, w), lambda i, off: (i, 0, 0)))
        ops.append(seen_tiles)
    kwargs = {}
    if not interpret:
        # resident carry (2× for Mosaic's output double-buffer) + one
        # streamed tile double-buffered + the seen rectangle + headroom
        out_bytes = 2 * b * k_top * 8
        tile_bytes = 2 * tile_m * (k + 1) * 4
        seen_bytes = (0 if seen_tiles is None
                      else 2 * b * seen_tiles.shape[2] * 4)
        params = getattr(pltpu, "CompilerParams", None) or getattr(
            pltpu, "TPUCompilerParams"
        )
        kwargs["compiler_params"] = params(
            vmem_limit_bytes=min(
                2 * out_bytes + 2 * tile_bytes + seen_bytes + (16 << 20),
                110 << 20,
            )
        )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nt,),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((b, k_top), lambda i, off: (0, 0)),
            pl.BlockSpec((b, k_top), lambda i, off: (0, 0)),
        ],
    )
    off = jnp.asarray(row_offset, jnp.int32).reshape(1)
    vals, ids = pl.pallas_call(
        functools.partial(
            _topk_kernel, t=tile_m, k_top=k_top, num_movies=num_movies,
            b=b, with_scale=scale is not None,
            with_seen=seen_tiles is not None,
        ),
        grid_spec=grid_spec,
        out_shape=(
            jax.ShapeDtypeStruct((b, k_top), jnp.float32),
            jax.ShapeDtypeStruct((b, k_top), jnp.int32),
        ),
        interpret=interpret,
        **kwargs,
    )(off, *ops)
    return vals, ids
