"""Open-loop synthetic load generator for the serve path.

Open-loop (arrivals scheduled by a clock, NOT gated on responses) is the
honest way to measure a server's latency under load: a closed loop slows
its own arrival rate the moment the server falls behind, hiding exactly
the tail it should expose (the coordinated-omission trap).  Here request
i's scheduled send time is ``i / rate``; the generator sends the moment
the clock passes it (never waits for responses to send), polls responses
opportunistically between sends, and reports per-request latency =
response-observed wall − SCHEDULED send — so queueing delay from the
generator itself falling behind counts against the server, as it would
for a real client.

Users are drawn Zipf-ish from the hot end of the row space (traffic skew
is what makes the hot-user cache meaningful); the draw is seeded, so a
bench row is reproducible.
"""

from __future__ import annotations

import dataclasses
import time

import numpy as np

from cfk_tpu.telemetry.metrics import Histogram

# Latency-reservoir size: big enough that the common bench sweeps
# (≤ 4096 requests) record EVERY sample (quantiles exact, bit-for-bit the
# old unbounded-list percentiles), bounded so a day-long soak stays O(1)
# in request count (quantiles become reservoir estimates past this).
LATENCY_RESERVOIR = 4096


@dataclasses.dataclass(frozen=True)
class LoadReport:
    """One open-loop run's measured outcome (the bench row's core)."""

    num_requests: int
    answered: int
    wall_s: float
    qps_target: float
    qps_achieved: float
    p50_ms: float
    p99_ms: float
    max_ms: float
    batches: int
    mean_batch: float

    def as_row(self) -> dict:
        return {
            "requests": self.num_requests,
            "answered": self.answered,
            "wall_s": round(self.wall_s, 4),
            "qps_target": round(self.qps_target, 1),
            "qps": round(self.qps_achieved, 1),
            "p50_ms": round(self.p50_ms, 3),
            "p99_ms": round(self.p99_ms, 3),
            "max_ms": round(self.max_ms, 3),
            "batches": self.batches,
            "mean_batch": round(self.mean_batch, 1),
        }


def zipf_user_rows(num_users: int, n: int, *, seed: int = 0,
                   a: float = 1.2) -> np.ndarray:
    """n user rows with a Zipf(a) popularity skew over the row space."""
    rng = np.random.default_rng(seed)
    draws = rng.zipf(a, size=n)
    return ((draws - 1) % num_users).astype(np.int64)


def warm_serve_programs(client, server, pool, k: int, max_batch: int) -> None:
    """Compile the serve path's batch-size program variants before a
    measured run: every pow2 coalesced size up to ``max_batch``, plus
    ``max_batch`` itself (a non-pow2 cap still pads to its own pow2
    bucket).  The ONE copy used by bench.py --serve, perf_lab --serve and
    the CLI loadgen mode.  Seen-rectangle widths (W) are data-dependent
    per batch, so a first-seen W can still trace mid-run — warming with
    the hottest pool rows makes the common widths resident."""
    pool = np.asarray(pool, np.int64)
    sizes = []
    warm = 4
    while warm < max_batch:
        sizes.append(warm)
        warm *= 2
    sizes.append(max_batch)
    for s in sizes:
        take = pool[: min(s, pool.shape[0])]
        if take.shape[0]:
            client.ask(take, k, server=server)


def run_open_loop(
    client,
    *,
    rate_qps: float,
    num_requests: int,
    user_rows,
    k: int = 10,
    server=None,
    drive_server: bool = False,
    timeout_s: float = 120.0,
    clock=time.monotonic,
    sleep=time.sleep,
) -> LoadReport:
    """Send ``num_requests`` at ``rate_qps`` open-loop; block for the tail.

    ``drive_server=True`` pumps ``server.step()`` inline between sends —
    the single-process bench mode, where the generator and server share
    one interpreter and a background thread would only serialize on the
    GIL anyway.  With a live server elsewhere, leave it False and pass
    ``server=None``.
    """
    user_rows = np.asarray(user_rows, np.int64)
    if user_rows.shape[0] < num_requests:
        user_rows = np.resize(user_rows, num_requests)
    # Latency accounting is a bounded histogram reservoir (ISSUE 14), not
    # the old per-request lists: outstanding sends are the only O(live)
    # state (entries leave the dict the moment their response arrives),
    # so memory is O(1) in request count while the p50/p99 contract is
    # unchanged (exact while answered <= LATENCY_RESERVOIR).
    outstanding: dict[int, float] = {}  # req_id -> scheduled send wall
    lat_hist = Histogram("serve_request_latency_ms",
                         reservoir=LATENCY_RESERVOIR)
    # warm-up batches before this run must not count against it
    batches_before = getattr(server, "batches", 0)

    def drain():
        for resp in client.poll_responses():
            scheduled = outstanding.pop(resp.req_id, None)
            if scheduled is not None:
                lat_hist.observe((clock() - scheduled) * 1e3)

    t0 = clock()
    for i in range(num_requests):
        scheduled = t0 + i / rate_qps
        while True:
            now = clock()
            if now >= scheduled:
                break
            if drive_server and server is not None and server.step():
                drain()
                continue
            drain()
            sleep(min(scheduled - now, 0.001))
        rid = client.request(int(user_rows[i]), k)
        client.flush()
        # latency clock starts at the SCHEDULED time: generator backlog
        # counts as server latency, not free slack (open-loop contract)
        outstanding[rid] = scheduled
        drain()
    deadline = clock() + timeout_s
    while outstanding:
        if drive_server and server is not None:
            server.step()
        drain()
        if clock() > deadline:
            break
        if not drive_server:
            sleep(0.001)
    wall = max(clock() - t0, 1e-9)
    answered = lat_hist.count
    if answered == 0:
        raise TimeoutError(
            f"no responses within {timeout_s}s — server not draining"
        )
    batches = getattr(server, "batches", 0) - batches_before
    return LoadReport(
        num_requests=num_requests,
        answered=answered,
        wall_s=wall,
        qps_target=rate_qps,
        qps_achieved=answered / wall,
        p50_ms=lat_hist.quantile(0.5),
        p99_ms=lat_hist.quantile(0.99),
        max_ms=lat_hist.max,
        batches=int(batches),
        mean_batch=(answered / batches if batches else 0.0),
    )
