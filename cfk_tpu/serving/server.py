"""Request server: top-K queries over the transport's partitioned log.

The serving analog of the streaming consumer — requests arrive on a
``serve-requests`` topic (any Transport: InMemory for tests, FileBroker,
or the native TCP broker for cross-process serving), the server coalesces
everything currently pending into ONE scoring batch (bounded by
``max_batch``), runs it through the ``ServeEngine`` (whose pow2 batch
bucketing turns the coalesced sizes into a handful of compiled programs),
and answers on a ``serve-responses`` topic partition chosen by the client
(one partition per client — responses need no routing logic beyond the
partition, the same PureModPartitioner spirit as everything else).

Batching is the throughput lever, exactly as it was for the reference's
Kafka producer and PR 6's fold-in micro-batches: under open-loop load the
natural batch size self-tunes — a busy server finds more requests pending
per poll, amortizing the per-batch dispatch over more queries, which is
what makes the QPS-vs-latency trade measurable (``bench.py --serve``).
"""

from __future__ import annotations

import time

import numpy as np

from cfk_tpu.serving.topk_kernel import _pow2_ceil
from cfk_tpu.telemetry import record_event, span
from cfk_tpu.transport.serdes import (
    ScoreRequest,
    ScoreResponse,
    decode_score_request,
    decode_score_response,
    encode_score_request,
    encode_score_response,
)

REQUESTS_TOPIC = "serve-requests"
RESPONSES_TOPIC = "serve-responses"


def ensure_serve_topics(transport, *, requests_topic: str = REQUESTS_TOPIC,
                        responses_topic: str = RESPONSES_TOPIC,
                        request_partitions: int = 1,
                        response_partitions: int = 1) -> None:
    """Create the serve topics if absent (existing ones keep their own
    partition counts, like the updates topic)."""
    for name, parts in ((requests_topic, request_partitions),
                        (responses_topic, response_partitions)):
        try:
            transport.num_partitions(name)
        except KeyError:
            transport.create_topic(name, parts)


class RecommendServer:
    """Drain score requests from the log, answer in coalesced batches."""

    def __init__(
        self,
        engine,
        transport,
        *,
        requests_topic: str = REQUESTS_TOPIC,
        responses_topic: str = RESPONSES_TOPIC,
        max_batch: int = 256,
        poll_wait_s: float = 0.002,
        metrics=None,
        metrics_port: int | None = None,
        partitions=None,
        admission=None,
        staleness_fn=None,
        labels: dict | None = None,
    ) -> None:
        from cfk_tpu.utils.metrics import Metrics

        self.engine = engine
        self.transport = transport
        self.requests_topic = requests_topic
        self.responses_topic = responses_topic
        self.max_batch = int(max_batch)
        self.poll_wait_s = poll_wait_s
        self.metrics = metrics if metrics is not None else Metrics()
        # Fleet seams (ISSUE 18): ``partitions`` restricts this server to
        # its OWN request partitions (a fleet replica owns partition i of
        # N; standalone servers keep draining them all); ``admission``
        # sheds polled backlog beyond the controller's queue depth with
        # retriable rejections; ``staleness_fn`` supplies the per-response
        # staleness bound (the replica's unapplied delta backlog).
        self.admission = admission
        self._staleness_fn = staleness_fn
        nparts = transport.num_partitions(requests_topic)
        own = (range(nparts) if partitions is None
               else [int(p) for p in partitions])
        self._cursors = {p: 0 for p in own}
        # Committed cursors move only AFTER a batch's responses are
        # produced and flushed — the failover handoff point: a survivor
        # adopting a dead replica's partition resumes here, re-serving
        # (at-least-once) anything the victim had polled but not yet
        # answered, so no accepted request is ever silently lost.
        self.committed_cursors = dict(self._cursors)
        self.requests_served = 0
        self.batches = 0
        self.malformed_requests = 0
        self.shed = 0
        # Live metrics export (ISSUE 14): with a port, this server scrapes
        # — GET /metrics answers the Prometheus text rendering of
        # self.metrics even while batches are in flight (the registry is
        # thread-safe; 0 binds an ephemeral port, read it back from
        # .metrics_server.port).  /readyz reports the ENGINE's readiness
        # (prewarmed + epoch table loaded), distinct from /healthz
        # liveness; ``labels`` ride every sample (per-replica attribution
        # through the PR 16 constant-label seam).
        self.metrics_server = None
        if metrics_port is not None:
            from cfk_tpu.telemetry import MetricsHTTPServer

            self.metrics_server = MetricsHTTPServer(
                self.metrics, port=int(metrics_port), labels=labels,
                ready_fn=lambda: self.ready,
            ).start()

    @property
    def ready(self) -> bool:
        """Readiness = the engine's (prewarmed + table loaded); engines
        without the flag (doubles in tests) read as ready."""
        return bool(getattr(self.engine, "ready", True))

    def adopt_partition(self, partition: int, cursor: int = 0) -> None:
        """Take over a request partition at ``cursor`` (failover: the
        supervisor hands a dead replica's partition to a survivor at the
        victim's COMMITTED cursor)."""
        p = int(partition)
        self._cursors[p] = int(cursor)
        self.committed_cursors[p] = int(cursor)

    def close(self) -> None:
        """Release the /metrics endpoint (idempotent)."""
        if self.metrics_server is not None:
            self.metrics_server.stop()
            self.metrics_server = None

    def __enter__(self) -> "RecommendServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def _poll_requests(self) -> list[ScoreRequest]:
        """Everything currently pending, up to ``max_batch``, in
        (partition, offset) order — the same deterministic order the
        streaming consumer uses."""
        out: list[ScoreRequest] = []
        for p in sorted(self._cursors):
            if len(out) >= self.max_batch:
                break
            take = self.max_batch - len(out)
            got = 0
            for rec in self.transport.consume(
                self.requests_topic, p, self._cursors[p]
            ):
                got += 1  # cursor advances past the frame either way: a
                # malformed frame must be SKIPPED, not re-read forever —
                # re-raising before the cursor moved would wedge every
                # restart on the same poison offset
                try:
                    out.append(decode_score_request(rec.value))
                except ValueError:
                    self.malformed_requests += 1
                    self.metrics.incr("serve_malformed_requests")
                if got >= take:
                    break
            self._cursors[p] += got
        return out

    def _stamp(self) -> tuple[int, int]:
        """(epoch, staleness) for this batch's response stamps."""
        epoch = int(getattr(self.engine, "epoch", 0))
        stale = 0
        if self._staleness_fn is not None:
            try:
                stale = int(self._staleness_fn())
            except Exception:
                stale = -1  # unknown beats a silently-wrong 0
        return epoch, stale

    def step(self) -> int:
        """Serve ONE coalesced batch; returns the number of requests
        answered (0 = nothing pending).  Requests shed by admission
        control are answered too — with an explicit RETRIABLE rejection,
        never a silent drop — and count toward the return value."""
        reqs = self._poll_requests()
        # a fuzzed frame can decode into a request whose reply_partition
        # doesn't exist — unanswerable (there is no partition to refuse
        # it on), so it is counted and dropped BEFORE admission rather
        # than letting the produce raise and kill its co-batched
        # neighbors (or consume queue depth a real request needed)
        nresp = self.transport.num_partitions(self.responses_topic)
        routable = []
        for r in reqs:
            if 0 <= r.reply_partition < nresp:
                routable.append(r)
            else:
                self.malformed_requests += 1
                self.metrics.incr("serve_malformed_requests")
        reqs = routable
        if not reqs:
            return 0
        shed: list[ScoreRequest] = []
        if self.admission is not None:
            reqs, shed = self.admission.admit(reqs)
        t_batch = time.perf_counter()
        epoch, staleness = self._stamp()
        with self.metrics.phase("serve_batch"), \
                span("serve/batch", requests=len(reqs), shed=len(shed)):
            # Refuse out-of-range rows per REQUEST (an error response),
            # never per batch — one bad query must not poison its
            # co-batched neighbors.
            with span("serve/batch/validate", requests=len(reqs)):
                valid: list[ScoreRequest] = []
                errors: list[ScoreRequest] = []
                for r in reqs:
                    ok = (0 <= r.user < self.engine.num_users
                          and 1 <= r.k <= self.engine.num_movies)
                    (valid if ok else errors).append(r)
            responses: list[tuple[int, ScoreResponse]] = []
            if valid:
                k_pad = _pow2_ceil(
                    max(r.k for r in valid),
                    min(8, self.engine.num_movies),
                )
                k_pad = min(k_pad, self.engine.num_movies)
                rows = np.asarray([r.user for r in valid], np.int64)
                # engine.topk opens the serve/batch/assemble + compute
                # spans — the kernel side of this batch's timeline
                scores, ids = self.engine.topk(rows, k_pad)
                for i, r in enumerate(valid):
                    responses.append((r.reply_partition, ScoreResponse(
                        req_id=r.req_id,
                        movie_rows=ids[i, : r.k],
                        scores=scores[i, : r.k],
                        epoch=epoch, staleness=staleness,
                    )))
            for r in errors:
                responses.append((r.reply_partition, ScoreResponse(
                    req_id=r.req_id,
                    movie_rows=np.zeros(0, np.int32),
                    scores=np.zeros(0, np.float32),
                    error=(f"user row {r.user} out of range "
                           f"[0, {self.engine.num_users}) or k {r.k} "
                           f"outside [1, {self.engine.num_movies}]"),
                    epoch=epoch, staleness=staleness,
                )))
            for r in shed:
                # Explicit retriable rejection: the client backs off and
                # re-sends; the request is ANSWERED, not dropped.
                responses.append((r.reply_partition, ScoreResponse(
                    req_id=r.req_id,
                    movie_rows=np.zeros(0, np.int32),
                    scores=np.zeros(0, np.float32),
                    error="overloaded: admission queue depth exceeded",
                    retriable=True, epoch=epoch, staleness=staleness,
                )))
            with span("serve/batch/respond", responses=len(responses)):
                for part, resp in responses:
                    self.transport.produce(
                        self.responses_topic,
                        key=int(resp.req_id % (1 << 31)),
                        value=encode_score_response(resp), partition=part,
                    )
                flush = getattr(self.transport, "flush", None)
                if flush is not None:
                    flush()
        # Responses durable → commit the read cursors (failover handoff).
        self.committed_cursors.update(self._cursors)
        self.requests_served += len(reqs)
        self.batches += 1
        if shed:
            self.shed += len(shed)
            self.metrics.incr("serve_shed", len(shed))
            record_event("serve", "shed", requests=len(shed),
                         served=len(reqs))
        self.metrics.incr("serve_requests", len(reqs))
        self.metrics.incr("serve_batches")
        # Bounded-reservoir latency distributions (ISSUE 14): per-batch
        # wall and coalesced size — the /metrics summary quantiles.
        self.metrics.observe("serve_batch_ms",
                             (time.perf_counter() - t_batch) * 1e3)
        self.metrics.observe("serve_batch_size", len(reqs))
        record_event("serve", "batch", requests=len(reqs),
                     batch=self.batches)
        return len(reqs) + len(shed)

    def serve_forever(self, *, max_requests: int | None = None,
                      idle_timeout_s: float | None = None,
                      stop=None) -> int:
        """Poll-and-serve loop; returns requests served.  Stops when
        ``stop()`` goes true, after ``max_requests``, or once the topic
        has been idle ``idle_timeout_s`` (None = keep polling)."""
        served = 0
        idle_since = time.monotonic()
        while True:
            if stop is not None and stop():
                return served
            if max_requests is not None and served >= max_requests:
                return served
            got = self.step()
            if got:
                served += got
                idle_since = time.monotonic()
                continue
            if (idle_timeout_s is not None
                    and time.monotonic() - idle_since >= idle_timeout_s):
                return served
            time.sleep(self.poll_wait_s)


class ServeClient:
    """Produce score requests, consume this client's response partition."""

    def __init__(
        self,
        transport,
        *,
        reply_partition: int = 0,
        requests_topic: str = REQUESTS_TOPIC,
        responses_topic: str = RESPONSES_TOPIC,
        route_by_user: bool = False,
        metrics=None,
    ) -> None:
        import os

        self.transport = transport
        self.requests_topic = requests_topic
        self.responses_topic = responses_topic
        self.reply_partition = int(reply_partition)
        self._req_parts = transport.num_partitions(requests_topic)
        # Fleet routing (ISSUE 18): user-keyed partitioning pins every
        # request for a user onto ONE replica's partition (user % N — the
        # PureModPartitioner rule), so a user's answers come from a single
        # hot-row overlay; the default req_id spread stays for standalone
        # servers, where any partition reaches the one server anyway.
        self.route_by_user = bool(route_by_user)
        self.metrics = metrics
        # req_ids start at a random 40-bit base: the response partition is
        # supposed to be one-per-client, but if two clients DO share one
        # (misconfiguration), colliding id sequences would silently
        # mis-attribute answers — a random base makes that astronomically
        # unlikely instead of guaranteed.
        self._next_req = int.from_bytes(os.urandom(5), "big") << 16
        self._cursor = transport.end_offset(responses_topic, reply_partition)
        self.malformed_responses = 0
        self.retries = 0
        self.rejections = 0

    def request(self, user: int, k: int) -> int:
        """Send one query; returns its req_id (the response's echo key)."""
        req_id = self._next_req
        self._next_req += 1
        part = (int(user) if self.route_by_user else req_id) % self._req_parts
        self.transport.produce(
            self.requests_topic,
            key=int(user) % (1 << 31),
            value=encode_score_request(ScoreRequest(
                req_id=req_id, user=int(user), k=int(k),
                reply_partition=self.reply_partition,
            )),
            partition=part,
        )
        return req_id

    def flush(self) -> None:
        flush = getattr(self.transport, "flush", None)
        if flush is not None:
            flush()

    def poll_responses(self) -> list[ScoreResponse]:
        """All responses that arrived since the last poll.  A malformed
        frame is counted and skipped with the cursor advanced — the same
        no-wedge rule as the server's request poll."""
        out = []
        seen = 0
        for rec in self.transport.consume(
            self.responses_topic, self.reply_partition, self._cursor
        ):
            seen += 1
            try:
                out.append(decode_score_response(rec.value))
            except ValueError:
                self.malformed_responses += 1
        self._cursor += seen
        return out

    def ask(self, users, k: int, *, server=None, timeout_s: float = 30.0,
            poll_wait_s: float = 0.002, retries: int = 3,
            backoff_base: float = 0.02, rng=None,
            sleep=time.sleep) -> dict[int, ScoreResponse]:
        """Blocking convenience: send, then poll until every response is
        back — driving ``server.step()`` inline when one is given (the
        single-threaded test mode; with a live server thread/process pass
        None).  Returns {req_id: response} keyed by the FIRST-attempt
        req_ids (stable for callers even when a retry re-sent a query
        under a fresh id).

        Resilience (ISSUE 18): instead of one hard raise at the deadline,
        the poll window splits across ``retries + 1`` attempts with
        exponential backoff + jitter between them (``resilience.retry``
        schedule; ``rng``/``sleep`` injectable so tests assert without
        waiting).  A RETRIABLE rejection (admission-control shed) and a
        response that never arrived (dead replica mid-failover) are both
        re-sent; permanent errors are final answers.  The final failure
        is still a TimeoutError — bounded, never an infinite loop."""
        from cfk_tpu.resilience.retry import backoff_delays

        self.flush()
        ids = [self.request(int(u), k) for u in users]
        self.flush()
        user_of = {rid: int(u) for rid, u in zip(ids, users)}
        alias: dict[int, int] = {}  # re-sent req_id -> original req_id
        got: dict[int, ScoreResponse] = {}
        attempts = max(int(retries), 0) + 1
        window = max(timeout_s / attempts, poll_wait_s)
        delays = backoff_delays(base=backoff_base, rng=rng)

        rejected: set[int] = set()  # orig ids shed THIS attempt

        def drain() -> None:
            for resp in self.poll_responses():
                orig = alias.get(resp.req_id, resp.req_id)
                if orig not in user_of:
                    continue  # stale duplicate from a pre-failover serve
                if resp.retriable:
                    self.rejections += 1
                    rejected.add(orig)
                    if self.metrics is not None:
                        self.metrics.incr("serve_client_rejections")
                    continue  # shed — stays missing, re-sent next attempt
                got.setdefault(orig, resp)

        for attempt in range(attempts):
            deadline = time.monotonic() + window
            rejected.clear()
            while set(user_of) - set(got):
                if server is not None:
                    server.step()
                drain()
                missing_now = set(user_of) - set(got)
                if missing_now:
                    # every straggler already answered "retry later" —
                    # nothing more arrives this attempt, back off now
                    if missing_now <= rejected:
                        break
                    if time.monotonic() > deadline:
                        break
                    if server is None:
                        sleep(poll_wait_s)
            missing = set(user_of) - set(got)
            if not missing:
                return got
            if attempt == attempts - 1:
                break
            sleep(next(delays))
            for orig in sorted(missing):
                new_id = self.request(user_of[orig], k)
                alias[new_id] = orig
                self.retries += 1
                if self.metrics is not None:
                    self.metrics.incr("serve_client_retries")
            self.flush()
        raise TimeoutError(
            f"{len(set(user_of) - set(got))} of {len(ids)} responses "
            f"missing after {timeout_s}s ({attempts} attempts, "
            f"{self.rejections} rejections seen)"
        )
