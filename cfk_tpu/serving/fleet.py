"""Replicated serving fleet (ISSUE 18 / ROADMAP item 3): failover,
admission control, versioned factor-delta shipping, zero-downtime epoch
rollover.

The reference's single-partition ``FeatureCollector`` was the serving
ceiling the paper never solved; one ``RecommendServer`` inherits it.  At
the ALX fleet regime (arXiv 2112.02194) the serving tier must match the
training tier's shape, and the iALS++ fold-in cadence (arXiv 2110.14044)
means fresh factor rows arrive continuously.  This module puts N replicas
behind the request log and makes the robustness claims testable:

- **Routing** — the requests topic carries one partition per replica and
  clients route user-keyed (``user % N``, the PureModPartitioner rule),
  so a user's traffic always lands on the replica holding their hot-row
  overlay.  Item-axis sharding stays per replica: each replica's engine
  may run the ``serve_topk_sharded`` merge over its own mesh.
- **Delta shipping** — the ``StreamSession`` commit listener is framed as
  epoch+seq-tagged ``FactorDelta`` messages on a durable single-partition
  deltas topic (``DeltaPublisher``).  Seq is strictly increasing; the
  PR 14 hot/cold split (running touch counts → ``knee_hot_rows``) decides
  which rows ship EAGERLY with factors in-frame and which ship as lazy
  ids whose factors live only in the ``SnapshotStore`` — replicas pull
  those in bulk before the next batch they serve (staleness bounded by
  one poll cycle, recorded per response).
- **Gap recovery** — a replica applies deltas strictly in seq order; a
  hole (lost/tampered frame) is detected LOUDLY (flight-recorder event +
  dump) and recovered by a full epoch-snapshot resync from the store —
  bit-exact vs a fresh engine, which ``table_crc`` lets tests pin.
- **Rollover** — a warm retrain announces a new epoch (``kind="epoch"``
  frame; the snapshot itself goes to the store, not the log).  The
  replica builds + ``prewarm()``s the new-epoch engine on a BACKGROUND
  thread while the old epoch keeps answering, then flips one reference
  at a batch boundary — zero downtime, and no request ever observes a
  mixed-epoch table (each batch captures exactly one engine).
- **Admission control** — ``AdmissionController`` bounds the per-poll
  queue depth (fed from loadgen-measured capacity); backlog beyond it is
  answered with explicit RETRIABLE rejections, never silently dropped.
- **Failover** — ``kill_replica`` stops a replica abruptly (mid-batch,
  worst case); the supervisor reassigns its partition to a survivor at
  the victim's COMMITTED cursor (advanced only after responses flushed),
  so every accepted request is re-served — at-least-once, deduped
  client-side by req_id, the consumer-group-rebalance analog.
"""

from __future__ import annotations

import threading
import time
import zlib

import numpy as np

from cfk_tpu.serving.server import (
    REQUESTS_TOPIC,
    RESPONSES_TOPIC,
    RecommendServer,
    ensure_serve_topics,
)
from cfk_tpu.telemetry import dump_flight, record_event, span
from cfk_tpu.transport.serdes import (
    FactorDelta,
    decode_factor_delta,
    encode_factor_delta,
    make_factor_delta,
)

DELTAS_TOPIC = "factor-deltas"


def ensure_deltas_topic(transport, *, topic: str = DELTAS_TOPIC) -> None:
    """Create the deltas topic if absent — ONE partition by design: seq
    order is the gap detector's whole contract, and a multi-partition
    delta log would interleave it away."""
    try:
        transport.num_partitions(topic)
    except KeyError:
        transport.create_topic(topic, 1)


def table_crc(engine) -> int:
    """crc32 of the engine's EFFECTIVE user factor table (base snapshot
    with the hot overlay applied, ``num_users`` rows) — the bit-exactness
    witness of the resync contract: a resynced replica must match a fresh
    engine that applied every commit."""
    with engine._lock:
        k = engine._u_base.shape[1]
        u = np.zeros((engine.num_users, k), np.float32)
        n = min(engine._u_base.shape[0], engine.num_users)
        u[:n] = engine._u_base[:n]
        for row, f in engine._u_hot.items():
            if 0 <= row < engine.num_users:
                u[row] = np.asarray(f, np.float32)
    return zlib.crc32(u.tobytes())


class SnapshotStore:
    """Durable epoch snapshots + a compacted per-row overlay.

    The side channel next to the deltas topic (the compacted-topic analog
    — Kafka ships state changes on a log and full state in a compacted
    store; we do the same): the publisher writes every epoch's full
    factor snapshot here, plus EVERY shipped row synchronously before the
    delta frame is produced, so a replica recovering from a gap can
    always rebuild bit-exact state no matter which frames it lost.  Lazy
    (cold) rows are served from the same overlay on demand."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._epochs: dict[int, dict] = {}
        self.latest_epoch = 0
        self.lazy_reads = 0

    def put_epoch(self, epoch: int, user_factors, movie_factors, *,
                  num_users: int, seq: int) -> None:
        """Install a full snapshot for ``epoch`` (copies taken).  ``seq``
        is the last delta seq the snapshot already contains — a resync
        from this epoch resumes strictly after it."""
        with self._lock:
            self._epochs[int(epoch)] = {
                "user_factors": np.array(user_factors, np.float32),
                "movie_factors": np.array(movie_factors, np.float32),
                "num_users": int(num_users),
                "seq": int(seq),
                "overlay": {},
                "cells": [],
            }
            self.latest_epoch = max(self.latest_epoch, int(epoch))

    def put_rows(self, epoch: int, rows, factors, cells=(),
                 *, num_users: int | None = None, seq: int | None = None
                 ) -> None:
        """Fold one commit's rows/cells into the epoch's overlay — called
        by the publisher BEFORE the delta frame is produced, so the store
        is never behind the log."""
        with self._lock:
            e = self._epochs[int(epoch)]
            f = np.asarray(factors, np.float32)
            for i, row in enumerate(np.asarray(rows).reshape(-1)):
                e["overlay"][int(row)] = np.array(f[i], np.float32)
            e["cells"].extend((int(r), int(m)) for r, m in cells)
            if num_users is not None:
                e["num_users"] = max(e["num_users"], int(num_users))
            if seq is not None:
                e["seq"] = max(e["seq"], int(seq))

    def get_rows(self, epoch: int, rows) -> np.ndarray:
        """Factors for ``rows`` from the epoch's overlay (falling back to
        the base snapshot) — the lazy-pull path for cold rows."""
        with self._lock:
            e = self._epochs[int(epoch)]
            base = e["user_factors"]
            out = np.zeros((len(rows), base.shape[1]), np.float32)
            for i, row in enumerate(rows):
                row = int(row)
                hot = e["overlay"].get(row)
                if hot is not None:
                    out[i] = hot
                elif row < base.shape[0]:
                    out[i] = base[row]
            self.lazy_reads += len(rows)
        return out

    def state(self, epoch: int | None = None) -> dict:
        """A consistent copy of one epoch's full state (base + overlay +
        cells + last seq) — the resync/rollover payload."""
        with self._lock:
            e = self._epochs[
                self.latest_epoch if epoch is None else int(epoch)
            ]
            return {
                "epoch": (self.latest_epoch if epoch is None
                          else int(epoch)),
                "user_factors": np.array(e["user_factors"]),
                "movie_factors": np.array(e["movie_factors"]),
                "num_users": e["num_users"],
                "seq": e["seq"],
                "overlay": {r: np.array(f)
                            for r, f in e["overlay"].items()},
                "cells": list(e["cells"]),
            }


class DeltaPublisher:
    """Frame ``StreamSession`` commits as ``FactorDelta`` messages.

    Attach with ``session.add_commit_listener(pub.on_commit)`` (or
    ``pub.attach(session)``).  Every commit becomes one seq-tagged frame
    on the deltas topic; the hot/cold split (running per-row touch
    counts → ``offload.hot.knee_hot_rows``, the PR 14 knee) decides
    eager-push (factors in-frame) vs lazy (ids only; factors reach
    replicas through the ``SnapshotStore`` overlay).  A retrain commit
    snapshots the new epoch into the store and announces it with a
    ``kind="epoch"`` frame."""

    def __init__(self, transport, store: SnapshotStore, *,
                 topic: str = DELTAS_TOPIC, epoch: int = 0,
                 metrics=None) -> None:
        self.transport = transport
        self.store = store
        self.topic = topic
        self.epoch = int(epoch)
        self.metrics = metrics
        self.seq = 0
        self.eager_rows = 0
        self.lazy_rows = 0
        self._touch = np.zeros(0, np.int64)
        self._lock = threading.Lock()
        ensure_deltas_topic(transport, topic=self.topic)

    def attach(self, session) -> None:
        session.add_commit_listener(self.on_commit)

    def _split_hot_cold(self, rows: np.ndarray
                        ) -> tuple[np.ndarray, np.ndarray]:
        """(eager mask over ``rows``) via the knee of the running touch
        counts — a row re-solved often enough to sit above the knee ships
        eagerly; the long tail goes lazy.  First touches always ship
        eagerly (no history to justify deferring a brand-new row)."""
        from cfk_tpu.offload.hot import knee_hot_rows, select_hot_rows

        hi = int(rows.max()) + 1 if rows.size else 0
        if hi > self._touch.shape[0]:
            grown = np.zeros(hi, np.int64)
            grown[: self._touch.shape[0]] = self._touch
            self._touch = grown
        first = self._touch[rows] == 0
        self._touch[rows] += 1
        f = knee_hot_rows(self._touch)
        if f <= 0:
            return np.ones(rows.shape[0], bool), np.zeros(rows.shape[0],
                                                          bool)
        hot = set(int(r) for r in select_hot_rows(self._touch, f))
        eager = np.asarray(
            [bool(first[i]) or int(r) in hot for i, r in enumerate(rows)],
            bool,
        )
        return eager, ~eager

    def _produce(self, delta: FactorDelta) -> None:
        self.transport.produce(
            self.topic, key=delta.seq % (1 << 31),
            value=encode_factor_delta(delta), partition=0,
        )
        flush = getattr(self.transport, "flush", None)
        if flush is not None:
            flush()
        if self.metrics is not None:
            self.metrics.incr("fleet_deltas_published")

    def on_commit(self, event: dict) -> None:
        """One commit → one frame (the durable unit replicas apply)."""
        with self._lock:
            if event.get("retrain"):
                self.epoch += 1
                self.seq += 1
                self.store.put_epoch(
                    self.epoch, event["user_factors"],
                    event["movie_factors"],
                    num_users=int(event.get(
                        "num_users",
                        np.asarray(event["user_factors"]).shape[0],
                    )),
                    seq=self.seq,
                )
                delta = make_factor_delta(
                    self.epoch, self.seq, "epoch",
                    num_users=int(event.get("num_users", 0)),
                )
                record_event("fleet", "epoch_published", epoch=self.epoch,
                             seq=self.seq)
                self._produce(delta)
                return
            touched = np.asarray(event.get("touched_rows") or (),
                                 np.int64)
            rows = event.get("rows")
            cells = list(event.get("cells") or ())
            if touched.size == 0 and not cells:
                return
            f = (np.asarray(rows, np.float32) if rows is not None
                 else np.zeros((0, 0), np.float32))
            eager, lazy = (self._split_hot_cold(touched)
                           if touched.size
                           else (np.zeros(0, bool), np.zeros(0, bool)))
            self.seq += 1
            # store FIRST (every row, hot and cold), frame second — the
            # store is the recovery source and must never trail the log
            if touched.size:
                self.store.put_rows(
                    self.epoch, touched, f, cells,
                    num_users=event.get("num_users"), seq=self.seq,
                )
            elif cells:
                self.store.put_rows(self.epoch, (), f, cells,
                                    num_users=event.get("num_users"),
                                    seq=self.seq)
            self.eager_rows += int(eager.sum())
            self.lazy_rows += int(lazy.sum())
            if self.metrics is not None:
                self.metrics.incr("fleet_eager_rows", int(eager.sum()))
                self.metrics.incr("fleet_lazy_rows", int(lazy.sum()))
            delta = make_factor_delta(
                self.epoch, self.seq, "rows",
                num_users=int(event.get("num_users", 0)),
                user_rows=touched[eager], user_factors=f[eager],
                lazy_user_rows=touched[lazy], cells=cells,
                rank=f.shape[1] if f.ndim == 2 else 0,
            )
            self._produce(delta)


class AdmissionController:
    """Bounded queue depth with explicit retriable shedding.

    ``max_queue`` is the most requests one poll may admit — fed from
    loadgen-measured capacity (``capacity_qps × max_queue_s``: the
    backlog the replica can clear within the latency budget).  Backlog
    beyond it is returned as ``shed`` and the server answers each with a
    RETRIABLE rejection — bounded latency for what's admitted, an honest
    "try again" for the rest, never a silent drop."""

    def __init__(self, *, max_queue: int | None = None,
                 capacity_qps: float | None = None,
                 max_queue_s: float = 0.05, metrics=None) -> None:
        if max_queue is None:
            if capacity_qps is None:
                raise ValueError("pass max_queue or capacity_qps")
            max_queue = max(1, int(capacity_qps * max_queue_s))
        self.max_queue = int(max_queue)
        self.metrics = metrics
        self.admitted = 0
        self.shed = 0

    def admit(self, reqs: list) -> tuple[list, list]:
        """(admitted, shed) split of one poll's backlog, FIFO — the
        oldest requests keep their place in line."""
        take, rest = reqs[: self.max_queue], reqs[self.max_queue:]
        self.admitted += len(take)
        self.shed += len(rest)
        if rest and self.metrics is not None:
            self.metrics.incr("admission_shed", len(rest))
        return take, rest


class FleetReplica:
    """One serving replica: a ``RecommendServer`` over its own request
    partition, a delta-apply loop, gap→resync recovery, and background
    epoch rollover.  Driven by its own thread (``ServeFleet``) or
    manually via ``pump()`` in single-threaded tests."""

    def __init__(self, index: int, engine, transport, store: SnapshotStore,
                 *, requests_topic: str = REQUESTS_TOPIC,
                 responses_topic: str = RESPONSES_TOPIC,
                 deltas_topic: str = DELTAS_TOPIC, max_batch: int = 256,
                 admission: AdmissionController | None = None,
                 metrics=None, metrics_port: int | None = None,
                 poll_wait_s: float = 0.001, prewarm_k: int = 10,
                 prewarm_batch: int | None = None) -> None:
        from cfk_tpu.utils.metrics import Metrics

        self.index = int(index)
        self.engine = engine
        self.transport = transport
        self.store = store
        self.deltas_topic = deltas_topic
        self.metrics = metrics if metrics is not None else Metrics()
        self.prewarm_k = int(prewarm_k)
        self.prewarm_batch = prewarm_batch or max_batch
        self.server = RecommendServer(
            engine, transport, requests_topic=requests_topic,
            responses_topic=responses_topic, max_batch=max_batch,
            poll_wait_s=poll_wait_s, metrics=self.metrics,
            metrics_port=metrics_port, partitions=[self.index],
            admission=admission, staleness_fn=self.staleness,
            labels={"replica": self.index},
        )
        self._delta_cursor = 0
        self.applied_seq = 0
        self.deltas_applied = 0
        self.gaps_detected = 0
        self.resyncs = 0
        self.rollovers = 0
        self.lazy_pending: set[int] = set()
        self.lazy_pulls = 0
        self._deferred: list[FactorDelta] = []
        self._pending: tuple[int, object, int] | None = None
        self._pending_thread: threading.Thread | None = None
        self._stop = threading.Event()
        self._kill = threading.Event()
        self._stopped = False
        self._thread: threading.Thread | None = None

    # -- delta application ---------------------------------------------------

    def staleness(self) -> int:
        """Unapplied delta backlog (frames): the per-response staleness
        bound every answer is stamped with."""
        end = self.transport.end_offset(self.deltas_topic, 0)
        return max(int(end) - self._delta_cursor, 0)

    def apply_deltas(self) -> int:
        """Drain the deltas topic in order; returns frames applied.
        ``seq`` must advance by exactly one — anything else is a GAP,
        detected loudly and recovered by a full snapshot resync."""
        applied = 0
        for rec in self.transport.consume(
            self.deltas_topic, 0, self._delta_cursor
        ):
            self._delta_cursor += 1
            try:
                d = decode_factor_delta(rec.value)
            except ValueError as e:
                # a tampered frame is a gap with a different spelling —
                # its seq is unknowable, so resync is the only recovery
                self._gap(expected=self.applied_seq + 1,
                          got=None, reason=f"undecodable frame: {e}")
                continue
            if d.seq <= self.applied_seq:
                continue  # duplicate / already covered by a resync
            if d.seq != self.applied_seq + 1:
                self._gap(expected=self.applied_seq + 1, got=d.seq,
                          reason="seq hole")
                if d.seq <= self.applied_seq:
                    continue  # the resync already covered this frame
            self._apply(d)
            self.applied_seq = max(self.applied_seq, d.seq)
            applied += 1
        if applied:
            self.deltas_applied += applied
            self.metrics.incr("fleet_deltas_applied", applied)
        return applied

    def _apply(self, d: FactorDelta) -> None:
        if d.kind == "epoch":
            self._begin_rollover(d.epoch)
            return
        if d.epoch != int(getattr(self.engine, "epoch", 0)):
            # rows for an epoch we have not flipped to yet: hold them in
            # seq order and replay at the flip
            self._deferred.append(d)
            return
        event = {
            "touched_rows": [int(r) for r in d.user_rows],
            "rows": d.user_factors,
            "cells": [(int(r), int(m)) for r, m in d.cells],
            "retrain": False,
        }
        if d.num_users:
            event["num_users"] = int(d.num_users)
        if d.movie_rows.size:
            event["movie_rows"] = d.movie_rows
            event["movie_row_factors"] = d.movie_factors
        self.engine.on_commit(event)
        # cold rows: factors are in the store, not the frame — remember
        # them and pull in bulk before the next served batch
        self.lazy_pending.update(int(r) for r in d.lazy_user_rows)

    def pull_lazy(self) -> int:
        """Bulk-pull pending cold rows from the store overlay into the
        engine's hot cache — called right before serving, so a lazy row's
        staleness is bounded by one poll cycle."""
        if not self.lazy_pending:
            return 0
        rows = sorted(self.lazy_pending)
        self.lazy_pending.clear()
        factors = self.store.get_rows(
            int(getattr(self.engine, "epoch", 0)), rows
        )
        self.engine.on_commit({
            "touched_rows": rows, "rows": factors, "cells": [],
            "retrain": False,
        })
        self.lazy_pulls += len(rows)
        self.metrics.incr("fleet_lazy_pulled", len(rows))
        return len(rows)

    def _gap(self, *, expected: int, got, reason: str) -> None:
        self.gaps_detected += 1
        self.metrics.incr("fleet_delta_gaps")
        record_event("fleet", "delta_gap", replica=self.index,
                     expected_seq=expected, got_seq=got, reason=reason)
        dump_flight(f"serve_delta_gap replica={self.index}")
        self.resync()

    def resync(self) -> None:
        """Full epoch-snapshot recovery: rebuild the engine's user-side
        state from the store's consistent copy — bit-exact vs a fresh
        engine (``table_crc`` pins it) — and resume strictly after the
        snapshot's last folded seq."""
        with span("serve/fleet/resync", replica=self.index):
            snap = self.store.state()
            same_epoch = (snap["epoch"]
                          == int(getattr(self.engine, "epoch", 0)))
            self.engine.load_state(
                snap["user_factors"],
                None if same_epoch else snap["movie_factors"],
                hot_rows=snap["overlay"], seen_cells=snap["cells"],
                num_users=snap["num_users"], epoch=snap["epoch"],
            )
            self.applied_seq = snap["seq"]
            self.lazy_pending.clear()
            self._deferred = [d for d in self._deferred
                              if d.seq > snap["seq"]]
        self.resyncs += 1
        self.metrics.incr("fleet_resyncs")
        record_event("fleet", "resync", replica=self.index,
                     epoch=snap["epoch"], seq=snap["seq"])

    # -- epoch rollover ------------------------------------------------------

    def _begin_rollover(self, epoch: int) -> None:
        """Prewarm the new epoch OFF the serving path: a background
        thread builds a fresh engine from the epoch snapshot and runs the
        PR 12 ``prewarm()`` readiness gate; the old epoch keeps answering
        until ``maybe_flip`` swaps one reference at a batch boundary."""
        if self._pending_thread is not None \
                and self._pending_thread.is_alive():
            return  # a newer epoch frame will re-trigger after the flip
        record_event("fleet", "rollover_begin", replica=self.index,
                     epoch=epoch)

        def build() -> None:
            from cfk_tpu.serving.engine import ServeEngine

            with span("serve/fleet/rollover", replica=self.index,
                      epoch=epoch):
                snap = self.store.state(epoch)
                old = self.engine
                eng = ServeEngine(
                    snap["user_factors"], snap["movie_factors"],
                    num_users=snap["num_users"],
                    num_movies=old.num_movies,
                    seen_movies=old._seen_movies,
                    seen_indptr=old._seen_indptr,
                    table_dtype=old.table_dtype, tile_m=old.tile_m,
                    batch_quantum=old.batch_quantum,
                    serve_mode=old.serve_mode,
                    metrics=self.metrics,
                )
                eng.epoch = snap["epoch"]
                for row, f in snap["overlay"].items():
                    eng._u_hot[int(row)] = np.asarray(f, np.float32)
                for row, mv in snap["cells"]:
                    eng._seen_hot.setdefault(int(row), []).append(int(mv))
                eng.prewarm(self.prewarm_k, max_batch=self.prewarm_batch)
                self._pending = (snap["epoch"], eng, snap["seq"])

        t = threading.Thread(target=build, daemon=True,
                             name=f"cfk-rollover-{self.index}")
        self._pending_thread = t
        t.start()

    def maybe_flip(self) -> bool:
        """The single pointer flip: if a prewarmed new-epoch engine is
        ready, swap it in between batches and replay any deferred
        new-epoch deltas.  Returns True on a flip."""
        pending = self._pending
        if pending is None:
            return False
        epoch, eng, base_seq = pending
        self._pending = None
        old_epoch = int(getattr(self.engine, "epoch", 0))
        self.engine = eng
        self.server.engine = eng  # the atomic handoff: one assignment
        self.applied_seq = max(self.applied_seq, base_seq)
        deferred, self._deferred = self._deferred, []
        for d in sorted(deferred, key=lambda x: x.seq):
            if d.seq > base_seq:
                self._apply(d)
                self.applied_seq = max(self.applied_seq, d.seq)
        self.rollovers += 1
        self.metrics.incr("fleet_rollovers")
        self.metrics.gauge("fleet_epoch", epoch)
        record_event("fleet", "rollover_flip", replica=self.index,
                     old_epoch=old_epoch, epoch=epoch)
        return True

    # -- serve loop ----------------------------------------------------------

    def pump(self) -> int:
        """One supervised iteration: flip if a new epoch is ready, apply
        deltas, pull lazy rows, serve one coalesced batch."""
        self.maybe_flip()
        self.apply_deltas()
        self.pull_lazy()
        return self.server.step()

    def _run(self) -> None:
        while not self._stop.is_set():
            if self._kill.is_set():
                return  # abrupt death: no cursor commit, no farewell
            got = self.pump()
            if self._kill.is_set():
                return
            if not got:
                time.sleep(self.server.poll_wait_s)

    def start(self) -> "FleetReplica":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._stopped = False
            self._thread = threading.Thread(
                target=self._run, daemon=True,
                name=f"cfk-replica-{self.index}",
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._stopped = True
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        if self._pending_thread is not None:
            self._pending_thread.join(timeout=30.0)
            self._pending_thread = None
        self.server.close()

    def kill(self) -> None:
        """Abrupt termination (the SIGKILL stand-in): the loop exits at
        the next instruction boundary WITHOUT committing cursors — polled
        but unanswered requests are left for the survivor to re-serve."""
        self._kill.set()
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        self.server.close()

    @property
    def alive(self) -> bool:
        """Not killed, and (when threaded) the loop is still running — a
        constructed-but-unstarted replica counts as alive: it serves via
        ``pump()`` and is a valid failover heir."""
        if self._kill.is_set() or self._stopped:
            return False
        return self._thread is None or self._thread.is_alive()


class ServeFleet:
    """N replicas behind the request log, one supervisor.

    ``engine_factory(i)`` builds replica i's engine (full table copies on
    one host; per-replica meshes in a real deployment).  The fleet
    creates the topics (requests: N partitions — one per replica;
    responses: per client; deltas: 1), wires the publisher's store into
    every replica, prewarms (the readiness gate), and runs one thread per
    replica.  ``kill_replica`` + automatic failover reassigns the
    victim's partition to a survivor at the committed cursor."""

    def __init__(self, engine_factory, transport, *, replicas: int = 2,
                 store: SnapshotStore | None = None,
                 requests_topic: str = REQUESTS_TOPIC,
                 responses_topic: str = RESPONSES_TOPIC,
                 deltas_topic: str = DELTAS_TOPIC,
                 response_partitions: int = 1, max_batch: int = 256,
                 admission_max_queue: int | None = None,
                 capacity_qps: float | None = None,
                 metrics_ports: bool = False, prewarm_k: int = 10,
                 poll_wait_s: float = 0.001) -> None:
        if replicas < 1:
            raise ValueError(f"replicas must be >= 1, got {replicas}")
        self.transport = transport
        self.replicas: list[FleetReplica] = []
        self.requests_topic = requests_topic
        self.store = store if store is not None else SnapshotStore()
        ensure_serve_topics(
            transport, requests_topic=requests_topic,
            responses_topic=responses_topic,
            request_partitions=replicas,
            response_partitions=response_partitions,
        )
        ensure_deltas_topic(transport, topic=deltas_topic)
        nparts = transport.num_partitions(requests_topic)
        if nparts < replicas:
            raise ValueError(
                f"requests topic has {nparts} partitions for "
                f"{replicas} replicas — one per replica required"
            )
        for i in range(replicas):
            admission = None
            if admission_max_queue is not None or capacity_qps is not None:
                admission = AdmissionController(
                    max_queue=admission_max_queue,
                    capacity_qps=capacity_qps,
                )
            self.replicas.append(FleetReplica(
                i, engine_factory(i), transport, self.store,
                requests_topic=requests_topic,
                responses_topic=responses_topic,
                deltas_topic=deltas_topic, max_batch=max_batch,
                admission=admission,
                metrics_port=0 if metrics_ports else None,
                poll_wait_s=poll_wait_s, prewarm_k=prewarm_k,
                prewarm_batch=max_batch,
            ))
        self.failovers: list[dict] = []

    @property
    def num_replicas(self) -> int:
        return len(self.replicas)

    def seed_store(self, user_factors, movie_factors, *,
                   num_users: int) -> None:
        """Install the epoch-0 base snapshot (the resync floor)."""
        self.store.put_epoch(0, user_factors, movie_factors,
                             num_users=num_users, seq=0)

    def prewarm(self, k: int | None = None,
                max_batch: int | None = None) -> dict:
        """Prewarm every replica's engine (the /readyz gate); returns the
        per-replica prewarm summaries."""
        out = {}
        for r in self.replicas:
            out[r.index] = r.engine.prewarm(
                k if k is not None else r.prewarm_k,
                max_batch=max_batch or r.prewarm_batch,
            )
        return out

    @property
    def ready(self) -> bool:
        return all(r.server.ready for r in self.replicas if r.alive)

    def start(self) -> "ServeFleet":
        for r in self.replicas:
            r.start()
        return self

    def stop(self) -> None:
        for r in self.replicas:
            if r.alive:
                r.stop()
            else:
                r.server.close()

    def kill_replica(self, index: int, *, failover: bool = True) -> None:
        """Kill replica ``index`` abruptly; with ``failover`` (default)
        its partition moves to a survivor at the COMMITTED cursor."""
        victim = self.replicas[index]
        record_event("fleet", "replica_kill", replica=index)
        dump_flight(f"serve_replica_kill replica={index}")
        victim.kill()
        if failover:
            self.failover(index)

    def failover(self, index: int) -> None:
        """Reassign the dead replica's partition to the next live one,
        starting at the victim's committed cursor — at-least-once: the
        survivor re-serves anything the victim polled but never answered
        (clients dedup by req_id)."""
        victim = self.replicas[index]
        survivors = [r for r in self.replicas if r.alive]
        if not survivors:
            raise RuntimeError("no live replica to absorb the partition")
        heir = survivors[index % len(survivors)]
        with span("serve/fleet/failover", dead=index, heir=heir.index):
            for p, cursor in victim.server.committed_cursors.items():
                heir.server.adopt_partition(p, cursor)
        self.failovers.append({"dead": index, "heir": heir.index})
        record_event("fleet", "failover", dead=index, heir=heir.index)

    def counters(self) -> dict:
        """Fleet-wide accounting for bench rows and chaos assertions."""
        return {
            "replicas": len(self.replicas),
            "alive": sum(r.alive for r in self.replicas),
            "served": sum(r.server.requests_served for r in self.replicas),
            "shed": sum(r.server.shed for r in self.replicas),
            "batches": sum(r.server.batches for r in self.replicas),
            "deltas_applied": sum(r.deltas_applied for r in self.replicas),
            "gaps_detected": sum(r.gaps_detected for r in self.replicas),
            "resyncs": sum(r.resyncs for r in self.replicas),
            "rollovers": sum(r.rollovers for r in self.replicas),
            "lazy_pulls": sum(r.lazy_pulls for r in self.replicas),
            "failovers": len(self.failovers),
        }

    def __enter__(self) -> "ServeFleet":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
