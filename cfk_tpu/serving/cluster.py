"""Clustered item-table index for two-stage retrieval (ISSUE 16).

The exact serve path streams the ENTIRE item table per batch, so QPS is
pinned to the table-scan byte floor no matter how fast the kernel gets.
This module builds the index the two-stage path probes instead: a seeded,
deterministic k-means over the item factor rows, with the table stored
CLUSTER-MAJOR — rows of one cluster contiguous — so a coarse
centroid-probe stage selects clusters and the rescore stage gathers their
rows as contiguous ranges (the memory-placement playbook of
arXiv 1808.03843 applied to serving: co-locate what is accessed
together).

Everything here is host-side numpy and bit-deterministic for a fixed
``(factors, clusters, seed)``: the k-means init draws from
``np.random.default_rng(seed)``, iteration count is fixed (no
convergence-dependent early exit), empty clusters are repaired by a
deterministic farthest-row rule, and the cluster-major permutation sorts
``kind="stable"`` so rows within a cluster keep ascending global order —
which is what makes the rescore stage's tie order reproducible.

Lifecycle (enforced by ``ServeEngine``):

- built at engine construction and REBUILT atomically on every full
  table swap (warm-retrain commit events),
- per-row fold-in deltas update factor rows IN PLACE at their existing
  cluster-major position (``note_stale`` records them; assignments and
  centroids intentionally go stale between swaps — bounded by the
  engine's stale-fraction cap, which degrades to the exact scan rather
  than serve from an index that no longer reflects the table),
- never mutated by the serve path itself.
"""

from __future__ import annotations

import dataclasses

import numpy as np


def kmeans_item_clusters(
    factors: np.ndarray,
    clusters: int,
    *,
    seed: int = 0,
    iters: int = 8,
) -> tuple[np.ndarray, np.ndarray]:
    """Deterministic k-means over item factor rows.

    Returns ``(centroids [C, k] f32, assign [M] int32)``.  Lloyd
    iterations with a fixed count (no data-dependent early exit — same
    input, same output, bit-for-bit on one platform), squared-Euclidean
    objective via the expanded form ``argmax(x·cᵀ − ½|c|²)`` so the
    assignment step is one BLAS matmul even at catalog scale.  Empty
    clusters re-seed at the highest-norm rows not already serving as a
    centroid seed — deterministic, and heavy rows are exactly the ones
    worth a dedicated cluster.
    """
    x = np.ascontiguousarray(np.asarray(factors, np.float32))
    if x.ndim != 2:
        raise ValueError(f"factors must be [M, k], got shape {x.shape}")
    m = x.shape[0]
    c = int(clusters)
    if not 1 <= c <= m:
        raise ValueError(f"clusters must be in [1, {m}], got {c}")
    rng = np.random.default_rng(seed)
    init = np.sort(rng.choice(m, size=c, replace=False))
    cent = x[init].copy()
    norms = (x * x).sum(axis=1)
    by_norm = np.argsort(-norms, kind="stable")
    assign = np.zeros(m, np.int32)
    for _ in range(max(int(iters), 1)):
        scores = x @ cent.T - 0.5 * (cent * cent).sum(axis=1)
        assign = np.argmax(scores, axis=1).astype(np.int32)
        sums = np.zeros((c, x.shape[1]), np.float64)
        np.add.at(sums, assign, x)
        counts = np.bincount(assign, minlength=c).astype(np.float64)
        cent = (sums / np.maximum(counts, 1.0)[:, None]).astype(np.float32)
        empty = np.flatnonzero(counts == 0)
        if empty.size:
            cent[empty] = x[by_norm[: empty.size]]
    scores = x @ cent.T - 0.5 * (cent * cent).sum(axis=1)
    assign = np.argmax(scores, axis=1).astype(np.int32)
    return cent, assign


@dataclasses.dataclass
class ClusterIndex:
    """The cluster-major view of one item-table snapshot.

    ``perm[pos] = global row`` (cluster-major order), ``inv_perm`` its
    inverse, ``offsets [C+1]`` the row ranges — cluster ``c`` owns
    cluster-major positions ``[offsets[c], offsets[c+1])``.  ``assign``
    is kept for the fold-in delta path and the nearest-centroid
    fallbacks ("similar items" / cold-start).
    """

    centroids: np.ndarray  # [C, k] f32
    assign: np.ndarray  # [M] int32 global row -> cluster
    perm: np.ndarray  # [M] int64 cluster-major position -> global row
    inv_perm: np.ndarray  # [M] int64 global row -> cluster-major position
    offsets: np.ndarray  # [C+1] int64 cluster row ranges
    seed: int
    stale_rows: int = 0  # fold-in delta rows applied since the build

    @property
    def num_clusters(self) -> int:
        return int(self.centroids.shape[0])

    @property
    def num_rows(self) -> int:
        return int(self.perm.shape[0])

    @property
    def stale_fraction(self) -> float:
        return self.stale_rows / max(self.num_rows, 1)

    def positions_of(self, rows) -> np.ndarray:
        """Cluster-major positions of global rows (the in-place delta
        target: the row moved here at build time and STAYS here until
        the next full rebuild)."""
        return self.inv_perm[np.asarray(rows, np.int64)]

    def note_stale(self, n_rows: int) -> int:
        """Record ``n_rows`` in-place delta rows; returns the total."""
        self.stale_rows += int(n_rows)
        return self.stale_rows

    def ranges(self, cluster_ids: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """(starts, ends) cluster-major row ranges for the given clusters."""
        cids = np.asarray(cluster_ids, np.int64)
        return self.offsets[cids], self.offsets[cids + 1]

    def nearest_clusters(self, vec: np.ndarray, n: int = 1) -> np.ndarray:
        """Top-n clusters by centroid dot score for one [k] query vector —
        the cold-start / "similar items" fallback: a user (or item) with
        no history still lands in the catalog region nearest its factor
        direction."""
        scores = self.centroids @ np.asarray(vec, np.float32)
        n = min(int(n), self.num_clusters)
        top = np.argpartition(-scores, n - 1)[:n]
        return top[np.argsort(-scores[top], kind="stable")]

    def similar_items(self, movie_row: int, n: int = 10) -> np.ndarray:
        """Global rows of the item's cluster neighbors (excluding itself)
        — the clustered layout's free "similar items" answer: one range
        slice, no table scan."""
        cid = int(self.assign[int(movie_row)])
        lo, hi = int(self.offsets[cid]), int(self.offsets[cid + 1])
        members = self.perm[lo:hi]
        return members[members != int(movie_row)][: int(n)]

    def quick_check(self) -> str | None:
        """Cheap per-batch health probe (O(C·k), no table pass): reason
        the index must not be served from, or None.  The chaos scenario
        corrupts exactly what this catches — NaN centroids, broken
        offsets — and the engine's response is the exact-scan fallback,
        never a wrong answer."""
        if not np.isfinite(self.centroids).all():
            return "non-finite centroid values"
        if self.offsets.shape[0] != self.num_clusters + 1:
            return "offsets length != clusters + 1"
        if int(self.offsets[0]) != 0 or int(self.offsets[-1]) != self.num_rows:
            return "offsets do not span the table rows"
        if np.any(np.diff(self.offsets) < 0):
            return "offsets not monotone"
        return None

    def validate(self) -> None:
        """Full structural check (O(M); build/swap time, not per batch)."""
        reason = self.quick_check()
        if reason is None:
            seen = np.zeros(self.num_rows, bool)
            seen[self.perm] = True
            if not seen.all():
                reason = "perm is not a permutation"
            elif np.any(self.perm[self.inv_perm]
                        != np.arange(self.num_rows)):
                reason = "inv_perm is not perm's inverse"
        if reason is not None:
            raise ValueError(f"corrupt cluster index: {reason}")


def build_cluster_index(
    movie_factors: np.ndarray,
    clusters: int,
    *,
    seed: int = 0,
    iters: int = 8,
) -> ClusterIndex:
    """Cluster the item factors and derive the cluster-major layout.

    The permutation sorts rows by cluster with ``kind="stable"``, so
    within a cluster global row order is preserved — the property the
    rescore stage's deterministic tie order (and the round-trip test)
    leans on.
    """
    centroids, assign = kmeans_item_clusters(
        movie_factors, clusters, seed=seed, iters=iters
    )
    perm = np.argsort(assign, kind="stable").astype(np.int64)
    inv_perm = np.empty_like(perm)
    inv_perm[perm] = np.arange(perm.shape[0], dtype=np.int64)
    counts = np.bincount(assign, minlength=int(clusters)).astype(np.int64)
    offsets = np.zeros(int(clusters) + 1, np.int64)
    np.cumsum(counts, out=offsets[1:])
    return ClusterIndex(
        centroids=centroids, assign=assign, perm=perm, inv_perm=inv_perm,
        offsets=offsets, seed=int(seed),
    )
