"""Two-stage clustered retrieval: centroid probe + exact shortlist rescore.

The exact serve path's cost is one full item-table scan per batch — the
O(users × catalog) floor ISSUE 16 breaks.  This module is the probe side:

- COARSE stage (``serve/candidate``): score the [B, k] user batch against
  the ``[C, k]`` cluster centroids (optionally over the int8/bf16
  quantized view — the canonical ``ops.quant`` dequant placement, same as
  the kernel's in-register rule) and take each user's top ``probe``
  clusters.
- SHORTLIST: the batch-union of selected clusters, gathered from the
  CLUSTER-MAJOR table (``serving.cluster``) as contiguous row ranges and
  padded to a pow2 multiple of ``tile_m`` — the same shape-bucketing
  trick the engine uses for batch sizes, so live traffic converges onto a
  handful of rescore programs.
- RESCORE stage (``serve/rescore``): the EXISTING Pallas top-K kernel
  over the gathered shortlist, with the same seen-item exclusion masks
  remapped to shortlist-local coordinates.  Scores of surviving rows are
  bit-identical to the exact path (same ``_score_tile_fold`` math, same
  k-order contraction); ties resolve to the earlier SHORTLIST position,
  i.e. cluster-major order of the gathered set — pinned by
  ``tests/test_twostage.py`` as "identical to the exact kernel run over
  the same gathered subtable".

The shortlist width is dynamic per batch, but the kernel's ``num_movies``
mask is jit-static — so the padded width is the static shape and the
ACTUAL row count rides the kernel's scalar-prefetched ``row_offset``:
with ``row_offset = rows_padded − rows`` and ``num_movies = rows_padded``
the kernel masks exactly the padding tail (global id ≥ num_movies), and
returned ids map back as ``shortlist_pos = id − row_offset``.  No
re-trace per distinct union size, only per pow2 bucket.
"""

from __future__ import annotations

import dataclasses
import functools
import math

import numpy as np

from cfk_tpu.serving.cluster import ClusterIndex
from cfk_tpu.serving.topk_kernel import (
    _pow2_ceil,
    build_seen_tiles,
    serve_compute_dtype,
    topk_scores_pallas,
)

# Trace counter for the two-stage programs (coarse + rescore), summed into
# ``serving.engine.trace_count`` so the prewarm zero-new-traces contract
# (PR 12) covers two_stage mode too.
_TRACES = [0]


def trace_count() -> int:
    """Coarse + rescore program traces this process."""
    return _TRACES[0]


def default_two_stage_params(num_movies: int, *,
                             min_recall: float | None = None
                             ) -> tuple[int, int]:
    """(clusters, probe_clusters) for a catalog size, sized like the plan
    resolver would: ~√M clusters (pow2), and the smallest probe count the
    recall model (``plan.cost.estimated_recall``) accepts at the plan
    recall constraint — the IVF nprobe ≈ √nlist rule of thumb."""
    from cfk_tpu.plan.cost import SERVE_MIN_RECALL, estimated_recall

    floor = SERVE_MIN_RECALL if min_recall is None else float(min_recall)
    m = max(int(num_movies), 1)
    clusters = min(_pow2_ceil(max(int(round(math.sqrt(m))), 1)), m)
    probe = 1
    while probe < clusters and estimated_recall(clusters, probe) < floor:
        probe += 1
    return clusters, probe


def _coarse_call(u, centroids, scale, *, probe):
    """Centroid score + per-user top-``probe`` clusters — the candidate
    stage, scored exactly like the kernel scores a tile (same compute
    dtype / precision / canonical int8 dequant as ``_score_tile_fold``)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    _TRACES[0] += 1
    ct, prec = serve_compute_dtype(centroids.dtype)
    if centroids.dtype == jnp.int8:
        cent_f = centroids.astype(jnp.float32) * scale[:, None]
    else:
        cent_f = centroids.astype(ct)
    scores = jax.lax.dot_general(
        u.astype(ct), cent_f,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=prec,
    )  # [B, C]
    return lax.top_k(scores, probe)


@functools.lru_cache(maxsize=1)
def coarse_jit_fn():
    """Jitted coarse entry — one program per (B, C, probe, dtype) class."""
    import jax

    return jax.jit(_coarse_call, static_argnames=("probe",))


def _rescore_call(u, indices, table, scale, seen_tiles, offset, *,
                  k_top, tile_m):
    """Gather the shortlist rows from the resident cluster-major table and
    run the EXISTING streaming top-K kernel over them.  ``indices`` is the
    jit-static-width [R_pad] position vector; ``offset = R_pad − R`` is the
    traced scalar that masks the padding tail (module docstring)."""
    import jax.numpy as jnp

    _TRACES[0] += 1
    sub = jnp.take(table, indices, axis=0)
    sub_scale = None if scale is None else jnp.take(scale, indices)
    return topk_scores_pallas(
        u, sub, sub_scale, seen_tiles, k_top=k_top,
        num_movies=indices.shape[0], tile_m=tile_m, row_offset=offset,
    )


@functools.lru_cache(maxsize=1)
def rescore_jit_fn():
    """Jitted rescore entry — with pow2 shortlist-width and batch
    bucketing, live traffic converges onto a handful of programs."""
    import jax

    return jax.jit(_rescore_call, static_argnames=("k_top", "tile_m"))


@dataclasses.dataclass
class Shortlist:
    """One batch's gathered candidate set (host-side bookkeeping).

    ``indices [R_pad]`` are cluster-major TABLE positions (padding slots
    repeat position 0 — masked by the kernel, never selected);
    ``global_ids [R]`` maps shortlist position → global movie row;
    ``cluster_ids``/``starts``/``ends``/``local_starts`` describe the
    contiguous ranges for the seen-mask remap."""

    cluster_ids: np.ndarray  # [S] int64 sorted selected clusters
    starts: np.ndarray  # [S] int64 cluster-major range starts
    ends: np.ndarray  # [S] int64 range ends
    local_starts: np.ndarray  # [S] int64 shortlist-local range starts
    indices: np.ndarray  # [R_pad] int32 table positions
    global_ids: np.ndarray  # [R] int64 shortlist pos -> global movie row
    rows: int  # R — real candidate rows
    rows_padded: int  # R_pad — pow2 multiple of tile_m

    @property
    def offset(self) -> int:
        """The kernel's ``row_offset`` (= padding-tail mask, module doc)."""
        return self.rows_padded - self.rows


def build_shortlist(index: ClusterIndex, cluster_ids, *, tile_m: int,
                    min_rows: int = 1) -> Shortlist:
    """The batch-union shortlist for the selected clusters.

    Rows come out in cluster-major order (ascending cluster, ascending
    global row within — the tie-order contract).  When the union holds
    fewer than ``min_rows`` rows (a tiny catalog or degenerate probe set
    cannot cover K), the shortlist WIDENS to every cluster — full
    coverage through the same code path, never a short answer."""
    cids = np.unique(np.asarray(cluster_ids, np.int64))
    if cids.size and (cids[0] < 0 or cids[-1] >= index.num_clusters):
        raise ValueError(
            f"cluster ids out of range [0, {index.num_clusters})"
        )
    starts, ends = index.ranges(cids)
    rows = int((ends - starts).sum())
    if rows < min_rows:
        cids = np.arange(index.num_clusters, dtype=np.int64)
        starts, ends = index.ranges(cids)
        rows = int((ends - starts).sum())
    lens = ends - starts
    local_starts = np.zeros(cids.size, np.int64)
    if cids.size > 1:
        np.cumsum(lens[:-1], out=local_starts[1:])
    positions = (
        np.concatenate([np.arange(s, e, dtype=np.int64)
                        for s, e in zip(starts, ends)])
        if rows else np.zeros(0, np.int64)
    )
    rows_padded = _pow2_ceil(max(rows, 1), tile_m)
    indices = np.zeros(rows_padded, np.int32)
    indices[:rows] = positions
    return Shortlist(
        cluster_ids=cids, starts=starts, ends=ends,
        local_starts=local_starts, indices=indices,
        global_ids=index.perm[positions], rows=rows,
        rows_padded=rows_padded,
    )


def shortlist_seen(index: ClusterIndex, shortlist: Shortlist,
                   seen_movies, seen_indptr):
    """Remap a batch seen-CSR (GLOBAL movie rows, sorted per user) to
    SHORTLIST-LOCAL positions, dropping entries outside the shortlist (an
    unselected seen item is not a candidate, so it needs no mask).  Local
    positions are re-sorted per user — ``build_seen_tiles``'s contract."""
    movies = np.asarray(seen_movies, np.int64)
    indptr = np.asarray(seen_indptr, np.int64)
    if movies.size:
        pos = index.inv_perm[movies]
        j = np.searchsorted(shortlist.starts, pos, side="right") - 1
        j = np.clip(j, 0, max(shortlist.starts.size - 1, 0))
        inside = ((pos >= shortlist.starts[j]) & (pos < shortlist.ends[j])
                  if shortlist.starts.size else np.zeros(pos.shape, bool))
        local = np.where(
            inside, shortlist.local_starts[j] + (pos - shortlist.starts[j]),
            -1,
        )
    else:
        local = np.zeros(0, np.int64)
    out_indptr = np.zeros(indptr.shape[0], np.int64)
    segs = []
    for i in range(indptr.shape[0] - 1):
        seg = local[indptr[i]: indptr[i + 1]]
        seg = np.sort(seg[seg >= 0])
        segs.append(seg)
        out_indptr[i + 1] = out_indptr[i] + seg.size
    out_movies = (np.concatenate(segs).astype(np.int32)
                  if out_indptr[-1] else np.zeros(0, np.int32))
    return out_movies, out_indptr


def shortlist_seen_tiles(index: ClusterIndex, shortlist: Shortlist,
                         seen_movies, seen_indptr, batch: int, *,
                         tile_m: int):
    """[NT_local, B, W] exclusion rectangle in shortlist coordinates —
    ``build_seen_tiles`` over the remapped CSR (W pow2-bucketed as ever)."""
    movies_l, indptr_l = shortlist_seen(
        index, shortlist, seen_movies, seen_indptr
    )
    return build_seen_tiles(
        movies_l, indptr_l, np.arange(batch),
        num_movies=max(shortlist.rows, 1), tile_m=tile_m,
        num_tiles=shortlist.rows_padded // tile_m,
    )


def map_shortlist_ids(ids: np.ndarray, shortlist: Shortlist) -> np.ndarray:
    """Kernel ids (``row_offset``-shifted shortlist positions, −1 empty)
    → GLOBAL movie rows."""
    ids = np.asarray(ids, np.int64)
    pos = np.clip(ids - shortlist.offset, 0,
                  max(shortlist.rows - 1, 0))
    mapped = (shortlist.global_ids[pos] if shortlist.rows
              else np.zeros_like(ids))
    return np.where(ids >= 0, mapped, -1).astype(np.int32)


def recall_at_k(ids: np.ndarray, oracle_ids: np.ndarray) -> float:
    """Mean per-user fraction of the exact oracle's top-K recovered —
    the first-class quality metric of the two-stage contract (every bench
    row carries it; the plan constraint is ≥ ``plan.cost.SERVE_MIN_RECALL``).
    −1 slots (fewer than K candidates) are ignored on both sides."""
    ids = np.asarray(ids)
    oracle_ids = np.asarray(oracle_ids)
    if ids.shape[0] != oracle_ids.shape[0]:
        raise ValueError(f"batch mismatch {ids.shape} vs {oracle_ids.shape}")
    hits = total = 0
    for got, want in zip(ids, oracle_ids):
        oracle = {int(x) for x in want if x >= 0}
        if not oracle:
            continue
        hits += len(oracle & {int(x) for x in got if x >= 0})
        total += len(oracle)
    return hits / total if total else 1.0
