"""Quantized HBM factor tables — the bytes lever under the gather floor.

The in-kernel gather (PR 4) put the tiled chunk bodies ON the gather
roofline; that floor itself is bytes-bound (every rating fetches one
factor row per side per iteration), so the remaining lever is making the
fetched rows smaller.  Following the approximate-computing MF line
(arXiv 1808.03843): the HBM-resident RAW table the gather kernels read is
stored bf16 (half the bytes) or int8 + one f32 per-row scale (a quarter,
plus 4 B/row), while every Gram/solve accumulation stays float32
in-register — the dequantize multiply rides the SAME per-entry premultiply
pass the kernels already run for the √aw weighting, so quantization adds
zero extra kernel passes.

This is distinct from ``ALSConfig.dtype`` (the persistent storage/exchange
dtype of the factor matrices): ``table_dtype`` quantizes only the
*gather operand* of each half-iteration — the solved (master) factors keep
the config dtype, so bf16/int8 tables compose with f32 masters.

Canonical dequant placement (the bit-exactness contract every path pins):

    scale fold FIRST:   wt' = wt · scale[nb]        (int8 only; no-op else)
    then one multiply:  g   = data[nb].astype(ct) · wt'

Both the XLA-gather schedule, the Mosaic in-kernel DMA gather, and their
CPU emulation twins compute exactly this, in exactly this order, so
factors are bit-identical across the gather knob for any table dtype
(``tests/test_quant_table.py``).  ``table_dtype="float32"`` is the
identity — the default path is bit-identical to pre-quantization behavior.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

TABLE_DTYPES = ("float32", "bfloat16", "int8")

# int8 symmetric per-row scheme: q = round(f / s) clipped to ±127 with
# s = max|row| / 127.  127 (not 128) keeps the grid symmetric so -f
# quantizes to -q exactly — ALS factors are sign-symmetric by construction.
_INT8_LEVELS = 127.0


def resolve_table_dtype(table_dtype: str | None) -> str:
    """None → the f32 identity; otherwise validate the name."""
    if table_dtype is None:
        return "float32"
    if table_dtype not in TABLE_DTYPES:
        raise ValueError(
            f"table_dtype must be one of {TABLE_DTYPES}, got {table_dtype!r}"
        )
    return table_dtype


def table_itemsize(table_dtype: str | None) -> int:
    """Bytes per table element — what the roofline byte model charges the
    gather floor per fetched cell."""
    return {"float32": 4, "bfloat16": 2, "int8": 1}[
        resolve_table_dtype(table_dtype)
    ]


def quantize_table(
    table: jax.Array, table_dtype: str | None
) -> tuple[jax.Array, jax.Array | None]:
    """(data, scale) for the HBM-resident gather table.

    ``float32``  → (table, None) — identity (bit-identical default path).
    ``bfloat16`` → (bf16 cast, None) — the existing bf16-stream machinery
                   consumes it unchanged (``_gram_compute_dtype``).
    ``int8``     → (int8 rows, [F] f32 per-row scales).  All-zero rows get
                   scale 1.0 so their dequant stays exactly 0 without a
                   0/0.
    """
    td = resolve_table_dtype(table_dtype)
    if td == "float32":
        return table, None
    if td == "bfloat16":
        return table.astype(jnp.bfloat16), None
    f = table.astype(jnp.float32)
    amax = jnp.max(jnp.abs(f), axis=-1)  # [F]
    # `amax == 0` (not `amax > 0`): a corrupt row's NaN amax must POISON
    # its scale — the `> 0` predicate is False for NaN and would launder
    # the row into finite codes × scale 1.0, invisible to every
    # downstream isfinite probe (the ring sentinel checks the scales, the
    # only int8 payload leaf that can go nonfinite).  Bit-identical for
    # finite rows.
    scale = jnp.where(amax == 0, 1.0, amax / _INT8_LEVELS)
    q = jnp.clip(
        jnp.round(f / scale[:, None]), -_INT8_LEVELS, _INT8_LEVELS
    ).astype(jnp.int8)
    return q, scale


def dequantize_table(
    data: jax.Array, scale: jax.Array | None
) -> jax.Array:
    """The full dequantized table (f32 for int8, pass-through otherwise).

    Used where a whole-table consumer needs the values the kernels read —
    the iALS global Gram YᵀY and the subspace sweeps' score streams must
    see the SAME dequantized rows the Gram kernels gather, or the fallback
    and kernel paths drift (the per-interaction-score bug class this
    module's canonical ordering exists to prevent)."""
    if scale is None:
        return data
    return data.astype(jnp.float32) * scale[:, None]


def scale_with_zero_row(scale: jax.Array) -> jax.Array:
    """[F+1] scales with the virtual zero row appended (index F = the
    gather kernels' padding row; its scale is 0 so any folded weight at a
    padding slot is exactly 0 regardless of the mask value)."""
    return jnp.concatenate([scale, jnp.zeros((1,), scale.dtype)])


def fold_scale(
    wt: jax.Array, scale: jax.Array | None, nb: jax.Array
) -> jax.Array:
    """The canonical scale fold: per-entry weight × the indexed row's
    dequant scale (identity when the table carries no scale).  Runs FIRST,
    before the single g = data[nb]·wt multiply — every path (XLA gather,
    Mosaic DMA gather, emulation twins, subspace score streams) shares
    this order, which is what makes them bit-identical.  ``nb`` may use
    the virtual-zero-row convention (index F): the appended scale row is 0.
    """
    if scale is None:
        return wt
    return wt * scale_with_zero_row(scale)[nb].astype(wt.dtype)


def gather_operand_view(
    table: jax.Array, table_dtype: str | None
) -> jax.Array:
    """The dequantized values the gather kernels read, as a whole table —
    for consumers that need the full matrix rather than gathered rows: the
    iALS global Gram YᵀY and any score recomputation.  bf16 returns the
    bf16 cast (``global_gram`` runs its native bf16 path on it); int8
    returns the f32 dequantized rows; f32 is the identity."""
    data, scale = quantize_table(table, table_dtype)
    return dequantize_table(data, scale)


def validate_table_dtype_layout(table_dtype: str | None, layout: str) -> None:
    """int8 needs the per-row scale threaded through the half-step weight
    streams, which the tiled chunk bodies, the bucketed walk, and the
    subspace sweeps do; the padded/segment layouts' classic formulations
    have no symmetric weight channel to fold it into (their iALS Gram uses
    asymmetric operands), so int8 is refused there rather than silently
    dequantizing up front (which would defeat the bytes win).  bf16 is a
    plain dtype cast and works on every layout."""
    td = resolve_table_dtype(table_dtype)
    if td == "int8" and layout not in ("tiled", "bucketed"):
        raise ValueError(
            f"table_dtype='int8' supports layout='tiled'/'bucketed' (the "
            f"per-row scale rides their weight streams); layout={layout!r} "
            "should use 'bfloat16' or 'float32'"
        )
