from cfk_tpu.ops.solve import (
    gather_gram,
    batched_spd_solve,
    regularized_solve,
    als_half_step,
    init_factors,
)

__all__ = [
    "gather_gram",
    "batched_spd_solve",
    "regularized_solve",
    "als_half_step",
    "init_factors",
]
