"""Pallas TPU kernel: batched small-SPD solve via lane-vectorized Gauss-Jordan.

The framework's FLOP hot spot after the Gram matmuls is solving E independent
k×k SPD systems (k = rank, 5..128; E = entities per shard).  XLA lowers
``jnp.linalg.cholesky`` + two ``triangular_solve``s to sequential custom
calls that vectorize poorly for small k.  This kernel instead runs
Gauss-Jordan elimination with the *batch* dimension laid out along the TPU's
128-wide vector lanes: every scalar step of the textbook algorithm becomes a
[k, T] or [k, k, T] VPU op over T systems at once.  No pivoting — the
systems are SPD with a λ·n ≥ λ ridge (``regularized_solve``), so diagonal
pivots stay safely positive.

Layout contract: A is passed [k, k, E] and b [k, E] (batch LAST, so tiles
sit in the lane dimension).  The dispatcher (``ops.solve.dispatch_spd_solve``)
pays an explicit transpose from the batch-first Gram layout — measured at
0.024 s/iter of the 0.82 full-Netflix iteration (round-3 profile), i.e.
~3%: emitting batch-last from the Gram kernel would force its per-entity
flush onto dynamic LANE offsets (lane-shift ops per flush), a worse trade
than the one bulk transpose, so the transpose stays by choice now rather
than as a follow-up.

Cost: ≈ 2k³ FLOPs per system (vs k³/3 for Cholesky) — a 6× FLOP overhead
traded for full lane utilization, a win while the custom-call path is
latency-bound on small k.  The fully-unrolled k-loop holds [k, k, TILE]
temporaries in VMEM, which bounds the supported rank: k ≤ PALLAS_MAX_RANK
(= 64 → A tile 2 MiB); larger ranks must use the cholesky backend (the
dispatcher falls back automatically).  Falls back to interpret mode off-TPU
so tests run on CPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from cfk_tpu.compat import typeof_vma
from jax.experimental import pallas as pl

try:  # TPU-specific memory spaces; absent on some builds
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except ImportError:  # pragma: no cover
    pltpu = None
    _VMEM = None

_LANES = 128
# VMEM budget cap: the kernel keeps [k, k, _LANES] float32 blocks live
# through an unrolled k-step elimination; k=64 → 2 MiB per buffer. k=128
# was measured (raising Mosaic's scoped-VMEM allowance to fit the 8 MiB
# A-block): it compiles but runs ~10× SLOWER than XLA's cholesky there —
# the fully-unrolled elimination is VPU-bound at O(k³) while cholesky's
# custom-call overhead amortizes at larger k. The crossover favors this
# kernel only up to k = 64, so the cap stays.
PALLAS_MAX_RANK = 64
# The LU variant does k³/3 VPU work (vs Gauss-Jordan's ~3k³ chain of
# fma+select over the full matrix), which moves its crossover past
# k = 128: one direct LU beats the blocked Schur composition of k=64 GJ
# kernels AND skips Schur's XLA-level [E,k,k] transposes.
LU_MAX_RANK = 128


def gj_solve_lanes(a, b, *, k: int):
    """In-register Gauss-Jordan over lanes: a [k,k,T], b [k,T] → x [k,T].

    The elimination core shared by the standalone solve kernels and the
    fused Gram+solve epilogue (``ops.pallas.gram_kernel``).  Row-index
    planes come from in-kernel iota (pallas kernels cannot capture array
    constants, and Mosaic needs multi-dim iota).
    """
    rows3 = jax.lax.broadcasted_iota(jnp.int32, (k, 1, 1), 0)
    rows2 = jax.lax.broadcasted_iota(jnp.int32, (k, 1), 0)
    for j in range(k):  # k is static → fully unrolled
        inv = 1.0 / a[j, j, :]  # [T]
        row = a[j] * inv[None, :]  # [k,T] normalized pivot row
        bj = b[j] * inv  # [T]
        col = a[:, j, :]  # [k,T]
        # Eliminate column j from every row, keeping the normalized pivot
        # row via a select (Mosaic has no scatter, so no .at[j].set; the
        # select is also exact where subtract-then-restore would leave an
        # epsilon residue on row j).
        a = jnp.where(rows3 == j, row[None, :, :],
                      a - col[:, None, :] * row[None, :, :])
        b = jnp.where(rows2 == j, bj[None, :], b - col * bj[None, :])
    return b


def lu_solve_lanes(tr, y, u_scr, y_scr, x_scr, *, k: int):
    """In-register reverse-order no-pivot LU over lanes: tr [k,k,T],
    y [k,T] → x [k,T] (read back from ``x_scr``).

    The k³/3 elimination core of ``_lu_reg_kernel``, factored so the fused
    Gram+solve epilogue can run it on VMEM-resident Gram tiles.  Pivot rows
    go to the ``u_scr``/``y_scr`` VMEM scratch; forward substitution
    rebuilds x in increasing order through ``x_scr``.  See ``_lu_reg_kernel``
    for why the elimination runs in REVERSE variable order (offset-0
    slices are the only ones Mosaic's sublane broadcast lowers).
    """
    for n in range(k, 0, -1):  # static → unrolled; eliminate x_{n-1}
        inv = 1.0 / tr[n - 1, n - 1, :]
        yn = y[n - 1] * inv
        y_scr[n - 1, :] = yn
        if n > 1:
            row = tr[n - 1, :n - 1, :] * inv[None, :]
            col = tr[:n - 1, n - 1, :]
            u_scr[n - 1, :n - 1, :] = row
            tr = tr[:n - 1, :n - 1, :] - col[:, None, :] * row[None, :, :]
            y = y[:n - 1] - col * yn[None, :]
    x_scr[0, :] = y_scr[0, :]
    for j in range(1, k):
        corr = jnp.sum(u_scr[j, :j, :] * x_scr[:j, :], axis=0)
        x_scr[j, :] = y_scr[j, :] - corr
    return x_scr[...]


def _gauss_kernel(a_ref, b_ref, x_ref, *, k: int):
    """Solve T systems at once: a_ref [k,k,T], b_ref [k,T] → x_ref [k,T]."""
    x_ref[:] = gj_solve_lanes(a_ref[:], b_ref[:], k=k)


def _gauss_multi_kernel(a_ref, b_ref, x_ref, *, k: int):
    """Multi-RHS variant: a_ref [k,k,T], b_ref [k,m,T] → x_ref [k,m,T].

    The same unrolled Gauss-Jordan with the row operations applied to an
    [m]-wide RHS block — the building block of the blocked (Schur) solve
    for ranks above the single-kernel VMEM cap."""
    a = a_ref[:]
    b = b_ref[:]
    rows3 = jax.lax.broadcasted_iota(jnp.int32, (k, 1, 1), 0)
    for j in range(k):
        inv = 1.0 / a[j, j, :]  # [T]
        row = a[j] * inv[None, :]  # [k,T]
        bj = b[j] * inv[None, :]  # [m,T]
        col = a[:, j, :]  # [k,T]
        a = jnp.where(rows3 == j, row[None, :, :],
                      a - col[:, None, :] * row[None, :, :])
        b = jnp.where(rows3 == j, bj[None, :, :],
                      b - col[:, None, :] * bj[None, :, :])
    x_ref[:] = b


def apply_reg_lanes(a, reg, *, k: int, reg_mode: str, lam: float):
    """Add the regularizer to a batch-last [k,k,T] block in-register:
    ``diag`` = λ·max(n,1)·I from a [T] count lane vector (ALS-WR),
    ``matrix`` = one shared [k,k] SPD term (iALS's YᵀY+λI).  Shared by
    the standalone reg+solve kernels and the fused Gram+solve epilogue."""
    if reg_mode == "diag":
        regv = lam * jnp.maximum(reg.astype(jnp.float32), 1.0)  # [T]
        r3 = jax.lax.broadcasted_iota(jnp.int32, (k, k, 1), 0)
        c3 = jax.lax.broadcasted_iota(jnp.int32, (k, k, 1), 1)
        return a + jnp.where(r3 == c3, regv[None, None, :], 0.0)
    # matrix: one [k,k] SPD term shared across the batch (iALS)
    return a + reg[:, :, None]


def _apply_reg(a, r_ref, *, k: int, reg_mode: str, lam: float):
    """``apply_reg_lanes`` from the kernel's regularizer ref: the diag
    counts ride as a [1, T] block (1-D s32 operands draw an XLA T(1024)
    layout Mosaic rejects; 2-D rows use the standard tiling)."""
    reg = r_ref[0, :] if reg_mode == "diag" else r_ref[...]
    return apply_reg_lanes(a, reg, k=k, reg_mode=reg_mode, lam=lam)


def _lu_reg_kernel(a_ref, b_ref, r_ref, x_ref, u_scr, y_scr, x_scr, *,
                   k: int, reg_mode: str, lam: float):
    """Fused reg + LU solve, batch-first in/out — the k³/3 alternative to
    Gauss-Jordan's k³.

    No-pivot LU is stable here for the same reason GJ is (SPD + ridge).
    The elimination runs in REVERSE variable order with a shrinking
    trailing matrix: pure-functional shrink needs no in-register scatter
    (Mosaic has none), and eliminating the LAST variable keeps every slice
    offset-0 — Mosaic's sublane-broadcast lowering rejects offset slices
    (measured: offset-1 slices fail to lower, offset-0 of any length
    compile).  Pivot rows go to a VMEM scratch; forward substitution then
    rebuilds x in increasing order.  ~6× fewer VPU ops than the GJ kernel
    (Σ(n−1)² vs k·k² select+fma chains).
    """
    a = jnp.transpose(a_ref[...], (1, 2, 0))  # [k,k,T]
    y = b_ref[...].T  # [k,T]
    tr = _apply_reg(a, r_ref, k=k, reg_mode=reg_mode, lam=lam)
    x_ref[...] = lu_solve_lanes(tr, y, u_scr, y_scr, x_scr, k=k).T


def _gauss_reg_kernel(a_ref, b_ref, r_ref, x_ref, *, k: int, reg_mode: str,
                      lam: float):
    """Fused batch-first solve: a_ref [T,k,k], b_ref [T,k], r_ref the
    regularizer (``diag``: [T] rating counts; ``matrix``: [k,k] YᵀY+λI),
    x_ref [T,k].

    The round-3 profile showed the batch-last pallas solve paying three
    HBM round-trips outside the kernel: the λ·n·I diagonal add re-wrote the
    whole [E,k,k] Gram batch (~40 MB per chunk), and the [E,k,k]→[k,k,E]
    transpose plus the output transpose-back each copied it again
    (``copy.65``/``fusion.41``, ~66 ms of the 820 ms iteration).  Here the
    transposes happen in VMEM on the [T,k,k] block and the regularizer is
    added to the diagonal in-register, so HBM sees exactly one read of
    (A, b) and one write of x.  Padding systems (count 0 ⇒ reg λ·1) become
    λ·I — SPD — so no identity-fill prologue is needed either.
    """
    a = jnp.transpose(a_ref[...], (1, 2, 0))  # [k,k,T] batch-last
    b = b_ref[...].T  # [k,T]
    a = _apply_reg(a, r_ref, k=k, reg_mode=reg_mode, lam=lam)
    x_ref[...] = gj_solve_lanes(a, b, k=k).T


def default_reg_solve_algo() -> str:
    """PROCESS-DEFAULT elimination algorithm for the fused reg+solve
    kernel: ``"lu"`` (reverse-order no-pivot LU, k³/3 VPU work, rank cap
    128) vs ``"gj"`` (Gauss-Jordan, k³, cap 64).  At k=64 they measure
    identically in the production chunk scan (the kernel is
    issue-rate-bound, not FLOP-bound); LU is the default because it
    extends the fused path to k=128 — one direct solve instead of the
    blocked Schur composition.  gj kept for A/B measurement (`perf_lab
    --reg-solve-algo` or the ``CFK_REG_SOLVE_ALGO`` env var, which also
    flips every bench.py path).

    This is only the DEFAULT: callers that thread an explicit algorithm
    (``ALSConfig.reg_solve_algo`` → the half-step dispatchers → the
    ``algo=`` kwargs here) bypass it — which is how the recovery ladder's
    GJ rung works now (``resilience.policy``; it used to ride the env
    var).  ``gauss_solve_reg_pallas`` resolves this default BEFORE its
    jit boundary, so the concrete algorithm is part of the jit cache key
    and flipping the default (or monkeypatching this function) between
    calls compiles the right kernel instead of silently reusing the
    previous one.  Programs that jit a whole training step still bake the
    value in at THEIR trace time.

    The ``CFK_REG_SOLVE_ALGO`` env var is a DEPRECATED alias (ISSUE 9):
    the process default is a plan concern now — pin it with
    ``ALSConfig.reg_solve_algo`` / a ``PlanConstraints(reg_solve_algo=)``
    pin / ``perf_lab --reg-solve-algo``.  A set env var still wins (so
    old scripts keep working) but warns ONCE per process."""
    import os

    algo = os.environ.get("CFK_REG_SOLVE_ALGO")
    if algo is None:
        return "lu"
    if algo not in ("lu", "gj"):
        raise ValueError(
            f"CFK_REG_SOLVE_ALGO must be 'lu' or 'gj', got {algo!r}"
        )
    global _ENV_ALGO_WARNED
    if not _ENV_ALGO_WARNED:
        _ENV_ALGO_WARNED = True
        import warnings

        warnings.warn(
            "CFK_REG_SOLVE_ALGO is deprecated: pin the elimination "
            "algorithm through the execution planner instead "
            "(ALSConfig.reg_solve_algo, a PlanConstraints pin, or "
            "perf_lab --reg-solve-algo); the env var still wins this "
            "process but will be removed",
            DeprecationWarning,
            stacklevel=2,
        )
    return algo


_ENV_ALGO_WARNED = False


def resolve_reg_solve_algo(algo: str | None) -> str:
    """The threaded elimination algorithm if given, else the process
    default.  ``None`` and ``"auto"`` both defer (``"auto"`` is the
    ``ALSConfig.reg_solve_algo`` spelling of "no opinion", so configs
    stay env-var/perf_lab patchable by default)."""
    if algo is None or algo == "auto":
        return default_reg_solve_algo()
    if algo not in ("lu", "gj"):
        raise ValueError(f"reg_solve_algo must be 'lu' or 'gj', got {algo!r}")
    return algo


def _fused_reg_rank_cap(algo: str | None = None) -> int:
    """Largest rank the fused reg+solve path handles with the given (or
    default) algorithm — what the dispatchers in ``ops.solve`` route on."""
    return (
        LU_MAX_RANK
        if resolve_reg_solve_algo(algo) == "lu" and pltpu is not None
        else PALLAS_MAX_RANK
    )


def gauss_solve_reg_pallas(
    a: jax.Array,  # [E, k, k] float32 Gram batch (batch-FIRST)
    b: jax.Array,  # [E, k] float32
    reg: jax.Array,  # diag mode: [E] rating counts; matrix mode: [k,k]
    *,
    reg_mode: str = "diag",
    lam: float = 0.0,
    interpret: bool | None = None,
    algo: str | None = None,
) -> jax.Array:  # [E, k]
    """Regularize and solve a batch of SPD systems in one kernel pass.

    ``reg_mode="diag"`` applies ALS-WR's λ·max(n,1)·I (reference semantics,
    ``processors/MFeatureCalculator.java:91-95``); ``reg_mode="matrix"``
    adds a shared [k,k] SPD term (iALS's YᵀY+λI).  Batch-first layout in
    and out — the transposes the batch-last kernels need are done in VMEM,
    so callers no longer pay the [E,k,k] HBM transpose or a separate
    regularization pass.

    ``algo=None``/``"auto"`` is resolved HERE, outside the jit boundary,
    so the jit cache key always carries the concrete 'lu'/'gj' — flipping
    the default between calls (env var or monkeypatch) recompiles instead
    of silently reusing the previously traced kernel.
    """
    algo = resolve_reg_solve_algo(algo)
    if algo == "lu" and pltpu is None:  # pragma: no cover - non-TPU build
        algo = "gj"
    return _gauss_solve_reg_pallas(
        a, b, reg, reg_mode=reg_mode, lam=lam, interpret=interpret,
        algo=algo,
    )


@functools.partial(
    jax.jit, static_argnames=("reg_mode", "lam", "interpret", "algo")
)
def _gauss_solve_reg_pallas(
    a: jax.Array,
    b: jax.Array,
    reg: jax.Array,
    *,
    reg_mode: str,
    lam: float,
    interpret: bool | None,
    algo: str,
) -> jax.Array:
    e, k, k2 = a.shape
    if k != k2 or b.shape != (e, k):
        raise ValueError(f"bad shapes a={a.shape} b={b.shape}")
    cap = LU_MAX_RANK if algo == "lu" else PALLAS_MAX_RANK
    if k > cap:
        raise ValueError(
            f"gauss_solve_reg_pallas[{algo}] supports rank <= {cap}, "
            f"got {k}; use the cholesky backend"
        )
    if reg_mode == "diag":
        if reg.shape != (e,):
            raise ValueError(f"diag reg shape {reg.shape} != ({e},)")
    elif reg_mode == "matrix":
        if reg.shape != (k, k):
            raise ValueError(f"matrix reg shape {reg.shape} != ({k},{k})")
    else:
        raise ValueError(f"unknown reg_mode {reg_mode!r}")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    tile = _LANES
    if interpret:
        # The HLO interpreter needs exact block tiling; compiled Mosaic
        # handles the ragged last block itself (out-of-bounds reads are
        # unspecified but stay in their own lanes — each lane is an
        # independent system — and out-of-bounds writes are dropped), so
        # on TPU no [E,k,k] pad/slice copy is paid (the pad alone was
        # ~28 ms/iter at full Netflix).
        e_pad = ((e + tile - 1) // tile) * tile
        a_p = _pad_to(a, e_pad, axis=0)
        b_p = _pad_to(b, e_pad, axis=0)
        r_p = (
            _pad_to(reg, e_pad, axis=0)[None, :]
            if reg_mode == "diag" else reg
        )
    else:
        e_pad = e
        a_p, b_p = a, b
        r_p = reg[None, :] if reg_mode == "diag" else reg
    mem = {"memory_space": _VMEM} if _VMEM is not None and not interpret else {}
    r_spec = (
        pl.BlockSpec((1, tile), lambda i: (0, i), **mem)
        if reg_mode == "diag"
        else pl.BlockSpec((k, k), lambda i: (0, 0), **mem)
    )
    vma = typeof_vma(a_p)
    out_shape = (
        jax.ShapeDtypeStruct((e_pad, k), jnp.float32, vma=vma)
        if vma
        else jax.ShapeDtypeStruct((e_pad, k), jnp.float32)
    )
    kwargs = {}
    if pltpu is not None and not interpret:
        # The batch-first input block + its in-kernel batch-last transpose
        # both sit in VMEM through the unrolled elimination (~20 MB at
        # k=64, ~4× that at k=128); the default 16 MB scoped allowance is
        # far short.
        params = getattr(pltpu, "CompilerParams", None) or getattr(
            pltpu, "TPUCompilerParams"
        )
        kwargs["compiler_params"] = params(
            vmem_limit_bytes=(40 if k <= 64 else 100) * 1024 * 1024
        )
    if algo == "lu":
        kern = functools.partial(
            _lu_reg_kernel, k=k, reg_mode=reg_mode, lam=lam
        )
        kwargs["scratch_shapes"] = [
            pltpu.VMEM((k, k, tile), jnp.float32),
            pltpu.VMEM((k, tile), jnp.float32),
            pltpu.VMEM((k, tile), jnp.float32),
        ]
    elif algo == "gj":
        kern = functools.partial(
            _gauss_reg_kernel, k=k, reg_mode=reg_mode, lam=lam
        )
    else:
        raise ValueError(f"unknown reg-solve algo {algo!r}")
    x = pl.pallas_call(
        kern,
        out_shape=out_shape,
        grid=((e_pad + tile - 1) // tile,),
        in_specs=[
            pl.BlockSpec((tile, k, k), lambda i: (i, 0, 0), **mem),
            pl.BlockSpec((tile, k), lambda i: (i, 0), **mem),
            r_spec,
        ],
        out_specs=pl.BlockSpec((tile, k), lambda i: (i, 0), **mem),
        interpret=interpret,
        **kwargs,
    )(a_p, b_p, r_p)
    return x[:e]


def _pad_to(x: jax.Array, size: int, axis: int) -> jax.Array:
    pad = size - x.shape[axis]
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _lane_padded_inputs(a, b, b_pad_axis, interpret):
    """Shared wrapper prologue: lane-pad the batch, turn the all-zero padded
    systems into identity systems (the elimination would divide by zero),
    and resolve interpret mode.  Returns (a_p, b_p, e, e_pad, tile, interp).
    """
    k = a.shape[0]
    e = a.shape[2]
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    tile = _LANES
    e_pad = ((e + tile - 1) // tile) * tile
    a_p = _pad_to(a, e_pad, axis=2)
    b_p = _pad_to(b, e_pad, axis=b_pad_axis)
    if e_pad != e:
        pad_lane = jnp.arange(e_pad) >= e
        a_p = a_p + jnp.eye(k, dtype=a.dtype)[:, :, None] * pad_lane[None, None, :]
    return a_p, b_p, e, e_pad, tile, interpret


def _solve_call(kernel, a_p, b_p, b_block, out_struct, tile, interpret,
                vmem_limit=None):
    """Shared pallas_call plumbing: VMEM block specs (skipped in interpret
    mode), vma tagging of the output aval (under shard_map the output must
    carry the inputs' varying-mesh-axes), and the optional scoped-VMEM
    raise."""
    k = a_p.shape[0]
    e_pad = a_p.shape[2]
    mem = {"memory_space": _VMEM} if _VMEM is not None and not interpret else {}
    nb = len(b_block)
    b_map = (lambda i: (0, 0, i)) if nb == 3 else (lambda i: (0, i))
    specs = dict(
        in_specs=[
            pl.BlockSpec((k, k, tile), lambda i: (0, 0, i), **mem),
            pl.BlockSpec(b_block, b_map, **mem),
        ],
        out_specs=pl.BlockSpec(b_block, b_map, **mem),
    )
    shape, dtype = out_struct
    vma = typeof_vma(a_p)
    if vma:
        out_shape = jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    else:
        out_shape = jax.ShapeDtypeStruct(shape, dtype)
    kwargs = {}
    if vmem_limit is not None and pltpu is not None and not interpret:
        params = getattr(pltpu, "CompilerParams", None) or getattr(
            pltpu, "TPUCompilerParams"
        )
        kwargs["compiler_params"] = params(vmem_limit_bytes=vmem_limit)
    return pl.pallas_call(
        kernel,
        out_shape=out_shape,
        grid=(e_pad // tile,),
        interpret=interpret,
        **specs,
        **kwargs,
    )(a_p, b_p)


@functools.partial(jax.jit, static_argnames=("interpret",))
def gauss_solve_multi_pallas(
    a: jax.Array,  # [k, k, E] float32, SPD per system
    b: jax.Array,  # [k, m, E] float32 — m right-hand sides per system
    *,
    interpret: bool | None = None,
) -> jax.Array:  # [k, m, E]
    """Solve A X = B with an [m]-wide RHS block per system (batch-last).

    Used by the blocked Schur solve for rank > PALLAS_MAX_RANK: one call
    computes A₁₁⁻¹[A₁₂ | b₁] in a single elimination.  VMEM holds
    [k, k, tile] + [k, m, tile] live through the unrolled elimination, so
    m is capped at PALLAS_MAX_RANK + 8 and the scoped-VMEM budget is raised
    (the default 16 MB is ~24 MB short at k = m = 64).
    """
    k, m, e = b.shape
    if a.shape != (k, k, e):
        raise ValueError(f"a shape {a.shape} != ({k},{k},{e})")
    if k > PALLAS_MAX_RANK or m > PALLAS_MAX_RANK + 8:
        raise ValueError(
            f"gauss_solve_multi_pallas supports k <= {PALLAS_MAX_RANK}, "
            f"m <= {PALLAS_MAX_RANK + 8} (VMEM budget), got k={k} m={m}"
        )
    a_p, b_p, e, e_pad, tile, interpret = _lane_padded_inputs(
        a, b, 2, interpret
    )
    x = _solve_call(
        functools.partial(_gauss_multi_kernel, k=k),
        a_p, b_p, (k, m, tile), ((k, m, e_pad), a.dtype), tile, interpret,
        vmem_limit=40 * 1024 * 1024,
    )
    return x[:, :, :e]


@functools.partial(jax.jit, static_argnames=("interpret",))
def gauss_solve_pallas(
    a: jax.Array,  # [k, k, E] float32, SPD per system
    b: jax.Array,  # [k, E] float32
    *,
    interpret: bool | None = None,
) -> jax.Array:  # [k, E]
    """Solve A[:, :, e] x = b[:, e] for every e. Batch-last layout."""
    k, _, e = a.shape
    if k > PALLAS_MAX_RANK:
        raise ValueError(
            f"gauss_solve_pallas supports rank <= {PALLAS_MAX_RANK} (VMEM "
            f"budget), got {k}; use the cholesky backend"
        )
    a_p, b_p, e, e_pad, tile, interpret = _lane_padded_inputs(
        a, b, 1, interpret
    )
    x = _solve_call(
        functools.partial(_gauss_kernel, k=k),
        a_p, b_p, (k, tile), ((k, e_pad), a.dtype), tile, interpret,
    )
    return x[:, :e]
