"""Pallas TPU kernel: batched small-SPD solve via lane-vectorized Gauss-Jordan.

The framework's FLOP hot spot after the Gram matmuls is solving E independent
k×k SPD systems (k = rank, 5..128; E = entities per shard).  XLA lowers
``jnp.linalg.cholesky`` + two ``triangular_solve``s to sequential custom
calls that vectorize poorly for small k.  This kernel instead runs
Gauss-Jordan elimination with the *batch* dimension laid out along the TPU's
128-wide vector lanes: every scalar step of the textbook algorithm becomes a
[k, T] or [k, k, T] VPU op over T systems at once.  No pivoting — the
systems are SPD with a λ·n ≥ λ ridge (``regularized_solve``), so diagonal
pivots stay safely positive.

Layout contract: A is passed [k, k, E] and b [k, E] (batch LAST, so tiles
sit in the lane dimension).  The dispatcher (``ops.solve.dispatch_spd_solve``)
currently pays an explicit transpose from the batch-first Gram layout;
emitting batch-last straight from the Gram einsum is a known follow-up.

Cost: ≈ 2k³ FLOPs per system (vs k³/3 for Cholesky) — a 6× FLOP overhead
traded for full lane utilization, a win while the custom-call path is
latency-bound on small k.  The fully-unrolled k-loop holds [k, k, TILE]
temporaries in VMEM, which bounds the supported rank: k ≤ PALLAS_MAX_RANK
(= 64 → A tile 2 MiB); larger ranks must use the cholesky backend (the
dispatcher falls back automatically).  Falls back to interpret mode off-TPU
so tests run on CPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU-specific memory spaces; absent on some builds
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except ImportError:  # pragma: no cover
    pltpu = None
    _VMEM = None

_LANES = 128
# VMEM budget cap: the kernel keeps [k, k, _LANES] float32 blocks live
# through an unrolled k-step elimination; k=64 → 2 MiB per buffer. k=128
# was measured (raising Mosaic's scoped-VMEM allowance to fit the 8 MiB
# A-block): it compiles but runs ~10× SLOWER than XLA's cholesky there —
# the fully-unrolled elimination is VPU-bound at O(k³) while cholesky's
# custom-call overhead amortizes at larger k. The crossover favors this
# kernel only up to k = 64, so the cap stays.
PALLAS_MAX_RANK = 64


def _gauss_kernel(a_ref, b_ref, x_ref, *, k: int):
    """Solve T systems at once: a_ref [k,k,T], b_ref [k,T] → x_ref [k,T]."""
    a = a_ref[:]
    b = b_ref[:]
    # Row-index planes for the pivot-row selects below (in-kernel iota:
    # pallas kernels cannot capture array constants, and Mosaic needs
    # multi-dim iota).
    rows3 = jax.lax.broadcasted_iota(jnp.int32, (k, 1, 1), 0)
    rows2 = jax.lax.broadcasted_iota(jnp.int32, (k, 1), 0)
    for j in range(k):  # k is static → fully unrolled
        inv = 1.0 / a[j, j, :]  # [T]
        row = a[j] * inv[None, :]  # [k,T] normalized pivot row
        bj = b[j] * inv  # [T]
        col = a[:, j, :]  # [k,T]
        # Eliminate column j from every row, keeping the normalized pivot
        # row via a select (Mosaic has no scatter, so no .at[j].set; the
        # select is also exact where subtract-then-restore would leave an
        # epsilon residue on row j).
        a = jnp.where(rows3 == j, row[None, :, :],
                      a - col[:, None, :] * row[None, :, :])
        b = jnp.where(rows2 == j, bj[None, :], b - col * bj[None, :])
    x_ref[:] = b


def _pad_to(x: jax.Array, size: int, axis: int) -> jax.Array:
    pad = size - x.shape[axis]
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


@functools.partial(jax.jit, static_argnames=("interpret",))
def gauss_solve_pallas(
    a: jax.Array,  # [k, k, E] float32, SPD per system
    b: jax.Array,  # [k, E] float32
    *,
    interpret: bool | None = None,
) -> jax.Array:  # [k, E]
    """Solve A[:, :, e] x = b[:, e] for every e. Batch-last layout."""
    k, _, e = a.shape
    if k > PALLAS_MAX_RANK:
        raise ValueError(
            f"gauss_solve_pallas supports rank <= {PALLAS_MAX_RANK} (VMEM "
            f"budget), got {k}; use the cholesky backend"
        )
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    tile = _LANES
    e_pad = ((e + tile - 1) // tile) * tile
    a_p = _pad_to(a, e_pad, axis=2)
    b_p = _pad_to(b, e_pad, axis=1)
    # Padded systems are all-zero → the kernel would divide by zero. Make
    # them identity systems (x = 0 for b = 0) to keep arithmetic finite.
    if e_pad != e:
        pad_lane = jnp.arange(e_pad) >= e
        a_p = a_p + jnp.eye(k, dtype=a.dtype)[:, :, None] * pad_lane[None, None, :]
    grid = (e_pad // tile,)
    kwargs = {}
    if _VMEM is not None and not interpret:
        kwargs = dict(
            in_specs=[
                pl.BlockSpec((k, k, tile), lambda i: (0, 0, i), memory_space=_VMEM),
                pl.BlockSpec((k, tile), lambda i: (0, i), memory_space=_VMEM),
            ],
            out_specs=pl.BlockSpec((k, tile), lambda i: (0, i), memory_space=_VMEM),
        )
    else:
        kwargs = dict(
            in_specs=[
                pl.BlockSpec((k, k, tile), lambda i: (0, 0, i)),
                pl.BlockSpec((k, tile), lambda i: (0, i)),
            ],
            out_specs=pl.BlockSpec((k, tile), lambda i: (0, i)),
        )
    # Under shard_map the output aval must carry the same varying-mesh-axes
    # (vma) tag as the inputs; outside shard_map vma is empty/absent.
    vma = getattr(jax.typeof(a_p), "vma", None)
    if vma:
        out_shape = jax.ShapeDtypeStruct((k, e_pad), a.dtype, vma=vma)
    else:
        out_shape = jax.ShapeDtypeStruct((k, e_pad), a.dtype)
    x = pl.pallas_call(
        functools.partial(_gauss_kernel, k=k),
        out_shape=out_shape,
        grid=grid,
        interpret=interpret,
        **kwargs,
    )(a_p, b_p)
    return x[:, :e]
