"""Pallas TPU kernel: fused grouped Gram accumulation over entity tiles.

The tiled layout (``cfk_tpu.ops.tiled``) computes per-entity normal-equation
terms A_e = Σ w·f fᵀ, b_e = Σ r·f from [T, k] tiles, each tile owned by one
entity.  The XLA formulation materializes the per-tile Gram batch [NT, k, k]
(128 MB per 1M-entry chunk), pays a layout copy of the gathered factors
before the batched GEMM, zero-fills a segment-sum accumulator, and reduces
tiles to entities through it — together ~60% of the measured chunk cost
(round-3 profile: gram GEMM 1.0 ms + segment-sum 1.5 ms + layout copy
0.6 ms + b-reduce 0.36 ms + zeros 0.2 ms per 1M-entry chunk, vs 1.7 ms for
the irreducible neighbor gather).  This kernel fuses all of it: the whole
per-chunk output (A [S, k, k], b [S, 1, k]; S = entities-per-chunk + trash)
stays resident in VMEM across the grid, each grid step computes
``group_tiles`` tile Grams on the MXU and accumulates them into their
owners' rows by dynamic index, and the result is written to HBM exactly
once.  Nothing intermediate ever touches HBM.

Round-2's one-tile-per-grid-step version (measured 2.36 vs 1.97 s/iter at
full Netflix — overhead-bound, parked in VERDICT r2) indexed the *output*
by the scalar-prefetched owner and relied on pallas' revisiting-output
pattern; the multi-tile redesign instead owns the whole output block, which
removes the per-tile grid overhead AND the one-entity-per-step write
pattern.  Requirements: each owner's tiles CONTIGUOUS in the stream (the
layout sorts by owner; a non-contiguous owner's later run would assign over
its earlier one) and the per-chunk segment count S small enough that
S·k·(k+1)·4 B fits VMEM alongside the streamed inputs (the builder's chunk
sizing keeps S ≲ 2.5k, ≤ ~37 MB).

Contract difference vs the XLA segment-sum path: rows of segments owning
no tile are NEVER WRITTEN (garbage — a row's first flush assigns, which is
what makes zero-initializing the 37 MB output block unnecessary).  The
tiled layout guarantees every real entity in a chunk owns ≥ 1 tile; callers
route absent rows to trash (stream mode) or mask them (accum mode), exactly
as they did for the round-2 kernel.

Reference semantics matched: per-entity normal equations of
``processors/MFeatureCalculator.java:85-99``; λ·n regularization and
float32 accumulation identical to ``cfk_tpu.ops.solve`` (asserted by
``tests/test_pallas_solve.py`` / ``tests/test_tiled.py`` parity tests).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from cfk_tpu.compat import has_vma_system, typeof_vma
from jax.experimental import pallas as pl

try:  # TPU-specific extensions; absent on some builds
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None


def _gram_groups_kernel(seg_ref, g_ref, *refs, m, t, k, precision,
                        with_carry):
    # refs = (rt_ref, [ca_ref, cb_ref, ci_ref], a_ref, b_ref): the carry
    # triple present iff the caller folds a previous chunk's partial
    # (A, b) into segment 0 (stream mode's boundary straddle — doing it
    # here is ~free, while folding it outside either rewrote the whole
    # Gram batch through HBM or cost a separate one-system solve per
    # chunk, 97 ms/iter at rank 128).  Per-entry weights are expressed
    # upstream as the sqrt-reparameterized stream (g = √w·f — see
    # ``ops.tiled.ials_tiled_half_step``), so ONE stream serves both
    # weight modes; round 4's second premultiplied gw stream is gone.
    refs = list(refs)
    a_ref, b_ref = refs[-2:]
    del refs[-2:]
    if with_carry:
        ca_ref, cb_ref, ci_ref = refs[-3:]
        del refs[-3:]
    rt_ref = refs[0]
    gi = pl.program_id(0)
    base = gi * m
    # All m tile Grams are issued before the accumulation walk (they have
    # no dependence on it), so the MXU pipelines them back-to-back.  Tiles
    # are sliced statically — a [m·t, k] → [m, t, k] shape cast is not
    # supported by Mosaic's layout inference for every (t, k).
    a_all, b_all = [], []
    for i in range(m):  # m is static → unrolled
        g_i = g_ref[i * t:(i + 1) * t, :]  # [t, k]
        r_i = rt_ref[:, i * t:(i + 1) * t]  # [1, t]
        a_all.append(jax.lax.dot_general(
            g_i, g_i, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision,
        ))  # [k, k]
        b_all.append(jax.lax.dot_general(
            r_i.astype(g_i.dtype), g_i, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision,
        ))  # [1, k]

    def flush(row, began, acc_a, acc_b):
        @pl.when(began)
        def _assign():
            a_ref[pl.ds(row, 1)] = acc_a[None]
            b_ref[pl.ds(row, 1)] = acc_b[None]

        @pl.when(jnp.logical_not(began))
        def _accumulate():
            a_ref[pl.ds(row, 1)] += acc_a[None]
            b_ref[pl.ds(row, 1)] += acc_b[None]

    # Walk the group's tiles holding the running owner's partial (A, b) in
    # registers; output rows are touched only when the owner changes — ~one
    # write per entity instead of one read-modify-write per tile.  ``began``
    # = the running owner's first tile is inside this group, so its flush
    # ASSIGNS (first visit — which is what makes zero-init unnecessary);
    # otherwise the row already holds earlier groups' partials and the
    # flush accumulates.  Rows owning no tile are never written (garbage);
    # callers route them to trash exactly as they did for the v1 kernel.
    began = (gi == 0) | (seg_ref[base] != seg_ref[jnp.maximum(base - 1, 0)])
    acc_a, acc_b = a_all[0], b_all[0]
    if with_carry:
        # Segment 0 owns the chunk's first tile whenever cin is 1 (the
        # continued entity has entries here by definition), so adding the
        # scaled carry into the running partial at grid step 0 lands it in
        # segment 0's flushed row; cin = 0 multiplies it away.
        fold = jnp.where(gi == 0, ci_ref[0, 0], 0.0)
        acc_a = acc_a + fold * ca_ref[...]
        acc_b = acc_b + fold * cb_ref[...]
    for i in range(1, m):  # m is static → unrolled
        change = seg_ref[base + i] != seg_ref[base + i - 1]
        prev_row = seg_ref[base + i - 1]

        @pl.when(change)
        def _flush(row=prev_row, began=began, acc_a=acc_a, acc_b=acc_b):
            flush(row, began, acc_a, acc_b)

        # Arithmetic select: acc·keep + a is ONE fused multiply-add per
        # vreg where where(keep, acc+a, a) costs an add AND a select —
        # the accumulation chain is the kernel's VPU hot path (~60 ns/tile
        # over 1.8M tiles/iter at full Netflix).  Failure-mode caveat: a
        # non-finite acc (diverged factors) survives the ×0.0 reset as NaN
        # (inf·0 = NaN), so ONE bad tile Gram poisons every later segment
        # in the group, where a where-select would have discarded it at
        # the boundary.  Acceptable: non-finite factors are already a
        # broken run, and the trainers' outputs go NaN either way — this
        # only widens the blast radius within an already-lost iteration.
        keep_f = 1.0 - change.astype(jnp.float32)
        acc_a = acc_a * keep_f + a_all[i]
        acc_b = acc_b * keep_f + b_all[i]
        began = jnp.logical_or(began, change)
    flush(seg_ref[base + m - 1], began, acc_a, acc_b)


def _gram_dense_kernel(sc_ref, g_ref, *refs, m, t, k, ng, nt,
                       precision, with_carry):
    # Dense-stream variant: tiles are [t]-row WINDOWS into the dense
    # gathered stream at 16-aligned dynamic offsets (``pl.multiple_of``
    # — Mosaic rejects unhinted dynamic sublane slices of bf16 refs, and
    # sub-(16,128)-tile offsets straddle two VMEM tiles per vreg load,
    # which measured away the whole dense-stream win), with
    # rows outside [lo, hi) masked out of ONE dot operand (zeroed rows
    # contribute nothing to A; the tile-aligned rt carries zeros outside
    # the window, so b needs no mask).  Walk/flush semantics are identical
    # to ``_gram_groups_kernel``: owners' tiles are contiguous (trash
    # slots inherit the previous owner's seg with an empty window), rows
    # of absent segments are never written.  Weighted (iALS) runs stream
    # gs = √aw·f through this same unit-weight form (sqrt
    # reparameterization, ``ops.tiled.ials_tiled_half_step``).
    refs = list(refs)
    a_ref, b_ref = refs[-2:]
    del refs[-2:]
    if with_carry:
        ca_ref, cb_ref, ci_ref = refs[-3:]
        del refs[-3:]
    rt_ref = refs[0]
    gi = pl.program_id(0)
    base = gi * m
    s_lb, s_lo, s_hi, s_seg = ng, ng + nt, ng + 2 * nt, ng + 3 * nt
    # Row iota hoisted out of the unrolled loop; the window test
    # (rows >= lo) & (rows < hi) is ONE unsigned compare on (rows - lo)
    # — the mask chain is per-tile VPU work on the walk's critical path.
    rows = jax.lax.broadcasted_iota(jnp.int32, (t, k), 0)
    a_all, b_all = [], []
    for i in range(m):
        ti = base + i
        lb = pl.multiple_of(sc_ref[s_lb + ti], 16)
        lo = sc_ref[s_lo + ti]
        hi = sc_ref[s_hi + ti]
        keep = (rows - lo).astype(jnp.uint32) < (hi - lo).astype(jnp.uint32)
        gt = g_ref[pl.ds(lb, t), :]
        # One masked operand suffices: masked rows contribute zero rank-1
        # terms.
        gm = jnp.where(keep, gt, jnp.zeros_like(gt))
        r_i = rt_ref[:, i * t:(i + 1) * t]  # [1, t]
        a_all.append(jax.lax.dot_general(
            gm, gt, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision,
        ))
        b_all.append(jax.lax.dot_general(
            r_i.astype(gt.dtype), gt, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision,
        ))

    def flush(row, began, acc_a, acc_b):
        @pl.when(began)
        def _assign():
            a_ref[pl.ds(row, 1)] = acc_a[None]
            b_ref[pl.ds(row, 1)] = acc_b[None]

        @pl.when(jnp.logical_not(began))
        def _accumulate():
            a_ref[pl.ds(row, 1)] += acc_a[None]
            b_ref[pl.ds(row, 1)] += acc_b[None]

    seg = lambda i: sc_ref[s_seg + i]
    began = (gi == 0) | (seg(base) != seg(jnp.maximum(base - 1, 0)))
    acc_a, acc_b = a_all[0], b_all[0]
    if with_carry:
        fold = jnp.where(gi == 0, ci_ref[0, 0], 0.0)
        acc_a = acc_a + fold * ca_ref[...]
        acc_b = acc_b + fold * cb_ref[...]
    for i in range(1, m):
        change = seg(base + i) != seg(base + i - 1)
        prev_row = seg(base + i - 1)

        @pl.when(change)
        def _flush(row=prev_row, began=began, acc_a=acc_a, acc_b=acc_b):
            flush(row, began, acc_a, acc_b)

        keep_f = 1.0 - change.astype(jnp.float32)
        acc_a = acc_a * keep_f + a_all[i]
        acc_b = acc_b * keep_f + b_all[i]
        began = jnp.logical_or(began, change)
    flush(seg(base + m - 1), began, acc_a, acc_b)


@functools.partial(
    jax.jit,
    static_argnames=(
        "num_segments", "tile_rows", "num_tiles", "num_groups",
        "block_rows", "interpret",
    ),
)
def gram_tiles_dense_pallas(
    g: jax.Array,  # [C, k] densely packed gathered factors (bf16/f32)
    rt: jax.Array,  # [NT·T] f32 TILE-ALIGNED b coefficients (0 off-window)
    meta: jax.Array,  # [NG + 4·NT] int32: g_blk ‖ lb ‖ lo ‖ hi ‖ seg
    *,
    num_segments: int,
    tile_rows: int,
    num_tiles: int,  # NT (tile slots)
    num_groups: int,  # NG (grid steps; group size m = NT // NG)
    block_rows: int,  # BG (stream rows per pipelined block)
    interpret: bool | None = None,
    carry: tuple[jax.Array, jax.Array, jax.Array] | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Dense-stream grouped Gram: the unpadded-gather variant of
    ``gram_tiles_pallas``.

    The stream ``g`` carries only real entries (16-row run alignment,
    ~3.4% pad at Netflix shape vs 26% tile padding) — the win is on XLA's
    row-slot-bound gather engine, which produces ``g`` upstream.  The
    kernel pipelines ``g`` in [BG, k] blocks chosen by the per-group
    prefetched block index ``meta[:NG]`` (the builder keeps every group's
    tile windows inside one block), loads each tile as a [T]-row window
    at a dynamic 16-aligned offset, and masks rows outside [lo, hi).
    Same unwritten-absent-rows contract and chunk-boundary ``carry`` as
    ``gram_tiles_pallas``.  Weighted (iALS) callers pass the
    sqrt-reparameterized stream g = √aw·f with rescaled ``rt`` — one
    stream serves both weight modes (round 5; the former second ``gw``
    stream doubled pipelined traffic and squeezed VMEM at k = 128).
    See ``data.blocks._build_dense_stream`` for the metadata layout and
    contiguity guarantees.
    """
    c, k = g.shape
    t = tile_rows
    nt, ng, bg = num_tiles, num_groups, block_rows
    if nt % ng != 0:
        raise ValueError(f"num_tiles {nt} not divisible by num_groups {ng}")
    m = nt // ng
    if rt.shape != (nt * t,):
        raise ValueError(f"rt shape {rt.shape} != ({nt * t},)")
    if meta.shape != (ng + 4 * nt,):
        raise ValueError(f"meta shape {meta.shape} != ({ng + 4 * nt},)")
    if c % bg != 0 or bg < t:
        raise ValueError(f"stream length {c} not a multiple of block_rows "
                         f"{bg} >= tile_rows {t}")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if interpret:
        # Vectorized emulation (CPU tests, shard_map interpret — same vma
        # rationale as gram_tiles_pallas): zeros for absent rows.
        prec = (jax.lax.Precision.HIGHEST if g.dtype == jnp.float32
                else None)
        gblk = meta[:ng]
        lb = meta[ng:ng + nt]
        lo = meta[ng + nt:ng + 2 * nt]
        hi = meta[ng + 2 * nt:ng + 3 * nt]
        seg = meta[ng + 3 * nt:]
        absrow = jnp.repeat(gblk, m) * bg + lb  # [NT]
        win = absrow[:, None] + jnp.arange(t)[None, :]  # [NT, T]
        gt = g[win]  # [NT, T, k]
        rows = jnp.arange(t)[None, :]
        keep = (rows >= lo[:, None]) & (rows < hi[:, None])
        gm = jnp.where(keep[..., None], gt, jnp.zeros_like(gt))
        a_t = jnp.einsum("ntk,ntl->nkl", gm, gt,
                         preferred_element_type=jnp.float32, precision=prec)
        # rt stays float32 (ADVICE r5): the iALS ε-clamped b-coefficient
        # loses ~0.5–1% relative accuracy under a bf16 cast, and the real
        # kernel consumes the f32 stream directly.
        b_t = jnp.einsum("ntk,nt->nk", gt,
                         rt.reshape(nt, t).astype(jnp.float32),
                         precision=prec,
                         preferred_element_type=jnp.float32)
        a = jax.ops.segment_sum(a_t, seg, num_segments=num_segments,
                                indices_are_sorted=True)
        b = jax.ops.segment_sum(b_t, seg, num_segments=num_segments,
                                indices_are_sorted=True)
        if carry is not None:
            ca, cb, ci = carry
            a = a.at[0].add(ci * ca)
            b = b.at[0].add(ci * cb)
        return a, b
    if pltpu is None:  # pragma: no cover - non-TPU pallas build
        raise RuntimeError("pallas TPU extensions unavailable")

    vma = typeof_vma(g)
    mk = (lambda s, d: jax.ShapeDtypeStruct(s, d, vma=vma)) if vma else (
        lambda s, d: jax.ShapeDtypeStruct(s, d)
    )
    out_shape = (
        mk((num_segments, k, k), jnp.float32),
        mk((num_segments, 1, k), jnp.float32),
    )
    carry_specs = [] if carry is None else [
        pl.BlockSpec((k, k), lambda i, sc: (0, 0)),
        pl.BlockSpec((1, k), lambda i, sc: (0, 0)),
        pl.BlockSpec((1, 1), lambda i, sc: (0, 0)),
    ]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(ng,),
        in_specs=[
            pl.BlockSpec((bg, k), lambda i, sc: (sc[i], 0)),
            pl.BlockSpec((1, m * t), lambda i, sc: (0, i)),
        ] + carry_specs,
        out_specs=[
            pl.BlockSpec((num_segments, k, k), lambda i, sc: (0, 0, 0)),
            pl.BlockSpec((num_segments, 1, k), lambda i, sc: (0, 0, 0)),
        ],
    )
    precision = (
        jax.lax.Precision.HIGHEST if g.dtype == jnp.float32 else None
    )
    out_bytes = num_segments * k * (k + 1) * 4
    # Mosaic budgets input windows at 4 B/elem even for bf16 (measured in
    # the compile-OOM dump), and the resident output at 2× its bytes.
    in_bytes = 2 * (bg * k * 4 + m * t * 4)
    params = getattr(pltpu, "CompilerParams", None) or getattr(
        pltpu, "TPUCompilerParams"
    )
    kwargs = {"compiler_params": params(
        vmem_limit_bytes=min(2 * out_bytes + in_bytes + (10 << 20),
                             124 << 20)
    )}
    carry_ops = [] if carry is None else [
        carry[0].astype(jnp.float32),
        carry[1].reshape(1, k).astype(jnp.float32),
        carry[2].reshape(1, 1).astype(jnp.float32),
    ]
    a, b = pl.pallas_call(
        functools.partial(
            _gram_dense_kernel, m=m, t=t, k=k, ng=ng, nt=nt,
            precision=precision, with_carry=carry is not None,
        ),
        grid_spec=grid_spec,
        out_shape=out_shape,
        interpret=interpret,
        **kwargs,
    )(meta, g, rt.reshape(1, nt * t), *carry_ops)
    return a, b[:, 0, :]


@functools.partial(
    jax.jit,
    static_argnames=("num_segments", "tile_rows", "group_tiles", "interpret"),
)
def gram_tiles_pallas(
    g: jax.Array,  # [C, k] gathered neighbor factors (bf16 or f32)
    rt: jax.Array,  # [C] f32 b-side coefficients (0 at padding)
    seg: jax.Array,  # [NT] int32 owner of each tile (sorted by the layout)
    *,
    num_segments: int,  # output rows (Ec + 1, trash last)
    tile_rows: int,
    group_tiles: int = 64,  # swept on-chip: 16→0.849, 32→0.830, 64→0.824,
    # 128→0.823 s/iter at full Netflix — 64 is the knee (128 only bloats
    # the unrolled walk and compile time)
    interpret: bool | None = None,
    carry: tuple[jax.Array, jax.Array, jax.Array] | None = None,
) -> tuple[jax.Array, jax.Array]:
    """(A [num_segments, k, k] f32, b [num_segments, k] f32).

    ONE stream serves both weight modes: weighted (iALS) callers pass the
    sqrt-reparameterized copy g = √w·f (which fuses into the producing
    gather for free and streams in the factors' natural layout) with
    b-coefficients rescaled by 1/√w — so A = gᵀg = Σ w·f fᵀ and
    b = Σ c·f exactly (``ops.tiled.ials_tiled_half_step``).  A raw
    [C, 1] weight column would relayout into one element per (8, 128)
    tile (measured 0.4 ms/chunk of pure copy), and round 4's second
    premultiplied gw stream doubled the pipelined input traffic — both
    are avoided by construction.  Padding entries gather the appended
    zero row, so they vanish from both sums.

    ``carry = (a0 [k,k] f32, b0 [k] f32, cin scalar f32)`` adds
    ``cin·(a0, b0)`` into segment 0's sums — the stream scan's
    chunk-boundary straddle, folded here where it costs one fma pass per
    group instead of an [Ec,k,k] HBM rewrite or a separate one-system
    solve outside.

    Rows of segments owning no tile are UNSPECIFIED (never written) —
    callers must route them to trash (stream mode) or mask them (accum
    mode).
    """
    c, k = g.shape
    t = tile_rows
    if c % t != 0:
        raise ValueError(f"entry count {c} not divisible by tile_rows {t}")
    nt = c // t
    if seg.shape != (nt,):
        raise ValueError(f"seg shape {seg.shape} != ({nt},)")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if interpret and (typeof_vma(g) or not has_vma_system()):
        # Under shard_map with vma checking, the pallas HLO interpreter's
        # grid loop slices varying operands with unvarying grid counters
        # and fails the vma match.  Mosaic compilation is unaffected (the
        # indexing lives inside the kernel binary), so only CPU-interpret
        # sharded runs (tests, dryrun_multichip) take this branch: the
        # same math via segment-sum, zeros for absent rows (a superset of
        # the kernel's unspecified-rows contract).  Old-jax installs
        # (no vma system) take it too: their HLO interpreter predates
        # this kernel's patterns and runs orders of magnitude slower.
        prec = (jax.lax.Precision.HIGHEST if g.dtype == jnp.float32
                else None)
        gt = g.reshape(-1, tile_rows, k)
        a_t = jnp.einsum("ntk,ntl->nkl", gt, gt,
                         preferred_element_type=jnp.float32, precision=prec)
        # rt stays float32 (ADVICE r5) — see the dense emulation above.
        b_t = jnp.einsum("ntk,nt->nk", gt,
                         rt.reshape(-1, tile_rows).astype(jnp.float32),
                         preferred_element_type=jnp.float32, precision=prec)
        a = jax.ops.segment_sum(a_t, seg, num_segments=num_segments,
                                indices_are_sorted=True)
        b = jax.ops.segment_sum(b_t, seg, num_segments=num_segments,
                                indices_are_sorted=True)
        if carry is not None:
            ca, cb, ci = carry
            a = a.at[0].add(ci * ca)
            b = b.at[0].add(ci * cb)
        return a, b
    m = group_tiles
    while nt % m != 0:  # grid must tile exactly; m=1 always divides
        m //= 2

    vma = typeof_vma(g)
    mk = (lambda s, d: jax.ShapeDtypeStruct(s, d, vma=vma)) if vma else (
        lambda s, d: jax.ShapeDtypeStruct(s, d)
    )
    out_shape = (
        mk((num_segments, k, k), jnp.float32),
        mk((num_segments, 1, k), jnp.float32),
    )
    if pltpu is None:  # pragma: no cover - non-TPU pallas build
        raise RuntimeError("pallas TPU extensions unavailable")
    fac_spec = pl.BlockSpec((m * t, k), lambda i, seg: (i, 0))
    carry_specs = [] if carry is None else [
        pl.BlockSpec((k, k), lambda i, seg: (0, 0)),
        pl.BlockSpec((1, k), lambda i, seg: (0, 0)),
        pl.BlockSpec((1, 1), lambda i, seg: (0, 0)),
    ]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nt // m,),
        in_specs=[fac_spec,
                  pl.BlockSpec((1, m * t), lambda i, seg: (0, i))]
        + carry_specs,
        out_specs=[
            pl.BlockSpec((num_segments, k, k), lambda i, seg: (0, 0, 0)),
            pl.BlockSpec((num_segments, 1, k), lambda i, seg: (0, 0, 0)),
        ],
    )
    # f32 factors keep the solve path's full-precision convention (default
    # TPU matmul is bf16 — it would break reference parity ~1e-2 relative).
    precision = (
        jax.lax.Precision.HIGHEST if g.dtype == jnp.float32 else None
    )
    kwargs = {}
    if not interpret:
        # The resident output block dominates VMEM — and Mosaic double-
        # buffers output blocks even at a constant output index, so budget
        # 2× it plus the streamed input blocks with headroom (the default
        # 16 MB scoped allowance is far too small for S ≈ 2.5k segments).
        out_bytes = num_segments * k * (k + 1) * 4
        in_bytes = 2 * (m * t * (k + 1) * 4)
        params = getattr(pltpu, "CompilerParams", None) or getattr(
            pltpu, "TPUCompilerParams"
        )
        kwargs["compiler_params"] = params(
            vmem_limit_bytes=min(2 * out_bytes + 4 * in_bytes + (12 << 20),
                                 110 << 20)
        )
    carry_ops = [] if carry is None else [
        carry[0].astype(jnp.float32),
        carry[1].reshape(1, k).astype(jnp.float32),
        carry[2].reshape(1, 1).astype(jnp.float32),
    ]
    a, b = pl.pallas_call(
        functools.partial(
            _gram_groups_kernel, m=m, t=t, k=k, precision=precision,
            with_carry=carry is not None,
        ),
        grid_spec=grid_spec,
        out_shape=out_shape,
        interpret=interpret,
        **kwargs,
    )(seg, g, rt.reshape(1, c), *carry_ops)
    return a, b[:, 0, :]
