"""Pallas TPU kernel: fused grouped Gram accumulation over entity tiles.

The tiled layout (``cfk_tpu.ops.tiled``) computes per-entity normal-equation
terms A_e = Σ w·f fᵀ, b_e = Σ r·f from [T, k] tiles, each tile owned by one
entity.  The XLA formulation materializes the per-tile Gram batch
[NT, k, k] (268 MB/chunk at full-Netflix shapes), pays a layout copy before
the batched GEMM, and segment-sums tiles to entities — together the
dominant cost of a half-iteration (profiled ~60% of the chunk scan).  This
kernel fuses all of it: one grid step per tile computes the [k, k] tile
Gram on the MXU and accumulates it *directly into the owning entity's
output block*, exploiting that tiles are sorted by owner — pallas keeps the
output block resident in VMEM across consecutive same-index steps and
writes each entity's block to HBM exactly once (the standard revisiting-
output accumulation pattern).  Per-tile weights fold into the kernel too,
so the weighted copy of the gathered factors is never materialized.

Wire-up: ``seg`` rides the scalar-prefetch channel (SMEM) because the
output index_map needs it; first-visit detection compares seg[i] with
seg[i−1].  Padding tiles carry weight 0 and rating 0, so whatever rows
they point at contribute exact zeros to their (trash) entity block.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU-specific extensions; absent on some builds
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None


def _gram_tiles_kernel(seg_ref, g_ref, wt_ref, rt_ref, a_ref, b_ref,
                       *, precision):
    i = pl.program_id(0)
    g = g_ref[0]  # [T, k] (factor dtype)
    wt = wt_ref[0]  # [T, 1] f32 (column layout: Mosaic cannot reshape 1-D)
    rt = rt_ref[0]  # [1, T] f32 (row layout, ready for the b matvec)
    gw = g * wt.astype(g.dtype)
    a = jax.lax.dot_general(
        gw, g, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32, precision=precision,
    )  # [k, k]
    b = jax.lax.dot_general(
        rt.astype(g.dtype), g, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32, precision=precision,
    )  # [1, k]
    prev = seg_ref[jnp.maximum(i - 1, 0)]
    first = (i == 0) | (seg_ref[i] != prev)

    @pl.when(first)
    def _init():
        a_ref[0] = a
        b_ref[0] = b

    @pl.when(jnp.logical_not(first))
    def _acc():
        a_ref[0] += a
        b_ref[0] += b


@functools.partial(
    jax.jit, static_argnames=("num_segments", "tile_rows", "interpret")
)
def gram_tiles_pallas(
    g: jax.Array,  # [C, k] gathered neighbor factors (bf16 or f32)
    wt: jax.Array,  # [C] f32 A-side weights (0 at padding)
    rt: jax.Array,  # [C] f32 b-side coefficients (0 at padding)
    seg: jax.Array,  # [NT] int32 owner of each tile, sorted ascending
    *,
    num_segments: int,  # output rows (Ec + 1, trash last)
    tile_rows: int,
    interpret: bool | None = None,
) -> tuple[jax.Array, jax.Array]:
    """(A [num_segments, k, k] f32, b [num_segments, k] f32).

    Segments NOT owning any tile are left untouched — callers must treat
    absent entities as zero (the tiled layout guarantees every real entity
    in a chunk owns ≥ 1 tile, and the trash row is always hit by padding
    tiles or ignored).
    """
    c, k = g.shape
    t = tile_rows
    if c % t != 0:
        raise ValueError(f"entry count {c} not divisible by tile_rows {t}")
    nt = c // t
    if seg.shape != (nt,):
        raise ValueError(f"seg shape {seg.shape} != ({nt},)")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    vma = getattr(jax.typeof(g), "vma", None)
    mk = (lambda s, d: jax.ShapeDtypeStruct(s, d, vma=vma)) if vma else (
        lambda s, d: jax.ShapeDtypeStruct(s, d)
    )
    out_shape = (
        mk((num_segments, k, k), jnp.float32),
        mk((num_segments, 1, k), jnp.float32),
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nt,),
        in_specs=[
            pl.BlockSpec((1, t, k), lambda i, seg: (i, 0, 0)),
            pl.BlockSpec((1, t, 1), lambda i, seg: (i, 0, 0)),
            pl.BlockSpec((1, 1, t), lambda i, seg: (i, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, k, k), lambda i, seg: (seg[i], 0, 0)),
            pl.BlockSpec((1, 1, k), lambda i, seg: (seg[i], 0, 0)),
        ],
    ) if pltpu is not None else None
    if grid_spec is None:  # pragma: no cover - non-TPU pallas build
        raise RuntimeError("pallas TPU extensions unavailable")
    # f32 factors keep the solve path's full-precision convention (default
    # TPU matmul is bf16 — it would break reference parity ~1e-2 relative).
    precision = (
        jax.lax.Precision.HIGHEST if g.dtype == jnp.float32 else None
    )
    a, b = pl.pallas_call(
        functools.partial(_gram_tiles_kernel, precision=precision),
        grid_spec=grid_spec,
        out_shape=out_shape,
        interpret=interpret,
    )(seg, g.reshape(nt, t, k), wt.reshape(nt, t, 1), rt.reshape(nt, 1, t))
    return a, b[:, 0, :]
