"""Pallas TPU kernel: fused grouped Gram accumulation over entity tiles.

The tiled layout (``cfk_tpu.ops.tiled``) computes per-entity normal-equation
terms A_e = Σ w·f fᵀ, b_e = Σ r·f from [T, k] tiles, each tile owned by one
entity.  The XLA formulation materializes the per-tile Gram batch [NT, k, k]
(128 MB per 1M-entry chunk), pays a layout copy of the gathered factors
before the batched GEMM, zero-fills a segment-sum accumulator, and reduces
tiles to entities through it — together ~60% of the measured chunk cost
(round-3 profile: gram GEMM 1.0 ms + segment-sum 1.5 ms + layout copy
0.6 ms + b-reduce 0.36 ms + zeros 0.2 ms per 1M-entry chunk, vs 1.7 ms for
the irreducible neighbor gather).  This kernel fuses all of it: the whole
per-chunk output (A [S, k, k], b [S, 1, k]; S = entities-per-chunk + trash)
stays resident in VMEM across the grid, each grid step computes
``group_tiles`` tile Grams on the MXU and accumulates them into their
owners' rows by dynamic index, and the result is written to HBM exactly
once.  Nothing intermediate ever touches HBM.

Round-2's one-tile-per-grid-step version (measured 2.36 vs 1.97 s/iter at
full Netflix — overhead-bound, parked in VERDICT r2) indexed the *output*
by the scalar-prefetched owner and relied on pallas' revisiting-output
pattern; the multi-tile redesign instead owns the whole output block, which
removes the per-tile grid overhead AND the one-entity-per-step write
pattern.  Requirements: each owner's tiles CONTIGUOUS in the stream (the
layout sorts by owner; a non-contiguous owner's later run would assign over
its earlier one) and the per-chunk segment count S small enough that
S·k·(k+1)·4 B fits VMEM alongside the streamed inputs (the builder's chunk
sizing keeps S ≲ 2.5k, ≤ ~37 MB).

Contract difference vs the XLA segment-sum path: rows of segments owning
no tile are NEVER WRITTEN (garbage — a row's first flush assigns, which is
what makes zero-initializing the 37 MB output block unnecessary).  The
tiled layout guarantees every real entity in a chunk owns ≥ 1 tile; callers
route absent rows to trash (stream mode) or mask them (accum mode), exactly
as they did for the round-2 kernel.

Reference semantics matched: per-entity normal equations of
``processors/MFeatureCalculator.java:85-99``; λ·n regularization and
float32 accumulation identical to ``cfk_tpu.ops.solve`` (asserted by
``tests/test_pallas_solve.py`` / ``tests/test_tiled.py`` parity tests).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from cfk_tpu.compat import has_vma_system, typeof_vma
from jax.experimental import pallas as pl

try:  # TPU-specific extensions; absent on some builds
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

_SOLVE_LANES = 128  # lane width of the fused epilogue's solve tiles — the
# same 128-system batching the standalone solve kernels use


def _tile_grams(g_ref, rt_ref, *, m, t, k, precision, row_off=None):
    """The m tile Grams of one grid step's [m·t, k] factor block.

    All m are issued before the accumulation walk (they have no dependence
    on it), so the MXU pipelines them back-to-back.  Tiles are sliced
    statically — a [m·t, k] → [m, t, k] shape cast is not supported by
    Mosaic's layout inference for every (t, k).  ``row_off`` (the
    gather-fused kernels) offsets every tile into the double-buffered
    VMEM gather scratch instead — a 16-aligned dynamic base (the gather
    support gate requires t % 16 == 0, so every tile keeps the
    alignment Mosaic's sublane slicing wants).
    """
    a_all, b_all = [], []
    for i in range(m):  # m is static → unrolled
        if row_off is None:
            g_i = g_ref[i * t:(i + 1) * t, :]  # [t, k]
        else:
            g_i = g_ref[pl.ds(pl.multiple_of(row_off + i * t, 16), t), :]
        r_i = rt_ref[:, i * t:(i + 1) * t]  # [1, t]
        a_all.append(jax.lax.dot_general(
            g_i, g_i, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision,
        ))  # [k, k]
        b_all.append(jax.lax.dot_general(
            r_i.astype(g_i.dtype), g_i, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision,
        ))  # [1, k]
    return a_all, b_all


def _tile_grams_dense(sc_ref, g_ref, rt_ref, *, m, t, k, base, ng, nt,
                      precision, row_off=None):
    """Dense-stream tile Grams: [t]-row WINDOWS into the gathered stream at
    16-aligned dynamic offsets (``pl.multiple_of`` — Mosaic rejects
    unhinted dynamic sublane slices of bf16 refs, and sub-(16,128)-tile
    offsets straddle two VMEM tiles per vreg load), with rows outside
    [lo, hi) masked out of ONE dot operand (zeroed rows contribute nothing
    to A; the tile-aligned rt carries zeros outside the window, so b needs
    no mask).  ``row_off`` (the gather-fused kernels) rebases the windows
    into the double-buffered VMEM gather scratch — 16-aligned because the
    gather gate requires block_rows % 16 == 0."""
    s_lb, s_lo, s_hi = ng, ng + nt, ng + 2 * nt
    # Row iota hoisted out of the unrolled loop; the window test
    # (rows >= lo) & (rows < hi) is ONE unsigned compare on (rows - lo)
    # — the mask chain is per-tile VPU work on the walk's critical path.
    rows = jax.lax.broadcasted_iota(jnp.int32, (t, k), 0)
    a_all, b_all = [], []
    for i in range(m):
        ti = base + i
        lb_val = sc_ref[s_lb + ti]
        if row_off is not None:
            lb_val = row_off + lb_val
        lb = pl.multiple_of(lb_val, 16)
        lo = sc_ref[s_lo + ti]
        hi = sc_ref[s_hi + ti]
        keep = (rows - lo).astype(jnp.uint32) < (hi - lo).astype(jnp.uint32)
        gt = g_ref[pl.ds(lb, t), :]
        # One masked operand suffices: masked rows contribute zero rank-1
        # terms.
        gm = jnp.where(keep, gt, jnp.zeros_like(gt))
        r_i = rt_ref[:, i * t:(i + 1) * t]  # [1, t]
        a_all.append(jax.lax.dot_general(
            gm, gt, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision,
        ))
        b_all.append(jax.lax.dot_general(
            r_i.astype(gt.dtype), gt, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision,
        ))
    return a_all, b_all


def _walk_tiles(seg_of, a_all, b_all, *, gi, base, m, a_ref, b_ref, carry):
    """The owner-run accumulation walk shared by every grouped-Gram kernel.

    Walks the group's m tiles holding the running owner's partial (A, b) in
    registers; (a_ref, b_ref) rows — output blocks in the split kernels,
    VMEM scratch in the fused ones — are touched only when the owner
    changes: ~one write per entity instead of one read-modify-write per
    tile.  ``began`` = the running owner's first tile is inside this group,
    so its flush ASSIGNS (first visit — which is what makes zero-init
    unnecessary); otherwise the row already holds earlier groups' partials
    and the flush accumulates.  Rows owning no tile are never written
    (garbage); callers route them to trash exactly as before.

    ``carry = (ca_ref, cb_ref, ci_ref)`` folds a previous chunk's partial
    (A, b) into segment 0 at grid step 0 (stream mode's boundary straddle
    — doing it here is ~free, while folding it outside either rewrote the
    whole Gram batch through HBM or cost a separate one-system solve per
    chunk, 97 ms/iter at rank 128).
    """
    def flush(row, began, acc_a, acc_b):
        @pl.when(began)
        def _assign():
            a_ref[pl.ds(row, 1)] = acc_a[None]
            b_ref[pl.ds(row, 1)] = acc_b[None]

        @pl.when(jnp.logical_not(began))
        def _accumulate():
            a_ref[pl.ds(row, 1)] += acc_a[None]
            b_ref[pl.ds(row, 1)] += acc_b[None]

    began = (gi == 0) | (seg_of(base) != seg_of(jnp.maximum(base - 1, 0)))
    acc_a, acc_b = a_all[0], b_all[0]
    if carry is not None:
        # Segment 0 owns the chunk's first tile whenever cin is 1 (the
        # continued entity has entries here by definition), so adding the
        # scaled carry into the running partial at grid step 0 lands it in
        # segment 0's flushed row; cin = 0 multiplies it away.
        ca_ref, cb_ref, ci_ref = carry
        fold = jnp.where(gi == 0, ci_ref[0, 0], 0.0)
        acc_a = acc_a + fold * ca_ref[...]
        acc_b = acc_b + fold * cb_ref[...]
    for i in range(1, m):  # m is static → unrolled
        change = seg_of(base + i) != seg_of(base + i - 1)
        prev_row = seg_of(base + i - 1)

        @pl.when(change)
        def _flush(row=prev_row, began=began, acc_a=acc_a, acc_b=acc_b):
            flush(row, began, acc_a, acc_b)

        # Arithmetic select: acc·keep + a is ONE fused multiply-add per
        # vreg where where(keep, acc+a, a) costs an add AND a select —
        # the accumulation chain is the kernel's VPU hot path (~60 ns/tile
        # over 1.8M tiles/iter at full Netflix).  Failure-mode caveat: a
        # non-finite acc (diverged factors) survives the ×0.0 reset as NaN
        # (inf·0 = NaN), so ONE bad tile Gram poisons every later segment
        # in the group, where a where-select would have discarded it at
        # the boundary.  Acceptable: non-finite factors are already a
        # broken run, and the trainers' outputs go NaN either way — this
        # only widens the blast radius within an already-lost iteration.
        keep_f = 1.0 - change.astype(jnp.float32)
        acc_a = acc_a * keep_f + a_all[i]
        acc_b = acc_b * keep_f + b_all[i]
        began = jnp.logical_or(began, change)
    flush(seg_of(base + m - 1), began, acc_a, acc_b)


def _solve_epilogue(a_scr, b_scr, reg_ref, lseg, x_ref, cao_ref, cbo_ref,
                    lu_scr, *, k, s_pad, reg_mode, lam, algo):
    """The fused Gram+solve epilogue: ridge + eliminate the VMEM-resident
    (A, b) in place, write back only the solved rows.

    Runs once, at the LAST grid step, after the walk's final flush: the
    chunk's whole (A [s_pad, k, k], b [s_pad, 1, k]) batch lives in VMEM
    *scratch* (never HBM — the split path's [Ec, k, k] write + readback is
    the round-trip this removes).  Per 128-lane tile it transposes to the
    solve kernels' batch-last layout, applies the regularizer in-register
    (``apply_reg_lanes`` — ``diag`` λ·max(n,1)·I from the padded count
    row, ``matrix`` one shared [k,k] Y'Y+λI), and runs the same
    lane-vectorized elimination the standalone reg+solve kernels use
    (``lu_solve_lanes``/``gj_solve_lanes``, ``solve_kernel.py``).  The
    chunk-boundary carry row (RAW, pre-ridge — the next chunk folds it
    into its own sums) is extracted at ``lseg`` before the solve.

    Rows of segments owning no tile hold scratch garbage; their "solves"
    produce garbage confined to their own lanes (every lane is an
    independent system) and callers route those rows to trash, exactly as
    they did for the unwritten rows of the split kernels.
    """
    cao_ref[...] = a_scr[pl.ds(lseg, 1)][0]
    cbo_ref[...] = b_scr[pl.ds(lseg, 1)][0]

    def tile_body(i, c):
        ts = pl.multiple_of(i * _SOLVE_LANES, _SOLVE_LANES)
        a_blt = jnp.transpose(
            a_scr[pl.ds(ts, _SOLVE_LANES)], (1, 2, 0)
        )  # [k, k, T] batch-last
        y = b_scr[pl.ds(ts, _SOLVE_LANES)][:, 0, :].T  # [k, T]
        reg = (reg_ref[0, pl.ds(ts, _SOLVE_LANES)] if reg_mode == "diag"
               else reg_ref[...])
        from cfk_tpu.ops.pallas.solve_kernel import (
            apply_reg_lanes,
            gj_solve_lanes,
            lu_solve_lanes,
        )

        tr = apply_reg_lanes(a_blt, reg, k=k, reg_mode=reg_mode, lam=lam)
        if algo == "lu":
            xt = lu_solve_lanes(tr, y, *lu_scr, k=k)
        else:
            xt = gj_solve_lanes(tr, y, k=k)
        x_ref[pl.ds(ts, _SOLVE_LANES)] = xt.T
        return c

    lax.fori_loop(0, s_pad // _SOLVE_LANES, tile_body, 0)


def _gram_groups_kernel(seg_ref, g_ref, *refs, m, t, k, precision,
                        with_carry):
    # refs = (rt_ref, [ca_ref, cb_ref, ci_ref], a_ref, b_ref): the carry
    # triple present iff the caller folds a previous chunk's partial
    # (A, b) into segment 0 (stream mode's boundary straddle — folded in
    # the walk, see ``_walk_tiles``).  Per-entry weights are expressed
    # upstream as the sqrt-reparameterized stream (g = √w·f — see
    # ``ops.tiled.ials_tiled_half_step``), so ONE stream serves both
    # weight modes; round 4's second premultiplied gw stream is gone.
    refs = list(refs)
    a_ref, b_ref = refs[-2:]
    del refs[-2:]
    carry = None
    if with_carry:
        carry = tuple(refs[-3:])
        del refs[-3:]
    rt_ref = refs[0]
    gi = pl.program_id(0)
    base = gi * m
    a_all, b_all = _tile_grams(g_ref, rt_ref, m=m, t=t, k=k,
                               precision=precision)
    _walk_tiles(lambda i: seg_ref[i], a_all, b_all, gi=gi, base=base, m=m,
                a_ref=a_ref, b_ref=b_ref, carry=carry)


def _gram_dense_kernel(sc_ref, g_ref, *refs, m, t, k, ng, nt,
                       precision, with_carry):
    # Dense-stream variant (see ``_tile_grams_dense`` for the windowing).
    # Walk/flush semantics are identical to ``_gram_groups_kernel``:
    # owners' tiles are contiguous (trash slots inherit the previous
    # owner's seg with an empty window), rows of absent segments are never
    # written.  Weighted (iALS) runs stream gs = √aw·f through this same
    # unit-weight form (sqrt reparameterization,
    # ``ops.tiled.ials_tiled_half_step``).
    refs = list(refs)
    a_ref, b_ref = refs[-2:]
    del refs[-2:]
    carry = None
    if with_carry:
        carry = tuple(refs[-3:])
        del refs[-3:]
    rt_ref = refs[0]
    gi = pl.program_id(0)
    base = gi * m
    s_seg = ng + 3 * nt
    a_all, b_all = _tile_grams_dense(
        sc_ref, g_ref, rt_ref, m=m, t=t, k=k, base=base, ng=ng, nt=nt,
        precision=precision,
    )
    _walk_tiles(lambda i: sc_ref[s_seg + i], a_all, b_all, gi=gi, base=base,
                m=m, a_ref=a_ref, b_ref=b_ref, carry=carry)


@functools.partial(
    jax.jit,
    static_argnames=(
        "num_segments", "tile_rows", "num_tiles", "num_groups",
        "block_rows", "interpret",
    ),
)
def gram_tiles_dense_pallas(
    g: jax.Array,  # [C, k] densely packed gathered factors (bf16/f32)
    rt: jax.Array,  # [NT·T] f32 TILE-ALIGNED b coefficients (0 off-window)
    meta: jax.Array,  # [NG + 4·NT] int32: g_blk ‖ lb ‖ lo ‖ hi ‖ seg
    *,
    num_segments: int,
    tile_rows: int,
    num_tiles: int,  # NT (tile slots)
    num_groups: int,  # NG (grid steps; group size m = NT // NG)
    block_rows: int,  # BG (stream rows per pipelined block)
    interpret: bool | None = None,
    carry: tuple[jax.Array, jax.Array, jax.Array] | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Dense-stream grouped Gram: the unpadded-gather variant of
    ``gram_tiles_pallas``.

    The stream ``g`` carries only real entries (16-row run alignment,
    ~3.4% pad at Netflix shape vs 26% tile padding) — the win is on XLA's
    row-slot-bound gather engine, which produces ``g`` upstream.  The
    kernel pipelines ``g`` in [BG, k] blocks chosen by the per-group
    prefetched block index ``meta[:NG]`` (the builder keeps every group's
    tile windows inside one block), loads each tile as a [T]-row window
    at a dynamic 16-aligned offset, and masks rows outside [lo, hi).
    Same unwritten-absent-rows contract and chunk-boundary ``carry`` as
    ``gram_tiles_pallas``.  Weighted (iALS) callers pass the
    sqrt-reparameterized stream g = √aw·f with rescaled ``rt`` — one
    stream serves both weight modes (round 5; the former second ``gw``
    stream doubled pipelined traffic and squeezed VMEM at k = 128).
    See ``data.blocks._build_dense_stream`` for the metadata layout and
    contiguity guarantees.
    """
    c, k = g.shape
    t = tile_rows
    nt, ng, bg = num_tiles, num_groups, block_rows
    if nt % ng != 0:
        raise ValueError(f"num_tiles {nt} not divisible by num_groups {ng}")
    m = nt // ng
    if rt.shape != (nt * t,):
        raise ValueError(f"rt shape {rt.shape} != ({nt * t},)")
    if meta.shape != (ng + 4 * nt,):
        raise ValueError(f"meta shape {meta.shape} != ({ng + 4 * nt},)")
    if c % bg != 0 or bg < t:
        raise ValueError(f"stream length {c} not a multiple of block_rows "
                         f"{bg} >= tile_rows {t}")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if interpret:
        # Vectorized emulation (CPU tests, shard_map interpret — same vma
        # rationale as gram_tiles_pallas): zeros for absent rows.
        return _emulate_gram_dense(
            g, rt, meta, num_segments=num_segments, tile_rows=t,
            num_tiles=nt, num_groups=ng, block_rows=bg, carry=carry,
        )
    if pltpu is None:  # pragma: no cover - non-TPU pallas build
        raise RuntimeError("pallas TPU extensions unavailable")

    vma = typeof_vma(g)
    mk = (lambda s, d: jax.ShapeDtypeStruct(s, d, vma=vma)) if vma else (
        lambda s, d: jax.ShapeDtypeStruct(s, d)
    )
    out_shape = (
        mk((num_segments, k, k), jnp.float32),
        mk((num_segments, 1, k), jnp.float32),
    )
    carry_specs = [] if carry is None else [
        pl.BlockSpec((k, k), lambda i, sc: (0, 0)),
        pl.BlockSpec((1, k), lambda i, sc: (0, 0)),
        pl.BlockSpec((1, 1), lambda i, sc: (0, 0)),
    ]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(ng,),
        in_specs=[
            pl.BlockSpec((bg, k), lambda i, sc: (sc[i], 0)),
            pl.BlockSpec((1, m * t), lambda i, sc: (0, i)),
        ] + carry_specs,
        out_specs=[
            pl.BlockSpec((num_segments, k, k), lambda i, sc: (0, 0, 0)),
            pl.BlockSpec((num_segments, 1, k), lambda i, sc: (0, 0, 0)),
        ],
    )
    precision = (
        jax.lax.Precision.HIGHEST if g.dtype == jnp.float32 else None
    )
    out_bytes = num_segments * k * (k + 1) * 4
    # Mosaic budgets input windows at 4 B/elem even for bf16 (measured in
    # the compile-OOM dump), and the resident output at 2× its bytes.
    in_bytes = 2 * (bg * k * 4 + m * t * 4)
    params = getattr(pltpu, "CompilerParams", None) or getattr(
        pltpu, "TPUCompilerParams"
    )
    kwargs = {"compiler_params": params(
        vmem_limit_bytes=min(2 * out_bytes + in_bytes + (10 << 20),
                             124 << 20)
    )}
    carry_ops = [] if carry is None else [
        carry[0].astype(jnp.float32),
        carry[1].reshape(1, k).astype(jnp.float32),
        carry[2].reshape(1, 1).astype(jnp.float32),
    ]
    a, b = pl.pallas_call(
        functools.partial(
            _gram_dense_kernel, m=m, t=t, k=k, ng=ng, nt=nt,
            precision=precision, with_carry=carry is not None,
        ),
        grid_spec=grid_spec,
        out_shape=out_shape,
        interpret=interpret,
        **kwargs,
    )(meta, g, rt.reshape(1, nt * t), *carry_ops)
    return a, b[:, 0, :]


@functools.partial(
    jax.jit,
    static_argnames=("num_segments", "tile_rows", "group_tiles", "interpret"),
)
def gram_tiles_pallas(
    g: jax.Array,  # [C, k] gathered neighbor factors (bf16 or f32)
    rt: jax.Array,  # [C] f32 b-side coefficients (0 at padding)
    seg: jax.Array,  # [NT] int32 owner of each tile (sorted by the layout)
    *,
    num_segments: int,  # output rows (Ec + 1, trash last)
    tile_rows: int,
    group_tiles: int = 64,  # swept on-chip: 16→0.849, 32→0.830, 64→0.824,
    # 128→0.823 s/iter at full Netflix — 64 is the knee (128 only bloats
    # the unrolled walk and compile time)
    interpret: bool | None = None,
    carry: tuple[jax.Array, jax.Array, jax.Array] | None = None,
) -> tuple[jax.Array, jax.Array]:
    """(A [num_segments, k, k] f32, b [num_segments, k] f32).

    ONE stream serves both weight modes: weighted (iALS) callers pass the
    sqrt-reparameterized copy g = √w·f (which fuses into the producing
    gather for free and streams in the factors' natural layout) with
    b-coefficients rescaled by 1/√w — so A = gᵀg = Σ w·f fᵀ and
    b = Σ c·f exactly (``ops.tiled.ials_tiled_half_step``).  A raw
    [C, 1] weight column would relayout into one element per (8, 128)
    tile (measured 0.4 ms/chunk of pure copy), and round 4's second
    premultiplied gw stream doubled the pipelined input traffic — both
    are avoided by construction.  Padding entries gather the appended
    zero row, so they vanish from both sums.

    ``carry = (a0 [k,k] f32, b0 [k] f32, cin scalar f32)`` adds
    ``cin·(a0, b0)`` into segment 0's sums — the stream scan's
    chunk-boundary straddle, folded here where it costs one fma pass per
    group instead of an [Ec,k,k] HBM rewrite or a separate one-system
    solve outside.

    Rows of segments owning no tile are UNSPECIFIED (never written) —
    callers must route them to trash (stream mode) or mask them (accum
    mode).
    """
    c, k = g.shape
    t = tile_rows
    if c % t != 0:
        raise ValueError(f"entry count {c} not divisible by tile_rows {t}")
    nt = c // t
    if seg.shape != (nt,):
        raise ValueError(f"seg shape {seg.shape} != ({nt},)")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if interpret and (typeof_vma(g) or not has_vma_system()):
        # Under shard_map with vma checking, the pallas HLO interpreter's
        # grid loop slices varying operands with unvarying grid counters
        # and fails the vma match.  Mosaic compilation is unaffected (the
        # indexing lives inside the kernel binary), so only CPU-interpret
        # sharded runs (tests, dryrun_multichip) take this branch: the
        # same math via segment-sum, zeros for absent rows (a superset of
        # the kernel's unspecified-rows contract).  Old-jax installs
        # (no vma system) take it too: their HLO interpreter predates
        # this kernel's patterns and runs orders of magnitude slower.
        return _emulate_gram_tiles(
            g, rt, seg, num_segments=num_segments, tile_rows=tile_rows,
            carry=carry,
        )
    m = group_tiles
    while nt % m != 0:  # grid must tile exactly; m=1 always divides
        m //= 2

    vma = typeof_vma(g)
    mk = (lambda s, d: jax.ShapeDtypeStruct(s, d, vma=vma)) if vma else (
        lambda s, d: jax.ShapeDtypeStruct(s, d)
    )
    out_shape = (
        mk((num_segments, k, k), jnp.float32),
        mk((num_segments, 1, k), jnp.float32),
    )
    if pltpu is None:  # pragma: no cover - non-TPU pallas build
        raise RuntimeError("pallas TPU extensions unavailable")
    fac_spec = pl.BlockSpec((m * t, k), lambda i, seg: (i, 0))
    carry_specs = [] if carry is None else [
        pl.BlockSpec((k, k), lambda i, seg: (0, 0)),
        pl.BlockSpec((1, k), lambda i, seg: (0, 0)),
        pl.BlockSpec((1, 1), lambda i, seg: (0, 0)),
    ]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nt // m,),
        in_specs=[fac_spec,
                  pl.BlockSpec((1, m * t), lambda i, seg: (0, i))]
        + carry_specs,
        out_specs=[
            pl.BlockSpec((num_segments, k, k), lambda i, seg: (0, 0, 0)),
            pl.BlockSpec((num_segments, 1, k), lambda i, seg: (0, 0, 0)),
        ],
    )
    # f32 factors keep the solve path's full-precision convention (default
    # TPU matmul is bf16 — it would break reference parity ~1e-2 relative).
    precision = (
        jax.lax.Precision.HIGHEST if g.dtype == jnp.float32 else None
    )
    kwargs = {}
    if not interpret:
        # The resident output block dominates VMEM — and Mosaic double-
        # buffers output blocks even at a constant output index, so budget
        # 2× it plus the streamed input blocks with headroom (the default
        # 16 MB scoped allowance is far too small for S ≈ 2.5k segments).
        out_bytes = num_segments * k * (k + 1) * 4
        in_bytes = 2 * (m * t * (k + 1) * 4)
        params = getattr(pltpu, "CompilerParams", None) or getattr(
            pltpu, "TPUCompilerParams"
        )
        kwargs["compiler_params"] = params(
            vmem_limit_bytes=min(2 * out_bytes + 4 * in_bytes + (12 << 20),
                                 110 << 20)
        )
    carry_ops = [] if carry is None else [
        carry[0].astype(jnp.float32),
        carry[1].reshape(1, k).astype(jnp.float32),
        carry[2].reshape(1, 1).astype(jnp.float32),
    ]
    a, b = pl.pallas_call(
        functools.partial(
            _gram_groups_kernel, m=m, t=t, k=k, precision=precision,
            with_carry=carry is not None,
        ),
        grid_spec=grid_spec,
        out_shape=out_shape,
        interpret=interpret,
        **kwargs,
    )(seg, g, rt.reshape(1, c), *carry_ops)
    return a, b[:, 0, :]


def _emulate_gram_tiles(g, rt, seg, *, num_segments, tile_rows, carry):
    """XLA segment-sum emulation of the grouped-Gram kernel (interpret /
    shard_map-vma / old-jax routes): zeros for absent rows — a superset of
    the kernel's unspecified-rows contract."""
    k = g.shape[-1]
    prec = (jax.lax.Precision.HIGHEST if g.dtype == jnp.float32 else None)
    gt = g.reshape(-1, tile_rows, k)
    a_t = jnp.einsum("ntk,ntl->nkl", gt, gt,
                     preferred_element_type=jnp.float32, precision=prec)
    # rt stays float32 (ADVICE r5): the iALS ε-clamped b-coefficient
    # loses ~0.5–1% relative accuracy under a bf16 cast, and the real
    # kernel consumes the f32 stream directly.
    b_t = jnp.einsum("ntk,nt->nk", gt,
                     rt.reshape(-1, tile_rows).astype(jnp.float32),
                     preferred_element_type=jnp.float32, precision=prec)
    a = jax.ops.segment_sum(a_t, seg, num_segments=num_segments,
                            indices_are_sorted=True)
    b = jax.ops.segment_sum(b_t, seg, num_segments=num_segments,
                            indices_are_sorted=True)
    if carry is not None:
        ca, cb, ci = carry
        a = a.at[0].add(ci * ca)
        b = b.at[0].add(ci * cb)
    return a, b


def _emulate_gram_dense(g, rt, meta, *, num_segments, tile_rows, num_tiles,
                        num_groups, block_rows, carry):
    """XLA emulation of the dense-stream grouped-Gram kernel: windowed
    gathers + masked einsums + segment-sum, zeros for absent rows."""
    k = g.shape[-1]
    t, nt, ng, bg = tile_rows, num_tiles, num_groups, block_rows
    m = nt // ng
    prec = (jax.lax.Precision.HIGHEST if g.dtype == jnp.float32 else None)
    gblk = meta[:ng]
    lb = meta[ng:ng + nt]
    lo = meta[ng + nt:ng + 2 * nt]
    hi = meta[ng + 2 * nt:ng + 3 * nt]
    seg = meta[ng + 3 * nt:ng + 4 * nt]
    absrow = jnp.repeat(gblk, m) * bg + lb  # [NT]
    win = absrow[:, None] + jnp.arange(t)[None, :]  # [NT, T]
    gt = g[win]  # [NT, T, k]
    rows = jnp.arange(t)[None, :]
    keep = (rows >= lo[:, None]) & (rows < hi[:, None])
    gm = jnp.where(keep[..., None], gt, jnp.zeros_like(gt))
    a_t = jnp.einsum("ntk,ntl->nkl", gm, gt,
                     preferred_element_type=jnp.float32, precision=prec)
    # rt stays float32 (ADVICE r5) — see _emulate_gram_tiles.
    b_t = jnp.einsum("ntk,nt->nk", gt,
                     rt.reshape(nt, t).astype(jnp.float32),
                     precision=prec, preferred_element_type=jnp.float32)
    a = jax.ops.segment_sum(a_t, seg, num_segments=num_segments,
                            indices_are_sorted=True)
    b = jax.ops.segment_sum(b_t, seg, num_segments=num_segments,
                            indices_are_sorted=True)
    if carry is not None:
        ca, cb, ci = carry
        a = a.at[0].add(ci * ca)
        b = b.at[0].add(ci * cb)
    return a, b


def _fused_scratch_bytes(s_pad: int, k: int) -> int:
    """VMEM bytes of the fused epilogue's resident state: the (A, b)
    scratch plus the elimination's [k, k, 128]-class temporaries (budgeted
    at the worst case — LU's three scratch buffers plus the in-register
    transposed tile).  ONE formula, shared by the support gate below and
    the pallas_call budget (``_fused_call_pieces``), so the two can never
    drift into a gate that admits a shape the compiler then rejects."""
    return (s_pad * k * (k + 1) + 4 * k * k * _SOLVE_LANES) * 4


def fused_gram_solve_supported(num_segments: int, k: int,
                               algo: str | None = None) -> bool:
    """Can the fused Gram+solve epilogue handle this chunk shape?

    Two gates: the rank must fit the fused reg+solve elimination's cap
    (LU 128 / GJ 64 — past it the dispatcher's cholesky/Schur backends are
    needed, which only exist as separate passes; ``algo`` threads the
    caller's elimination choice, None/'auto' = the process default), and
    the lane-padded (A, b) scratch (``_fused_scratch_bytes`` — same
    formula the compile budget uses) must leave VMEM headroom for the
    double-buffered input blocks under the ~124 MB scoped ceiling.  The
    72 MB gate reserves ≥ 50 MB for inputs (the gate cannot see the
    chunk's block size, so it is conservative: a refused shape takes the
    split path — same math, one extra round-trip — never a Mosaic compile
    failure).
    """
    from cfk_tpu.ops.pallas.solve_kernel import _fused_reg_rank_cap

    if k > _fused_reg_rank_cap(algo):
        return False
    s_pad = -(-num_segments // _SOLVE_LANES) * _SOLVE_LANES
    return _fused_scratch_bytes(s_pad, k) <= (72 << 20)


def _gram_solve_groups_kernel(seg_ref, g_ref, *refs, m, t, k, s_pad,
                              nt_total, precision, with_carry, reg_mode,
                              lam, algo):
    """Fused variant of ``_gram_groups_kernel``: the walk accumulates into
    VMEM *scratch* instead of output blocks, and the last grid step runs
    the ridge+solve epilogue in place (``_solve_epilogue``), writing back
    only the solved [s_pad, k] rows and the chunk-boundary carry row.
    ``seg_ref`` carries the chunk's lseg appended at index ``nt_total``.
    """
    refs = list(refs)
    if algo == "lu":
        lu_scr = tuple(refs[-3:])
        del refs[-3:]
    else:
        lu_scr = None
    a_scr, b_scr = refs[-2:]
    del refs[-2:]
    x_ref, cao_ref, cbo_ref = refs[-3:]
    del refs[-3:]
    carry = None
    if with_carry:
        carry = tuple(refs[-3:])
        del refs[-3:]
    rt_ref, reg_ref = refs[0], refs[1]
    gi = pl.program_id(0)
    base = gi * m
    a_all, b_all = _tile_grams(g_ref, rt_ref, m=m, t=t, k=k,
                               precision=precision)
    _walk_tiles(lambda i: seg_ref[i], a_all, b_all, gi=gi, base=base, m=m,
                a_ref=a_scr, b_ref=b_scr, carry=carry)

    @pl.when(gi == pl.num_programs(0) - 1)
    def _epilogue():
        _solve_epilogue(
            a_scr, b_scr, reg_ref, seg_ref[nt_total], x_ref, cao_ref,
            cbo_ref, lu_scr, k=k, s_pad=s_pad, reg_mode=reg_mode, lam=lam,
            algo=algo,
        )


def _gram_solve_dense_kernel(sc_ref, g_ref, *refs, m, t, k, ng, nt, s_pad,
                             precision, with_carry, reg_mode, lam, algo):
    """Fused variant of ``_gram_dense_kernel`` — same scratch-resident walk
    + last-step ridge+solve epilogue as ``_gram_solve_groups_kernel``.
    ``sc_ref`` carries the chunk's lseg appended at index ``ng + 4·nt``."""
    refs = list(refs)
    if algo == "lu":
        lu_scr = tuple(refs[-3:])
        del refs[-3:]
    else:
        lu_scr = None
    a_scr, b_scr = refs[-2:]
    del refs[-2:]
    x_ref, cao_ref, cbo_ref = refs[-3:]
    del refs[-3:]
    carry = None
    if with_carry:
        carry = tuple(refs[-3:])
        del refs[-3:]
    rt_ref, reg_ref = refs[0], refs[1]
    gi = pl.program_id(0)
    base = gi * m
    s_seg = ng + 3 * nt
    a_all, b_all = _tile_grams_dense(
        sc_ref, g_ref, rt_ref, m=m, t=t, k=k, base=base, ng=ng, nt=nt,
        precision=precision,
    )
    _walk_tiles(lambda i: sc_ref[s_seg + i], a_all, b_all, gi=gi, base=base,
                m=m, a_ref=a_scr, b_ref=b_scr, carry=carry)

    @pl.when(gi == pl.num_programs(0) - 1)
    def _epilogue():
        _solve_epilogue(
            a_scr, b_scr, reg_ref, sc_ref[ng + 4 * nt], x_ref, cao_ref,
            cbo_ref, lu_scr, k=k, s_pad=s_pad, reg_mode=reg_mode, lam=lam,
            algo=algo,
        )


def _fused_call_pieces(k, s_pad, num_segments, reg, reg_mode, carry, vma,
                       algo):
    """The plumbing every fused wrapper shares: reg/carry operands and
    specs, lane-padded output shapes, scratch shapes, and the VMEM budget.
    Returns (reg_op, reg_spec, carry_ops, carry_specs, out_shape,
    scratch_shapes, extra_vmem_bytes)."""
    mk = (lambda s, d: jax.ShapeDtypeStruct(s, d, vma=vma)) if vma else (
        lambda s, d: jax.ShapeDtypeStruct(s, d)
    )
    if reg_mode == "diag":
        reg_op = jnp.pad(
            reg.astype(jnp.float32), (0, s_pad - num_segments)
        ).reshape(1, s_pad)
        reg_spec = pl.BlockSpec((1, s_pad), lambda i, sc: (0, 0))
    else:
        reg_op = reg.astype(jnp.float32)
        reg_spec = pl.BlockSpec((k, k), lambda i, sc: (0, 0))
    carry_specs = [] if carry is None else [
        pl.BlockSpec((k, k), lambda i, sc: (0, 0)),
        pl.BlockSpec((1, k), lambda i, sc: (0, 0)),
        pl.BlockSpec((1, 1), lambda i, sc: (0, 0)),
    ]
    carry_ops = [] if carry is None else [
        carry[0].astype(jnp.float32),
        carry[1].reshape(1, k).astype(jnp.float32),
        carry[2].reshape(1, 1).astype(jnp.float32),
    ]
    out_shape = (
        mk((s_pad, k), jnp.float32),      # x
        mk((k, k), jnp.float32),          # carry A row (raw, pre-ridge)
        mk((1, k), jnp.float32),          # carry b row
    )
    out_specs = [
        pl.BlockSpec((s_pad, k), lambda i, sc: (0, 0)),
        pl.BlockSpec((k, k), lambda i, sc: (0, 0)),
        pl.BlockSpec((1, k), lambda i, sc: (0, 0)),
    ]
    scratch = [
        pltpu.VMEM((s_pad, k, k), jnp.float32),
        pltpu.VMEM((s_pad, 1, k), jnp.float32),
    ]
    if algo == "lu":
        scratch += [
            pltpu.VMEM((k, k, _SOLVE_LANES), jnp.float32),
            pltpu.VMEM((k, _SOLVE_LANES), jnp.float32),
            pltpu.VMEM((k, _SOLVE_LANES), jnp.float32),
        ]
    # Scratch is single-buffered (unlike the split kernels' resident
    # output, which Mosaic double-buffers even at a constant index) — the
    # fused path actually NEEDS LESS VMEM than split despite solving in
    # place.  Budget: scratch + elimination temporaries + headroom
    # (same formula the support gate applies — see _fused_scratch_bytes).
    scratch_bytes = _fused_scratch_bytes(s_pad, k)
    return (reg_op, reg_spec, carry_ops, carry_specs, out_shape, out_specs,
            scratch, scratch_bytes)


def gram_solve_tiles_pallas(
    g: jax.Array,  # [C, k] gathered neighbor factors (bf16 or f32)
    rt: jax.Array,  # [C] f32 b-side coefficients (0 at padding)
    seg: jax.Array,  # [NT] int32 owner of each tile (sorted by the layout)
    reg: jax.Array,  # diag: [num_segments] counts; matrix: [k, k] YᵀY+λI
    lseg: jax.Array,  # int32 scalar: the carry row to extract
    *,
    num_segments: int,
    tile_rows: int,
    group_tiles: int = 64,
    reg_mode: str = "diag",
    lam: float = 0.0,
    interpret: bool | None = None,
    carry: tuple[jax.Array, jax.Array, jax.Array] | None = None,
    algo: str | None = None,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Fused Gram + ridge + solve over entity tiles: the chunk's normal
    equations never leave the kernel's VMEM residency.

    Same contract as ``gram_tiles_pallas`` for the Gram accumulation
    (sorted contiguous owners, unwritten absent rows, the chunk-boundary
    ``carry`` fold), but instead of writing (A [S, k, k], b [S, k]) to HBM
    for a separate batched solve, the last grid step applies the
    regularizer and runs the lane-vectorized elimination on the
    VMEM-resident batch (``_solve_epilogue``), returning

        (x [num_segments, k], carry_a [k, k], carry_b [k])

    where (carry_a, carry_b) is the RAW (pre-ridge) row at ``lseg`` — the
    partial sums of the entity straddling the next chunk boundary.  This
    removes the split path's per-chunk [Ec, k, k] A-batch write + readback
    (~2·Ec·k² f32 of pure HBM traffic per chunk) that PR 1's prefetch
    pipelines left as the exposed hot path.

    Off-TPU (interpret) and on old-jax installs this routes to the
    XLA-emulation twin (``cfk_tpu.compat.emulate_fused_gram_solve``): the
    same segment-sum Gram the split path emulates plus the interpret-mode
    fused reg+solve kernel — bit-identical to running split with
    ``gram_backend="xla"`` + the pallas solver, which is what the fused/
    split regression tests pin.  Rank cap and VMEM sizing are gated by
    ``fused_gram_solve_supported``; callers fall back to split past it.
    """
    from cfk_tpu.ops.pallas.solve_kernel import resolve_reg_solve_algo

    algo = resolve_reg_solve_algo(algo)
    if algo == "lu" and pltpu is None:  # pragma: no cover - non-TPU build
        algo = "gj"
    return _gram_solve_tiles_pallas(
        g, rt, seg, reg, lseg, num_segments=num_segments,
        tile_rows=tile_rows, group_tiles=group_tiles, reg_mode=reg_mode,
        lam=lam, interpret=interpret, carry=carry, algo=algo,
    )


@functools.partial(
    jax.jit,
    static_argnames=("num_segments", "tile_rows", "group_tiles", "reg_mode",
                     "lam", "interpret", "algo"),
)
def _gram_solve_tiles_pallas(
    g, rt, seg, reg, lseg, *, num_segments, tile_rows, group_tiles,
    reg_mode, lam, interpret, carry, algo,
):
    c, k = g.shape
    t = tile_rows
    if c % t != 0:
        raise ValueError(f"entry count {c} not divisible by tile_rows {t}")
    nt = c // t
    if seg.shape != (nt,):
        raise ValueError(f"seg shape {seg.shape} != ({nt},)")
    _check_reg_shape(reg, reg_mode, num_segments, k)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if interpret or not has_vma_system():
        # The XLA-emulation twin (compat.py): CPU CI and old-jax installs
        # exercise the same fused code shape without Mosaic.
        from cfk_tpu.compat import emulate_fused_gram_solve

        a, b = _emulate_gram_tiles(
            g, rt, seg, num_segments=num_segments, tile_rows=t, carry=carry,
        )
        return emulate_fused_gram_solve(
            a, b, reg, reg_mode=reg_mode, lam=lam, lseg=lseg,
        )
    if pltpu is None:  # pragma: no cover - non-TPU pallas build
        raise RuntimeError("pallas TPU extensions unavailable")
    m = group_tiles
    while nt % m != 0:  # grid must tile exactly; m=1 always divides
        m //= 2
    s_pad = -(-num_segments // _SOLVE_LANES) * _SOLVE_LANES
    vma = typeof_vma(g)
    (reg_op, reg_spec, carry_ops, carry_specs, out_shape, out_specs,
     scratch, scratch_bytes) = _fused_call_pieces(
        k, s_pad, num_segments, reg, reg_mode, carry, vma, algo)
    fac_spec = pl.BlockSpec((m * t, k), lambda i, sc: (i, 0))
    seg_plus = jnp.concatenate(
        [seg.astype(jnp.int32), jnp.asarray(lseg, jnp.int32).reshape(1)]
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nt // m,),
        in_specs=[fac_spec,
                  pl.BlockSpec((1, m * t), lambda i, sc: (0, i)),
                  reg_spec] + carry_specs,
        out_specs=out_specs,
        scratch_shapes=scratch,
    )
    precision = (
        jax.lax.Precision.HIGHEST if g.dtype == jnp.float32 else None
    )
    in_bytes = 2 * (m * t * (k + 1) * 4)
    params = getattr(pltpu, "CompilerParams", None) or getattr(
        pltpu, "TPUCompilerParams"
    )
    kwargs = {"compiler_params": params(
        vmem_limit_bytes=min(scratch_bytes + 4 * in_bytes + (12 << 20),
                             124 << 20)
    )}
    x, cao, cbo = pl.pallas_call(
        functools.partial(
            _gram_solve_groups_kernel, m=m, t=t, k=k, s_pad=s_pad,
            nt_total=nt, precision=precision,
            with_carry=carry is not None, reg_mode=reg_mode, lam=lam,
            algo=algo,
        ),
        grid_spec=grid_spec,
        out_shape=out_shape,
        interpret=interpret,
        **kwargs,
    )(seg_plus, g, rt.reshape(1, c), reg_op, *carry_ops)
    return x[:num_segments], cao, cbo[0]


def gram_solve_tiles_dense_pallas(
    g: jax.Array,  # [C, k] densely packed gathered factors (bf16/f32)
    rt: jax.Array,  # [NT·T] f32 TILE-ALIGNED b coefficients (0 off-window)
    meta: jax.Array,  # [NG + 4·NT] int32: g_blk ‖ lb ‖ lo ‖ hi ‖ seg
    reg: jax.Array,  # diag: [num_segments] counts; matrix: [k, k]
    lseg: jax.Array,  # int32 scalar: the carry row to extract
    *,
    num_segments: int,
    tile_rows: int,
    num_tiles: int,
    num_groups: int,
    block_rows: int,
    reg_mode: str = "diag",
    lam: float = 0.0,
    interpret: bool | None = None,
    carry: tuple[jax.Array, jax.Array, jax.Array] | None = None,
    algo: str | None = None,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Fused Gram+solve for the dense-stream layout — the unpadded-gather
    variant of ``gram_solve_tiles_pallas`` (same epilogue, dense windowed
    walk; see ``gram_tiles_dense_pallas`` for the stream/metadata
    contract)."""
    from cfk_tpu.ops.pallas.solve_kernel import resolve_reg_solve_algo

    algo = resolve_reg_solve_algo(algo)
    if algo == "lu" and pltpu is None:  # pragma: no cover - non-TPU build
        algo = "gj"
    return _gram_solve_tiles_dense_pallas(
        g, rt, meta, reg, lseg, num_segments=num_segments,
        tile_rows=tile_rows, num_tiles=num_tiles, num_groups=num_groups,
        block_rows=block_rows, reg_mode=reg_mode, lam=lam,
        interpret=interpret, carry=carry, algo=algo,
    )


@functools.partial(
    jax.jit,
    static_argnames=("num_segments", "tile_rows", "num_tiles", "num_groups",
                     "block_rows", "reg_mode", "lam", "interpret", "algo"),
)
def _gram_solve_tiles_dense_pallas(
    g, rt, meta, reg, lseg, *, num_segments, tile_rows, num_tiles,
    num_groups, block_rows, reg_mode, lam, interpret, carry, algo,
):
    c, k = g.shape
    t = tile_rows
    nt, ng, bg = num_tiles, num_groups, block_rows
    if nt % ng != 0:
        raise ValueError(f"num_tiles {nt} not divisible by num_groups {ng}")
    m = nt // ng
    if rt.shape != (nt * t,):
        raise ValueError(f"rt shape {rt.shape} != ({nt * t},)")
    if meta.shape != (ng + 4 * nt,):
        raise ValueError(f"meta shape {meta.shape} != ({ng + 4 * nt},)")
    if c % bg != 0 or bg < t:
        raise ValueError(f"stream length {c} not a multiple of block_rows "
                         f"{bg} >= tile_rows {t}")
    _check_reg_shape(reg, reg_mode, num_segments, k)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if interpret or not has_vma_system():
        from cfk_tpu.compat import emulate_fused_gram_solve

        a, b = _emulate_gram_dense(
            g, rt, meta, num_segments=num_segments, tile_rows=t,
            num_tiles=nt, num_groups=ng, block_rows=bg, carry=carry,
        )
        return emulate_fused_gram_solve(
            a, b, reg, reg_mode=reg_mode, lam=lam, lseg=lseg,
        )
    if pltpu is None:  # pragma: no cover - non-TPU pallas build
        raise RuntimeError("pallas TPU extensions unavailable")
    s_pad = -(-num_segments // _SOLVE_LANES) * _SOLVE_LANES
    vma = typeof_vma(g)
    (reg_op, reg_spec, carry_ops, carry_specs, out_shape, out_specs,
     scratch, scratch_bytes) = _fused_call_pieces(
        k, s_pad, num_segments, reg, reg_mode, carry, vma, algo)
    meta_plus = jnp.concatenate(
        [meta.astype(jnp.int32), jnp.asarray(lseg, jnp.int32).reshape(1)]
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(ng,),
        in_specs=[
            pl.BlockSpec((bg, k), lambda i, sc: (sc[i], 0)),
            pl.BlockSpec((1, m * t), lambda i, sc: (0, i)),
            reg_spec,
        ] + carry_specs,
        out_specs=out_specs,
        scratch_shapes=scratch,
    )
    precision = (
        jax.lax.Precision.HIGHEST if g.dtype == jnp.float32 else None
    )
    in_bytes = 2 * (bg * k * 4 + m * t * 4)
    params = getattr(pltpu, "CompilerParams", None) or getattr(
        pltpu, "TPUCompilerParams"
    )
    kwargs = {"compiler_params": params(
        vmem_limit_bytes=min(scratch_bytes + in_bytes + (10 << 20),
                             124 << 20)
    )}
    x, cao, cbo = pl.pallas_call(
        functools.partial(
            _gram_solve_dense_kernel, m=m, t=t, k=k, ng=ng, nt=nt,
            s_pad=s_pad, precision=precision, with_carry=carry is not None,
            reg_mode=reg_mode, lam=lam, algo=algo,
        ),
        grid_spec=grid_spec,
        out_shape=out_shape,
        interpret=interpret,
        **kwargs,
    )(meta_plus, g, rt.reshape(1, nt * t), reg_op, *carry_ops)
    return x[:num_segments], cao, cbo[0]


# --------------------------------------------------------------------------
# In-kernel neighbor gather (gather-fused kernel variants)
#
# Every half-iteration above consumes a PRE-GATHERED [C, k] stream: XLA
# materializes fz[nb] in HBM and the kernel reads it straight back — the
# same write+readback shape the fused epilogue removed for the [Ec, k, k]
# A-batches, and the dominant measured roofline gap (BENCH_r05
# vs_gather_roofline 1.88–9.94×).  The ``*_gather_pallas`` variants retire
# that stream: the RAW fixed factor table stays in HBM/ANY memory, each
# tile's neighbor indices ride the scalar prefetch, and the kernel DMAs
# the indexed rows straight into a double-buffered VMEM block (group g+1's
# row DMAs are in flight while group g's Gram walk runs).  The zero-
# appended padding row is realized IN-REGISTER: indices are clamped to the
# last real row for the DMA and the per-entry premultiply ``wt`` (the 0/1
# validity mask for unit weights, √aw·mask for iALS) zeroes padding rows —
# the [F+1, k] zero-row copy of the table is never built.  Dense-stream
# padding needs no mask at all: pad slots sit outside every [lo, hi)
# window, so the existing one-operand window mask annihilates them.
# Failure-mode caveat (same class the walk's arithmetic select accepts —
# see _walk_tiles): clamped-row × 0.0 is exactly 0 only for FINITE table
# rows; a diverged table (Inf/NaN rows) turns padding slots into NaN via
# 0·inf on the Mosaic route, where the XLA zero-row gather stayed 0.
# Acceptable: non-finite factors are already a broken run — the health
# sentinel (cfk_tpu.resilience) trips on the half-step's OUTPUT either
# way — this only widens the blast radius within an already-lost
# iteration, and only on real TPU (the emulation twin gathers true
# zeros).
#
# Index convention (all gather variants): ``nb == table.shape[0]`` is the
# virtual zero row; the clamp + wt/window masking makes its contribution
# exactly 0.  Off-TPU and on old-jax installs the wrappers route to
# ``compat.emulate_in_kernel_gather`` + the existing emulation twins,
# which run the numerically identical append-zero-row + gather + multiply
# the XLA-gather path runs — fused-gather vs XLA-gather factors are
# BIT-IDENTICAL on that route (tests/test_in_kernel_gather.py).  The
# Mosaic row-DMA path itself needs on-TPU validation (ROADMAP).
# --------------------------------------------------------------------------

# Scalar-prefetch budget for the gather variants: the whole index chunk
# (plus seg/meta words) lives in SMEM.  512 KiB admits the production 64k-
# entry chunks (64k indices + ~20k meta words ≈ 336 KiB); past it the
# resolver keeps the XLA-gather path.  Needs on-TPU validation against the
# real SMEM ceiling (ROADMAP) — a too-large cap fails at Mosaic compile
# time, never silently.
_GATHER_SMEM_BYTES_CAP = 512 << 10


def in_kernel_gather_supported(entries: int, meta_words: int, tile_rows: int,
                               block_rows: int | None = None) -> bool:
    """Can the gather-fused kernels handle this chunk shape?

    Gates: the scalar prefetch (indices + seg/meta + lseg) must fit the
    SMEM budget, and tile/block row counts must be 16-aligned — the
    double-buffered gather scratch is addressed at ``slot·rows + i·t``
    dynamic offsets, which Mosaic's sublane slicing only lowers at
    (16, 128)-tile alignment.  A refused shape keeps the XLA-gather path
    (same math, the materialized stream) — never a compile failure.
    """
    if tile_rows % 16:
        return False
    if block_rows is not None and block_rows % 16:
        return False
    return (entries + meta_words + 1) * 4 <= _GATHER_SMEM_BYTES_CAP


def _any_memory_space():
    """The compiler-placed (HBM-resident for big operands) memory space
    across pallas versions — where the gather variants keep the full
    fixed table."""
    if pltpu is not None:
        ms = getattr(pltpu, "ANY", None)
        if ms is None:
            tms = getattr(pltpu, "TPUMemorySpace", None)
            ms = getattr(tms, "ANY", None) if tms is not None else None
        if ms is not None:
            return ms
    return getattr(pl, "ANY", None)  # pragma: no cover - exotic builds


def _gather_dma(table_ref, g_buf, sem, sc_ref, nb_base, row0, rows, slot,
                f_rows):
    """Descriptor factory for one group's per-row gather DMAs: scratch row
    ``slot·rows + r`` ← ``table[min(nb[row0 + r], F−1)]``.  Start and wait
    recreate identical descriptors (the pallas DMA idiom); all of a
    group's copies signal the slot's semaphore."""
    def copy(r):
        idx = sc_ref[nb_base + row0 + r]
        src = jnp.minimum(idx, f_rows - 1)
        return pltpu.make_async_copy(
            table_ref.at[pl.ds(src, 1)],
            g_buf.at[pl.ds(slot * rows + r, 1)],
            sem.at[slot],
        )

    return copy


def _gather_double_buffer(g_buf, sem, table_ref, sc_ref, *, nb_base, rows,
                          gi, ng, f_rows, group_row0):
    """The gather variants' double buffer: issue group gi+1's row DMAs
    (and group 0's at the prologue step) BEFORE waiting on group gi's, so
    the next block's HBM row fetches run under this block's Gram walk —
    the in-kernel analog of ``ops.pipeline.prefetch_scan``.  Slot parity
    alternates; the slot being filled for gi+1 was last read at step
    gi−1, which the sequential grid has already retired.  Returns the
    VMEM row offset of group gi's ready block.  ``group_row0`` maps a
    group index to its first index-stream position (``g·rows`` for the
    tile stream, ``meta[g]·BG`` for the dense stream — dense groups may
    revisit a block, in which case its rows are simply re-fetched)."""
    def start(group):
        slot = lax.rem(group, 2)
        copy = _gather_dma(table_ref, g_buf, sem, sc_ref, nb_base,
                           group_row0(group), rows, slot, f_rows)

        def body(r, c):
            copy(r).start()
            return c

        lax.fori_loop(0, rows, body, 0)

    @pl.when(gi == 0)
    def _prologue():
        start(gi)

    @pl.when(gi + 1 < ng)
    def _prefetch():
        start(gi + 1)

    slot = lax.rem(gi, 2)
    copy = _gather_dma(table_ref, g_buf, sem, sc_ref, nb_base,
                       group_row0(gi), rows, slot, f_rows)

    def wait_body(r, c):
        copy(r).wait()
        return c

    lax.fori_loop(0, rows, wait_body, 0)
    return slot * rows


def _premultiply_rows(g_buf, off, rows, wt_ref, out_buf=None):
    """In-register per-entry premultiply on the gathered block: one
    (1, rows) → (rows, 1) relayout per grid step (VMEM-local — the XLA
    path's [C, 1] weight column relayout through HBM is what this
    replaces), then a fused broadcast multiply.  The weight is cast to
    the factor dtype first, matching the XLA path's ``wt.astype(ct)``
    bit-for-bit.  ``wt`` is the 0/1 validity mask for unit-weight callers
    — which is what zeroes the clamped padding rows in-register.

    ``out_buf`` (int8 quantized tables — ``ops.quant``) redirects the
    product into a separate f32 compute scratch instead of multiplying in
    place: the DMA'd int8 rows cannot hold the dequantized product, and
    the per-row dequant scale is already folded into ``wt`` upstream
    (``quant.fold_scale`` — the canonical order), so THIS multiply is
    also the dequantize.  One pass either way."""
    base = pl.ds(pl.multiple_of(off, 16), rows)
    blk = g_buf[base, :]
    if out_buf is None:
        w = jnp.transpose(wt_ref[...], (1, 0)).astype(blk.dtype)
        g_buf[base, :] = blk * w
    else:
        w = jnp.transpose(wt_ref[...], (1, 0)).astype(out_buf.dtype)
        out_buf[base, :] = blk.astype(out_buf.dtype) * w


def _pop_gather_scratch(refs, int8_table):
    """Pop the gather scratch tail (``… g_buf, sem[, dq_buf]``) off a
    kernel's ref list: returns (g_buf, sem, dq_buf-or-None).  ``dq_buf``
    (int8 tables only) is the f32 dequant compute buffer appended LAST in
    the scratch list."""
    dq_buf = None
    if int8_table:
        dq_buf = refs[-1]
        del refs[-1]
    g_buf, sem = refs[-2], refs[-1]
    del refs[-2:]
    return g_buf, sem, dq_buf


def _gram_gather_groups_kernel(sc_ref, table_ref, *refs, m, t, k, nt, f_rows,
                               precision, with_carry, int8_table=False):
    """Gather-fused twin of ``_gram_groups_kernel``: the [m·t, k] factor
    block is row-DMA'd from the ANY-memory table instead of streamed as a
    pipelined input.  Scalar layout: seg [NT] ‖ nb [NT·T]."""
    refs = list(refs)
    g_buf, sem, dq_buf = _pop_gather_scratch(refs, int8_table)
    a_ref, b_ref = refs[-2:]
    del refs[-2:]
    carry = None
    if with_carry:
        carry = tuple(refs[-3:])
        del refs[-3:]
    rt_ref, wt_ref = refs[0], refs[1]
    gi = pl.program_id(0)
    base = gi * m
    rows = m * t
    off = _gather_double_buffer(
        g_buf, sem, table_ref, sc_ref, nb_base=nt, rows=rows, gi=gi,
        ng=pl.num_programs(0), f_rows=f_rows,
        group_row0=lambda g: g * rows,
    )
    _premultiply_rows(g_buf, off, rows, wt_ref, out_buf=dq_buf)
    a_all, b_all = _tile_grams(dq_buf if int8_table else g_buf, rt_ref,
                               m=m, t=t, k=k,
                               precision=precision, row_off=off)
    _walk_tiles(lambda i: sc_ref[i], a_all, b_all, gi=gi, base=base, m=m,
                a_ref=a_ref, b_ref=b_ref, carry=carry)


def _gram_solve_gather_groups_kernel(sc_ref, table_ref, *refs, m, t, k, nt,
                                     s_pad, f_rows, precision, with_carry,
                                     reg_mode, lam, algo, int8_table=False):
    """Gather-fused twin of ``_gram_solve_groups_kernel`` (in-kernel
    gather + scratch-resident walk + last-step ridge+solve epilogue).
    Scalar layout: seg [NT] ‖ lseg ‖ nb [NT·T]."""
    refs = list(refs)
    g_buf, sem, dq_buf = _pop_gather_scratch(refs, int8_table)
    if algo == "lu":
        lu_scr = tuple(refs[-3:])
        del refs[-3:]
    else:
        lu_scr = None
    a_scr, b_scr = refs[-2:]
    del refs[-2:]
    x_ref, cao_ref, cbo_ref = refs[-3:]
    del refs[-3:]
    carry = None
    if with_carry:
        carry = tuple(refs[-3:])
        del refs[-3:]
    rt_ref, wt_ref, reg_ref = refs[0], refs[1], refs[2]
    gi = pl.program_id(0)
    base = gi * m
    rows = m * t
    off = _gather_double_buffer(
        g_buf, sem, table_ref, sc_ref, nb_base=nt + 1, rows=rows, gi=gi,
        ng=pl.num_programs(0), f_rows=f_rows,
        group_row0=lambda g: g * rows,
    )
    _premultiply_rows(g_buf, off, rows, wt_ref, out_buf=dq_buf)
    a_all, b_all = _tile_grams(dq_buf if int8_table else g_buf, rt_ref,
                               m=m, t=t, k=k,
                               precision=precision, row_off=off)
    _walk_tiles(lambda i: sc_ref[i], a_all, b_all, gi=gi, base=base, m=m,
                a_ref=a_scr, b_ref=b_scr, carry=carry)

    @pl.when(gi == pl.num_programs(0) - 1)
    def _epilogue():
        _solve_epilogue(
            a_scr, b_scr, reg_ref, sc_ref[nt], x_ref, cao_ref, cbo_ref,
            lu_scr, k=k, s_pad=s_pad, reg_mode=reg_mode, lam=lam, algo=algo,
        )


def _gram_gather_dense_kernel(sc_ref, table_ref, *refs, m, t, k, ng, nt, bg,
                              f_rows, precision, with_carry, weighted,
                              int8_table=False):
    """Gather-fused twin of ``_gram_dense_kernel``: the [BG, k] stream
    block is row-DMA'd by index instead of streamed.  Dense padding slots
    need no premultiply mask — they sit outside every [lo, hi) window, so
    the windowed walk's one-operand mask annihilates whatever the clamped
    DMA fetched.  Scalar layout: meta [NG+4·NT] ‖ nb [C]."""
    refs = list(refs)
    g_buf, sem, dq_buf = _pop_gather_scratch(refs, int8_table)
    a_ref, b_ref = refs[-2:]
    del refs[-2:]
    carry = None
    if with_carry:
        carry = tuple(refs[-3:])
        del refs[-3:]
    rt_ref = refs[0]
    wt_ref = refs[1] if weighted else None
    gi = pl.program_id(0)
    base = gi * m
    meta_words = ng + 4 * nt
    off = _gather_double_buffer(
        g_buf, sem, table_ref, sc_ref, nb_base=meta_words, rows=bg, gi=gi,
        ng=pl.num_programs(0), f_rows=f_rows,
        group_row0=lambda g: sc_ref[g] * bg,
    )
    if weighted:
        _premultiply_rows(g_buf, off, bg, wt_ref, out_buf=dq_buf)
    a_all, b_all = _tile_grams_dense(
        sc_ref, dq_buf if int8_table else g_buf, rt_ref, m=m, t=t, k=k,
        base=base, ng=ng, nt=nt,
        precision=precision, row_off=off,
    )
    _walk_tiles(lambda i: sc_ref[ng + 3 * nt + i], a_all, b_all, gi=gi,
                base=base, m=m, a_ref=a_ref, b_ref=b_ref, carry=carry)


def _gram_solve_gather_dense_kernel(sc_ref, table_ref, *refs, m, t, k, ng,
                                    nt, bg, s_pad, f_rows, precision,
                                    with_carry, weighted, reg_mode, lam,
                                    algo, int8_table=False):
    """Gather-fused twin of ``_gram_solve_dense_kernel``.  Scalar layout:
    meta [NG+4·NT] ‖ lseg ‖ nb [C]."""
    refs = list(refs)
    g_buf, sem, dq_buf = _pop_gather_scratch(refs, int8_table)
    if algo == "lu":
        lu_scr = tuple(refs[-3:])
        del refs[-3:]
    else:
        lu_scr = None
    a_scr, b_scr = refs[-2:]
    del refs[-2:]
    x_ref, cao_ref, cbo_ref = refs[-3:]
    del refs[-3:]
    carry = None
    if with_carry:
        carry = tuple(refs[-3:])
        del refs[-3:]
    rt_ref = refs[0]
    wt_ref = refs[1] if weighted else None
    reg_ref = refs[2] if weighted else refs[1]
    gi = pl.program_id(0)
    base = gi * m
    meta_words = ng + 4 * nt
    off = _gather_double_buffer(
        g_buf, sem, table_ref, sc_ref, nb_base=meta_words + 1, rows=bg,
        gi=gi, ng=pl.num_programs(0), f_rows=f_rows,
        group_row0=lambda g: sc_ref[g] * bg,
    )
    if weighted:
        _premultiply_rows(g_buf, off, bg, wt_ref, out_buf=dq_buf)
    a_all, b_all = _tile_grams_dense(
        sc_ref, dq_buf if int8_table else g_buf, rt_ref, m=m, t=t, k=k,
        base=base, ng=ng, nt=nt,
        precision=precision, row_off=off,
    )
    _walk_tiles(lambda i: sc_ref[ng + 3 * nt + i], a_all, b_all, gi=gi,
                base=base, m=m, a_ref=a_scr, b_ref=b_scr, carry=carry)

    @pl.when(gi == pl.num_programs(0) - 1)
    def _epilogue():
        _solve_epilogue(
            a_scr, b_scr, reg_ref, sc_ref[meta_words], x_ref, cao_ref,
            cbo_ref, lu_scr, k=k, s_pad=s_pad, reg_mode=reg_mode, lam=lam,
            algo=algo,
        )


def _int8_gather_pieces(table, rows, k, weighted=True):
    """int8-quantized-table extras for the gather wrappers (``ops.quant``):
    the f32 dequant compute scratch (appended LAST in the scratch list —
    the convention ``_pop_gather_scratch`` reverses) and its VMEM bytes.
    int8 rows REQUIRE a weight stream — the per-row dequant scale rides it
    (folded upstream by ``quant.fold_scale``, which is also what makes the
    single premultiply the dequantize) — so an unweighted int8 call is
    refused rather than silently accumulating raw quantized codes."""
    if table.dtype != jnp.int8:
        return False, [], 0
    if not weighted:
        raise ValueError(
            "int8 gather tables need a weight stream (quant.fold_scale "
            "folds the per-row dequant scale into wt); got wt=None"
        )
    return True, [pltpu.VMEM((2 * rows, k), jnp.float32)], 2 * rows * k * 4


def _gather_precision(table):
    """Einsum precision for the gather kernels' Gram walk: full-f32 MXU
    passes for f32 tables AND int8 tables (whose compute buffer is the f32
    dequant scratch); the bf16 stream keeps the fast default passes."""
    return (
        jax.lax.Precision.HIGHEST
        if table.dtype in (jnp.float32, jnp.int8) else None
    )


def _emulate_gather(table, nb, wt):
    """The wrappers' interpret/old-jax gather: the XLA twin of the DMA
    fetch + in-register premultiply (``compat.emulate_in_kernel_gather``),
    at the factor compute dtype the materialized-stream path uses (f32
    for int8 tables — the dequant scratch dtype)."""
    from cfk_tpu.compat import emulate_in_kernel_gather
    from cfk_tpu.ops.solve import _gram_compute_dtype

    ct, _ = _gram_compute_dtype(table)
    return emulate_in_kernel_gather(table, nb, wt, ct)


@functools.partial(
    jax.jit,
    static_argnames=("num_segments", "tile_rows", "group_tiles", "interpret"),
)
def gram_tiles_gather_pallas(
    table: jax.Array,  # [F, k] RAW fixed factor table (no zero row)
    nb: jax.Array,  # [C] int32 row indices; F = the virtual zero row
    wt: jax.Array,  # [C] f32 premultiply (0/1 mask, or √aw·mask for iALS)
    rt: jax.Array,  # [C] f32 b-side coefficients (0 at padding)
    seg: jax.Array,  # [NT] int32 owner of each tile (sorted by the layout)
    *,
    num_segments: int,
    tile_rows: int,
    group_tiles: int = 64,
    interpret: bool | None = None,
    carry: tuple[jax.Array, jax.Array, jax.Array] | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Gather-fused ``gram_tiles_pallas``: same (A, b) contract, but the
    [C, k] neighbor stream is never materialized — the kernel DMAs the
    indexed table rows into VMEM itself (see the section comment above).
    ``wt`` is REQUIRED: it is both the weighted (√aw) premultiply and the
    in-register realization of the zero-appended padding row (unit-weight
    callers pass their 0/1 validity mask, e.g. the tiled layout's
    ``weight`` channel)."""
    c = nb.shape[0]
    k = table.shape[-1]
    t = tile_rows
    if c % t != 0:
        raise ValueError(f"entry count {c} not divisible by tile_rows {t}")
    nt = c // t
    if seg.shape != (nt,):
        raise ValueError(f"seg shape {seg.shape} != ({nt},)")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if interpret or not has_vma_system():
        return _emulate_gram_tiles(
            _emulate_gather(table, nb, wt), rt, seg,
            num_segments=num_segments, tile_rows=t, carry=carry,
        )
    if pltpu is None:  # pragma: no cover - non-TPU pallas build
        raise RuntimeError("pallas TPU extensions unavailable")
    m = group_tiles
    while nt % m != 0:
        m //= 2
    rows = m * t
    f_rows = table.shape[0]
    int8_table, dq_scratch, dq_bytes = _int8_gather_pieces(table, rows, k)
    vma = typeof_vma(table)
    mk = (lambda s, d: jax.ShapeDtypeStruct(s, d, vma=vma)) if vma else (
        lambda s, d: jax.ShapeDtypeStruct(s, d)
    )
    out_shape = (
        mk((num_segments, k, k), jnp.float32),
        mk((num_segments, 1, k), jnp.float32),
    )
    carry_specs = [] if carry is None else [
        pl.BlockSpec((k, k), lambda i, sc: (0, 0)),
        pl.BlockSpec((1, k), lambda i, sc: (0, 0)),
        pl.BlockSpec((1, 1), lambda i, sc: (0, 0)),
    ]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nt // m,),
        in_specs=[
            pl.BlockSpec(memory_space=_any_memory_space()),  # table
            pl.BlockSpec((1, rows), lambda i, sc: (0, i)),   # rt
            pl.BlockSpec((1, rows), lambda i, sc: (0, i)),   # wt
        ] + carry_specs,
        out_specs=[
            pl.BlockSpec((num_segments, k, k), lambda i, sc: (0, 0, 0)),
            pl.BlockSpec((num_segments, 1, k), lambda i, sc: (0, 0, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((2 * rows, k), table.dtype),
            pltpu.SemaphoreType.DMA((2,)),
        ] + dq_scratch,
    )
    precision = _gather_precision(table)
    out_bytes = num_segments * k * (k + 1) * 4
    g_bytes = 2 * rows * k * table.dtype.itemsize + dq_bytes
    params = getattr(pltpu, "CompilerParams", None) or getattr(
        pltpu, "TPUCompilerParams"
    )
    kwargs = {"compiler_params": params(
        vmem_limit_bytes=min(2 * out_bytes + g_bytes + 4 * rows * 8
                             + (12 << 20), 124 << 20)
    )}
    carry_ops = [] if carry is None else [
        carry[0].astype(jnp.float32),
        carry[1].reshape(1, k).astype(jnp.float32),
        carry[2].reshape(1, 1).astype(jnp.float32),
    ]
    scalar = jnp.concatenate([seg.astype(jnp.int32), nb.astype(jnp.int32)])
    a, b = pl.pallas_call(
        functools.partial(
            _gram_gather_groups_kernel, m=m, t=t, k=k, nt=nt, f_rows=f_rows,
            precision=precision, with_carry=carry is not None,
            int8_table=int8_table,
        ),
        grid_spec=grid_spec,
        out_shape=out_shape,
        interpret=interpret,
        **kwargs,
    )(scalar, table, rt.reshape(1, c).astype(jnp.float32),
      wt.reshape(1, c).astype(jnp.float32), *carry_ops)
    return a, b[:, 0, :]


def gram_solve_tiles_gather_pallas(
    table: jax.Array,  # [F, k] RAW fixed factor table (no zero row)
    nb: jax.Array,  # [C] int32 row indices; F = the virtual zero row
    wt: jax.Array,  # [C] f32 premultiply (0/1 mask, or √aw·mask for iALS)
    rt: jax.Array,  # [C] f32
    seg: jax.Array,  # [NT] int32
    reg: jax.Array,  # diag: [num_segments] counts; matrix: [k, k] YᵀY+λI
    lseg: jax.Array,  # int32 scalar: the carry row to extract
    *,
    num_segments: int,
    tile_rows: int,
    group_tiles: int = 64,
    reg_mode: str = "diag",
    lam: float = 0.0,
    interpret: bool | None = None,
    carry: tuple[jax.Array, jax.Array, jax.Array] | None = None,
    algo: str | None = None,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Gather-fused ``gram_solve_tiles_pallas``: in-kernel neighbor gather
    AND the in-VMEM ridge+solve epilogue — per chunk, neither the [C, k]
    gathered stream nor the [Ec, k, k] A-batch ever touches HBM."""
    from cfk_tpu.ops.pallas.solve_kernel import resolve_reg_solve_algo

    algo = resolve_reg_solve_algo(algo)
    if algo == "lu" and pltpu is None:  # pragma: no cover - non-TPU build
        algo = "gj"
    return _gram_solve_tiles_gather_pallas(
        table, nb, wt, rt, seg, reg, lseg, num_segments=num_segments,
        tile_rows=tile_rows, group_tiles=group_tiles, reg_mode=reg_mode,
        lam=lam, interpret=interpret, carry=carry, algo=algo,
    )


@functools.partial(
    jax.jit,
    static_argnames=("num_segments", "tile_rows", "group_tiles", "reg_mode",
                     "lam", "interpret", "algo"),
)
def _gram_solve_tiles_gather_pallas(
    table, nb, wt, rt, seg, reg, lseg, *, num_segments, tile_rows,
    group_tiles, reg_mode, lam, interpret, carry, algo,
):
    c = nb.shape[0]
    k = table.shape[-1]
    t = tile_rows
    if c % t != 0:
        raise ValueError(f"entry count {c} not divisible by tile_rows {t}")
    nt = c // t
    if seg.shape != (nt,):
        raise ValueError(f"seg shape {seg.shape} != ({nt},)")
    _check_reg_shape(reg, reg_mode, num_segments, k)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if interpret or not has_vma_system():
        from cfk_tpu.compat import emulate_fused_gram_solve

        a, b = _emulate_gram_tiles(
            _emulate_gather(table, nb, wt), rt, seg,
            num_segments=num_segments, tile_rows=t, carry=carry,
        )
        return emulate_fused_gram_solve(
            a, b, reg, reg_mode=reg_mode, lam=lam, lseg=lseg,
        )
    if pltpu is None:  # pragma: no cover - non-TPU pallas build
        raise RuntimeError("pallas TPU extensions unavailable")
    m = group_tiles
    while nt % m != 0:
        m //= 2
    rows = m * t
    f_rows = table.shape[0]
    int8_table, dq_scratch, dq_bytes = _int8_gather_pieces(table, rows, k)
    s_pad = -(-num_segments // _SOLVE_LANES) * _SOLVE_LANES
    vma = typeof_vma(table)
    (reg_op, reg_spec, carry_ops, carry_specs, out_shape, out_specs,
     scratch, scratch_bytes) = _fused_call_pieces(
        k, s_pad, num_segments, reg, reg_mode, carry, vma, algo)
    scratch = scratch + [
        pltpu.VMEM((2 * rows, k), table.dtype),
        pltpu.SemaphoreType.DMA((2,)),
    ] + dq_scratch
    scalar = jnp.concatenate([
        seg.astype(jnp.int32),
        jnp.asarray(lseg, jnp.int32).reshape(1),
        nb.astype(jnp.int32),
    ])
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nt // m,),
        in_specs=[
            pl.BlockSpec(memory_space=_any_memory_space()),  # table
            pl.BlockSpec((1, rows), lambda i, sc: (0, i)),   # rt
            pl.BlockSpec((1, rows), lambda i, sc: (0, i)),   # wt
            reg_spec,
        ] + carry_specs,
        out_specs=out_specs,
        scratch_shapes=scratch,
    )
    precision = _gather_precision(table)
    g_bytes = 2 * rows * k * table.dtype.itemsize + dq_bytes
    params = getattr(pltpu, "CompilerParams", None) or getattr(
        pltpu, "TPUCompilerParams"
    )
    kwargs = {"compiler_params": params(
        vmem_limit_bytes=min(scratch_bytes + g_bytes + 4 * rows * 8
                             + (12 << 20), 124 << 20)
    )}
    x, cao, cbo = pl.pallas_call(
        functools.partial(
            _gram_solve_gather_groups_kernel, m=m, t=t, k=k, nt=nt,
            s_pad=s_pad, f_rows=f_rows, precision=precision,
            with_carry=carry is not None, reg_mode=reg_mode, lam=lam,
            algo=algo, int8_table=int8_table,
        ),
        grid_spec=grid_spec,
        out_shape=out_shape,
        interpret=interpret,
        **kwargs,
    )(scalar, table, rt.reshape(1, c).astype(jnp.float32),
      wt.reshape(1, c).astype(jnp.float32), reg_op, *carry_ops)
    return x[:num_segments], cao, cbo[0]


@functools.partial(
    jax.jit,
    static_argnames=("num_segments", "tile_rows", "num_tiles", "num_groups",
                     "block_rows", "interpret"),
)
def gram_tiles_dense_gather_pallas(
    table: jax.Array,  # [F, k] RAW fixed factor table (no zero row)
    nb: jax.Array,  # [C] int32 dense-stream row indices (pad8 → F)
    wt: jax.Array | None,  # [C] f32 √aw stream (iALS) or None (unit)
    rt: jax.Array,  # [NT·T] f32 TILE-ALIGNED b coefficients
    meta: jax.Array,  # [NG + 4·NT] int32: g_blk ‖ lb ‖ lo ‖ hi ‖ seg
    *,
    num_segments: int,
    tile_rows: int,
    num_tiles: int,
    num_groups: int,
    block_rows: int,
    interpret: bool | None = None,
    carry: tuple[jax.Array, jax.Array, jax.Array] | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Gather-fused ``gram_tiles_dense_pallas``: the dense [C, k] stream
    is never materialized — each grid step row-DMAs its [BG, k] block by
    index.  Unit-weight callers pass ``wt=None``: dense padding slots sit
    outside every window, so the walk's one-operand mask annihilates the
    clamped rows without a premultiply."""
    c = nb.shape[0]
    k = table.shape[-1]
    t = tile_rows
    nt, ng, bg = num_tiles, num_groups, block_rows
    if nt % ng != 0:
        raise ValueError(f"num_tiles {nt} not divisible by num_groups {ng}")
    m = nt // ng
    if rt.shape != (nt * t,):
        raise ValueError(f"rt shape {rt.shape} != ({nt * t},)")
    if meta.shape != (ng + 4 * nt,):
        raise ValueError(f"meta shape {meta.shape} != ({ng + 4 * nt},)")
    if c % bg != 0 or bg < t:
        raise ValueError(f"stream length {c} not a multiple of block_rows "
                         f"{bg} >= tile_rows {t}")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if interpret or not has_vma_system():
        return _emulate_gram_dense(
            _emulate_gather(table, nb, wt), rt, meta,
            num_segments=num_segments, tile_rows=t, num_tiles=nt,
            num_groups=ng, block_rows=bg, carry=carry,
        )
    if pltpu is None:  # pragma: no cover - non-TPU pallas build
        raise RuntimeError("pallas TPU extensions unavailable")
    f_rows = table.shape[0]
    weighted = wt is not None
    int8_table, dq_scratch, dq_bytes = _int8_gather_pieces(
        table, bg, k, weighted=weighted)
    vma = typeof_vma(table)
    mk = (lambda s, d: jax.ShapeDtypeStruct(s, d, vma=vma)) if vma else (
        lambda s, d: jax.ShapeDtypeStruct(s, d)
    )
    out_shape = (
        mk((num_segments, k, k), jnp.float32),
        mk((num_segments, 1, k), jnp.float32),
    )
    carry_specs = [] if carry is None else [
        pl.BlockSpec((k, k), lambda i, sc: (0, 0)),
        pl.BlockSpec((1, k), lambda i, sc: (0, 0)),
        pl.BlockSpec((1, 1), lambda i, sc: (0, 0)),
    ]
    wt_specs = ([pl.BlockSpec((1, bg), lambda i, sc: (0, sc[i]))]
                if weighted else [])
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(ng,),
        in_specs=[
            pl.BlockSpec(memory_space=_any_memory_space()),  # table
            pl.BlockSpec((1, m * t), lambda i, sc: (0, i)),  # rt
        ] + wt_specs + carry_specs,
        out_specs=[
            pl.BlockSpec((num_segments, k, k), lambda i, sc: (0, 0, 0)),
            pl.BlockSpec((num_segments, 1, k), lambda i, sc: (0, 0, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((2 * bg, k), table.dtype),
            pltpu.SemaphoreType.DMA((2,)),
        ] + dq_scratch,
    )
    precision = _gather_precision(table)
    out_bytes = num_segments * k * (k + 1) * 4
    g_bytes = 2 * bg * k * table.dtype.itemsize + dq_bytes
    params = getattr(pltpu, "CompilerParams", None) or getattr(
        pltpu, "TPUCompilerParams"
    )
    kwargs = {"compiler_params": params(
        vmem_limit_bytes=min(2 * out_bytes + g_bytes + 4 * bg * 8
                             + (10 << 20), 124 << 20)
    )}
    carry_ops = [] if carry is None else [
        carry[0].astype(jnp.float32),
        carry[1].reshape(1, k).astype(jnp.float32),
        carry[2].reshape(1, 1).astype(jnp.float32),
    ]
    wt_ops = ([wt.reshape(1, c).astype(jnp.float32)] if weighted else [])
    scalar = jnp.concatenate([meta.astype(jnp.int32), nb.astype(jnp.int32)])
    a, b = pl.pallas_call(
        functools.partial(
            _gram_gather_dense_kernel, m=m, t=t, k=k, ng=ng, nt=nt, bg=bg,
            f_rows=f_rows, precision=precision,
            with_carry=carry is not None, weighted=weighted,
            int8_table=int8_table,
        ),
        grid_spec=grid_spec,
        out_shape=out_shape,
        interpret=interpret,
        **kwargs,
    )(scalar, table, rt.reshape(1, nt * t), *wt_ops, *carry_ops)
    return a, b[:, 0, :]


def gram_solve_tiles_dense_gather_pallas(
    table: jax.Array,  # [F, k] RAW fixed factor table (no zero row)
    nb: jax.Array,  # [C] int32 dense-stream row indices (pad8 → F)
    wt: jax.Array | None,  # [C] f32 √aw stream (iALS) or None (unit)
    rt: jax.Array,  # [NT·T] f32 TILE-ALIGNED b coefficients
    meta: jax.Array,  # [NG + 4·NT] int32
    reg: jax.Array,  # diag: [num_segments] counts; matrix: [k, k]
    lseg: jax.Array,  # int32 scalar
    *,
    num_segments: int,
    tile_rows: int,
    num_tiles: int,
    num_groups: int,
    block_rows: int,
    reg_mode: str = "diag",
    lam: float = 0.0,
    interpret: bool | None = None,
    carry: tuple[jax.Array, jax.Array, jax.Array] | None = None,
    algo: str | None = None,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Gather-fused ``gram_solve_tiles_dense_pallas``: in-kernel dense
    gather AND the in-VMEM ridge+solve epilogue."""
    from cfk_tpu.ops.pallas.solve_kernel import resolve_reg_solve_algo

    algo = resolve_reg_solve_algo(algo)
    if algo == "lu" and pltpu is None:  # pragma: no cover - non-TPU build
        algo = "gj"
    return _gram_solve_tiles_dense_gather_pallas(
        table, nb, wt, rt, meta, reg, lseg, num_segments=num_segments,
        tile_rows=tile_rows, num_tiles=num_tiles, num_groups=num_groups,
        block_rows=block_rows, reg_mode=reg_mode, lam=lam,
        interpret=interpret, carry=carry, algo=algo,
    )


@functools.partial(
    jax.jit,
    static_argnames=("num_segments", "tile_rows", "num_tiles", "num_groups",
                     "block_rows", "reg_mode", "lam", "interpret", "algo"),
)
def _gram_solve_tiles_dense_gather_pallas(
    table, nb, wt, rt, meta, reg, lseg, *, num_segments, tile_rows,
    num_tiles, num_groups, block_rows, reg_mode, lam, interpret, carry,
    algo,
):
    c = nb.shape[0]
    k = table.shape[-1]
    t = tile_rows
    nt, ng, bg = num_tiles, num_groups, block_rows
    if nt % ng != 0:
        raise ValueError(f"num_tiles {nt} not divisible by num_groups {ng}")
    m = nt // ng
    if rt.shape != (nt * t,):
        raise ValueError(f"rt shape {rt.shape} != ({nt * t},)")
    if meta.shape != (ng + 4 * nt,):
        raise ValueError(f"meta shape {meta.shape} != ({ng + 4 * nt},)")
    if c % bg != 0 or bg < t:
        raise ValueError(f"stream length {c} not a multiple of block_rows "
                         f"{bg} >= tile_rows {t}")
    _check_reg_shape(reg, reg_mode, num_segments, k)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if interpret or not has_vma_system():
        from cfk_tpu.compat import emulate_fused_gram_solve

        a, b = _emulate_gram_dense(
            _emulate_gather(table, nb, wt), rt, meta,
            num_segments=num_segments, tile_rows=t, num_tiles=nt,
            num_groups=ng, block_rows=bg, carry=carry,
        )
        return emulate_fused_gram_solve(
            a, b, reg, reg_mode=reg_mode, lam=lam, lseg=lseg,
        )
    if pltpu is None:  # pragma: no cover - non-TPU pallas build
        raise RuntimeError("pallas TPU extensions unavailable")
    f_rows = table.shape[0]
    weighted = wt is not None
    int8_table, dq_scratch, dq_bytes = _int8_gather_pieces(
        table, bg, k, weighted=weighted)
    s_pad = -(-num_segments // _SOLVE_LANES) * _SOLVE_LANES
    vma = typeof_vma(table)
    (reg_op, reg_spec, carry_ops, carry_specs, out_shape, out_specs,
     scratch, scratch_bytes) = _fused_call_pieces(
        k, s_pad, num_segments, reg, reg_mode, carry, vma, algo)
    scratch = scratch + [
        pltpu.VMEM((2 * bg, k), table.dtype),
        pltpu.SemaphoreType.DMA((2,)),
    ] + dq_scratch
    wt_specs = ([pl.BlockSpec((1, bg), lambda i, sc: (0, sc[i]))]
                if weighted else [])
    scalar = jnp.concatenate([
        meta.astype(jnp.int32),
        jnp.asarray(lseg, jnp.int32).reshape(1),
        nb.astype(jnp.int32),
    ])
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(ng,),
        in_specs=[
            pl.BlockSpec(memory_space=_any_memory_space()),  # table
            pl.BlockSpec((1, m * t), lambda i, sc: (0, i)),  # rt
        ] + wt_specs + [reg_spec] + carry_specs,
        out_specs=out_specs,
        scratch_shapes=scratch,
    )
    precision = _gather_precision(table)
    g_bytes = 2 * bg * k * table.dtype.itemsize + dq_bytes
    params = getattr(pltpu, "CompilerParams", None) or getattr(
        pltpu, "TPUCompilerParams"
    )
    kwargs = {"compiler_params": params(
        vmem_limit_bytes=min(scratch_bytes + g_bytes + 4 * bg * 8
                             + (10 << 20), 124 << 20)
    )}
    wt_ops = ([wt.reshape(1, c).astype(jnp.float32)] if weighted else [])
    x, cao, cbo = pl.pallas_call(
        functools.partial(
            _gram_solve_gather_dense_kernel, m=m, t=t, k=k, ng=ng, nt=nt,
            bg=bg, s_pad=s_pad, f_rows=f_rows, precision=precision,
            with_carry=carry is not None, weighted=weighted,
            reg_mode=reg_mode, lam=lam, algo=algo, int8_table=int8_table,
        ),
        grid_spec=grid_spec,
        out_shape=out_shape,
        interpret=interpret,
        **kwargs,
    )(scalar, table, rt.reshape(1, nt * t), *wt_ops, reg_op, *carry_ops)
    return x[:num_segments], cao, cbo[0]


def _gather_rows_kernel(sc_ref, table_ref, *refs, bg, k, f_rows, weighted,
                        sep_buf):
    """Row-DMA stream producer: each grid step fetches its [BG] indexed
    rows into the double-buffered scratch (next group's copies in flight
    under this group's write-out), applies the premultiply (which is also
    the dequantize for quantized tables — scale folded into ``wt``
    upstream), and writes the [BG, k] block to the output stream.  The
    bucketed half-steps and the subspace sweeps use this where their
    consumer needs the whole gathered rectangle resident (the b×b sweeps
    rank-update a score stream across blocks, so the stream must exist) —
    it replaces XLA's operand-size-cliffed gather with per-row DMA, not
    the stream itself."""
    refs = list(refs)
    g_buf, sem, dq_buf = _pop_gather_scratch(refs, sep_buf)
    out_ref = refs[-1]
    wt_ref = refs[0] if weighted else None
    gi = pl.program_id(0)
    off = _gather_double_buffer(
        g_buf, sem, table_ref, sc_ref, nb_base=0, rows=bg, gi=gi,
        ng=pl.num_programs(0), f_rows=f_rows,
        group_row0=lambda g: g * bg,
    )
    base = pl.ds(pl.multiple_of(off, 16), bg)
    if weighted:
        _premultiply_rows(g_buf, off, bg, wt_ref, out_buf=dq_buf)
        src = dq_buf if sep_buf else g_buf
        out_ref[...] = src[base, :].astype(out_ref.dtype)
    else:
        out_ref[...] = g_buf[base, :].astype(out_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("out_dtype", "block_rows", "interpret"),
)
def gather_rows_pallas(
    table: jax.Array,  # [F, k] RAW table (f32 / bf16 / int8 — no zero row)
    nb: jax.Array,  # [C] int32 row indices; F = the virtual zero row
    wt: jax.Array | None,  # [C] premultiply (mask / √aw·mask, scale folded)
    *,
    out_dtype=None,
    block_rows: int | None = None,
    interpret: bool | None = None,
) -> jax.Array:
    """Materialized gathered stream via in-kernel row DMA:
    ``out[i] = table[nb[i]].astype(out_dtype) · wt[i]`` with the virtual
    zero row realized by clamp + the ``wt`` mask (``wt=None`` skips the
    multiply — callers whose padding is annihilated downstream).

    Off-TPU / old-jax / refused shapes route through the bit-identical
    XLA twin (``compat.emulate_in_kernel_gather``), so CPU CI pins the
    same numbers the Mosaic DMA path produces on hardware."""
    from cfk_tpu.ops.solve import _gram_compute_dtype

    c = nb.shape[0]
    k = table.shape[-1]
    if table.dtype == jnp.int8 and wt is None:
        # Same loud refusal as the gram kernels (_int8_gather_pieces):
        # the per-row dequant scale rides ONLY in wt (quant.fold_scale),
        # so a scale-less int8 gather would return raw codes as numbers.
        raise ValueError(
            "gather_rows_pallas: an int8 table needs the per-row dequant "
            "scale folded into wt (ops.quant.fold_scale); wt=None would "
            "return raw quantized codes"
        )
    if out_dtype is None:
        out_dtype, _ = _gram_compute_dtype(table)
    out_dtype = jnp.dtype(out_dtype)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    bg = block_rows or min(c, 1024)
    while bg > 16 and c % bg:
        bg //= 2
    supported = (
        not interpret and has_vma_system() and pltpu is not None
        and c % bg == 0 and bg % 16 == 0
        and in_kernel_gather_supported(c, 0, 16)
    )
    if not supported:
        from cfk_tpu.compat import emulate_in_kernel_gather

        return emulate_in_kernel_gather(table, nb, wt, out_dtype)
    f_rows = table.shape[0]
    weighted = wt is not None
    sep_buf = weighted and out_dtype != table.dtype
    vma = typeof_vma(table)
    mk = (lambda s, d: jax.ShapeDtypeStruct(s, d, vma=vma)) if vma else (
        lambda s, d: jax.ShapeDtypeStruct(s, d)
    )
    wt_specs = ([pl.BlockSpec((1, bg), lambda i, sc: (0, i))]
                if weighted else [])
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(c // bg,),
        in_specs=[pl.BlockSpec(memory_space=_any_memory_space())] + wt_specs,
        out_specs=[pl.BlockSpec((bg, k), lambda i, sc: (i, 0))],
        scratch_shapes=[
            pltpu.VMEM((2 * bg, k), table.dtype),
            pltpu.SemaphoreType.DMA((2,)),
        ] + ([pltpu.VMEM((2 * bg, k), out_dtype)] if sep_buf else []),
    )
    params = getattr(pltpu, "CompilerParams", None) or getattr(
        pltpu, "TPUCompilerParams"
    )
    g_bytes = 2 * bg * k * (table.dtype.itemsize
                            + (out_dtype.itemsize if sep_buf else 0))
    kwargs = {"compiler_params": params(
        vmem_limit_bytes=min(g_bytes + 2 * bg * k * out_dtype.itemsize
                             + 4 * bg * 8 + (8 << 20), 124 << 20)
    )}
    wt_ops = ([wt.reshape(1, c).astype(jnp.float32)] if weighted else [])
    (out,) = pl.pallas_call(
        functools.partial(
            _gather_rows_kernel, bg=bg, k=k, f_rows=f_rows,
            weighted=weighted, sep_buf=sep_buf,
        ),
        grid_spec=grid_spec,
        out_shape=(mk((c, k), out_dtype),),
        interpret=interpret,
        **kwargs,
    )(nb.astype(jnp.int32), table, *wt_ops)
    return out


def _check_reg_shape(reg, reg_mode, num_segments, k):
    if reg_mode == "diag":
        if reg.shape != (num_segments,):
            raise ValueError(
                f"diag reg shape {reg.shape} != ({num_segments},)"
            )
    elif reg_mode == "matrix":
        if reg.shape != (k, k):
            raise ValueError(f"matrix reg shape {reg.shape} != ({k},{k})")
    else:
        raise ValueError(f"unknown reg_mode {reg_mode!r}")
