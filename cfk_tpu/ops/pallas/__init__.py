from cfk_tpu.ops.pallas.solve_kernel import (
    PALLAS_MAX_RANK,
    gauss_solve_multi_pallas,
    gauss_solve_pallas,
    gauss_solve_reg_pallas,
)

__all__ = [
    "PALLAS_MAX_RANK",
    "gauss_solve_multi_pallas",
    "gauss_solve_pallas",
    "gauss_solve_reg_pallas",
]
