"""Batched ALS-WR normal-equation solves — the FLOP hot spot.

TPU-native re-design of the per-entity EJML solve in the reference
(``processors/MFeatureCalculator.java:85-99`` / ``UFeatureCalculator.java:85-99``):

    V = UᵀR;  A = UᵀU;  A += λ·n_ratings·I;  m = A⁻¹V        (per entity)

Instead of a HashMap accumulate-until-complete per entity, all entities of a
shard are solved at once: one gather of neighbor factors into a
[E, P, k] tensor, two einsums (MXU matmuls) for all Gram matrices and
right-hand sides, and a batched Cholesky solve of the k×k systems.  The
reference's explicit matrix inverse becomes a Cholesky factorization (A is
SPD by construction); float32 throughout, matching EJML's FMatrixRMaj.

ALS-WR weighted regularization λ·n_ratings·I is exact reference semantics;
the regularizer is floored at λ·1 only for all-padding rows (n = 0), which
the reference cannot have (its HashMap only ever contains rated entities).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def _gram_compute_dtype(fixed_factors):
    """(compute dtype, einsum precision) for Gram/RHS contractions.

    float32 factors: full-float32 MXU passes (precision="highest") — the
    default bf16 passes would perturb the normal equations ~1e-2 relative
    and break parity with the reference's float32 EJML math.

    bfloat16 factors (the at-scale storage mode): feed the MXU bf16
    directly with float32 accumulation, at twice the MXU rate and half the
    gather traffic (profiled: the f32 upcast fusion was the single hottest
    op in the full-Netflix iteration).  For the UNWEIGHTED Gram A = Σ ffᵀ
    and the rating RHS this is bit-identical to upcasting first — bf16×bf16
    products are exact in the float32 accumulator, and star/half-star
    ratings fit bf16's 8-bit mantissa exactly (measured: medium-config RMSE
    unchanged to the last printed digit).  The iALS confidence
    pre-multiplies (gm·(c−1) etc.) DO round each weighted product to bf16
    before the matmul in this mode — ~0.4% relative on those Gram entries,
    on top of the storage rounding the caller already opted into.
    """
    if fixed_factors.dtype == jnp.bfloat16:
        return jnp.bfloat16, None
    return jnp.float32, "highest"


def gather_gram(
    fixed_factors: jax.Array,  # [F, k] factors of the side held fixed
    neighbor_idx: jax.Array,  # [E, P] int32
    rating: jax.Array,  # [E, P] float32 (0 at padding)
    mask: jax.Array,  # [E, P] float32 (1 = real)
) -> tuple[jax.Array, jax.Array]:
    """Compute Gram matrices A = Σ f fᵀ and RHS b = Σ r·f for every entity.

    Returns (A [E, k, k], b [E, k]).  The gather + einsum pair is what XLA
    tiles onto the MXU; padding rows contribute zero via the mask.
    """
    ct, prec = _gram_compute_dtype(fixed_factors)
    gathered = fixed_factors[neighbor_idx]  # [E, P, k]
    gm = gathered.astype(ct) * mask[..., None].astype(ct)
    a = jnp.einsum(
        "epk,epl->ekl", gm, gm,
        preferred_element_type=jnp.float32, precision=prec,
    )
    b = jnp.einsum(
        "epk,ep->ek", gm, rating.astype(ct),
        preferred_element_type=jnp.float32, precision=prec,
    )
    return a, b


def batched_spd_solve(a: jax.Array, b: jax.Array) -> jax.Array:
    """Solve A x = b for a batch of SPD k×k systems via Cholesky.

    a: [E, k, k], b: [E, k] → x: [E, k].
    """
    chol = jnp.linalg.cholesky(a)
    y = lax.linalg.triangular_solve(
        chol, b[..., None], left_side=True, lower=True, transpose_a=False
    )
    x = lax.linalg.triangular_solve(
        chol, y, left_side=True, lower=True, transpose_a=True
    )
    return x[..., 0]


def gather_gram_implicit(
    fixed_factors: jax.Array,  # [F, k]
    neighbor_idx: jax.Array,  # [E, P]
    confidence_m1: jax.Array,  # [E, P] c−1 = α·r at observed cells, 0 at padding
    mask: jax.Array,  # [E, P]
) -> tuple[jax.Array, jax.Array]:
    """Per-entity observed-part Gram for implicit ALS (Hu et al. 2008).

    Returns (A_obs [E,k,k], b [E,k]) with A_obs = Σ (c−1)·f fᵀ over observed
    neighbors and b = Σ c·f (preferences are 1 at observed cells).  The full
    normal matrix is A = YᵀY + A_obs + λI where YᵀY is the *global* Gram over
    all fixed-side rows — computed once per half-iteration (the O(k²)
    speedup trick), not per entity.
    """
    ct, prec = _gram_compute_dtype(fixed_factors)
    gathered = fixed_factors[neighbor_idx].astype(ct)
    gm = gathered * mask[..., None].astype(ct)
    gw = gm * confidence_m1[..., None].astype(ct)
    a = jnp.einsum(
        "epk,epl->ekl", gw, gm,
        preferred_element_type=jnp.float32, precision=prec,
    )
    b = jnp.einsum(
        "epk,ep->ek", gm, ((confidence_m1 + 1.0) * mask).astype(ct),
        preferred_element_type=jnp.float32, precision=prec,
    )
    return a, b


def global_gram(factors: jax.Array) -> jax.Array:
    """YᵀY over all rows (float32 accumulation) — [k, k]."""
    ct, prec = _gram_compute_dtype(factors)
    f = factors.astype(ct)
    return jnp.einsum(
        "fk,fl->kl", f, f, preferred_element_type=jnp.float32, precision=prec
    )


# Canonical block height for the blocked global-Gram reduction.  One value
# shared by the resident bucketed implicit paths and the out-of-core Gram
# pass (offload/windowed.py) — the summation ORDER is part of the bit
# contract between them, and the block height is what fixes it.
GRAM_BLOCK_ROWS = 4096


def global_gram_blocked(factors: jax.Array,
                        block_rows: int = GRAM_BLOCK_ROWS) -> jax.Array:
    """YᵀY by a pinned blocked reduction — [k, k], float32.

    Same math as ``global_gram`` with one canonical summation order: the
    table is cut into consecutive ``[block_rows, k]`` blocks (zero-padded
    tail — the pad contributes exact 0.0) and the per-block Grams
    accumulate in f32, block 0 first.  The out-of-core Gram pass replays
    this reduction block-for-block against staged ``HostFactorStore``
    rows, which is what keeps the resident and host_window implicit
    half-steps crc-identical: both run THIS program, never the
    whole-table einsum whose reassociation XLA owns.
    """
    f, k = factors.shape
    nb = max(-(-f // block_rows), 1)
    pad = nb * block_rows - f
    x = factors
    if pad:
        x = jnp.concatenate(
            [x, jnp.zeros((pad, k), x.dtype)], axis=0
        )
    acc = jnp.zeros((k, k), jnp.float32)

    def body(acc, blk):
        return gram_block_add(acc, blk), None

    acc, _ = jax.lax.scan(body, acc, x.reshape(nb, block_rows, k))
    return acc


def gram_block_add(acc: jax.Array, blk: jax.Array) -> jax.Array:
    """One blocked-Gram step: ``acc + blkᵀblk`` (f32).  The single body
    both ``global_gram_blocked`` and the windowed store reduction run —
    per-block shapes and this op are the whole bit contract."""
    ct, prec = _gram_compute_dtype(blk)
    b = blk.astype(ct)
    return acc + jnp.einsum(
        "fk,fl->kl", b, b, preferred_element_type=jnp.float32, precision=prec
    )


def ials_half_step(
    fixed_factors: jax.Array,  # [F, k] (full fixed side)
    neighbor_idx: jax.Array,
    rating: jax.Array,  # raw ratings/counts; confidence = 1 + alpha·r
    mask: jax.Array,
    lam: float,
    alpha: float,
    *,
    gram: jax.Array | None = None,  # precomputed YᵀY (pass psum'd under SPMD)
    solver: str = "cholesky",
    reg_solve_algo: str | None = None,
) -> jax.Array:
    """Solve all entities of one side for implicit feedback.

    Regularization is plain λI (Hu et al.), not the ALS-WR λ·n·I of the
    explicit model.
    """
    k = fixed_factors.shape[-1]
    if gram is None:
        gram = global_gram(fixed_factors)
    a_obs, b = gather_gram_implicit(fixed_factors, neighbor_idx, alpha * rating, mask)
    reg = gram + lam * jnp.eye(k, dtype=jnp.float32)
    return regularized_solve_matrix(a_obs, b, reg, solver,
                                    algo=reg_solve_algo)


def walk_buckets(buckets, chunk_rows, arrays_of, piece, out, overlap=None):
    """The bucket scaffolding every width-bucketed half-step shares.

    For each bucket: extract its per-row arrays (``arrays_of(blk, out)`` —
    ``out`` is passed so warm-started optimizers can gather the bucket's
    current factors), run ``piece(*arrays) -> [rows, k]`` — streamed through
    HBM in [chunk, ...] pieces when ``chunk_rows`` bounds the bucket — and
    scatter the result into ``out`` at the bucket's entity rows (padding
    rows target the trash slot; real rows are unique across buckets).

    The chunk stream is double-buffered by default
    (``ops.pipeline.chunk_map``): chunk c+1's operand fetch is issued
    before ``piece`` runs on chunk c, so the HBM reads hide behind the
    solve; ``overlap=False`` is the serial ``lax.map`` reference schedule.
    """
    from cfk_tpu.ops.pipeline import chunk_map

    k = out.shape[-1]
    for blk, chunk in zip(buckets, chunk_rows):
        arrs = arrays_of(blk, out)
        rows = arrs[0].shape[0]
        if chunk is None or chunk >= rows:
            x = piece(*arrs)
        else:
            if rows % chunk != 0:
                raise ValueError(f"bucket rows {rows} not divisible by chunk {chunk}")
            n_chunks = rows // chunk
            reshaped = tuple(
                a.reshape((n_chunks, chunk) + a.shape[1:]) for a in arrs
            )
            x = chunk_map(
                piece, reshaped, n_chunks, overlap=overlap
            ).reshape(rows, k)
        out = out.at[blk["entity_local"]].set(x)
    return out


def ials_half_step_bucketed(
    fixed_factors: jax.Array,  # [F, k]
    buckets,  # sequence of dicts {neighbor, rating, mask, entity_local}
    chunk_rows,  # same-length sequence of static ints / None
    local_entities: int,
    lam: float,
    alpha: float,
    *,
    gram: jax.Array | None = None,
    solver: str = "cholesky",
    overlap: bool | None = None,
    reg_solve_algo: str | None = None,
    fused_epilogue: bool | None = None,
    in_kernel_gather: bool | None = None,
    table_dtype: str | None = None,
) -> jax.Array:
    """Implicit-feedback half-iteration over width-bucketed InBlocks.

    Same bucket walk as ``als_half_step_bucketed``; per entity the normal
    matrix is YᵀY + Σ_obs (c−1)·f fᵀ + λI.  Zero-interaction rows stay 0,
    identical to the padded path's (YᵀY + λI)x = 0 solve.

    Width classes that pass the port gates run the tiled gather kernels
    via ``ops.bucketed`` (in-kernel DMA gather + fused b-batch epilogue,
    sqrt-reparameterized single weighted stream — the tiled iALS trick);
    refused classes keep this legacy schedule.  ``table_dtype`` quantizes
    the gather table (``ops.quant``); the legacy fallback and the global
    Gram consume the dequantized view so every route sees the same values.
    """
    from cfk_tpu.ops import bucketed as bport, quant

    k = fixed_factors.shape[-1]
    data, scale = quant.quantize_table(fixed_factors, table_dtype)
    view = quant.dequantize_table(data, scale)
    if gram is None:
        # Blocked (not whole-einsum) so the out-of-core Gram pass can
        # replay the identical reduction — see global_gram_blocked.
        gram = global_gram_blocked(view)
    reg_m = gram + lam * jnp.eye(k, dtype=jnp.float32)

    def solve_piece(ni, rt, mk):
        rows, width = ni.shape
        modes = bport.resolve_bucket_modes(
            fused_epilogue, in_kernel_gather, solver, rows, width, k,
            None, reg_solve_algo,
        )
        if modes is None:
            a_obs, b = gather_gram_implicit(view, ni, alpha * rt, mk)
            return regularized_solve_matrix(a_obs, b, reg_m, solver,
                                            algo=reg_solve_algo)
        fused, gather = modes
        wt, rt_b = bport.ials_reparam(rt, mk, alpha)
        return bport.bucket_gram_solve(
            data, scale, ni, wt, rt_b, reg_m, lam=0.0, reg_mode="matrix",
            solver=solver, fused=fused, gather=gather, algo=reg_solve_algo,
        )

    out = walk_buckets(
        buckets, chunk_rows,
        lambda blk, _out: (blk["neighbor"], blk["rating"], blk["mask"]),
        solve_piece,
        jnp.zeros((local_entities + 1, k), jnp.float32),
        overlap=overlap,
    )
    return out[:local_entities]


def _blocked_spd_solve_pallas(a: jax.Array, b: jax.Array) -> jax.Array:
    """SPD solve for PALLAS_MAX_RANK < k ≤ 2·PALLAS_MAX_RANK via one level
    of block (Schur-complement) elimination.

    Split A = [[A₁₁ A₁₂],[A₂₁ A₂₂]] at k₁ = PALLAS_MAX_RANK.  One multi-RHS
    Gauss-Jordan computes Y = A₁₁⁻¹[A₁₂ | b₁]; the Schur complement
    S = A₂₂ − A₂₁·Y₁₂ (SPD) is solved by the single-RHS kernel; and
    x₁ = y₁ − Y₁₂·x₂ back-substitutes.  Everything else is batched k₁³
    matmuls — MXU work — so rank 128 costs two lane-vectorized solves plus
    GEMMs instead of XLA's latency-bound 128×128 cholesky custom calls
    (measured: full-Netflix rank-128 drops from 15.8 to well under the
    12 s/iter bar; see BASELINE.md).
    """
    from cfk_tpu.ops.pallas import (
        PALLAS_MAX_RANK,
        gauss_solve_multi_pallas,
        gauss_solve_pallas,
    )

    k = a.shape[-1]
    k1 = PALLAS_MAX_RANK
    k2 = k - k1
    al = jnp.transpose(a, (1, 2, 0))  # [k, k, E]
    bl = b.T  # [k, E]
    a11, a12 = al[:k1, :k1], al[:k1, k1:]
    a21, a22 = al[k1:, :k1], al[k1:, k1:]
    b1, b2 = bl[:k1], bl[k1:]
    y = gauss_solve_multi_pallas(
        a11, jnp.concatenate([a12, b1[:, None, :]], axis=1)
    )  # [k1, k2+1, E]
    y12, y1 = y[:, :k2], y[:, k2]
    # Batch-last contractions: S = A₂₂ − A₂₁·Y₁₂ etc. (einsum over the k₁
    # axis with the batch as the trailing dim — XLA lowers these to batched
    # GEMMs; f32 operands keep full precision).
    s = a22 - jnp.einsum(
        "ije,jke->ike", a21, y12,
        preferred_element_type=jnp.float32, precision="highest",
    )
    rhs2 = b2 - jnp.einsum(
        "ije,je->ie", a21, y1,
        preferred_element_type=jnp.float32, precision="highest",
    )
    x2 = gauss_solve_pallas(s, rhs2)  # [k2, E]
    x1 = y1 - jnp.einsum(
        "ije,je->ie", y12, x2,
        preferred_element_type=jnp.float32, precision="highest",
    )
    return jnp.concatenate([x1, x2], axis=0).T  # [E, k]


def dispatch_spd_solve(a: jax.Array, b: jax.Array, solver: str) -> jax.Array:
    """Solve batched SPD systems with the selected backend.

    ``"cholesky"`` — XLA's cholesky + triangular solves.
    ``"pallas"``   — lane-vectorized Gauss-Jordan TPU kernel
                     (``cfk_tpu.ops.pallas``); interpret-mode off TPU.
    ``"auto"``     — pallas on a TPU backend (XLA's batched cholesky custom
                     calls are latency-bound at small k; the kernel is
                     ~7× faster on 100k rank-64 systems and ~1.7× on the
                     end-to-end full-Netflix iteration), cholesky elsewhere.

    The pallas path pays an explicit [E,k,k] → [k,k,E] transpose to put the
    batch in the lane dimension.  Ranks in (PALLAS_MAX_RANK, 2·PALLAS_MAX_RANK]
    use one level of blocked Schur elimination on the same kernels; anything
    larger falls back to cholesky.
    """
    solver = _resolve_solver(solver)
    if solver == "cholesky":
        return batched_spd_solve(a, b)
    if solver == "pallas":
        from cfk_tpu.ops.pallas import PALLAS_MAX_RANK, gauss_solve_pallas

        k = a.shape[-1]
        if k > 2 * PALLAS_MAX_RANK:
            return batched_spd_solve(a, b)
        if k > PALLAS_MAX_RANK:
            return _blocked_spd_solve_pallas(a, b)
        x = gauss_solve_pallas(jnp.transpose(a, (1, 2, 0)), b.T)
        return x.T
    raise ValueError(f"unknown solver {solver!r}")


def _resolve_solver(solver: str) -> str:
    if solver == "auto":
        return "pallas" if jax.default_backend() == "tpu" else "cholesky"
    return solver


def default_fused_epilogue() -> bool:
    """Process-wide default for the fused-epilogue family: the Gram
    kernels' in-VMEM ridge+solve (``ops.pallas.gram_kernel.
    gram_solve_tiles_pallas``) and the fused reg+solve dispatch below.
    True = fuse wherever the backend/rank gates allow — the production
    mode (the split path's per-chunk [Ec, k, k] A-batch write + readback
    is pure HBM traffic the fusion removes).  Patchable for A/B
    measurement (``scripts/perf_lab.py --fused off``, ``bench.py
    --fused-ab``) exactly like ``ops.pipeline.default_overlap``; per-call
    ``fused=`` and ``ALSConfig.fused_epilogue`` override it explicitly."""
    return True


def resolve_fused_epilogue(fused) -> bool:
    """Per-call override if given, else the process default."""
    return default_fused_epilogue() if fused is None else bool(fused)


def regularized_solve(
    a: jax.Array, b: jax.Array, count: jax.Array, lam: float,
    solver: str = "cholesky", fused: bool | None = None,
    algo: str | None = None,
) -> jax.Array:
    """Apply ALS-WR regularization λ·n_ratings·I and solve.

    The n floor at 1 keeps all-padding rows (n = 0) SPD; real rows always have
    n ≥ 1 so their math is exact reference semantics
    (``processors/MFeatureCalculator.java:91-95``).

    On the pallas backend at supported ranks the regularization, the
    batch-last transposes, and the elimination run as ONE kernel
    (``gauss_solve_reg_pallas``) — the separate diagonal-add pass re-wrote
    the whole Gram batch through HBM every chunk (round-3 profile).
    ``fused=False`` (or the process default off) pins the split
    ridge-add + dispatch schedule — the measurement baseline of
    ``bench.py --fused-ab``.  ``algo`` threads the fused elimination
    choice ('lu'/'gj'; None/'auto' = the process default) — the knob the
    recovery ladder's GJ rung flips (``ALSConfig.reg_solve_algo``).
    """
    from cfk_tpu.ops.pallas import gauss_solve_reg_pallas
    from cfk_tpu.ops.pallas.solve_kernel import _fused_reg_rank_cap

    k = a.shape[-1]
    if (resolve_fused_epilogue(fused)
            and _resolve_solver(solver) == "pallas"
            and k <= _fused_reg_rank_cap(algo)):
        # The fused kernel bakes λ in as a compile-time constant; a traced
        # lam (e.g. a per-step tuned regularizer) cannot concretize, so it
        # takes the unfused path below — same math, one extra HBM pass —
        # instead of a ConcretizationTypeError only the pallas path raised.
        try:
            lam_static = float(lam)
        except jax.errors.ConcretizationTypeError:
            # Only the traced case falls through; genuinely invalid lam
            # (None, multi-element arrays) still raises at the call site.
            lam_static = None
        if lam_static is not None:
            return gauss_solve_reg_pallas(
                a, b, count, reg_mode="diag", lam=lam_static, algo=algo
            )
    reg = lam * jnp.maximum(count.astype(jnp.float32), 1.0)
    a = a + reg[:, None, None] * jnp.eye(k, dtype=a.dtype)
    return dispatch_spd_solve(a, b, solver)


def regularized_solve_matrix(
    a: jax.Array, b: jax.Array, reg: jax.Array, solver: str = "cholesky",
    fused: bool | None = None, algo: str | None = None,
) -> jax.Array:
    """Solve (A_e + R) x_e = b_e with one shared [k,k] SPD term R.

    The iALS half-steps' per-entity systems all add the same global
    YᵀY + λI (Hu et al. 2008); fusing the add into the pallas solve skips
    an [E,k,k] HBM rewrite per chunk, exactly like ``regularized_solve``
    (and like it, ``fused=False`` pins the split schedule for A/B runs
    and ``algo`` threads the elimination choice).
    """
    from cfk_tpu.ops.pallas import gauss_solve_reg_pallas
    from cfk_tpu.ops.pallas.solve_kernel import _fused_reg_rank_cap

    k = a.shape[-1]
    if (resolve_fused_epilogue(fused)
            and _resolve_solver(solver) == "pallas"
            and k <= _fused_reg_rank_cap(algo)):
        return gauss_solve_reg_pallas(a, b, reg, reg_mode="matrix", algo=algo)
    return dispatch_spd_solve(a + reg[None], b, solver)


def pad_rows_to_multiple(arrays, multiple: int):
    """Zero-pad every array's leading (entity) axis to a multiple.

    The shared prologue of entity-chunked scans whose chunk size comes
    from the HBM cell budget (an arbitrary integer): padded rows carry
    zero mask/count, so their solves/Grams are inert and callers slice
    the result back to the real count.  Returns (arrays, pad)."""
    e = arrays[0].shape[0]
    pad = (-e) % multiple
    if pad:
        rowpad = lambda x: jnp.pad(x, ((0, pad),) + ((0, 0),) * (x.ndim - 1))
        arrays = tuple(rowpad(x) for x in arrays)
    return arrays, pad


def _solve_chunk(
    fixed_factors: jax.Array,
    lam: float,
    neighbor_idx: jax.Array,
    rating: jax.Array,
    mask: jax.Array,
    count: jax.Array,
    solver: str = "cholesky",
    algo: str | None = None,
) -> jax.Array:
    a, b = gather_gram(fixed_factors, neighbor_idx, rating, mask)
    return regularized_solve(a, b, count, lam, solver, algo=algo)


def als_half_step(
    fixed_factors: jax.Array,  # [F, k]
    neighbor_idx: jax.Array,  # [E, P]
    rating: jax.Array,  # [E, P]
    mask: jax.Array,  # [E, P]
    count: jax.Array,  # [E]
    lam: float,
    *,
    solve_chunk: Optional[int] = None,
    solver: str = "cholesky",
    overlap: bool | None = None,
    reg_solve_algo: str | None = None,
) -> jax.Array:
    """One ALS half-iteration: solve all [E] entities against fixed factors.

    ``solve_chunk`` bounds the [chunk, P, k] gather living in HBM at once
    by scanning over entity chunks.  An indivisible E is padded with
    zero-mask rows (their λ-floored solves are sliced off), so budget-
    derived chunk sizes (``ALSConfig.padded_solve_chunk``) always work.
    The chunk stream is double-buffered by default (``ops.pipeline``).
    """
    if solve_chunk is None or solve_chunk >= neighbor_idx.shape[0]:
        return _solve_chunk(
            fixed_factors, lam, neighbor_idx, rating, mask, count, solver,
            reg_solve_algo,
        )
    from cfk_tpu.ops.pipeline import chunk_map

    e = neighbor_idx.shape[0]
    (neighbor_idx, rating, mask, count), pad = pad_rows_to_multiple(
        (neighbor_idx, rating, mask, count), solve_chunk
    )
    n_chunks = (e + pad) // solve_chunk

    reshape = lambda x: x.reshape((n_chunks, solve_chunk) + x.shape[1:])
    out = chunk_map(
        lambda ni, r, m, c: _solve_chunk(fixed_factors, lam, ni, r, m, c,
                                         solver, reg_solve_algo),
        (reshape(neighbor_idx), reshape(rating), reshape(mask),
         reshape(count)),
        n_chunks, overlap=overlap,
    )
    return out.reshape(e + pad, fixed_factors.shape[-1])[:e]


def _ragged_gram_ddn():
    """Dimension numbers for the grouped-Gram ragged matmul: contract the
    (ragged, sorted-by-group) entry axis of both operands → [G, k, k]."""
    return lax.RaggedDotDimensionNumbers(
        dot_dimension_numbers=(((0,), (0,)), ((), ())),
        lhs_ragged_dimensions=[0],
        rhs_group_dimensions=[],
    )


def default_segment_backend() -> str:
    """Gram backend for the segment layout: grouped ragged matmul (MXU; no
    [C, k, k] intermediate) when this JAX has it, else sorted segment_sum."""
    return "ragged" if hasattr(lax, "ragged_dot_general") else "segsum"


def _segment_gram_flat(
    fixed_factors, neighbor_idx, weight, rating, mask, num_segments,
    segment_ids, group_sizes, backend,
):
    """Gram/RHS contributions of a flat sorted run of ratings.

    A[e] += Σ w·f fᵀ and b[e] += Σ r·f over the run's entries owned by e
    (``weight`` is 1 for explicit ALS, the confidence excess c−1 for iALS;
    ``rating`` is r for explicit, c·preference = c for iALS).  Padding
    entries are masked to zero so their (trash) segment contributes nothing.

    ``backend="ragged"`` computes A and b together as ONE grouped matmul on
    the MXU (``lax.ragged_dot_general`` with the rating appended as lhs
    column k — out[:, :k, :] is A, out[:, k, :] is b), using the
    host-precomputed per-segment entry counts (``group_sizes``); no scatter
    ops anywhere, peak memory is the [C, k] gather.  ``"segsum"``
    materializes the [C, k, k] per-entry outer products and segment-sums
    them by ``segment_ids``.
    """
    ct, prec = _gram_compute_dtype(fixed_factors)
    f = fixed_factors[neighbor_idx].astype(ct) * mask[:, None].astype(ct)
    fw = f * weight[:, None].astype(ct)
    if backend == "ragged":
        lhs = jnp.concatenate([fw, rating[:, None].astype(ct)], axis=1)  # [C, k+1]
        out = lax.ragged_dot_general(
            lhs, f, group_sizes, _ragged_gram_ddn(),
            precision=(lax.Precision.HIGHEST if prec else None),
            preferred_element_type=jnp.float32,
        )  # [G, k+1, k]
        return out[:, :-1, :], out[:, -1, :]
    if backend != "segsum":
        raise ValueError(f"unknown segment gram backend {backend!r}")
    # segment_sum accumulates in the operand dtype — upcast so bf16-stored
    # factors still get float32 accumulation like the ragged path.
    f = f.astype(jnp.float32)
    fw = fw.astype(jnp.float32)
    a = jax.ops.segment_sum(
        fw[:, :, None] * f[:, None, :], segment_ids,
        num_segments=num_segments, indices_are_sorted=True,
    )
    b = jax.ops.segment_sum(
        rating[:, None] * f, segment_ids,
        num_segments=num_segments, indices_are_sorted=True,
    )
    return a, b


def _match_varying(z, ref):
    """Give constant ``z`` the same device-varying axes as traced ``ref``.

    Inside ``shard_map`` (with vma checking) a scan carry initialized from
    constants must be explicitly pcast/pvary'd to the mesh axes the body's
    data is varying over; outside shard_map this is the identity.
    """
    try:
        vma = jax.typeof(ref).vma
    except (AttributeError, TypeError):
        return z
    if not vma:
        return z
    if hasattr(lax, "pcast"):
        return lax.pcast(z, tuple(vma), to="varying")
    return lax.pvary(z, tuple(vma))


def _segment_scan(fixed_factors, per_chunk_gram, solve_rows, arrays, statics,
                  local_entities):
    """The chunk scan both segment half-steps share.

    ``arrays`` = (nb, rt, mk, seg, sizes, ent, cnt, cin, lseg) flat
    shard-local device arrays; ``per_chunk_gram(nb, rt, mk, seg, sizes) ->
    (A, b)`` builds one chunk's raw Gram/RHS [Ec+1, k, k]/[Ec+1, k];
    ``solve_rows(a, b, cnt) -> x`` solves the chunk's Ec rows.  The scan
    carries (partial A, partial b) of the entity straddling each chunk
    boundary — ``cin`` gates adding it to segment 0, ``lseg`` extracts the
    next carry — plus the output matrix, scattered per chunk (non-finalized
    rows target the trash slot).
    """
    nc, cap, e_c = statics
    k = fixed_factors.shape[-1]
    nb, rt, mk, seg, sizes, ent, cnt, cin, lseg = arrays
    chunks = (
        nb.reshape(nc, cap), rt.reshape(nc, cap), mk.reshape(nc, cap),
        seg.reshape(nc, cap), sizes.reshape(nc, e_c + 1),
        ent.reshape(nc, e_c), cnt.reshape(nc, e_c),
        cin.reshape(nc), lseg.reshape(nc),
    )

    def body(carry, chunk):
        a0, b0, out = carry
        nb_c, rt_c, mk_c, seg_c, sz_c, ent_c, cnt_c, cin_c, lseg_c = chunk
        a, b = per_chunk_gram(nb_c, rt_c, mk_c, seg_c, sz_c)
        a = a.at[0].add(cin_c * a0)
        b = b.at[0].add(cin_c * b0)
        x = solve_rows(a[:e_c], b[:e_c], cnt_c)
        out = out.at[ent_c].set(x)
        a1 = lax.dynamic_index_in_dim(a, lseg_c, 0, keepdims=False)
        b1 = lax.dynamic_index_in_dim(b, lseg_c, 0, keepdims=False)
        return (a1, b1, out), None

    init = jax.tree.map(
        lambda z: _match_varying(z, nb),
        (
            jnp.zeros((k, k), jnp.float32),
            jnp.zeros((k,), jnp.float32),
            jnp.zeros((local_entities + 1, k), jnp.float32),
        ),
    )
    (_, _, out), _ = lax.scan(body, init, chunks)
    # Rows never finalized by any chunk (zero-rating global-pad tail) stay
    # exactly 0 — matching the rectangular paths' λ-floored zero solve.
    return out[:local_entities]


def als_half_step_segment(
    fixed_factors: jax.Array,  # [F, k]
    neighbor_idx: jax.Array,  # [NC·C]
    rating: jax.Array,  # [NC·C]
    mask: jax.Array,  # [NC·C]
    seg_rel: jax.Array,  # [NC·C] chunk-relative entity rows, sorted per chunk
    chunk_entity: jax.Array,  # [NC·Ec] shard-local entity row (trash = E_local)
    chunk_count: jax.Array,  # [NC·Ec] full rating count of finalized rows
    group_sizes: jax.Array,  # [NC·(Ec+1)] physical entries per segment
    carry_in: jax.Array,  # [NC] 1.0 = seg 0 continues the previous chunk
    last_seg: jax.Array,  # [NC] chunk-relative index of the last real segment
    local_entities: int,
    lam: float,
    *,
    statics: tuple[int, int, int],
    solver: str = "cholesky",
    gram_backend: str | None = None,
    reg_solve_algo: str | None = None,
) -> jax.Array:
    """One explicit ALS-WR half-iteration over the packed segment layout.

    Semantics match ``als_half_step`` exactly (same normal equations, same
    λ·n·I regularization); only the Gram accumulation differs — a grouped
    ragged matmul over the flat sorted run, scanned over nnz chunks with the
    boundary-straddling entity's partial Gram carried across, so device
    memory is O(chunk) regardless of E or the degree distribution's head.
    """
    backend = gram_backend or default_segment_backend()
    e_c = statics[2]

    def chunk_gram(nb_c, rt_c, mk_c, seg_c, sz_c):
        return _segment_gram_flat(
            fixed_factors, nb_c, jnp.ones_like(rt_c), rt_c, mk_c,
            e_c + 1, seg_c, sz_c, backend,
        )

    def solve_rows(a, b, cnt_c):
        return regularized_solve(a, b, cnt_c, lam, solver,
                                 algo=reg_solve_algo)

    return _segment_scan(
        fixed_factors, chunk_gram, solve_rows,
        (neighbor_idx, rating, mask, seg_rel, group_sizes, chunk_entity,
         chunk_count, carry_in, last_seg),
        statics, local_entities,
    )


def ials_half_step_segment(
    fixed_factors: jax.Array,  # [F, k]
    neighbor_idx: jax.Array,  # [NC·C]
    rating: jax.Array,  # [NC·C] raw counts/ratings; confidence c = 1 + α·r
    mask: jax.Array,  # [NC·C]
    seg_rel: jax.Array,  # [NC·C]
    chunk_entity: jax.Array,  # [NC·Ec]
    group_sizes: jax.Array,  # [NC·(Ec+1)]
    carry_in: jax.Array,  # [NC]
    last_seg: jax.Array,  # [NC]
    local_entities: int,
    lam: float,
    alpha: float,
    *,
    statics: tuple[int, int, int],
    gram: jax.Array | None = None,  # precomputed YᵀY (pass psum'd under SPMD)
    solver: str = "cholesky",
    gram_backend: str | None = None,
    reg_solve_algo: str | None = None,
) -> jax.Array:
    """Implicit-feedback half-iteration over the packed segment layout.

    Per entity A = YᵀY + Σ_obs (c−1)·f fᵀ + λI, b = Σ_obs c·f (Hu et al.
    2008 with the global-Gram trick).  The scan carries the raw observed
    Gram of boundary-straddling entities; YᵀY + λI is added per chunk at
    solve time only.  Zero-interaction rows (chunk padding and rows outside
    every chunk) end up exactly 0: padding rows solve (YᵀY + λI)x = 0
    inside the chunk and scatter to the trash slot anyway.
    """
    k = fixed_factors.shape[-1]
    if gram is None:
        gram = global_gram(fixed_factors)
    reg = gram + lam * jnp.eye(k, dtype=jnp.float32)
    backend = gram_backend or default_segment_backend()
    e_c = statics[2]

    def chunk_gram(nb_c, rt_c, mk_c, seg_c, sz_c):
        return _segment_gram_flat(
            fixed_factors, nb_c, alpha * rt_c, (1.0 + alpha * rt_c) * mk_c,
            mk_c, e_c + 1, seg_c, sz_c, backend,
        )

    def solve_rows(a_obs, b, _cnt):
        return regularized_solve_matrix(a_obs, b, reg, solver,
                                        algo=reg_solve_algo)

    return _segment_scan(
        fixed_factors, chunk_gram, solve_rows,
        (neighbor_idx, rating, mask, seg_rel, group_sizes, chunk_entity,
         jnp.zeros(chunk_entity.shape, jnp.int32), carry_in, last_seg),
        statics, local_entities,
    )


def init_factors(
    key: jax.Array,
    rating: jax.Array,  # [E, P]
    mask: jax.Array,  # [E, P]
    count: jax.Array,  # [E]
    rank: int,
    *,
    num_entities: int | None = None,
) -> jax.Array:
    """Zhou et al. initialization, matching ``processors/UFeatureInitializer.java:50-56``:

    f[0] = entity's average rating, f[1:] ~ U(0, 1).
    """
    return init_factors_stats(key, jnp.sum(rating * mask, axis=1), count, rank,
                              num_entities=num_entities)


def init_factors_stats(
    key: jax.Array,
    rating_sum: jax.Array,  # [E] per-entity rating sum
    count: jax.Array,  # [E]
    rank: int,
    *,
    num_entities: int | None = None,
) -> jax.Array:
    """Zhou et al. init from per-entity stats (the bucketed-layout entry:
    bucketed blocks never materialize an [E, P] rectangle to sum over).

    ``num_entities`` (static) is the REAL entity count when the [E] arrays
    carry shard-count padding: threefry output DEPENDS on the draw shape
    (uniform(key, (2998, k)) and uniform(key, (3000, k)) share no values),
    so drawing at the padded length made an N-way run's init — hence its
    whole trajectory — a function of how E rounds against num_shards (the
    4-shard tiled SPMD mismatch).  Drawing at the real count and zero-
    padding keeps every shard count on the 1-way init exactly; pad rows
    were zeroed by the count mask anyway.
    """
    e = rating_sum.shape[0]
    n = e if num_entities is None else int(num_entities)
    avg = rating_sum / jnp.maximum(count.astype(jnp.float32), 1.0)
    rest = jax.random.uniform(key, (n, rank - 1), dtype=jnp.float32)
    if n != e:
        rest = jnp.pad(rest, ((0, e - n), (0, 0)))
    f = jnp.concatenate([avg[:, None], rest], axis=1)
    # Zero all-padding rows (n = 0): nothing references them in explicit ALS,
    # but the implicit model's global Gram YᵀY sums *every* row, so garbage
    # init there would silently poison iALS.
    return f * (count > 0).astype(jnp.float32)[:, None]


def als_half_step_bucketed(
    fixed_factors: jax.Array,  # [F, k]
    buckets,  # sequence of dicts {neighbor, rating, mask, count, entity_local}
    chunk_rows,  # same-length sequence of static ints / None
    local_entities: int,
    lam: float,
    *,
    solver: str = "cholesky",
    overlap: bool | None = None,
    reg_solve_algo: str | None = None,
    fused_epilogue: bool | None = None,
    in_kernel_gather: bool | None = None,
    table_dtype: str | None = None,
) -> jax.Array:
    """One ALS half-iteration over width-bucketed InBlocks.

    Width classes that pass the port gates (``ops.bucketed``) run the
    tiled gather kernels — in-kernel row DMA (``in_kernel_gather``) and
    the in-VMEM ridge+solve epilogue (``fused_epilogue``), one tile per
    entity, so the ported f32 path is bit-identical to this legacy
    schedule on the emulation route.  Refused classes (width < 16, SMEM
    overflow) keep the legacy gather + einsum + solve batch.  Rows absent
    from every bucket (zero ratings) stay exactly 0, matching the padded
    path's λ·I-floor solve of an all-zero system.  ``chunk_rows`` streams
    oversized buckets through HBM in [chunk, width, k] pieces.
    ``table_dtype`` quantizes the gather table (``ops.quant``).
    """
    from cfk_tpu.ops import bucketed as bport, quant

    k = fixed_factors.shape[-1]
    data, scale = quant.quantize_table(fixed_factors, table_dtype)
    view = quant.dequantize_table(data, scale)

    def solve_piece(ni, rt, mk, cnt):
        rows, width = ni.shape
        modes = bport.resolve_bucket_modes(
            fused_epilogue, in_kernel_gather, solver, rows, width, k,
            lam, reg_solve_algo,
        )
        if modes is None:
            return _solve_chunk(view, lam, ni, rt, mk, cnt, solver,
                                reg_solve_algo)
        fused, gather = modes
        return bport.bucket_gram_solve(
            data, scale, ni, mk, rt, cnt, lam=lam, reg_mode="diag",
            solver=solver, fused=fused, gather=gather, algo=reg_solve_algo,
        )

    out = walk_buckets(
        buckets, chunk_rows,
        lambda blk, _out: (
            blk["neighbor"], blk["rating"], blk["mask"], blk["count"]
        ),
        solve_piece,
        jnp.zeros((local_entities + 1, k), jnp.float32),
        overlap=overlap,
    )
    return out[:local_entities]
