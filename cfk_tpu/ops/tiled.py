"""Tile-padded Gram half-steps — the MXU-native segment layout.

Why this exists (measured on a real v5e, see BASELINE.md roofline notes):
the flat segment layout's grouped ragged matmul (``lax.ragged_dot_general``)
runs the per-entity Gram accumulation ~15× below what the MXU can do, and
XLA's row gather falls off a cliff (4×) once the fixed factor table exceeds
~34 MB.  This layout restructures the same math so both hot ops hit the
hardware's fast paths:

- Every entity's rating run is padded to a multiple of ``T`` rows (weight 0
  padding), so a chunk is an exact grid of [T, k] *tiles, each tile owned by
  one entity*.  The Gram contributions become ONE batched GEMM per chunk —
  ``einsum("ntk,ntl->nkl")`` on [NT, T, k] tiles, a shape XLA tiles straight
  onto the MXU — followed by a segment-sum of [NT, k, k] tile Grams by tile
  owner (≈3 tiles per entity), instead of a grouped matmul over 1M ragged
  segments.

- The side whose *fixed* table is large (solving movies gathers from the
  480k-row user table at full Netflix scale) additionally sorts its entries
  by (table slice, entity) and gathers each chunk from a
  ``lax.dynamic_slice`` of ≤ ``H`` rows — statically small, so XLA keeps the
  fast-gather strategy.  Entities then recur across slices, so this side
  accumulates per-entity Grams in a persistent [E+1, k, k] scan carry
  (``accum`` mode — only legal when the solve side has few entities, which
  is exactly the side whose fixed table is big) and solves once at the end.

- The side with many entities ("stream" mode) keeps the segment layout's
  chunk-scan structure: finalized rows are solved per chunk, an entity
  straddling a chunk boundary has its partial (A, b) carried across.

The reference computes the same normal equations one entity at a time in
EJML (``processors/MFeatureCalculator.java:85-99``); the λ·n_ratings
regularization and float32 accumulation semantics here are identical to
``cfk_tpu.ops.solve`` (the rectangular/segment paths), which the parity
tests assert.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from cfk_tpu.ops.pipeline import prefetch_scan, resolve_overlap
from cfk_tpu.ops.solve import (
    _gram_compute_dtype,
    _match_varying,
    regularized_solve,
    regularized_solve_matrix,
)


_GZ_HOISTED_BUDGET_BYTES = 2 << 30  # accum-mode hoisted gather windows:
# past ~2 GB the duplicate table stops being a rounding error next to the
# [E+1, k, k] accumulator and the per-chunk dynamic_slice path takes over


def default_in_kernel_gather() -> bool:
    """Process-wide default for the in-kernel neighbor gather: fuse the
    per-chunk neighbor-factor gather into the Pallas Gram kernels (the
    ``*_gather_pallas`` variants DMA the indexed table rows straight into
    VMEM), retiring the materialized [C, k] gathered stream — the largest
    measured roofline gap in BENCH_r05 (``vs_gather_roofline``
    1.88–9.94×).  True = gather in-kernel wherever the gates allow
    (``resolve_gather_mode``).  Patchable for A/B measurement
    (``scripts/perf_lab.py --gather xla``, ``bench.py --gather-ab``)
    exactly like ``default_tiled_gram_backend``; per-call
    ``in_kernel_gather=`` and ``ALSConfig.in_kernel_gather`` override it
    explicitly."""
    return True


def resolve_in_kernel_gather(in_kernel_gather) -> bool:
    """Per-call override if given, else the process default."""
    if in_kernel_gather is None:
        return default_in_kernel_gather()
    return bool(in_kernel_gather)


def resolve_gather_mode(in_kernel_gather, backend, stage, entries,
                        meta_words, tile_rows, num_segments, k,
                        block_rows=None) -> str:
    """Static gating of the in-kernel gather — ``"fused"`` or ``"xla"``.

    The logic lives in ``cfk_tpu.plan.registry`` now (ISSUE 9): ONE
    resolver shared by the tiled chunk bodies, the bucketed port, both
    SPMD ring half-steps, and the plan resolver's feasibility gates — and
    it consults the kernel registry's backend availability, so a forced
    ``mosaic_tpu`` outage reroutes the next trace to the emulation
    schedule (same math, bit-identical factors).  This alias keeps every
    existing call site and test import working."""
    from cfk_tpu.plan.registry import resolve_gather_mode as _resolve

    return _resolve(in_kernel_gather, backend, stage, entries, meta_words,
                    tile_rows, num_segments, k, block_rows)


def default_tiled_gram_backend() -> str:
    """Tile-Gram backend: the fused pallas grouped-Gram kernel.

    Measured on the real v5e at the full Netflix shape (rank 64, bf16,
    512k-entry chunks): the multi-tile kernel holds the whole per-chunk
    (A, b) output resident in VMEM, so the [NT, k, k] tile-Gram batch, its
    segment-sum read-back, the zero-fill, and the pre-GEMM layout copy all
    disappear — 1.285 s/iter (XLA backend) → 0.85 s/iter end-to-end.
    Round 2's one-tile-per-grid-step kernel lost this comparison (2.36 vs
    1.97 — overhead-bound); the multi-tile redesign (VERDICT r2 item #1)
    is what made pallas the measured default.  ``gram_backend="xla"``
    (batched GEMM + segment-sum) remains for A/B measurement."""
    return "pallas"


def _entity_gram_chunk(
    fixed_slice, nb, wt, rt, seg, tile_rows, num_segments, backend,
    unit_weights=False, zero_appended=False, carry=None, stage="full",
    pregathered=None, gather="xla",
):
    """One chunk's per-entity Gram/RHS: (A [num_segments, k, k], b [.., k]).

    ``seg`` maps each [tile_rows]-entry tile to its owner (sorted;
    ``num_segments - 1`` = trash).  Rows of segments owning no tile are
    UNSPECIFIED under the pallas backend (never written) — callers must
    route them to trash (stream mode) or mask them (accum mode).

    A zero row is appended to the fixed slice and padding entries index it
    (format-3 blocks), so padding contributes exact zeros BEFORE any weight
    is applied.  ``zero_appended=True`` says the caller already placed that
    zero row (accum mode appends it per SLICE outside the chunk scan — the
    in-body concatenate re-copied the 17 MB slice every chunk, ~25 ms/iter
    in the round-3 profile).  ``unit_weights=True`` (explicit ALS: real
    weights are all 1.0) skips the w·f multiply entirely — measured 0.18
    s/iter of pure elementwise traffic at the full Netflix shape.

    The weighted path (iALS) takes ``wt`` as the **sqrt-reparameterized**
    per-entry weight √aw: the single stream gs = √aw·f (the multiply fuses
    into the producing gather) is used as BOTH Gram operands, so
    A = Σ aw·f fᵀ with the same kernel traffic as the unit path — round
    4's premultiplied second stream (gw = aw·f next to plain g) doubled
    the pipelined input for nothing (``ials_tiled_half_step`` rescales the
    b-coefficients by 1/√aw to compensate).

    ``pregathered`` (the overlap pipelines) hands in the chunk's gathered
    stream ``fz[nb].astype(ct)`` fetched one loop step early
    (``ops.pipeline.prefetch_scan``); the weight multiply and everything
    downstream run here unchanged, so the pipelined result is bit-equal to
    the in-body gather.

    ``gather="fused"`` (gated upstream by ``resolve_gather_mode``;
    stage="full" + pallas backend only) retires the materialized stream
    entirely: ``fixed_slice`` must then be the RAW table (no zero row)
    and ``nb`` indexes it with ``table_rows`` as the virtual zero row;
    the kernel DMAs the rows itself and applies ``wt`` in-register —
    which is also what realizes the padding zero row, so ``wt`` (the 0/1
    mask for the unit-weight path, √aw·mask for iALS) is consumed even
    when ``unit_weights=True``.
    """
    k = fixed_slice.shape[-1]
    g = _gathered_stream(fixed_slice, nb, wt, unit_weights, zero_appended,
                         pregathered, gather=gather)
    if g is None:  # gather == "fused": the kernel DMAs the rows itself
        from cfk_tpu.ops.pallas.gram_kernel import gram_tiles_gather_pallas

        return gram_tiles_gather_pallas(
            fixed_slice, nb, wt, rt, seg, num_segments=num_segments,
            tile_rows=tile_rows, carry=carry,
        )
    ct, prec = _gram_compute_dtype(fixed_slice)
    if stage == "gather":
        # Measurement probe (``tiled_half_step(stage=...)``): stop after
        # the gather (+ the fused √aw multiply where weighted) and fold
        # everything into a scalar so nothing is dead-code eliminated —
        # the full-array reduce is negligible next to the row-slot-bound
        # gather it sinks.
        return jnp.sum(g.astype(jnp.float32)), None
    if backend == "pallas" and 2 * num_segments * k * (k + 1) * 4 > (96 << 20):
        # The kernel keeps the whole (A, b) chunk output resident in VMEM
        # (double-buffered); past ~96 MB it cannot compile.  Dense shapes
        # never get here (full Netflix peaks at ~37 MB), but sparse ones
        # (many distinct entities per chunk) fall back to the XLA
        # segment-sum path instead of a Mosaic OOM.
        backend = "xla"
    if backend == "pallas":
        from cfk_tpu.ops.pallas.gram_kernel import gram_tiles_pallas

        return gram_tiles_pallas(
            g, rt, seg, num_segments=num_segments,
            tile_rows=tile_rows, carry=carry,
        )
    if backend != "xla":
        raise ValueError(f"unknown tiled gram backend {backend!r}")
    gt = g.reshape(-1, tile_rows, k)
    a_t = jnp.einsum(
        "ntk,ntl->nkl", gt, gt,
        preferred_element_type=jnp.float32, precision=prec,
    )
    # rt stays float32: the iALS sqrt-reparameterized b-coefficient
    # c/√(ε-clamped aw) reaches ~1e6·c at zero-strength entries, where a
    # bf16 cast costs ~0.5–1% relative b error (ADVICE r5); accumulation
    # is float32 anyway via preferred_element_type, so only this operand's
    # input rounding was at stake.
    b_t = jnp.einsum(
        "ntk,nt->nk", gt, rt.reshape(-1, tile_rows).astype(jnp.float32),
        preferred_element_type=jnp.float32, precision=prec,
    )
    a = jax.ops.segment_sum(
        a_t, seg, num_segments=num_segments, indices_are_sorted=True
    )
    b = jax.ops.segment_sum(
        b_t, seg, num_segments=num_segments, indices_are_sorted=True
    )
    if carry is not None:
        ca, cb, ci = carry
        a = a.at[0].add(ci * ca)
        b = b.at[0].add(ci * cb)
    return a, b


def _gathered_stream(fixed_slice, nb, wt, unit_weights, zero_appended,
                     pregathered, gather="xla"):
    """The gather prologue both chunk-Gram entries share: fetch the chunk's
    neighbor factors (or accept the pipeline-prefetched stream) and apply
    the sqrt-reparameterized weight — see ``_entity_gram_chunk``.

    ``gather="fused"`` returns None: there is no host-side stream to
    build — the gather-fused Pallas kernels DMA the indexed table rows
    into VMEM themselves (``ops.pallas.gram_kernel`` ``*_gather_pallas``)
    and apply the premultiply in-register; chunk bodies pass the index
    (and weight) chunks through instead of gathered rows."""
    if gather == "fused":
        return None
    k = fixed_slice.shape[-1]
    ct, _ = _gram_compute_dtype(fixed_slice)
    if pregathered is not None:
        g = pregathered  # [C, k], already in ct
    else:
        if zero_appended:
            fz = fixed_slice
        else:
            fz = jnp.concatenate([
                fixed_slice,
                _match_varying(
                    jnp.zeros((1, k), fixed_slice.dtype), fixed_slice
                ),
            ])
        g = fz[nb].astype(ct)  # [C, k]
    if not unit_weights:
        # Sqrt-weighted single stream (see _entity_gram_chunk): the
        # multiply fuses into the producing gather, and everything
        # downstream — kernel operands, probes, both backends — sees one
        # stream, exactly like the unit path.
        g = g * wt.astype(ct)[:, None]
    return g


def _entity_gram_solve_chunk(
    fixed_slice, nb, wt, rt, seg, tile_rows, num_segments, lseg, reg,
    reg_mode, lam, unit_weights=False, zero_appended=False, carry=None,
    pregathered=None, gather="xla", algo=None,
):
    """Fused-epilogue twin of ``_entity_gram_chunk`` + the per-chunk solve.

    Returns (x [num_segments, k], carry_a [k, k], carry_b [k]): the
    chunk's (A, b) batch stays inside the Gram kernel's VMEM residency
    (``gram_solve_tiles_pallas``) where the ridge and the lane-vectorized
    elimination run in place — the split path's [Ec, k, k] HBM write +
    readback for the separate batched solve never happens.  The carry pair
    is the RAW (pre-ridge) partial of the boundary-straddling entity at
    ``lseg`` — exactly the ``a[lseg]``/``b[lseg]`` rows the split scan
    extracts.  Callers gate on ``resolve_fused_chunk_lam`` first (pallas
    backend + pallas solver + rank within the fused elimination cap).

    ``gather="fused"`` additionally keeps the [C, k] neighbor stream out
    of HBM (``gram_solve_tiles_gather_pallas`` — in-kernel DMA gather;
    see ``_entity_gram_chunk``); ``algo`` threads the elimination choice.
    """
    from cfk_tpu.ops.pallas.gram_kernel import (
        gram_solve_tiles_gather_pallas,
        gram_solve_tiles_pallas,
    )

    g = _gathered_stream(fixed_slice, nb, wt, unit_weights, zero_appended,
                         pregathered, gather=gather)
    if g is None:  # gather == "fused"
        return gram_solve_tiles_gather_pallas(
            fixed_slice, nb, wt, rt, seg, reg, lseg,
            num_segments=num_segments, tile_rows=tile_rows,
            reg_mode=reg_mode, lam=lam, carry=carry, algo=algo,
        )
    return gram_solve_tiles_pallas(
        g, rt, seg, reg, lseg, num_segments=num_segments,
        tile_rows=tile_rows, reg_mode=reg_mode, lam=lam, carry=carry,
        algo=algo,
    )


def _chunk_reg(cnt_c, implicit_reg):
    """The fused epilogue's regularizer operand: per-row counts (ALS-WR
    λ·n with the trash row floored at 1 — exactly the cnt_full the split
    path's ``regularized_solve`` sees) or the shared YᵀY+λI matrix
    (iALS).  One definition, so the stream and dense fused paths can
    never diverge on the trash-row floor."""
    if implicit_reg is None:
        return jnp.concatenate([cnt_c, jnp.ones((1,), cnt_c.dtype)])
    return implicit_reg


def resolve_fused_chunk_lam(fused_epilogue, solver, k, num_segments,
                            backend, lam, implicit, algo=None):
    """Static gating of the fused Gram+solve chunk path — the concretized
    λ when legal, None → the split Gram→HBM→solve schedule.

    Like ``resolve_gather_mode``, the logic lives in
    ``cfk_tpu.plan.registry`` (one resolver for the tiled bodies, the
    bucketed port, both ring half-steps, and the plan resolver's gates,
    with kernel-backend availability consulted); this alias keeps the
    existing import surface."""
    from cfk_tpu.plan.registry import resolve_fused_chunk_lam as _resolve

    return _resolve(fused_epilogue, solver, k, num_segments, backend, lam,
                    implicit, algo)


def quantize_tiled_operand(fixed_factors, blk, chunks, table_dtype):
    """Quantize a tiled half-step's gather operand (``ops.quant``).

    Returns (table, blk): the HBM-resident table the chunk bodies gather
    from (f32 identity / bf16 cast / int8 codes) and the block dict with
    the int8 per-row dequant scale FOLDED into the mode's per-entry weight
    stream — the canonical order (``quant.fold_scale`` first, then the one
    ``g = data[nb]·wt`` multiply) every gather path shares, which is what
    keeps the XLA gather, the Mosaic DMA gather, and the emulation twins
    bit-identical for any table dtype.  Mode specifics:

    - stream: the tile-aligned ``weight`` channel (0/1 mask, or √aw·mask
      for iALS) absorbs the scale; ``nb`` already indexes the table with
      F as the zero row.
    - dstream: the stream-aligned ``aweight_dense`` channel absorbs it —
      synthesized as the bare scale stream for explicit ALS, which has no
      weight channel of its own (dense padding indexes the zero row, whose
      appended scale is 0).
    - accum: slice-local indices are rebased to absolute table rows via
      the chunk's clamped window base (the same map ``abs_idx`` applies on
      the fused-gather route), so the fold indexes the true row's scale.
    """
    from cfk_tpu.ops import quant

    td = quant.resolve_table_dtype(table_dtype)
    if td == "float32":
        return fixed_factors, blk
    if td == "bfloat16":
        return fixed_factors.astype(jnp.bfloat16), blk
    data, scale = quant.quantize_table(fixed_factors, "int8")
    blk = dict(blk)
    mode = chunks[1]
    nb = blk["neighbor_idx"]
    if mode == "accum":
        nc, cap, t, h, e_c = tuple(chunks[2:])
        f_rows = fixed_factors.shape[0]
        base = jnp.repeat(blk["chunk_base"].reshape(nc), cap)
        abs_nb = jnp.where(nb < h, base + nb, f_rows)
        blk["weight"] = quant.fold_scale(blk["weight"], scale, abs_nb)
    elif mode == "dstream":
        wt = blk.get("aweight_dense")
        if wt is None:
            wt = jnp.ones(nb.shape, jnp.float32)
        blk["aweight_dense"] = quant.fold_scale(wt, scale, nb)
    else:
        blk["weight"] = quant.fold_scale(blk["weight"], scale, nb)
    return data, blk


def tiled_half_step(
    fixed_factors, blk, chunks, local_entities, lam, *,
    solver="cholesky", implicit_reg=None, stage="full", overlap=None,
    fused_epilogue=None, in_kernel_gather=None, reg_solve_algo=None,
    table_dtype=None, return_chunk_rows=False,
):
    """Mode dispatch shared by the single-device and SPMD trainers.

    ``chunks`` is the static tuple ``("tiled", mode, *statics)`` the layout
    setup emits; ``blk`` the device-array dict of ``TiledBlocks`` fields.

    ``stage`` (static; measurement hook for ``scripts/decompose.py``) stops
    the half-step after a prefix of its pipeline and returns a [1, 1] f32
    sink instead of factors, so each term of an iteration can be timed as
    the LITERAL production ops (VERDICT r4 #4): ``"gather"`` = the per-chunk
    neighbor-factor gather (incl. the weighted premultiply where the
    production path pays it), ``"gram"`` = gather + the fused Gram kernel
    with carry threading, ``"accum"`` (accum mode only) = everything but
    the final solve.  ``"full"`` (default) is the unchanged production path.

    ``table_dtype`` quantizes the gather operand for this half-step
    (``ops.quant``; the solved factors keep the storage dtype): bf16
    halves the gather bytes, int8+per-row-scale quarters them, Gram/solve
    accumulation stays float32 either way.  ``None``/"float32" is
    bit-identical to the pre-quantization path.
    """
    mode = chunks[1]
    st = tuple(chunks[2:])
    fixed_factors, blk = quantize_tiled_operand(
        fixed_factors, blk, chunks, table_dtype
    )
    if return_chunk_rows and mode != "stream":
        # The windowed host-offload driver (cfk_tpu.offload) scatters on
        # the host; only the stream scan's per-chunk solve rows have that
        # shape — accum solves once at the end, dstream could support it
        # but no caller needs it yet.
        raise ValueError(
            f"return_chunk_rows is a stream-mode contract; mode={mode!r}"
        )
    if mode == "accum":
        return als_half_step_tiled_accum(
            fixed_factors, blk["neighbor_idx"], blk["rating"], blk["weight"],
            blk["tile_seg"], blk["chunk_base"], blk["chunk_entity"],
            blk["count"], local_entities, lam,
            statics=st, solver=solver, implicit_reg=implicit_reg,
            stage=stage, overlap=overlap, fused_epilogue=fused_epilogue,
            in_kernel_gather=in_kernel_gather, reg_solve_algo=reg_solve_algo,
        )
    if mode == "dstream":
        return als_half_step_tiled_dense(
            fixed_factors, blk["neighbor_idx"], blk["rating"],
            blk["tile_meta"], blk["chunk_entity"], blk["chunk_count"],
            blk["carry_in"], blk["last_seg"], local_entities, lam,
            statics=st, solver=solver, implicit_reg=implicit_reg,
            aweight_dense=blk.get("aweight_dense"), stage=stage,
            overlap=overlap, fused_epilogue=fused_epilogue,
            in_kernel_gather=in_kernel_gather, reg_solve_algo=reg_solve_algo,
        )
    return als_half_step_tiled(
        fixed_factors, blk["neighbor_idx"], blk["rating"], blk["weight"],
        blk["tile_seg"], blk["chunk_entity"], blk["chunk_count"],
        blk["carry_in"], blk["last_seg"], local_entities, lam,
        statics=st, solver=solver, implicit_reg=implicit_reg, stage=stage,
        overlap=overlap, fused_epilogue=fused_epilogue,
        in_kernel_gather=in_kernel_gather, reg_solve_algo=reg_solve_algo,
        return_chunk_rows=return_chunk_rows,
    )


_SQRT_WEIGHT_EPS = 1e-12  # clamp for α·r = 0 entries: their A-term becomes
# ε·f fᵀ (≪ the λ ≥ 0.01 ridge) while b stays exact — (c/√ε)·(√ε·f) = c·f.


def ials_tiled_half_step(
    fixed_factors, blk, chunks, local_entities, lam, alpha, *,
    gram=None, solver="cholesky", stage="full", overlap=None,
    fused_epilogue=None, in_kernel_gather=None, reg_solve_algo=None,
    table_dtype=None,
):
    """Implicit-feedback (Hu et al. 2008) half-iteration on tiled blocks.

    Same global-Gram trick as ``ops.solve.ials_half_step``: per entity
    A = YᵀY + Σ_obs (c−1)·f fᵀ + λI with c = 1 + α·r.  The per-entry
    A-weight is carried as a **sqrt reparameterization** (round 5): the
    half-steps stream ONE weighted copy gs = √(α·r)·f and compute
    A = gsᵀgs = Σ α·r·f fᵀ exactly, with the b-coefficient rescaled to
    c/√(α·r) so b = Σ (c/√aw)·(√aw·f) = Σ c·f.  Round 4's premultiplied
    gw = α·r·f second stream DOUBLED the Gram kernels' pipelined input
    traffic and (at k = 128) squeezed VMEM — which is what made the dense
    layout measure slower for iALS (VERDICT r4 #3); the reparameterization
    makes the weighted path byte-identical in kernel traffic to the
    unit-weight path (no second stream, no kernel change).  Entries with
    α·r = 0 are kept exact in b by the ε clamp (``_SQRT_WEIGHT_EPS``);
    negative interaction strengths are invalid for iALS — the trainers
    reject them at entry (``models.ials._check_nonnegative_strengths``),
    so the clamp here never sees one on a supported path.  Both tile
    modes work unchanged with the YᵀY + λI term added at solve time via
    ``implicit_reg``.
    """
    k = fixed_factors.shape[-1]
    if gram is None:
        from cfk_tpu.ops import quant
        from cfk_tpu.ops.solve import global_gram

        # YᵀY must sum the SAME dequantized rows the Gram kernels gather
        # (ops.quant.gather_operand_view), or the shared implicit_reg term
        # and the per-entity observed Grams would disagree on what the
        # fixed factors ARE — the quantized-table analog of the subspace
        # score-stream consistency rule.
        gram = global_gram(
            quant.gather_operand_view(fixed_factors, table_dtype)
        )
    reg = gram + lam * jnp.eye(k, dtype=jnp.float32)
    blk = dict(blk)
    if chunks[1] == "dstream" and ("rating_dense" not in blk
                                   or "weight" not in blk):
        raise ValueError(
            "iALS on dense-stream blocks needs the weighted channels "
            "(rating_dense + tile-aligned weight); this dataset was "
            "staged without them — use the iALS device setup "
            "(weighted=True) or rebuild"
        )
    # b-coefficient c·mask, rescaled by 1/√aw from the TILE-ALIGNED
    # channels (rating carries r at valid slots, weight the 1.0 mask; both
    # zero at padding, so rt' is zero there too).
    aw_tile = jnp.sqrt(jnp.maximum(alpha * blk["rating"], _SQRT_WEIGHT_EPS))
    rt_scaled = (1.0 + alpha * blk["rating"]) * blk["weight"] / aw_tile
    if chunks[1] == "dstream":
        # Dense-stream weighted path: the √aw factor multiplies the
        # gathered stream (aweight_dense, STREAM-ALIGNED), fusing into the
        # gather; the kernel then runs its UNIT-weight path on gs.
        blk["rating"] = rt_scaled
        blk["aweight_dense"] = jnp.sqrt(jnp.maximum(
            alpha * blk["rating_dense"], _SQRT_WEIGHT_EPS))
        return tiled_half_step(
            fixed_factors, blk, chunks, local_entities, lam,
            solver=solver, implicit_reg=reg, stage=stage, overlap=overlap,
            fused_epilogue=fused_epilogue,
            in_kernel_gather=in_kernel_gather, reg_solve_algo=reg_solve_algo,
            table_dtype=table_dtype,
        )
    # The ε-clamped √aw is re-masked by the original 0/1 weight channel:
    # at valid entries ×1.0 is exact, and at padding the XLA path's
    # zero-row gather made ×√ε indistinguishable from ×0 anyway (0·√ε =
    # 0·0 = 0, bit-equal) — but the in-kernel gather path uses this
    # weight AS the padding mask (the DMA'd rows are clamped table rows,
    # not zeros), so the mask must survive the reparameterization.
    blk["rating"], blk["weight"] = rt_scaled, aw_tile * blk["weight"]
    return tiled_half_step(
        fixed_factors, blk, chunks, local_entities, lam,
        solver=solver, implicit_reg=reg, stage=stage, overlap=overlap,
        fused_epilogue=fused_epilogue,
        in_kernel_gather=in_kernel_gather, reg_solve_algo=reg_solve_algo,
        table_dtype=table_dtype,
    )


def als_half_step_tiled(
    fixed_factors: jax.Array,  # [F, k] full fixed side
    neighbor_idx: jax.Array,  # [NC·C] int32
    rating: jax.Array,  # [NC·C] f32 (b coefficient; 0 at padding)
    weight: jax.Array,  # [NC·C] f32 (A weight; 0 at padding)
    tile_seg: jax.Array,  # [NC·NT] int32 chunk-relative entity of each tile
    chunk_entity: jax.Array,  # [NC·Ec] shard-local entity row (trash = E_local)
    chunk_count: jax.Array,  # [NC·Ec] full rating count of finalized rows
    carry_in: jax.Array,  # [NC] 1.0 = seg 0 continues the previous chunk
    last_seg: jax.Array,  # [NC] chunk-relative index of the last real segment
    local_entities: int,
    lam: float,
    *,
    statics: tuple[int, int, int, int],  # (NC, C, Ec, T)
    solver: str = "cholesky",
    implicit_reg: jax.Array | None = None,  # [k,k] YᵀY+λI (iALS); None = ALS-WR
    gram_backend: str | None = None,
    stage: str = "full",
    overlap: bool | None = None,
    fused_epilogue: bool | None = None,
    in_kernel_gather: bool | None = None,
    reg_solve_algo: str | None = None,
    return_chunk_rows: bool = False,
) -> jax.Array:
    """Stream-mode tiled half-iteration (the many-entities side).

    Chunk-scan structure and carry semantics match
    ``ops.solve.als_half_step_segment`` exactly; only the Gram accumulation
    differs (fused pallas grouped-Gram kernel / batched tile GEMM +
    segment-sum).  Under the pallas backend, rows of segments owning no
    tile are unwritten garbage; their solves land in the trash row of
    ``out`` (``chunk_entity`` routes non-finalized rows there), so nothing
    real ever reads them.

    With ``overlap`` (the default) the chunk scan is double-buffered
    (``ops.pipeline.prefetch_scan``): chunk c+1's neighbor-factor gather —
    the row-slot-bound phase — is issued before chunk c's Gram+solve
    consume the other buffer, so the gather engine and the MXU run
    concurrently instead of strictly alternating.  Same gathers, same
    per-chunk op order, bit-identical factors (``tests/test_overlap.py``).

    ``fused_epilogue`` (default: on wherever legal — see
    ``resolve_fused_chunk_lam``) solves each chunk's normal equations
    INSIDE the Gram kernel's VMEM residency: the per-chunk [Ec, k, k]
    A-batch never round-trips through HBM, and the scan body consumes
    (x, carry) straight from the fused kernel.

    ``in_kernel_gather`` (default: on wherever legal — see
    ``resolve_gather_mode``) additionally retires the materialized [C, k]
    neighbor stream: the chunk bodies pass the index/weight chunks and
    the kernel DMAs the table rows itself; with overlap the pipelines
    then prefetch the INDEX chunk instead of the gathered one (the
    double-buffering moves inside the kernel).  Factors are bit-identical
    across the knob (tests/test_in_kernel_gather.py).
    """
    backend = gram_backend or default_tiled_gram_backend()
    overlap = resolve_overlap(overlap)
    nc, cap, e_c, t = statics
    k = fixed_factors.shape[-1]
    nt = cap // t
    # int8 tables (ops.quant) carry the per-row dequant scale folded into
    # the weight channel, so the single premultiply that realizes the
    # padding zero row is ALSO the dequantize — the unit-weight shortcut
    # (which skips that multiply on the XLA route) must not fire.
    unit = implicit_reg is None and fixed_factors.dtype != jnp.int8
    fused_lam = (
        resolve_fused_chunk_lam(
            fused_epilogue, solver, k, e_c + 1, backend, lam,
            implicit_reg is not None, reg_solve_algo,
        ) if stage == "full" else None
    )
    gather = resolve_gather_mode(
        in_kernel_gather, backend, stage, cap, nt + 1, t, e_c + 1, k,
    )
    chunks = (
        neighbor_idx.reshape(nc, cap), rating.reshape(nc, cap),
        weight.reshape(nc, cap), tile_seg.reshape(nc, nt),
        chunk_entity.reshape(nc, e_c), chunk_count.reshape(nc, e_c),
        carry_in.reshape(nc), last_seg.reshape(nc),
    )

    if stage != "full":
        if stage not in ("gather", "gram"):
            raise ValueError(f"stream mode has no stage {stage!r}")

        def probe(carry, chunk):
            acc, a0, b0 = carry
            nb_c, rt_c, wt_c, ts_c, ent_c, cnt_c, cin_c, lseg_c = chunk
            if stage == "gather":
                s, _ = _entity_gram_chunk(
                    fixed_factors, nb_c, wt_c, rt_c, ts_c, t, e_c + 1,
                    backend, unit_weights=unit,
                    stage="gather",
                )
                return (acc + s, a0, b0), None
            a, b = _entity_gram_chunk(
                fixed_factors, nb_c, wt_c, rt_c, ts_c, t, e_c + 1, backend,
                unit_weights=unit, carry=(a0, b0, cin_c),
            )
            a1 = lax.dynamic_index_in_dim(a, lseg_c, 0, keepdims=False)
            b1 = lax.dynamic_index_in_dim(b, lseg_c, 0, keepdims=False)
            return (acc + a[0, 0, 0] + b[0, 0], a1, b1), None

        init = jax.tree.map(
            lambda z: _match_varying(z, neighbor_idx),
            (jnp.zeros((), jnp.float32), jnp.zeros((k, k), jnp.float32),
             jnp.zeros((k,), jnp.float32)),
        )
        (acc, _, _), _ = lax.scan(probe, init, chunks)
        return acc.reshape(1, 1)

    def solve_chunk_rows(a, b, cnt_c):
        # The whole batch is solved including the trash row — solving it
        # beats slicing it away, which copied the batch again.  fused=True
        # pins the reg+solve FUSION (one kernel pass, the pre-existing
        # default): the fused_epilogue A/B toggles only the Gram→HBM→solve
        # round-trip, so split and fused chunk factors stay bit-exact and
        # a patched process default (perf_lab --fused off) cannot swap the
        # elimination algorithm under the baseline.
        if implicit_reg is None:
            return regularized_solve(a, b, _chunk_reg(cnt_c, None), lam,
                                     solver, fused=True,
                                     algo=reg_solve_algo)
        return regularized_solve_matrix(a, b, implicit_reg, solver,
                                        fused=True, algo=reg_solve_algo)

    def body(carry, chunk):
        a0, b0 = carry
        nb_c, rt_c, wt_c, ts_c, ent_c, cnt_c, cin_c, lseg_c = chunk
        # Segment 0 may continue the previous chunk's last entity; the
        # carried partial is folded into segment 0 INSIDE the Gram kernel
        # (one fma pass over the resident accumulator) — folding it
        # outside either rewrote the whole [Ec,k,k] batch through HBM
        # (~0.17 ms/chunk) or cost a separate one-system solve per chunk
        # (~0.1 ms/chunk at rank 128).  The non-default gram_backend="xla"
        # A/B path DOES still pay the at[0].add batch rewrite (see
        # _entity_gram_chunk) — acceptable for a measurement-only branch.
        if fused_lam is not None:
            # Fused epilogue: ridge + solve run on the VMEM-resident
            # (A, b); only the solved rows and the raw carry row return.
            x, a1, b1 = _entity_gram_solve_chunk(
                fixed_factors, nb_c, wt_c, rt_c, ts_c, t, e_c + 1, lseg_c,
                _chunk_reg(cnt_c, implicit_reg),
                "diag" if implicit_reg is None else "matrix", fused_lam,
                unit_weights=unit, carry=(a0, b0, cin_c),
                gather=gather, algo=reg_solve_algo,
            )
            return (a1, b1), x[:e_c]
        a, b = _entity_gram_chunk(
            fixed_factors, nb_c, wt_c, rt_c, ts_c, t, e_c + 1, backend,
            unit_weights=unit, carry=(a0, b0, cin_c),
            gather=gather,
        )
        x = solve_chunk_rows(a, b, cnt_c)
        a1 = lax.dynamic_index_in_dim(a, lseg_c, 0, keepdims=False)
        b1 = lax.dynamic_index_in_dim(b, lseg_c, 0, keepdims=False)
        return (a1, b1), x[:e_c]

    init = jax.tree.map(
        lambda z: _match_varying(z, neighbor_idx),
        (
            jnp.zeros((k, k), jnp.float32),
            jnp.zeros((k,), jnp.float32),
        ),
    )
    # Solutions are emitted as stacked scan outputs and scattered ONCE
    # after the loop — carrying the [E+1, k] output buffer through the
    # scan rewrote it copy-on-write every chunk.  Trash-row collisions
    # (every non-finalized position routes to E_local) are harmless:
    # scatter-set keeps one of them and the trash row is dropped below.
    if overlap:
        # Double-buffered: the [cap, k] gather for chunk c+1 is issued
        # before chunk c's Gram/solve; the zero row is appended to the
        # fixed table ONCE (the serial body re-concatenates per chunk —
        # same values either way).  With the in-kernel gather the
        # pipeline prefetches the INDEX chunk instead — the gather itself
        # (and its double-buffering) now lives inside the kernel, so the
        # fetch is one cheap dynamic_slice and on/off stay bit-equal by
        # construction.
        ct, _ = _gram_compute_dtype(fixed_factors)
        if gather == "fused":
            from cfk_tpu.ops.pipeline import index_fetch

            fetch = index_fetch(neighbor_idx, cap)
        else:
            fz = jnp.concatenate([
                fixed_factors,
                _match_varying(
                    jnp.zeros((k,), fixed_factors.dtype)[None], fixed_factors
                ),
            ])

            def fetch(i):
                nb_c = lax.dynamic_slice(neighbor_idx, (i * cap,), (cap,))
                return fz[nb_c].astype(ct)

        def compute(carry, buf, x, _i):
            a0, b0 = carry
            rt_c, wt_c, ts_c, cnt_c, cin_c, lseg_c = x
            nb_c = buf if gather == "fused" else None
            g_cur = None if gather == "fused" else buf
            if fused_lam is not None:
                x_rows, a1, b1 = _entity_gram_solve_chunk(
                    fixed_factors, nb_c, wt_c, rt_c, ts_c, t, e_c + 1,
                    lseg_c, _chunk_reg(cnt_c, implicit_reg),
                    "diag" if implicit_reg is None else "matrix", fused_lam,
                    unit_weights=unit,
                    carry=(a0, b0, cin_c), pregathered=g_cur, gather=gather,
                    algo=reg_solve_algo,
                )
                return (a1, b1), x_rows[:e_c]
            a, b = _entity_gram_chunk(
                fixed_factors, nb_c, wt_c, rt_c, ts_c, t, e_c + 1, backend,
                unit_weights=unit, carry=(a0, b0, cin_c),
                pregathered=g_cur, gather=gather,
            )
            x_rows = solve_chunk_rows(a, b, cnt_c)
            a1 = lax.dynamic_index_in_dim(a, lseg_c, 0, keepdims=False)
            b1 = lax.dynamic_index_in_dim(b, lseg_c, 0, keepdims=False)
            return (a1, b1), x_rows[:e_c]

        _, xs = prefetch_scan(
            fetch, compute, nc, init,
            xs=(chunks[1], chunks[2], chunks[3], chunks[5], chunks[6],
                chunks[7]),
        )
    else:
        _, xs = lax.scan(body, init, chunks)
    if return_chunk_rows:
        # The windowed host-offload driver (cfk_tpu.offload.windowed)
        # scatters these by chunk_entity on the HOST — same values the
        # device scatter below would place, minus the [E, k] buffer.
        return xs.reshape(nc * e_c, k)
    out = _match_varying(
        jnp.zeros((local_entities + 1, k), jnp.float32), neighbor_idx
    )
    out = out.at[chunk_entity.reshape(nc * e_c)].set(xs.reshape(nc * e_c, k))
    return out[:local_entities]


def als_half_step_tiled_dense(
    fixed_factors: jax.Array,  # [F, k] full fixed side
    neighbor_idx: jax.Array,  # [NC·C] int32 DENSE stream (pad8 → zero row)
    rating: jax.Array,  # [NC·NT·T] f32 TILE-ALIGNED b coefficients
    tile_meta: jax.Array,  # [NC·(NG+4·NT)] int32 per-tile window metadata
    chunk_entity: jax.Array,  # [NC·Ec] finalization rows (trash = E_local)
    chunk_count: jax.Array,  # [NC·Ec]
    carry_in: jax.Array,  # [NC]
    last_seg: jax.Array,  # [NC]
    local_entities: int,
    lam: float,
    *,
    statics: tuple[int, int, int, int, int, int, int],  # (NC,C,Ec,T,NT,NG,BG)
    solver: str = "cholesky",
    implicit_reg: jax.Array | None = None,
    gram_backend: str | None = None,
    aweight_dense: jax.Array | None = None,  # [NC·C] per-entry A-weights
    stage: str = "full",
    overlap: bool | None = None,
    fused_epilogue: bool | None = None,
    in_kernel_gather: bool | None = None,
    reg_solve_algo: str | None = None,
) -> jax.Array:
    """Dense-stream tiled half-iteration (the many-entities side, unpadded).

    Identical scan/carry/finalization semantics to ``als_half_step_tiled``;
    the difference is the stream: entries are packed with only 16-row run
    alignment (the XLA gather that feeds each chunk fetches ~nnz rows, not
    ~1.26·nnz — the row-slot-bound gather engine is the iteration's
    binding resource), and the pallas kernel reconstructs [T]-row tiles as
    masked dynamic windows (``gram_tiles_dense_pallas``).  The weighted
    path (iALS: ``implicit_reg`` + ``aweight_dense`` carrying √aw)
    multiplies the single gathered stream (gs = √aw·g, fused into the
    gather) and runs the kernel's unit-weight path on it — see
    ``ials_tiled_half_step`` for the sqrt reparameterization.  ``overlap``
    double-buffers the chunk scan exactly as in ``als_half_step_tiled``
    (the dense gather for chunk c+1 runs under chunk c's Gram/solve)."""
    if implicit_reg is not None and aweight_dense is None:
        raise ValueError(
            "weighted dense-stream half-step needs aweight_dense (the "
            "per-entry A-weights aligned with the gather stream)"
        )
    backend = gram_backend or default_tiled_gram_backend()
    overlap = resolve_overlap(overlap)
    nc, cap, e_c, t, nt, ng, bg = statics
    k = fixed_factors.shape[-1]
    fused_lam = (
        resolve_fused_chunk_lam(
            fused_epilogue, solver, k, e_c + 1, backend, lam,
            implicit_reg is not None, reg_solve_algo,
        ) if stage == "full" else None
    )
    gather = resolve_gather_mode(
        in_kernel_gather, backend, stage, cap, ng + 4 * nt + 1, t,
        e_c + 1, k, block_rows=bg,
    )
    ct, _ = _gram_compute_dtype(fixed_factors)
    if gather != "fused" or stage != "full":
        # The zero-row-appended table only exists for the XLA-gather
        # schedule; the in-kernel gather realizes the zero row in-register
        # (clamp + window mask) and never builds this copy.
        fz = jnp.concatenate([
            fixed_factors,
            _match_varying(jnp.zeros((1, k), fixed_factors.dtype),
                           fixed_factors),
        ])
    chunks = (
        neighbor_idx.reshape(nc, cap), rating.reshape(nc, nt * t),
        tile_meta.reshape(nc, ng + 4 * nt), last_seg.reshape(nc),
        carry_in.reshape(nc), chunk_count.reshape(nc, e_c),
    )
    # The weighted stream channel exists whenever aweight_dense is staged —
    # iALS (√aw), or explicit ALS on an int8 table (the synthesized dequant
    # scale stream, quantize_tiled_operand) — not only under implicit_reg.
    if aweight_dense is not None:
        chunks = chunks + (aweight_dense.reshape(nc, cap),)

    if stage != "full":
        if stage not in ("gather", "gram"):
            raise ValueError(f"dstream mode has no stage {stage!r}")

        def probe(carry, chunk):
            acc, a0, b0 = carry
            nb_c, rt_c, meta_c, lseg_c, cin_c, cnt_c = chunk[:6]
            g = fz[nb_c].astype(ct)
            if aweight_dense is not None:  # sqrt-weighted single stream
                g = g * chunk[6].astype(ct)[:, None]
            if stage == "gather":
                return (acc + jnp.sum(g.astype(jnp.float32)), a0, b0), None
            a, b = gram_tiles_dense_pallas_dispatch(
                g, rt_c, meta_c, num_segments=e_c + 1, tile_rows=t,
                num_tiles=nt, num_groups=ng, block_rows=bg,
                carry=(a0, b0, cin_c), backend=backend,
            )
            a1 = lax.dynamic_index_in_dim(a, lseg_c, 0, keepdims=False)
            b1 = lax.dynamic_index_in_dim(b, lseg_c, 0, keepdims=False)
            return (acc + a[0, 0, 0] + b[0, 0], a1, b1), None

        init = jax.tree.map(
            lambda z: _match_varying(z, neighbor_idx),
            (jnp.zeros((), jnp.float32), jnp.zeros((k, k), jnp.float32),
             jnp.zeros((k,), jnp.float32)),
        )
        (acc, _, _), _ = lax.scan(probe, init, chunks)
        return acc.reshape(1, 1)

    def gram_solve(carry, g, x, nb_c=None):
        # ``g`` is the gathered stream on the XLA-gather schedule; with
        # the in-kernel gather it is None and ``nb_c`` carries the index
        # chunk instead — the kernel DMAs the rows and applies the √aw
        # premultiply (the stream-aligned weight channel) in-register.
        a0, b0 = carry
        rt_c, meta_c, lseg_c, cin_c, cnt_c = x[:5]
        wt_c = x[5] if aweight_dense is not None else None
        if gather != "fused" and wt_c is not None:
            g = g * wt_c.astype(ct)[:, None]  # sqrt-weighted single stream
        if fused_lam is not None:
            # Fused epilogue: the dense kernel solves its VMEM-resident
            # (A, b) in place — no [Ec, k, k] HBM round-trip per chunk.
            from cfk_tpu.ops.pallas.gram_kernel import (
                gram_solve_tiles_dense_gather_pallas,
                gram_solve_tiles_dense_pallas,
            )

            reg_kw = dict(
                num_segments=e_c + 1, tile_rows=t, num_tiles=nt,
                num_groups=ng, block_rows=bg,
                reg_mode="diag" if implicit_reg is None else "matrix",
                lam=fused_lam, carry=(a0, b0, cin_c), algo=reg_solve_algo,
            )
            if gather == "fused":
                x_rows, a1, b1 = gram_solve_tiles_dense_gather_pallas(
                    fixed_factors, nb_c, wt_c, rt_c, meta_c,
                    _chunk_reg(cnt_c, implicit_reg), lseg_c, **reg_kw,
                )
            else:
                x_rows, a1, b1 = gram_solve_tiles_dense_pallas(
                    g, rt_c, meta_c, _chunk_reg(cnt_c, implicit_reg),
                    lseg_c, **reg_kw,
                )
            return (a1, b1), x_rows[:e_c]
        if gather == "fused":
            from cfk_tpu.ops.pallas.gram_kernel import (
                gram_tiles_dense_gather_pallas,
            )

            a, b = gram_tiles_dense_gather_pallas(
                fixed_factors, nb_c, wt_c, rt_c, meta_c,
                num_segments=e_c + 1, tile_rows=t, num_tiles=nt,
                num_groups=ng, block_rows=bg, carry=(a0, b0, cin_c),
            )
        else:
            a, b = gram_tiles_dense_pallas_dispatch(
                g, rt_c, meta_c, num_segments=e_c + 1, tile_rows=t,
                num_tiles=nt, num_groups=ng, block_rows=bg,
                carry=(a0, b0, cin_c), backend=backend,
            )
        # fused=True: same rationale as the stream body's solve_chunk_rows
        # — the A/B axis is the round-trip, not the reg+solve fusion.
        if implicit_reg is None:
            x_rows = regularized_solve(a, b, _chunk_reg(cnt_c, None), lam,
                                       solver, fused=True,
                                       algo=reg_solve_algo)
        else:
            x_rows = regularized_solve_matrix(a, b, implicit_reg, solver,
                                              fused=True,
                                              algo=reg_solve_algo)
        a1 = lax.dynamic_index_in_dim(a, lseg_c, 0, keepdims=False)
        b1 = lax.dynamic_index_in_dim(b, lseg_c, 0, keepdims=False)
        return (a1, b1), x_rows[:e_c]

    init = jax.tree.map(
        lambda z: _match_varying(z, neighbor_idx),
        (
            jnp.zeros((k, k), jnp.float32),
            jnp.zeros((k,), jnp.float32),
        ),
    )
    if overlap:
        # Double-buffered: chunk c+1's dense gather (the iteration's
        # binding resource — see the layout rationale above) is issued
        # before chunk c's Gram/solve; the √aw premultiply stays at
        # compute time so the fetch is a pure gather.  With the in-kernel
        # gather the pipeline prefetches the index chunk instead — the
        # gather (and its double buffer) lives inside the kernel.
        if gather == "fused":
            from cfk_tpu.ops.pipeline import index_fetch

            fetch = index_fetch(neighbor_idx, cap)

            def compute(carry, buf, x, _i):
                return gram_solve(carry, None, x, nb_c=buf)
        else:
            def fetch(i):
                nb_c = lax.dynamic_slice(neighbor_idx, (i * cap,), (cap,))
                return fz[nb_c].astype(ct)

            def compute(carry, buf, x, _i):
                return gram_solve(carry, buf, x)

        _, xs = prefetch_scan(fetch, compute, nc, init, xs=chunks[1:])
    elif gather == "fused":
        _, xs = lax.scan(
            lambda carry, chunk: gram_solve(
                carry, None, chunk[1:], nb_c=chunk[0]
            ),
            init, chunks,
        )
    else:
        _, xs = lax.scan(
            lambda carry, chunk: gram_solve(
                carry, fz[chunk[0]].astype(ct), chunk[1:]
            ),
            init, chunks,
        )
    out = _match_varying(
        jnp.zeros((local_entities + 1, k), jnp.float32), neighbor_idx
    )
    out = out.at[chunk_entity.reshape(nc * e_c)].set(xs.reshape(nc * e_c, k))
    return out[:local_entities]


def gram_tiles_dense_pallas_dispatch(g, rt, meta, *, num_segments, tile_rows,
                                     num_tiles, num_groups, block_rows,
                                     carry, backend):
    """Route to the dense kernel (or its XLA emulation for A/B runs)."""
    from cfk_tpu.ops.pallas.gram_kernel import gram_tiles_dense_pallas

    return gram_tiles_dense_pallas(
        g, rt, meta, num_segments=num_segments, tile_rows=tile_rows,
        num_tiles=num_tiles, num_groups=num_groups, block_rows=block_rows,
        carry=carry, interpret=True if backend == "xla" else None,
    )


def als_half_step_tiled_accum(
    fixed_factors: jax.Array,  # [F, k] full fixed side
    neighbor_idx: jax.Array,  # [NC·C] int32 SLICE-LOCAL indices
    rating: jax.Array,  # [NC·C] f32
    weight: jax.Array,  # [NC·C] f32
    tile_seg: jax.Array,  # [NC·NT] int32 chunk-dense entity rank (trash = Ec)
    chunk_base: jax.Array,  # [NC] int32 table-slice row offset per chunk
    chunk_entity: jax.Array,  # [NC·Ec] shard-local entity of each rank (trash = E_local)
    count: jax.Array,  # [E_local] real rating count (regularizer)
    local_entities: int,
    lam: float,
    *,
    statics: tuple[int, int, int, int, int],  # (NC, C, T, H, Ec)
    solver: str = "cholesky",
    implicit_reg: jax.Array | None = None,
    gram_backend: str | None = None,
    stage: str = "full",
    overlap: bool | None = None,
    fused_epilogue: bool | None = None,
    in_kernel_gather: bool | None = None,
    reg_solve_algo: str | None = None,
) -> jax.Array:
    """Accumulator-mode tiled half-iteration (the few-entities side).

    Entries are sorted by (fixed-table slice, entity); each chunk gathers
    from a ``lax.dynamic_slice`` of H rows (statically small ⇒ the fast
    gather strategy).  Tile Grams first reduce *within the chunk* to its ≤
    Ec distinct entities (high-degree sides average ~90 tiles per entity,
    so 16k tiles collapse to a few hundred rows) and scatter-add into the
    persistent [E+1, k, k] accumulator via the chunk's entity list —
    touching megabytes per chunk instead of rewriting the whole accumulator
    (profiled at 3.6× the traffic).  ``tile_seg`` ranks are chunk-DENSE
    (slicing leaves gaps in the entity sequence, so ranks, not offsets);
    ranks owning no tile keep their unwritten-garbage Gram rows, and their
    ``chunk_entity`` slot routes them to the accumulator's trash row.
    Entities recur across slices, so per-chunk finalization is impossible
    and the solve happens once at the end.  Only legal when E_local·k² fits
    comfortably in HBM; the builder picks this mode exactly when the fixed
    side is the big one, which is also when the solve side is small
    (480k-user table ⇔ 17.7k movies).

    ``overlap`` double-buffers the chunk scan: chunk c+1's window select +
    gather is issued before chunk c's Gram + accumulator scatter-add.

    ``in_kernel_gather`` (default on where legal) retires accum mode's
    whole window machinery for the production stage: slice-local indices
    are rebased to ABSOLUTE table rows (a cheap [C] int32 map — the
    clamped window base comes along as data) and the gather-fused kernel
    DMAs the rows straight from the full table, so neither the hoisted
    duplicate window stack (``gz``, a second resident copy of the fixed
    table) nor the per-chunk window copy is built — in-kernel DMA has no
    analog of XLA's operand-size gather cliff that forced them.
    """
    backend = gram_backend or default_tiled_gram_backend()
    overlap = resolve_overlap(overlap)
    nc, cap, t, h, e_c = statics
    k = fixed_factors.shape[-1]
    nt = cap // t
    # int8 tables: the dequant scale rides the (absolute-index-folded)
    # weight channel, so the weighted multiply must run (see the stream
    # body / quantize_tiled_operand).
    unit = implicit_reg is None and fixed_factors.dtype != jnp.int8
    gather = resolve_gather_mode(
        in_kernel_gather, backend, stage, cap, nt, t, e_c + 1, k,
    )
    chunks = (
        neighbor_idx.reshape(nc, cap), rating.reshape(nc, cap),
        weight.reshape(nc, cap), tile_seg.reshape(nc, nt),
        chunk_base.reshape(nc), chunk_entity.reshape(nc, e_c),
    )

    # Build each slice's [h+1, k] gather window (zero row appended) ONCE,
    # outside the chunk scan — the in-body concatenate re-copied the whole
    # 17 MB slice every chunk (``pad.41``, ~25 ms/iter at full Netflix).
    # Cost of the win: ``gz`` is a second resident copy of the fixed-side
    # table (~61 MB bf16 for the full-Netflix user side) — accepted
    # because accum mode's dominant allocation is the [E+1,k,k]
    # accumulator (~290 MB there) and HBM is 16 GB; revisit before the
    # accumulator side ever grows past HBM/3.
    # Window bases replicate the builder's clamp (`min(s·h, F−h)`,
    # blocks.py) and are static, so the windows are static slices; a chunk
    # finds its window by comparing its base against the static base list
    # (the clamped last base is NOT a multiple of h, so `base // h` would
    # misroute it).
    f_rows = fixed_factors.shape[0]
    n_slices = max(1, -(-f_rows // h))
    bases = [min(s * h, max(f_rows - h, 0)) for s in range(n_slices)]
    # The hoisted window stack is a second resident copy of the fixed
    # table (~61 MB bf16 at full Netflix — fine next to the ~290 MB
    # accumulator).  On corpora where it would stop being a rounding
    # error (> _GZ_HOISTED_BUDGET_BYTES), degrade to the per-chunk
    # dynamic_slice + concat path instead of OOMing: same math, pays the
    # in-body slice copy the hoist was measured to save (~25 ms/iter).
    # The in-kernel gather (gather == "fused", production stage) never
    # builds the windows at all — absolute indices go straight to the
    # kernel's DMA, which has no operand-size gather cliff to dodge.
    gz_bytes = n_slices * (h + 1) * k * fixed_factors.dtype.itemsize
    hoist = gz_bytes <= _GZ_HOISTED_BUDGET_BYTES and gather != "fused"
    if gather != "fused":
        zrow = _match_varying(
            jnp.zeros((1, k), fixed_factors.dtype), fixed_factors
        )
    if hoist:
        gz = jnp.stack([
            jnp.concatenate([
                lax.slice_in_dim(fixed_factors, b, b + h), zrow
            ])
            for b in bases
        ])  # [n_slices, h+1, k]
    bases_arr = _match_varying(
        jnp.asarray(bases, jnp.int32), fixed_factors
    )

    def abs_idx(nb_c, base_c):
        # Slice-local → absolute (gather == "fused"): valid rows offset
        # by the chunk's clamped window base; the slice-local zero row
        # (index h) maps to the table-level virtual zero row (index F)
        # the gather kernels realize in-register.
        return jnp.where(nb_c < h, base_c + nb_c, f_rows)

    def select_window(base_c):
        if hoist:
            s_idx = jnp.sum((base_c >= bases_arr).astype(jnp.int32)) - 1
            # The per-chunk window COPY (dynamic_index of gz, ~9 ms/iter
            # at rank 64) is the cheap side of a measured trade: gathering
            # straight from the flattened [n_slices·(h+1), k] table with a
            # scalar row offset (no copy) regressed 0.71 → 1.67 s/iter —
            # XLA's gather strategy keys on OPERAND size, and the flat
            # table is past the ~34 MB fast-gather cliff even though each
            # chunk only touches one window of it.
            fixed_slice = lax.dynamic_index_in_dim(
                gz, s_idx, 0, keepdims=False
            )
        else:
            fixed_slice = jnp.concatenate([
                lax.dynamic_slice_in_dim(fixed_factors, base_c, h), zrow
            ])
        return fixed_slice

    if stage == "gather":
        def probe(acc, chunk):
            nb_c, rt_c, wt_c, ts_c, base_c, ent_c = chunk
            s, _ = _entity_gram_chunk(
                select_window(base_c), nb_c, wt_c, rt_c, ts_c, t, e_c + 1,
                backend, unit_weights=unit,
                zero_appended=True, stage="gather",
            )
            return acc + s, None

        init = _match_varying(jnp.zeros((), jnp.float32), neighbor_idx)
        acc, _ = lax.scan(probe, init, chunks)
        return acc.reshape(1, 1)
    if stage == "gram":
        def probe(acc, chunk):
            nb_c, rt_c, wt_c, ts_c, base_c, ent_c = chunk
            a, b = _entity_gram_chunk(
                select_window(base_c), nb_c, wt_c, rt_c, ts_c, t, e_c + 1,
                backend, unit_weights=unit,
                zero_appended=True,
            )
            # Sink a row the pallas kernel is GUARANTEED to have written:
            # the owner of the chunk's first tile (ts_c[0] — the accum
            # analog of the stream probe's lseg-indexed a1/b1).  Row 0 is
            # unwritten garbage in all-trash padding chunks, and garbage
            # NaN would poison the probe accumulator (ADVICE r5).
            s0 = ts_c[0]
            a1 = lax.dynamic_index_in_dim(a, s0, 0, keepdims=False)
            b1 = lax.dynamic_index_in_dim(b, s0, 0, keepdims=False)
            return acc + a1[0, 0] + b1[0], None

        init = _match_varying(jnp.zeros((), jnp.float32), neighbor_idx)
        acc, _ = lax.scan(probe, init, chunks)
        return acc.reshape(1, 1)
    if stage not in ("accum", "full"):
        raise ValueError(f"accum mode has no stage {stage!r}")

    def accumulate(carry, a, b, ent_c):
        # Rank rows owning no tile are unwritten garbage under the pallas
        # backend; ent_c routes them (and any NaN they hold) to the trash
        # row, which nothing reads.  The trash segment a[e_c] is dropped.
        acc_a, acc_b = carry
        acc_a = acc_a.at[ent_c].add(a[:e_c])
        acc_b = acc_b.at[ent_c].add(b[:e_c])
        return acc_a, acc_b

    def body(carry, chunk):
        nb_c, rt_c, wt_c, ts_c, base_c, ent_c = chunk
        if gather == "fused":
            a, b = _entity_gram_chunk(
                fixed_factors, abs_idx(nb_c, base_c), wt_c, rt_c, ts_c, t,
                e_c + 1, backend, unit_weights=unit,
                gather=gather,
            )
        else:
            fixed_slice = select_window(base_c)
            a, b = _entity_gram_chunk(
                fixed_slice, nb_c, wt_c, rt_c, ts_c, t, e_c + 1, backend,
                unit_weights=unit, zero_appended=True,
            )
        return accumulate(carry, a, b, ent_c), None

    init = jax.tree.map(
        lambda z: _match_varying(z, neighbor_idx),
        (
            jnp.zeros((local_entities + 1, k, k), jnp.float32),
            jnp.zeros((local_entities + 1, k), jnp.float32),
        ),
    )
    if overlap:
        # Double-buffered: chunk c+1's window select + slice-local gather
        # runs under chunk c's Gram + accumulator scatter-add.  The window
        # bases come from the raw [NC] chunk_base array so the fetch needs
        # no chunk tuple.  With the in-kernel gather the fetch is the
        # absolute-index rebase only (the DMA gather moved into the
        # kernel).
        ct, _ = _gram_compute_dtype(fixed_factors)
        base_flat = chunk_base.reshape(nc)

        def fetch(i):
            base_c = lax.dynamic_index_in_dim(
                base_flat, i, 0, keepdims=False
            )
            nb_c = lax.dynamic_slice(neighbor_idx, (i * cap,), (cap,))
            if gather == "fused":
                return abs_idx(nb_c, base_c)
            return select_window(base_c)[nb_c].astype(ct)

        def compute(carry, buf, x, _i):
            rt_c, wt_c, ts_c, ent_c = x
            if gather == "fused":
                a, b = _entity_gram_chunk(
                    fixed_factors, buf, wt_c, rt_c, ts_c, t, e_c + 1,
                    backend, unit_weights=unit,
                    gather=gather,
                )
            else:
                a, b = _entity_gram_chunk(
                    fixed_factors, None, wt_c, rt_c, ts_c, t, e_c + 1,
                    backend, unit_weights=unit,
                    zero_appended=True, pregathered=buf,
                )
            return accumulate(carry, a, b, ent_c), None

        (acc_a, acc_b), _ = prefetch_scan(
            fetch, compute, nc, init,
            xs=(chunks[1], chunks[2], chunks[3], chunks[5]),
        )
    else:
        (acc_a, acc_b), _ = lax.scan(body, init, chunks)
    if stage == "accum":  # everything but the final solve
        return (acc_a[0, 0, 0] + acc_b[0, 0]).reshape(1, 1)
    # Accum mode's (A, b) lives in HBM ACROSS chunks by design (entities
    # recur across table slices), so there is no per-chunk VMEM residency
    # to solve inside; the fused knob here gates the one fused reg+solve
    # pass over the final accumulator vs the split ridge-add + dispatch
    # (the bench's fused/split A/B axis).
    a, b = acc_a[:local_entities], acc_b[:local_entities]
    if implicit_reg is None:
        return regularized_solve(a, b, count, lam, solver,
                                 fused=fused_epilogue, algo=reg_solve_algo)
    return regularized_solve_matrix(a, b, implicit_reg, solver,
                                    fused=fused_epilogue,
                                    algo=reg_solve_algo)
