"""Bucketed-layout kernel port — the PR 2/4 machinery on the width classes.

BENCH_r05 measured the bucketed layout as the worst remaining roofline gap
(`ialspp_ml25m` 9.94× `vs_gather_roofline`): its half-steps still ran the
original XLA schedule — materialized `fixed[nb]` gather, whole-rectangle
Gram einsum, separate batched solve — while the tiled layout got in-kernel
DMA gathers (PR 4) and the fused Gram+solve epilogue (PR 2).

The port is an ADAPTER, not a new kernel: a width bucket is a [rows, width]
rectangle of power-of-two width, and flattening it with ``tile_rows =
width`` makes it EXACTLY the tiled stream kernels' shape with one tile per
entity — ``seg = arange(rows)``, no chunk-straddling carry.  Per width
class (the ISSUE's "per-width-class grids") the bucket walk then calls

  - ``gram_solve_tiles_gather_pallas``  (gather=fused + fused epilogue:
    scalar-prefetched indices, double-buffered VMEM row DMA, in-VMEM
    ridge + lane-vectorized elimination — neither the gathered stream nor
    the [rows, k, k] A-batch touches HBM), or
  - ``gram_tiles_gather_pallas`` + the one-pass reg+solve kernel (split
    epilogue), or the same pair fed by an XLA-materialized stream
    (gather=xla) — the A/B axes toggle exactly what they toggle in tiled
    land, and factors are bit-identical across both knobs because every
    route runs the canonical ``g = table[nb]·wt`` + per-tile Gram ops
    (CPU CI pins this through the kernels' XLA emulation twins).

One-tile-per-entity also means the emulation twin's per-tile einsum
``ntk,ntl->nkl`` IS the legacy whole-rectangle ``epk,epl->ekl`` — so the
ported f32 explicit path is bit-identical to the pre-port bucketed path on
the emulation route, not merely close.  The implicit (iALS) port uses the
tiled layout's sqrt reparameterization (one gs = √aw·f stream instead of
the asymmetric (c−1)-premultiplied pair), which changes last-bit rounding
vs the legacy formulation — the same accepted trade the tiled iALS path
made in round 5.

Buckets whose width cannot tile (width < 16 — Mosaic's sublane alignment)
or whose flattened piece exceeds the scalar-prefetch SMEM budget keep the
legacy XLA schedule; they are the narrow tail of the byte distribution.

Quantized tables (``ops.quant``): the kernels read the bf16/int8 table
directly, with the int8 per-row dequant scale folded into the premultiply
weight (the canonical order); the legacy fallback consumes the
``gather_operand_view`` (whole-table dequant) so both routes see the same
values.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from cfk_tpu.ops import quant

# VMEM row budget per kernel grid step: group_tiles·width rows double-
# buffered.  4096 rows × k=128 × 4 B × 2 buffers ≈ 4 MB — comfortable
# next to the fused epilogue's scratch.
_GROUP_ROWS = 4096


def bucket_port_supported(rows: int, width: int, k: int) -> bool:
    """Can this width class run the tiled-kernel adapter at all?

    Width must be 16-row-tileable (Mosaic sublane alignment — the same
    gate ``in_kernel_gather_supported`` applies to tile_rows) and one
    single-row piece must fit the scalar-prefetch SMEM budget.  Refused
    classes keep the legacy XLA schedule — same math, the measured-slow
    path — never a compile failure.
    """
    from cfk_tpu.ops.pallas.gram_kernel import in_kernel_gather_supported

    if width < 16 or width % 16:
        return False
    return in_kernel_gather_supported(width, 3, width)


def _sub_rows(rows: int, width: int, k: int, fused: bool,
              algo: str | None) -> int:
    """Rows per kernel call: the largest power-of-two piece whose
    flattened entry count passes the SMEM gate (and whose segment count
    passes the fused epilogue's scratch gate when fused).  The bucket is
    row-padded to a multiple and lax.map'd — each entity is wholly inside
    its own row, so pieces need no cross-piece accumulation."""
    from cfk_tpu.ops.pallas.gram_kernel import (
        fused_gram_solve_supported,
        in_kernel_gather_supported,
    )

    sub = 1
    while True:
        nxt = sub * 2
        if nxt > rows:
            break
        if not in_kernel_gather_supported(nxt * width, nxt + 2, width):
            break
        if fused and not fused_gram_solve_supported(nxt, k, algo):
            break
        sub = nxt
    return sub


def resolve_bucket_modes(fused_epilogue, in_kernel_gather, solver,
                         rows: int, width: int, k: int, lam,
                         algo: str | None) -> tuple[bool, str] | None:
    """Static gating of the ported bucket piece.

    Returns (fused, gather) — ``None`` keeps the legacy XLA schedule.
    Delegates to the ONE shared mode resolver in ``cfk_tpu.plan.registry``
    (``resolve_gather_mode``/``resolve_fused_chunk_lam`` — the same gates
    the tiled chunk bodies and both ring half-steps run, including the
    kernel registry's backend-availability consult): the gather knob picks
    who fetches the rows (kernel DMA vs XLA stream), the fused knob
    whether the ridge+solve runs inside the Gram kernel's VMEM residency
    (pallas solver + a concretizable λ; ``lam=None`` is the iALS matrix
    mode, whose λ rides inside the shared reg matrix).  The duplicated
    copy of these gates this function used to carry is gone (ISSUE 9).
    """
    from cfk_tpu.plan.registry import (
        resolve_fused_chunk_lam,
        resolve_gather_mode,
    )

    if not bucket_port_supported(rows, width, k):
        return None
    gather = resolve_gather_mode(
        in_kernel_gather, "pallas", "full", width, 3, width, 2, k,
    )
    lam_f = resolve_fused_chunk_lam(
        fused_epilogue, solver, k, 1, "pallas",
        0.0 if lam is None else lam, implicit=lam is None, algo=algo,
    )
    return lam_f is not None, gather


def _xla_stream(table, nb_flat, wt_flat):
    """The gather=xla route's materialized stream — the numerically
    identical ops the DMA gather's emulation twin runs (zero-row append,
    gather, cast, single premultiply), so the two gather modes stay
    bit-identical."""
    from cfk_tpu.compat import emulate_in_kernel_gather
    from cfk_tpu.ops.solve import _gram_compute_dtype

    ct, _ = _gram_compute_dtype(table)
    return emulate_in_kernel_gather(table, nb_flat, wt_flat, ct)


def bucket_gram_solve(
    table: jax.Array,  # [F, k] gather table (f32 / bf16 / int8 codes)
    scale: jax.Array | None,  # [F] int8 per-row dequant scales
    nb: jax.Array,  # [rows, width] int32 neighbor indices (< F)
    wt: jax.Array,  # [rows, width] premultiply (mask / √aw·mask)
    rt: jax.Array,  # [rows, width] b-side coefficients (0 at padding)
    reg,  # [rows] counts (diag) or [k, k] shared matrix (iALS)
    *,
    lam: float,
    reg_mode: str,
    solver: str,
    fused: bool,
    gather: str,
    algo: str | None,
) -> jax.Array:
    """One ported width-class piece: flatten to the tile stream, run the
    tiled kernels per sub-piece, return the solved [rows, k] factors."""
    from cfk_tpu.ops.pallas.gram_kernel import (
        gram_solve_tiles_gather_pallas,
        gram_solve_tiles_pallas,
        gram_tiles_gather_pallas,
        gram_tiles_pallas,
    )
    from cfk_tpu.ops.solve import (
        _match_varying,
        regularized_solve,
        regularized_solve_matrix,
    )

    rows, width = nb.shape
    k = table.shape[-1]
    wt = quant.fold_scale(wt, scale, nb)
    sub = _sub_rows(rows, width, k, fused, algo)
    pad = (-rows) % sub
    if pad:
        zrow = lambda x: jnp.pad(x, ((0, pad), (0, 0)))
        nb, wt, rt = zrow(nb), zrow(wt), zrow(rt)
        if reg_mode == "diag":
            reg = jnp.pad(reg, ((0, pad),))
    n_pieces = (rows + pad) // sub
    seg = _match_varying(jnp.arange(sub, dtype=jnp.int32), nb)
    lseg = _match_varying(jnp.asarray(sub - 1, jnp.int32), nb)
    gt = max(1, _GROUP_ROWS // width)
    kw = dict(num_segments=sub, tile_rows=width, group_tiles=gt)

    def piece(args):
        nb_p, wt_p, rt_p, reg_p = args
        nb_f = nb_p.reshape(-1)
        wt_f = wt_p.reshape(-1)
        rt_f = rt_p.reshape(-1)
        if fused:
            if gather == "fused":
                x, _, _ = gram_solve_tiles_gather_pallas(
                    table, nb_f, wt_f, rt_f, seg, reg_p, lseg,
                    reg_mode=reg_mode, lam=lam, algo=algo, **kw,
                )
            else:
                x, _, _ = gram_solve_tiles_pallas(
                    _xla_stream(table, nb_f, wt_f), rt_f, seg, reg_p, lseg,
                    reg_mode=reg_mode, lam=lam, algo=algo, **kw,
                )
            return x
        if gather == "fused":
            a, b = gram_tiles_gather_pallas(
                table, nb_f, wt_f, rt_f, seg, **kw,
            )
        else:
            a, b = gram_tiles_pallas(
                _xla_stream(table, nb_f, wt_f), rt_f, seg, **kw,
            )
        # fused=True pins the one-pass reg+solve kernel (where the solver
        # allows), exactly like the tiled chunk bodies' split path — the
        # fused A/B axis toggles only the Gram→HBM→solve round-trip.
        if reg_mode == "diag":
            return regularized_solve(a, b, reg_p, lam, solver, fused=True,
                                     algo=algo)
        return regularized_solve_matrix(a, b, reg_p, solver, fused=True,
                                        algo=algo)

    if n_pieces == 1:
        return piece((nb, wt, rt, reg))[:rows]
    nb_s = nb.reshape(n_pieces, sub, width)
    wt_s = wt.reshape(n_pieces, sub, width)
    rt_s = rt.reshape(n_pieces, sub, width)
    if reg_mode == "diag":
        reg_s = reg.reshape(n_pieces, sub)
    else:
        reg_s = jnp.broadcast_to(reg, (n_pieces,) + reg.shape)
    x = lax.map(piece, (nb_s, wt_s, rt_s, reg_s))
    return x.reshape(n_pieces * sub, k)[:rows]


_SQRT_WEIGHT_EPS = 1e-12  # the tiled reparameterization's clamp — see
# ops.tiled.ials_tiled_half_step for the exactness argument at aw = 0


def ials_reparam(rt, mk, alpha):
    """The sqrt reparameterization for the implicit port: one weighted
    stream gs = √(α·r)·f (A = Σ α·r·f fᵀ exactly) with the b-coefficient
    rescaled to c/√aw, the ε-clamp keeping aw = 0 entries exact in b, and
    the 0/1 mask re-applied so padding survives the clamp (it is the DMA
    route's padding mask).  Returns (wt, rt_scaled)."""
    aw = jnp.sqrt(jnp.maximum(alpha * rt, _SQRT_WEIGHT_EPS))
    return aw * mk, (1.0 + alpha * rt) * mk / aw
