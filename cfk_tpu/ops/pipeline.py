"""Double-buffered chunk pipelining shared by the streaming half-steps.

Every tiled/bucketed half-iteration streams its work through fixed-size
chunks inside one XLA loop; executed naively, each loop step SERIALIZES its
memory phase (the neighbor-factor gather / chunk operand fetch) against its
compute phase (Gram GEMM + solve), so the gather engine idles during
compute and the MXU idles during the fetch.  ``prefetch_scan`` restructures
the loop as a classic software pipeline: two chunk buffers are alive at any
time, the fetch for chunk ``c+1`` is ISSUED (in program order) before the
compute for chunk ``c`` consumes the other buffer, and XLA's async
scheduler is free to overlap the two — the fetch has no data dependence on
the compute.  The math is unchanged: same fetches, same computes, same
order per chunk, so results are bit-identical to the serial loop
(``tests/test_overlap.py`` pins this).

The same shape serves the ring exchanges in ``cfk_tpu.parallel.spmd``
(there the "fetch" is a ``lax.ppermute`` over ICI), the tiled chunk scans
in ``cfk_tpu.ops.tiled``, and the bucketed chunk walks in
``cfk_tpu.ops.solve.walk_buckets`` / ``cfk_tpu.ops.subspace``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def default_overlap() -> bool:
    """Process-wide default for comm/compute overlap (the production mode).

    Patchable for A/B measurement (``scripts/perf_lab.py --overlap off``)
    the same way the gram/solve backends are; per-call ``overlap=`` and
    ``ALSConfig.overlap`` override it explicitly."""
    return True


def resolve_overlap(overlap) -> bool:
    """Per-call override if given, else the process default."""
    return default_overlap() if overlap is None else bool(overlap)


def index_fetch(flat, cap):
    """A ``prefetch_scan`` fetch that slices chunk ``i``'s [cap] window
    out of a flat array — the in-kernel-gather pipelines' fetch phase.

    When the neighbor gather is fused into the Gram kernels
    (``ops.tiled`` ``in_kernel_gather``), the expensive memory phase the
    pipeline used to hide (the [cap, k] factor gather) moves inside the
    kernel's own DMA double buffer; what the scan prefetches is just the
    index chunk.  Keeping the prefetch_scan structure (rather than
    collapsing to a plain lax.scan) preserves the overlap on/off
    bit-equality contract and keeps the slice itself off the compute
    phase's critical path."""
    def fetch(i):
        return lax.dynamic_slice(flat, (i * cap,), (cap,))

    return fetch


def prefetch_scan(fetch, compute, num_chunks, init, xs=None):
    """Software-pipelined chunk scan with a one-chunk prefetch distance.

    ``fetch(i) -> buf`` produces chunk ``i``'s input buffer (a pytree; the
    expensive memory phase — a big gather, a dynamic slice, a permuted
    block).  ``compute(carry, buf, x, i) -> (carry, y)`` consumes it
    (``x`` is chunk ``i``'s slice of ``xs``, or None).  The schedule is::

        buf0 = fetch(0)                       # prologue
        step i: fetch(i+1)  ||  compute(buf_i)  # double buffer
        (the last step's prefetch index clamps to num_chunks-1; its result
         is dead and XLA removes nothing real with it)

    Returns ``(carry, ys)`` exactly like ``lax.scan`` over the chunks.
    """
    if xs is None:
        xs_leaves = jnp.arange(num_chunks)
        take = lambda s: None
        idx_of = lambda s: s
    else:
        xs_leaves = (jnp.arange(num_chunks), xs)
        take = lambda s: s[1]
        idx_of = lambda s: s[0]

    buf0 = fetch(jnp.asarray(0, jnp.int32))

    def step(carry, scanned):
        buf, inner = carry
        i = idx_of(scanned)
        nxt = fetch(jnp.minimum(i + 1, num_chunks - 1).astype(jnp.int32))
        inner, y = compute(inner, buf, take(scanned), i)
        return (nxt, inner), y

    (_, carry), ys = lax.scan(step, (buf0, init), xs_leaves)
    return carry, ys


def chunk_map(piece, arrs, num_chunks, *, overlap=None):
    """``lax.map(piece, arrs)`` over the leading chunk axis, pipelined.

    ``arrs`` is a tuple of [num_chunks, ...] arrays.  With overlap on, the
    read of chunk ``c+1``'s operands is issued before ``piece`` runs on
    chunk ``c`` (double buffer); with overlap off this is exactly
    ``lax.map`` (the serial reference schedule).  Used by the bucketed
    chunk walks, where ``piece`` is opaque (full iALS solve or a subspace
    sweep) and the operand fetch is the part worth hiding.
    """
    if not resolve_overlap(overlap):
        return lax.map(lambda c: piece(*c), arrs)

    def fetch(i):
        return tuple(
            lax.dynamic_index_in_dim(a, i, 0, keepdims=False) for a in arrs
        )

    def compute(carry, buf, _x, _i):
        return carry, piece(*buf)

    _, ys = prefetch_scan(fetch, compute, num_chunks, init=None)
    return ys
