"""iALS++ subspace optimization — block coordinate descent for implicit ALS.

Implements the optimizer of Rendle et al., "iALS++: Speeding up Matrix
Factorization with Subspace Optimization" (PAPERS.md): instead of solving the
full k×k normal equations per entity per epoch (O(nnz·k² + E·k³)), sweep over
coordinate blocks of size b, solving a b×b subsystem per entity per block
(O(nnz·k + nnz·k·b + E·k·b²) per sweep).  At rank 128 with b=32 this is the
difference between a 2M-FLOP and a 130K-FLOP solve per entity, and the Gram
work drops by k/b — the big-k regime (the BASELINE.md MovieLens-25M rank-128
target) is exactly where it pays.

Math (implicit objective, Hu et al. 2008, preferences 1, confidence
c = 1 + α·r, unobserved weight 1):

    A_u = G + Σ_obs (c−1)·f fᵀ + λI,   b_u = Σ_obs c·f,   G = YᵀY

Block update for coordinate block B with current iterate x:

    A_u[B,B] δ = −g_u[B],   g_u = A_u x − b_u,   x[B] += δ

using  g_u[B] = (x·G)[B] + λ·x[B] + Σ_obs f[B]·((c−1)·s − c),  s = fᵀx.
The per-interaction scores s are computed once per sweep (the O(nnz·k) term)
and updated incrementally after each block: s += f[B]ᵀ δ.

Exactness anchor: with block_size = k, one sweep from ANY iterate x0 gives
x0 + A⁻¹(b − A·x0) = A⁻¹b — bit-for-bit the full iALS solve path's answer
(same Gram assembly, same solver).  ``tests/test_ialspp.py`` pins this.

Each entity's update is independent given (fixed, G), so the sweep
vectorizes over entities exactly like the plain half-steps: one rectangle
for the padded layout, per-width-class rectangles (optionally chunked
through HBM) for the bucketed layout.  The reference has no implicit model
at all (SURVEY.md §2.6); this module is beyond-parity capability.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from cfk_tpu.ops.solve import dispatch_spd_solve


def _sweep_rect(
    fixed: jax.Array,  # [F, k] fixed-side factors
    x: jax.Array,  # [E, k] current own-side iterate (float32)
    neighbor_idx: jax.Array,  # [E, P]
    rating: jax.Array,  # [E, P] raw interaction strengths
    mask: jax.Array,  # [E, P] 1 = real
    lam: float,
    alpha: float,
    gram: jax.Array,  # [k, k] YᵀY over the FULL fixed side
    block_size: int,
    solver: str,
) -> jax.Array:
    """One full sweep over all k/block_size coordinate blocks of a rectangle."""
    k = x.shape[-1]
    if k % block_size != 0:
        raise ValueError(f"rank {k} not divisible by block_size {block_size}")
    f32 = jnp.float32
    x = x.astype(f32)
    conf_m1 = (alpha * rating * mask).astype(f32)  # c−1 at observed, 0 at pad
    c_obs = conf_m1 + mask.astype(f32)  # c at observed, 0 at pad
    gathered = fixed[neighbor_idx].astype(f32) * mask[..., None]
    # Scores s = fᵀx per interaction — once per sweep, then rank-b updates.
    s = jnp.einsum(
        "epk,ek->ep", gathered, x,
        preferred_element_type=f32, precision="highest",
    )
    eye_b = jnp.eye(block_size, dtype=f32)
    for j in range(k // block_size):
        cols = slice(j * block_size, (j + 1) * block_size)
        f_b = gathered[:, :, cols]  # [E, P, b]
        w = conf_m1 * s - c_obs  # [E, P]; pad entries are exactly 0
        g_b = (
            jnp.einsum("ek,kb->eb", x, gram[:, cols],
                       preferred_element_type=f32, precision="highest")
            + lam * x[:, cols]
            + jnp.einsum("epb,ep->eb", f_b, w,
                         preferred_element_type=f32, precision="highest")
        )
        a_bb = (
            gram[cols, cols]
            + lam * eye_b
            + jnp.einsum("ep,epb,epc->ebc", conf_m1, f_b, f_b,
                         preferred_element_type=f32, precision="highest")
        )
        delta = dispatch_spd_solve(a_bb, -g_b, solver)
        x = x.at[:, cols].add(delta)
        s = s + jnp.einsum("epb,eb->ep", f_b, delta,
                           preferred_element_type=f32, precision="highest")
    return x


def ials_pp_half_step(
    fixed: jax.Array,  # [F, k]
    x_prev: jax.Array,  # [E, k] previous own-side factors (warm start)
    neighbor_idx: jax.Array,
    rating: jax.Array,
    mask: jax.Array,
    lam: float,
    alpha: float,
    *,
    gram: jax.Array | None = None,
    block_size: int = 32,
    sweeps: int = 1,
    solver: str = "cholesky",
) -> jax.Array:
    """iALS++ half-iteration over the padded rectangle layout."""
    from cfk_tpu.ops.solve import global_gram

    if gram is None:
        gram = global_gram(fixed)
    for _ in range(sweeps):
        x_prev = _sweep_rect(
            fixed, x_prev, neighbor_idx, rating, mask, lam, alpha, gram,
            block_size, solver,
        )
    return x_prev


def ials_pp_half_step_bucketed(
    fixed: jax.Array,  # [F, k]
    x_prev: jax.Array,  # [local_entities(+pad rows ok), k]
    buckets,  # sequence of dicts {neighbor, rating, mask, entity_local}
    chunk_rows,  # same-length sequence of static ints / None
    local_entities: int,
    lam: float,
    alpha: float,
    *,
    gram: jax.Array | None = None,
    block_size: int = 32,
    sweeps: int = 1,
    solver: str = "cholesky",
) -> jax.Array:
    """iALS++ half-iteration over width-bucketed InBlocks.

    Buckets partition the entities (each rated entity lives in exactly one
    bucket), so the sweep runs independently per bucket rectangle and
    scatters back.  Entities in no bucket (zero interactions) keep their
    previous value — matching the warm-started full-iALS fixpoint, which
    drives such rows to 0 and our inits already start them at 0.
    ``chunk_rows`` streams oversized buckets through HBM like the plain
    bucketed half-step does.
    """
    from cfk_tpu.ops.solve import global_gram, walk_buckets

    if gram is None:
        gram = global_gram(fixed)
    k = fixed.shape[-1]
    out = jnp.zeros((local_entities + 1, k), jnp.float32)
    n = min(x_prev.shape[0], local_entities)
    out = out.at[:n].set(x_prev[:n].astype(jnp.float32))

    def sweep_piece(xb, ni, rt, mk):
        for _ in range(sweeps):
            xb = _sweep_rect(
                fixed, xb, ni, rt, mk, lam, alpha, gram, block_size, solver
            )
        return xb

    out = walk_buckets(
        buckets, chunk_rows,
        lambda blk, cur: (
            cur[blk["entity_local"]], blk["neighbor"], blk["rating"],
            blk["mask"],
        ),
        sweep_piece,
        out,
    )
    return out[:local_entities]
