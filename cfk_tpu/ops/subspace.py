"""Subspace optimization — block coordinate descent for both ALS families.

Implements the optimizer of Rendle et al., "iALS++: Speeding up Matrix
Factorization with Subspace Optimization" (PAPERS.md), plus its direct
explicit-feedback analog for the flagship ALS-WR model (same block
coordinate descent on each entity's quadratic, with λ·n·I regularization
and no global-Gram term): instead of solving the full k×k normal equations
per entity per epoch (O(nnz·k² + E·k³)), sweep over coordinate blocks of
size b, solving a b×b subsystem per entity per block
(O(nnz·k + nnz·k·b + E·k·b²) per sweep).  At rank 128 with b=32 this is the
difference between a 2M-FLOP and a 130K-FLOP solve per entity, and the Gram
work drops by k/b — the big-k regime (the BASELINE.md MovieLens-25M rank-128
target) is exactly where it pays.

Math (implicit objective, Hu et al. 2008, preferences 1, confidence
c = 1 + α·r, unobserved weight 1):

    A_u = G + Σ_obs (c−1)·f fᵀ + λI,   b_u = Σ_obs c·f,   G = YᵀY

Block update for coordinate block B with current iterate x:

    A_u[B,B] δ = −g_u[B],   g_u = A_u x − b_u,   x[B] += δ

using  g_u[B] = (x·G)[B] + λ·x[B] + Σ_obs f[B]·((c−1)·s − c),  s = fᵀx.
The per-interaction scores s are computed once per sweep (the O(nnz·k) term)
and updated incrementally after each block: s += f[B]ᵀ δ.

Exactness anchor: with block_size = k, one sweep from ANY iterate x0 gives
x0 + A⁻¹(b − A·x0) = A⁻¹b — bit-for-bit the full iALS solve path's answer
(same Gram assembly, same solver).  ``tests/test_ialspp.py`` pins this.

Each entity's update is independent given (fixed, G), so the sweep
vectorizes over entities exactly like the plain half-steps: one rectangle
for the padded layout, per-width-class rectangles (optionally chunked
through HBM) for the bucketed layout.  The reference has no implicit model
at all (SURVEY.md §2.6); this module is beyond-parity capability.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from cfk_tpu.ops.solve import (
    regularized_solve,
    regularized_solve_matrix,
)


def _sweep_gather(fixed, scale, neighbor_idx, maskf, in_kernel_gather):
    """The sweep's gathered rectangle ``[E, P, k]`` — the ONE place the
    fixed-side rows enter the sweep, so the Gram blocks, the b-side, AND
    the per-interaction score stream all read the same values.

    With ``in_kernel_gather`` (default on) the rows are row-DMA'd by the
    Pallas stream producer (``gather_rows_pallas`` — scalar-prefetched
    indices, double-buffered VMEM scratch; interpret/old-jax routes run
    the bit-identical XLA twin), retiring the operand-size-cliffed XLA
    gather; off, the same canonical ops run as plain XLA.  For quantized
    tables (``ops.quant``) the per-row dequant scale is folded into the
    mask weight FIRST, so the single premultiply is also the dequantize —
    the score stream therefore sees exactly the dequantized values the
    kernels read (recomputing scores from the f32 master factors would
    make the fallback and kernel paths disagree bit-for-bit).
    """
    from cfk_tpu.ops import quant
    from cfk_tpu.ops.tiled import resolve_in_kernel_gather

    e, p = neighbor_idx.shape
    k = fixed.shape[-1]
    wt = quant.fold_scale(maskf, scale, neighbor_idx)
    if resolve_in_kernel_gather(in_kernel_gather):
        from cfk_tpu.ops.pallas.gram_kernel import gather_rows_pallas

        g = gather_rows_pallas(
            fixed, neighbor_idx.reshape(-1), wt.reshape(-1),
            out_dtype=jnp.float32,
        )
        return g.reshape(e, p, k)
    return fixed[neighbor_idx].astype(jnp.float32) * wt[..., None]


def _sweep_rect(
    fixed: jax.Array,  # [F, k] fixed-side gather table (f32/bf16/int8)
    x: jax.Array,  # [E, k] current own-side iterate (float32)
    neighbor_idx: jax.Array,  # [E, P]
    rating: jax.Array,  # [E, P] raw interaction strengths
    mask: jax.Array,  # [E, P] 1 = real
    lam: float,
    alpha: float,
    gram: jax.Array | None,  # [k, k] YᵀY over the FULL fixed side (implicit)
    block_size: int,
    solver: str,
    count: jax.Array | None = None,  # [E] rating counts (explicit: λ·n·I reg)
    scale: jax.Array | None = None,  # [F] int8 per-row dequant scales
    in_kernel_gather: bool | None = None,
    fused_epilogue: bool | None = None,
    reg_solve_algo: str | None = None,
) -> jax.Array:
    """One full sweep over all k/block_size coordinate blocks of a rectangle.

    Implicit mode (``gram`` given): per entity A = G + Σ(c−1)ffᵀ + λI,
    b = Σ c·f with c = 1 + α·r.  Explicit mode (``count`` given): ALS-WR's
    A = Σ ffᵀ + λ·n·I, b = Σ r·f — no global-Gram term (unobserved cells
    don't enter the explicit objective).  Either way the block update is
    A[B,B]δ = −g[B], g = A·x − b, with the per-interaction scores s = fᵀx
    computed once and rank-b updated after every block.

    The b×b subsystems route through the fused reg+solve dispatchers
    (``regularized_solve{,_matrix}``): the shared regularizer block
    (G[B,B]+λI, or λ·n·I diag) is applied INSIDE the lane-vectorized
    elimination kernel where the pallas solver is active — the b×b blocks
    sit far below the elimination's rank cap (LU 128 / GJ 64), which is
    what makes iALS++ an even better fit for the fused epilogue than the
    full-rank solves.  On the cholesky backend the dispatcher's split
    add + solve is the bit-identical pre-port computation (f32 adds
    commute), so the default CPU path is unchanged.
    """
    implicit = gram is not None
    if implicit == (count is not None):
        raise ValueError("exactly one of gram (implicit) / count (explicit)")
    k = x.shape[-1]
    if k % block_size != 0:
        raise ValueError(f"rank {k} not divisible by block_size {block_size}")
    f32 = jnp.float32
    x = x.astype(f32)
    maskf = mask.astype(f32)
    gathered = _sweep_gather(fixed, scale, neighbor_idx, maskf,
                             in_kernel_gather)
    if implicit:
        conf_m1 = (alpha * rating).astype(f32) * maskf  # c−1 obs, 0 pad
        c_obs = conf_m1 + maskf  # c at observed, 0 at pad
    else:
        # ALS-WR weighted ridge: λ·n per entity, floored at λ·1 for
        # all-padding rows (same floor as regularized_solve).
        reg_n = lam * jnp.maximum(count.astype(f32), 1.0)  # [E]
    # Scores s = fᵀx per interaction — once per sweep, then rank-b updates.
    s = jnp.einsum(
        "epk,ek->ep", gathered, x,
        preferred_element_type=f32, precision="highest",
    )
    eye_b = jnp.eye(block_size, dtype=f32)
    for j in range(k // block_size):
        cols = slice(j * block_size, (j + 1) * block_size)
        f_b = gathered[:, :, cols]  # [E, P, b]
        if implicit:
            w = conf_m1 * s - c_obs  # [E, P]; pad entries are exactly 0
            g_b = (
                jnp.einsum("ek,kb->eb", x, gram[:, cols],
                           preferred_element_type=f32, precision="highest")
                + lam * x[:, cols]
                + jnp.einsum("epb,ep->eb", f_b, w,
                             preferred_element_type=f32, precision="highest")
            )
            a_obs = jnp.einsum("ep,epb,epc->ebc", conf_m1, f_b, f_b,
                               preferred_element_type=f32,
                               precision="highest")
            delta = regularized_solve_matrix(
                a_obs, -g_b, gram[cols, cols] + lam * eye_b, solver,
                fused=fused_epilogue, algo=reg_solve_algo,
            )
        else:
            w = (s - rating.astype(f32)) * maskf  # residual at observed
            g_b = (
                reg_n[:, None] * x[:, cols]
                + jnp.einsum("epb,ep->eb", f_b, w,
                             preferred_element_type=f32, precision="highest")
            )
            a_obs = jnp.einsum("epb,epc->ebc", f_b, f_b,
                               preferred_element_type=f32,
                               precision="highest")
            delta = regularized_solve(
                a_obs, -g_b, count, lam, solver,
                fused=fused_epilogue, algo=reg_solve_algo,
            )
        x = x.at[:, cols].add(delta)
        s = s + jnp.einsum("epb,eb->ep", f_b, delta,
                           preferred_element_type=f32, precision="highest")
    return x


def als_pp_half_step(
    fixed: jax.Array,  # [F, k]
    x_prev: jax.Array,  # [E, k] previous own-side factors (warm start)
    neighbor_idx: jax.Array,
    rating: jax.Array,
    mask: jax.Array,
    count: jax.Array,  # [E] rating counts (ALS-WR λ·n·I)
    lam: float,
    *,
    block_size: int = 32,
    sweeps: int = 1,
    solver: str = "cholesky",
    in_kernel_gather: bool | None = None,
    fused_epilogue: bool | None = None,
    reg_solve_algo: str | None = None,
    table_dtype: str | None = None,
) -> jax.Array:
    """Explicit ALS-WR half-iteration by subspace sweeps (padded layout)."""
    from cfk_tpu.ops import quant

    data, scale = quant.quantize_table(fixed, table_dtype)
    for _ in range(sweeps):
        x_prev = _sweep_rect(
            data, x_prev, neighbor_idx, rating, mask, lam, 0.0, None,
            block_size, solver, count=count, scale=scale,
            in_kernel_gather=in_kernel_gather, fused_epilogue=fused_epilogue,
            reg_solve_algo=reg_solve_algo,
        )
    return x_prev


def _warm_bucket_walk(
    k, x_prev, buckets, chunk_rows, local_entities, bucket_keys, sweep_piece,
    overlap=None,
):
    """Warm-started bucket scatter shared by both families' bucketed sweeps.

    Seeds the output (with the trash row) from ``x_prev``, walks every
    bucket extracting the current factor rows plus ``bucket_keys`` arrays,
    runs ``sweep_piece`` on each piece, and scatters back.  Entities in no
    bucket (zero interactions) keep their previous value — the warm-started
    fixpoint for them is 0 and both trainers start them at 0.  ``overlap``
    double-buffers chunked buckets (chunk c+1's operand fetch under chunk
    c's sweep — ``ops.pipeline``), the default.
    """
    from cfk_tpu.ops.solve import walk_buckets

    out = jnp.zeros((local_entities + 1, k), jnp.float32)
    n = min(x_prev.shape[0], local_entities)
    out = out.at[:n].set(x_prev[:n].astype(jnp.float32))
    out = walk_buckets(
        buckets, chunk_rows,
        lambda blk, cur: (cur[blk["entity_local"]],)
        + tuple(blk[key] for key in bucket_keys),
        sweep_piece,
        out,
        overlap=overlap,
    )
    return out[:local_entities]


def als_pp_half_step_bucketed(
    fixed: jax.Array,  # [F, k]
    x_prev: jax.Array,  # [local_entities, k]
    buckets,  # sequence of dicts {neighbor, rating, mask, count, entity_local}
    chunk_rows,
    local_entities: int,
    lam: float,
    *,
    block_size: int = 32,
    sweeps: int = 1,
    solver: str = "cholesky",
    overlap: bool | None = None,
    in_kernel_gather: bool | None = None,
    fused_epilogue: bool | None = None,
    reg_solve_algo: str | None = None,
    table_dtype: str | None = None,
) -> jax.Array:
    """Explicit ALS-WR half-iteration by subspace sweeps over width buckets."""
    from cfk_tpu.ops import quant

    data, scale = quant.quantize_table(fixed, table_dtype)

    def sweep_piece(xb, ni, rt, mk, cnt):
        for _ in range(sweeps):
            xb = _sweep_rect(
                data, xb, ni, rt, mk, lam, 0.0, None, block_size, solver,
                count=cnt, scale=scale, in_kernel_gather=in_kernel_gather,
                fused_epilogue=fused_epilogue, reg_solve_algo=reg_solve_algo,
            )
        return xb

    return _warm_bucket_walk(
        fixed.shape[-1], x_prev, buckets, chunk_rows, local_entities,
        ("neighbor", "rating", "mask", "count"), sweep_piece,
        overlap=overlap,
    )


def ials_pp_half_step(
    fixed: jax.Array,  # [F, k]
    x_prev: jax.Array,  # [E, k] previous own-side factors (warm start)
    neighbor_idx: jax.Array,
    rating: jax.Array,
    mask: jax.Array,
    lam: float,
    alpha: float,
    *,
    gram: jax.Array | None = None,
    block_size: int = 32,
    sweeps: int = 1,
    solver: str = "cholesky",
    in_kernel_gather: bool | None = None,
    fused_epilogue: bool | None = None,
    reg_solve_algo: str | None = None,
    table_dtype: str | None = None,
) -> jax.Array:
    """iALS++ half-iteration over the padded rectangle layout."""
    from cfk_tpu.ops import quant
    from cfk_tpu.ops.solve import global_gram

    data, scale = quant.quantize_table(fixed, table_dtype)
    if gram is None:
        # YᵀY over the SAME dequantized rows the sweep gathers — see
        # quant.gather_operand_view.
        gram = global_gram(quant.dequantize_table(data, scale))
    for _ in range(sweeps):
        x_prev = _sweep_rect(
            data, x_prev, neighbor_idx, rating, mask, lam, alpha, gram,
            block_size, solver, scale=scale,
            in_kernel_gather=in_kernel_gather, fused_epilogue=fused_epilogue,
            reg_solve_algo=reg_solve_algo,
        )
    return x_prev


def ials_pp_half_step_bucketed(
    fixed: jax.Array,  # [F, k]
    x_prev: jax.Array,  # [local_entities(+pad rows ok), k]
    buckets,  # sequence of dicts {neighbor, rating, mask, entity_local}
    chunk_rows,  # same-length sequence of static ints / None
    local_entities: int,
    lam: float,
    alpha: float,
    *,
    gram: jax.Array | None = None,
    block_size: int = 32,
    sweeps: int = 1,
    solver: str = "cholesky",
    overlap: bool | None = None,
    in_kernel_gather: bool | None = None,
    fused_epilogue: bool | None = None,
    reg_solve_algo: str | None = None,
    table_dtype: str | None = None,
) -> jax.Array:
    """iALS++ half-iteration over width-bucketed InBlocks.

    Buckets partition the entities (each rated entity lives in exactly one
    bucket), so the sweep runs independently per bucket rectangle and
    scatters back; ``chunk_rows`` streams oversized buckets through HBM like
    the plain bucketed half-step does.  The per-width-class sweeps gather
    by in-kernel row DMA and solve their b×b subsystems through the fused
    reg+solve dispatchers (see ``_sweep_rect``); ``table_dtype`` quantizes
    the HBM gather table (``ops.quant``).
    """
    from cfk_tpu.ops import quant
    from cfk_tpu.ops.solve import global_gram_blocked

    data, scale = quant.quantize_table(fixed, table_dtype)
    if gram is None:
        # Blocked (not whole-einsum) so the out-of-core Gram pass can
        # replay the identical reduction — see global_gram_blocked.
        gram = global_gram_blocked(quant.dequantize_table(data, scale))

    def sweep_piece(xb, ni, rt, mk):
        for _ in range(sweeps):
            xb = _sweep_rect(
                data, xb, ni, rt, mk, lam, alpha, gram, block_size, solver,
                scale=scale, in_kernel_gather=in_kernel_gather,
                fused_epilogue=fused_epilogue, reg_solve_algo=reg_solve_algo,
            )
        return xb

    return _warm_bucket_walk(
        fixed.shape[-1], x_prev, buckets, chunk_rows, local_entities,
        ("neighbor", "rating", "mask"), sweep_piece,
        overlap=overlap,
    )
