// cfk_broker — native partitioned-log broker server for cfk_tpu.
//
// The reference's L0 is a Kafka broker (dev/docker-compose.yaml:18-31): a
// network service holding partitioned, offset-addressed, durable record
// logs.  This is that role as a native component of this framework: a TCP
// server speaking a small length-prefixed binary protocol, backed by the
// SAME on-disk segment format as cfk_tpu/transport/filelog.py (topic
// directory + meta.json + pNNNNN.log files of big-endian int32-key /
// uint32-length frames, torn trailing frames truncated on reopen) — so a
// data directory written by the broker can be reopened by FileBroker and
// vice versa.
//
// Concurrency: thread-per-connection, one global mutex over broker state.
// Appends and in-memory reads are O(1)/O(records) under the lock; this is a
// durable-ingest/checkpoint endpoint (SURVEY.md §2.6: the compute fabric is
// XLA collectives over ICI, NOT this), so contention is a non-goal.
//
// Protocol (all integers big-endian):
//   request  := u32 body_len ‖ u8 opcode ‖ payload
//   response := u32 body_len ‖ u8 status ‖ payload
//     status 0 = OK, 1 = error (payload: u16 len ‖ utf-8 message)
//   opcodes:
//     1 CREATE_TOPIC  name, u32 num_partitions            → —
//     2 PRODUCE_BATCH name, u32 n, n×{i32 partition(-1 = key mod N),
//                       i32 key, u32 value_len, value}    → u64 end_offset
//     3 FETCH         name, u32 partition, u64 start_offset,
//                       u32 max_records, u32 max_bytes    → u64 log_end,
//                       u32 n, n×{i32 key, u32 value_len, value}
//     4 NUM_PARTITIONS name                               → u32
//     5 END_OFFSET    name, u32 partition                 → u64
//     6 DELETE_TOPIC  name                                → —
//     7 PING                                              → —
//     8 LIST_TOPICS                                       → u32 n, n×name
//   name := u16 len ‖ utf-8 bytes
//
// Usage: cfk_broker PORT [DATA_DIR] [BIND_ADDR]
//   PORT 0 picks an ephemeral port.  With no DATA_DIR the logs are
//   memory-only (the InMemoryBroker behavior, reachable over TCP).
//   BIND_ADDR defaults to 127.0.0.1; pass 0.0.0.0 to accept cross-host
//   clients (DATA_DIR "" selects memory-only when a bind addr is needed).
//   Prints "CFK_BROKER LISTENING <port>" on stdout once accepting
//   connections.

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <unistd.h>

#include <cerrno>
#include <csignal>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <dirent.h>
#include <map>
#include <memory>
#include <mutex>
#include <stdexcept>
#include <string>
#include <thread>
#include <vector>

namespace {

constexpr uint32_t kMaxBodyLen = 64u << 20;  // 64 MiB request/response cap
constexpr int kFrameHeader = 8;              // i32 key + u32 value_len

// -- big-endian helpers ------------------------------------------------------

void put_u16(std::string& b, uint16_t v) {
  b.push_back(char(v >> 8));
  b.push_back(char(v));
}
void put_u32(std::string& b, uint32_t v) {
  for (int s = 24; s >= 0; s -= 8) b.push_back(char(v >> s));
}
void put_u64(std::string& b, uint64_t v) {
  for (int s = 56; s >= 0; s -= 8) b.push_back(char(v >> s));
}
void put_i32(std::string& b, int32_t v) { put_u32(b, uint32_t(v)); }

struct Reader {
  const uint8_t* p;
  const uint8_t* end;
  bool ok = true;
  bool need(size_t n) {
    if (size_t(end - p) < n) ok = false;
    return ok;
  }
  uint16_t u16() {
    if (!need(2)) return 0;
    uint16_t v = (uint16_t(p[0]) << 8) | p[1];
    p += 2;
    return v;
  }
  uint32_t u32() {
    if (!need(4)) return 0;
    uint32_t v = (uint32_t(p[0]) << 24) | (uint32_t(p[1]) << 16) |
                 (uint32_t(p[2]) << 8) | p[3];
    p += 4;
    return v;
  }
  uint64_t u64() {
    uint64_t hi = u32();
    return (hi << 32) | u32();
  }
  int32_t i32() { return int32_t(u32()); }
  std::string str(size_t n) {
    if (!need(n)) return {};
    std::string s(reinterpret_cast<const char*>(p), n);
    p += n;
    return s;
  }
  std::string name() { return str(u16()); }
};

// -- log storage -------------------------------------------------------------

struct PartitionLog {
  // Byte offset of the start of each record's frame within `bytes` (memory
  // mode) or the segment file (durable mode); count = positions.size().
  std::vector<uint64_t> positions;
  std::string bytes;         // memory mode: the whole log
  FILE* file = nullptr;      // durable mode: append handle
  FILE* read_file = nullptr; // durable mode: cached fetch handle
  uint64_t file_len = 0;     // valid byte length of the segment file
};

struct Topic {
  uint32_t num_partitions = 0;
  std::vector<PartitionLog> parts;
};

struct BrokerError : std::runtime_error {
  using std::runtime_error::runtime_error;
};

class Broker {
 public:
  explicit Broker(std::string data_dir) : data_dir_(std::move(data_dir)) {
    if (!data_dir_.empty()) recover();
  }

  void create_topic(const std::string& name, uint32_t nparts) {
    std::lock_guard<std::mutex> g(mu_);
    if (nparts < 1) throw BrokerError("num_partitions must be >= 1");
    if (topics_.count(name)) throw BrokerError("topic already exists: " + name);
    if (name.empty() || name[0] == '.' ||
        name.find('/') != std::string::npos)
      throw BrokerError("invalid topic name: " + name);
    Topic t;
    t.num_partitions = nparts;
    t.parts.resize(nparts);
    if (!data_dir_.empty()) {
      std::string dir = data_dir_ + "/" + name;
      if (::mkdir(dir.c_str(), 0777) != 0 && errno != EEXIST)
        throw BrokerError("mkdir failed: " + dir);
      write_meta(dir, nparts);
      for (uint32_t p = 0; p < nparts; ++p) open_segment(t, name, p);
    }
    topics_.emplace(name, std::move(t));
  }

  void delete_topic(const std::string& name) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = topics_.find(name);
    if (it == topics_.end()) return;
    for (auto& part : it->second.parts) {
      if (part.file) std::fclose(part.file);
      if (part.read_file) std::fclose(part.read_file);
    }
    if (!data_dir_.empty()) {
      std::string dir = data_dir_ + "/" + name;
      for (uint32_t p = 0; p < it->second.num_partitions; ++p)
        ::unlink(log_path(dir, p).c_str());
      ::unlink((dir + "/meta.json").c_str());
      ::rmdir(dir.c_str());
    }
    topics_.erase(it);
  }

  // Returns the end offset of the LAST partition appended to.
  uint64_t produce_batch(const std::string& name, Reader& r, uint32_t n) {
    std::lock_guard<std::mutex> g(mu_);
    Topic& t = find(name);
    // Validate the WHOLE batch before appending anything: a rejected
    // request must append nothing, so the client can safely re-buffer and
    // retry (all-or-nothing — never a committed prefix the producer
    // believes failed).
    {
      Reader check = r;
      for (uint32_t i = 0; i < n; ++i) {
        int32_t partition = check.i32();
        int32_t key = check.i32();
        uint32_t vlen = check.u32();
        if (!check.need(vlen)) throw BrokerError("truncated produce batch");
        check.p += vlen;
        if (partition < 0 && key < 0)
          throw BrokerError(
              "negative key requires an explicit partition (control records "
              "are routed explicitly, like the reference's EOF fan-out)");
        if (partition >= 0 && uint32_t(partition) >= t.num_partitions)
          throw BrokerError("partition out of range");
      }
    }
    // Snapshot every partition's committed extent so a mid-batch append
    // failure (disk full) can roll the whole batch back — the client treats
    // a rejected batch as not-appended and re-buffers it, so a committed
    // prefix would be served twice after a retry.
    std::vector<std::pair<size_t, uint64_t>> before(t.num_partitions);
    for (uint32_t p = 0; p < t.num_partitions; ++p) {
      PartitionLog& log = t.parts[p];
      before[p] = {log.positions.size(),
                   log.file ? log.file_len : log.bytes.size()};
    }
    uint64_t last_end = 0;
    try {
      for (uint32_t i = 0; i < n; ++i) {
        int32_t partition = r.i32();
        int32_t key = r.i32();
        uint32_t vlen = r.u32();
        const char* value = reinterpret_cast<const char*>(r.p);
        r.p += vlen;
        if (partition < 0)
          partition = int32_t(uint32_t(key) % t.num_partitions);
        PartitionLog& log = t.parts[partition];
        std::string frame;
        frame.reserve(kFrameHeader + vlen);
        put_i32(frame, key);
        put_u32(frame, vlen);
        frame.append(value, vlen);
        if (log.file) {
          if (std::fwrite(frame.data(), 1, frame.size(), log.file) !=
              frame.size())
            throw BrokerError("append failed (disk full?)");
          log.positions.push_back(log.file_len);
          log.file_len += frame.size();
        } else if (data_dir_.empty()) {
          log.positions.push_back(log.bytes.size());
          log.bytes.append(frame);
        } else {
          throw BrokerError("partition segment unavailable");
        }
        last_end = log.positions.size();
      }
    } catch (...) {
      rollback(t, name, before);
      throw;
    }
    // One flush per batch, not per record (the durability contract is the
    // same page-cache one as FileBroker(fsync=False); torn tails recover).
    // A failed flush means indexed bytes never reached the file — roll the
    // batch back and reject it rather than ack records a FETCH or restart
    // recovery would not see.
    bool flush_ok = true;
    for (auto& part : t.parts)
      if (part.file && std::fflush(part.file) != 0) flush_ok = false;
    if (!flush_ok) {
      rollback(t, name, before);
      throw BrokerError("flush failed (disk full?)");
    }
    return last_end;
  }

  void fetch(const std::string& name, uint32_t partition, uint64_t start,
             uint32_t max_records, uint32_t max_bytes, std::string& out) {
    std::lock_guard<std::mutex> g(mu_);
    Topic& t = find(name);
    if (partition >= t.num_partitions)
      throw BrokerError("partition out of range");
    PartitionLog& log = t.parts[partition];
    uint64_t end = log.positions.size();
    put_u64(out, end);
    size_t count_at = out.size();
    put_u32(out, 0);  // patched below
    uint32_t n = 0;
    if (log.file) std::fflush(log.file);
    // Reads go through a cached per-partition descriptor (opened once, kept
    // until topic deletion) — no fopen/fclose per FETCH under the lock.
    if (log.file && !log.read_file) {
      log.read_file = std::fopen(
          log_path(data_dir_ + "/" + name, partition).c_str(), "rb");
      if (!log.read_file) throw BrokerError("cannot open segment for read");
    }
    FILE* rf = log.read_file;
    for (uint64_t off = start; off < end; ++off, ++n) {
      if (n >= max_records) break;
      uint64_t pos = log.positions[off];
      uint64_t frame_end =
          (off + 1 < end) ? log.positions[off + 1]
                          : (log.file ? log.file_len : log.bytes.size());
      uint64_t flen = frame_end - pos;
      if (n > 0 && out.size() + flen > max_bytes) break;
      if (log.file) {
        size_t prev = out.size();
        out.resize(prev + flen);
        if (std::fseek(rf, long(pos), SEEK_SET) != 0 ||
            std::fread(&out[prev], 1, flen, rf) != flen)
          throw BrokerError("segment read failed");
      } else {
        out.append(log.bytes, pos, flen);
      }
    }
    out[count_at + 0] = char(n >> 24);
    out[count_at + 1] = char(n >> 16);
    out[count_at + 2] = char(n >> 8);
    out[count_at + 3] = char(n);
  }

  uint32_t num_partitions(const std::string& name) {
    std::lock_guard<std::mutex> g(mu_);
    return find(name).num_partitions;
  }

  uint64_t end_offset(const std::string& name, uint32_t partition) {
    std::lock_guard<std::mutex> g(mu_);
    Topic& t = find(name);
    if (partition >= t.num_partitions)
      throw BrokerError("partition out of range");
    return t.parts[partition].positions.size();
  }

  std::vector<std::string> list_topics() {
    std::lock_guard<std::mutex> g(mu_);
    std::vector<std::string> names;
    for (auto& kv : topics_) names.push_back(kv.first);
    return names;
  }

 private:
  Topic& find(const std::string& name) {
    auto it = topics_.find(name);
    if (it == topics_.end())
      throw BrokerError("unknown topic: " + name + " (create_topic first)");
    return it->second;
  }

  static std::string log_path(const std::string& dir, uint32_t p) {
    char buf[16];
    std::snprintf(buf, sizeof buf, "p%05u.log", p);
    return dir + "/" + buf;
  }

  static void write_meta(const std::string& dir, uint32_t nparts) {
    // Matches filelog.py's meta.json ({"num_partitions": N}); written via a
    // temp file + rename like FileBroker.create_topic.
    std::string tmp = dir + "/meta.json.tmp";
    FILE* f = std::fopen(tmp.c_str(), "w");
    if (!f) throw BrokerError("cannot write meta: " + tmp);
    std::fprintf(f, "{\"num_partitions\": %u}", nparts);
    std::fflush(f);
    ::fsync(::fileno(f));
    std::fclose(f);
    if (::rename(tmp.c_str(), (dir + "/meta.json").c_str()) != 0)
      throw BrokerError("meta rename failed");
  }

  void open_segment(Topic& t, const std::string& name, uint32_t p) {
    std::string path = log_path(data_dir_ + "/" + name, p);
    PartitionLog& log = t.parts[p];
    log.file = std::fopen(path.c_str(), "ab");
    if (!log.file) throw BrokerError("cannot open segment: " + path);
  }

  // Restore every partition of `t` to its pre-batch extent after a failed
  // produce.  Durable partitions close + truncate + reopen the segment so
  // bytes stranded in the stdio buffer by a short fwrite are discarded with
  // the torn tail instead of landing after later appends; a partition whose
  // segment cannot be reopened keeps file == nullptr, which the append path
  // rejects loudly (never silently falling back to the memory log).
  void rollback(Topic& t, const std::string& name,
                const std::vector<std::pair<size_t, uint64_t>>& before) {
    for (uint32_t p = 0; p < t.num_partitions; ++p) {
      PartitionLog& log = t.parts[p];
      // Leave partitions the batch never touched alone — no reason to risk
      // a close/reopen on a healthy segment.
      uint64_t extent = log.file ? log.file_len : log.bytes.size();
      if (log.positions.size() == before[p].first &&
          extent == before[p].second)
        continue;
      log.positions.resize(before[p].first);
      if (log.file) {
        std::fclose(log.file);
        log.file = nullptr;
        std::string path = log_path(data_dir_ + "/" + name, p);
        ::truncate(path.c_str(), off_t(before[p].second));
        log.file_len = before[p].second;
        log.file = std::fopen(path.c_str(), "ab");
      } else if (data_dir_.empty()) {
        log.bytes.resize(before[p].second);
      }
    }
  }

  // mkdir -p: create every missing component of `path`.
  static void mkdirs(const std::string& path) {
    for (size_t i = 1; i <= path.size(); ++i) {
      if (i == path.size() || path[i] == '/') {
        std::string prefix = path.substr(0, i);
        if (::mkdir(prefix.c_str(), 0777) != 0 && errno != EEXIST)
          throw BrokerError("cannot create data dir: " + prefix);
      }
    }
  }

  // FileBroker-compatible startup recovery: scan each segment, index record
  // positions, truncate a torn trailing frame.
  void recover() {
    mkdirs(data_dir_);
    DIR* d = ::opendir(data_dir_.c_str());
    if (!d) throw BrokerError("cannot open data dir: " + data_dir_);
    while (dirent* e = ::readdir(d)) {
      std::string name = e->d_name;
      if (name == "." || name == "..") continue;
      std::string dir = data_dir_ + "/" + name;
      FILE* mf = std::fopen((dir + "/meta.json").c_str(), "r");
      if (!mf) continue;
      char meta[128] = {0};
      size_t got = std::fread(meta, 1, sizeof meta - 1, mf);
      std::fclose(mf);
      (void)got;
      uint32_t nparts = 0;
      const char* colon = std::strchr(meta, ':');
      if (!colon || std::sscanf(colon + 1, "%u", &nparts) != 1 || nparts < 1)
        continue;
      Topic t;
      t.num_partitions = nparts;
      t.parts.resize(nparts);
      for (uint32_t p = 0; p < nparts; ++p) {
        std::string path = log_path(dir, p);
        FILE* f = std::fopen(path.c_str(), "rb");
        if (f) {
          PartitionLog& log = t.parts[p];
          uint8_t hdr[kFrameHeader];
          uint64_t pos = 0;
          std::fseek(f, 0, SEEK_END);
          uint64_t size = uint64_t(std::ftell(f));
          std::fseek(f, 0, SEEK_SET);
          while (pos + kFrameHeader <= size) {
            if (std::fread(hdr, 1, kFrameHeader, f) != kFrameHeader) break;
            uint32_t vlen = (uint32_t(hdr[4]) << 24) | (uint32_t(hdr[5]) << 16) |
                            (uint32_t(hdr[6]) << 8) | hdr[7];
            if (pos + kFrameHeader + vlen > size) break;  // torn tail
            log.positions.push_back(pos);
            pos += kFrameHeader + vlen;
            std::fseek(f, long(vlen), SEEK_CUR);
          }
          std::fclose(f);
          log.file_len = pos;
          if (pos < size) ::truncate(path.c_str(), long(pos));
        }
        open_segment(t, name, p);
      }
      topics_.emplace(name, std::move(t));
    }
    ::closedir(d);
  }

  std::string data_dir_;
  std::mutex mu_;
  std::map<std::string, Topic> topics_;
};

// -- connection handling -----------------------------------------------------

bool read_exact(int fd, void* buf, size_t n) {
  uint8_t* p = static_cast<uint8_t*>(buf);
  while (n > 0) {
    ssize_t got = ::recv(fd, p, n, 0);
    if (got <= 0) return false;
    p += got;
    n -= size_t(got);
  }
  return true;
}

bool write_exact(int fd, const void* buf, size_t n) {
  const uint8_t* p = static_cast<const uint8_t*>(buf);
  while (n > 0) {
    ssize_t put = ::send(fd, p, n, MSG_NOSIGNAL);
    if (put <= 0) return false;
    p += put;
    n -= size_t(put);
  }
  return true;
}

void handle_request(Broker& broker, const std::vector<uint8_t>& body,
                    std::string& resp) {
  Reader r{body.data(), body.data() + body.size()};
  uint8_t opcode = 0;
  if (r.need(1)) {
    opcode = *r.p;
    ++r.p;
  }
  resp.push_back(char(0));  // OK; rewritten on error
  try {
    switch (opcode) {
      case 1: {  // CREATE_TOPIC
        std::string name = r.name();
        uint32_t nparts = r.u32();
        if (!r.ok) throw BrokerError("malformed request");
        broker.create_topic(name, nparts);
        break;
      }
      case 2: {  // PRODUCE_BATCH
        std::string name = r.name();
        uint32_t n = r.u32();
        if (!r.ok) throw BrokerError("malformed request");
        put_u64(resp, broker.produce_batch(name, r, n));
        break;
      }
      case 3: {  // FETCH
        std::string name = r.name();
        uint32_t partition = r.u32();
        uint64_t start = r.u64();
        uint32_t max_records = r.u32();
        uint32_t max_bytes = r.u32();
        if (!r.ok) throw BrokerError("malformed request");
        broker.fetch(name, partition, start, max_records,
                     std::min(max_bytes, kMaxBodyLen - 64), resp);
        break;
      }
      case 4: {  // NUM_PARTITIONS
        std::string name = r.name();
        if (!r.ok) throw BrokerError("malformed request");
        put_u32(resp, broker.num_partitions(name));
        break;
      }
      case 5: {  // END_OFFSET
        std::string name = r.name();
        uint32_t partition = r.u32();
        if (!r.ok) throw BrokerError("malformed request");
        put_u64(resp, broker.end_offset(name, partition));
        break;
      }
      case 6: {  // DELETE_TOPIC
        std::string name = r.name();
        if (!r.ok) throw BrokerError("malformed request");
        broker.delete_topic(name);
        break;
      }
      case 7:  // PING
        break;
      case 8: {  // LIST_TOPICS
        auto names = broker.list_topics();
        put_u32(resp, uint32_t(names.size()));
        for (auto& n : names) {
          put_u16(resp, uint16_t(n.size()));
          resp.append(n);
        }
        break;
      }
      default:
        throw BrokerError("unknown opcode");
    }
  } catch (const std::exception& e) {
    resp.clear();
    resp.push_back(char(1));  // error status
    std::string msg = e.what();
    if (msg.size() > 0xffff) msg.resize(0xffff);
    put_u16(resp, uint16_t(msg.size()));
    resp.append(msg);
  }
}

void serve_connection(Broker* broker, int fd) {
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
  std::vector<uint8_t> body;
  for (;;) {
    uint8_t lenbuf[4];
    if (!read_exact(fd, lenbuf, 4)) break;
    uint32_t blen = (uint32_t(lenbuf[0]) << 24) | (uint32_t(lenbuf[1]) << 16) |
                    (uint32_t(lenbuf[2]) << 8) | lenbuf[3];
    if (blen == 0 || blen > kMaxBodyLen) break;
    body.resize(blen);
    if (!read_exact(fd, body.data(), blen)) break;
    std::string resp;
    handle_request(*broker, body, resp);
    uint8_t hdr[4] = {uint8_t(resp.size() >> 24), uint8_t(resp.size() >> 16),
                      uint8_t(resp.size() >> 8), uint8_t(resp.size())};
    if (!write_exact(fd, hdr, 4) ||
        !write_exact(fd, resp.data(), resp.size()))
      break;
  }
  ::close(fd);
}

}  // namespace

int main(int argc, char** argv) {
  if (argc < 2 || argc > 4) {
    std::fprintf(stderr, "usage: cfk_broker PORT [DATA_DIR] [BIND_ADDR]\n");
    return 2;
  }
  ::signal(SIGPIPE, SIG_IGN);
  int port = std::atoi(argv[1]);
  std::unique_ptr<Broker> broker;
  try {
    broker = std::make_unique<Broker>(argc >= 3 ? argv[2] : "");
  } catch (const std::exception& e) {
    std::fprintf(stderr, "cfk_broker: %s\n", e.what());
    return 1;
  }

  int lfd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (lfd < 0) {
    std::perror("socket");
    return 1;
  }
  int one = 1;
  ::setsockopt(lfd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  if (argc == 4 && ::inet_pton(AF_INET, argv[3], &addr.sin_addr) != 1) {
    std::fprintf(stderr, "cfk_broker: bad bind address %s\n", argv[3]);
    return 2;
  }
  addr.sin_port = htons(uint16_t(port));
  if (::bind(lfd, reinterpret_cast<sockaddr*>(&addr), sizeof addr) != 0) {
    std::perror("bind");
    return 1;
  }
  if (::listen(lfd, 64) != 0) {
    std::perror("listen");
    return 1;
  }
  socklen_t alen = sizeof addr;
  ::getsockname(lfd, reinterpret_cast<sockaddr*>(&addr), &alen);
  std::printf("CFK_BROKER LISTENING %d\n", int(ntohs(addr.sin_port)));
  std::fflush(stdout);

  for (;;) {
    int cfd = ::accept(lfd, nullptr, nullptr);
    if (cfd < 0) {
      if (errno == EINTR) continue;
      break;
    }
    std::thread(serve_connection, broker.get(), cfd).detach();
  }
  ::close(lfd);
  return 0;
}
