// Native ingest + wire-codec library for cfk_tpu.
//
// The reference has no native components (SURVEY.md §2: pure Java + one
// Python script); this library is the framework's runtime-side native layer:
// a single-pass Netflix-format parser (the role of
// producers/NetflixDataFormatProducer.java's per-line Java loop), a MovieLens
// CSV parser, and batch big-endian codecs for the 6-byte id+rating wire
// frames (serdes layout of serdes/IdRatingPairMessage/*.java).
//
// C ABI only — loaded from Python via ctypes (no pybind11 in the image).
// Error convention: functions returning long return >= 0 on success and
// -lineno on a malformed input line (mirrors the Python parser's
// "path:lineno" ValueError).

#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <strings.h>  // strncasecmp
#include <sys/stat.h>
#include <vector>

namespace {

struct FileBuf {
  char* data = nullptr;
  size_t size = 0;
  ~FileBuf() { std::free(data); }
  bool read(const char* path) {
    struct stat st;
    if (::stat(path, &st) != 0 || !S_ISREG(st.st_mode)) return false;
    FILE* f = std::fopen(path, "rb");
    if (!f) return false;
    long n = static_cast<long>(st.st_size);
    data = static_cast<char*>(std::malloc(n + 1));
    if (!data) {
      std::fclose(f);
      return false;
    }
    size = std::fread(data, 1, n, f);
    bool ok = size == static_cast<size_t>(n) && !std::ferror(f);
    data[size] = '\0';
    std::fclose(f);
    return ok;
  }
};

// Parse a non-negative decimal integer; advances *p. Returns false if no
// digits were consumed.
inline bool parse_uint(const char*& p, const char* end, long long* out) {
  const char* start = p;
  long long v = 0;
  while (p < end && *p >= '0' && *p <= '9') {
    if (v > 922337203685477579LL) return false;  // would overflow int64
    v = v * 10 + (*p - '0');
    ++p;
  }
  if (p == start) return false;
  *out = v;
  return true;
}

// Parse a non-negative decimal float (digits[.digits]) bounded by `end` —
// never reads past the line like strtod would. Advances *p.
inline bool parse_ufloat(const char*& p, const char* end, double* out) {
  long long ip = 0;
  const char* start = p;
  while (p < end && *p >= '0' && *p <= '9') {
    ip = ip * 10 + (*p - '0');
    ++p;
  }
  bool any = p != start;
  double v = static_cast<double>(ip);
  if (p < end && *p == '.') {
    ++p;
    double scale = 0.1;
    const char* fstart = p;
    while (p < end && *p >= '0' && *p <= '9') {
      v += (*p - '0') * scale;
      scale *= 0.1;
      ++p;
    }
    any = any || p != fstart;
  }
  if (!any) return false;
  *out = v;
  return true;
}

}  // namespace

extern "C" {

// Netflix format: "movieId:" header lines, "userId,rating,date" rows.
// Pass movie/user/rating == nullptr (cap 0) to count; otherwise fills up to
// cap entries. Returns number of ratings, or -lineno on malformed input
// (including a rating row before any header), or -0x7fffffff on I/O error.
long long cfk_parse_netflix(const char* path, long long* movie, long long* user,
                            float* rating, long long cap) {
  FileBuf buf;
  if (!buf.read(path)) return -0x7fffffffLL;
  const char* p = buf.data;
  const char* end = buf.data + buf.size;
  long long current_movie = -1;
  long long count = 0;
  long long lineno = 0;
  while (p < end) {
    ++lineno;
    const char* line_end = static_cast<const char*>(memchr(p, '\n', end - p));
    if (!line_end) line_end = end;
    const char* q = p;
    const char* qe = line_end;
    while (qe > q && (qe[-1] == '\r' || qe[-1] == ' ' || qe[-1] == '\t')) --qe;
    while (q < qe && (*q == ' ' || *q == '\t')) ++q;
    if (q == qe) {  // blank line
      p = line_end + 1;
      continue;
    }
    long long v;
    const char* r = q;
    // Header branch first (mirrors the Python parser's endswith(':')):
    // any line ending in ':' must be "<digits>:", else it is malformed.
    if (qe[-1] == ':') {
      if (!parse_uint(r, qe, &v) || r + 1 != qe) return -lineno;
      current_movie = v;
    } else if (!parse_uint(r, qe, &v)) {
      return -lineno;
    } else {
      if (current_movie < 0) return -lineno;  // rating row before header
      if (r >= qe || *r != ',') return -lineno;
      ++r;
      long long rat;
      if (!parse_uint(r, qe, &rat)) return -lineno;
      if (r >= qe || *r != ',') return -lineno;  // date must be present
      if (count < cap && movie && user && rating) {
        movie[count] = current_movie;
        user[count] = v;
        rating[count] = static_cast<float>(rat);
      }
      ++count;
    }
    p = line_end + 1;
  }
  return count;
}

// MovieLens CSV: optional "userId,..." header, rows userId,movieId,rating,ts.
// min_rating filters; same count/fill + -lineno conventions.
long long cfk_parse_movielens(const char* path, long long* movie,
                              long long* user, float* rating, long long cap,
                              float min_rating) {
  FileBuf buf;
  if (!buf.read(path)) return -0x7fffffffLL;
  const char* p = buf.data;
  const char* end = buf.data + buf.size;
  long long count = 0;
  long long lineno = 0;
  while (p < end) {
    ++lineno;
    const char* line_end = static_cast<const char*>(memchr(p, '\n', end - p));
    if (!line_end) line_end = end;
    const char* q = p;
    const char* qe = line_end;
    while (qe > q && (qe[-1] == '\r' || qe[-1] == ' ')) --qe;
    while (q < qe && *q == ' ') ++q;
    if (q == qe) {
      p = line_end + 1;
      continue;
    }
    if (lineno == 1 && qe - q >= 6 &&
        (strncasecmp(q, "userid", 6) == 0)) {  // header row
      p = line_end + 1;
      continue;
    }
    long long uid, mid;
    const char* r = q;
    if (!parse_uint(r, qe, &uid) || r >= qe || *r != ',') return -lineno;
    ++r;
    if (!parse_uint(r, qe, &mid) || r >= qe || *r != ',') return -lineno;
    ++r;
    double rat;
    if (!parse_ufloat(r, qe, &rat)) return -lineno;
    // Rating must be followed by the timestamp separator or end the line —
    // trailing garbage ("3.5abc") is malformed, like the Python parser says.
    if (r != qe && *r != ',') return -lineno;
    if (rat >= min_rating) {
      if (count < cap && movie && user && rating) {
        movie[count] = mid;
        user[count] = uid;
        rating[count] = static_cast<float>(rat);
      }
      ++count;
    }
    p = line_end + 1;
  }
  return count;
}

// Batch-encode n (id, rating) pairs as 6-byte big-endian frames.
void cfk_encode_id_rating_batch(const int32_t* ids, const int16_t* ratings,
                                long long n, uint8_t* out) {
  for (long long i = 0; i < n; ++i) {
    uint32_t id = static_cast<uint32_t>(ids[i]);
    uint16_t rt = static_cast<uint16_t>(ratings[i]);
    uint8_t* o = out + i * 6;
    o[0] = id >> 24;
    o[1] = id >> 16;
    o[2] = id >> 8;
    o[3] = id;
    o[4] = rt >> 8;
    o[5] = rt;
  }
}

// Batch-decode n 6-byte frames. Returns n, or -1 if nbytes != 6*n.
long long cfk_decode_id_rating_batch(const uint8_t* in, long long nbytes,
                                     int32_t* ids, int16_t* ratings) {
  if (nbytes % 6 != 0) return -1;
  long long n = nbytes / 6;
  for (long long i = 0; i < n; ++i) {
    const uint8_t* o = in + i * 6;
    ids[i] = static_cast<int32_t>((uint32_t(o[0]) << 24) | (uint32_t(o[1]) << 16) |
                                  (uint32_t(o[2]) << 8) | uint32_t(o[3]));
    ratings[i] = static_cast<int16_t>((uint16_t(o[4]) << 8) | uint16_t(o[5]));
  }
  return n;
}

// Stable counting-sort group-by over dense keys: the block builders' hot
// grouping step (the np.argsort in cfk_tpu/data/blocks.py builders is
// O(n log n) comparison sort; dense entity keys admit O(n + k)).
// order_out[j] = original index of the j-th entry in (key, original index)
// order; count_out[k] = entries with key k; start_out[k] = exclusive prefix
// sum of counts. Returns 0, or -1 if a key is outside [0, num_keys).
int cfk_group_by(const int64_t* keys, long long nnz, long long num_keys,
                 int64_t* order_out, int32_t* count_out, int64_t* start_out) {
  std::memset(count_out, 0, sizeof(int32_t) * num_keys);
  for (long long i = 0; i < nnz; ++i) {
    int64_t k = keys[i];
    if (k < 0 || k >= num_keys) return -1;
    ++count_out[k];
  }
  int64_t acc = 0;
  for (long long k = 0; k < num_keys; ++k) {
    start_out[k] = acc;
    acc += count_out[k];
  }
  std::vector<int64_t> cursor(start_out, start_out + num_keys);
  for (long long i = 0; i < nnz; ++i) {
    order_out[cursor[keys[i]]++] = i;  // ascending i per key = stable
  }
  return 0;
}

// Dense-index raw entity ids by rank among the distinct values present:
// unique_out gets the sorted distinct ids, dense_out[i] the rank of raw[i].
// O(n + max_raw) via a presence table — raw ids must lie in [0, max_raw]
// (rating datasets' ids are small positive ints; the Python caller checks
// the range and falls back to sort-based indexing otherwise).
// Returns the number of distinct ids, or -1 on an out-of-range id.
long long cfk_index_dense(const int64_t* raw, long long nnz, int64_t max_raw,
                          int64_t* unique_out, int32_t* dense_out) {
  std::vector<int32_t> rank(static_cast<size_t>(max_raw) + 1, -1);
  for (long long i = 0; i < nnz; ++i) {
    int64_t v = raw[i];
    if (v < 0 || v > max_raw) return -1;
    rank[v] = 1;
  }
  long long n_unique = 0;
  for (int64_t v = 0; v <= max_raw; ++v) {
    if (rank[v] >= 0) {
      rank[v] = static_cast<int32_t>(n_unique);
      if (unique_out) unique_out[n_unique] = v;
      ++n_unique;
    }
  }
  if (dense_out) {
    for (long long i = 0; i < nnz; ++i) dense_out[i] = rank[raw[i]];
  }
  return n_unique;
}

// Bump when parser semantics or signatures change: a stale .so must be
// treated as unavailable (Python fallback), never silently divergent.
int cfk_native_abi_version() { return 3; }

}  // extern "C"
