"""True multi-process SPMD: 2 processes × 4 CPU devices over Gloo.

The single-controller tests elsewhere fake 8 devices in one process; this
spawns two real JAX processes (the multi-host programming model — one
controller per host, collectives over the DCN stand-in) and checks the full
sharded trainer produces the same quality as the single-process run.

The ``slow``-marked drills exercise the preemption-tolerance ladder across
the real process boundary (ISSUE 5): lockstep rollback/escalation on a
fault local to one process, SIGKILL of one worker with bounded survivor
exit + intact checkpoints + full-fleet resume, and the
``initialize_distributed`` startup-timeout error.  Every subprocess wait is
bounded (the existing 540 s pattern) so a wedged drill fails instead of
hanging the suite.
"""

import json
import os
import re
import signal

import numpy as np
import pytest

from multihost_worker import communicate_all, spawn_workers

# Per-run port: a fixed one can collide with a lingering coordinator (or
# TIME_WAIT socket) from a previous suite run on the same machine.
_PORT = 29000 + (os.getpid() % 2000)


def test_two_process_training_matches_single_process(tiny_coo, tmp_path):
    # The checkpoint dir doubles as the resume test's shared store; each
    # worker also re-trains from it and asserts the broadcast resume path.
    procs = spawn_workers(_PORT, 2, str(tmp_path / "ck"))
    outs = communicate_all(procs)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"process {i} failed:\n{out[-3000:]}"
    m = re.search(r"MULTIHOST_RESULT mse=([0-9.]+) rmse=([0-9.]+) devices=8",
                  "".join(outs))
    assert m, f"no result line:\n{outs[0][-2000:]}"
    mse_multi = float(m.group(1))
    # The at-scale tiled layout (exchange="auto" + dense stream) ran across
    # the process boundary too; the worker asserts its parity in-process
    # and reports it here for the record.
    mt = re.search(r"MULTIHOST_TILED mse_auto=([0-9.]+) mse_dense=([0-9.]+)",
                   "".join(outs))
    assert mt, f"no tiled result line:\n{outs[0][-2000:]}"
    assert abs(float(mt.group(1)) - mse_multi) < 1e-3
    assert abs(float(mt.group(2)) - mse_multi) < 1e-3

    # Single-process 8-device reference (the conftest already provides the
    # 8-virtual-device CPU platform in this process).
    from cfk_tpu.config import ALSConfig
    from cfk_tpu.data.blocks import Dataset
    from cfk_tpu.eval.metrics import mse_rmse_from_blocks
    from cfk_tpu.parallel.mesh import make_mesh
    from cfk_tpu.parallel.spmd import train_als_sharded

    ds = Dataset.from_coo(tiny_coo, num_shards=8)
    config = ALSConfig(rank=5, lam=0.05, num_iterations=7, seed=0, num_shards=8)
    model = train_als_sharded(ds, config, make_mesh(8))
    mse_single, _ = mse_rmse_from_blocks(model.predict_dense(), ds)
    np.testing.assert_allclose(mse_multi, mse_single, rtol=1e-3, atol=1e-4)


# --- preemption-tolerance drills (ISSUE 5) ---------------------------------


@pytest.mark.slow
def test_lockstep_rollback_drill():
    """A FactorCorruption whose rows live entirely in process 1's shard:
    the replicated probe word must make BOTH processes take the identical
    rollback/escalation path (the untested PR 3 claim), with bit-identical
    post-recovery factors — and the one-shot recovery must land exactly on
    the fault-free trajectory."""
    procs = spawn_workers(_PORT + 1, 2, None, "--drill", "lockstep")
    outs = communicate_all(procs)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"process {i} failed:\n{out[-3000:]}"
    rows = [json.loads(line.split(" ", 1)[1])
            for out in outs for line in out.splitlines()
            if line.startswith("DRILL_LOCKSTEP ")]
    by_phase = {}
    for r in rows:
        by_phase.setdefault(r["phase"], {})[r["pid"]] = r
    assert set(by_phase) == {"faultfree", "oneshot", "persistent"}, by_phase
    for phase, per_pid in by_phase.items():
        assert set(per_pid) == {0, 1}, (phase, per_pid)
        a, b = per_pid[0], per_pid[1]
        # identical recovery rung sequence AND bit-identical factors
        strip = lambda r: {k: v for k, v in r.items() if k != "pid"}
        assert strip(a) == strip(b), (phase, a, b)
    # the fault actually fired, was detected, and recovery replayed onto
    # the fault-free trajectory bit-exactly
    assert by_phase["faultfree"][0]["trips"] == 0
    one = by_phase["oneshot"][0]
    assert one["fired"] >= 1 and one["trips"] == 1 and one["rollbacks"] == 1
    assert one["crc"] == by_phase["faultfree"][0]["crc"]
    # the persistent fault climbed the ladder in lockstep and degraded
    per = by_phase["persistent"][0]
    assert per["degraded"] == 1 and per["trips"] >= 2
    assert per["rungs"], per  # at least the λ-bump rung fired identically


@pytest.mark.slow
def test_worker_kill_and_resume_drill(tmp_path):
    """SIGKILL one worker mid-run: the survivor must exit within a bound
    (watchdog or collective error — never hang), the checkpoint store must
    hold only intact committed steps, and restarting both workers must
    resume to the same quality as an uninterrupted run."""
    from cfk_tpu.resilience.preempt import STALL_EXIT_CODE

    ck = str(tmp_path / "ck")
    kill_iter = 4
    procs = spawn_workers(
        _PORT + 2, 2, ck, "--drill", "kill",
        "--kill-iteration", str(kill_iter), "--stall-timeout", "10",
    )
    outs = communicate_all(procs, timeout=240)  # detection must be BOUNDED
    # victim died by SIGKILL; the survivor exited cleanly via the watchdog
    # or the Gloo error path — either way nonzero, never a hang
    assert procs[1].returncode == -signal.SIGKILL, (
        procs[1].returncode, outs[1][-2000:],
    )
    assert procs[0].returncode != 0, outs[0][-2000:]
    survivor_graceful = procs[0].returncode == STALL_EXIT_CODE
    # progress lines prove the run was mid-flight when the peer died
    assert any("DRILL_ITER" in o for o in outs), outs[0][-2000:]

    # the store holds ONLY intact, verified steps, reaching the last
    # iteration completed before the kill
    from cfk_tpu.transport.checkpoint import CheckpointManager

    mgr = CheckpointManager(ck)
    steps = mgr.iterations()
    assert steps, "no checkpoint survived the kill"
    # The victim dies between completing iteration kill_iter and the
    # survivor's commit of that step (the gather is a collective), so the
    # newest committed step straddles kill_iter by at most one.
    assert kill_iter - 1 <= max(steps) <= kill_iter + 1, (
        steps, outs[0][-1500:],
    )
    for it in steps:
        mgr.verify(it)  # raises CheckpointCorruptError on a torn step
    assert mgr.latest_valid_iteration() == max(steps)

    # restart the full fleet: resume must reach the uninterrupted quality
    procs = spawn_workers(_PORT + 3, 2, ck, "--drill", "resume")
    outs = communicate_all(procs)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"resume process {i} failed:\n{out[-3000:]}"
    m = re.search(r"DRILL_RESUME mse=([0-9.]+)", "".join(outs))
    assert m, f"no resume result:\n{outs[0][-2000:]}"
    mse_resumed = float(m.group(1))

    # uninterrupted single-process 8-device reference (same num_shards=8
    # trajectory; the conftest provides the 8-virtual-device platform)
    from cfk_tpu.config import ALSConfig
    from cfk_tpu.data.blocks import Dataset
    from cfk_tpu.data.synthetic import synthetic_netflix_coo
    from cfk_tpu.eval.metrics import mse_rmse_from_blocks
    from cfk_tpu.parallel.mesh import make_mesh
    from cfk_tpu.parallel.spmd import train_als_sharded

    ds = Dataset.from_coo(synthetic_netflix_coo(64, 32, 900, seed=0),
                          num_shards=8)
    cfg = ALSConfig(rank=4, lam=0.05, num_iterations=8, seed=0,
                    num_shards=8, health_check_every=1)
    model = train_als_sharded(ds, cfg, make_mesh(8))
    mse_single, _ = mse_rmse_from_blocks(model.predict_dense(), ds)
    np.testing.assert_allclose(mse_resumed, mse_single, rtol=1e-3, atol=1e-4)
    # record which survivor path fired for the log (both are in-contract)
    print(f"survivor_graceful_stall_exit={survivor_graceful}")


@pytest.mark.slow
def test_one_process_sigterm_evicts_whole_fleet(tmp_path):
    """SIGTERM exactly ONE of two processes: the per-boundary evict-sync
    allgather must make BOTH agree on the eviction, run the emergency
    save's collectives in lockstep, and exit resumable — acting on the
    local flag alone would desync the fleet into a stall exit."""
    ck = str(tmp_path / "ck")
    procs = spawn_workers(_PORT + 5, 2, ck, "--drill", "preempt",
                          "--preempt-iteration", "3")
    outs = communicate_all(procs, timeout=240)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"process {i} failed:\n{out[-3000:]}"
    rows = {json.loads(line.split(" ", 1)[1])["pid"]:
            json.loads(line.split(" ", 1)[1])
            for out in outs for line in out.splitlines()
            if line.startswith("DRILL_PREEMPT ")}
    assert set(rows) == {0, 1}, rows
    assert rows[1]["locally_signalled"] and not rows[0]["locally_signalled"]
    # both agreed on the SAME eviction boundary and exited resumable
    assert rows[0]["preempted"] == rows[1]["preempted"] == 1
    assert (rows[0]["trained_iterations"]
            == rows[1]["trained_iterations"] == 4)
    assert "peer process signalled" in rows[0]["note"]

    from cfk_tpu.transport.checkpoint import CheckpointManager

    mgr = CheckpointManager(ck)
    assert mgr.latest_valid_iteration() == 4  # the emergency save committed


@pytest.mark.slow
def test_initialize_distributed_timeout_is_actionable():
    """One process of a declared 2-process fleet: initialize_distributed
    must fail within the bounded init_timeout_s naming the missing process
    id — not hang for the 300 s runtime default, and not die on the bare
    absl-fatal DEADLINE_EXCEEDED abort that names nobody (jax 0.4.37's
    only native behavior, measured)."""
    from cfk_tpu.parallel.mesh import INIT_TIMEOUT_EXIT_CODE

    (p,) = spawn_workers(_PORT + 4, 2, None, "--drill", "init-timeout",
                         "--init-timeout", "6", pids=[0])
    out, _ = p.communicate(timeout=120)  # bounded: ~6s + interpreter startup
    text = out.decode()
    # either the watchdog exit (runtimes that abort uncatchably) or a
    # caught TimeoutError (runtimes that raise) — both must carry the
    # actionable message naming the missing peer
    if p.returncode == INIT_TIMEOUT_EXIT_CODE:
        assert "initialize_distributed timed out" in text, text[-2000:]
    else:
        assert p.returncode == 0, text[-3000:]
        assert "DRILL_INIT_TIMEOUT actionable=True" in text, text[-2000:]
    assert "process ids [1]" in text, text[-2000:]

# --- fleet out-of-core drills (distributed window exchange) ----------------


@pytest.mark.slow
def test_offload_fleet_matches_one_process_driver():
    """The exchange contract: a 2-process host-window run — each process
    owning HALF the HostFactorStore and receiving the other half's cold
    window residuals over the hier-ring DCN phases — must produce factor
    tables bit-identical to the one-process driver on the same config."""
    procs = spawn_workers(_PORT + 6, 2, None, "--drill", "offload")
    outs = communicate_all(procs)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"process {i} failed:\n{out[-3000:]}"
    rows = {json.loads(line.split(" ", 1)[1])["pid"]:
            json.loads(line.split(" ", 1)[1])
            for out in outs for line in out.splitlines()
            if line.startswith("DRILL_OFFLOAD ")}
    assert set(rows) == {0, 1}, rows
    assert rows[0]["processes"] == rows[1]["processes"] == 2
    assert rows[0]["crc"] == rows[1]["crc"], rows
    # residual bytes actually crossed the process boundary
    assert rows[0]["rows_dcn"] > 0 and rows[1]["rows_dcn"] > 0, rows

    # one-process driver reference: bit-identical, not merely close
    import warnings

    from multihost_worker import _crc, _offload_setup

    from cfk_tpu.offload.windowed import train_als_host_window

    ds, cfg = _offload_setup()
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        model = train_als_host_window(ds, cfg)
    crc_one = _crc(model.user_factors, model.movie_factors)
    assert rows[0]["crc"] == crc_one, (rows[0]["crc"], crc_one)


@pytest.mark.slow
def test_offload_fleet_kill_and_resume(tmp_path):
    """SIGKILL one host of the 2-process offload fleet after it commits
    its per-host checkpoint: the survivor exits bounded (Gloo error or
    StallWatchdog — never a hang), and the restarted fleet min-agrees the
    resume step across per-host manifests and lands on the uninterrupted
    run's crc bit-exactly."""
    from cfk_tpu.resilience.preempt import STALL_EXIT_CODE
    from cfk_tpu.transport.checkpoint import CheckpointManager

    ck = str(tmp_path / "ck")
    kill_iter = 2
    procs = spawn_workers(
        _PORT + 7, 2, ck, "--drill", "offload-kill",
        "--kill-iteration", str(kill_iter), "--stall-timeout", "10",
    )
    outs = communicate_all(procs, timeout=240)
    assert procs[1].returncode == -signal.SIGKILL, (
        procs[1].returncode, outs[1][-2000:],
    )
    assert procs[0].returncode != 0, outs[0][-2000:]
    survivor_graceful = procs[0].returncode == STALL_EXIT_CODE
    assert any("DRILL_ITER" in o for o in outs), outs[0][-2000:]

    # every host's manifest holds only intact committed steps; the kill
    # fired after the victim's save of kill_iter, so both reached it
    for pid in (0, 1):
        mgr = CheckpointManager(os.path.join(ck, f"host_{pid}"))
        steps = mgr.iterations()
        assert steps, f"host_{pid}: no checkpoint survived the kill"
        assert kill_iter <= max(steps) <= kill_iter + 1, (pid, steps)
        for it in steps:
            mgr.verify(it)
        assert mgr.latest_valid_iteration() == max(steps)

    procs = spawn_workers(_PORT + 8, 2, ck, "--drill", "offload-resume")
    outs = communicate_all(procs)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"resume process {i} failed:\n{out[-3000:]}"
    rows = {json.loads(line.split(" ", 1)[1])["pid"]:
            json.loads(line.split(" ", 1)[1])
            for out in outs for line in out.splitlines()
            if line.startswith("DRILL_OFFLOAD_RESUME ")}
    assert set(rows) == {0, 1}, rows
    assert rows[0]["resumed_from"] >= kill_iter, rows
    assert rows[0]["crc"] == rows[1]["crc"], rows

    # the resumed fleet lands on the uninterrupted trajectory bit-exactly
    import warnings

    from multihost_worker import _crc, _offload_setup

    from cfk_tpu.offload.windowed import train_als_host_window

    ds, cfg = _offload_setup()
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        model = train_als_host_window(ds, cfg)
    crc_one = _crc(model.user_factors, model.movie_factors)
    assert rows[0]["crc"] == crc_one, (rows[0]["crc"], crc_one)
    print(f"survivor_graceful_stall_exit={survivor_graceful}")


@pytest.mark.slow
def test_offload_fleet_bench_row():
    """The fleet scale-sweep row: a power-law shape the simulated
    single-host RAM budget refuses completes under 2 processes, with the
    DCN residual accounting recorded and reduced by the hot/delta split."""
    procs = spawn_workers(_PORT + 9, 2, None, "--drill", "offload-bench")
    outs = communicate_all(procs)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"process {i} failed:\n{out[-3000:]}"
    m = [json.loads(line.split(" ", 1)[1])
         for out in outs for line in out.splitlines()
         if line.startswith("OFFLOAD_BENCH_ROW ")]
    assert len(m) == 1, outs[0][-2000:]
    row = m[0]
    assert row["processes"] == 2
    assert not row["budget"]["single_host_fits"]
    assert row["budget"]["fleet_fits"]
    assert row["rows_dcn"] > 0 and row["mb_dcn"] > 0
    # the hot/delta split beat the dense no-split exchange at this skew
    assert row["hot"] == "on"
    assert 0.0 < row["dcn_reduction"] < 1.0, row
    assert row["recv_rows_iter"] < row["dense_rows_iter"], row
