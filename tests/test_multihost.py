"""True multi-process SPMD: 2 processes × 4 CPU devices over Gloo.

The single-controller tests elsewhere fake 8 devices in one process; this
spawns two real JAX processes (the multi-host programming model — one
controller per host, collectives over the DCN stand-in) and checks the full
sharded trainer produces the same quality as the single-process run.
"""

import os
import re
import subprocess
import sys

import numpy as np
import pytest

# Per-run port: a fixed one can collide with a lingering coordinator (or
# TIME_WAIT socket) from a previous suite run on the same machine.
_PORT = 29000 + (os.getpid() % 2000)


def _spawn(pid: int, nprocs: int, ckdir: str) -> subprocess.Popen:
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(
        os.environ,
        XLA_FLAGS="--xla_force_host_platform_device_count=4",
        PYTHONPATH=root + os.pathsep + os.environ.get("PYTHONPATH", ""),
    )
    return subprocess.Popen(
        [sys.executable, os.path.join("tests", "multihost_worker.py"),
         str(pid), str(nprocs), str(_PORT), ckdir],
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        cwd=root,
    )


def test_two_process_training_matches_single_process(tiny_coo, tmp_path):
    # The checkpoint dir doubles as the resume test's shared store; each
    # worker also re-trains from it and asserts the broadcast resume path.
    procs = [_spawn(i, 2, str(tmp_path / "ck")) for i in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=540)
            outs.append(out.decode())
    finally:
        for p in procs:
            p.kill()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"process {i} failed:\n{out[-3000:]}"
    m = re.search(r"MULTIHOST_RESULT mse=([0-9.]+) rmse=([0-9.]+) devices=8",
                  "".join(outs))
    assert m, f"no result line:\n{outs[0][-2000:]}"
    mse_multi = float(m.group(1))
    # The at-scale tiled layout (exchange="auto" + dense stream) ran across
    # the process boundary too; the worker asserts its parity in-process
    # and reports it here for the record.
    mt = re.search(r"MULTIHOST_TILED mse_auto=([0-9.]+) mse_dense=([0-9.]+)",
                   "".join(outs))
    assert mt, f"no tiled result line:\n{outs[0][-2000:]}"
    assert abs(float(mt.group(1)) - mse_multi) < 1e-3
    assert abs(float(mt.group(2)) - mse_multi) < 1e-3

    # Single-process 8-device reference (the conftest already provides the
    # 8-virtual-device CPU platform in this process).
    from cfk_tpu.config import ALSConfig
    from cfk_tpu.data.blocks import Dataset
    from cfk_tpu.eval.metrics import mse_rmse_from_blocks
    from cfk_tpu.parallel.mesh import make_mesh
    from cfk_tpu.parallel.spmd import train_als_sharded

    ds = Dataset.from_coo(tiny_coo, num_shards=8)
    config = ALSConfig(rank=5, lam=0.05, num_iterations=7, seed=0, num_shards=8)
    model = train_als_sharded(ds, config, make_mesh(8))
    mse_single, _ = mse_rmse_from_blocks(model.predict_dense(), ds)
    np.testing.assert_allclose(mse_multi, mse_single, rtol=1e-3, atol=1e-4)