"""Fused Gram+solve epilogue: each chunk's normal equations solved inside
the Gram kernel's VMEM residency (cfk_tpu/ops/pallas/gram_kernel.py
``gram_solve_tiles_pallas`` / ``gram_solve_tiles_dense_pallas``).

Equivalence contract pinned here: on the interpret/XLA-emulation route the
fused path is BIT-IDENTICAL to the split Gram→HBM→solve schedule with the
pallas solver (both run the same segment-sum Gram + the same fused
reg+solve elimination), for the stream, dense-stream, and ring-tiled
bodies, both weight modes, with the rank>cap automatic fallback; the accum
body's knob (which swaps the final batched solve's algorithm, not a
per-chunk round-trip) is equivalent to tight tolerance.
"""

import dataclasses

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from cfk_tpu.config import ALSConfig
from cfk_tpu.data.blocks import Dataset, build_tiled_blocks
from cfk_tpu.data.synthetic import synthetic_netflix_coo
from cfk_tpu.models.als import _tiled_to_device, train_als
from cfk_tpu.ops.tiled import ials_tiled_half_step, tiled_half_step


@pytest.fixture(scope="module")
def synth():
    coo = synthetic_netflix_coo(3000, 400, 60_000, seed=1)
    return Dataset.from_coo(coo)


def _half(blocks, fixed, lam, fused, **kw):
    return np.asarray(tiled_half_step(
        fixed, _tiled_to_device(blocks),
        ("tiled", blocks.mode) + blocks.statics,
        blocks.padded_entities, lam, solver="pallas",
        fused_epilogue=fused, **kw,
    ))


def test_stream_fused_matches_split_bitexact(synth):
    d = synth.coo_dense
    rng = np.random.default_rng(0)
    M = jnp.asarray(rng.standard_normal((400, 8)).astype(np.float32))
    ub = build_tiled_blocks(
        d.user_raw, d.movie_raw, d.rating, 3000, 400,
        accum_max_entities=16, chunk_elems=2048, tile_rows=8,
    )
    assert ub.mode == "stream"
    fused = _half(ub, M, 0.05, True)
    split = _half(ub, M, 0.05, False)
    np.testing.assert_array_equal(fused, split)


def test_stream_fused_matches_xla_split_bitexact(synth):
    """The emulation twin runs the identical segment-sum + fused reg+solve
    the split XLA gram backend runs — bit-exact on ANY jax version."""
    from cfk_tpu.ops.tiled import als_half_step_tiled

    d = synth.coo_dense
    rng = np.random.default_rng(1)
    M = jnp.asarray(rng.standard_normal((400, 8)).astype(np.float32))
    ub = build_tiled_blocks(
        d.user_raw, d.movie_raw, d.rating, 3000, 400,
        accum_max_entities=16, chunk_elems=2048, tile_rows=8,
    )
    blk = _tiled_to_device(ub)
    fused = _half(ub, M, 0.05, True)
    xla_split = np.asarray(als_half_step_tiled(
        M, blk["neighbor_idx"], blk["rating"], blk["weight"],
        blk["tile_seg"], blk["chunk_entity"], blk["chunk_count"],
        blk["carry_in"], blk["last_seg"], ub.padded_entities, 0.05,
        statics=ub.statics, solver="pallas", gram_backend="xla",
        fused_epilogue=False,
    ))
    np.testing.assert_array_equal(fused, xla_split)


def test_dense_stream_fused_matches_split_bitexact(synth):
    d = synth.coo_dense
    rng = np.random.default_rng(2)
    M = jnp.asarray(rng.standard_normal((400, 8)).astype(np.float32))
    ub = build_tiled_blocks(
        d.user_raw, d.movie_raw, d.rating, 3000, 400,
        accum_max_entities=0, chunk_elems=256, tile_rows=16,
        dense_stream=True,
    )
    assert ub.mode == "dstream"
    fused = _half(ub, M, 0.05, True)
    split = _half(ub, M, 0.05, False)
    np.testing.assert_array_equal(fused, split)


@pytest.mark.parametrize("dense", [False, True])
def test_ials_fused_matches_split_bitexact(synth, dense):
    """The matrix-reg (YᵀY+λI) fused mode, both tiled stream layouts."""
    d = synth.coo_dense
    rng = np.random.default_rng(3)
    M = jnp.asarray(rng.standard_normal((400, 8)).astype(np.float32))
    ub = build_tiled_blocks(
        d.user_raw, d.movie_raw, d.rating, 3000, 400,
        accum_max_entities=0, chunk_elems=256, tile_rows=16,
        dense_stream=dense,
    )
    outs = {}
    for fused in (False, True):
        outs[fused] = np.asarray(ials_tiled_half_step(
            M, _tiled_to_device(ub, weighted=dense),
            ("tiled", ub.mode) + ub.statics,
            ub.padded_entities, 0.1, 2.0, solver="pallas",
            fused_epilogue=fused,
        ))
    np.testing.assert_array_equal(outs[True], outs[False])


def test_accum_fused_knob_tight_tolerance(synth):
    """Accum mode has no per-chunk residency to fuse into; the knob swaps
    the final batched solve between the fused reg+solve kernel and the
    split ridge-add + dispatch — different elimination order, same math."""
    d = synth.coo_dense
    rng = np.random.default_rng(4)
    U = jnp.asarray(rng.standard_normal((3000, 8)).astype(np.float32))
    mb = build_tiled_blocks(
        d.movie_raw, d.user_raw, d.rating, 400, 3000,
        slice_rows=128, chunk_elems=2048,
    )
    assert mb.mode == "accum"
    fused = _half(mb, U, 0.05, True)
    split = _half(mb, U, 0.05, False)
    np.testing.assert_allclose(fused, split, rtol=2e-5, atol=2e-5)


def test_rank_above_cap_falls_back_to_split(synth):
    """rank > the fused elimination's cap must silently take the split
    path — bit-identical to fused_epilogue=False."""
    from cfk_tpu.ops.pallas.solve_kernel import LU_MAX_RANK

    d = synth.coo_dense
    rng = np.random.default_rng(5)
    k = LU_MAX_RANK + 8
    M = jnp.asarray(rng.standard_normal((400, k)).astype(np.float32))
    ub = build_tiled_blocks(
        d.user_raw, d.movie_raw, d.rating, 3000, 400,
        accum_max_entities=16, chunk_elems=2048, tile_rows=8,
    )
    fused = _half(ub, M, 0.05, True)
    split = _half(ub, M, 0.05, False)
    np.testing.assert_array_equal(fused, split)


def test_kernel_fused_vs_split_with_carry():
    """Kernel-level contract: (x, carry) of the fused wrapper equals the
    split gram + fused reg+solve + lseg extraction, diag and matrix."""
    from cfk_tpu.ops.pallas.gram_kernel import (
        fused_gram_solve_supported,
        gram_solve_tiles_pallas,
        gram_tiles_pallas,
    )
    from cfk_tpu.ops.solve import regularized_solve, regularized_solve_matrix

    rng = np.random.default_rng(0)
    k, t, nt, S = 8, 16, 12, 5
    g = jnp.asarray(rng.standard_normal((nt * t, k)).astype(np.float32))
    rt = jnp.asarray(rng.standard_normal(nt * t).astype(np.float32))
    seg = jnp.asarray(np.sort(rng.integers(0, S, nt)).astype(np.int32))
    cnt = jnp.asarray(rng.integers(1, 50, S).astype(np.int32))
    carry = (jnp.asarray(rng.standard_normal((k, k)).astype(np.float32)),
             jnp.asarray(rng.standard_normal(k).astype(np.float32)),
             jnp.asarray(1.0, jnp.float32))
    lseg = jnp.asarray(3, jnp.int32)

    a, b = gram_tiles_pallas(g, rt, seg, num_segments=S, tile_rows=t,
                             carry=carry)
    x, ca, cb = gram_solve_tiles_pallas(
        g, rt, seg, cnt, lseg, num_segments=S, tile_rows=t,
        reg_mode="diag", lam=0.05, carry=carry,
    )
    np.testing.assert_array_equal(
        np.asarray(x),
        np.asarray(regularized_solve(a, b, cnt, 0.05, solver="pallas")),
    )
    np.testing.assert_array_equal(np.asarray(ca), np.asarray(a)[3])
    np.testing.assert_array_equal(np.asarray(cb), np.asarray(b)[3])

    reg = jnp.asarray(np.eye(k, dtype=np.float32) * 0.1 + 0.01)
    xm, _, _ = gram_solve_tiles_pallas(
        g, rt, seg, reg, lseg, num_segments=S, tile_rows=t,
        reg_mode="matrix", carry=carry,
    )
    np.testing.assert_array_equal(
        np.asarray(xm),
        np.asarray(regularized_solve_matrix(a, b, reg, solver="pallas")),
    )

    assert fused_gram_solve_supported(2000, 64)
    assert not fused_gram_solve_supported(2000, 129)


def test_trainer_fused_matches_split_bitexact(synth):
    """End-to-end: the tiled trainer with fused_epilogue on == off."""
    ds = Dataset.from_coo(synth.coo_dense, layout="tiled", chunk_elems=2048,
                          accum_max_entities=16)
    base = ALSConfig(rank=8, lam=0.05, num_iterations=2, seed=0,
                     layout="tiled", solver="pallas")
    on = train_als(
        ds, dataclasses.replace(base, fused_epilogue=True)
    ).predict_dense()
    off = train_als(
        ds, dataclasses.replace(base, fused_epilogue=False)
    ).predict_dense()
    np.testing.assert_array_equal(on, off)


def test_ring_tiled_fused_matches_single(synth):
    """The ring half-step's fused knob: 4-way ring with fused on matches
    the single-device split reference (the knob gates the ring's final
    reg+solve pass; the accumulation itself is unchanged)."""
    if len(jax.devices()) < 4:
        pytest.skip("needs 4 devices")
    from cfk_tpu.parallel.mesh import make_mesh
    from cfk_tpu.parallel.spmd import train_als_sharded

    coo = synthetic_netflix_coo(3000, 400, 60_000, seed=1)
    cfg1 = ALSConfig(rank=8, lam=0.05, num_iterations=2, seed=0,
                     layout="tiled", solver="cholesky")
    ref = train_als(
        Dataset.from_coo(coo, layout="tiled"), cfg1
    ).predict_dense()
    ds4 = Dataset.from_coo(coo, layout="tiled", num_shards=4, ring=True,
                           ring_warn=False)
    cfg4 = dataclasses.replace(cfg1, num_shards=4, exchange="ring",
                               solver="pallas", fused_epilogue=True)
    got = train_als_sharded(ds4, cfg4, make_mesh(4)).predict_dense()
    np.testing.assert_allclose(got, ref, rtol=2e-3, atol=2e-3)


@pytest.mark.parametrize("overlap", [True, False])
def test_sharded_tiled_matches_single_overlap_axis(synth, overlap):
    """The 4-shard tiled SPMD equivalence (the pre-existing mismatch fixed
    by the padding-invariant init) holds with overlap on AND off."""
    if len(jax.devices()) < 4:
        pytest.skip("needs 4 devices")
    from cfk_tpu.parallel.mesh import make_mesh
    from cfk_tpu.parallel.spmd import train_als_sharded

    coo = synthetic_netflix_coo(3000, 400, 60_000, seed=1)
    cfg1 = ALSConfig(rank=8, lam=0.05, num_iterations=2, seed=0,
                     layout="tiled", solver="cholesky", overlap=overlap)
    ref = train_als(
        Dataset.from_coo(coo, layout="tiled"), cfg1
    ).predict_dense()
    cfg4 = dataclasses.replace(cfg1, num_shards=4)
    got = train_als_sharded(
        Dataset.from_coo(coo, layout="tiled", num_shards=4), cfg4,
        make_mesh(4),
    ).predict_dense()
    np.testing.assert_allclose(got, ref, rtol=2e-3, atol=2e-3)


def test_config_validates_fused_epilogue():
    assert ALSConfig(fused_epilogue=True).fused_epilogue is True
    assert ALSConfig().fused_epilogue is None
    with pytest.raises(ValueError, match="fused_epilogue"):
        ALSConfig(fused_epilogue="yes")
