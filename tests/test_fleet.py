"""Replicated serving fleet (ISSUE 18): delta shipping, gap resync,
rollover, admission control, failover, client retry, /readyz.

Single-threaded where possible: ``FleetReplica.pump()`` runs one
supervised iteration (flip → apply deltas → pull lazy → serve) without
the replica thread, so the protocol assertions are deterministic; the
thread/kill paths run under chaos_lab as well (``serve_replica_kill``,
``serve_delta_gap``, ``serve_rollover``)."""

import threading
import time
import urllib.request
import warnings

import numpy as np
import pytest

from cfk_tpu.serving import (
    AdmissionController,
    DeltaPublisher,
    FleetReplica,
    RecommendServer,
    ServeClient,
    ServeEngine,
    ServeFleet,
    SnapshotStore,
    ensure_serve_topics,
    table_crc,
)
from cfk_tpu.transport import InMemoryBroker

U, M, K = 48, 64, 6


def _factors(seed=0):
    rng = np.random.default_rng(seed)
    return (rng.standard_normal((U, K)).astype(np.float32),
            rng.standard_normal((M, K)).astype(np.float32))


def _engine(u, m, **kw):
    return ServeEngine(u, m, num_users=U, num_movies=M, tile_m=16, **kw)


def _wired(replicas=1, seed=0, **fleet_kw):
    """(fleet, publisher, broker, (u, m)) with the store seeded."""
    u, m = _factors(seed)
    broker = InMemoryBroker()
    fleet = ServeFleet(lambda i: _engine(u, m), broker, replicas=replicas,
                       **fleet_kw)
    fleet.seed_store(u, m, num_users=U)
    pub = DeltaPublisher(broker, fleet.store)
    return fleet, pub, broker, (u, m)


def _commit(rng, rows, *, num_users=U, cells=()):
    rows = np.asarray(rows, np.int64)
    return {
        "touched_rows": rows.tolist(),
        "rows": rng.standard_normal((rows.size, K)).astype(np.float32),
        "cells": list(cells), "retrain": False, "num_users": num_users,
    }


# -- publisher ---------------------------------------------------------------


def test_publisher_seq_monotonic_across_epochs():
    fleet, pub, broker, (u, m) = _wired()
    rng = np.random.default_rng(1)
    pub.on_commit(_commit(rng, [1, 2]))
    pub.on_commit(_commit(rng, [3]))
    u2, m2 = _factors(9)
    pub.on_commit({"retrain": True, "user_factors": u2,
                   "movie_factors": m2, "num_users": U})
    pub.on_commit(_commit(rng, [4]))
    from cfk_tpu.transport.serdes import decode_factor_delta

    frames = [decode_factor_delta(r.value)
              for r in broker.consume("factor-deltas", 0, 0)]
    assert [f.seq for f in frames] == [1, 2, 3, 4]
    assert [f.kind for f in frames] == ["rows", "rows", "epoch", "rows"]
    assert [f.epoch for f in frames] == [0, 0, 1, 1]
    # the epoch frame carries NO factors — the snapshot is in the store
    assert frames[2].user_rows.size == 0
    snap = fleet.store.state(1)
    np.testing.assert_array_equal(snap["user_factors"], u2)
    # store is written BEFORE the frame is produced: its seq covers the
    # newest frame, so a gap resync never lands behind the log
    assert fleet.store.state()["seq"] == 4


def test_publisher_hot_cold_split_ships_tail_lazy():
    fleet, pub, broker, _ = _wired()
    rng = np.random.default_rng(2)
    # a heavily skewed touch stream: rows 0-2 re-solved every commit,
    # the tail rows exactly once after their first touch
    for i in range(12):
        pub.on_commit(_commit(rng, [0, 1, 2, 10 + i]))
        pub.on_commit(_commit(rng, [0, 1, 2]))
    # later tail touches: by now the knee separates the 3 hot rows
    pub.on_commit(_commit(rng, [0, 10, 11, 12, 13, 14]))
    assert pub.lazy_rows > 0
    assert pub.eager_rows > pub.lazy_rows  # the head ships eagerly
    from cfk_tpu.transport.serdes import decode_factor_delta

    last = decode_factor_delta(
        list(broker.consume("factor-deltas", 0, 0))[-1].value
    )
    assert 0 in last.user_rows.tolist()  # hot row: factors in-frame
    assert last.lazy_user_rows.size > 0  # cold tail: ids only
    # every shipped row — eager AND lazy — is in the store overlay
    snap = fleet.store.state()
    for row in last.lazy_user_rows.tolist():
        assert row in snap["overlay"]


# -- replica apply / crc-exactness -------------------------------------------


def test_replica_apply_matches_direct_engine_crc():
    fleet, pub, broker, (u, m) = _wired()
    oracle = _engine(u, m)
    rng = np.random.default_rng(3)
    replica = fleet.replicas[0]
    for i in range(8):
        ev = _commit(rng, rng.integers(0, U, size=4),
                     cells=[(int(rng.integers(0, U)),
                             int(rng.integers(0, M)))])
        pub.on_commit(ev)
        oracle.on_commit(ev)
    replica.apply_deltas()
    replica.pull_lazy()  # cold rows arrive via the store, not the frame
    assert replica.applied_seq == 8
    assert replica.gaps_detected == 0
    assert table_crc(replica.engine) == table_crc(oracle)


def test_delta_gap_detected_and_resynced_crc_exact():
    from cfk_tpu.resilience.faults import DeltaStreamTamper

    u, m = _factors()
    broker = InMemoryBroker()
    tampered = DeltaStreamTamper(broker, topic="factor-deltas", hide=[3])
    fleet = ServeFleet(lambda i: _engine(u, m), tampered, replicas=1)
    fleet.seed_store(u, m, num_users=U)
    pub = DeltaPublisher(broker, fleet.store)  # publishes to the REAL log
    oracle = _engine(u, m)
    rng = np.random.default_rng(4)
    replica = fleet.replicas[0]
    for i in range(6):
        ev = _commit(rng, rng.integers(0, U, size=3))
        pub.on_commit(ev)
        oracle.on_commit(ev)
    replica.apply_deltas()
    replica.pull_lazy()
    # the hidden frame (offset 3 = seq 4) forced the gap path
    assert tampered.hidden >= 1
    assert replica.gaps_detected == 1
    assert replica.resyncs == 1
    # recovery contract: bit-exact vs an engine that saw EVERY commit
    assert replica.applied_seq == 6
    assert table_crc(replica.engine) == table_crc(oracle)


def test_undecodable_delta_frame_takes_gap_path():
    from cfk_tpu.resilience.faults import DeltaStreamTamper

    u, m = _factors()
    broker = InMemoryBroker()
    tampered = DeltaStreamTamper(broker, topic="factor-deltas", hide=[1],
                                 mode="truncate")
    fleet = ServeFleet(lambda i: _engine(u, m), tampered, replicas=1)
    fleet.seed_store(u, m, num_users=U)
    pub = DeltaPublisher(broker, fleet.store)
    oracle = _engine(u, m)
    rng = np.random.default_rng(5)
    replica = fleet.replicas[0]
    for i in range(4):
        ev = _commit(rng, [int(rng.integers(0, U))])
        pub.on_commit(ev)
        oracle.on_commit(ev)
    replica.apply_deltas()
    replica.pull_lazy()
    assert tampered.truncated >= 1
    assert replica.gaps_detected >= 1 and replica.resyncs >= 1
    assert table_crc(replica.engine) == table_crc(oracle)


def test_duplicate_delta_delivery_is_idempotent():
    # at-least-once delivery: the same frames consumed twice apply once
    fleet, pub, broker, (u, m) = _wired()
    oracle = _engine(u, m)
    rng = np.random.default_rng(6)
    replica = fleet.replicas[0]
    for i in range(3):
        ev = _commit(rng, [i, i + 10])
        pub.on_commit(ev)
        oracle.on_commit(ev)
    replica.apply_deltas()
    replica._delta_cursor = 0  # replay the whole log (rebalance replay)
    replica.apply_deltas()
    replica.pull_lazy()
    assert replica.applied_seq == 3
    assert replica.gaps_detected == 0
    assert table_crc(replica.engine) == table_crc(oracle)


# -- rollover ----------------------------------------------------------------


def test_rollover_flips_epoch_and_applies_deferred_deltas():
    fleet, pub, broker, (u, m) = _wired()
    rng = np.random.default_rng(7)
    replica = fleet.replicas[0]
    pub.on_commit(_commit(rng, [1]))
    replica.pump()
    assert replica.engine.epoch == 0
    u2, m2 = _factors(21)
    pub.on_commit({"retrain": True, "user_factors": u2,
                   "movie_factors": m2, "num_users": U})
    # rows for the NEW epoch arriving before this replica has flipped:
    # must be deferred, then applied post-flip
    late = _commit(rng, [5, 6])
    pub.on_commit(late)
    deadline = time.monotonic() + 30
    while replica.rollovers == 0 and time.monotonic() < deadline:
        replica.pump()
        time.sleep(0.01)
    assert replica.rollovers == 1
    assert replica.engine.epoch == 1
    replica.pump()  # drain anything the flip left pending
    assert replica.applied_seq == 3
    # the deferred commit landed on the NEW engine
    oracle = _engine(u2, m2)
    oracle.epoch = 1
    oracle.on_commit(late)
    assert table_crc(replica.engine) == table_crc(oracle)
    # old epoch's overlay did NOT leak into the new table
    assert 1 not in replica.engine._u_hot


def test_rollover_serves_old_epoch_until_flip():
    fleet, pub, broker, (u, m) = _wired()
    ensure_serve_topics(broker)
    client = ServeClient(broker)
    replica = fleet.replicas[0]
    fleet.prewarm(3, max_batch=8)
    got = client.ask([1], 3, server=replica.server)
    assert next(iter(got.values())).epoch == 0
    u2, m2 = _factors(22)
    pub.on_commit({"retrain": True, "user_factors": u2,
                   "movie_factors": m2, "num_users": U})
    replica.apply_deltas()  # starts the background prewarm
    # until the new engine is ready, answers still come from epoch 0 —
    # zero downtime, and every response is stamped with ONE epoch
    got = client.ask([2], 3, server=replica.server)
    assert next(iter(got.values())).epoch in (0, 1)
    deadline = time.monotonic() + 30
    while replica.rollovers == 0 and time.monotonic() < deadline:
        replica.pump()
        time.sleep(0.01)
    got = client.ask([3], 3, server=replica.server)
    resp = next(iter(got.values()))
    assert resp.epoch == 1
    # post-flip answers score the NEW table exactly
    fresh = _engine(u2, m2)
    s, i = fresh.topk(np.asarray([3]), 3)
    np.testing.assert_array_equal(resp.movie_rows, i[0])
    np.testing.assert_array_equal(resp.scores, s[0])


# -- admission control -------------------------------------------------------


def test_admission_bounds_queue_with_retriable_rejections():
    u, m = _factors()
    broker = InMemoryBroker()
    ensure_serve_topics(broker)
    server = RecommendServer(
        _engine(u, m), broker,
        admission=AdmissionController(max_queue=2),
    )
    client = ServeClient(broker)
    ids = [client.request(i, 3) for i in range(6)]
    client.flush()
    assert server.step() == 6  # every request ANSWERED (2 scored, 4 shed)
    by_id = {r.req_id: r for r in client.poll_responses()}
    assert len(by_id) == 6
    shed = [r for r in by_id.values() if r.retriable]
    ok = [r for r in by_id.values() if not r.error]
    assert len(ok) == 2 and len(shed) == 4
    assert all("overloaded" in r.error for r in shed)
    assert server.shed == 4
    # FIFO: the first two req_ids got real answers
    assert not by_id[ids[0]].error and not by_id[ids[1]].error


def test_admission_capacity_qps_sizing():
    a = AdmissionController(capacity_qps=1000.0, max_queue_s=0.05)
    assert a.max_queue == 50
    with pytest.raises(ValueError):
        AdmissionController()


def test_client_retries_through_shedding():
    # a shed request is re-sent after backoff and eventually answered —
    # injectable sleep so the test asserts the schedule without waiting
    u, m = _factors()
    broker = InMemoryBroker()
    ensure_serve_topics(broker)
    server = RecommendServer(
        _engine(u, m), broker,
        admission=AdmissionController(max_queue=2),
    )
    client = ServeClient(broker)
    slept = []
    got = client.ask(list(range(6)), 3, server=server, retries=4,
                     rng=np.random.default_rng(0), sleep=slept.append)
    assert len(got) == 6
    assert all(not r.error for r in got.values())
    assert client.rejections >= 4  # the shed really happened
    assert client.retries >= 4  # and the re-sends really happened
    # backoff schedule: positive, and the base delays grow exponentially
    assert slept and all(s > 0 for s in slept)


def test_client_retry_exhaustion_raises_timeout():
    u, m = _factors()
    broker = InMemoryBroker()
    ensure_serve_topics(broker)
    client = ServeClient(broker)
    slept = []
    with pytest.raises(TimeoutError, match="attempts"):
        # no server at all: every attempt times out, then raises
        client.ask([1], 3, timeout_s=0.2, retries=2,
                   rng=np.random.default_rng(0), sleep=slept.append)
    assert client.retries == 2
    assert len(slept) >= 2  # one backoff per retry


# -- fleet: routing, failover, staleness -------------------------------------


def test_fleet_user_keyed_routing_partitions_traffic():
    fleet, pub, broker, _ = _wired(replicas=2)
    client = ServeClient(broker, route_by_user=True)
    for user in range(8):
        client.request(user, 3)
    client.flush()
    # user % 2 routing: each replica's partition holds exactly its users
    from cfk_tpu.transport.serdes import decode_score_request

    for part in (0, 1):
        users = [decode_score_request(r.value).user
                 for r in broker.consume("serve-requests", part, 0)]
        assert users == [u for u in range(8) if u % 2 == part]


def test_fleet_kill_failover_answers_every_accepted_request():
    fleet, pub, broker, _ = _wired(replicas=2)
    fleet.prewarm(3, max_batch=8)
    fleet.start()
    client = ServeClient(broker, route_by_user=True)
    try:
        got = client.ask(list(range(16)), 3, timeout_s=20)
        assert len(got) == 16
        fleet.kill_replica(0)
        assert not fleet.replicas[0].alive and fleet.replicas[1].alive
        # partition 0's users are now served by the survivor
        got = client.ask(list(range(16)), 3, timeout_s=20)
        assert len(got) == 16
        assert all(not r.error for r in got.values())
        assert fleet.counters()["failovers"] == 1
    finally:
        fleet.stop()


def test_failover_reserves_uncommitted_requests_at_least_once():
    # the victim polled (cursor advanced) but died before answering
    # (committed cursor did not): the survivor must re-serve from the
    # COMMITTED cursor, so the request is answered, not lost
    fleet, pub, broker, _ = _wired(replicas=2)
    client = ServeClient(broker, route_by_user=True)
    victim, heir = fleet.replicas
    rid = client.request(0, 3)  # user 0 -> partition 0 (victim)
    client.flush()
    victim.server._poll_requests()  # polled... then killed mid-batch
    assert victim.server._cursors[0] == 1
    assert victim.server.committed_cursors[0] == 0
    victim.kill()
    fleet.failover(0)
    heir.pump()
    by_id = {r.req_id: r for r in client.poll_responses()}
    assert rid in by_id and not by_id[rid].error


def test_responses_stamped_with_staleness_backlog():
    fleet, pub, broker, _ = _wired()
    ensure_serve_topics(broker)
    rng = np.random.default_rng(8)
    replica = fleet.replicas[0]
    client = ServeClient(broker)
    for _ in range(3):
        pub.on_commit(_commit(rng, [1]))
    # serve WITHOUT applying: the stamp must expose the 3-frame backlog
    client.request(2, 3)
    client.flush()
    replica.server.step()
    resp = client.poll_responses()[0]
    assert resp.staleness == 3
    replica.apply_deltas()
    client.request(2, 3)
    client.flush()
    replica.server.step()
    assert client.poll_responses()[0].staleness == 0


# -- readiness ---------------------------------------------------------------


def test_readyz_gated_on_prewarm():
    u, m = _factors()
    broker = InMemoryBroker()
    ensure_serve_topics(broker)
    server = RecommendServer(_engine(u, m), broker, metrics_port=0,
                             labels={"replica": 3})
    try:
        base = f"http://127.0.0.1:{server.metrics_server.port}"
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(f"{base}/readyz", timeout=5)
        assert exc.value.code == 503  # alive but NOT ready (no prewarm)
        with urllib.request.urlopen(f"{base}/healthz", timeout=5) as r:
            assert r.status == 200  # liveness is a different question
        server.engine.prewarm(3, max_batch=8)
        with urllib.request.urlopen(f"{base}/readyz", timeout=5) as r:
            assert r.status == 200
        # per-replica constant labels ride every sample (PR 16 seam)
        client = ServeClient(broker)
        client.ask([1], 3, server=server)
        with urllib.request.urlopen(f"{base}/metrics", timeout=5) as r:
            text = r.read().decode()
        assert 'replica="3"' in text
    finally:
        server.close()


def test_fleet_ready_property():
    fleet, pub, broker, _ = _wired(replicas=2)
    assert not fleet.ready
    fleet.prewarm(3, max_batch=8)
    assert fleet.ready


# -- commit-listener isolation -----------------------------------------------


def test_broken_commit_listener_does_not_poison_stream(tmp_path):
    # ISSUE 18 satellite: a serving subscriber that raises must not kill
    # the training stream or starve the OTHER listeners
    from cfk_tpu.config import ALSConfig
    from cfk_tpu.data.blocks import Dataset
    from cfk_tpu.data.synthetic import synthetic_netflix_coo
    from cfk_tpu.models.als import train_als
    from cfk_tpu.streaming import StreamConfig, StreamProducer, StreamSession
    from cfk_tpu.transport.checkpoint import CheckpointManager

    ds = Dataset.from_coo(synthetic_netflix_coo(40, 20, 400, seed=1))
    cfg = ALSConfig(rank=4, num_iterations=2)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        model = train_als(ds, cfg)
    broker = InMemoryBroker()
    prod = StreamProducer(broker)
    prod.send(int(ds.user_map.raw_ids[0]), int(ds.movie_map.raw_ids[1]), 5.0)
    sess = StreamSession(
        ds, cfg, broker, CheckpointManager(str(tmp_path)),
        stream=StreamConfig(batch_records=8), base_model=model,
    )

    def bomb(event):
        raise RuntimeError("replica fell over")

    seen = []
    sess.add_commit_listener(bomb)
    sess.add_commit_listener(seen.append)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        sess.run()  # must NOT raise
    assert len(seen) == 1  # the healthy listener still got the commit
    assert sess.metrics.counters.get("commit_listener_errors", 0) >= 1


def test_publisher_end_to_end_with_stream_session(tmp_path):
    # the full wire: StreamSession commit -> DeltaPublisher frame ->
    # FleetReplica apply -> served scores match an attached engine's
    from cfk_tpu.config import ALSConfig
    from cfk_tpu.data.blocks import Dataset
    from cfk_tpu.data.synthetic import synthetic_netflix_coo
    from cfk_tpu.models.als import train_als
    from cfk_tpu.streaming import StreamConfig, StreamProducer, StreamSession
    from cfk_tpu.transport.checkpoint import CheckpointManager

    ds = Dataset.from_coo(synthetic_netflix_coo(40, 20, 400, seed=2))
    cfg = ALSConfig(rank=4, num_iterations=2)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        model = train_als(ds, cfg)
    nu = ds.user_map.num_entities
    nm = ds.movie_map.num_entities
    broker = InMemoryBroker()

    def factory(i):
        return ServeEngine(model.user_factors, model.movie_factors,
                           num_users=nu, num_movies=nm, tile_m=16)

    fleet = ServeFleet(factory, broker, replicas=1)
    fleet.seed_store(model.user_factors, model.movie_factors, num_users=nu)
    pub = DeltaPublisher(broker, fleet.store)
    prod = StreamProducer(broker)
    prod.send(int(ds.user_map.raw_ids[0]), int(ds.movie_map.raw_ids[1]), 5.0)
    sess = StreamSession(
        ds, cfg, broker, CheckpointManager(str(tmp_path)),
        stream=StreamConfig(batch_records=8), base_model=model,
    )
    attached = ServeEngine(model.user_factors, model.movie_factors,
                           num_users=nu, num_movies=nm, tile_m=16)
    attached.attach_session(sess)
    pub.attach(sess)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        sess.run()
    replica = fleet.replicas[0]
    replica.pump()
    assert replica.applied_seq >= 1
    assert table_crc(replica.engine) == table_crc(attached)
