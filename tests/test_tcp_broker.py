"""Native TCP broker: Transport conformance, durability, FileBroker interop.

The broker process (``native/cfk_broker.cpp``) fills the reference's
Kafka-broker role (``dev/docker-compose.yaml:18-31``): a network service of
partitioned, offset-addressed durable logs.  These tests run the same
contract checks as the in-process Transports, plus the cross-implementation
property the design promises: the broker's on-disk format IS FileBroker's,
so either side can read what the other wrote.
"""

import os

import numpy as np
import pytest

from cfk_tpu.transport import (
    BrokerProcess,
    BrokerRequestError,
    FileBroker,
    IncompleteIngestError,
    RATINGS_TOPIC,
    collect_ratings,
    produce_ratings_file,
)
from cfk_tpu.transport.tcp import build_broker

TINY = "/root/reference/data/data_sample_tiny.txt"

pytestmark = pytest.mark.skipif(
    not build_broker(), reason="cfk_broker binary unavailable (g++/make missing)"
)


@pytest.fixture(scope="module")
def server():
    with BrokerProcess() as bp:
        yield bp


def test_roundtrip_and_mod_partitioning(server):
    with server.connect() as c:
        c.ping()
        c.create_topic("t-round", 4)
        for k in range(10):
            c.produce("t-round", key=k, value=bytes([k]))
        c.produce("t-round", key=-1, value=b"eof", partition=2)
        assert c.num_partitions("t-round") == 4
        for p in range(4):
            for r in c.consume("t-round", p):
                if r.key >= 0:
                    assert r.key % 4 == p
        assert [r.key for r in c.consume("t-round", 2)] == [2, 6, -1]
        assert [r.value for r in c.consume("t-round", 2)] == [
            bytes([2]), bytes([6]), b"eof",
        ]
        assert c.end_offset("t-round", 2) == 3
        assert [r.key for r in c.consume("t-round", 2, start_offset=2)] == [-1]
        assert "t-round" in c.topics()


def test_read_your_writes_across_batching(server):
    # produce() buffers client-side; every read op must flush first.
    with server.connect(batch_records=10_000) as c:
        c.create_topic("t-ryw", 2)
        for k in range(7):
            c.produce("t-ryw", key=k, value=b"x" * k)
        assert c.end_offset("t-ryw", 0) == 4  # 0,2,4,6
        assert [len(r.value) for r in c.consume("t-ryw", 1)] == [1, 3, 5]


def test_two_clients_see_each_other(server):
    # Cross-process visibility is the whole point of a broker *server*.
    with server.connect() as a, server.connect() as b:
        a.create_topic("t-xc", 1)
        a.produce("t-xc", key=1, value=b"from-a")
        a.flush()
        assert [r.value for r in b.consume("t-xc", 0)] == [b"from-a"]


def test_errors(server):
    with server.connect() as c:
        with pytest.raises(KeyError):
            c.num_partitions("no-such-topic")
        with pytest.raises(KeyError):
            list(c.consume("no-such-topic", 0))
        c.create_topic("t-err", 2)
        with pytest.raises(ValueError):
            c.create_topic("t-err", 2)  # duplicate
        with pytest.raises(ValueError):
            c.produce("t-err", key=-1, value=b"")  # negative key, no partition
        with pytest.raises(BrokerRequestError):
            c.end_offset("t-err", 99)  # partition out of range
        with pytest.raises(ValueError):
            c.create_topic("t-zero", 0)


def test_large_values_cross_fetch_batches(server):
    with server.connect(fetch_records=3, fetch_bytes=1 << 14) as c:
        c.create_topic("t-big", 1)
        values = [os.urandom(4000) for _ in range(10)]
        for i, v in enumerate(values):
            c.produce("t-big", key=i, value=v, partition=0)
        got = list(c.consume("t-big", 0))
        assert [r.value for r in got] == values
        assert [r.offset for r in got] == list(range(10))


@pytest.mark.reference_data
def test_ingest_eof_barrier_over_tcp(server):
    # The reference's end-to-end ingest contract (producer EOF fan-out +
    # barrier check) running against a real broker process.
    with server.connect() as c:
        c.create_topic(RATINGS_TOPIC, 4)
        n = produce_ratings_file(c, TINY)
        c.flush()
        coo = collect_ratings(c)
        assert coo.num_ratings == n == 3415
        c.delete_topic(RATINGS_TOPIC)


@pytest.mark.reference_data
def test_ingest_missing_eof_fails_loudly(server):
    with server.connect() as c:
        c.create_topic("ratings-fault", 4)
        produce_ratings_file(c, TINY, topic="ratings-fault", drop_eof_for={1, 3})
        with pytest.raises(IncompleteIngestError, match=r"\[1, 3\]"):
            collect_ratings(c, topic="ratings-fault")
        c.delete_topic("ratings-fault")


def test_durability_across_restart(tmp_path):
    data_dir = str(tmp_path / "broker-data")
    with BrokerProcess(data_dir=data_dir) as bp:
        with bp.connect() as c:
            c.create_topic("t-dur", 2)
            for k in range(6):
                c.produce("t-dur", key=k, value=f"v{k}".encode())
    # new server process over the same directory: full recovery
    with BrokerProcess(data_dir=data_dir) as bp2:
        with bp2.connect() as c:
            assert c.num_partitions("t-dur") == 2
            assert [(r.key, r.value) for r in c.consume("t-dur", 0)] == [
                (0, b"v0"), (2, b"v2"), (4, b"v4"),
            ]
            c.produce("t-dur", key=6, value=b"v6")
            assert [r.key for r in c.consume("t-dur", 0)] == [0, 2, 4, 6]


@pytest.mark.reference_data
def test_filebroker_reads_broker_data_dir(tmp_path):
    data_dir = str(tmp_path / "shared")
    with BrokerProcess(data_dir=data_dir) as bp:
        with bp.connect() as c:
            c.create_topic(RATINGS_TOPIC, 4)
            produce_ratings_file(c, TINY)
    # Server gone; the same directory opens as a FileBroker and the full
    # ingest barrier passes on its logs.
    with FileBroker(data_dir) as fb:
        coo = collect_ratings(fb)
        assert coo.num_ratings == 3415


def test_broker_reads_filebroker_data_dir(tmp_path):
    data_dir = str(tmp_path / "shared2")
    with FileBroker(data_dir, fsync=False) as fb:
        fb.create_topic("t-interop", 3)
        for k in range(9):
            fb.produce("t-interop", key=k, value=bytes([100 + k]))
    with BrokerProcess(data_dir=data_dir) as bp:
        with bp.connect() as c:
            assert c.num_partitions("t-interop") == 3
            assert [r.key for r in c.consume("t-interop", 1)] == [1, 4, 7]
            assert [r.value for r in c.consume("t-interop", 1)] == [
                bytes([101]), bytes([104]), bytes([107]),
            ]


def test_torn_tail_recovery(tmp_path):
    data_dir = str(tmp_path / "torn")
    with BrokerProcess(data_dir=data_dir) as bp:
        with bp.connect() as c:
            c.create_topic("t-torn", 1)
            c.produce("t-torn", key=1, value=b"aaaa", partition=0)
            c.produce("t-torn", key=2, value=b"bbbb", partition=0)
    log = os.path.join(data_dir, "t-torn", "p00000.log")
    size = os.path.getsize(log)
    with open(log, "r+b") as f:  # crash mid-append: chop the final frame
        f.truncate(size - 3)
    with BrokerProcess(data_dir=data_dir) as bp2:
        with bp2.connect() as c:
            assert [r.key for r in c.consume("t-torn", 0)] == [1]
            c.produce("t-torn", key=3, value=b"cccc", partition=0)
            assert [r.key for r in c.consume("t-torn", 0)] == [1, 3]


def test_consume_snapshots_log_end(server):
    # A concurrent producer must not turn the iterator into an endless tail:
    # records appended mid-iteration are not yielded.
    with server.connect(fetch_records=2) as a, server.connect() as b:
        a.create_topic("t-snap", 1)
        for k in range(6):
            a.produce("t-snap", key=k, value=b"v", partition=0)
        a.flush()
        seen = []
        it = a.consume("t-snap", 0)
        for r in it:
            seen.append(r.key)
            if len(seen) == 2:  # mid-iteration append from another client
                b.produce("t-snap", key=99, value=b"late", partition=0)
                b.flush()
        assert seen == [0, 1, 2, 3, 4, 5]
        # a fresh consume sees the late record
        assert [r.key for r in a.consume("t-snap", 0, start_offset=6)] == [99]


def test_flush_is_retriable_after_unknown_topic(server):
    with server.connect() as c:
        c.create_topic("t-keep", 1)
        # Buffer records for a topic that does not exist yet plus one that
        # does; the server validates batches before appending, so a failed
        # flush loses nothing — create the topic and flush again.
        c.produce("t-nonexistent", key=1, value=b"a", partition=0)
        c.produce("t-keep", key=2, value=b"b", partition=0)
        with pytest.raises(KeyError):
            c.flush()
        c.create_topic("t-nonexistent", 1)
        c.flush()
        assert [r.key for r in c.consume("t-keep", 0)] == [2]
        assert [r.key for r in c.consume("t-nonexistent", 0)] == [1]


def test_rejected_batch_appends_nothing(server):
    # All-or-nothing produce: a batch with one bad record commits no prefix.
    with server.connect() as c:
        c.create_topic("t-atomic", 2)
        c.produce("t-atomic", key=1, value=b"ok")
        c.produce("t-atomic", key=2, value=b"bad", partition=7)  # out of range
        from cfk_tpu.transport import BrokerRequestError

        with pytest.raises(BrokerRequestError, match="out of range"):
            c.flush()
        # fresh client: nothing from the rejected batch landed
        with server.connect() as c2:
            assert c2.end_offset("t-atomic", 0) == 0
            assert c2.end_offset("t-atomic", 1) == 0


@pytest.mark.reference_data
def test_multi_file_produce_with_no_eof(server, capsys):
    from cfk_tpu.cli import main

    url = f"tcp://127.0.0.1:{server.port}/ratings-multi"
    assert main(["produce", "--broker", url, "--data", TINY,
                 "--partitions", "2", "--no-eof"]) == 0
    assert "open (no EOF yet)" in capsys.readouterr().err
    with server.connect() as c:  # not finalized: the barrier refuses it
        with pytest.raises(IncompleteIngestError):
            collect_ratings(c, topic="ratings-multi")
    # second file finalizes; totals add up
    assert main(["produce", "--broker", url, "--data", TINY,
                 "--append"]) == 0
    with server.connect() as c:
        coo = collect_ratings(c, topic="ratings-multi")
        assert coo.num_ratings == 2 * 3415
        c.delete_topic("ratings-multi")


def test_bad_broker_urls():
    from cfk_tpu.cli import _parse_tcp_url

    for bad in ("localhost:29092", "tcp://:12", "tcp://h:", "tcp://h:abc"):
        with pytest.raises(ValueError, match="expected tcp://"):
            _parse_tcp_url(bad)
    assert _parse_tcp_url("tcp://h:1/topic") == ("h", 1, "topic")


@pytest.mark.reference_data
def test_cli_produce_then_train_from_broker(server, capsys, tmp_path):
    # The reference's producer → broker → app process split as CLI commands.
    from cfk_tpu.cli import main

    url = f"tcp://127.0.0.1:{server.port}/ratings-cli"
    assert main(["produce", "--broker", url, "--data", TINY,
                 "--partitions", "4"]) == 0
    assert "produced 3415 ratings" in capsys.readouterr().err
    pred = str(tmp_path / "pred.csv")
    rc = main([
        "train", "--data", url, "--rank", "4", "--iterations", "2",
        "--seed", "0", "--output", pred, "--metrics", "json",
    ])
    assert rc == 0
    assert os.path.exists(pred)
    # stale-EOF guard: un-flagged re-produce into the same topic is refused
    assert main(["produce", "--broker", url, "--data", TINY]) == 1


@pytest.mark.reference_data
def test_cli_tcp_dataset_cache_fingerprints_offsets(server, capsys, tmp_path):
    """The dataset cache's build key for tcp:// sources is the topic's
    per-partition end offsets: same log → cache hit; a topic with different
    contents at the same URL → rebuild, never silent reuse of stale blocks."""
    from cfk_tpu.cli import main

    url = f"tcp://127.0.0.1:{server.port}/ratings-cache-fp"
    cache = str(tmp_path / "dscache")
    train = [
        "train", "--data", url, "--rank", "3", "--iterations", "1",
        "--seed", "0", "--dataset-cache", cache, "--output", "none",
        "--metrics", "json",
    ]
    assert main(["produce", "--broker", url, "--data", TINY,
                 "--partitions", "2"]) == 0
    capsys.readouterr()
    assert main(train) == 0
    capsys.readouterr()
    assert main(train) == 0  # same offsets → cache hit
    assert "ignoring dataset cache" not in capsys.readouterr().err
    # same URL, different log contents (re-produced with more partitions →
    # different per-partition offsets) → the cache must be rebuilt
    with server.connect() as c:
        c.delete_topic("ratings-cache-fp")
    assert main(["produce", "--broker", url, "--data", TINY,
                 "--partitions", "4"]) == 0
    capsys.readouterr()
    assert main(train) == 0
    assert "ignoring dataset cache" in capsys.readouterr().err


@pytest.mark.reference_data
def test_cli_tcp_cache_works_with_broker_down(capsys, tmp_path):
    """A matching tcp-sourced cache still trains with the broker gone —
    the offset freshness check is skipped with a warning, the other build-key
    fields must still match exactly."""
    from cfk_tpu.cli import main

    cache = str(tmp_path / "dscache")
    with BrokerProcess() as bp:
        url = f"tcp://127.0.0.1:{bp.port}/ratings-offline"
        assert main(["produce", "--broker", url, "--data", TINY,
                     "--partitions", "2"]) == 0
        train = [
            "train", "--data", url, "--rank", "3", "--iterations", "1",
            "--seed", "0", "--dataset-cache", cache, "--output", "none",
            "--metrics", "json",
        ]
        assert main(train) == 0
    capsys.readouterr()
    # broker process is dead now; same command must run from the cache
    assert main(train) == 0
    err = capsys.readouterr().err
    assert "broker unreachable" in err
    # but a cache from different layout flags must NOT be used offline
    assert main(train + ["--layout", "segment"]) == 1
    assert "error:" in capsys.readouterr().err


@pytest.mark.reference_data
def test_end_to_end_train_from_tcp_ingest(server):
    # Full pipeline: broker ingest → blocks → ALS → finite predictions.
    from cfk_tpu.config import ALSConfig
    from cfk_tpu.data.blocks import Dataset
    from cfk_tpu.models.als import train_als

    with server.connect() as c:
        c.create_topic("ratings-e2e", 2)
        produce_ratings_file(c, TINY, topic="ratings-e2e")
        coo = collect_ratings(c, topic="ratings-e2e")
        c.delete_topic("ratings-e2e")
    ds = Dataset.from_coo(coo)
    model = train_als(ds, ALSConfig(rank=4, lam=0.05, num_iterations=2, seed=0))
    preds = model.predict_dense()
    assert np.all(np.isfinite(preds))


def test_delete_topic_releases_pending_counters(server):
    # Dropping a topic's buffered records must also drop their byte/record
    # counts, or the next produce flushes a near-empty batch immediately.
    with server.connect(batch_records=50) as c:
        c.create_topic("counters-a", 2)
        c.create_topic("counters-b", 2)
        for i in range(40):
            c.produce("counters-a", i, b"v")
        c.delete_topic("counters-a")
        assert c._pending_count == 0 and c._pending_bytes == 0
        for i in range(40):  # under batch_records: must stay buffered
            c.produce("counters-b", i, b"w")
        assert c._pending_count == 40
        c.delete_topic("counters-b")


def test_oversized_record_rejected_on_client(server):
    # The server closes the connection on an over-cap frame with no error
    # response; the client must refuse the record up front instead.
    from cfk_tpu.transport.tcp import _MAX_BATCH_BYTES

    with server.connect() as c:
        c.create_topic("oversize", 1)
        with pytest.raises(ValueError, match="frame budget"):
            c.produce("oversize", 0, b"x" * (_MAX_BATCH_BYTES + 1))
        c.delete_topic("oversize")


def test_flush_splits_batches_under_frame_cap(server, monkeypatch):
    # A buffered batch larger than the server's request cap ships as several
    # PRODUCE_BATCH requests, none over the cap.
    import cfk_tpu.transport.tcp as tcp_mod

    monkeypatch.setattr(tcp_mod, "_MAX_BATCH_BYTES", 4096)
    with server.connect(batch_records=10_000, batch_bytes=1 << 30) as c:
        c.create_topic("split", 2)
        payload = b"p" * 1500
        for i in range(20):  # ~30 KiB pending >> patched 4 KiB cap
            c.produce("split", i, payload)
        c.flush()
        got = sum(1 for _ in c.consume("split", 0))
        got += sum(1 for _ in c.consume("split", 1))
        assert got == 20
        c.delete_topic("split")


def test_exit_does_not_mask_body_exception(server):
    # close() on the exception path must not flush (a failing exit-time
    # request would replace the body's error).
    with pytest.raises(RuntimeError, match="the real error"):
        with server.connect() as c:
            c.create_topic("mask", 1)
            c.produce("nonexistent-topic", 0, b"v")  # would KeyError on flush
            raise RuntimeError("the real error")
    with server.connect() as c:
        c.delete_topic("mask")


def test_cli_topics_admin(server, capsys):
    # The reference's setup.sh role (delete + recreate topics) as a CLI.
    from cfk_tpu.cli import main

    base = f"tcp://127.0.0.1:{server.port}"
    assert main(["topics", "create", "--broker", f"{base}/adm",
                 "--partitions", "3"]) == 0
    assert main(["topics", "list", "--broker", base]) == 0
    out = capsys.readouterr().out
    assert "adm\tpartitions=3" in out
    assert main(["topics", "recreate", "--broker", f"{base}/adm",
                 "--partitions", "5"]) == 0
    assert main(["topics", "list", "--broker", base]) == 0
    assert "adm\tpartitions=5" in capsys.readouterr().out
    assert main(["topics", "delete", "--broker", f"{base}/adm"]) == 0
    assert main(["topics", "list", "--broker", base]) == 0
    assert "adm" not in capsys.readouterr().out
    # create without a topic segment is a clean one-line error
    assert main(["topics", "create", "--broker", base]) == 1


# --- fault injection: flaky connections / delayed frames -------------------
# (cfk_tpu.resilience.faults.FlakyBrokerProxy; ISSUE 3 chaos harness)


def test_connect_retry_survives_dropped_connections(server):
    from cfk_tpu.resilience.faults import FlakyBrokerProxy, FlakyPlan
    from cfk_tpu.transport.tcp import TcpBrokerClient

    plan = FlakyPlan(drop_first_connects=2)
    with FlakyBrokerProxy(server.port, plan) as proxy:
        with TcpBrokerClient(
            "127.0.0.1", proxy.port, connect_retries=4, retry_base=0.01
        ) as c:
            c.create_topic("t-flaky", 2)
            c.produce("t-flaky", key=0, value=b"survived")
            assert [r.value for r in c.consume("t-flaky", 0)] == [b"survived"]
            c.delete_topic("t-flaky")
        assert proxy.dropped == 2  # the fault really fired


def test_delayed_frames_waited_out_by_read_retries(server):
    from cfk_tpu.resilience.faults import FlakyBrokerProxy, FlakyPlan
    from cfk_tpu.transport.tcp import TcpBrokerClient

    plan = FlakyPlan(delay_frames=3, frame_delay=0.12)
    with FlakyBrokerProxy(server.port, plan) as proxy:
        with TcpBrokerClient(
            "127.0.0.1", proxy.port,
            read_timeout=0.05, read_retries=20,
        ) as c:
            c.ping()
            c.create_topic("t-slow", 1)
            c.produce("t-slow", key=0, value=b"late but intact")
            assert [r.value for r in c.consume("t-slow", 0)] == [
                b"late but intact"
            ]
            c.delete_topic("t-slow")
        assert proxy.delayed >= 1


def test_connect_gives_up_after_bounded_retries():
    import socket

    from cfk_tpu.transport.tcp import TcpBrokerClient

    # a bound-but-not-listening port refuses instantly
    probe = socket.socket()
    probe.bind(("127.0.0.1", 0))
    port = probe.getsockname()[1]
    probe.close()
    with pytest.raises(OSError, match="after 2 attempts"):
        TcpBrokerClient(
            "127.0.0.1", port, connect_retries=1, retry_base=0.01
        )
