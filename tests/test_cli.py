"""CLI tests: reference-compatible run form, flag form, and the evaluator."""

import json

import numpy as np
import pytest

from cfk_tpu.cli import main

TINY = "/root/reference/data/data_sample_tiny.txt"


@pytest.mark.reference_data
def test_run_reference_form(capsys, tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)  # predictions/ lands under tmp
    rc = main(["run", "4", "5", "0.05", "7", TINY, "426", "302"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "MSE:" in out and "RMSE:" in out
    mse = float(out.split("MSE:")[1].split()[0])
    assert mse <= 0.30


@pytest.mark.reference_data
def test_run_warns_on_wrong_counts(capsys, tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    rc = main(["run", "4", "3", "0.05", "1", TINY, "9999", "1"])
    assert rc == 0
    err = capsys.readouterr().err
    assert "warning: NUM_MOVIES=9999" in err
    assert "warning: NUM_USERS=1" in err


@pytest.mark.reference_data
def test_train_and_evaluate_roundtrip(capsys, tmp_path):
    pred = str(tmp_path / "pred.csv")
    rc = main([
        "train", "--data", TINY, "--rank", "5", "--lam", "0.05",
        "--iterations", "7", "--seed", "0", "--output", pred,
        "--metrics", "json",
    ])
    assert rc == 0
    captured = capsys.readouterr()
    metrics = json.loads(captured.out.strip().splitlines()[-1])
    assert metrics["gauges"]["mse"] <= 0.27
    assert metrics["counters"]["iterations"] == 7
    assert metrics["phase_seconds"]["train"] > 0

    rc = main(["evaluate", TINY, pred])
    assert rc == 0
    out = capsys.readouterr().out
    mse = float(out.split("MSE:")[1].split()[0])
    assert mse <= 0.27


@pytest.mark.reference_data
def test_auto_layout_resolution(capsys, monkeypatch):
    """--layout auto (the default): padded below the threshold, tiled
    above, and ring/auto exchanges force tiled up front."""
    import cfk_tpu.cli as cli

    class _Coo:
        num_ratings = 100

    assert cli._resolve_auto_layout(_Coo()) == "padded"
    _Coo.num_ratings = cli.AUTO_LAYOUT_TILED_NNZ
    assert cli._resolve_auto_layout(_Coo()) == "tiled"
    # End-to-end: tiny data under auto trains on the padded path and the
    # resolved layout reaches the config (no 'auto' leaks into ALSConfig).
    rc = main(["train", "--data", TINY, "--rank", "3", "--iterations", "2",
               "--seed", "0", "--output", "none"])
    assert rc == 0
    # Forcing the threshold to 0 makes the same data resolve to tiled.
    monkeypatch.setattr(cli, "AUTO_LAYOUT_TILED_NNZ", 0)
    rc = main(["train", "--data", TINY, "--rank", "3", "--iterations", "2",
               "--seed", "0", "--output", "none", "--chunk-elems", "4096"])
    assert rc == 0


@pytest.mark.reference_data
def test_train_survives_unmaterializable_dense_preds(capsys, tmp_path, monkeypatch):
    """At BASELINE scales the dense U·Mᵀ cannot exist; training must still
    finish, report factored train MSE, and only skip the CSV dump."""
    from cfk_tpu.models.als import ALSModel

    def boom(self, *, allow_huge=False):
        raise ValueError("dense prediction matrix would be huge")

    monkeypatch.setattr(ALSModel, "predict_dense", boom)
    rc = main([
        "train", "--data", TINY, "--rank", "3", "--lam", "0.05",
        "--iterations", "2", "--seed", "0",
        "--output", str(tmp_path / "pred.csv"), "--metrics", "json",
    ])
    assert rc == 0
    captured = capsys.readouterr()
    assert "skipping the prediction CSV dump" in captured.err
    assert "RMSE=" in captured.err  # factored MSE eval still ran
    metrics = json.loads(captured.out.strip().splitlines()[-1])
    assert "mse" in metrics["gauges"]


@pytest.mark.reference_data
def test_checkpoint_journal_bad_tcp_url(capsys, tmp_path):
    """A malformed tcp journal target must be a clean flag error, not a
    traceback deep in training."""
    rc = main([
        "train", "--data", TINY, "--rank", "3", "--iterations", "1",
        "--checkpoint-journal", "tcp://nohost", "--output", "none",
    ])
    assert rc == 2
    assert "bad broker url" in capsys.readouterr().err


@pytest.mark.reference_data
def test_checkpoint_journal_conflicts_with_dir(capsys, tmp_path):
    rc = main([
        "train", "--data", TINY, "--rank", "3", "--iterations", "1",
        "--checkpoint-dir", str(tmp_path / "a"),
        "--checkpoint-journal", str(tmp_path / "b"), "--output", "none",
    ])
    assert rc == 2
    assert "mutually exclusive" in capsys.readouterr().err


@pytest.mark.reference_data
def test_evaluate_shape_mismatch(capsys, tmp_path):
    bad = tmp_path / "bad.csv"
    bad.write_text("2 3 real\n1 2 3\n4 5 6\n")
    rc = main(["evaluate", TINY, str(bad)])
    assert rc == 2
    assert "prediction matrix is" in capsys.readouterr().err


@pytest.mark.reference_data
def test_predict_from_checkpoint(capsys, tmp_path):
    """train --checkpoint-dir, then predict + evaluate without retraining:
    the standalone dump must score identically to the train-time metrics."""
    import re

    from cfk_tpu.cli import main

    data = "/root/reference/data/data_sample_tiny.txt"
    ck = str(tmp_path / "ck")
    assert main([
        "train", "--data", data, "--rank", "4", "--iterations", "2",
        "--seed", "0", "--checkpoint-dir", ck, "--output", "none",
    ]) == 0
    rmse_train = re.search(r"RMSE=([0-9.]+)", capsys.readouterr().err).group(1)
    pred = str(tmp_path / "pred.csv")
    assert main(["predict", "--checkpoint-dir", ck, "--data", data,
                 "--output", pred]) == 0
    assert "iteration-2 checkpoint" in capsys.readouterr().err
    assert main(["evaluate", data, pred]) == 0
    rmse_eval = re.search(r"RMSE: ([0-9.]+)", capsys.readouterr().out).group(1)
    assert abs(float(rmse_train) - float(rmse_eval)) < 1e-3
    # wrong data for the checkpoint fails loudly
    assert main(["predict", "--checkpoint-dir", ck, "--data",
                 "/root/reference/data/data_sample_medium.txt",
                 "--output", str(tmp_path / "x.csv")]) == 1
    assert "smaller than the data implies" in capsys.readouterr().err


@pytest.mark.reference_data
def test_train_implicit_eval_ranking(capsys, tmp_path):
    from cfk_tpu.cli import main

    rc = main([
        "train", "--data", "/root/reference/data/data_sample_tiny.txt",
        "--implicit", "--rank", "8", "--alpha", "2", "--iterations", "4",
        "--seed", "0", "--eval-ranking", "10", "--output", "none",
        "--metrics", "json",
    ])
    assert rc == 0
    out = capsys.readouterr()
    assert "recall_at_10" in out.out and "mpr" in out.out
    assert "leave-one-out Recall@10=" in out.err
    # explicit model refuses the flag
    assert main([
        "train", "--data", "/root/reference/data/data_sample_tiny.txt",
        "--rank", "8", "--iterations", "1", "--eval-ranking", "5",
        "--output", "none",
    ]) == 1
    assert "requires --implicit" in capsys.readouterr().err


@pytest.mark.reference_data
def test_train_implicit(capsys, tmp_path):
    rc = main([
        "train", "--data", TINY, "--implicit", "--rank", "4",
        "--lam", "0.1", "--alpha", "5", "--iterations", "2",
        "--output", "none",
    ])
    assert rc == 0


@pytest.mark.reference_data
def test_train_with_checkpointing(capsys, tmp_path):
    ck = str(tmp_path / "ck")
    args = [
        "train", "--data", TINY, "--rank", "3", "--iterations", "3",
        "--seed", "1", "--checkpoint-dir", ck, "--output", "none",
        "--metrics", "json",
    ]
    assert main(args) == 0
    metrics = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert metrics["counters"]["checkpoints"] == 3
    # Re-run: resumes at 3, no new iterations.
    assert main(args) == 0
    metrics = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert metrics["counters"].get("iterations", 0) == 0
