"""scripts/perf_lab.py — the source of every headline perf number — gets the
same contract protection as bench.py: the JSON row shape, the min/median
timing math (against an injected deterministic clock), and the dataset
cache round-trip, all on CPU with tiny shapes."""

import importlib.util
import json
import os

import numpy as np

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_spec = importlib.util.spec_from_file_location(
    "perf_lab", os.path.join(_ROOT, "scripts", "perf_lab.py")
)
perf_lab = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(perf_lab)


def _args(**over):
    base = dict(
        users=300, movies=80, nnz=2000, seed=0, rank=8,
        layout="segment", chunk_elems=1024, tile_rows=16, slice_rows=None,
        solver="cholesky", dtype="float32", gram_backend=None,
        tiled_gram_backend=None, group_tiles=None, reg_solve_algo=None,
        ials=False, alpha=40.0, accum_chunk_elems=None, dense_stream=False,
        overlap="on", fused="on", gather="fused", table_dtype="float32",
        health="off",
        health_norm_limit=1e6, ckpt=None,
        foldin="off", foldin_updates=4096, foldin_batch_records=256,
        serve="off", serve_batch=64, serve_k=10, serve_requests=512,
        serve_tile_m=512, serve_mode="exact", serve_clusters=0,
        offload=None, offload_window_chunks=4, offload_budget_mb=None,
        offload_shards=1, optimizer="als",
        staging=None, staging_pool_depth=None, compile_cache_dir=None,
        hot_rows=None,
        plan=None, plan_cache=None,
        telemetry="off", trace_dir=None,
        iters=2, repeats=3, profile_dir=None,
    )
    base.update(over)
    import argparse

    return argparse.Namespace(**base)


def test_parser_matches_args_fixture():
    # The fixture above must cover exactly the parser's surface, so a new
    # flag cannot silently diverge from what run_lab is tested with.
    ns = perf_lab.make_parser().parse_args([])
    assert set(vars(ns)) == set(vars(_args()))


def test_json_row_contract(tmp_path, monkeypatch, capsys):
    monkeypatch.setattr(perf_lab, "CACHE_ROOT", str(tmp_path))
    row = perf_lab.run_lab(_args())
    out = capsys.readouterr().out.strip().splitlines()
    assert json.loads(out[-1]) == row  # last stdout line IS the row
    for key in ("s_per_iter_min", "s_per_iter_median", "mfu",
                "hbm_roofline_s", "gather_roofline_s", "vs_gather_roofline",
                "layout", "rank", "iters_per_call"):
        assert key in row, key
    assert row["s_per_iter_min"] >= 0
    assert row["s_per_iter_min"] <= row["s_per_iter_median"]
    assert row["layout"] == "segment"


def test_tiled_dense_stream_row(tmp_path, monkeypatch):
    monkeypatch.setattr(perf_lab, "CACHE_ROOT", str(tmp_path))
    row = perf_lab.run_lab(_args(layout="tiled", dense_stream=True,
                                 chunk_elems=512, repeats=2))
    assert row["layout"] == "tiled"
    assert row["s_per_iter_min"] >= 0


def test_dataset_cache_round_trip(tmp_path, monkeypatch, capsys):
    monkeypatch.setattr(perf_lab, "CACHE_ROOT", str(tmp_path))
    a = _args()
    ds1 = perf_lab.get_dataset(a)
    first = capsys.readouterr().out
    assert "cache hit" not in first
    ds2 = perf_lab.get_dataset(_args())
    second = capsys.readouterr().out
    assert "cache hit" in second
    np.testing.assert_array_equal(
        ds1.coo_dense.rating, ds2.coo_dense.rating
    )


def test_measure_steps_min_median_math(capsys):
    # Deterministic clock: each timed call brackets exactly one pair of
    # clock() reads; scripted durations 0.9, 0.3, 0.6 → min 0.3.
    durations = iter([0.9, 0.3, 0.6])
    now = [0.0]
    pending = [None]

    def clock():
        if pending[0] is None:
            pending[0] = next(durations)
            return now[0]
        now[0] += pending[0]
        pending[0] = None
        return now[0]

    calls = []

    def fake_steps(u, m):
        calls.append(1)
        return u, m

    u = np.zeros((2, 2), np.float32)
    times, *_ = perf_lab.measure_steps(
        fake_steps, u, u, repeats=3, iters=3, clock=clock,
    )
    assert len(calls) == 3
    np.testing.assert_allclose(times, [0.9, 0.3, 0.6])
    per_iter = [t / 3 for t in times]
    np.testing.assert_allclose(min(per_iter), 0.1)
    np.testing.assert_allclose(sorted(per_iter)[1], 0.2)  # the reported median


def test_health_axis_row(tmp_path, monkeypatch):
    import contextlib
    import io

    # the sentinel axis rides the same row contract (ISSUE 3: the
    # --health {on,off} pair is how its overhead is recorded)
    perf_lab.CACHE_ROOT, old = str(tmp_path), perf_lab.CACHE_ROOT
    try:
        with contextlib.redirect_stdout(io.StringIO()):
            on = perf_lab.run_lab(_args(health="on"))
            off = perf_lab.run_lab(_args(health="off"))
    finally:
        perf_lab.CACHE_ROOT = old
    assert on["health"] == "on" and off["health"] == "off"
    assert on["s_per_iter_min"] >= 0


def test_foldin_axis_row(tmp_path, monkeypatch, capsys):
    # the streaming fold-in axis (ISSUE 6): the tier-1 smoke path for the
    # whole streaming loop — in-memory broker, tiny synthetic stream,
    # through StreamSession's exactly-once batch/solve/probe/commit cycle
    monkeypatch.setattr(perf_lab, "CACHE_ROOT", str(tmp_path))
    row = perf_lab.run_lab(_args(
        foldin="on", foldin_updates=48, foldin_batch_records=16,
        layout="padded",
    ))
    out = capsys.readouterr().out.strip().splitlines()
    assert json.loads(out[-1]) == row  # scoreboard contract holds here too
    assert row["foldin"] == "on"
    assert row["updates"] == 48
    assert row["updates_per_s"] > 0
    assert row["batches"] >= 1
    for key in ("stage_s", "foldin_solve_s", "health_check_s", "commit_s"):
        assert row[key] >= 0, key


def test_ckpt_axis_row(tmp_path, monkeypatch):
    import contextlib
    import io

    # the checkpoint-writer axis (ISSUE 5): per-iteration saves ride the
    # timed call, and the row records the in-loop save stall + drain
    perf_lab.CACHE_ROOT, old = str(tmp_path), perf_lab.CACHE_ROOT
    try:
        with contextlib.redirect_stdout(io.StringIO()):
            a = perf_lab.run_lab(_args(ckpt="async"))
            s = perf_lab.run_lab(_args(ckpt="sync"))
    finally:
        perf_lab.CACHE_ROOT = old
    assert a["ckpt"] == "async" and s["ckpt"] == "sync"
    for row in (a, s):
        assert row["ckpt_save_stall_s_per_save"] >= 0
        assert row["ckpt_drain_s"] >= 0
        assert row["s_per_iter_min"] >= 0
    # NO relative sync-vs-async timing assert here: at this toy shape the
    # steps are ~ms while fsync dominates, so back-pressure makes the two
    # writers near-equal and noise flips the sign — the measured win lives
    # in bench.py --ckpt-ab at a real shape, where compute hides the disk.


def test_plan_axis_row(tmp_path, monkeypatch, capsys):
    # the execution-planner axis (ISSUE 9): the tier-1 smoke of the whole
    # resolve→thread-knobs→measure→provenance loop, mirroring
    # test_serve_axis_row's role for serving.  'model' resolves the free
    # knobs through the cost model and the row carries the provenance
    # columns; 'autotune' measures candidates with the lab's own step
    # timing and caches the winner (second run must hit).
    monkeypatch.setattr(perf_lab, "CACHE_ROOT", str(tmp_path))
    cache = str(tmp_path / "plan_cache.json")
    row = perf_lab.run_lab(_args(
        plan="model", layout="tiled", chunk_elems=512, tile_rows=16,
    ))
    out = capsys.readouterr().out.strip().splitlines()
    assert json.loads(out[-1]) == row  # scoreboard contract holds here too
    assert row["plan_axis"] == "model"
    assert row["plan_source"] in ("model", "pinned")
    assert row["plan_est_s"] >= 0
    assert "plan" in row and "table=" in row["plan"]
    # the roofline column charges the EXECUTED dtype, i.e. the plan's
    assert row["table_dtype"] in ("float32", "bfloat16", "int8")

    miss = perf_lab.run_lab(_args(
        plan="autotune", plan_cache=cache, layout="tiled",
        chunk_elems=512, tile_rows=16, repeats=2,
    ))
    assert miss["plan_cache"] == "miss"
    assert miss["plan_source"] == "autotune"
    assert miss["plan_measured_s"] > 0
    hit = perf_lab.run_lab(_args(
        plan="autotune", plan_cache=cache, layout="tiled",
        chunk_elems=512, tile_rows=16, repeats=2,
    ))
    assert hit["plan_cache"] == "hit"
    assert hit["plan_source"] == "autotune-cache"
    # the cached winner is the measured one
    assert hit["plan"] == miss["plan"]

    pinned = perf_lab.run_lab(_args(
        plan="pinned", layout="tiled", chunk_elems=512, tile_rows=16,
    ))
    assert pinned["plan_source"] == "pinned"
    assert pinned["table_dtype"] == "float32"  # legacy threading kept


def test_offload_axis_row(tmp_path, monkeypatch, capsys):
    # the out-of-core axis (ISSUE 11): the tier-1 in-memory smoke of the
    # whole store→window-plan→stage→windowed-half-step→host-scatter loop,
    # mirroring test_plan_axis_row's role for the planner.  Both tier
    # values run the SAME stream-forced tiled workload; crc equality IS
    # the windowed == resident bit-exactness contract.
    monkeypatch.setattr(perf_lab, "CACHE_ROOT", str(tmp_path))
    base = dict(layout="tiled", users=200, movies=60, nnz=1500,
                chunk_elems=512, tile_rows=16, rank=8, iters=2, repeats=2)
    dev = perf_lab.run_lab(_args(offload="device", **base))
    out = capsys.readouterr().out.strip().splitlines()
    assert json.loads(out[-1]) == dev  # scoreboard contract holds here too
    assert dev["offload"] == "device"
    assert dev["s_per_iter_min"] >= 0
    assert dev["factors_crc32"] > 0

    win = perf_lab.run_lab(_args(offload="host_window",
                                 offload_window_chunks=2, **base))
    assert win["offload"] == "host_window"
    assert win["windows_m"] >= 1 and win["windows_u"] >= 1
    assert win["window_rows_m"] >= 8
    assert win["staged_mb_per_run"] > 0
    assert win["staged_cold_mb_per_run"] > 0
    assert win["plan_held_mb"] > 0
    # windowed == resident, bit-exact — the ISSUE 11 acceptance contract
    assert win["factors_crc32"] == dev["factors_crc32"]


def test_offload_axis_optimizer_row(tmp_path, monkeypatch):
    # The --optimizer axis (ISSUE 19), mirroring test_offload_axis_row
    # for the implicit family: iALS++ on the bucketed width-class layout,
    # resident vs host_window through the out-of-core subspace driver
    # (width-class windows + global-Gram reduction) — crc equality is the
    # windowed == resident bit-exactness proof for the subspace sweeps,
    # and the windowed row carries the Gram reduction's own meters.
    # (iALS++ only, repeats=1: the plain-ials windowed == resident pair
    # lives in tests/test_offload_ials.py — duplicating it here pushed
    # the tier-1 suite past its wall-clock budget.)
    monkeypatch.setattr(perf_lab, "CACHE_ROOT", str(tmp_path))
    base = dict(layout="bucketed", users=120, movies=40, nnz=900,
                chunk_elems=512, rank=4, iters=2, repeats=1,
                optimizer="ialspp")
    dev = perf_lab.run_lab(_args(offload="device", **base))
    assert dev["offload"] == "device"
    assert dev["optimizer"] == "ialspp"
    assert dev["factors_crc32"] > 0

    win = perf_lab.run_lab(_args(offload="host_window",
                                 offload_window_chunks=2, **base))
    assert win["offload"] == "host_window"
    assert win["optimizer"] == "ialspp"
    assert win["windows_m"] >= 1 and win["windows_u"] >= 1
    assert win["staged_mb_per_run"] > 0
    assert win["gram_staged_mb_per_run"] > 0
    assert win["gram_reserved_mb"] > 0
    # windowed == resident, bit-exact — the ISSUE 19 acceptance contract
    assert win["factors_crc32"] == dev["factors_crc32"]


def test_offload_axis_hot_row(tmp_path, monkeypatch):
    # The hot-row cache axis (ISSUE 15): hot off (the PR 12 engine),
    # auto (coverage-knee resolution), and a pinned count all run the
    # SAME host_window workload — crc equality across the axis is the
    # hot/cold bit-exactness proof through the lab itself, and the hot
    # arms' rows carry the split metering (cold staged vs hot resident).
    monkeypatch.setattr(perf_lab, "CACHE_ROOT", str(tmp_path))
    base = dict(layout="tiled", users=200, movies=60, nnz=1500,
                chunk_elems=512, tile_rows=16, rank=8, iters=2, repeats=2,
                offload="host_window", offload_window_chunks=2)
    off = perf_lab.run_lab(_args(hot_rows=0, **base))
    auto = perf_lab.run_lab(_args(hot_rows=None, **base))
    pinned = perf_lab.run_lab(_args(hot_rows=12, **base))
    assert off["hot"] == "off" and off["hot_rows"] == 0
    assert off["hot_resident_mb"] in (None, 0, 0.0)
    assert auto["hot"] == "on" and auto["hot_rows"] > 0
    assert auto["hot_coverage"] > 0
    assert auto["hot_resident_mb"] > 0
    # The cache exists to cut staged table bytes — auto must not stage
    # MORE than full staging on the same schedule.
    assert auto["staged_cold_mb_per_run"] < off["staged_cold_mb_per_run"]
    assert pinned["hot_rows"] <= 12 and pinned["hot_rows"] > 0
    assert (off["factors_crc32"] == auto["factors_crc32"]
            == pinned["factors_crc32"])


def test_offload_axis_staging_row(tmp_path, monkeypatch):
    # The staging A/B axis (ISSUE 13): both engine modes run the SAME
    # 2-shard host_window workload — crc equality is the pooled==serial
    # bit-exactness proof through the lab itself, and the pool arm's row
    # carries the engine columns (depth, hidden fraction, trace count).
    monkeypatch.setattr(perf_lab, "CACHE_ROOT", str(tmp_path))
    base = dict(layout="tiled", users=200, movies=60, nnz=1500,
                chunk_elems=512, tile_rows=16, rank=8, iters=2, repeats=2,
                offload="host_window", offload_window_chunks=2,
                offload_shards=2)
    serial = perf_lab.run_lab(_args(staging="serial", **base))
    pool = perf_lab.run_lab(_args(staging="pool", **base))
    assert serial["staging"] == "serial" and pool["staging"] == "pool"
    assert pool["factors_crc32"] == serial["factors_crc32"]
    assert pool["pool_depth"] >= 1
    assert pool["stage_busy_s"] >= 0
    # the first (cold) arm traced the window programs; the second reuses
    # them — the process-wide jit cache IS the re-trace bound at work
    assert serial["trace_count"] >= 1
    assert pool["trace_count"] == 0
    assert pool["time_to_first_step_s"] > 0
    # serial stages on the consuming thread: stall == busy ⇒ hidden 0
    assert serial["overlap_hidden_fraction"] == 0.0
    assert serial["pool_depth"] is None


def test_offload_axis_sharded_row(tmp_path, monkeypatch):
    # The SHARDED arm (ISSUE 12): the host_window side runs the sharded
    # windowed driver; the device side the real shard_map trainer (this
    # test env forces 4 virtual devices) — crc equality between the arms
    # is the sharded windowed == resident bit-exactness proof, through
    # the lab's own two-point fit.
    import jax

    if len(jax.devices()) < 2:
        import pytest

        pytest.skip("needs 2 virtual devices")
    monkeypatch.setattr(perf_lab, "CACHE_ROOT", str(tmp_path))
    base = dict(layout="tiled", users=200, movies=60, nnz=1500,
                chunk_elems=512, tile_rows=16, rank=8, iters=2, repeats=2,
                offload_shards=2)
    dev = perf_lab.run_lab(_args(offload="device", **base))
    assert dev["offload_shards"] == 2
    win = perf_lab.run_lab(_args(offload="host_window",
                                 offload_window_chunks=2, **base))
    assert win["offload_shards"] == 2
    assert win["factors_crc32"] == dev["factors_crc32"]


def test_telemetry_axis_row(tmp_path, monkeypatch, capsys):
    # The --telemetry A/B axis (ISSUE 14), mirroring test_offload_axis_row:
    # both arms run the SAME trimmed host_window workload — crc equality is
    # the telemetry-on == telemetry-off bit-exactness contract (spans are
    # host-side observation only), and the on arm's row carries the span
    # count + the written Chrome trace.
    import cfk_tpu.telemetry as telemetry

    monkeypatch.setattr(perf_lab, "CACHE_ROOT", str(tmp_path))
    base = dict(layout="tiled", users=200, movies=60, nnz=1500,
                chunk_elems=512, tile_rows=16, rank=8, iters=2, repeats=2,
                offload="host_window", offload_window_chunks=2)
    off = perf_lab.run_lab(_args(telemetry="off", **base))
    assert "telemetry" not in off  # off arm is byte-for-byte pre-axis
    on = perf_lab.run_lab(_args(telemetry="on",
                                trace_dir=str(tmp_path / "trace"), **base))
    out = capsys.readouterr().out.strip().splitlines()
    assert json.loads(out[-1]) == on  # scoreboard contract incl. telemetry
    assert on["telemetry"] == "on"
    assert on["telemetry_spans"] > 0
    # spans are observation only: factors bit-identical across the arms
    assert on["factors_crc32"] == off["factors_crc32"]
    with open(on["telemetry_trace_path"]) as f:
        trace = json.load(f)
    names = {e["name"] for e in trace["traceEvents"] if e.get("ph") == "X"}
    assert "train/iter" in names
    assert any(n.endswith("window_stage") for n in names)
    # the axis tears the tracer down — later labs must not keep tracing
    assert telemetry.get_tracer() is None


def test_serve_axis_row(tmp_path, monkeypatch, capsys):
    # the top-K serving axis (ISSUE 8): the tier-1 smoke of the whole
    # request→score→top-K→respond loop — in-memory log, RecommendServer
    # coalescing, the score+top-K kernel with exclude-seen, open-loop
    # latency accounting — mirroring test_foldin_axis_row's role
    monkeypatch.setattr(perf_lab, "CACHE_ROOT", str(tmp_path))
    row = perf_lab.run_lab(_args(
        serve="on", serve_requests=24, serve_batch=8, serve_k=3,
        serve_tile_m=16, repeats=2,
    ))
    out = capsys.readouterr().out.strip().splitlines()
    assert json.loads(out[-1]) == row  # scoreboard contract holds here too
    assert row["serve"] == "on"
    assert row["answered"] == 24
    assert row["qps"] > 0
    assert row["serve_k"] == 3
    assert row["vs_roofline"] > 0
    assert row["batches"] >= 1
    for key in ("p50_ms", "p99_ms", "batch_s", "capacity_qps",
                "serve_roofline_s"):
        assert row[key] >= 0, key
    assert row["p50_ms"] <= row["p99_ms"]
    # every serve row now carries the ISSUE 16 A/B columns
    assert row["serve_mode"] == "exact"
    assert row["recall_at_k"] == 1.0
    assert row["bytes_scanned_per_batch"] > 0


def test_serve_axis_two_stage_row(tmp_path, monkeypatch, capsys):
    # the --serve-mode A/B axis (ISSUE 16), mirroring test_serve_axis_row:
    # the clustered candidate → exact-rescore path through the same full
    # request loop, with measured recall vs the bit-exact scan and the
    # executed mode's scan bytes in the row
    monkeypatch.setattr(perf_lab, "CACHE_ROOT", str(tmp_path))
    row = perf_lab.run_lab(_args(
        serve="on", serve_requests=24, serve_batch=8, serve_k=3,
        serve_tile_m=16, repeats=2, serve_mode="two_stage",
        serve_clusters=8,
    ))
    out = capsys.readouterr().out.strip().splitlines()
    assert json.loads(out[-1]) == row
    assert row["serve"] == "on"
    assert row["serve_mode"] == "two_stage"
    assert row["answered"] == 24
    assert row["qps"] > 0
    assert row["clusters"] == 8
    assert row["probe_clusters"] >= 1
    assert 0 < row["shortlist_rows"] <= row["movies"]
    assert 0.0 <= row["recall_at_k"] <= 1.0
    assert row["bytes_scanned_per_batch"] > 0
    assert row["vs_roofline"] > 0
