"""Packed flat-segment InBlock layout: structure, equivalence, SPMD, scale.

The segment layout is the third answer to ragged InBlocks (SURVEY.md §5
long-context analog): flat sorted rating runs packed into fixed-size nnz
chunks; per-entity Gram matrices accumulate by grouped ragged matmul
(``lax.ragged_dot_general``, ``segment_sum`` fallback), with entities
hotter than a chunk straddling chunks via a carried partial Gram — O(nnz)
memory for arbitrarily skewed degree distributions, with the device-side
accumulator bounded per chunk (full-Netflix user side would otherwise need
an 8 GB accumulator).
"""

import numpy as np
import pytest

from cfk_tpu.config import ALSConfig
from cfk_tpu.data.blocks import (
    Dataset,
    build_padded_blocks,
    build_segment_blocks,
)
from tests.test_bucketed import powerlaw_coo


def reconstruct_triples(blocks):
    """(entity_dense, neighbor_dense, rating) triples from packed chunks."""
    nc, cap, e_c = blocks.statics
    e_local = blocks.local_entities
    out = []
    for s in range(blocks.num_shards):
        for c in range(nc):
            ci = s * nc + c
            base = ci * cap
            sl = slice(base, base + cap)
            mk = blocks.mask[sl] > 0
            entity = s * e_local + blocks.chunk_first[ci] + blocks.seg_rel[sl][mk]
            out.append(
                np.stack(
                    [entity, blocks.neighbor_idx[sl][mk], blocks.rating[sl][mk]],
                    axis=1,
                )
            )
    return np.concatenate(out, axis=0)


def test_segment_structure_roundtrip():
    coo = powerlaw_coo()
    ds = Dataset.from_coo(coo)
    cd = ds.coo_dense
    for shards in (1, 4):
        for chunk_nnz in (None, 512):
            blocks = build_segment_blocks(
                cd.movie_raw, cd.user_raw, cd.rating,
                ds.movie_map.num_entities, num_shards=shards,
                chunk_nnz=chunk_nnz,
            )
            got = reconstruct_triples(blocks)
            want = np.stack([cd.movie_raw, cd.user_raw, cd.rating], axis=1)
            got = got[np.lexsort(got.T[::-1])]
            want = want[np.lexsort(want.T[::-1])]
            np.testing.assert_array_equal(got, want)
            np.testing.assert_array_equal(
                blocks.count[: ds.movie_map.num_entities],
                np.bincount(cd.movie_raw, minlength=ds.movie_map.num_entities),
            )
            # per-chunk seg_rel sorted, real rel < chunk_entities, padding = trash
            nc, cap, e_c = blocks.statics
            seg = blocks.seg_rel.reshape(-1, cap)
            assert np.all(np.diff(seg, axis=1) >= 0)
            mk = blocks.mask.reshape(-1, cap) > 0
            assert np.all(seg[mk] < e_c)
            assert np.all(seg[~mk] == e_c)
            # every chunk's nnz within capacity, entity rows within Ec
            assert mk.sum(axis=1).max() <= cap
            # each real entity is finalized by exactly one chunk row
            ent = blocks.chunk_entity.reshape(blocks.num_shards, -1)
            for s in range(shards):
                real = ent[s][ent[s] < blocks.local_entities]
                assert real.size == np.unique(real).size
            # finalized rows cover every rated entity exactly once
            all_real = blocks.chunk_entity[blocks.chunk_entity < blocks.local_entities]
            rated = (blocks.count.reshape(shards, -1) > 0).sum()
            assert all_real.size == rated
            # group_sizes: every chunk's sizes sum to the chunk capacity and
            # agree with the seg_rel histogram
            gs = blocks.group_sizes.reshape(-1, e_c + 1)
            assert np.all(gs.sum(axis=1) == cap)
            for ci in range(gs.shape[0]):
                hist = np.bincount(
                    blocks.seg_rel[ci * cap : (ci + 1) * cap], minlength=e_c + 1
                )
                np.testing.assert_array_equal(gs[ci], hist)
            # carry flags: a chunk with carry_in continues the previous
            # chunk's last entity (same shard, seg 0 == prev last_seg entity)
            cin = blocks.carry_in.reshape(shards, nc)
            first = blocks.chunk_first.reshape(shards, nc)
            lseg = blocks.last_seg.reshape(shards, nc)
            assert np.all(cin[:, 0] == 0.0)
            for s in range(shards):
                for c in range(1, nc):
                    if cin[s, c]:
                        assert first[s, c] == first[s, c - 1] + lseg[s, c - 1]


def test_segment_hot_entity_straddles_chunks():
    """An entity hotter than the chunk capacity spans chunks via the Gram
    carry instead of inflating every chunk to its degree."""
    rng = np.random.default_rng(1)
    hot_users = np.arange(1, 5001)
    tail_m = rng.integers(2, 200, size=2000)
    tail_u = rng.integers(1, 5001, size=2000)
    movie = np.concatenate([np.ones(5000, np.int64), tail_m])
    user = np.concatenate([hot_users, tail_u]).astype(np.int64)
    rating = rng.integers(1, 6, size=movie.size).astype(np.float32)

    from cfk_tpu.data.blocks import IdMap

    mmap = IdMap.from_raw(movie)
    m_dense = mmap.to_dense(movie)
    u_dense = IdMap.from_raw(user).to_dense(user)
    blocks = build_segment_blocks(
        m_dense, u_dense, rating, mmap.num_entities, chunk_nnz=512
    )
    # capacity stays at the requested chunk size, not the hot degree
    assert blocks.chunk_cap == 512
    assert blocks.carry_in.sum() >= 9  # 5000-degree entity spans ≥ 10 chunks
    got = reconstruct_triples(blocks)
    want = np.stack([m_dense, u_dense, rating], axis=1)
    got = got[np.lexsort(got.T[::-1])]
    want = want[np.lexsort(want.T[::-1])]
    np.testing.assert_array_equal(got, want)

    # end-to-end: training through the straddled layout matches padded
    from cfk_tpu.data.blocks import RatingsCOO
    from cfk_tpu.models.als import train_als

    coo = RatingsCOO(movie_raw=movie, user_raw=user, rating=rating)
    config = ALSConfig(rank=4, lam=0.05, num_iterations=2, seed=0)
    preds_p = train_als(Dataset.from_coo(coo, layout="padded"), config).predict_dense()
    preds_s = train_als(
        Dataset.from_coo(coo, layout="segment", chunk_elems=512), config
    ).predict_dense()
    np.testing.assert_allclose(preds_s, preds_p, atol=2e-3, rtol=1e-3)


def test_segment_gram_backends_agree(tiny_coo):
    """The ragged grouped-matmul Gram and the segment_sum fallback compute
    the same half-step."""
    import jax.numpy as jnp

    from cfk_tpu.ops.solve import als_half_step_segment

    ds = Dataset.from_coo(tiny_coo, layout="segment", chunk_elems=512)
    mb = ds.movie_blocks
    rng = np.random.default_rng(0)
    fixed = jnp.asarray(
        rng.standard_normal((ds.user_blocks.padded_entities, 6)).astype(np.float32)
    )
    args = (
        fixed, jnp.asarray(mb.neighbor_idx), jnp.asarray(mb.rating),
        jnp.asarray(mb.mask), jnp.asarray(mb.seg_rel),
        jnp.asarray(mb.chunk_entity), jnp.asarray(mb.chunk_count),
        jnp.asarray(mb.group_sizes),
        jnp.asarray(mb.carry_in), jnp.asarray(mb.last_seg),
        mb.local_entities, 0.05,
    )
    x_ragged = als_half_step_segment(*args, statics=mb.statics, gram_backend="ragged")
    x_segsum = als_half_step_segment(*args, statics=mb.statics, gram_backend="segsum")
    np.testing.assert_allclose(
        np.asarray(x_ragged), np.asarray(x_segsum), atol=5e-4, rtol=5e-4
    )


def test_segment_memory_is_nnz_proportional():
    """One degree-10k head entity blows up rectangles, not the packed runs."""
    rng = np.random.default_rng(0)
    head_users = np.arange(1, 10001)
    tail_m = rng.integers(2, 300, size=3000)
    tail_u = rng.integers(1, 10001, size=3000)
    movie = np.concatenate([np.ones(10000, np.int64), tail_m])
    user = np.concatenate([head_users, tail_u]).astype(np.int64)
    rating = rng.integers(1, 6, size=movie.size).astype(np.float32)

    from cfk_tpu.data.blocks import IdMap

    mmap = IdMap.from_raw(movie)
    m_dense = mmap.to_dense(movie)
    u_dense = IdMap.from_raw(user).to_dense(user)
    padded = build_padded_blocks(m_dense, u_dense, rating, mmap.num_entities)
    seg = build_segment_blocks(m_dense, u_dense, rating, mmap.num_entities,
                               chunk_nnz=1 << 14)
    assert padded.neighbor_idx.size > 20 * seg.neighbor_idx.size


def test_segment_als_matches_padded(tiny_coo):
    from cfk_tpu.eval.metrics import mse_rmse_from_blocks
    from cfk_tpu.models.als import train_als

    config = ALSConfig(rank=5, lam=0.05, num_iterations=3, seed=0)
    ds_p = Dataset.from_coo(tiny_coo, layout="padded")
    ds_s = Dataset.from_coo(tiny_coo, layout="segment")
    preds_p = train_als(ds_p, config).predict_dense()
    preds_s = train_als(ds_s, config).predict_dense()
    np.testing.assert_allclose(preds_s, preds_p, atol=2e-3, rtol=1e-3)
    mse_p, _ = mse_rmse_from_blocks(preds_p, ds_p)
    mse_s, _ = mse_rmse_from_blocks(preds_s, ds_s)
    assert abs(mse_p - mse_s) < 1e-4


def test_segment_chunked_matches_unchunked(tiny_coo):
    from cfk_tpu.models.als import train_als

    config = ALSConfig(rank=4, lam=0.05, num_iterations=2, seed=0)
    ds_one = Dataset.from_coo(tiny_coo, layout="segment", chunk_elems=None)
    ds_chunked = Dataset.from_coo(tiny_coo, layout="segment", chunk_elems=512)
    assert ds_one.movie_blocks.num_chunks == 1
    assert ds_chunked.movie_blocks.num_chunks > 1
    preds_one = train_als(ds_one, config).predict_dense()
    preds_chunked = train_als(ds_chunked, config).predict_dense()
    np.testing.assert_allclose(preds_chunked, preds_one, atol=1e-4, rtol=1e-4)


def test_segment_spmd_matches_single_device():
    from cfk_tpu.models.als import train_als
    from cfk_tpu.parallel.mesh import make_mesh
    from cfk_tpu.parallel.spmd import train_als_sharded

    coo = powerlaw_coo(n_movies=96, n_users=160, nnz=3000)
    config1 = ALSConfig(rank=6, lam=0.05, num_iterations=3, seed=3)
    single = train_als(Dataset.from_coo(coo, layout="segment"), config1).predict_dense()

    config8 = ALSConfig(
        rank=6, lam=0.05, num_iterations=3, seed=3, num_shards=8,
        layout="segment",
    )
    ds8 = Dataset.from_coo(coo, num_shards=8, layout="segment")
    sharded = train_als_sharded(ds8, config8, make_mesh(8)).predict_dense()
    np.testing.assert_allclose(sharded, single, atol=2e-3, rtol=1e-3)


def test_segment_spmd_chunked_matches_single_device():
    """Sharded + packed chunks together (the full-Netflix configuration)."""
    from cfk_tpu.models.als import train_als
    from cfk_tpu.parallel.mesh import make_mesh
    from cfk_tpu.parallel.spmd import train_als_sharded

    coo = powerlaw_coo(n_movies=64, n_users=96, nnz=2000)
    config1 = ALSConfig(rank=4, lam=0.05, num_iterations=2, seed=1)
    single = train_als(Dataset.from_coo(coo, layout="segment"), config1).predict_dense()
    config8 = ALSConfig(
        rank=4, lam=0.05, num_iterations=2, seed=1, num_shards=8, layout="segment",
    )
    ds8 = Dataset.from_coo(coo, num_shards=8, layout="segment", chunk_elems=256)
    assert ds8.movie_blocks.num_chunks > 1
    sharded = train_als_sharded(ds8, config8, make_mesh(8)).predict_dense()
    np.testing.assert_allclose(sharded, single, atol=2e-3, rtol=1e-3)


def test_segment_ials_matches_padded():
    from cfk_tpu.models.ials import IALSConfig, train_ials

    coo = powerlaw_coo(n_movies=80, n_users=120, nnz=2000)
    config = IALSConfig(rank=6, lam=0.1, alpha=10.0, num_iterations=3, seed=0)
    preds_p = train_ials(Dataset.from_coo(coo, layout="padded"), config).predict_dense()
    preds_s = train_ials(Dataset.from_coo(coo, layout="segment"), config).predict_dense()
    np.testing.assert_allclose(preds_s, preds_p, atol=2e-3, rtol=1e-3)


def test_segment_ials_chunked_matches_padded():
    from cfk_tpu.models.ials import IALSConfig, train_ials

    coo = powerlaw_coo(n_movies=48, n_users=64, nnz=1200)
    config = IALSConfig(rank=4, lam=0.1, alpha=5.0, num_iterations=2, seed=2)
    preds_p = train_ials(Dataset.from_coo(coo, layout="padded"), config).predict_dense()
    ds_c = Dataset.from_coo(coo, layout="segment", chunk_elems=256)
    assert ds_c.movie_blocks.num_chunks > 1
    preds_c = train_ials(ds_c, config).predict_dense()
    np.testing.assert_allclose(preds_c, preds_p, atol=2e-3, rtol=1e-3)


def test_segment_ials_sharded_matches_single():
    from cfk_tpu.models.ials import IALSConfig, train_ials, train_ials_sharded
    from cfk_tpu.parallel.mesh import make_mesh

    coo = powerlaw_coo(n_movies=64, n_users=96, nnz=1500)
    config1 = IALSConfig(rank=5, lam=0.1, alpha=5.0, num_iterations=2, seed=1)
    single = train_ials(
        Dataset.from_coo(coo, layout="segment"), config1
    ).predict_dense()
    config8 = IALSConfig(
        rank=5, lam=0.1, alpha=5.0, num_iterations=2, seed=1, num_shards=8,
        layout="segment",
    )
    ds8 = Dataset.from_coo(coo, num_shards=8, layout="segment")
    sharded = train_ials_sharded(ds8, config8, make_mesh(8)).predict_dense()
    np.testing.assert_allclose(sharded, single, atol=2e-3, rtol=1e-3)


def test_segment_golden_tiny(tiny_coo):
    """Reference config on tiny must hit the published quality bar
    (README.md:207-211: MSE 0.265) with the segment layout too."""
    from cfk_tpu.eval.metrics import mse_rmse_from_blocks
    from cfk_tpu.models.als import train_als

    ds = Dataset.from_coo(tiny_coo, layout="segment")
    config = ALSConfig(rank=5, lam=0.05, num_iterations=7, seed=42)
    preds = train_als(ds, config).predict_dense()
    mse, rmse = mse_rmse_from_blocks(preds, ds)
    assert mse <= 0.30, f"tiny MSE {mse} above reference-quality bar"


def test_config_rejects_segment_ring():
    with pytest.raises(ValueError, match="all_gather"):
        ALSConfig(layout="segment", exchange="ring")


def test_single_device_rejects_sharded_segments():
    from cfk_tpu.models.als import train_als

    coo = powerlaw_coo(n_movies=40, n_users=60, nnz=500)
    ds = Dataset.from_coo(coo, num_shards=4, layout="segment")
    with pytest.raises(ValueError, match="num_shards=4"):
        train_als(ds, ALSConfig(rank=4, num_iterations=1))
